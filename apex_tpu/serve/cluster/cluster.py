"""ServeCluster — the disaggregated prefill/decode step loop.

One object wires the whole multi-host story together: an SLO-aware
:class:`~apex_tpu.serve.cluster.router.Router` in front, ``n_prefill``
:class:`~apex_tpu.serve.cluster.workers.PrefillWorker` hosts feeding a
:class:`~apex_tpu.serve.cluster.transfer.SimTransport` (or a real ICI
link built from the same payloads), and ``n_decode``
:class:`~apex_tpu.serve.cluster.workers.DecodeWorker` hosts draining it.
Every :meth:`ServeCluster.step` is one cluster tick:

    chaos plan → preemption/heartbeat/watchdog checks → deliver
    transfers (CRC-validated; corrupt/late ones retried with backoff) →
    router dispatch (WFQ + TTFT feasibility, sheds are terminal) → one
    prefill chunk per busy prefill host → ship finished prefills →
    admit + one decode step per ALIVE decode host

All timestamps come from ONE :class:`~apex_tpu.monitor.events.EventLog`
clock shared by the router, both worker kinds, the membership ledger
and every decode engine, so the request lifecycle — ``submitted →
prefill_start/end → first_token → transfer_start/end → admitted →
decode_chunk* → retired`` (or ``submitted → shed``) — lines up across
hosts in the JSONL stream and the Chrome trace, and so do the elastic
events: ``worker_join`` / ``worker_leave``, ``migrate_start →
migrate_end`` spans when a request hops off a dying host, ``replay``
when its unacked tail is re-emitted.

**The elastic tier** (ROADMAP item 3): the dispatch set is a runtime
quantity. Workers join and leave through a
:class:`~apex_tpu.serve.cluster.membership.ClusterMembership` ledger
(alive → draining → dead) with heartbeat-miss detection on the shared
clock and optional autoscale driven by the backlog/occupancy gauges.
When a decode worker dies (killed, heartbeat-missed, watchdog-stalled)
or drains (preempted via its
:class:`~apex_tpu.resilience.preemption.PreemptionHandler`), its live
requests' pool blocks ship to a surviving worker over the SAME
extract/pack/insert wire a prefill handoff takes — verbatim for
quantized pools — the slot is reinstalled exactly as a handoff
admission would, and the last unacked token is replayed: resumed
streams are **bitwise identical** to an uninterrupted run
(``tests/test_serve_chaos.py`` pins it, greedy and sampled, fp32 and
int8/int4 pools). Every handoff is CRC-stamped; a transfer that rots,
stalls past ``transfer_timeout_ms`` or drops is detected and retried
with exponential backoff — the stream never silently diverges, and a
transfer that exhausts ``transfer_max_retries`` becomes an explicit
``transfer_failed`` terminal state, never a hang.

Parity is the design invariant, not an aspiration: the prefill hosts run
the engine's own chunk program, the wire ships pool blocks bitwise (raw
mode, and quantized pools under EITHER mode), and the decode hosts
install slots exactly as local prefill completion would — so per-request
token streams from a multi-host cluster are **bitwise equal** to the
single-engine path, greedy and sampled
(``tests/test_serve_cluster.py`` pins it). Overload degrades by
shedding and failure degrades by migrating: the cluster never deadlocks
and never raises the engine's pool-exhaustion error.
"""

from __future__ import annotations

import dataclasses
import heapq
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

from apex_tpu.monitor.alerts import AlertEngine, AlertRule, Condition
from apex_tpu.monitor.attrib import AttributionAccumulator
from apex_tpu.monitor.events import EventLog
from apex_tpu.monitor.flight import FlightRecorder
from apex_tpu.monitor.meter import CostModel, Meter
from apex_tpu.monitor.hist import DEFAULT_LATENCY_SPEC, Histogram
from apex_tpu.monitor.registry import FleetScraper, MetricsRegistry
from apex_tpu.monitor.trace import span
from apex_tpu.resilience.preemption import StallWatchdog
from apex_tpu.serve.cluster.chaos import ClusterChaos
from apex_tpu.serve.cluster.membership import (
    ALIVE,
    DEAD,
    DRAINING,
    AutoscalePolicy,
    ClusterMembership,
)
from apex_tpu.serve.cluster.router import Router, RouterConfig, ShedDecision
from apex_tpu.serve.cluster.transfer import (
    SimTransport,
    corrupt_payload,
    pack_blocks,
    payload_crc32,
    validate_wire_mode,
)
from apex_tpu.serve.cluster.workers import (
    DecodeWorker,
    KVHandoff,
    PrefillWorker,
    _cache_size_of,
)
from apex_tpu.serve.engine import Request, ServeConfig

Pytree = Any

__all__ = ["ClusterConfig", "ServeCluster"]


class _WorkerSink:
    """Per-worker step-record shim: stamps ``host=`` on every record so
    step records join the host-attributed event stream, rings it into
    the worker's flight recorder (which forwards to the shared sink)."""

    def __init__(self, ring: FlightRecorder, host: str):
        self._ring = ring
        self._host = host

    def write(self, step=None, metrics=None, **extra) -> None:
        extra.setdefault("host", self._host)
        self._ring.write(step=step, metrics=metrics, **extra)

    def flush(self) -> None:
        self._ring.flush()


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Cluster shape. ``serve`` configures each DECODE host's engine
    (slots, pool, kv_quant, spec_k, megakernel…); prefill hosts derive
    their staging config from it. ``wire_mode`` picks the transfer codec
    (``"int8"`` on a float pool cuts wire bytes ~3.6×; quantized pools
    ship their codes+scales verbatim either way). ``link_fixed_ms`` /
    ``link_gib_per_s`` shape the simulated transport's modeled latency
    (both 0: instant — the deterministic test default).

    Elastic knobs (all off by default — a cluster with none of them set
    behaves exactly like the pre-elastic one): ``heartbeat_timeout_ms``
    declares a worker dead after that long without a beat on the shared
    clock; ``watchdog_timeout_ms`` arms one
    :class:`~apex_tpu.resilience.preemption.StallWatchdog` per decode
    worker on the same clock (diagnostics to the sink, then death +
    migration); ``transfer_timeout_ms`` / ``transfer_max_retries`` /
    ``retry_backoff_ms`` govern the CRC/timeout retry ladder on the
    handoff wire; ``autoscale`` turns the backlog/occupancy gauges into
    join/drain decisions."""

    n_prefill: int = 1
    n_decode: int = 1
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    router: RouterConfig = dataclasses.field(default_factory=RouterConfig)
    wire_mode: str = "raw"
    prefill_queue_limit: int = 1
    link_fixed_ms: float = 0.0
    link_gib_per_s: float = 0.0
    heartbeat_timeout_ms: Optional[float] = None
    watchdog_timeout_ms: Optional[float] = None
    transfer_timeout_ms: Optional[float] = None
    transfer_max_retries: int = 3
    retry_backoff_ms: float = 10.0
    autoscale: Optional[AutoscalePolicy] = None
    # fleet observability (monitor tier 3). scrape_every: FleetScraper
    # cadence in cluster ticks; extra declarative alert rules ride
    # alert_rules (the autoscale policy's thresholds compile into
    # scale_up/scale_down rules automatically). flight_capacity bounds
    # the per-worker flight-recorder rings; flight_dir (when set) is
    # where rings dump on chaos kill / watchdog fire / page-severity
    # alert escalation (unset: rings still record, dump on demand via
    # ServeCluster.dump_flight).
    scrape_every: int = 1
    alert_rules: Tuple[Any, ...] = ()
    flight_capacity: int = 2048
    flight_dir: Optional[str] = None
    # performance forensics (monitor tier 4). metering: one shared
    # Meter across the decode fleet — every retirement charges its
    # tenant (modeled flops, KV block-seconds, adapter residency), the
    # wire charges at delivery, sheds at the shed funnel; cost_model
    # prices the resources (None: DEFAULT_WEIGHTS); meter_max_tenants
    # bounds the ledger (overflow folds loudly into "_overflow").
    # attribution: an AttributionAccumulator tapped on the shared
    # EventLog decomposes every retired request's e2e into queue/
    # prefill/transfer/decode/stall components on cluster.stats().
    # Both default ON (host-side dict work only — bench_attrib_cost
    # pins the A/B overhead ≤ 5%); OFF restores the tier-3 cluster.
    metering: bool = True
    attribution: bool = True
    cost_model: Optional[CostModel] = None
    meter_max_tenants: int = 1024

    def validate(self) -> None:
        if self.n_prefill < 1:
            raise ValueError("n_prefill must be >= 1")
        if self.n_decode < 1:
            raise ValueError("n_decode must be >= 1")
        validate_wire_mode(self.wire_mode)
        self.serve.validate()
        self.router.validate()
        if self.link_fixed_ms < 0 or self.link_gib_per_s < 0:
            raise ValueError("link latency knobs must be >= 0")
        for knob in ("heartbeat_timeout_ms", "watchdog_timeout_ms",
                     "transfer_timeout_ms"):
            v = getattr(self, knob)
            if v is not None and v <= 0:
                raise ValueError(f"{knob} must be > 0 when given")
        if self.transfer_max_retries < 0:
            raise ValueError("transfer_max_retries must be >= 0")
        if self.retry_backoff_ms < 0:
            raise ValueError("retry_backoff_ms must be >= 0")
        if self.autoscale is not None:
            self.autoscale.validate()
        if self.scrape_every < 0:
            raise ValueError("scrape_every must be >= 0 (0: scraping off)")
        if ((self.autoscale is not None or self.alert_rules)
                and self.scrape_every == 0):
            # autoscaling and alert rules act on the alert engine, and
            # the alert engine evaluates over scraped views — with
            # scraping off every rule would silently never fire; fail
            # the configuration loudly instead
            raise ValueError(
                "autoscale/alert_rules need scrape_every >= 1: alert "
                "rules evaluate over the scraped fleet view, so a "
                "non-scraping cluster can never fire them")
        if self.flight_capacity < 0:
            raise ValueError(
                "flight_capacity must be >= 0 (0: flight recorder off)")
        if self.meter_max_tenants < 1:
            raise ValueError("meter_max_tenants must be >= 1")


class ServeCluster:
    """Disaggregated serving over simulated (or real) hosts.

    Duck-type compatible with the single :class:`InferenceEngine` where
    it matters — ``submit`` / ``step`` / ``active`` / ``stats`` — so
    ``benchmarks/loadgen.run_workload`` drives a cluster unchanged.
    ``params`` is one replicated pytree (every host serves the same
    model). Streams are retained in :attr:`finished` unless
    ``retain_streams=False`` routes them to ``on_retire``; shed requests
    land in :attr:`shed` (uid → :class:`ShedDecision`) instead — the
    explicit terminal state (reason ``"transfer_failed"`` when the
    retry ladder ran dry).

    ``chaos``: a :class:`~apex_tpu.serve.cluster.chaos.ClusterChaos`
    plan consulted at the top of every tick — the deterministic fault
    harness the elastic claims are proven against."""

    def __init__(self, params: Pytree, cfg, cluster_cfg: ClusterConfig, *,
                 base_key=None, sink=None,
                 events: Optional[EventLog] = None,
                 retain_streams: bool = True,
                 on_retire: Optional[Callable[[str, List[int]], None]] = None,
                 use_pallas: Optional[bool] = None,
                 peak_flops_per_s: Optional[float] = None,
                 chaos: Optional[ClusterChaos] = None):
        cluster_cfg.validate()
        self.cfg = cfg
        self.cluster_cfg = cluster_cfg
        base_key = (base_key if base_key is not None
                    else jax.random.PRNGKey(0))
        # one clock for the whole cluster: every event, latency fold and
        # transfer timestamp subtracts the same anchor
        self._events = events if events is not None else EventLog()
        self._sink = sink
        self.router = Router(cluster_cfg.router)
        self.transport = SimTransport(fixed_ms=cluster_cfg.link_fixed_ms,
                                      gib_per_s=cluster_cfg.link_gib_per_s)
        self.membership = ClusterMembership(
            heartbeat_timeout_ms=cluster_cfg.heartbeat_timeout_ms,
            events=self._events, autoscale=cluster_cfg.autoscale)
        self._chaos = chaos
        # -- fleet observability (monitor tier 3) --------------------------
        # distributed tracing: one trace id minted per submission, bound
        # to the uid so EVERY producer's events carry it
        self._trace_seq = 0
        # flight recorders: one bounded ring per worker + one
        # cluster-scope ring for router/transfer/membership records;
        # records route by their host attribution via an EventLog tap
        self._flight: Dict[str, FlightRecorder] = {}
        self._flight_cluster: Optional[FlightRecorder] = None
        if cluster_cfg.flight_capacity > 0:
            self._flight_cluster = FlightRecorder(
                cluster_cfg.flight_capacity, worker="cluster",
                clock=self._events.now_ms)
            self._events.tap(self._route_flight)
        # alert rules: user rules + the autoscale policy's thresholds
        # compiled into scale_up/scale_down rules — the engine's
        # firings, not raw gauge peeks, are what trigger scaling
        rules = list(cluster_cfg.alert_rules)
        if cluster_cfg.autoscale is not None:
            pol = cluster_cfg.autoscale
            rules.append(AlertRule("scale_up", conditions=(
                Condition("cluster_queue_depth", ">=",
                          pol.scale_up_queue_depth),
                Condition("occupancy", ">=", pol.scale_up_occupancy,
                          agg="avg"))))
            rules.append(AlertRule("scale_down", conditions=(
                Condition("cluster_queue_depth", "<=", 0),
                Condition("occupancy", "<=", pol.scale_down_occupancy,
                          agg="avg"))))
        self._alerts = AlertEngine(rules, events=self._events,
                                   on_fire=self._on_alert)
        self.scraper = FleetScraper(self._scrape_targets,
                                    clock=self._events.now_ms)
        scfg = cluster_cfg.serve
        # decode hosts keep the full engine feature set minus the prefix
        # cache (blocks arrive by wire, not by content address); prefill
        # hosts need no speculation/megakernel — they never decode
        self._decode_cfg = dataclasses.replace(scfg, prefix_cache=False)
        self._prefill_cfg = dataclasses.replace(
            scfg, prefix_cache=False, spec_k=0, megakernel="off")
        self._retain_streams = retain_streams
        self._on_retire = on_retire
        self._finished: Dict[str, List[int]] = {}
        self.shed: Dict[str, ShedDecision] = {}
        # ctor args retained so autoscale can spawn identical workers
        self._params = params
        self._base_key = base_key
        self._use_pallas = use_pallas
        self._peak_flops_per_s = peak_flops_per_s
        # -- performance forensics (monitor tier 4) ------------------------
        # ONE meter shared by every decode host (each charge stamps the
        # retiring worker's name, so per-worker cost rates fall out of
        # the shared pool), created BEFORE the workers that hold it
        self.meter: Optional[Meter] = None
        if cluster_cfg.metering:
            self.meter = Meter(model=cluster_cfg.cost_model,
                               max_tenants=cluster_cfg.meter_max_tenants)
        # latency attribution: a tap on the shared EventLog streams
        # every retirement's lifecycle into the five-component
        # decomposition — no producer knows it exists
        self.attrib: Optional[AttributionAccumulator] = None
        if cluster_cfg.attribution:
            self.attrib = AttributionAccumulator()
            self._events.tap(self.attrib.tap)
        self.prefill_workers = [
            PrefillWorker(params, cfg, self._prefill_cfg, base_key=base_key,
                          wire_mode=cluster_cfg.wire_mode,
                          events=self._events,
                          now_ms=self._events.now_ms,
                          queue_limit=cluster_cfg.prefill_queue_limit,
                          use_pallas=use_pallas, name=f"prefill{i}")
            for i in range(cluster_cfg.n_prefill)]
        for w in self.prefill_workers:
            self._arm_flight(w.name)
        self.decode_workers = [
            self._make_decode_worker(f"decode{i}")
            for i in range(cluster_cfg.n_decode)]
        self._next_decode_id = cluster_cfg.n_decode
        self._workers: Dict[str, Any] = {
            w.name: w for w in self.prefill_workers + self.decode_workers}
        t0 = self._now_ms()
        for w in self.prefill_workers:
            self.membership.join(w.name, "prefill", t0)
        for w in self.decode_workers:
            self.membership.join(w.name, "decode", t0)
        # chaos-stalled workers: name -> step index the stall ends at
        # (None: wedged until something declares it dead)
        self._stalled: Dict[str, Optional[int]] = {}
        # per-decode-worker stall watchdogs on the shared clock (seconds)
        self._watchdogs: Dict[str, StallWatchdog] = {}
        if cluster_cfg.watchdog_timeout_ms is not None:
            for w in self.decode_workers:
                self._arm_watchdog(w.name)
        # the ONE extract program migration uses, shared by every decode
        # worker (identical kv config + padded shape) — a kill-and-
        # migrate on warmed workers mints ZERO new compilations
        decode_kv = self.decode_workers[0].engine.kv_cfg
        wire_mode = cluster_cfg.wire_mode

        def migrate_extract(cache, ids):
            return pack_blocks(cache, decode_kv, ids, wire_mode=wire_mode)

        self._migrate_extract = jax.jit(migrate_extract)
        # transfer reliability: uid -> {handoff, attempt, deadline};
        # resends scheduled on the shared clock with exponential backoff
        self._awaiting: Dict[str, Dict[str, Any]] = {}
        self._resend_at: List[Tuple[float, int, str]] = []  # (t, seq, uid)
        self._resend_seq = 0
        self._redeliver: List[KVHandoff] = []  # delivered, unplaced
        self.migrations_total = 0
        self.transfer_retries = 0
        self.transfer_crc_failures = 0
        self.transfer_timeouts = 0
        self.transfer_failed = 0
        self.duplicates_ignored = 0
        # per-tenant LoRA: the cluster-level adapter CATALOG (name ->
        # (weights, scale)). Loading puts the adapter eagerly into every
        # prefill host (prompts place by feasibility, not warmth) and
        # lazily into decode hosts on first cold placement — the
        # router's warm preference keeps cold loads rare at steady state
        self._adapter_catalog: Dict[str, Tuple[Any, float]] = {}
        self.adapter_loads = 0        # cold decode-side catalog loads
        # hard capacity for the unservable check: the roomiest decode pool
        self._max_servable_tokens = max(
            w.engine.kv_cfg.num_blocks * w.engine.kv_cfg.block_size
            for w in self.decode_workers)
        self.max_context = self.decode_workers[0].engine.max_context
        self.transfer_ms_hist = Histogram(DEFAULT_LATENCY_SPEC)
        self._step_idx = 0
        self._t_first_submit_ms: Optional[float] = None
        # start time of the PREVIOUS tick: the heartbeat/watchdog floor
        # (a worker that beat during that tick took its chance — one
        # slow wall-clock tick must not age the whole fleet to death)
        self._prev_tick_start_ms: Optional[float] = None

    def _make_decode_worker(self, name: str) -> DecodeWorker:
        ring = self._arm_flight(name)
        # the engine's step records flow host-stamped through the
        # worker's flight ring (which forwards to the shared sink) —
        # the ring is the black box, the sink stays the durable log
        sink = (_WorkerSink(ring, name) if ring is not None
                else self._sink)
        return DecodeWorker(
            self._params, self.cfg, self._decode_cfg,
            base_key=self._base_key,
            wire_mode=self.cluster_cfg.wire_mode, sink=sink,
            events=self._events, slo=self.cluster_cfg.router.slo,
            retain_streams=False, on_retire=self._retired,
            use_pallas=self._use_pallas,
            peak_flops_per_s=self._peak_flops_per_s,
            meter=self.meter, name=name)

    # -- flight recorders (monitor tier 3) ---------------------------------
    def _arm_flight(self, name: str) -> Optional[FlightRecorder]:
        if self.cluster_cfg.flight_capacity <= 0:
            return None
        ring = self._flight.get(name)
        if ring is None:
            ring = FlightRecorder(
                self.cluster_cfg.flight_capacity, worker=name,
                inner=self._sink, clock=self._events.now_ms)
            self._flight[name] = ring
        return ring

    def _route_flight(self, rec: Dict[str, Any]) -> None:
        """EventLog tap: every event/gauge record lands in exactly one
        ring — the named worker's when the record is host-attributed
        (bound or explicit), else the cluster-scope ring."""
        host = rec.get("host") or rec.get("worker")
        ring = self._flight.get(host) if host is not None else None
        if ring is not None:
            ring.record(rec)
        elif self._flight_cluster is not None:
            self._flight_cluster.record(rec)

    def _flight_rings(self) -> Dict[str, FlightRecorder]:
        out = dict(self._flight)
        if self._flight_cluster is not None:
            out["cluster"] = self._flight_cluster
        return out

    def dump_flight(self, directory: Optional[str] = None,
                    reason: str = "manual",
                    workers: Optional[Sequence[str]] = None) -> List[str]:
        """Atomically dump flight rings (all, or ``workers``) into
        ``directory`` (default ``ClusterConfig.flight_dir``); returns
        the dump paths and events each dump. ``python -m
        apex_tpu.monitor.postmortem DIR`` rebuilds the merged timeline
        from these files alone. With NO directory configured but a
        durable sink wired, each ring instead streams into the shared
        JSONL as one contiguous ``write_many`` batch (header-fenced) —
        the black box lands in the log the operator already has."""
        directory = directory or self.cluster_cfg.flight_dir
        if self.cluster_cfg.flight_capacity <= 0:
            return []
        t = self._now_ms()
        paths = []
        to_sink = (directory is None and self._sink is not None
                   and hasattr(self._sink, "write_many"))
        if directory is None and not to_sink:
            return []
        for name, ring in sorted(self._flight_rings().items()):
            if workers is not None and name not in workers:
                continue
            if to_sink:
                ring.dump_to_sink(self._sink, reason=reason, t_ms=t)
                path = f"sink:{name}"
            else:
                path = ring.dump(directory, reason=reason, t_ms=t)
            paths.append(path)
            self._events.emit("flight_dump", t_ms=t, worker=name,
                              reason=reason, path=path)
        return paths

    def _flight_sink_ok(self) -> bool:
        return (self.cluster_cfg.flight_dir is not None
                or (self._sink is not None
                    and hasattr(self._sink, "write_many")))

    def _dump_on_death(self, name: str, reason: str) -> None:
        """A worker died for a non-voluntary reason: preserve ITS ring
        and the cluster-scope ring (router/transfer context) before the
        telemetry goes stale — the chaos-kill black-box path."""
        if self._flight_sink_ok():
            self.dump_flight(reason=reason,
                             workers=(name, "cluster"))

    def _on_alert(self, firing) -> None:
        """Page-severity firings escalate: every surviving ring dumps
        (the 'capture the whole fleet's last seconds' trigger)."""
        if firing.severity == "page" and self._flight_sink_ok():
            self.dump_flight(reason=f"alert:{firing.rule}")

    def _arm_watchdog(self, name: str) -> None:
        self._watchdogs[name] = StallWatchdog(
            timeout_s=self.cluster_cfg.watchdog_timeout_ms / 1e3,
            sink=self._sink,
            clock=lambda: self._events.now_ms() / 1e3)

    # -- fleet scraping (monitor tier 3) -----------------------------------
    def _scrape_targets(self) -> List:
        """The FleetScraper's live target set: the cluster's own series
        plus every non-dead worker. A chaos-stalled worker is a SCRAPE
        MISS (its target answers None) — coverage drops below 1.0 and
        an absence rule over its series can fire, exactly how a wedged
        exporter looks to a real scraper."""
        out: List = [("cluster", self._scrape_self)]
        for w in self.prefill_workers + self.decode_workers:
            if self._state(w.name) == DEAD:
                continue
            if w.name in self._stalled:
                out.append((w.name, lambda: None))
            else:
                out.append((w.name, w.scrape))
        return out

    def _scrape_self(self) -> Dict[str, Any]:
        """Router/transport/membership series (the per-tenant plane
        rides tenant labels; the registry bound tracks the router's own
        tenant-state bound so a tenant flood degrades loudly, never
        unboundedly)."""
        limit = self.cluster_cfg.router.max_tenant_states or 1024
        # headroom: 3 router series + 3 meter series per tenant, plus
        # the fixed cluster series — both tenant planes are themselves
        # cardinality-bounded (router GC, meter overflow fold)
        reg = MetricsRegistry(max_series=8 * limit + 64)
        t = self._now_ms()
        L = {"worker": "cluster"}
        r = self.router
        reg.gauge("cluster_queue_depth", float(r.queue_depth), t_ms=t, **L)
        reg.gauge("queued_tokens", float(r.queued_tokens()), t_ms=t, **L)
        reg.counter("submitted_total", r.submitted, **L)
        reg.counter("admitted_total", r.admitted, **L)
        reg.counter("shed_total", r.shed, **L)
        reg.gauge("shed_rate",
                  (r.shed / r.submitted) if r.submitted else 0.0,
                  t_ms=t, **L)
        reg.gauge("transfers_in_flight", float(self.transport.in_flight),
                  t_ms=t, **L)
        reg.counter("transfer_retries_total", self.transfer_retries, **L)
        reg.counter("migrations_total", self.migrations_total, **L)
        if self.cluster_cfg.serve.lora_rank > 0:
            reg.counter("adapter_warm_dispatches_total",
                        r.adapter_warm_dispatches, **L)
            reg.counter("adapter_cold_dispatches_total",
                        r.adapter_cold_dispatches, **L)
            reg.counter("adapter_catalog_loads_total",
                        self.adapter_loads, **L)
        reg.counter("worker_deaths_total", self.membership.worker_deaths,
                    **L)
        for tenant, rec in self.router.tenants.items():
            reg.counter("tenant_submitted_total", rec["submitted"],
                        tenant=tenant)
            reg.counter("tenant_admitted_total", rec["admitted"],
                        tenant=tenant)
            reg.counter("tenant_shed_total", rec["shed"], tenant=tenant)
        if self.membership.heartbeat_timeout_ms is not None:
            for name in self.membership.names():
                wrec = self.membership.record(name)
                if wrec.state != DEAD:
                    reg.gauge("heartbeat_age_ms",
                              max(0.0, t - wrec.last_beat_ms),
                              t_ms=t, worker=name)
        if self.meter is not None:
            self.meter.collect_registry(reg, t_ms=t)
        return reg.snapshot(t)

    # -- adapter catalog (per-tenant LoRA) ---------------------------------
    def load_adapter(self, name: str, weights: Any, *,
                     scale: float = 1.0) -> None:
        """Register a named LoRA adapter fleet-wide. Eager into every
        prefill host NOW (the prompt's K/V must be written with adapted
        projections wherever it lands); decode hosts pick it up lazily —
        the router prefers adapter-warm workers, and a cold placement
        triggers the worker-local ``adapter_load`` there. Requires
        ``ServeConfig(lora_rank > 0)``."""
        if self.cluster_cfg.serve.lora_rank <= 0:
            raise RuntimeError(
                "adapters are disabled (ServeConfig.lora_rank == 0) — "
                "configure lora_rank/max_adapters to serve adapters")
        self._adapter_catalog[name] = (weights, float(scale))
        for w in self.prefill_workers:
            if self._state(w.name) != DEAD and w.adapters is not None:
                if w.adapters.lookup(name) is None:
                    w.load_adapter(name, weights, scale=scale)
                    self._events.emit("adapter_load", name,
                                      worker=w.name, eager=True)

    def adapter_catalog(self) -> List[str]:
        return sorted(self._adapter_catalog)

    def _ensure_adapter_on(self, worker: DecodeWorker,
                           name: str, t_ms: float) -> bool:
        """Make ``name`` resident on ``worker`` before a handoff bound
        to it is admitted (restore raises on a cold registry). False
        when the worker's pool is wholly pinned by decoding slots —
        the caller defers placement, never crashes."""
        eng = worker.engine
        if eng.adapters is not None and eng.adapters.lookup(name) is not None:
            return True
        weights, scale = self._adapter_catalog[name]
        try:
            worker.load_adapter(name, weights, scale=scale)
        except RuntimeError:
            return False
        self.adapter_loads += 1
        # the load IS liveness — advertise immediately so handoffs later
        # this same tick see the fresh resident set, not last tick's
        self.membership.beat(worker.name, t_ms,
                             adapters=worker.resident_adapters())
        return True

    # -- lifecycle ---------------------------------------------------------
    def _now_ms(self) -> float:
        return self._events.now_ms()

    def _retired(self, uid: str, tokens: List[int]) -> None:
        if self._retain_streams:
            self._finished[uid] = tokens
        if self._on_retire is not None:
            self._on_retire(uid, tokens)
        # terminal: the trace's bound fields (trace id, tenant, host)
        # are no longer needed — the table stays O(in-flight)
        self._events.unbind(uid)

    def submit(self, request: Request) -> None:
        """Route one request in. Input validation mirrors the engine's
        (garbage raises); a request that can never FIT the decode pool is
        shed — terminal, recorded, never a deadlock."""
        p = len(request.tokens)
        if p < 1:
            raise ValueError(f"{request.uid}: empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError(f"{request.uid}: max_new_tokens must be >= 1")
        if p >= self.max_context:
            raise ValueError(
                f"{request.uid}: prompt ({p}) must leave room to generate "
                f"(max_context {self.max_context})")
        t = self._now_ms()
        if self._t_first_submit_ms is None:
            self._t_first_submit_ms = t
        # mint the request's trace id HERE — router submission is the
        # start of the distributed trace; binding threads it (plus the
        # tenant) through every later producer's events, across hosts
        # and migrations, without any producer knowing about tracing
        self._trace_seq += 1
        self._events.bind(request.uid,
                          trace=f"tr{self._trace_seq:06d}",
                          tenant=getattr(request, "tenant", "default"))
        self._events.emit("submitted", request.uid, t_ms=t,
                          prompt_tokens=p,
                          max_new_tokens=request.max_new_tokens,
                          tenant=getattr(request, "tenant", "default"))
        adapter = getattr(request, "adapter", None)
        if adapter is not None and adapter not in self._adapter_catalog:
            # bound to an adapter nobody registered: terminal shed at
            # the front door — NEVER served on the base model by
            # accident, never a crash deep in a worker
            self._record_shed(self.router.shed_submitted(
                request, "unknown_adapter", t))
            self._events.gauge("queue_depth", self.router.queue_depth,
                               t_ms=t)
            return
        total = min(p + request.max_new_tokens, self.max_context)
        decision = self.router.submit(
            request, t, total_tokens=total,
            max_servable_tokens=self._max_servable_tokens)
        if decision is not None:
            self._record_shed(decision)
        self._events.gauge("queue_depth", self.router.queue_depth, t_ms=t)

    def _record_shed(self, d: ShedDecision) -> None:
        self.shed[d.request.uid] = d
        if self.meter is not None:
            # the single shed-charge funnel: EVERY terminal shed (front
            # door, infeasible dispatch, transfer_failed, headless)
            # flows through here exactly once — the engine deliberately
            # never charges sheds, so there is no double-count
            self.meter.charge(getattr(d.request, "tenant", "default"),
                              t_ms=d.t_ms, shed=1)
        self._events.emit(
            "shed", d.request.uid, t_ms=d.t_ms, reason=d.reason,
            predicted_ttft_ms=(round(d.predicted_ttft_ms, 3)
                               if d.predicted_ttft_ms is not None else None),
            budget_ms=d.budget_ms)
        self._events.unbind(d.request.uid)  # terminal state

    # -- membership views --------------------------------------------------
    def _state(self, name: str) -> str:
        return self.membership.state(name)

    def _steppable(self, name: str) -> bool:
        return self._state(name) != DEAD and name not in self._stalled

    def alive_decode_workers(self) -> List[DecodeWorker]:
        return [w for w in self.decode_workers if self._state(w.name) == ALIVE]

    def alive_prefill_workers(self) -> List[PrefillWorker]:
        return [w for w in self.prefill_workers
                if self._state(w.name) == ALIVE]

    # -- elastic transitions (chaos entry points + real operations) --------
    def kill_worker(self, name: str) -> None:
        """Fail-stop ``name`` NOW: out of the dispatch set, decode slots
        migrate to survivors, staged prefill prompts re-enqueue at the
        router. (The simulated failure keeps the dying pool readable —
        the preemption-notice / reachable-HBM failure class the KV wire
        can actually rescue; a hard asic loss would re-prefill instead,
        which the prefill re-enqueue path already covers.)"""
        t = self._now_ms()
        if not self.membership.mark_dead(name, t, "killed"):
            return
        self._evacuate(name, t)
        # black box: the dying worker's ring (holding its last records
        # INCLUDING the migrate_start exits evacuation just stamped) and
        # the cluster ring's router-side context dump atomically — the
        # postmortem CLI rebuilds the pre-kill timeline from these alone
        self._dump_on_death(name, "killed")

    def preempt_worker(self, name: str) -> None:
        """Deliver a preemption through the worker's PreemptionHandler —
        the exact path a real SIGTERM takes. The drain protocol runs on
        the next tick."""
        self._workers[name].preemption.trigger()

    def stall_worker(self, name: str, for_steps: int = 0) -> None:
        """Chaos: ``name`` stops stepping (and beating) for
        ``for_steps`` ticks (0: until declared dead)."""
        self._stalled[name] = (self._step_idx + for_steps
                               if for_steps > 0 else None)

    def request_drain(self, name: str, reason: str = "drained") -> None:
        """Voluntary exit: decode migrates its live requests now and
        leaves; prefill finishes its current prompt, re-enqueues the
        rest, and leaves when idle."""
        t = self._now_ms()
        if not self.membership.mark_draining(name, t, reason):
            return
        w = self._workers[name]
        if isinstance(w, DecodeWorker):
            self._evacuate(name, t)
            self.membership.mark_dead(name, t, reason)
        else:
            for req, t_sub in reversed(w.drain_queued()):
                self.router.requeue(req, t_sub)
            if not w.busy:
                self.membership.mark_dead(name, t, reason)

    def _evacuate(self, name: str, t_ms: float) -> None:
        """Move everything off a dead/draining worker: pending handoffs
        re-dispatch, live decode slots migrate over the KV wire, staged
        prefill prompts re-enqueue at the router."""
        w = self._workers[name]
        if isinstance(w, PrefillWorker):
            aborted = w.abort_current()
            if aborted is not None:
                self.router.requeue(*aborted)
            for req, t_sub in reversed(w.drain_queued()):
                self.router.requeue(req, t_sub)
            return
        for h in w.drain_pending():
            # not yet installed: just re-place on a survivor (the
            # payload is cluster-side and its transfer already counted
            # — no new wire transit, no new transfer telemetry)
            self._redeliver.append(h)
        for uid in w.live_uids():
            self._events.emit("migrate_start", uid, t_ms=t_ms,
                              src=name)
            h = w.evict_to_handoff(uid, self._migrate_extract)
            self.migrations_total += 1
            self._send_handoff(h, t_ms)

    # -- transfer reliability ----------------------------------------------
    def _send_handoff(self, h: KVHandoff, t_ms: float,
                      attempt: int = 1) -> None:
        uid = h.request.uid
        timeout = self.cluster_cfg.transfer_timeout_ms
        self._awaiting[uid] = {
            "handoff": h, "attempt": attempt,
            "deadline": (t_ms + timeout) if timeout is not None else None,
        }
        with span("transfer"):
            self._events.emit("transfer_start", uid, t_ms=t_ms,
                              wire_bytes=h.wire_bytes,
                              n_blocks=h.n_blocks, handoff_kind=h.kind,
                              attempt=attempt)
            self.transport.send((h, attempt), h.wire_bytes, t_ms)

    def _schedule_retry(self, uid: str, t_ms: float, reason: str) -> None:
        entry = self._awaiting.get(uid)
        if entry is None:
            return
        if entry["attempt"] > self.cluster_cfg.transfer_max_retries:
            # retry ladder ran dry: explicit terminal state, never a
            # hang — and the router's ledger moves it admitted → shed
            # so shed_rate reflects the loss
            del self._awaiting[uid]
            self.transfer_failed += 1
            h = entry["handoff"]
            self._record_shed(self.router.shed_admitted(
                h.request, "transfer_failed", t_ms))
            return
        self.transfer_retries += 1
        self._events.emit("transfer_retry", uid, t_ms=t_ms, reason=reason,
                          attempt=entry["attempt"])
        backoff = (self.cluster_cfg.retry_backoff_ms
                   * (2 ** (entry["attempt"] - 1)))
        entry["attempt"] += 1
        entry["deadline"] = None  # re-armed when the resend goes out
        self._resend_seq += 1
        heapq.heappush(self._resend_at,
                       (t_ms + backoff, self._resend_seq, uid))

    def _pump_retries(self, t_ms: float) -> int:
        """Resend due retries; time out overdue transfers."""
        n = 0
        while self._resend_at and self._resend_at[0][0] <= t_ms:
            _, _, uid = heapq.heappop(self._resend_at)
            entry = self._awaiting.get(uid)
            if entry is None:
                continue
            self._send_handoff(entry["handoff"], t_ms,
                               attempt=entry["attempt"])
            n += 1
        for uid, entry in list(self._awaiting.items()):
            if entry["deadline"] is not None and t_ms >= entry["deadline"]:
                self.transfer_timeouts += 1
                self._schedule_retry(uid, t_ms, "timeout")
                n += 1
        return n

    def _deliver(self, t_ms: float) -> int:
        n = 0
        for d in self.transport.poll(t_ms):
            h, attempt = d.item
            uid = h.request.uid
            entry = self._awaiting.get(uid)
            if entry is None:
                # already satisfied by an earlier copy: true duplicate
                self.duplicates_ignored += 1
                continue
            payload = (corrupt_payload(h.payload) if d.corrupted
                       else h.payload)
            valid = (h.crc32 is None
                     or payload_crc32(payload) == h.crc32)
            if attempt != entry["attempt"]:
                # a copy from a superseded attempt (it stalled past the
                # timeout and a retry is pending): a VALID copy still
                # satisfies the request — first good copy wins, and the
                # scheduled resend lapses against the empty awaiting
                # entry, saving the backoff wait and a full KV
                # retransmit. An invalid one is just dropped: the newer
                # attempt is already underway.
                if not valid:
                    self.duplicates_ignored += 1
                    continue
            elif not valid:
                self.transfer_crc_failures += 1
                self._schedule_retry(uid, t_ms, "crc")
                continue
            # validated: the transfer is DONE exactly once (one
            # transfer_end, one histogram sample) whether or not a
            # destination is alive right now — placement is a separate
            # concern handled below
            del self._awaiting[uid]
            self.transfer_ms_hist.add([d.transfer_ms])
            self._events.emit(
                "transfer_end", uid, t_ms=d.t_deliver_ms,
                wire_bytes=d.wire_bytes, handoff_kind=h.kind,
                transfer_ms=round(d.transfer_ms, 3))
            if self.meter is not None:
                # the wire is fleet infrastructure, not a worker — the
                # charge carries no worker attribution, and a retried
                # transfer bills each transit (retries cost real bytes)
                self.meter.charge(
                    getattr(h.request, "tenant", "default"),
                    t_ms=d.t_deliver_ms, wire_bytes=d.wire_bytes)
            self._redeliver.append(h)
            n += 1
        # place everything delivered-but-unplaced (fresh arrivals above,
        # plus handoffs evacuated from a dead worker's pending queue —
        # those crossed the wire once already and get NO new transfer
        # telemetry). Placement is the router's adapter-aware pick over
        # the membership advertisements: least-loaded among the
        # ADAPTER-WARM workers when the handoff is adapter-bound, else
        # classic least-loaded; a cold pick loads the adapter from the
        # catalog first (the explicit adapter_load lifecycle event).
        if self._redeliver and self.alive_decode_workers():
            todo, self._redeliver = self._redeliver, []
            for h in todo:
                alive = self.alive_decode_workers()
                cands = [(w.name, w.load,
                          self.membership.record(w.name).adapters)
                         for w in alive]
                name = self.router.select_worker(cands, adapter=h.adapter)
                if h.adapter is None:
                    self._workers[name].admit(h)
                    continue
                # adapter-bound: the adapter must be RESIDENT before the
                # restore lands. Try the router's pick first, then the
                # rest by load; a fleet whose every pool is pinned
                # defers to the next tick (never a crash, never a hang
                # — retiring slots free pool capacity)
                ordered = [name] + [
                    c[0] for c in sorted(cands, key=lambda c: c[1])
                    if c[0] != name]
                for wname in ordered:
                    w2 = self._workers[wname]
                    if self._ensure_adapter_on(w2, h.adapter, t_ms):
                        w2.admit(h)
                        break
                else:
                    self._redeliver.append(h)
        return n

    def _abort_if_headless(self, t_ms: float) -> int:
        """No ALIVE decode worker and no autoscale to mint one: every
        delivered-or-in-flight handoff (and everything still queued at
        the router) can never be served — turn them into explicit
        ``no_decode_workers`` terminal sheds instead of waiting forever.
        With autoscale armed the cluster instead waits for the join."""
        if self.alive_decode_workers() or (
                self.membership.autoscale_policy is not None):
            return 0
        n = 0
        doomed: List[Request] = [h.request for h in self._redeliver]
        self._redeliver.clear()
        for entry in self._awaiting.values():
            doomed.append(entry["handoff"].request)
        self._awaiting.clear()
        self._resend_at.clear()
        # in-flight requests were admitted: the router moves them to its
        # shed column; queued ones shed through the normal queue path —
        # either way the per-tenant ledger stays exact
        for req in doomed:
            self._record_shed(self.router.shed_admitted(
                req, "no_decode_workers", t_ms))
            n += 1
        for d in self.router.shed_queued("no_decode_workers", t_ms):
            self._record_shed(d)
            n += 1
        return n

    # -- failure detection (per tick) --------------------------------------
    def _poll_preemptions(self, t_ms: float) -> int:
        n = 0
        for name, w in list(self._workers.items()):
            if (self._state(name) == ALIVE and w.preemption.preempted()):
                self.request_drain(name, "preempted")
                n += 1
        return n

    def _finish_drains(self, t_ms: float) -> None:
        # draining prefill workers leave once their current prompt ships
        for w in self.prefill_workers:
            if self._state(w.name) == DRAINING and not w.busy:
                self.membership.mark_dead(
                    w.name, t_ms,
                    self.membership.record(w.name).reason or "drained")

    def _check_watchdogs(self, t_ms: float,
                         beat_floor_ms: Optional[float] = None) -> int:
        n = 0
        for name, wd in self._watchdogs.items():
            if self._state(name) == DEAD:
                continue
            if (beat_floor_ms is not None
                    and self.membership.record(name).last_beat_ms
                    >= beat_floor_ms):
                continue  # beat during the previous tick: not wedged
            if wd.check(now=t_ms / 1e3):
                w = self._workers[name]
                if self._sink is not None:
                    self._sink.write(
                        step=self._step_idx, phase="watchdog",
                        worker=name,
                        occupied_slots=len(w.live_uids()),
                        handoffs_pending=len(w._pending),
                        last_beat_ms=round(
                            self.membership.record(name).last_beat_ms, 3))
                # the watchdog verdict is an alert: same ledger, same
                # events, same escalation plane as an evaluated rule
                self._alerts.fire("watchdog_stall", t_ms, worker=name)
                self.membership.mark_dead(name, t_ms, "stall")
                self._evacuate(name, t_ms)
                self._dump_on_death(name, "stall")
                n += 1
        return n

    def _autoscale(self, t_ms: float) -> None:
        """Act on the ALERT ENGINE's scale firings (the thresholds are
        declarative rules over the scraped fleet view — no gauge
        peeking here); membership's ``approve_scale`` stays the one
        cooldown/fleet-bounds actuation gate."""
        if self.membership.autoscale_policy is None:
            return
        if not self.alive_decode_workers():
            # headless with autoscale armed: no occupancy series exists
            # for a rule to fire on (zero capacity exports nothing), but
            # lost capacity must be replaced or the fleet stays headless
            # forever — an explicit page-severity firing records WHY the
            # spawn happened, then spawn immediately (0 alive is always
            # under the fleet cap, which counts ALIVE workers)
            self._alerts.fire("fleet_headless", t_ms, severity="page",
                              alive_decode=0)
            self.spawn_decode_worker()
            self.membership.autoscale_ups += 1
            return
        if (self._alerts.active("scale_up")
                and self.membership.approve_scale("up", t_ms)):
            self.spawn_decode_worker()
        elif self._alerts.active("scale_down"):
            candidates = self.alive_decode_workers()
            if (len(candidates) > 1
                    and self.membership.approve_scale("down", t_ms)):
                victim = min(candidates, key=lambda w: w.load)
                self.request_drain(victim.name, "scale_down")

    def spawn_decode_worker(self) -> DecodeWorker:
        """Join a fresh decode worker at runtime (the autoscale-up hook;
        also callable directly to replace lost capacity). Its programs
        compile on first use — an explicit, bounded cost the compile
        gates exclude by construction (new worker = new program set)."""
        name = f"decode{self._next_decode_id}"
        self._next_decode_id += 1
        w = self._make_decode_worker(name)
        self.decode_workers.append(w)
        self._workers[name] = w
        self.membership.join(name, "decode", self._now_ms())
        if self.cluster_cfg.watchdog_timeout_ms is not None:
            self._arm_watchdog(name)
        return w

    # -- the cluster tick --------------------------------------------------
    def _outstanding(self) -> int:
        """Requests in flight anywhere downstream of the router: mid- or
        awaiting prefill, on the wire (or awaiting a retry), pending or
        occupying a decode slot on a non-dead worker."""
        n = len(self._awaiting) + len(self._redeliver)
        for w in self.prefill_workers:
            if self._state(w.name) == DEAD:
                continue
            n += (1 if w._current is not None else 0) + len(w._queue)
        for w in self.decode_workers:
            if self._state(w.name) == DEAD:
                continue
            n += len(w._pending)
            n += sum(s is not None for s in w.engine._slots)
        return n

    def _pipeline_tokens(self) -> int:
        """Token-denominated outstanding work the feasibility predictor
        charges at the measured prefill rate: unprefilled prompt tokens
        plus the decode side's remaining generation budgets — a
        deliberately simple stand-in for per-stage service curves, but
        one that GROWS with congestion, which is all admission control
        needs."""
        n = sum(w.backlog_tokens for w in self.prefill_workers
                if self._state(w.name) != DEAD)
        for w in self.decode_workers:
            if self._state(w.name) == DEAD:
                continue
            for h in w._pending:
                n += h.request.max_new_tokens
            for s in w.engine._slots:
                if s is not None:
                    n += max(0, s.request.max_new_tokens
                             - len(s.generated))
        return n

    def _dispatch(self, t_ms: float) -> int:
        """Admit from the router while the pipeline has credit. The
        credit bound (ALIVE decode slots + one buffered handoff per
        alive decode host) is BACKPRESSURE: when decode saturates,
        dispatch stops, queue wait mounts at the ROUTER, and the TTFT
        feasibility check — waited + pipeline-work · measured ms/token —
        sheds there, where a rejection is still cheap. Without it,
        prefill would race ahead and mint first tokens whose streams
        then stall for seconds in a decode queue no budget knows
        about. Only ALIVE workers are in the dispatch set — the elastic
        invariant."""
        n = 0
        alive_decode = self.alive_decode_workers()
        if not alive_decode:
            return 0
        capacity = (sum(w.engine.serve_cfg.num_slots for w in alive_decode)
                    + len(alive_decode))
        outstanding = self._outstanding()
        backlog = self._pipeline_tokens()
        for worker in sorted(self.alive_prefill_workers(),
                             key=lambda w: w.backlog_tokens):
            while worker.can_accept and outstanding < capacity:
                item, sheds = self.router.next_request(backlog, t_ms)
                for d in sheds:
                    self._record_shed(d)
                if item is None:
                    return n
                request, t_submit = item
                worker.accept(request, t_submit)
                backlog += len(request.tokens) + request.max_new_tokens
                outstanding += 1
                n += 1
        return n

    def step(self) -> bool:
        """One cluster tick; False when nothing moved anywhere."""
        t = self._now_ms()
        faults = (self._chaos.apply(self, self._step_idx)
                  if self._chaos is not None else [])
        # expire finished chaos stalls (a dead worker's stall is moot —
        # leaving it would make the waiting term below report progress
        # forever after the death was already handled)
        for name, until in list(self._stalled.items()):
            if ((until is not None and self._step_idx >= until)
                    or self._state(name) == DEAD):
                del self._stalled[name]
        moved = len(faults)
        moved += self._poll_preemptions(t)
        floor = self._prev_tick_start_ms
        for name in self.membership.check_heartbeats(t,
                                                     beat_floor_ms=floor):
            # the heartbeat verdict (reached by the beat-floor detector,
            # not a scraped rule — the floor logic needs per-tick state
            # a series can't carry) lands in the alert plane: one
            # ledger, one event stream, and the firing is what precedes
            # the migration in the trace
            self._alerts.fire(
                "heartbeat_absent", t, worker=name,
                last_beat_ms=round(
                    self.membership.record(name).last_beat_ms, 3))
            self._evacuate(name, t)
            self._dump_on_death(name, "heartbeat")
            moved += 1
        moved += self._check_watchdogs(t, floor)
        with span("transfer"):
            delivered = self._deliver(t)
            retried = self._pump_retries(t)
        moved += self._abort_if_headless(t)
        dispatched = self._dispatch(t)
        chunks = 0
        sent = 0
        for w in self.prefill_workers:
            if not self._steppable(w.name):
                continue
            before = w.chunks_run
            h = w.step()
            # beat with a FRESH timestamp: the step above may have been
            # the slow thing (a compile, a long chunk) — the worker that
            # just proved liveness must never look stale for it. The
            # beat carries the worker's ADVERTISEMENT: resident adapter
            # set + quant mode (the heterogeneous-fleet gossip)
            self.membership.beat(
                w.name, self._now_ms(),
                adapters=(sorted(w.adapters.resident())
                          if w.adapters is not None else None),
                quant=w.serve_cfg.kv_quant)
            if w.chunks_run > before:  # feed only a FRESH measurement
                self.router.observe_chunk(w.last_chunk_tokens,
                                          w.last_chunk_ms)
            if w.busy or h is not None:
                chunks += 1
            if h is not None:
                self._send_handoff(h, self._now_ms())
                sent += 1
        self._finish_drains(t)
        decoded = 0
        for w in self.decode_workers:
            if self._state(w.name) != ALIVE or w.name in self._stalled:
                continue
            if w.step():
                decoded += 1
            t_beat = self._now_ms()
            self.membership.beat(
                w.name, t_beat,
                adapters=(w.resident_adapters()
                          if w.engine.adapters is not None else None),
                quant=w.engine.serve_cfg.kv_quant,
                # the tier-4 half of the advertisement: this worker's
                # accrued cost units/second (the ROADMAP 5c routing
                # signal — a fleet-mix policy reads membership, not
                # the meter)
                cost_rate=(self.meter.worker_cost_rate(w.name, t_beat)
                           if self.meter is not None else None))
            wd = self._watchdogs.get(w.name)
            if wd is not None:
                wd.tick(self._step_idx)
        # fleet observability tick: scrape the live workers into one
        # view, evaluate the alert rules over it — autoscale (below)
        # acts on the engine's ACTIVE alerts, not on raw gauges
        if (self.cluster_cfg.scrape_every
                and self._step_idx % self.cluster_cfg.scrape_every == 0):
            with span("scrape"):
                view = self.scraper.scrape(self._now_ms())
            self._alerts.evaluate(view, self._now_ms())
        self._autoscale(t)
        # transfers still on the (modeled-latency) wire — or waiting out
        # a retry backoff / failure-detection timeout — count as pending
        # progress: a driver polling "did anything move?" must not
        # declare the cluster drained while recovery is in flight
        detection_armed = (
            self.cluster_cfg.heartbeat_timeout_ms is not None
            or self.cluster_cfg.watchdog_timeout_ms is not None)
        waiting = (self.transport.in_flight or self._awaiting
                   or self._resend_at or self._redeliver
                   or (bool(self._stalled) and detection_armed))
        progressed = bool(moved or delivered or retried or dispatched
                          or chunks or sent or decoded or waiting)
        self._prev_tick_start_ms = t
        self._step_idx += 1
        if self._sink is not None and progressed:
            self._sink.write(
                step=self._step_idx, phase="cluster",
                queue_depth=self.router.queue_depth,
                prefill_backlog_tokens=sum(
                    w.backlog_tokens for w in self.prefill_workers
                    if self._state(w.name) != DEAD),
                transfers_in_flight=self.transport.in_flight,
                shed_total=self.router.shed)
        return progressed

    # -- driving -----------------------------------------------------------
    @property
    def active(self) -> bool:
        return (self.router.queue_depth > 0
                or any(w.busy for w in self.prefill_workers
                       if self._state(w.name) != DEAD)
                or self.transport.in_flight > 0
                or bool(self._awaiting) or bool(self._redeliver)
                or bool(self._resend_at)
                or any(w.active for w in self.decode_workers
                       if self._state(w.name) != DEAD))

    def run(self, requests: Sequence[Request],
            max_steps: Optional[int] = None) -> Dict[str, List[int]]:
        """Serve ``requests`` to completion (or shed — check
        :attr:`shed`); returns uid → generated tokens for the completed
        ones. Never deadlocks: a tick that moves nothing while work
        remains is impossible by construction (queued work either
        dispatches, sheds, chunks, ships, decodes, migrates or retries),
        and ``max_steps`` is a belt-and-braces bound for drivers."""
        for r in requests:
            self.submit(r)
        steps = 0
        while self.active:
            if max_steps is not None and steps >= max_steps:
                break
            self.step()
            steps += 1
        return dict(self._finished)

    @property
    def finished(self) -> Dict[str, List[int]]:
        return dict(self._finished)

    @property
    def completed(self) -> int:
        return sum(w.engine.completed for w in self.decode_workers)

    def compile_counts(self) -> Dict[str, Any]:
        return {
            "prefill": [w.compile_counts() for w in self.prefill_workers],
            "decode": [w.compile_counts() for w in self.decode_workers],
            "migrate_extract": _cache_size_of(self._migrate_extract),
        }

    def programs(self) -> Dict[str, Callable]:
        """Every jitted program in the cluster, uniquely named — hand
        straight to ``analyze.recompile_guard`` to pin that a
        kill-and-migrate run on warmed workers mints ZERO new
        compilations (migration reuses the existing
        extract/insert/decode programs)."""
        out: Dict[str, Callable] = {"migrate_extract": self._migrate_extract}
        for w in self.prefill_workers:
            out[f"{w.name}.chunk_prefill"] = w._chunk_prefill
            out[f"{w.name}.extract"] = w._extract
        for w in self.decode_workers:
            for k, fn in w.engine.programs().items():
                if fn is not None:
                    out[f"{w.name}.{k}"] = fn
            out[f"{w.name}.insert"] = w._insert
        return out

    # -- stats -------------------------------------------------------------
    def occupancy(self) -> float:
        """Occupied / total decode slots over the ALIVE fleet (the
        autoscale gauge — dead capacity is not capacity)."""
        alive = self.alive_decode_workers()
        tot = sum(w.engine.serve_cfg.num_slots for w in alive)
        occ = sum(sum(s is not None for s in w.engine._slots)
                  for w in alive)
        return occ / tot if tot else 0.0

    def stats(self) -> Dict[str, Any]:
        """One JSON-serializable snapshot of the whole cluster: router
        admission/shed accounting, transfer wire totals, membership and
        elastic counters, merged decode latency quantiles and the summed
        goodput-under-SLO report — ``shed_rate`` / ``admitted_rps`` /
        ``transfer_ms_p50`` plus the chaos-gated ``migrations_total`` /
        ``replayed_tokens`` / ``worker_deaths`` / ``heartbeat_misses`` /
        ``transfer_retries`` are the flat headline fields
        ``monitor.regress`` gates."""
        router_stats = self.router.stats()
        out: Dict[str, Any] = {
            "hosts": {"prefill": len(self.prefill_workers),
                      "decode": len(self.decode_workers)},
            "steps": self._step_idx,
            "completed": self.completed,
            "generated_tokens": sum(
                w.engine._tokens_generated for w in self.decode_workers),
            "occupancy": self.occupancy(),
            "router": router_stats,
            "shed_rate": router_stats["shed_rate"],
        }
        # admitted requests per second of cluster wall time (elapsed on
        # the shared clock since the first submission)
        elapsed_ms = (self._now_ms() - self._t_first_submit_ms
                      if self._t_first_submit_ms is not None else 0.0)
        out["admitted_rps"] = (
            round(self.router.admitted / (elapsed_ms / 1e3), 4)
            if elapsed_ms > 0 else None)
        tr = self.transport
        out["transfer"] = {
            "transfers": tr.transfers_total,
            "wire_bytes_total": tr.wire_bytes_total,
            "transfer_ms_total": round(tr.transfer_ms_total, 3),
            "wire_mode": self.cluster_cfg.wire_mode,
            "bytes_per_transfer": (
                tr.wire_bytes_total // tr.transfers_total
                if tr.transfers_total else None),
            "bytes_per_ms": (
                round(tr.wire_bytes_total / tr.transfer_ms_total, 1)
                if tr.transfer_ms_total > 0 else None),
            "in_flight": tr.in_flight,
            "faults": {"drops": tr.drops_total, "stalls": tr.stalls_total,
                       "corrupts": tr.corrupts_total},
        }
        # the elastic ledger + flat chaos-gate headline fields
        out["membership"] = self.membership.stats()
        out["elastic"] = {
            "migrations_total": self.migrations_total,
            "replayed_tokens": sum(
                w.replayed_tokens for w in self.decode_workers),
            "transfer_retries": self.transfer_retries,
            "transfer_crc_failures": self.transfer_crc_failures,
            "transfer_timeouts": self.transfer_timeouts,
            "transfer_failed": self.transfer_failed,
            "duplicates_ignored": self.duplicates_ignored,
        }
        out["migrations_total"] = self.migrations_total
        out["replayed_tokens"] = out["elastic"]["replayed_tokens"]
        out["worker_deaths"] = self.membership.worker_deaths
        out["heartbeat_misses"] = self.membership.heartbeat_misses
        out["transfer_retries"] = self.transfer_retries
        # the per-tenant adapter plane: catalog + warm-dispatch ledger
        # (adapter_hit_rate / adapter_warm_dispatch_rate higher-better,
        # adapter_load_ms / adapter_evictions lower-better — all four
        # are monitor.regress polarity entries)
        if self.cluster_cfg.serve.lora_rank > 0:
            regs = [w.engine.adapters for w in self.decode_workers
                    if w.engine.adapters is not None]
            hits = sum(r.hits_total for r in regs)
            misses = sum(r.misses_total for r in regs)
            out["adapters"] = {
                "catalog": self.adapter_catalog(),
                "rank": self.cluster_cfg.serve.lora_rank,
                "max_adapters": self.cluster_cfg.serve.max_adapters,
                "catalog_loads": self.adapter_loads,
                "hits": hits,
                "misses": misses,
                "evictions": sum(r.evictions_total for r in regs),
                "warm_dispatches": self.router.adapter_warm_dispatches,
                "cold_dispatches": self.router.adapter_cold_dispatches,
            }
            out["adapter_hit_rate"] = (
                round(hits / (hits + misses), 4)
                if (hits + misses) else None)
            out["adapter_evictions"] = out["adapters"]["evictions"]
            out["adapter_warm_dispatch_rate"] = router_stats[
                "adapter_warm_dispatch_rate"]
            out["adapter_load_ms"] = round(
                sum(w.engine._adapter_load_ms_total
                    for w in self.decode_workers), 3)
        h = self.transfer_ms_hist
        if h.total:
            out["transfer_ms_p50"] = round(h.quantile(0.5), 4)
            out["transfer_ms_p99"] = round(h.quantile(0.99), 4)
        # merged decode-side latency quantiles: the per-worker streaming
        # histograms are associative — merging them equals one engine
        # having seen every retirement
        for dim in ("ttft_ms", "tpot_ms", "queue_ms", "e2e_ms",
                    "decode_step_ms"):
            merged = None
            for w in self.decode_workers:
                hw = w.engine.hists[dim]
                merged = hw if merged is None else merged.merge(hw)
            if merged is not None and merged.total:
                out[f"{dim}_p50"] = round(merged.quantile(0.5), 3)
                out[f"{dim}_p99"] = round(merged.quantile(0.99), 3)
        # summed SLO/goodput accounting across decode hosts
        reports = [w.engine._slo.report() for w in self.decode_workers
                   if w.engine._slo is not None]
        if reports:
            slo_rep: Dict[str, Any] = {
                "completed": sum(r["completed"] for r in reports),
                "good": sum(r["good"] for r in reports),
                "goodput_rps": round(
                    sum(r["goodput_rps"] for r in reports), 4),
                "throughput_rps": round(
                    sum(r["throughput_rps"] for r in reports), 4),
                "violations": {
                    k: sum(r["violations"].get(k, 0) for r in reports)
                    for k in reports[0]["violations"]},
                "slo": reports[0]["slo"],
            }
            comp = slo_rep["completed"]
            slo_rep["good_fraction"] = (round(slo_rep["good"] / comp, 4)
                                        if comp else None)
            out["slo_report"] = slo_rep
            out["goodput_rps"] = slo_rep["goodput_rps"]
            out["good_fraction"] = slo_rep["good_fraction"]
            # the fleet roll-up alias (regress-gated higher-is-better):
            # cluster-wide goodput as the scrape/alert plane reports it
            out["fleet_goodput_rps"] = slo_rep["goodput_rps"]
        # performance forensics (monitor tier 4): the event-derived
        # per-component decomposition and the per-tenant ledger, with
        # the flat regress-gated duals (attrib_coverage /
        # {c}_component_ms_* / cost_per_token / cost_per_request /
        # meter_coverage) hoisted next to the other headline fields
        if self.attrib is not None:
            att = self.attrib.summary()
            out["attribution"] = att
            if att.get("attrib_coverage") is not None:
                out["attrib_coverage"] = att["attrib_coverage"]
            for c in ("queue", "prefill", "transfer", "decode", "stall"):
                for q in ("p50", "p99"):
                    k = f"{c}_component_ms_{q}"
                    if att.get(k) is not None:
                        out[k] = att[k]
        if self.meter is not None:
            m = self.meter.stats(completed=self.completed)
            m["worker_cost_rates"] = self.meter.worker_rates(
                self._now_ms())
            out["meter"] = m
            out["cost_per_token"] = m["cost_per_token"]
            out["cost_per_request"] = m["cost_per_request"]
            out["meter_coverage"] = m["meter_coverage"]
        out["prefill_hosts"] = [
            {"host": w.name, "state": self._state(w.name),
             "chunks_run": w.chunks_run,
             "prefills_done": w.prefills_done,
             "backlog_tokens": w.backlog_tokens}
            for w in self.prefill_workers]
        out["decode_hosts"] = [
            {"host": w.name, "state": self._state(w.name),
             "completed": w.engine.completed,
             "handoffs_admitted": w.admitted,
             "handoffs_pending": len(w._pending),
             "migrations_in": w.migrations_in,
             "migrations_out": w.migrations_out,
             "occupancy": w.engine.occupancy()}
            for w in self.decode_workers]
        # the fleet observability plane's own accounting (monitor tier
        # 3): scrape cost/coverage, alert ledger, flight-ring fill —
        # flat headline duals (alerts_fired_total / scrape_ms /
        # scrape_coverage / trace stitch) are regress-gated
        fleet: Dict[str, Any] = dict(self.scraper.stats())
        fleet["alerts"] = self._alerts.stats()
        fleet["traces_minted"] = self._trace_seq
        if self._flight_cluster is not None:
            fleet["flight"] = {
                name: {"records": len(ring),
                       "dropped_records": ring.dropped_records,
                       "dumps": ring.dumps_total}
                for name, ring in sorted(self._flight_rings().items())}
        out["fleet"] = fleet
        out["alerts_fired_total"] = self._alerts.alerts_fired_total
        if self.scraper.last_coverage is not None:
            out["scrape_coverage"] = self.scraper.last_coverage
        if self.scraper.scrape_ms_hist.total:
            out["scrape_ms_p50"] = fleet.get("scrape_ms_p50")
        if self._chaos is not None:
            out["chaos"] = self._chaos.summary()
        return out


"""ServeCluster — the disaggregated prefill/decode step loop.

One object wires the whole multi-host story together: an SLO-aware
:class:`~apex_tpu.serve.cluster.router.Router` in front, ``n_prefill``
:class:`~apex_tpu.serve.cluster.workers.PrefillWorker` hosts feeding a
:class:`~apex_tpu.serve.cluster.transfer.SimTransport` (or a real ICI
link built from the same payloads), and ``n_decode``
:class:`~apex_tpu.serve.cluster.workers.DecodeWorker` hosts draining it.
Every :meth:`ServeCluster.step` is one cluster tick:

    deliver transfers → router dispatch (WFQ + TTFT feasibility, sheds
    are terminal) → one prefill chunk per busy prefill host → ship
    finished prefills → admit + one decode step per decode host

All timestamps come from ONE :class:`~apex_tpu.monitor.events.EventLog`
clock shared by the router, both worker kinds and every decode engine,
so the request lifecycle — ``submitted → prefill_start/end →
first_token → transfer_start/end → admitted → decode_chunk* → retired``
(or ``submitted → shed``) — lines up across hosts in the JSONL stream
and the Chrome trace (``monitor.chrome_trace`` renders the new
``transfer`` span like any other; a request visibly hops hosts in
Perfetto).

Parity is the design invariant, not an aspiration: the prefill hosts run
the engine's own chunk program, the wire ships pool blocks bitwise (raw
mode, and int8 pools under EITHER mode), and the decode hosts install
slots exactly as local prefill completion would — so per-request token
streams from a multi-host cluster are **bitwise equal** to the
single-engine path, greedy and sampled
(``tests/test_serve_cluster.py`` pins it). Overload degrades by
shedding: offered load beyond capacity turns into ``shed`` terminal
records while the kept traffic's goodput-under-SLO holds — the cluster
never deadlocks and never raises the engine's pool-exhaustion error.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax

from apex_tpu.monitor.events import EventLog
from apex_tpu.monitor.hist import DEFAULT_LATENCY_SPEC, Histogram
from apex_tpu.monitor.trace import span
from apex_tpu.serve.cluster.router import Router, RouterConfig, ShedDecision
from apex_tpu.serve.cluster.transfer import SimTransport, validate_wire_mode
from apex_tpu.serve.cluster.workers import (
    DecodeWorker,
    KVHandoff,
    PrefillWorker,
)
from apex_tpu.serve.engine import Request, ServeConfig

Pytree = Any

__all__ = ["ClusterConfig", "ServeCluster"]


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Cluster shape. ``serve`` configures each DECODE host's engine
    (slots, pool, kv_quant, spec_k, megakernel…); prefill hosts derive
    their staging config from it. ``wire_mode`` picks the transfer codec
    (``"int8"`` on a float pool cuts wire bytes ~3.6×; int8 pools ship
    their codes+scales verbatim either way). ``link_fixed_ms`` /
    ``link_gib_per_s`` shape the simulated transport's modeled latency
    (both 0: instant — the deterministic test default)."""

    n_prefill: int = 1
    n_decode: int = 1
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    router: RouterConfig = dataclasses.field(default_factory=RouterConfig)
    wire_mode: str = "raw"
    prefill_queue_limit: int = 1
    link_fixed_ms: float = 0.0
    link_gib_per_s: float = 0.0

    def validate(self) -> None:
        if self.n_prefill < 1:
            raise ValueError("n_prefill must be >= 1")
        if self.n_decode < 1:
            raise ValueError("n_decode must be >= 1")
        validate_wire_mode(self.wire_mode)
        self.serve.validate()
        self.router.validate()
        if self.link_fixed_ms < 0 or self.link_gib_per_s < 0:
            raise ValueError("link latency knobs must be >= 0")


class ServeCluster:
    """Disaggregated serving over simulated (or real) hosts.

    Duck-type compatible with the single :class:`InferenceEngine` where
    it matters — ``submit`` / ``step`` / ``active`` / ``stats`` — so
    ``benchmarks/loadgen.run_workload`` drives a cluster unchanged.
    ``params`` is one replicated pytree (every host serves the same
    model). Streams are retained in :attr:`finished` unless
    ``retain_streams=False`` routes them to ``on_retire``; shed requests
    land in :attr:`shed` (uid → :class:`ShedDecision`) instead — the
    explicit terminal state."""

    def __init__(self, params: Pytree, cfg, cluster_cfg: ClusterConfig, *,
                 base_key=None, sink=None,
                 events: Optional[EventLog] = None,
                 retain_streams: bool = True,
                 on_retire: Optional[Callable[[str, List[int]], None]] = None,
                 use_pallas: Optional[bool] = None,
                 peak_flops_per_s: Optional[float] = None):
        cluster_cfg.validate()
        self.cfg = cfg
        self.cluster_cfg = cluster_cfg
        base_key = (base_key if base_key is not None
                    else jax.random.PRNGKey(0))
        # one clock for the whole cluster: every event, latency fold and
        # transfer timestamp subtracts the same anchor
        self._events = events if events is not None else EventLog()
        self._sink = sink
        self.router = Router(cluster_cfg.router)
        self.transport = SimTransport(fixed_ms=cluster_cfg.link_fixed_ms,
                                      gib_per_s=cluster_cfg.link_gib_per_s)
        scfg = cluster_cfg.serve
        # decode hosts keep the full engine feature set minus the prefix
        # cache (blocks arrive by wire, not by content address); prefill
        # hosts need no speculation/megakernel — they never decode
        decode_cfg = dataclasses.replace(scfg, prefix_cache=False)
        prefill_cfg = dataclasses.replace(
            scfg, prefix_cache=False, spec_k=0, megakernel="off")
        self._retain_streams = retain_streams
        self._on_retire = on_retire
        self._finished: Dict[str, List[int]] = {}
        self.shed: Dict[str, ShedDecision] = {}
        self.prefill_workers = [
            PrefillWorker(params, cfg, prefill_cfg, base_key=base_key,
                          wire_mode=cluster_cfg.wire_mode,
                          events=self._events,
                          now_ms=self._events.now_ms,
                          queue_limit=cluster_cfg.prefill_queue_limit,
                          use_pallas=use_pallas, name=f"prefill{i}")
            for i in range(cluster_cfg.n_prefill)]
        self.decode_workers = [
            DecodeWorker(params, cfg, decode_cfg, base_key=base_key,
                         wire_mode=cluster_cfg.wire_mode, sink=sink,
                         events=self._events,
                         slo=cluster_cfg.router.slo,
                         retain_streams=False,
                         on_retire=self._retired,
                         use_pallas=use_pallas,
                         peak_flops_per_s=peak_flops_per_s,
                         name=f"decode{i}")
            for i in range(cluster_cfg.n_decode)]
        # hard capacity for the unservable check: the roomiest decode pool
        self._max_servable_tokens = max(
            w.engine.kv_cfg.num_blocks * w.engine.kv_cfg.block_size
            for w in self.decode_workers)
        self.max_context = self.decode_workers[0].engine.max_context
        self.transfer_ms_hist = Histogram(DEFAULT_LATENCY_SPEC)
        self._step_idx = 0
        self._t_first_submit_ms: Optional[float] = None

    # -- lifecycle ---------------------------------------------------------
    def _now_ms(self) -> float:
        return self._events.now_ms()

    def _retired(self, uid: str, tokens: List[int]) -> None:
        if self._retain_streams:
            self._finished[uid] = tokens
        if self._on_retire is not None:
            self._on_retire(uid, tokens)

    def submit(self, request: Request) -> None:
        """Route one request in. Input validation mirrors the engine's
        (garbage raises); a request that can never FIT the decode pool is
        shed — terminal, recorded, never a deadlock."""
        p = len(request.tokens)
        if p < 1:
            raise ValueError(f"{request.uid}: empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError(f"{request.uid}: max_new_tokens must be >= 1")
        if p >= self.max_context:
            raise ValueError(
                f"{request.uid}: prompt ({p}) must leave room to generate "
                f"(max_context {self.max_context})")
        t = self._now_ms()
        if self._t_first_submit_ms is None:
            self._t_first_submit_ms = t
        self._events.emit("submitted", request.uid, t_ms=t,
                          prompt_tokens=p,
                          max_new_tokens=request.max_new_tokens,
                          tenant=getattr(request, "tenant", "default"))
        total = min(p + request.max_new_tokens, self.max_context)
        decision = self.router.submit(
            request, t, total_tokens=total,
            max_servable_tokens=self._max_servable_tokens)
        if decision is not None:
            self._record_shed(decision)
        self._events.gauge("queue_depth", self.router.queue_depth, t_ms=t)

    def _record_shed(self, d: ShedDecision) -> None:
        self.shed[d.request.uid] = d
        self._events.emit(
            "shed", d.request.uid, t_ms=d.t_ms, reason=d.reason,
            predicted_ttft_ms=(round(d.predicted_ttft_ms, 3)
                               if d.predicted_ttft_ms is not None else None),
            budget_ms=d.budget_ms)

    # -- the cluster tick --------------------------------------------------
    def _deliver(self, t_ms: float) -> int:
        n = 0
        for d in self.transport.poll(t_ms):
            h: KVHandoff = d.item
            self.transfer_ms_hist.add([d.transfer_ms])
            self._events.emit(
                "transfer_end", h.request.uid, t_ms=d.t_deliver_ms,
                wire_bytes=d.wire_bytes,
                transfer_ms=round(d.transfer_ms, 3))
            worker = min(self.decode_workers, key=lambda w: w.load)
            worker.admit(h)
            n += 1
        return n

    def _outstanding(self) -> int:
        """Requests in flight anywhere downstream of the router: mid- or
        awaiting prefill, on the wire, pending or occupying a decode
        slot."""
        n = self.transport.in_flight
        for w in self.prefill_workers:
            n += (1 if w._current is not None else 0) + len(w._queue)
        for w in self.decode_workers:
            n += len(w._pending)
            n += sum(s is not None for s in w.engine._slots)
        return n

    def _pipeline_tokens(self) -> int:
        """Token-denominated outstanding work the feasibility predictor
        charges at the measured prefill rate: unprefilled prompt tokens
        plus the decode side's remaining generation budgets — a
        deliberately simple stand-in for per-stage service curves, but
        one that GROWS with congestion, which is all admission control
        needs."""
        n = sum(w.backlog_tokens for w in self.prefill_workers)
        for w in self.decode_workers:
            for h in w._pending:
                n += h.request.max_new_tokens
            for s in w.engine._slots:
                if s is not None:
                    n += max(0, s.request.max_new_tokens
                             - len(s.generated))
        return n

    def _dispatch(self, t_ms: float) -> int:
        """Admit from the router while the pipeline has credit. The
        credit bound (decode slots + one buffered handoff per decode
        host) is BACKPRESSURE: when decode saturates, dispatch stops,
        queue wait mounts at the ROUTER, and the TTFT feasibility check
        — waited + pipeline-work · measured ms/token — sheds there,
        where a rejection is still cheap. Without it, prefill would race
        ahead and mint first tokens whose streams then stall for seconds
        in a decode queue no budget knows about."""
        n = 0
        capacity = (sum(w.engine.serve_cfg.num_slots
                        for w in self.decode_workers)
                    + len(self.decode_workers))
        outstanding = self._outstanding()
        backlog = self._pipeline_tokens()
        for worker in sorted(self.prefill_workers,
                             key=lambda w: w.backlog_tokens):
            while worker.can_accept and outstanding < capacity:
                item, sheds = self.router.next_request(backlog, t_ms)
                for d in sheds:
                    self._record_shed(d)
                if item is None:
                    return n
                request, t_submit = item
                worker.accept(request, t_submit)
                backlog += len(request.tokens) + request.max_new_tokens
                outstanding += 1
                n += 1
        return n

    def step(self) -> bool:
        """One cluster tick; False when nothing moved anywhere."""
        t = self._now_ms()
        with span("transfer"):
            delivered = self._deliver(t)
        dispatched = self._dispatch(t)
        chunks = 0
        sent = 0
        for w in self.prefill_workers:
            before = w.chunks_run
            h = w.step()
            if w.chunks_run > before:  # feed only a FRESH measurement
                self.router.observe_chunk(w.last_chunk_tokens,
                                          w.last_chunk_ms)
            if w.busy or h is not None:
                chunks += 1
            if h is not None:
                with span("transfer"):
                    t_send = self._now_ms()
                    self._events.emit("transfer_start", h.request.uid,
                                      t_ms=t_send,
                                      wire_bytes=h.wire_bytes,
                                      n_blocks=h.n_blocks)
                    self.transport.send(h, h.wire_bytes, t_send)
                sent += 1
        decoded = 0
        for w in self.decode_workers:
            if w.step():
                decoded += 1
        # transfers still on the (modeled-latency) wire count as pending
        # progress: a driver polling "did anything move?" must not
        # declare the cluster drained while a handoff is in flight
        progressed = bool(delivered or dispatched or chunks or sent
                          or decoded or self.transport.in_flight)
        self._step_idx += 1
        if self._sink is not None and progressed:
            self._sink.write(
                step=self._step_idx, phase="cluster",
                queue_depth=self.router.queue_depth,
                prefill_backlog_tokens=sum(
                    w.backlog_tokens for w in self.prefill_workers),
                transfers_in_flight=self.transport.in_flight,
                shed_total=self.router.shed)
        return progressed

    # -- driving -----------------------------------------------------------
    @property
    def active(self) -> bool:
        return (self.router.queue_depth > 0
                or any(w.busy for w in self.prefill_workers)
                or self.transport.in_flight > 0
                or any(w.active for w in self.decode_workers))

    def run(self, requests: Sequence[Request],
            max_steps: Optional[int] = None) -> Dict[str, List[int]]:
        """Serve ``requests`` to completion (or shed — check
        :attr:`shed`); returns uid → generated tokens for the completed
        ones. Never deadlocks: a tick that moves nothing while work
        remains is impossible by construction (queued work either
        dispatches, sheds, chunks, ships or decodes), and ``max_steps``
        is a belt-and-braces bound for drivers."""
        for r in requests:
            self.submit(r)
        steps = 0
        while self.active:
            if max_steps is not None and steps >= max_steps:
                break
            self.step()
            steps += 1
        return dict(self._finished)

    @property
    def finished(self) -> Dict[str, List[int]]:
        return dict(self._finished)

    @property
    def completed(self) -> int:
        return sum(w.engine.completed for w in self.decode_workers)

    def compile_counts(self) -> Dict[str, Any]:
        return {
            "prefill": [w.compile_counts() for w in self.prefill_workers],
            "decode": [w.compile_counts() for w in self.decode_workers],
        }

    # -- stats -------------------------------------------------------------
    def occupancy(self) -> float:
        tot = sum(w.engine.serve_cfg.num_slots for w in self.decode_workers)
        occ = sum(sum(s is not None for s in w.engine._slots)
                  for w in self.decode_workers)
        return occ / tot if tot else 0.0

    def stats(self) -> Dict[str, Any]:
        """One JSON-serializable snapshot of the whole cluster: router
        admission/shed accounting, transfer wire totals, merged decode
        latency quantiles and the summed goodput-under-SLO report —
        ``shed_rate`` / ``admitted_rps`` / ``transfer_ms_p50`` are the
        flat headline fields ``monitor.regress`` gates."""
        router_stats = self.router.stats()
        out: Dict[str, Any] = {
            "hosts": {"prefill": len(self.prefill_workers),
                      "decode": len(self.decode_workers)},
            "steps": self._step_idx,
            "completed": self.completed,
            "generated_tokens": sum(
                w.engine._tokens_generated for w in self.decode_workers),
            "occupancy": self.occupancy(),
            "router": router_stats,
            "shed_rate": router_stats["shed_rate"],
        }
        # admitted requests per second of cluster wall time (elapsed on
        # the shared clock since the first submission)
        elapsed_ms = (self._now_ms() - self._t_first_submit_ms
                      if self._t_first_submit_ms is not None else 0.0)
        out["admitted_rps"] = (
            round(self.router.admitted / (elapsed_ms / 1e3), 4)
            if elapsed_ms > 0 else None)
        tr = self.transport
        out["transfer"] = {
            "transfers": tr.transfers_total,
            "wire_bytes_total": tr.wire_bytes_total,
            "transfer_ms_total": round(tr.transfer_ms_total, 3),
            "wire_mode": self.cluster_cfg.wire_mode,
            "bytes_per_transfer": (
                tr.wire_bytes_total // tr.transfers_total
                if tr.transfers_total else None),
            "bytes_per_ms": (
                round(tr.wire_bytes_total / tr.transfer_ms_total, 1)
                if tr.transfer_ms_total > 0 else None),
            "in_flight": tr.in_flight,
        }
        h = self.transfer_ms_hist
        if h.total:
            out["transfer_ms_p50"] = round(h.quantile(0.5), 4)
            out["transfer_ms_p99"] = round(h.quantile(0.99), 4)
        # merged decode-side latency quantiles: the per-worker streaming
        # histograms are associative — merging them equals one engine
        # having seen every retirement
        for dim in ("ttft_ms", "tpot_ms", "queue_ms", "e2e_ms",
                    "decode_step_ms"):
            merged = None
            for w in self.decode_workers:
                hw = w.engine.hists[dim]
                merged = hw if merged is None else merged.merge(hw)
            if merged is not None and merged.total:
                out[f"{dim}_p50"] = round(merged.quantile(0.5), 3)
                out[f"{dim}_p99"] = round(merged.quantile(0.99), 3)
        # summed SLO/goodput accounting across decode hosts
        reports = [w.engine._slo.report() for w in self.decode_workers
                   if w.engine._slo is not None]
        if reports:
            slo_rep: Dict[str, Any] = {
                "completed": sum(r["completed"] for r in reports),
                "good": sum(r["good"] for r in reports),
                "goodput_rps": round(
                    sum(r["goodput_rps"] for r in reports), 4),
                "throughput_rps": round(
                    sum(r["throughput_rps"] for r in reports), 4),
                "violations": {
                    k: sum(r["violations"].get(k, 0) for r in reports)
                    for k in reports[0]["violations"]},
                "slo": reports[0]["slo"],
            }
            comp = slo_rep["completed"]
            slo_rep["good_fraction"] = (round(slo_rep["good"] / comp, 4)
                                        if comp else None)
            out["slo_report"] = slo_rep
            out["goodput_rps"] = slo_rep["goodput_rps"]
            out["good_fraction"] = slo_rep["good_fraction"]
        out["prefill_hosts"] = [
            {"host": w.name, "chunks_run": w.chunks_run,
             "prefills_done": w.prefills_done,
             "backlog_tokens": w.backlog_tokens}
            for w in self.prefill_workers]
        out["decode_hosts"] = [
            {"host": w.name, "completed": w.engine.completed,
             "handoffs_admitted": w.admitted,
             "handoffs_pending": len(w._pending),
             "occupancy": w.engine.occupancy()}
            for w in self.decode_workers]
        return out

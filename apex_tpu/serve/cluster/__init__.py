"""apex_tpu.serve.cluster — disaggregated prefill/decode serving.

The multi-host tier over the single-engine serve stack (ROADMAP item 2):

* :mod:`~apex_tpu.serve.cluster.workers` — :class:`PrefillWorker`
  (chunked prefill into a staging pool, emits KV handoffs) and
  :class:`DecodeWorker` (a full :class:`~apex_tpu.serve.engine.
  InferenceEngine` admitted into via transferred blocks);
* :mod:`~apex_tpu.serve.cluster.transfer` — KV-block pack/ship/unpack
  with raw and blockwise-int8 wire modes (int8 pools transfer bitwise —
  no dequant-requant), modeled wire-byte accounting that matches the
  payload to the byte, the in-process :class:`SimTransport` and the
  real-mesh :func:`ppermute_blocks` hop;
* :mod:`~apex_tpu.serve.cluster.router` — SLO-aware admission:
  TTFT-budget feasibility against the measured prefill backlog,
  per-tenant weighted fair queueing, explicit ``shed`` terminal states;
* :mod:`~apex_tpu.serve.cluster.cluster` — :class:`ServeCluster`, the
  router → prefill → transfer → decode step loop with one shared
  monotonic clock and full lifecycle events (new ``transfer`` span).
"""

from apex_tpu.serve.cluster.cluster import (  # noqa: F401
    ClusterConfig,
    ServeCluster,
)
from apex_tpu.serve.cluster.router import (  # noqa: F401
    Router,
    RouterConfig,
    ShedDecision,
)
from apex_tpu.serve.cluster.transfer import (  # noqa: F401
    SimTransport,
    extract_blocks,
    insert_blocks,
    pack_blocks,
    payload_nbytes,
    ppermute_blocks,
    transfer_wire_bytes,
)
from apex_tpu.serve.cluster.workers import (  # noqa: F401
    DecodeWorker,
    KVHandoff,
    PrefillWorker,
)

__all__ = [
    "ClusterConfig",
    "DecodeWorker",
    "KVHandoff",
    "PrefillWorker",
    "Router",
    "RouterConfig",
    "ServeCluster",
    "ShedDecision",
    "SimTransport",
    "extract_blocks",
    "insert_blocks",
    "pack_blocks",
    "payload_nbytes",
    "ppermute_blocks",
    "transfer_wire_bytes",
]

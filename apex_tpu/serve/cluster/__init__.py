"""apex_tpu.serve.cluster — disaggregated prefill/decode serving.

The multi-host tier over the single-engine serve stack (ROADMAP item 2):

* :mod:`~apex_tpu.serve.cluster.workers` — :class:`PrefillWorker`
  (chunked prefill into a staging pool, emits KV handoffs) and
  :class:`DecodeWorker` (a full :class:`~apex_tpu.serve.engine.
  InferenceEngine` admitted into via transferred blocks);
* :mod:`~apex_tpu.serve.cluster.transfer` — KV-block pack/ship/unpack
  with raw and blockwise-int8 wire modes (int8 pools transfer bitwise —
  no dequant-requant), modeled wire-byte accounting that matches the
  payload to the byte, the in-process :class:`SimTransport` and the
  real-mesh :func:`ppermute_blocks` hop;
* :mod:`~apex_tpu.serve.cluster.router` — SLO-aware admission:
  TTFT-budget feasibility against the measured prefill backlog,
  per-tenant weighted fair queueing, explicit ``shed`` terminal states;
* :mod:`~apex_tpu.serve.cluster.cluster` — :class:`ServeCluster`, the
  router → prefill → transfer → decode step loop with one shared
  monotonic clock and full lifecycle events (``transfer`` and
  ``migrate`` spans);
* :mod:`~apex_tpu.serve.cluster.membership` — the elastic tier's health
  ledger: :class:`ClusterMembership` (alive/draining/dead states,
  heartbeat-miss detection, ``worker_join``/``worker_leave`` events)
  and :class:`AutoscalePolicy` (join/drain decisions off the
  backlog/occupancy gauges);
* :mod:`~apex_tpu.serve.cluster.chaos` — deterministic cluster fault
  injection (:class:`ClusterChaos`: kill/preempt/stall a worker at tick
  k, drop/stall/corrupt the next transfers) — the harness the live-KV-
  migration and retry claims are proven against.

The fleet observability plane (monitor tier 3) is wired through the
cluster: a trace id minted per submission threads every worker's
events (one Perfetto track per host), each worker is a
:class:`~apex_tpu.monitor.registry.FleetScraper` target (Prometheus-
style snapshots merged on the cluster clock), the
:class:`~apex_tpu.monitor.alerts.AlertEngine` drives autoscaling and
brands heartbeat/watchdog deaths, and per-worker
:class:`~apex_tpu.monitor.flight.FlightRecorder` rings dump atomically
on kill/stall/escalation for ``python -m apex_tpu.monitor.postmortem``.
"""

from apex_tpu.serve.cluster.chaos import (  # noqa: F401
    ClusterChaos,
    CorruptTransfer,
    DropTransfer,
    KillWorker,
    PreemptWorker,
    StallLink,
    StallWorker,
)
from apex_tpu.serve.cluster.cluster import (  # noqa: F401
    ClusterConfig,
    ServeCluster,
)
from apex_tpu.serve.cluster.membership import (  # noqa: F401
    AutoscalePolicy,
    ClusterMembership,
    WorkerRecord,
)
from apex_tpu.serve.cluster.router import (  # noqa: F401
    Router,
    RouterConfig,
    ShedDecision,
)
from apex_tpu.serve.cluster.transfer import (  # noqa: F401
    SimTransport,
    corrupt_payload,
    extract_blocks,
    insert_blocks,
    pack_blocks,
    payload_crc32,
    payload_nbytes,
    ppermute_blocks,
    transfer_wire_bytes,
)
from apex_tpu.serve.cluster.workers import (  # noqa: F401
    DecodeWorker,
    KVHandoff,
    PrefillWorker,
)

__all__ = [
    "AutoscalePolicy",
    "ClusterChaos",
    "ClusterConfig",
    "ClusterMembership",
    "CorruptTransfer",
    "DecodeWorker",
    "DropTransfer",
    "KVHandoff",
    "KillWorker",
    "PreemptWorker",
    "PrefillWorker",
    "Router",
    "RouterConfig",
    "ServeCluster",
    "ShedDecision",
    "SimTransport",
    "StallLink",
    "StallWorker",
    "WorkerRecord",
    "corrupt_payload",
    "extract_blocks",
    "insert_blocks",
    "pack_blocks",
    "payload_crc32",
    "payload_nbytes",
    "ppermute_blocks",
    "transfer_wire_bytes",
]

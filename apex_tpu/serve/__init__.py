"""apex_tpu.serve — continuous-batching TPU inference engine.

The serving side of the north star (reference Apex has none — its only
inference story is ``amp.initialize`` eval-mode half precision):

* :mod:`~apex_tpu.serve.kv_cache` — block-paged KV cache pools as one
  donated pytree, host-side free-list allocator, optional int8 KV
  quantization (the ``comm.quantize`` codec), modeled byte accounting;
* :mod:`~apex_tpu.serve.decode` — q_len=1 paged attention (pure-JAX
  reference + Pallas gather-attend kernel) and the ``gpt_prefill`` /
  ``gpt_decode_step`` programs built from the ``standalone_gpt`` layers;
* :mod:`~apex_tpu.serve.sampling` — in-graph greedy/temperature/top-k/
  top-p with request-intrinsic fold_in keys;
* :mod:`~apex_tpu.serve.engine` — the iteration-level continuous-batching
  :class:`InferenceEngine`: bucketed prefill + one decode program,
  admission into freed slots, EOS/max-len retirement, checkpoint loading
  via ``resilience``, telemetry via ``monitor``.
"""

from apex_tpu.serve.decode import (  # noqa: F401
    gpt_decode_step,
    gpt_prefill,
    paged_attention,
    paged_attention_reference,
    serve_logits,
)
from apex_tpu.serve.engine import (  # noqa: F401
    InferenceEngine,
    Request,
    ServeConfig,
    decode_flops_per_token,
    default_bucket_ladder,
)
from apex_tpu.serve.kv_cache import (  # noqa: F401
    BlockAllocator,
    KVCacheConfig,
    gather_kv,
    init_kv_cache,
    kv_cache_bytes,
    kv_read_bytes,
    kv_write_bytes_per_token,
    paged_write,
)
from apex_tpu.serve.sampling import (  # noqa: F401
    SamplingConfig,
    request_key,
    sample,
    step_keys,
)

__all__ = [
    "BlockAllocator",
    "InferenceEngine",
    "KVCacheConfig",
    "Request",
    "SamplingConfig",
    "ServeConfig",
    "decode_flops_per_token",
    "default_bucket_ladder",
    "gather_kv",
    "gpt_decode_step",
    "gpt_prefill",
    "init_kv_cache",
    "kv_cache_bytes",
    "kv_read_bytes",
    "kv_write_bytes_per_token",
    "paged_attention",
    "paged_attention_reference",
    "paged_write",
    "request_key",
    "sample",
    "serve_logits",
    "step_keys",
]

"""apex_tpu.serve — continuous-batching TPU inference engine.

The serving side of the north star (reference Apex has none — its only
inference story is ``amp.initialize`` eval-mode half precision):

* :mod:`~apex_tpu.serve.kv_cache` — block-paged KV cache pools as one
  donated pytree, host-side refcounted allocator with content-addressed
  **prefix caching** (hash-of-token-prefix block reuse, LRU eviction,
  copy-on-write), optional int8 KV quantization (the ``comm.quantize``
  codec), modeled byte accounting;
* :mod:`~apex_tpu.serve.decode` — paged attention (pure-JAX reference +
  Pallas gather-attend kernel) and the unified ``gpt_paged_forward``
  serve programs: ``gpt_decode_step`` (q=1), ``gpt_verify_step``
  (speculative verify, q=k+1), ``gpt_prefill_chunk`` (chunked prefill),
  plus ``gpt_prefill`` — the full-prompt flash prefill kept as the
  cold-path oracle;
* :mod:`~apex_tpu.serve.megakernel` — the fused per-layer decode block
  (``ServeConfig(megakernel=...)``): LN + QKV + paged gather-attend +
  MLP with in-kernel int8 dequant as ONE Pallas kernel per layer,
  current-token K/V folded in-register, ``gpt_decode_step_fused`` as the
  drop-in decode program;
* :mod:`~apex_tpu.serve.sampling` — in-graph greedy/temperature/top-k/
  top-p with request-intrinsic fold_in keys (position-keyed draws make
  speculative verification bitwise-exact);
* :mod:`~apex_tpu.serve.drafter` — host-side draft proposers for
  self-speculative decoding (prompt-lookup n-gram; pluggable);
* :mod:`~apex_tpu.serve.engine` — the iteration-level continuous-batching
  :class:`InferenceEngine`: ONE chunked-prefill + ONE decode program
  (+ one optional verify program), prefix-cached admission, speculative
  decode, EOS/max-len retirement, checkpoint loading via ``resilience``,
  telemetry via ``monitor``;
* :mod:`~apex_tpu.serve.adapters` — per-tenant paged LoRA serving:
  rank-r A/B deltas for QKV / out-proj / FC1 / FC2 as ONE donated paged
  pytree beside the KV pools, a host-side :class:`AdapterRegistry`
  (load/unload at runtime, refcounts while slots decode, LRU eviction of
  idle adapters — the BlockAllocator discipline applied to weights), and
  Punica-style gathered BGMV threaded through ``gpt_paged_forward`` so
  one compiled program serves every tenant (``adapter_id 0`` = base =
  exact zero delta);
* :mod:`~apex_tpu.serve.cluster` — disaggregated prefill/decode serving
  past one host: :class:`~apex_tpu.serve.cluster.ServeCluster` =
  SLO-aware router (TTFT feasibility, per-tenant WFQ, explicit ``shed``)
  → prefill workers → KV-block transfer (raw or int8 wire, modeled +
  measured byte accounting) → decode workers, with bitwise stream
  parity against the single engine;
* :mod:`~apex_tpu.serve.sharded` — pod-scale model-parallel serving:
  ``ServeConfig(plan=ParallelismPlan(...))`` +
  :func:`~apex_tpu.serve.sharded.build_engine` serve a model too big
  for one chip's HBM from a mesh slice under the SAME frozen plan that
  configures the train step — TP serving (q_len>1 exits ride the
  ``comm.overlap`` rings, proven from compiled HLO; q=1 decode stays
  monolithic), PP-staged serving (activations stream between layer
  shards, backpressure credits, ``pp_bubble_fraction``), and FSDP
  weight residency (gather-on-demand per layer via the stateless
  ``matmul_param_gather`` forward, int8 ``weight_gather`` codec) —
  streams bitwise the single-chip engine, compile gate intact.
"""

from apex_tpu.serve.adapters import (  # noqa: F401
    ADAPTER_TARGETS,
    AdapterRegistry,
    adapter_pool_bytes,
    init_adapter_pool,
    lora_delta,
    make_adapter_weights,
    merge_adapter_params,
    write_adapter,
)
from apex_tpu.serve.decode import (  # noqa: F401
    gpt_decode_step,
    gpt_paged_forward,
    gpt_prefill,
    gpt_prefill_chunk,
    gpt_verify_step,
    paged_attention,
    paged_attention_reference,
    serve_logits,
)
from apex_tpu.serve.drafter import (  # noqa: F401
    Drafter,
    NGramDrafter,
)
from apex_tpu.serve.engine import (  # noqa: F401
    InferenceEngine,
    Request,
    ServeConfig,
    decode_flops_per_token,
    default_bucket_ladder,
)
from apex_tpu.serve.kv_cache import (  # noqa: F401
    BlockAllocator,
    KVCacheConfig,
    copy_block,
    gather_kv,
    hash_block_tokens,
    init_kv_cache,
    kv_cache_bytes,
    kv_read_bytes,
    kv_write_bytes_per_token,
    paged_write,
    prefix_block_hashes,
)
from apex_tpu.serve.megakernel import (  # noqa: F401
    default_tiles,
    fused_layer_decode,
    fused_layer_verify,
    fused_live_bytes,
    gpt_decode_step_fused,
    gpt_verify_step_fused,
    megakernel_ok,
    megakernel_refusal,
)
from apex_tpu.serve.sharded import (  # noqa: F401
    PPStagedEngine,
    build_engine,
    plan_world,
    program_hlo,
    tp_transform,
)
from apex_tpu.serve.sampling import (  # noqa: F401
    SamplingConfig,
    request_key,
    sample,
    step_keys,
)
from apex_tpu.serve.cluster import (  # noqa: F401  (isort: after engine)
    AutoscalePolicy,
    ClusterChaos,
    ClusterConfig,
    ClusterMembership,
    DecodeWorker,
    KVHandoff,
    PrefillWorker,
    Router,
    RouterConfig,
    ServeCluster,
    SimTransport,
    transfer_wire_bytes,
)

__all__ = [
    "ADAPTER_TARGETS",
    "AdapterRegistry",
    "AutoscalePolicy",
    "BlockAllocator",
    "ClusterChaos",
    "ClusterConfig",
    "ClusterMembership",
    "DecodeWorker",
    "KVHandoff",
    "PrefillWorker",
    "Router",
    "RouterConfig",
    "ServeCluster",
    "SimTransport",
    "transfer_wire_bytes",
    "adapter_pool_bytes",
    "Drafter",
    "PPStagedEngine",
    "build_engine",
    "plan_world",
    "program_hlo",
    "tp_transform",
    "InferenceEngine",
    "KVCacheConfig",
    "NGramDrafter",
    "Request",
    "SamplingConfig",
    "ServeConfig",
    "copy_block",
    "decode_flops_per_token",
    "default_bucket_ladder",
    "default_tiles",
    "fused_layer_decode",
    "fused_layer_verify",
    "fused_live_bytes",
    "gather_kv",
    "gpt_decode_step",
    "gpt_decode_step_fused",
    "gpt_paged_forward",
    "gpt_prefill",
    "gpt_prefill_chunk",
    "gpt_verify_step",
    "gpt_verify_step_fused",
    "hash_block_tokens",
    "init_adapter_pool",
    "init_kv_cache",
    "kv_cache_bytes",
    "kv_read_bytes",
    "kv_write_bytes_per_token",
    "lora_delta",
    "make_adapter_weights",
    "megakernel_ok",
    "megakernel_refusal",
    "merge_adapter_params",
    "paged_attention",
    "paged_attention_reference",
    "paged_write",
    "prefix_block_hashes",
    "request_key",
    "sample",
    "serve_logits",
    "step_keys",
    "write_adapter",
]

"""Megakernel decode step — one fused Pallas block per transformer layer.

The MPK observation (arXiv 2512.22219) taken past the scheduler: at
q_len=1 the decode step's per-op work is tiny — a (slots, hidden) GEMM
here, a layer norm there — and the compiled program spends its time
dispatching ~14 XLA ops per layer rather than computing. PR 7 already
made the whole step ONE program; this module makes each layer's interior
ONE kernel:

* :func:`fused_layer_decode` — a single ``pallas_call`` per layer fusing
  **LN1 → QKV projection → paged gather-attend → output projection →
  residual → LN2 → FC1+gelu → FC2 → residual** over a ``(slots, blocks)``
  grid. The block tables ride scalar prefetch (the
  ``decode._paged_pallas`` idiom) so each grid step DMAs exactly the pool
  block it attends to, dead blocks clamp to the last live block (the
  repeated fetch is elided), and the int8 KV pools dequantize **in
  kernel** — codes and scales never round-trip through HBM as fp.
* the **current token's K/V stay in registers**: the kernel computes them
  from the QKV GEMM, folds their attention contribution directly into the
  online-softmax accumulator (at the END of the walk, mirroring the
  reference's position order), and emits them as outputs — the pool write
  stays the engine's proven ``paged_write`` ``mode="drop"`` scatter, so
  there is no in-kernel read-after-write hazard and invalid slots keep
  the exact masking contract of the unfused path. In the int8 cache the
  in-register contribution uses the codec's round-trip value
  (``clip(round(x/scale)) * scale``, scale = absmax/127 per head vector)
  — bit-for-bit what the unfused path reads back from the pool.
* :func:`gpt_decode_step_fused` — drop-in replacement for
  ``decode.gpt_decode_step``: embed, ``lax.scan`` of the fused layer
  block over the stacked layer params (cache pools riding xs/ys — one
  compiled fused block regardless of depth), final LN + logits. The
  per-layer op count drops from ~14 to 2 (fused block + K/V scatter)
  while ``decode.gpt_paged_forward`` remains the parity oracle
  (``tests/test_megakernel.py`` pins fp32 agreement and the engine-level
  greedy/sampled stream equality).

Honest gating: the fused block keeps the layer's full weight set resident
in VMEM, so :func:`megakernel_ok` refuses configurations whose per-layer
weights exceed the VMEM budget (GPT-2-124M bf16 at ~14 MB does NOT fit —
tiling the FFN GEMMs over the grid is the follow-up), MoE layers, and
tensor-parallel programs (a sharded head set needs the collective exits
the unfused path provides). ``ServeConfig(megakernel="auto")`` silently
falls back to the unfused program in those cases; ``"on"`` raises.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.ops._pallas_util import compiled_backend as _compiled_backend
from apex_tpu.ops._pallas_util import sds as _sds
from apex_tpu.ops.attention import NEG_INF
from apex_tpu.serve.kv_cache import KVCacheConfig, paged_write

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

Pytree = Any

from apex_tpu.comm.quantize import QMAX as _QMAX  # the codec's code range:
# _codec_roundtrip must track comm.quantize bit-for-bit (parity-pinned)

# The fused block holds every weight matrix of the layer in VMEM for the
# whole grid (constant index maps): qkv (h, 3h) + out (hd, h) + fc1 (h, f)
# + fc2 (f, h), plus one pool block per pool and the activation scratch.
# Budget well under the ~16 MB/core so the pool blocks and double-buffered
# windows still fit.
_VMEM_BUDGET_BYTES = 10 * 1024 * 1024


def layer_weight_bytes(cfg) -> int:
    """Resident VMEM bytes of one layer's weight set inside the fused
    block (matrices + bias/norm vectors, in the model dtype)."""
    h, f = cfg.hidden, cfg.ffn_hidden
    hd = cfg.num_heads * cfg.head_dim
    elems = h * 3 * h + hd * h + h * f + f * h  # the four GEMMs
    # qkv_b (3h) + ln1 w/b (2h) + fc1_b (f) + ln2 w/b (2h) + out_b + fc2_b
    elems += 3 * h + 2 * h + f + 2 * h + h + h
    return elems * jnp.dtype(cfg.dtype).itemsize


def megakernel_ok(cfg, kv_cfg: KVCacheConfig,
                  allow_interpret: bool = True) -> bool:
    """Whether the fused decode block supports this model/cache shape.

    Static gate, no params needed: pallas importable, no MoE, attention
    heads covering the hidden size (the residual add needs hd == h),
    head_dim lane-friendly, and the layer's weights within the VMEM
    budget. ``allow_interpret=False`` additionally requires a compiled
    Mosaic backend (the ``"auto"`` resolution off-TPU).
    """
    if not _HAS_PALLAS:
        return False
    if cfg.num_experts:
        return False
    if cfg.num_heads * cfg.head_dim != cfg.hidden:
        return False
    if kv_cfg.head_dim != cfg.head_dim or kv_cfg.head_dim % 8 != 0:
        return False
    if layer_weight_bytes(cfg) > _VMEM_BUDGET_BYTES:
        return False
    return allow_interpret or _compiled_backend()


# ---------------------------------------------------------------------------
# The fused layer kernel. Grid (slots, blocks): j walks slot i's block
# table exactly like decode._paged_kernel; the layer compute hangs off the
# walk's endpoints — QKV at j == 0 (filling the q/k/v scratch and the K/V
# outputs), the current-token softmax fold + out-proj + MLP at j == nb-1.


def _ln_rows(x, w, b, eps):
    """fp32 layer norm over the last axis — the ``layer_norm_reference``
    math (E[x²]−E[x]² with the cancellation clamp) inlined so the fused
    block and the unfused path normalize identically."""
    n = x.shape[-1]
    mean = jnp.sum(x, axis=-1, keepdims=True) / n
    msq = jnp.sum(x * x, axis=-1, keepdims=True) / n
    var = jnp.maximum(msq - mean * mean, 0.0)
    y = (x - mean) * lax.rsqrt(var + eps)
    return y * w + b


def _codec_roundtrip(x):
    """comm.quantize blockwise codec round-trip at codec-block = head_dim:
    what the unfused path reads back from an int8 pool. (H, D) fp32 in
    and out. The pool write outside re-quantizes the RAW values through
    the same deterministic codec, so the codes it stores match this
    round-trip bit-for-bit."""
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / _QMAX, 1.0)
    q = jnp.clip(jnp.round(x / scale), -_QMAX, _QMAX)
    return q * scale


def _codec_roundtrip4(x, group):
    """The int4 pool round-trip (``kv_cache._quant_rows_int4`` math):
    per-group absmax/7 scale ROUNDED TO bf16 (the stored scale dtype),
    ±7 round/clip, dequant — bit-for-bit what the unfused path reads
    back from an int4 pool. (H, D) fp32 in and out."""
    from apex_tpu.comm.quantize import QMAX4

    h, d = x.shape
    g = x.reshape(h, d // group, group)
    amax = jnp.max(jnp.abs(g), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / QMAX4, 1.0)
    scale = scale.astype(jnp.bfloat16).astype(jnp.float32)
    q = jnp.clip(jnp.round(g / scale), -QMAX4, QMAX4)
    return (q * scale).reshape(h, d)


def _fused_layer_kernel(bt_ref, len_ref, x_ref, ln1w_ref, ln1b_ref,
                        qkvk_ref, qkvb_ref, outk_ref, outb_ref,
                        ln2w_ref, ln2b_ref, fc1k_ref, fc1b_ref,
                        fc2k_ref, fc2b_ref, k_ref, v_ref, *refs,
                        scale, block_size, nb, heads, head_dim,
                        quantized, pool_dtype, eps, kv_bits=8, kv_group=0):
    if quantized:
        (ks_ref, vs_ref, xo_ref, ko_ref, vo_ref,
         q_scr, kc_scr, vc_scr, m_scr, l_scr, acc_scr) = refs
    else:
        (xo_ref, ko_ref, vo_ref,
         q_scr, kc_scr, vc_scr, m_scr, l_scr, acc_scr) = refs
    i = pl.program_id(0)
    j = pl.program_id(1)
    ctx = len_ref[i]  # OLD tokens in the pool (current token is in-register)

    @pl.when(j == 0)
    def _qkv():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)
        x = x_ref[:].astype(jnp.float32)                      # (1, h)
        h1 = _ln_rows(x, ln1w_ref[:].astype(jnp.float32),
                      ln1b_ref[:].astype(jnp.float32), eps)
        h1 = h1.astype(x_ref.dtype)
        qkv = jnp.dot(h1, qkvk_ref[:],
                      preferred_element_type=jnp.float32)
        qkv = qkv + qkvb_ref[:].astype(jnp.float32)           # (1, 3h)
        # per-head interleaved unpack (the standalone_gpt packing):
        # row-major (1, 3h) -> (H, 3, D)
        hqkv = qkv.reshape(heads, 3, head_dim)
        qh, kh, vh = hqkv[:, 0], hqkv[:, 1], hqkv[:, 2]       # (H, D) f32
        q_scr[:] = qh
        # the EMITTED values (model dtype) are what paged_write consumes —
        # the in-register fold must round-trip through that cast first,
        # or a bf16 model's codec scales/codes diverge from the pool's
        kq = kh.astype(ko_ref.dtype)
        vq = vh.astype(vo_ref.dtype)
        ko_ref[0] = kq
        vo_ref[0] = vq
        # what the pool hands back for this token: the codec round-trip
        # (int8/int4 cache) or the pool-dtype cast (fp cache)
        if quantized and kv_bits == 4:
            kc_scr[:] = _codec_roundtrip4(kq.astype(jnp.float32), kv_group)
            vc_scr[:] = _codec_roundtrip4(vq.astype(jnp.float32), kv_group)
        elif quantized:
            kc_scr[:] = _codec_roundtrip(kq.astype(jnp.float32))
            vc_scr[:] = _codec_roundtrip(vq.astype(jnp.float32))
        else:
            kc_scr[:] = kq.astype(pool_dtype).astype(jnp.float32)
            vc_scr[:] = vq.astype(pool_dtype).astype(jnp.float32)

    @pl.when(j * block_size < ctx)
    def _attend_block():
        from apex_tpu.serve.decode import _nibble_dequant

        q = q_scr[:]                      # (H, D)
        k = k_ref[:, 0]                   # (H, bs, D) | packed (H, bs, D/2)
        v = v_ref[:, 0]
        if quantized and kv_bits == 4:
            k = _nibble_dequant(k, ks_ref[:, 0], kv_group)
            v = _nibble_dequant(v, vs_ref[:, 0], kv_group)
        elif quantized:
            k = k.astype(jnp.float32) * ks_ref[:, 0][..., None]
            v = v.astype(jnp.float32) * vs_ref[:, 0][..., None]
        s = lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale       # (H, bs)
        kpos = j * block_size + lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(kpos >= ctx, NEG_INF, s)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nb - 1)
    def _finish_layer():
        # fold the current token in LAST — its position is the end of the
        # context, so the online softmax visits scores in reference order
        q = q_scr[:]
        kc = kc_scr[:]
        vc = vc_scr[:]
        s_cur = jnp.sum(q * kc, axis=1, keepdims=True) * scale  # (H, 1)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, s_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s_cur - m_new)                               # (H, 1)
        l_new = corr * l_prev + p
        acc = acc_scr[:] * corr + p * vc                         # (H, D)
        ctx_vec = acc / l_new                                    # l_new >= p > 0
        ctx_row = ctx_vec.reshape(1, heads * head_dim)
        ctx_row = ctx_row.astype(x_ref.dtype)
        a = jnp.dot(ctx_row, outk_ref[:],
                    preferred_element_type=jnp.float32)
        a = a + outb_ref[:].astype(jnp.float32)
        x1 = x_ref[:].astype(jnp.float32) + a                    # (1, h)
        h2 = _ln_rows(x1, ln2w_ref[:].astype(jnp.float32),
                      ln2b_ref[:].astype(jnp.float32), eps)
        h2 = h2.astype(x_ref.dtype)
        y = jnp.dot(h2, fc1k_ref[:],
                    preferred_element_type=jnp.float32)
        y = jax.nn.gelu(y + fc1b_ref[:].astype(jnp.float32),
                        approximate=True)
        y = y.astype(x_ref.dtype)
        m_out = jnp.dot(y, fc2k_ref[:],
                        preferred_element_type=jnp.float32)
        m_out = m_out + fc2b_ref[:].astype(jnp.float32)
        xo_ref[:] = (x1 + m_out).astype(xo_ref.dtype)


def fused_layer_decode(x, layer_params, cache_layer, cfg,
                       kv_cfg: KVCacheConfig, block_tables, ctx_lens,
                       interpret: Optional[bool] = None
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One transformer layer of the decode step as ONE fused Pallas block.

    ``x``: (n, hidden) residual-stream rows, one per slot. ``ctx_lens``:
    (n,) OLD tokens cached per slot (0 for inactive slots — the kernel
    then skips every pool block and produces finite junk from the
    in-register current token alone). Returns ``(x', k_new, v_new)`` with
    ``k_new``/``v_new`` (n, H, D) in the model dtype — the caller scatters
    them via ``paged_write`` (masking invalid slots exactly like the
    unfused path).
    """
    n, h = x.shape
    heads, d = kv_cfg.num_heads, kv_cfg.head_dim
    nb = block_tables.shape[1]
    bs = kv_cfg.block_size
    f = cfg.ffn_hidden
    if interpret is None:
        interpret = not _compiled_backend()
    lp = layer_params
    bt_flat = block_tables.reshape(-1).astype(jnp.int32)
    lens = ctx_lens.astype(jnp.int32)
    att_scale = 1.0 / math.sqrt(d)

    def row(i, j, bt, ln):       # per-slot activation rows
        return (i, 0)

    def const2(i, j, bt, ln):    # weights resident across the whole grid
        return (0, 0)

    def blk_index(i, j, bt, ln):
        # dead steps clamp at the last live block — the repeated index
        # elides the DMA (decode._paged_pallas idiom); ctx==0 stays in
        # range via the max()
        jl = jnp.maximum(ln[i] - 1, 0) // bs
        return (0, bt[i * nb + jnp.minimum(j, jl)], 0, 0)

    def blk_index_s(i, j, bt, ln):
        jl = jnp.maximum(ln[i] - 1, 0) // bs
        return (0, bt[i * nb + jnp.minimum(j, jl)], 0)

    dk = d // 2 if kv_cfg.quantized and kv_cfg.bits == 4 else d
    in_specs = [
        pl.BlockSpec((1, h), row),                 # x
        pl.BlockSpec((1, h), const2),              # ln1_w
        pl.BlockSpec((1, h), const2),              # ln1_b
        pl.BlockSpec((h, 3 * h), const2),          # qkv_kernel
        pl.BlockSpec((1, 3 * h), const2),          # qkv_bias
        pl.BlockSpec((heads * d, h), const2),      # out_kernel
        pl.BlockSpec((1, h), const2),              # out_bias
        pl.BlockSpec((1, h), const2),              # ln2_w
        pl.BlockSpec((1, h), const2),              # ln2_b
        pl.BlockSpec((h, f), const2),              # fc1_kernel
        pl.BlockSpec((1, f), const2),              # fc1_bias
        pl.BlockSpec((f, h), const2),              # fc2_kernel
        pl.BlockSpec((1, h), const2),              # fc2_bias
        pl.BlockSpec((heads, 1, bs, dk), blk_index),  # k pool
        pl.BlockSpec((heads, 1, bs, dk), blk_index),  # v pool
    ]
    vec = lambda a: a.reshape(1, -1)
    inputs = [
        x,
        vec(lp["ln1_w"]), vec(lp["ln1_b"]),
        lp["qkv_kernel"], vec(lp["qkv_bias"]),
        lp["out_kernel"], vec(lp["out_bias"]),
        vec(lp["ln2_w"]), vec(lp["ln2_b"]),
        lp["fc1_kernel"], vec(lp["fc1_bias"]),
        lp["fc2_kernel"], vec(lp["fc2_bias"]),
        cache_layer["k"], cache_layer["v"],
    ]
    if kv_cfg.quantized and kv_cfg.bits == 4:
        gdim = d // kv_cfg.kv_group
        in_specs += [pl.BlockSpec((heads, 1, bs, gdim), blk_index),
                     pl.BlockSpec((heads, 1, bs, gdim), blk_index)]
        inputs += [cache_layer["k_scale"], cache_layer["v_scale"]]
    elif kv_cfg.quantized:
        in_specs += [pl.BlockSpec((heads, 1, bs), blk_index_s),
                     pl.BlockSpec((heads, 1, bs), blk_index_s)]
        inputs += [cache_layer["k_scale"], cache_layer["v_scale"]]
    kernel = functools.partial(
        _fused_layer_kernel, scale=att_scale, block_size=bs, nb=nb,
        heads=heads, head_dim=d, quantized=kv_cfg.quantized,
        pool_dtype=kv_cfg.dtype, eps=1e-5,
        kv_bits=kv_cfg.bits if kv_cfg.quantized else 8,
        kv_group=kv_cfg.kv_group if kv_cfg.quantized else 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n, nb),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, h), row),
            pl.BlockSpec((1, heads, d), lambda i, j, bt, ln: (i, 0, 0)),
            pl.BlockSpec((1, heads, d), lambda i, j, bt, ln: (i, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((heads, d), jnp.float32),    # q
            pltpu.VMEM((heads, d), jnp.float32),    # current-token K
            pltpu.VMEM((heads, d), jnp.float32),    # current-token V
            pltpu.VMEM((heads, 128), jnp.float32),  # online-softmax m
            pltpu.VMEM((heads, 128), jnp.float32),  # online-softmax l
            pltpu.VMEM((heads, d), jnp.float32),    # acc
        ],
    )
    x_new, k_new, v_new = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _sds((n, h), x.dtype, x),
            _sds((n, heads, d), x.dtype, x),
            _sds((n, heads, d), x.dtype, x),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(bt_flat, lens, *inputs)
    return x_new, k_new, v_new


# ---------------------------------------------------------------------------
# The fused decode step: embed + scan(fused layer block + K/V scatter) +
# final LN/logits. Signature mirrors decode.gpt_decode_step (minus TP,
# which the megakernel refuses) so the engine swaps programs freely.


def gpt_decode_step_fused(params, last_tokens, seq_lens, active, cache,
                          block_tables, cfg, kv_cfg: KVCacheConfig,
                          interpret: Optional[bool] = None
                          ) -> Tuple[Pytree, jnp.ndarray]:
    """Advance every active slot by one token with the fused per-layer
    block. Bit-compatible contract with ``decode.gpt_decode_step``
    (q=1, ``tp_axis=None``): same cache-write masking, same junk-logits
    behavior for inactive slots; logits agree within fp32 tolerance
    (``tests/test_megakernel.py`` pins it, plus engine-level greedy and
    same-key sampled stream equality)."""
    from apex_tpu.serve.decode import _check_serve_cfg, _embed, serve_logits

    _check_serve_cfg(cfg, kv_cfg, None)
    if not megakernel_ok(cfg, kv_cfg, allow_interpret=True):
        raise ValueError(
            "megakernel unsupported for this config (MoE, hd != hidden, "
            "head_dim % 8, or per-layer weights over the VMEM budget) — "
            "use decode.gpt_decode_step")
    positions = jnp.minimum(seq_lens, cfg.max_seq - 1)
    x = _embed(params["embed"], last_tokens, positions, None)   # (n, h)
    ctx_old = jnp.where(active, seq_lens, 0).astype(jnp.int32)

    def body(x, xs):
        lp, cl = xs
        x, k_new, v_new = fused_layer_decode(
            x, lp, cl, cfg, kv_cfg, block_tables, ctx_old,
            interpret=interpret)
        cl = paged_write(cl, kv_cfg, k_new.transpose(1, 0, 2),
                         v_new.transpose(1, 0, 2), block_tables,
                         seq_lens, active)
        return x, cl

    x, cache = lax.scan(body, x, (params["layers"], cache))
    return cache, serve_logits(params, x, cfg, None)

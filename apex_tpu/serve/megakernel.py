"""Megakernel decode/verify step — one fused Pallas block per layer,
with the layer's weights STREAMED through VMEM as grid-indexed tiles.

The MPK observation (arXiv 2512.22219) taken past the scheduler: at small
q_len the decode step's per-op work is tiny — a (slots, hidden) GEMM
here, a layer norm there — and the compiled program spends its time
dispatching ~14 XLA ops per layer rather than computing. PR 7 made the
whole step ONE program; PR 8 made each layer's interior ONE kernel but
required the layer's full weight set resident in VMEM, so the 10 MB
budget gated OFF exactly the GPT-2-124M-class models the bench measures
(~14 MB bf16 per layer). This tier lifts that gate:

* **weight-tile streaming** — the four GEMM weights (qkv ``(h, 3h)``,
  out-proj ``(hd, h)``, fc1 ``(h, f)``, fc2 ``(f, h)``) arrive as
  BlockSpec-indexed column/row tiles over a flattened phase grid
  ``j = [qkv tiles | pool-block walk | out tiles | ffn tiles]``. Each
  tile's index map clamps outside its phase, so Mosaic elides the
  repeated fetch and double-buffers the next tile behind the current
  tile's compute. Partial results accumulate in fp32 VMEM scratch
  (gelu applies per fc1 tile — each output column's h-contraction
  completes inside its tile, so the nonlinearity is exact), and the
  single-tile degenerate ``tiles=(1, 1, 1)`` reproduces the PR-8
  resident-weight kernel op for op.
* **tile-budget gating** — :func:`megakernel_ok` now asks whether the
  MAX LIVE TILE SET fits the budget, not the whole layer:
  :func:`default_tiles` greedily splits the largest-tile matrix until
  :func:`fused_live_bytes` (tiles × double-buffering + vectors + pool
  blocks + scratch) fits, and :func:`megakernel_refusal` reports the
  measured bytes vs the budget when nothing fits. GPT-2-124M gates ON.
* :func:`fused_layer_decode` / :func:`fused_layer_verify` — the same
  kernel at q_len=1 and q_len=k+1. The verify variant computes ALL q
  fed rows' K/V in-kernel and folds them with a causal-within-window
  online softmax AFTER the pool walk (position order — row ``w``
  attends the pool's ``start_ctx`` old tokens plus fed rows ``0..w``),
  through the exact codec round-trip, so int8/int4 pool codes stay
  bitwise and logits match the unfused ``gpt_verify_step`` that writes
  first and reads back. The pool write stays the engine's proven
  ``paged_write`` scatter outside the kernel — no in-kernel
  read-after-write hazard, same invalid-row masking contract.
* :func:`gpt_decode_step_fused` / :func:`gpt_verify_step_fused` —
  drop-in replacements for ``decode.gpt_decode_step`` /
  ``decode.gpt_verify_step`` (embed, ``lax.scan`` of the fused block +
  K/V scatter over the stacked layers, final LN + logits), so with
  ``ServeConfig(megakernel=...)`` speculative decoding rides the fused
  path end to end. ``decode.gpt_paged_forward`` remains the parity
  oracle (``tests/test_megakernel.py`` pins fp32 agreement, bitwise
  quantized pool codes, and engine-level stream equality).

Honest gating, unchanged in spirit: MoE layers, TP-sharded programs,
LoRA adapters and lane-hostile head_dims still refuse (the unfused path
provides the collective exits / adapter deltas), and a config whose
FINEST valid tiling still exceeds the budget refuses with the measured
bytes. ``megakernel="auto"`` silently falls back (warn-once, with the
reason); ``"on"`` raises.
"""

from __future__ import annotations

import functools
import math
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.ops._pallas_util import compiled_backend as _compiled_backend
from apex_tpu.ops._pallas_util import sds as _sds
from apex_tpu.ops.attention import NEG_INF
from apex_tpu.serve.kv_cache import KVCacheConfig, paged_write

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

Pytree = Any

from apex_tpu.comm.quantize import QMAX as _QMAX  # the codec's code range:
# _codec_roundtrip must track comm.quantize bit-for-bit (parity-pinned)

# VMEM budget for the fused block's live set: the CURRENT weight tiles
# (double-buffered while their phase streams), the resident bias/norm
# vectors, one pool block per pool (double-buffered walk) and the fp32
# activation scratch. Well under the ~16 MB/core so Mosaic keeps
# headroom for its own spills.
_VMEM_BUDGET_BYTES = 10 * 1024 * 1024
_LANE = 128


def layer_weight_bytes(cfg) -> int:
    """FULL-RESIDENCY bytes of one layer's weight set (matrices +
    bias/norm vectors, in the model dtype) — what the PR-8 kernel kept
    live and what ``tiles=(1, 1, 1)`` still keeps live. The gate itself
    compares :func:`fused_live_bytes` at :func:`default_tiles`."""
    h, f = cfg.hidden, cfg.ffn_hidden
    hd = cfg.num_heads * cfg.head_dim
    elems = h * 3 * h + hd * h + h * f + f * h  # the four GEMMs
    # qkv_b (3h) + ln1 w/b (2h) + fc1_b (f) + ln2 w/b (2h) + out_b + fc2_b
    elems += 3 * h + 2 * h + f + 2 * h + h + h
    return elems * jnp.dtype(cfg.dtype).itemsize


def _tiled_dims(cfg) -> Tuple[int, int, int]:
    """The dim each tile count divides: qkv columns (3h), out-proj
    columns (h), and the shared ffn axis f (fc1 columns == fc2 rows)."""
    return 3 * cfg.hidden, cfg.hidden, cfg.ffn_hidden


def _valid_tile_counts(dim: int, compiled: bool = True) -> List[int]:
    """Tile counts that evenly divide ``dim``. Count 1 (full residency —
    the PR-8 path) is always valid; compiled Mosaic additionally needs
    every streamed tile lane-aligned (``dim // t`` a multiple of 128) so
    the BlockSpec slices land on register boundaries. Interpret mode
    (the CPU test rig) accepts any even division."""
    out = [1]
    for t in range(2, dim + 1):
        if dim % t:
            continue
        if compiled and (dim // t) % _LANE:
            continue
        out.append(t)
    return out


def _axis_live_bytes(cfg, axis: int, t: int) -> int:
    """Live VMEM bytes of one tiled matrix group at tile count ``t``:
    the current tile, times two when streaming (Mosaic double-buffers
    the next tile's DMA behind the current tile's compute; at t == 1
    the constant index map means one resident buffer, no prefetch)."""
    h, f = cfg.hidden, cfg.ffn_hidden
    hd = cfg.num_heads * cfg.head_dim
    w = jnp.dtype(cfg.dtype).itemsize
    buf = 2 if t > 1 else 1
    if axis == 0:                       # qkv (h, 3h) column tiles
        return h * (3 * h // t) * w * buf
    if axis == 1:                       # out-proj (hd, h) column tiles
        return hd * (h // t) * w * buf
    # ffn: fc1 (h, f/t) column tile + fc2 (f/t, h) row tile
    return (h * (f // t) + (f // t) * h) * w * buf


def fused_live_bytes(cfg, kv_cfg: KVCacheConfig,
                     tiles: Tuple[int, int, int], q: int = 1) -> int:
    """Peak VMEM bytes of the fused block at weight tiling ``tiles =
    (t_qkv, t_out, t_ffn)`` and ``q`` fed rows per slot: live weight
    tiles (clamped index maps keep ONE tile of every matrix resident
    across the whole grid, double-buffered while streaming), resident
    bias/norm vectors, the double-buffered pool-block pair, the
    activation blocks and the fp32 scratch set."""
    t_qkv, t_out, t_ffn = tiles
    h, f = cfg.hidden, cfg.ffn_hidden
    heads, d = cfg.num_heads, cfg.head_dim
    hd = heads * d
    w = jnp.dtype(cfg.dtype).itemsize
    total = sum(_axis_live_bytes(cfg, a, t)
                for a, t in enumerate((t_qkv, t_out, t_ffn)))
    total += (3 * h + 2 * h + f + 2 * h + h + h) * w  # resident vectors
    bs = kv_cfg.block_size
    if kv_cfg.quantized and kv_cfg.bits == 4:
        # packed uint8 codes + bf16 group scales
        pool = heads * bs * (d // 2) + heads * bs * (d // kv_cfg.kv_group) * 2
    elif kv_cfg.quantized:
        pool = heads * bs * d + heads * bs * 4  # int8 codes + fp32 scales
    else:
        pool = heads * bs * d * jnp.dtype(kv_cfg.dtype).itemsize
    total += 2 * 2 * pool                       # k+v pools, double-buffered
    total += (2 * q * h + 2 * q * hd) * w       # x/x' + emitted K/V blocks
    # fp32 scratch: h1/x1/h2/mlp (q,h) + qkv (q,3h) + ctx (q,hd) +
    # q/kc/vc/acc rows (q,H,D) + online-softmax m/l (q,H,128)
    total += 4 * (4 * q * h + q * 3 * h + q * hd + 4 * q * hd
                  + 2 * q * heads * _LANE)
    return int(total)


def default_tiles(cfg, kv_cfg: KVCacheConfig, q: int = 1,
                  compiled: bool = True
                  ) -> Optional[Tuple[int, int, int]]:
    """Coarsest weight tiling whose live set fits the VMEM budget.

    Greedy: start at full residency ``(1, 1, 1)`` (the PR-8 fast path —
    no streaming DMAs at all) and, while over budget, split whichever
    matrix group currently holds the most live bytes to its next valid
    count that strictly shrinks it (t=1 -> t=2 shrinks nothing: the
    streaming double-buffer cancels the halving). Returns ``None`` when
    even the finest valid tiling does not fit (the refusal path)."""
    dims = _tiled_dims(cfg)
    counts = [_valid_tile_counts(dim, compiled) for dim in dims]
    tiles = [1, 1, 1]
    while fused_live_bytes(cfg, kv_cfg, tuple(tiles), q=q) \
            > _VMEM_BUDGET_BYTES:
        best_axis, best_next = None, None
        best_cur = -1
        for a in range(3):
            cur = _axis_live_bytes(cfg, a, tiles[a])
            nxt = next((t for t in counts[a]
                        if t > tiles[a] and _axis_live_bytes(cfg, a, t) < cur),
                       None)
            if nxt is not None and cur > best_cur:
                best_axis, best_next, best_cur = a, nxt, cur
        if best_axis is None:
            return None
        tiles[best_axis] = best_next
    return tuple(tiles)


def _finest_tiles(cfg, compiled: bool = True) -> Tuple[int, int, int]:
    return tuple(_valid_tile_counts(dim, compiled)[-1]
                 for dim in _tiled_dims(cfg))


def megakernel_refusal(cfg, kv_cfg: KVCacheConfig,
                       allow_interpret: bool = True,
                       q: int = 1) -> Optional[str]:
    """Why the fused block refuses this model/cache shape — ``None``
    when it is supported. Budget refusals report the MEASURED bytes
    (finest-tiling live set vs the budget) so operators see how far
    over a config is, not a bare no."""
    if not _HAS_PALLAS:
        return "pallas is not importable"
    if cfg.num_experts:
        return ("MoE layers (num_experts > 0) — the fused block assumes "
                "a dense FFN (ROADMAP item 5a)")
    if cfg.num_heads * cfg.head_dim != cfg.hidden:
        return (f"num_heads * head_dim ({cfg.num_heads} * {cfg.head_dim} "
                f"= {cfg.num_heads * cfg.head_dim}) != hidden "
                f"({cfg.hidden}) — the residual add needs hd == h")
    if kv_cfg.head_dim != cfg.head_dim or kv_cfg.head_dim % 8 != 0:
        return (f"head_dim {kv_cfg.head_dim} must match the model "
                f"({cfg.head_dim}) and be a multiple of 8 (sublane "
                f"alignment)")
    compiled = _compiled_backend()
    if not allow_interpret and not compiled:
        return ("no compiled Mosaic backend (interpret mode simulates "
                "the kernel — it saves no dispatch)")
    tiles = default_tiles(cfg, kv_cfg, q=q, compiled=compiled)
    if tiles is None:
        finest = _finest_tiles(cfg, compiled)
        live = fused_live_bytes(cfg, kv_cfg, finest, q=q)
        return (f"per-layer weights {layer_weight_bytes(cfg)} B resident; "
                f"even the finest weight tiling {finest} keeps "
                f"{live} B live, over the {_VMEM_BUDGET_BYTES} B VMEM "
                f"budget")
    return None


def megakernel_ok(cfg, kv_cfg: KVCacheConfig,
                  allow_interpret: bool = True, q: int = 1) -> bool:
    """Whether the fused decode/verify block supports this model/cache
    shape. Static gate, no params needed: pallas importable, no MoE,
    attention heads covering the hidden size (the residual add needs
    hd == h), head_dim lane-friendly, and SOME weight tiling whose live
    tile set fits the VMEM budget (``default_tiles``) — full residency
    is no longer required. ``allow_interpret=False`` additionally
    requires a compiled Mosaic backend (the ``"auto"`` resolution
    off-TPU)."""
    return megakernel_refusal(cfg, kv_cfg,
                              allow_interpret=allow_interpret, q=q) is None


# configs whose silent fused->unfused auto-fallback was already logged
# (warn ONCE per reason — the decode._warn_reference_fallback pattern:
# a slower serve run must be diagnosable from the log, not only from
# the bench line's decode_kernel field)
_FALLBACK_WARNED: set = set()


def warn_megakernel_fallback(reason: str) -> None:
    """Log (once per distinct reason) that ``megakernel="auto"`` fell
    back to the per-op layer body on a compiled backend — with the
    measured-bytes refusal text so operators see how far over budget
    (or which shape rule) the config was."""
    if reason in _FALLBACK_WARNED:
        return
    _FALLBACK_WARNED.add(reason)
    from apex_tpu._logging import get_logger

    get_logger("apex_tpu.serve").warning(
        "megakernel='auto': falling back to the unfused per-op decode "
        "path — %s", reason)


def _check_tiles(cfg, tiles: Tuple[int, int, int], compiled: bool) -> None:
    names = ("qkv-column (3*hidden)", "out-proj-column (hidden)",
             "ffn-axis (ffn_hidden)")
    for t, dim, nm in zip(tiles, _tiled_dims(cfg), names):
        if t < 1 or dim % t:
            raise ValueError(
                f"megakernel weight-tile count {t} does not divide the "
                f"{nm} dim {dim}; valid counts: "
                f"{_valid_tile_counts(dim, compiled)}")
        if compiled and t > 1 and (dim // t) % _LANE:
            raise ValueError(
                f"compiled Mosaic needs lane-aligned weight tiles: "
                f"{nm} {dim} / {t} = {dim // t} is not a multiple of "
                f"{_LANE}; valid counts: {_valid_tile_counts(dim, True)}")


# ---------------------------------------------------------------------------
# The fused block kernel. Grid (slots, S) with S = tq + nb + to + tf — a
# single flattened phase axis per slot:
#
#   j in [0, tq)           qkv column tiles (LN1 + per-tile GEMM)
#   j in [tq, tq+nb)       pool-block gather-attend walk (all q rows)
#   j in [b_end, b_end+to) out-proj column tiles -> fp32 residual x1
#   j in [c_end, c_end+tf) ffn tiles: fc1 col + gelu + fc2 row, fp32 acc
#
# Each weight's index map clamps outside its phase, so its current tile
# stays resident (DMA elided) and streams only while its phase runs.
# Tile bodies are STATICALLY UNROLLED Python loops guarded by
# ``pl.when(j == step)`` writing STATIC scratch slices — no dynamic
# lane-dim stores for Mosaic to refuse. Per-row work (q_len rows) is
# likewise unrolled with rows on a LEADING (untiled) scratch dim, so
# every per-row body is byte-identical to the PR-8 q=1 kernel.


def _ln_rows(x, w, b, eps):
    """fp32 layer norm over the last axis — the ``layer_norm_reference``
    math (E[x²]−E[x]² with the cancellation clamp) inlined so the fused
    block and the unfused path normalize identically."""
    n = x.shape[-1]
    mean = jnp.sum(x, axis=-1, keepdims=True) / n
    msq = jnp.sum(x * x, axis=-1, keepdims=True) / n
    var = jnp.maximum(msq - mean * mean, 0.0)
    y = (x - mean) * lax.rsqrt(var + eps)
    return y * w + b


def _codec_roundtrip(x):
    """comm.quantize blockwise codec round-trip at codec-block = head_dim:
    what the unfused path reads back from an int8 pool. (H, D) fp32 in
    and out. The pool write outside re-quantizes the RAW values through
    the same deterministic codec, so the codes it stores match this
    round-trip bit-for-bit."""
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / _QMAX, 1.0)
    q = jnp.clip(jnp.round(x / scale), -_QMAX, _QMAX)
    return q * scale


def _codec_roundtrip4(x, group):
    """The int4 pool round-trip (``kv_cache._quant_rows_int4`` math):
    per-group absmax/7 scale ROUNDED TO bf16 (the stored scale dtype),
    ±7 round/clip, dequant — bit-for-bit what the unfused path reads
    back from an int4 pool. (H, D) fp32 in and out."""
    from apex_tpu.comm.quantize import QMAX4

    h, d = x.shape
    g = x.reshape(h, d // group, group)
    amax = jnp.max(jnp.abs(g), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / QMAX4, 1.0)
    scale = scale.astype(jnp.bfloat16).astype(jnp.float32)
    q = jnp.clip(jnp.round(g / scale), -QMAX4, QMAX4)
    return (q * scale).reshape(h, d)


def _fused_block_kernel(bt_ref, len_ref, x_ref, ln1w_ref, ln1b_ref,
                        qkvk_ref, qkvb_ref, outk_ref, outb_ref,
                        ln2w_ref, ln2b_ref, fc1k_ref, fc1b_ref,
                        fc2k_ref, fc2b_ref, k_ref, v_ref, *refs,
                        scale, block_size, nb, heads, head_dim, q_rows,
                        tiles, quantized, pool_dtype, eps,
                        kv_bits=8, kv_group=0):
    tq, to, tf = tiles
    if quantized:
        (ks_ref, vs_ref, xo_ref, ko_ref, vo_ref,
         h1_scr, qkv_scr, q_scr, kc_scr, vc_scr, m_scr, l_scr, acc_scr,
         ctx_scr, x1_scr, h2_scr, mlp_scr) = refs
    else:
        (xo_ref, ko_ref, vo_ref,
         h1_scr, qkv_scr, q_scr, kc_scr, vc_scr, m_scr, l_scr, acc_scr,
         ctx_scr, x1_scr, h2_scr, mlp_scr) = refs
    i = pl.program_id(0)
    j = pl.program_id(1)
    ctx = len_ref[i]  # OLD tokens in the pool (fed rows are in-register)
    h = x_ref.shape[-1]
    hd = heads * head_dim
    a_end = tq
    b_end = tq + nb
    c_end = b_end + to
    ct3 = (3 * h) // tq
    co = h // to
    cf = fc1k_ref.shape[-1]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)
        x = x_ref[0].astype(jnp.float32)                      # (q, h)
        h1_scr[:] = _ln_rows(x, ln1w_ref[:].astype(jnp.float32),
                             ln1b_ref[:].astype(jnp.float32), eps)

    # phase A: qkv column tiles. Each body writes a STATIC column slice
    # of the qkv scratch; the h-contraction is full per tile, so every
    # output column matches the resident-weight dot exactly.
    for t in range(tq):
        @pl.when(j == t)
        def _qkv_tile(t=t):
            h1 = h1_scr[:].astype(x_ref.dtype)
            part = jnp.dot(h1, qkvk_ref[:],
                           preferred_element_type=jnp.float32)  # (q, ct3)
            part = part + qkvb_ref[:, t * ct3:(t + 1) * ct3].astype(
                jnp.float32)
            qkv_scr[:, t * ct3:(t + 1) * ct3] = part

    @pl.when(j == a_end - 1)
    def _emit_qkv():
        # per-head interleaved unpack (the standalone_gpt packing), one
        # fed row at a time: row-major (1, 3h) -> (H, 3, D)
        for w in range(q_rows):
            hq = qkv_scr[w:w + 1, :].reshape(heads, 3, head_dim)
            qh, kh, vh = hq[:, 0], hq[:, 1], hq[:, 2]         # (H, D) f32
            q_scr[w] = qh
            # the EMITTED values (model dtype) are what paged_write
            # consumes — the in-register fold must round-trip through
            # that cast first, or a bf16 model's codec scales/codes
            # diverge from the pool's
            kq = kh.astype(ko_ref.dtype)
            vq = vh.astype(vo_ref.dtype)
            ko_ref[0, w] = kq
            vo_ref[0, w] = vq
            # what the pool hands back for this row: the codec
            # round-trip (int8/int4 cache) or the pool-dtype cast
            if quantized and kv_bits == 4:
                kc_scr[w] = _codec_roundtrip4(kq.astype(jnp.float32),
                                              kv_group)
                vc_scr[w] = _codec_roundtrip4(vq.astype(jnp.float32),
                                              kv_group)
            elif quantized:
                kc_scr[w] = _codec_roundtrip(kq.astype(jnp.float32))
                vc_scr[w] = _codec_roundtrip(vq.astype(jnp.float32))
            else:
                kc_scr[w] = kq.astype(pool_dtype).astype(jnp.float32)
                vc_scr[w] = vq.astype(pool_dtype).astype(jnp.float32)

    @pl.when((j >= a_end) & (j < b_end)
             & ((j - a_end) * block_size < ctx))
    def _attend_block():
        from apex_tpu.serve.decode import _nibble_dequant

        k = k_ref[:, 0]              # (H, bs, D) | packed (H, bs, D/2)
        v = v_ref[:, 0]
        if quantized and kv_bits == 4:
            k = _nibble_dequant(k, ks_ref[:, 0], kv_group)
            v = _nibble_dequant(v, vs_ref[:, 0], kv_group)
        elif quantized:
            k = k.astype(jnp.float32) * ks_ref[:, 0][..., None]
            v = v.astype(jnp.float32) * vs_ref[:, 0][..., None]
        for w in range(q_rows):
            qw = q_scr[w]                                     # (H, D)
            s = lax.dot_general(
                qw, k, (((1,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32) * scale   # (H, bs)
            kpos = ((j - a_end) * block_size
                    + lax.broadcasted_iota(jnp.int32, s.shape, 1))
            s = jnp.where(kpos >= ctx, NEG_INF, s)
            m_prev = m_scr[w][:, :1]
            l_prev = l_scr[w][:, :1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            corr = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new)
            l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
            acc_scr[w] = acc_scr[w] * corr + lax.dot_general(
                p.astype(v.dtype), v, (((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            m_scr[w] = jnp.broadcast_to(m_new, (heads, _LANE))
            l_scr[w] = jnp.broadcast_to(l_new, (heads, _LANE))

    @pl.when(j == b_end - 1)
    def _fold_window():
        # fold the in-register fed rows LAST, in POSITION order — their
        # positions are the end of each row's context, so the online
        # softmax visits scores exactly as the reference does. Row w
        # attends fed rows 0..w (causal within the window); the diagonal
        # is always allowed, so even ctx == 0 slots stay finite.
        for w in range(q_rows):
            qw = q_scr[w]
            m_prev = m_scr[w][:, :1]
            l_prev = l_scr[w][:, :1]
            acc = acc_scr[w]
            for t in range(w + 1):
                kc = kc_scr[t]
                vc = vc_scr[t]
                s_cur = jnp.sum(qw * kc, axis=1,
                                keepdims=True) * scale        # (H, 1)
                m_new = jnp.maximum(m_prev, s_cur)
                corr = jnp.exp(m_prev - m_new)
                p = jnp.exp(s_cur - m_new)
                l_new = corr * l_prev + p
                acc = acc * corr + p * vc
                m_prev, l_prev = m_new, l_new
            ctx_vec = acc / l_prev                     # l >= p(diag) > 0
            ctx_scr[w:w + 1, :] = ctx_vec.reshape(1, hd)

    # phase C: out-proj column tiles -> the fp32 residual x1
    for t in range(to):
        @pl.when(j == b_end + t)
        def _out_tile(t=t):
            ctx_rows = ctx_scr[:].astype(x_ref.dtype)         # (q, hd)
            a = jnp.dot(ctx_rows, outk_ref[:],
                        preferred_element_type=jnp.float32)   # (q, co)
            a = a + outb_ref[:, t * co:(t + 1) * co].astype(jnp.float32)
            x1_scr[:, t * co:(t + 1) * co] = (
                x_ref[0][:, t * co:(t + 1) * co].astype(jnp.float32) + a)

    @pl.when(j == c_end - 1)
    def _ln2():
        h2_scr[:] = _ln_rows(x1_scr[:], ln2w_ref[:].astype(jnp.float32),
                             ln2b_ref[:].astype(jnp.float32), eps)
        mlp_scr[:] = jnp.zeros_like(mlp_scr)

    # phase D: ffn tiles — fc1 column tile (gelu exact: each output
    # column's h-contraction completes inside its tile) + fc2 row tile,
    # partials accumulating in fp32
    for t in range(tf):
        @pl.when(j == c_end + t)
        def _ffn_tile(t=t):
            h2 = h2_scr[:].astype(x_ref.dtype)
            y = jnp.dot(h2, fc1k_ref[:],
                        preferred_element_type=jnp.float32)   # (q, cf)
            y = jax.nn.gelu(
                y + fc1b_ref[:, t * cf:(t + 1) * cf].astype(jnp.float32),
                approximate=True)
            y = y.astype(x_ref.dtype)
            mlp_scr[:] = mlp_scr[:] + jnp.dot(
                y, fc2k_ref[:], preferred_element_type=jnp.float32)

    @pl.when(j == c_end + tf - 1)
    def _emit():
        m_out = mlp_scr[:] + fc2b_ref[:].astype(jnp.float32)
        xo_ref[0] = (x1_scr[:] + m_out).astype(xo_ref.dtype)


def _fused_block(x, layer_params, cache_layer, cfg,
                 kv_cfg: KVCacheConfig, block_tables, ctx_lens,
                 tiles: Optional[Tuple[int, int, int]],
                 interpret: Optional[bool]
                 ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The shared pallas_call builder: ``x`` (n, q, h) fed rows ->
    ``(x', k_new (n, q, H, D), v_new)``."""
    n, q, h = x.shape
    heads, d = kv_cfg.num_heads, kv_cfg.head_dim
    nb = block_tables.shape[1]
    bs = kv_cfg.block_size
    f = cfg.ffn_hidden
    if interpret is None:
        interpret = not _compiled_backend()
    if tiles is None:
        tiles = default_tiles(cfg, kv_cfg, q=q, compiled=not interpret)
        if tiles is None:
            raise ValueError(
                megakernel_refusal(cfg, kv_cfg, q=q)
                or "megakernel: no weight tiling fits the VMEM budget")
    _check_tiles(cfg, tiles, compiled=not interpret)
    tq, to, tf = tiles
    a_end, b_end, c_end = tq, tq + nb, tq + nb + to
    steps = c_end + tf
    lp = layer_params
    bt_flat = block_tables.reshape(-1).astype(jnp.int32)
    lens = ctx_lens.astype(jnp.int32)
    att_scale = 1.0 / math.sqrt(d)

    def row3(i, j, bt, ln):      # per-slot activation rows
        return (i, 0, 0)

    def const2(i, j, bt, ln):    # vectors resident across the whole grid
        return (0, 0)

    # each weight's tile index clamps OUTSIDE its phase: the repeated
    # index elides the DMA, so the tile streams only while its phase runs
    def qkv_tile(i, j, bt, ln):
        return (0, jnp.minimum(j, tq - 1))

    def out_tile(i, j, bt, ln):
        return (0, jnp.clip(j - b_end, 0, to - 1))

    def fc1_tile(i, j, bt, ln):
        return (0, jnp.clip(j - c_end, 0, tf - 1))

    def fc2_tile(i, j, bt, ln):
        return (jnp.clip(j - c_end, 0, tf - 1), 0)

    def blk_index(i, j, bt, ln):
        # dead steps clamp at the last live block — the repeated index
        # elides the DMA (decode._paged_pallas idiom); ctx==0 stays in
        # range via the max(); j < a_end clamps to the walk's first block
        jl = jnp.maximum(ln[i] - 1, 0) // bs
        return (0, bt[i * nb + jnp.clip(j - a_end, 0, jl)], 0, 0)

    def blk_index_s(i, j, bt, ln):
        jl = jnp.maximum(ln[i] - 1, 0) // bs
        return (0, bt[i * nb + jnp.clip(j - a_end, 0, jl)], 0)

    dk = d // 2 if kv_cfg.quantized and kv_cfg.bits == 4 else d
    in_specs = [
        pl.BlockSpec((1, q, h), row3),             # x
        pl.BlockSpec((1, h), const2),              # ln1_w
        pl.BlockSpec((1, h), const2),              # ln1_b
        pl.BlockSpec((h, 3 * h // tq), qkv_tile),  # qkv_kernel tile
        pl.BlockSpec((1, 3 * h), const2),          # qkv_bias
        pl.BlockSpec((heads * d, h // to), out_tile),  # out_kernel tile
        pl.BlockSpec((1, h), const2),              # out_bias
        pl.BlockSpec((1, h), const2),              # ln2_w
        pl.BlockSpec((1, h), const2),              # ln2_b
        pl.BlockSpec((h, f // tf), fc1_tile),      # fc1_kernel tile
        pl.BlockSpec((1, f), const2),              # fc1_bias
        pl.BlockSpec((f // tf, h), fc2_tile),      # fc2_kernel tile
        pl.BlockSpec((1, h), const2),              # fc2_bias
        pl.BlockSpec((heads, 1, bs, dk), blk_index),  # k pool
        pl.BlockSpec((heads, 1, bs, dk), blk_index),  # v pool
    ]
    vec = lambda a: a.reshape(1, -1)
    inputs = [
        x,
        vec(lp["ln1_w"]), vec(lp["ln1_b"]),
        lp["qkv_kernel"], vec(lp["qkv_bias"]),
        lp["out_kernel"], vec(lp["out_bias"]),
        vec(lp["ln2_w"]), vec(lp["ln2_b"]),
        lp["fc1_kernel"], vec(lp["fc1_bias"]),
        lp["fc2_kernel"], vec(lp["fc2_bias"]),
        cache_layer["k"], cache_layer["v"],
    ]
    if kv_cfg.quantized and kv_cfg.bits == 4:
        gdim = d // kv_cfg.kv_group
        in_specs += [pl.BlockSpec((heads, 1, bs, gdim), blk_index),
                     pl.BlockSpec((heads, 1, bs, gdim), blk_index)]
        inputs += [cache_layer["k_scale"], cache_layer["v_scale"]]
    elif kv_cfg.quantized:
        in_specs += [pl.BlockSpec((heads, 1, bs), blk_index_s),
                     pl.BlockSpec((heads, 1, bs), blk_index_s)]
        inputs += [cache_layer["k_scale"], cache_layer["v_scale"]]
    kernel = functools.partial(
        _fused_block_kernel, scale=att_scale, block_size=bs, nb=nb,
        heads=heads, head_dim=d, q_rows=q, tiles=tiles,
        quantized=kv_cfg.quantized, pool_dtype=kv_cfg.dtype, eps=1e-5,
        kv_bits=kv_cfg.bits if kv_cfg.quantized else 8,
        kv_group=kv_cfg.kv_group if kv_cfg.quantized else 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n, steps),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, q, h), row3),
            pl.BlockSpec((1, q, heads, d),
                         lambda i, j, bt, ln: (i, 0, 0, 0)),
            pl.BlockSpec((1, q, heads, d),
                         lambda i, j, bt, ln: (i, 0, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((q, h), jnp.float32),          # h1 (LN1 rows)
            pltpu.VMEM((q, 3 * h), jnp.float32),      # qkv accumulator
            pltpu.VMEM((q, heads, d), jnp.float32),   # q rows
            pltpu.VMEM((q, heads, d), jnp.float32),   # fed-row K
            pltpu.VMEM((q, heads, d), jnp.float32),   # fed-row V
            pltpu.VMEM((q, heads, _LANE), jnp.float32),  # softmax m
            pltpu.VMEM((q, heads, _LANE), jnp.float32),  # softmax l
            pltpu.VMEM((q, heads, d), jnp.float32),   # softmax acc
            pltpu.VMEM((q, heads * d), jnp.float32),  # attended ctx rows
            pltpu.VMEM((q, h), jnp.float32),          # residual x1
            pltpu.VMEM((q, h), jnp.float32),          # h2 (LN2 rows)
            pltpu.VMEM((q, h), jnp.float32),          # mlp accumulator
        ],
    )
    x_new, k_new, v_new = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _sds((n, q, h), x.dtype, x),
            _sds((n, q, heads, d), x.dtype, x),
            _sds((n, q, heads, d), x.dtype, x),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(bt_flat, lens, *inputs)
    return x_new, k_new, v_new


def fused_layer_decode(x, layer_params, cache_layer, cfg,
                       kv_cfg: KVCacheConfig, block_tables, ctx_lens,
                       interpret: Optional[bool] = None,
                       tiles: Optional[Tuple[int, int, int]] = None
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One transformer layer of the decode step as ONE fused Pallas block.

    ``x``: (n, hidden) residual-stream rows, one per slot. ``ctx_lens``:
    (n,) OLD tokens cached per slot (0 for inactive slots — the kernel
    then skips every pool block and produces finite junk from the
    in-register current token alone). ``tiles``: the weight-tile counts
    ``(t_qkv, t_out, t_ffn)``; ``None`` picks :func:`default_tiles`
    (full residency when it fits — the PR-8 path — else the coarsest
    streaming split that fits). Returns ``(x', k_new, v_new)`` with
    ``k_new``/``v_new`` (n, H, D) in the model dtype — the caller
    scatters them via ``paged_write`` (masking invalid slots exactly
    like the unfused path).
    """
    x_new, k_new, v_new = _fused_block(
        x[:, None, :], layer_params, cache_layer, cfg, kv_cfg,
        block_tables, ctx_lens, tiles, interpret)
    return x_new[:, 0], k_new[:, 0], v_new[:, 0]


def fused_layer_verify(x, layer_params, cache_layer, cfg,
                       kv_cfg: KVCacheConfig, block_tables, start_ctx,
                       interpret: Optional[bool] = None,
                       tiles: Optional[Tuple[int, int, int]] = None
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One transformer layer of the VERIFY step (q fed rows per slot) as
    ONE fused Pallas block.

    ``x``: (n, q, hidden) — each slot's last sampled token plus its
    drafted continuation, embedded. ``start_ctx``: (n,) OLD tokens in
    the pool BEFORE the fed window (0 for inactive slots). Row ``w``
    attends the pool's ``start_ctx`` tokens plus fed rows ``0..w``
    (causal within the window), with every in-register contribution
    passed through the exact pool codec round-trip — so logits match the
    unfused ``gpt_verify_step`` (which writes all q rows first, then
    reads them back) on every VALID row. Rows past ``n_fed`` differ only
    in their junk (the unfused path zeroes their context; this kernel
    gives them the causal window) — both are finite and masked by the
    engine's acceptance loop. Returns ``(x', k_new (n, q, H, D), v_new)``
    for the caller's masked ``paged_write``.
    """
    return _fused_block(x, layer_params, cache_layer, cfg, kv_cfg,
                        block_tables, start_ctx, tiles, interpret)


# ---------------------------------------------------------------------------
# The fused serve programs: embed + scan(fused block + K/V scatter) +
# final LN/logits. Signatures mirror decode.gpt_decode_step /
# decode.gpt_verify_step (minus TP/LoRA, which the megakernel refuses)
# so the engine swaps programs freely.


def gpt_decode_step_fused(params, last_tokens, seq_lens, active, cache,
                          block_tables, cfg, kv_cfg: KVCacheConfig,
                          interpret: Optional[bool] = None,
                          tiles: Optional[Tuple[int, int, int]] = None
                          ) -> Tuple[Pytree, jnp.ndarray]:
    """Advance every active slot by one token with the fused per-layer
    block. Bit-compatible contract with ``decode.gpt_decode_step``
    (q=1, ``tp_axis=None``): same cache-write masking, same junk-logits
    behavior for inactive slots; logits agree within fp32 tolerance
    (``tests/test_megakernel.py`` pins it, plus engine-level greedy and
    same-key sampled stream equality)."""
    from apex_tpu.serve.decode import _check_serve_cfg, _embed, serve_logits

    _check_serve_cfg(cfg, kv_cfg, None)
    refusal = megakernel_refusal(cfg, kv_cfg, allow_interpret=True)
    if refusal is not None:
        raise ValueError(f"megakernel unsupported: {refusal} — use "
                         f"decode.gpt_decode_step")
    positions = jnp.minimum(seq_lens, cfg.max_seq - 1)
    x = _embed(params["embed"], last_tokens, positions, None)   # (n, h)
    ctx_old = jnp.where(active, seq_lens, 0).astype(jnp.int32)

    def body(x, xs):
        lp, cl = xs
        x, k_new, v_new = fused_layer_decode(
            x, lp, cl, cfg, kv_cfg, block_tables, ctx_old,
            interpret=interpret, tiles=tiles)
        cl = paged_write(cl, kv_cfg, k_new.transpose(1, 0, 2),
                         v_new.transpose(1, 0, 2), block_tables,
                         seq_lens, active)
        return x, cl

    x, cache = lax.scan(body, x, (params["layers"], cache))
    return cache, serve_logits(params, x, cfg, None)


def gpt_verify_step_fused(params, fed_tokens, seq_lens, n_fed, active,
                          cache, block_tables, cfg,
                          kv_cfg: KVCacheConfig,
                          interpret: Optional[bool] = None,
                          tiles: Optional[Tuple[int, int, int]] = None
                          ) -> Tuple[Pytree, jnp.ndarray]:
    """Speculative verify on the fused path: feed ``fed_tokens``
    (n, k+1) — each slot's last sampled token followed by up to k
    drafted tokens — through the fused per-layer block in ONE call.

    Same caller contract as ``decode.gpt_verify_step``: returns
    ``(cache', logits (n, k+1, vocab) fp32)`` with logits[i, j] scoring
    the token AFTER fed_tokens[i, j]; rejected drafts' K/V writes need
    no rollback (the accepted length caps ``seq_lens``; stale positions
    are masked by every later context window and overwritten when real
    tokens reach them). The fused block computes all q rows' K/V
    in-kernel and folds them causally through the exact pool codec
    round-trip, then the cache write is the same masked ``paged_write``
    scatter the unfused path uses — pool bytes are BITWISE identical,
    and valid-row logits match within fp32 tolerance (engine streams
    bitwise-equal; ``tests/test_megakernel.py`` pins both)."""
    from apex_tpu.serve.decode import _check_serve_cfg, _embed, serve_logits

    _check_serve_cfg(cfg, kv_cfg, None)
    n, q = fed_tokens.shape
    refusal = megakernel_refusal(cfg, kv_cfg, allow_interpret=True, q=q)
    if refusal is not None:
        raise ValueError(f"megakernel unsupported: {refusal} — use "
                         f"decode.gpt_verify_step")
    heads, d = kv_cfg.num_heads, kv_cfg.head_dim
    offs = jnp.arange(q)
    positions = seq_lens[:, None] + offs[None, :]              # (n, q)
    valid = active[:, None] & (offs[None, :] < n_fed[:, None])
    positions_c = jnp.minimum(positions, cfg.max_seq - 1)
    # flat row views for the paged write (each fed row is its own "slot"
    # sharing its owner's block-table row — the gpt_paged_forward idiom)
    bt_rows = jnp.repeat(block_tables, q, axis=0)
    pos_flat = positions.reshape(-1)
    valid_flat = valid.reshape(-1)
    x = _embed(params["embed"], fed_tokens, positions_c, None)  # (n, q, h)
    ctx_old = jnp.where(active, seq_lens, 0).astype(jnp.int32)

    def body(x, xs):
        lp, cl = xs
        x, k_new, v_new = fused_layer_verify(
            x, lp, cl, cfg, kv_cfg, block_tables, ctx_old,
            interpret=interpret, tiles=tiles)
        k_flat = k_new.reshape(n * q, heads, d)
        v_flat = v_new.reshape(n * q, heads, d)
        cl = paged_write(cl, kv_cfg, k_flat.transpose(1, 0, 2),
                         v_flat.transpose(1, 0, 2), bt_rows, pos_flat,
                         valid_flat)
        return x, cl

    x, cache = lax.scan(body, x, (params["layers"], cache))
    return cache, serve_logits(params, x, cfg, None)

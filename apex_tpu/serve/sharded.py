"""Plan-driven model-parallel serving — one :class:`ParallelismPlan` from
training to pod-scale inference.

The single-chip engine caps the servable model at one chip's HBM. This
module lifts that: :func:`build_engine` reads ``ServeConfig.plan`` (the
SAME frozen plan object a train step is configured by) and builds an
:class:`~apex_tpu.serve.engine.InferenceEngine` whose programs run one of
three residency strategies on a mesh slice:

``tp`` (``ParallelismPlan(tp=N)``)
    Megatron weight shards, one engine, ``shard_map``-wrapped programs.
    The q_len>1 paths (chunked prefill, spec verify) route their
    row-parallel exits through the ``comm.overlap`` rings when the plan
    sets ``overlap_comm`` — partial GEMMs hide the hops, provable from
    compiled HLO via ``analyze.collectives.overlap_assertion`` on
    :func:`program_hlo`. q_len=1 decode stays monolithic (the PR-5 pin: a
    single-row GEMM has nothing to hide a hop behind). Numerics: psum
    ring association ⇒ logits equal up to fp reorder; the greedy/sampled
    token STREAMS still match the oracle at test tolerances.

``pp`` (``ParallelismPlan(pp=S)``)
    :class:`PPStagedEngine`: each stage holds ``num_layers/S`` layers and
    the KV pools for exactly those layers (same block ids, one shared
    host allocator), committed to its own device. Activations — not KV
    blocks — stream between stages; decode/verify split the slot grid
    into microbatches and drive a 1F tick loop with a bounded per-stage
    handoff window (the cluster backpressure-credit idea applied to
    activations). ``stats()`` reports the measured
    ``pp_bubble_fraction`` next to the (S-1)/(M+S-1) model. Numerics:
    splitting the layer scan changes no op order ⇒ BITWISE vs the
    oracle.

``fsdp`` (``ParallelismPlan("fsdp")``)
    Weight residency: per-layer block-aligned flat shards stay resident
    ((L, k) leaves, model dtype); each scan step gathers exactly one
    layer's full weights through the stateless ``FSDP.gather_leaf``
    VJP-forward (inference carries no EF state — the plan validates
    those knobs away) and drops them with the scan step. The
    ``weight_gather`` codec (int8/int4) halves/quarters the gather wire
    bytes; ``stats()`` reports measured ``weight_gather_ms`` and the
    modeled wire bytes. Embed/head stay replicated: every step embeds
    and samples, and a per-step vocab-table gather would dominate the
    ring. Numerics: uncompressed gather is slice-concat reconstruction ⇒
    BITWISE; a codec trades exactness for wire bytes (opt-in).

``fsdp/accounting.hbm_serve_bytes`` prices all three against a chip
budget before anything compiles — the bench headline is a model whose
``hbm_model_bytes`` EXCEEDS one chip served under SLO from the slice.
"""

from __future__ import annotations

import collections
import dataclasses
import inspect
import math
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.fsdp import accounting as _acct
from apex_tpu.fsdp.core import FSDP, LeafMeta
from apex_tpu.contrib.optimizers._sharding import slice_leaf
from apex_tpu.parallel.mesh import TP_AXIS
from apex_tpu.serve.decode import (
    _embed,
    paged_layer_stack,
    serve_logits,
)
from apex_tpu.serve.engine import InferenceEngine
from apex_tpu.serve.kv_cache import (
    copy_block,
    init_kv_cache,
    kv_cache_bytes,
)
from apex_tpu.serve.sampling import sample
from apex_tpu.monitor.metrics import Metrics
from apex_tpu.transformer.testing.standalone_gpt import gpt_param_specs

from jax.sharding import NamedSharding, PartitionSpec as P


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma=False):
    """The 0.4.37 shard_map shim (the PR-9/12 test idiom, packaged):
    graft jax exposes ``jax.shard_map(check_vma=)``; stock 0.4.37 has
    ``jax.experimental.shard_map.shard_map(check_rep=)`` — same replication
    semantics, older spelling. One call site, both toolchains."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)

Pytree = Any

__all__ = [
    "build_engine",
    "PPStagedEngine",
    "plan_world",
    "program_hlo",
    "tp_transform",
]


# ---------------------------------------------------------------------------
# shared plumbing


def plan_world(plan, devices: Optional[Sequence[Any]] = None) -> int:
    """Chip count the plan's serve strategy spans — ``tp``/``pp`` read
    their own degree; ``fsdp`` reads ``dp`` (-1 = every device given)."""
    s = plan.serve_strategy()
    if s == "tp":
        return plan.tp
    if s == "pp":
        return plan.pp
    if plan.dp > 0:
        return plan.dp
    return len(devices) if devices is not None else len(jax.devices())


def _apply_overrides(cfg, plan):
    if not hasattr(plan, "serve_overrides"):
        # the ServeConfig.validate() message, raised here too so
        # build_engine(plan="tp") dies loudly instead of AttributeError
        raise ValueError(f"plan must be a ParallelismPlan "
                         f"(apex_tpu.parallel.plan), got {type(plan)!r}")
    ov = plan.serve_overrides()
    if cfg.overlap_comm != ov["overlap_comm"]:
        cfg = dataclasses.replace(cfg, overlap_comm=ov["overlap_comm"])
    return cfg, ov


def _in_specs_for(fn: Callable, param_spec, cache_spec) -> Tuple:
    """Positional in_specs for one engine program closure: params get the
    model layout, the cache its pool layout, everything else (tokens,
    lens, tables, keys) is replicated. Keyed by NAME — the engine's
    closures share a fixed argument vocabulary."""
    specs = []
    for nm in inspect.signature(fn).parameters:
        if nm == "params":
            specs.append(param_spec)
        elif nm == "cache":
            specs.append(cache_spec)
        else:
            specs.append(P())
    return tuple(specs)


# out_specs per program closure name: decode/verify -> (cache, toks,
# Metrics), chunk_prefill -> (cache, tok), cow -> cache
def _out_specs_for(name: str, cache_spec):
    return {
        "chunk_prefill": (cache_spec, P()),
        "decode": (cache_spec, P(), P()),
        "verify": (cache_spec, P(), P()),
        "cow": cache_spec,
    }[name]


# ---------------------------------------------------------------------------
# (a) TP serving — Megatron shards under shard_map


def tp_transform(cfg, mesh) -> Callable[[Callable], Callable]:
    """The ``transform=`` for a TP-serving engine: wraps each program in
    ``shard_map`` with ``gpt_param_specs`` on params and heads-sharded
    pools on the cache (every pool leaf — K, V, and the quantized scales
    — carries heads at dim 1, so ONE spec covers them all).
    ``check_vma=False`` is the repo idiom for type-varying ring outputs
    (the overlap exits return psum-reordered, replicated-value arrays)."""
    param_spec = gpt_param_specs(cfg)
    cache_spec = P(None, TP_AXIS)

    def wrap(fn):
        return shard_map(
            fn, mesh=mesh,
            in_specs=_in_specs_for(fn, param_spec, cache_spec),
            out_specs=_out_specs_for(fn.__name__, cache_spec),
            check_vma=False)

    return wrap


def _build_tp_engine(params, cfg, serve_cfg, plan, mesh, devices,
                     **engine_kw) -> InferenceEngine:
    tp = plan.tp
    if mesh is None:
        mesh = plan.mesh(devices[:tp] if devices is not None else None)
    engine = InferenceEngine(
        params, cfg, serve_cfg, transform=tp_transform(cfg, mesh),
        tp_axis=TP_AXIS, tp_size=tp, **engine_kw)
    # the engine sized kv_cfg per-CHIP (local heads — its byte accounting
    # and the in-shard_map layer stack both want that view); the GLOBAL
    # pool the jitted programs take holds full heads, sharded by in_specs
    full_kv = dataclasses.replace(engine.kv_cfg,
                                  num_heads=cfg.num_heads)
    # place params and pool in their STEADY-STATE layouts up front — the
    # first program call otherwise sees single-device inputs, returns
    # mesh-sharded outputs, and the layout flip costs one retrace (the
    # compile-count gate would read 2 where the plain engine reads 1)
    engine.params = jax.device_put(
        params, jax.tree.map(lambda s: NamedSharding(mesh, s),
                             gpt_param_specs(cfg),
                             is_leaf=lambda x: isinstance(x, P)))
    engine.cache = jax.device_put(init_kv_cache(full_kv),
                                  NamedSharding(mesh, P(None, TP_AXIS)))
    model_bytes = _acct.hbm_model_bytes(params)
    chip = _acct.hbm_serve_bytes(
        params, strategy="tp", world=tp,
        kv_bytes=kv_cache_bytes(engine.kv_cfg),
        num_layers=cfg.num_layers)

    def plan_stats() -> Dict[str, Any]:
        return {
            "plan": "tp",
            "plan_world": tp,
            "hbm_model_bytes": model_bytes,
            "hbm_chip_bytes": chip["total"],
        }

    engine.plan_stats = plan_stats
    return engine


# ---------------------------------------------------------------------------
# (c) FSDP weight residency — resident shards, gather-on-demand per layer


def _layer_shard_meta(layers: Dict[str, Any]) -> Dict[str, LeafMeta]:
    """Per-LAYER LeafMeta for each stacked leaf: shape minus the leading
    L axis — what one scan step's gather must reconstruct."""
    return {k: LeafMeta(tuple(jnp.shape(v))[1:], str(jnp.result_type(v)))
            for k, v in layers.items()}


def _build_fsdp_engine(params, cfg, serve_cfg, plan, mesh, devices,
                       **engine_kw) -> InferenceEngine:
    world = plan_world(plan, devices)
    if mesh is None:
        mesh = plan.mesh(devices[:world] if devices is not None else None)
    axis = plan.dp_axis
    fsdp = FSDP(axis_name=axis, weight_gather=plan.weight_gather)
    mult = fsdp.shard_multiple
    layers = params["layers"]
    metas = _layer_shard_meta(layers)

    # one-time resharding program: stacked (L, *rest) -> resident (L, k)
    # model-dtype rows, block-aligned so no codec scale block straddles
    # ranks (bitwise gather when no codec: pad + slice + concat + unpad
    # is the identity)
    def _shard_layers(ls):
        return {
            k: jax.vmap(lambda row: slice_leaf(row, axis, multiple=mult))(v)
            for k, v in ls.items()}

    shard_prog = jax.jit(shard_map(
        _shard_layers, mesh=mesh, in_specs=(P(),),
        out_specs=P(None, axis), check_vma=False))
    shards = shard_prog(layers)
    # embed/head replicas placed mesh-wide up front (same retrace-avoidance
    # as the tp build: first-call layout must already be steady state)
    repl = NamedSharding(mesh, P())
    serve_params = {"embed": jax.device_put(params["embed"], repl),
                    "head": jax.device_put(params["head"], repl),
                    "layers": shards}

    def gather_layer(lp: Dict[str, Any]) -> Dict[str, Any]:
        return {k: fsdp.gather_leaf(v, metas[k]) for k, v in lp.items()}

    param_spec = {"embed": P(), "head": P(), "layers": P(None, axis)}

    def wrap(fn):
        return shard_map(
            fn, mesh=mesh,
            in_specs=_in_specs_for(fn, param_spec, P()),
            out_specs=_out_specs_for(fn.__name__, P()),
            check_vma=False)

    engine = InferenceEngine(serve_params, cfg, serve_cfg, transform=wrap,
                             gather_layer=gather_layer, **engine_kw)
    engine.cache = jax.device_put(engine.cache, repl)
    # flops accounting wants the MODEL's parameter count, not the
    # padded resident-shard count
    engine._n_params = sum(
        x.size for x in jax.tree_util.tree_leaves(params))

    # measured full-gather latency: a dedicated program running exactly
    # the per-layer gathers the decode scan runs, timed end to end —
    # lazily, once (compiling it is pointless if stats() never asks)
    def _gather_all(ls):
        return {k: jax.vmap(lambda s: fsdp.gather_leaf(s, metas[k]))(v)
                for k, v in ls.items()}

    gather_prog = jax.jit(shard_map(
        _gather_all, mesh=mesh, in_specs=(P(None, axis),),
        out_specs=P(), check_vma=False))
    measured: Dict[str, float] = {}

    def _measure_gather_ms() -> float:
        if "ms" not in measured:
            jax.block_until_ready(gather_prog(shards))  # compile + warm
            t0 = time.perf_counter()
            jax.block_until_ready(gather_prog(shards))
            measured["ms"] = (time.perf_counter() - t0) * 1e3
        return measured["ms"]

    model_bytes = _acct.hbm_model_bytes(params)
    chip = _acct.hbm_serve_bytes(
        params, strategy="fsdp", world=world,
        kv_bytes=kv_cache_bytes(engine.kv_cfg),
        num_layers=cfg.num_layers, shard_multiple=mult)
    wire = cfg.num_layers * _acct.param_gather_wire_bytes(
        metas, world, plan.weight_gather, mult)

    def plan_stats() -> Dict[str, Any]:
        return {
            "plan": "fsdp",
            "plan_world": world,
            "hbm_model_bytes": model_bytes,
            "hbm_chip_bytes": chip["total"],
            "weight_gather_ms": _measure_gather_ms(),
            "weight_gather_wire_bytes": wire,
        }

    engine.plan_stats = plan_stats
    return engine


# ---------------------------------------------------------------------------
# (b) PP-staged serving — activations stream between layer shards


class PPStagedEngine(InferenceEngine):
    """Pipeline-staged engine: stage s owns layers ``[s·L/S, (s+1)·L/S)``
    and the KV pools for exactly those layers, committed to its own
    device. The public surface is the base engine's — ``submit``/
    ``step``/``run``/``stats`` — but the four programs become host
    drivers over per-stage jitted programs: decode/verify split the slot
    grid into M microbatches and tick a 1F schedule where stage s runs
    microbatch ``t - s``, bounded by a per-stage handoff window (the
    cluster backpressure-credit contract: a stage whose downstream
    buffer is full stalls, and the stall is COUNTED, not hidden).
    Prefill (one prompt) runs straight through — a single chunk cannot
    pipeline against itself, and its S-tick bubble is reported, not
    smoothed over.

    Bitwise vs the single-chip oracle: splitting the layer scan at stage
    boundaries reorders no per-layer op, rows are independent, and
    sampling draws are (request, position)-keyed.
    """

    def __init__(self, params, cfg, serve_cfg, *,
                 devices: Optional[Sequence[Any]] = None,
                 microbatches: Optional[int] = None,
                 stage_window: int = 1,
                 **engine_kw):
        plan = serve_cfg.plan
        if plan is None or plan.serve_strategy() != "pp":
            raise ValueError("PPStagedEngine needs ServeConfig.plan with "
                             "pp > 1 (and nothing else sharding)")
        S = plan.pp
        if cfg.num_layers % S:
            raise ValueError(
                f"pp={S} stages need num_layers ({cfg.num_layers}) "
                f"divisible by the stage count")
        n = serve_cfg.num_slots
        if microbatches is None:
            # largest microbatch count <= S that divides the slot grid:
            # more would add handoffs without shrinking the bubble below
            # (S-1)/(M+S-1)'s knee; fewer wastes overlap
            microbatches = next(m for m in range(min(S, n), 0, -1)
                                if n % m == 0)
        if n % microbatches:
            raise ValueError(
                f"microbatches ({microbatches}) must divide num_slots "
                f"({n}) — ragged microbatches would retrace per step")
        if stage_window < 1:
            raise ValueError(
                f"stage_window must be >= 1, got {stage_window}")
        self._pp_stages = S
        self._pp_mb = microbatches
        self._pp_window = stage_window
        if devices is None:
            devices = jax.devices()
        if len(devices) < S:
            raise ValueError(
                f"pp={S} stages need {S} devices, have {len(devices)}")
        self._pp_devs = list(devices)[:S]
        self._pp_busy_cells = 0
        self._pp_total_cells = 0
        self._pp_credit_waits = 0
        for bad in ("transform", "tp_axis", "tp_size", "gather_layer"):
            if engine_kw.get(bad):
                raise ValueError(f"{bad} is owned by the PP engine")
        super().__init__(params, cfg, serve_cfg, **engine_kw)
        model_bytes = _acct.hbm_model_bytes(params)
        chip = _acct.hbm_serve_bytes(
            params, strategy="pp", world=S,
            kv_bytes=kv_cache_bytes(self._stage_kv),
            num_layers=cfg.num_layers)
        self._pp_chip_bytes = chip["total"]
        self._pp_model_bytes = model_bytes
        # base __init__ pins the instance attr to None; point it at the
        # stage accounting so engine.stats() carries the plan block
        self.plan_stats = self._pp_plan_stats

    # -- program construction ---------------------------------------------
    def _build_programs(self, wrap) -> None:
        cfg, scfg = self.cfg, self.serve_cfg
        S = self._pp_stages
        Ls = cfg.num_layers // S
        self._stage_kv = dataclasses.replace(self.kv_cfg, num_layers=Ls)
        skv = self._stage_kv
        layers = self.params["layers"]
        stage_params: List[Pytree] = []
        for s in range(S):
            pd: Dict[str, Any] = {
                "layers": {k: v[s * Ls:(s + 1) * Ls]
                           for k, v in layers.items()}}
            if s == 0:
                pd["embed"] = self.params["embed"]
            if s == S - 1:
                pd["head"] = self.params["head"]
                # tied logits read the token table; last stage holds a
                # replica either way (embed/head replication is the
                # accounting model's assumption too)
                pd["embed"] = self.params["embed"]
            stage_params.append(jax.device_put(pd, self._pp_devs[s]))
        self.params = stage_params
        # per-stage pools, committed: stage s writes/reads ITS layers
        # under the engine-global block ids and allocator
        self.cache = [jax.device_put(init_kv_cache(skv), d)
                      for d in self._pp_devs]

        def _make_stage(s: int):
            first, last = s == 0, s == S - 1

            def stage_fwd(pd, cache_s, x, start_lens, n_valid, active,
                          block_tables):
                if first:
                    q = x.shape[1]
                    offs = jnp.arange(q)
                    positions = start_lens[:, None] + offs[None, :]
                    positions_c = jnp.minimum(positions, cfg.max_seq - 1)
                    x = _embed(pd["embed"], x, positions_c, None)
                x, cache_s = paged_layer_stack(
                    x, pd["layers"], start_lens, n_valid, active, cache_s,
                    block_tables, cfg, skv, tp_axis=None,
                    use_pallas=self._use_pallas)
                if last:
                    x = serve_logits(pd, x, cfg, None)
                return cache_s, x

            def stage_cow(cache_s, src, dst):
                return copy_block(cache_s, src, dst)

            return (jax.jit(stage_fwd, donate_argnums=(1,)),
                    jax.jit(stage_cow, donate_argnums=(0,)))

        made = [_make_stage(s) for s in range(S)]
        self._stage_fwd = [f for f, _ in made]
        self._stage_cow = [c for _, c in made]
        self._chunk_prefill = self._pp_chunk_prefill
        self._decode = self._pp_decode
        self._verify = self._pp_verify if scfg.spec_k > 0 else None
        self._cow = self._pp_cow

    # -- the pipeline tick loop -------------------------------------------
    def _pp_forward(self, tokens, start_lens, n_valid, active,
                    block_tables, microbatches: int):
        """Drive (n, q) token rows through the stages in ``microbatches``
        row-slices; returns (n, q, vocab) fp32 logits. Stage caches
        update in place (donated per stage call)."""
        S = self._pp_stages
        n = tokens.shape[0]
        nmb = n // microbatches
        ready: List[collections.deque] = [collections.deque()
                                          for _ in range(S)]
        for m in range(microbatches):
            sl = slice(m * nmb, (m + 1) * nmb)
            ready[0].append((m, (tokens[sl], start_lens[sl], n_valid[sl],
                                 active[sl], block_tables[sl])))
        out: List[Any] = [None] * microbatches
        pending = microbatches
        while pending:
            self._pp_total_cells += S
            progressed = False
            # drain downstream first: a handoff produced this tick is
            # consumed next tick — the 1F timing the bubble model prices
            for s in reversed(range(S)):
                if not ready[s]:
                    continue
                if s < S - 1 and len(ready[s + 1]) >= self._pp_window:
                    # backpressure credit exhausted: the downstream
                    # buffer is full, this stage idles the tick
                    self._pp_credit_waits += 1
                    continue
                m, (x, st, nv, ac, bt) = ready[s].popleft()
                if s > 0:  # activation handoff: the inter-stage stream
                    x = jax.device_put(x, self._pp_devs[s])
                cache_s, y = self._stage_fwd[s](
                    self.params[s], self.cache[s], x, st, nv, ac, bt)
                self.cache[s] = cache_s
                self._pp_busy_cells += 1
                progressed = True
                if s == S - 1:
                    out[m] = y
                    pending -= 1
                else:
                    ready[s + 1].append((m, (y, st, nv, ac, bt)))
            if not progressed:  # pragma: no cover - schedule invariant
                raise RuntimeError("pipeline deadlock: no stage ran")
        # host hop: the concat-and-sample epilogue runs on the default
        # device; per-microbatch logits are committed to the last stage
        return jnp.asarray(np.concatenate(
            [np.asarray(o) for o in out], axis=0))

    # -- the four engine programs, as host drivers ------------------------
    def _pp_decode(self, params, cache, last_tokens, seq_lens, active,
                   block_tables, keys):
        del params, cache  # the engine passes them back; stages own them
        n = last_tokens.shape[0]
        logits = self._pp_forward(
            jnp.asarray(last_tokens)[:, None], jnp.asarray(seq_lens),
            jnp.ones((n,), jnp.int32), jnp.asarray(active),
            jnp.asarray(block_tables), self._pp_mb)[:, 0]
        toks = sample(logits, keys, seq_lens + 1, self.serve_cfg.sampling)
        m = Metrics().record(
            active_slots=jnp.sum(active),
            context_tokens=jnp.sum(jnp.where(active, seq_lens + 1, 0)))
        return self.cache, toks, m

    def _pp_verify(self, params, cache, fed_tokens, seq_lens, n_fed,
                   active, block_tables, keys):
        del params, cache
        k1 = fed_tokens.shape[1]
        logits = self._pp_forward(
            jnp.asarray(fed_tokens), jnp.asarray(seq_lens),
            jnp.asarray(n_fed), jnp.asarray(active),
            jnp.asarray(block_tables), self._pp_mb)
        draw_pos = seq_lens[:, None] + 1 + jnp.arange(k1)[None, :]
        toks = sample(logits, keys, draw_pos, self.serve_cfg.sampling)
        m = Metrics().record(
            active_slots=jnp.sum(active),
            context_tokens=jnp.sum(jnp.where(active, seq_lens + 1, 0)))
        return self.cache, toks, m

    def _pp_chunk_prefill(self, params, cache, tokens, start, n_valid,
                          block_row, key):
        del params, cache
        logits = self._pp_forward(
            jnp.asarray(tokens)[None, :], jnp.asarray(start)[None],
            jnp.asarray(n_valid)[None], jnp.ones((1,), bool),
            jnp.asarray(block_row)[None, :], 1)
        last = jnp.take(logits[0], jnp.maximum(jnp.asarray(n_valid) - 1, 0),
                        axis=0)
        tok = sample(last[None], key[None],
                     jnp.reshape(start + n_valid, (1,)),
                     self.serve_cfg.sampling)
        return self.cache, tok[0]

    def _pp_cow(self, cache, src, dst):
        return [cow(c, src, dst)
                for cow, c in zip(self._stage_cow, cache)]

    # -- surfaces ----------------------------------------------------------
    def programs(self) -> Dict[str, Optional[Callable]]:
        progs: Dict[str, Optional[Callable]] = {}
        for s in range(self._pp_stages):
            progs[f"pp_stage{s}"] = self._stage_fwd[s]
            progs[f"pp_cow{s}"] = self._stage_cow[s]
        return progs

    def pp_bubble_fraction(self) -> float:
        """Measured idle fraction of stage·tick cells across every
        pipeline drive so far (0.0 before any)."""
        if not self._pp_total_cells:
            return 0.0
        return 1.0 - self._pp_busy_cells / self._pp_total_cells

    def _pp_plan_stats(self) -> Dict[str, Any]:
        S, M = self._pp_stages, self._pp_mb
        return {
            "plan": "pp",
            "plan_world": S,
            "hbm_model_bytes": self._pp_model_bytes,
            "hbm_chip_bytes": self._pp_chip_bytes,
            "pp_microbatches": M,
            "pp_bubble_fraction": self.pp_bubble_fraction(),
            "pp_bubble_fraction_modeled": (S - 1) / (M + S - 1),
            "pp_credit_waits": self._pp_credit_waits,
        }


# ---------------------------------------------------------------------------
# front door


def build_engine(params, cfg, serve_cfg, *,
                 devices: Optional[Sequence[Any]] = None,
                 mesh=None, **engine_kw) -> InferenceEngine:
    """One constructor for every residency: reads ``serve_cfg.plan`` and
    returns a ready engine — the plain single-chip
    :class:`InferenceEngine` when the plan is None, else the strategy the
    plan's ``serve_overrides()`` resolves (``tp``/``pp``/``fsdp``).

    ``params`` is always the MERGED single-chip checkpoint layout
    (``init_gpt_params`` structure); resharding into the plan's resident
    layout happens here, on device. ``devices`` defaults to
    ``jax.devices()`` — the first ``plan_world(plan)`` of them form the
    slice. ``mesh`` overrides the plan-built mesh (tp/fsdp only).
    """
    plan = serve_cfg.plan
    if plan is None:
        return InferenceEngine(params, cfg, serve_cfg, **engine_kw)
    cfg, ov = _apply_overrides(cfg, plan)
    strategy = ov["strategy"]
    devs = list(devices) if devices is not None else None
    if strategy == "tp":
        return _build_tp_engine(params, cfg, serve_cfg, plan, mesh, devs,
                                **engine_kw)
    if strategy == "fsdp":
        return _build_fsdp_engine(params, cfg, serve_cfg, plan, mesh,
                                  devs, **engine_kw)
    return PPStagedEngine(params, cfg, serve_cfg, devices=devs,
                          **engine_kw)


def program_hlo(engine: InferenceEngine, name: str = "verify") -> str:
    """Compiled HLO text of one engine program, lowered at the engine's
    own shapes — feed ``analyze.collectives.overlap_assertion`` /
    ``assert_no_exposed`` to PROVE the q_len>1 TP exits hide their ring
    hops behind partial GEMMs (the acceptance gate), instead of trusting
    the flag. Lowers out-of-band: the engine's jit caches see nothing."""
    progs = engine.programs()
    if name not in progs or progs[name] is None:
        raise ValueError(
            f"engine has no program {name!r} (have "
            f"{[k for k, v in progs.items() if v is not None]})")
    scfg = engine.serve_cfg
    n = scfg.num_slots
    bps = engine._blocks_per_slot
    i32, u32 = jnp.int32, jnp.uint32
    if name == "chunk_prefill":
        args = (engine.params, engine.cache,
                jnp.zeros((scfg.prefill_chunk,), i32), i32(0), i32(1),
                jnp.zeros((bps,), i32), jnp.zeros((2,), u32))
    elif name == "decode":
        args = (engine.params, engine.cache, jnp.zeros((n,), i32),
                jnp.zeros((n,), i32), jnp.zeros((n,), bool),
                jnp.zeros((n, bps), i32), jnp.zeros((n, 2), u32))
    elif name == "verify":
        args = (engine.params, engine.cache,
                jnp.zeros((n, scfg.spec_k + 1), i32),
                jnp.zeros((n,), i32), jnp.ones((n,), i32),
                jnp.zeros((n,), bool), jnp.zeros((n, bps), i32),
                jnp.zeros((n, 2), u32))
    else:
        raise ValueError(f"no dummy-arg recipe for program {name!r}")
    return progs[name].lower(*args).compile().as_text()

"""In-graph token sampling for the decode loop.

One jitted :func:`sample` over the whole slot grid — greedy, temperature,
top-k and nucleus (top-p) filtering composed in that order, then a
categorical draw (Gumbel argmax). Determinism is the design center:

* **per-slot keys** — every request owns a PRNG key derived once at
  admission (:func:`request_key`); each step folds the token's absolute
  position into it, so the draw for "request r, position p" is a pure
  function of ``(r, p)`` — independent of which slot the request occupies,
  what else is batched alongside it, or when it was admitted. This is what
  makes continuous batching **request-order-invariant**: the engine's
  streams are bitwise reproducible against single-request decode
  (``tests/test_serve.py`` pins it).
* **greedy is argmax** — ``temperature == 0`` bypasses the draw entirely;
  no key is consumed, so greedy streams are key-independent too.

The filters run on fp32 logits; masked entries go to ``-inf`` (exact zero
probability under the Gumbel draw). Top-p always keeps the highest-probability
token, so the mask can never empty a row.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """``temperature == 0`` -> greedy (argmax; top_k/top_p ignored).
    ``top_k == 0`` / ``top_p == 1.0`` disable the respective filter."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0

    def validate(self) -> None:
        if self.temperature < 0:
            raise ValueError("temperature must be >= 0")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")


def request_key(base_key, request_seed: int):
    """The request's own PRNG key: ``fold_in(base, seed)``. The seed is a
    request-intrinsic integer (the engine derives it from the request id),
    NOT an admission index — keys must not depend on arrival order."""
    return jax.random.fold_in(base_key, request_seed)


def step_keys(keys, positions):
    """Fold each slot's token position into its request key: (n, 2) uint32
    keys + (n,) positions -> (n, 2) per-step keys."""
    return jax.vmap(jax.random.fold_in)(keys, positions)


def _top_k_mask(x, k: int):
    kth = jax.lax.top_k(x, k)[0][..., -1:]
    return jnp.where(x < kth, -jnp.inf, x)


def _top_p_mask(x, p: float):
    """Nucleus filter: keep the smallest prefix of the probability-sorted
    vocab whose (exclusive) cumulative mass is < p — the top token always
    survives."""
    sorted_x = jnp.flip(jnp.sort(x, axis=-1), axis=-1)
    probs = jax.nn.softmax(sorted_x, axis=-1)
    cum_excl = jnp.cumsum(probs, axis=-1) - probs
    kept = cum_excl < p
    thresh = jnp.min(jnp.where(kept, sorted_x, jnp.inf), axis=-1,
                     keepdims=True)
    return jnp.where(x < thresh, -jnp.inf, x)


def sample(logits, keys, positions, cfg: SamplingConfig):
    """(n, vocab) fp32 logits -> (n,) int32 tokens.

    ``keys``: (n, 2) uint32 per-slot request keys; ``positions``: (n,)
    int32 absolute position of the token being sampled. Greedy
    (``temperature == 0``) ignores both.

    Also accepts (n, q, vocab) logits with (n, q) positions (the
    speculative-verify shape: q draws per slot under ONE request key) —
    rows flatten to (n*q,) draws and the result is (n, q). Because every
    draw is keyed by (request, position) alone, the q-at-a-time draws are
    bitwise the ones sequential decode would make at those positions —
    the speculative path's acceptance oracle rests on exactly this.
    """
    if logits.ndim == 3:
        n, q, v = logits.shape
        flat = sample(logits.reshape(n * q, v),
                      jnp.repeat(keys, q, axis=0),
                      positions.reshape(n * q), cfg)
        return flat.reshape(n, q)
    logits = logits.astype(jnp.float32)
    if cfg.temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    x = logits / jnp.float32(cfg.temperature)
    if cfg.top_k > 0 and cfg.top_k < logits.shape[-1]:
        x = _top_k_mask(x, cfg.top_k)
    if cfg.top_p < 1.0:
        x = _top_p_mask(x, cfg.top_p)
    ks = step_keys(keys, positions)
    return jax.vmap(
        lambda k, row: jax.random.categorical(k, row)
    )(ks, x).astype(jnp.int32)

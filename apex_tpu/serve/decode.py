"""Decode-path attention against the paged KV cache + the GPT serve programs.

Two halves:

* **paged attention** — attention where K/V live in the block-paged
  pools (``apex_tpu.serve.kv_cache``): a pure-JAX reference (gather
  through the block tables, then exactly the
  ``ops.attention.attention_reference`` math — fp32 accumulation, NEG_INF
  masking) and a Pallas gather-attend kernel that walks each slot's block
  table with scalar-prefetched indices (the ``ops/attention_varlen.py``
  ``PrefetchScalarGridSpec`` idiom) and an online-softmax accumulator (the
  ``ops/attention.py`` forward scheme, no lse output — decode never
  differentiates). The MPK case (arXiv 2512.22219) is why this is one
  kernel and the whole decode step one compiled program: at q_len=1 the
  work per op is tiny and dispatch dominates.

* **serve programs** — one unified :func:`gpt_paged_forward` (q tokens
  per slot against the paged cache, per-row math independent of q) with
  three thin wrappers that are the engine's ONLY compiled programs:
  :func:`gpt_decode_step` (q=1), :func:`gpt_verify_step` (q=k+1 — verify
  k drafted tokens in one call, amortizing the dispatch-bound decode
  step k-fold exactly the way the fused computation-collective ops of
  arXiv 2305.06942 amortize launch overhead), and
  :func:`gpt_prefill_chunk` (one slot, q=chunk — the fixed-size prefill
  chunk that replaced the PR-5 bucket ladder). :func:`gpt_prefill` (the
  full-prompt flash-attention prefill) remains as the COLD-PATH ORACLE
  the chunked/cached/speculative streams are tested against. All are
  built from the SAME ``standalone_gpt`` parameter pytree (tied LM head,
  per-head interleaved QKV packing, ``ops.layer_norm``/``flash_attention``
  cores). TP is axis-optional: with ``tp_axis`` bound (inside a mesh
  program) the projections ride ``tensor_parallel``'s column/row-parallel
  layers — heads sharded, the flash-prefill row-parallel exits honoring
  ``cfg.overlap_comm`` (the decomposed ``comm.overlap`` rings) — and the
  vocab-sharded logits are all-gathered for sampling; with ``tp_axis=None``
  (single device, stock-jax serving) the same math runs as plain dots.
  The q_len=1 decode step's TP exits stay monolithic by design — a
  single-row GEMM has no flops to hide a ring behind — while the
  q_len>1 paths (speculative verify, chunked prefill) honor
  ``cfg.overlap_comm`` exactly like the flash prefill: k+1 or
  chunk-many rows give the ``comm.overlap.matmul_all_reduce`` ring
  partial GEMMs to travel behind (``apex_tpu.serve.sharded`` is the
  plan-driven engine builder that wires this up).

Layers scan over the stacked layer params with the per-layer cache pools
riding the scan's xs/ys — one compiled layer body regardless of depth,
and the updated pools restack for donation.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.ops._pallas_util import compiled_backend as _compiled_backend
from apex_tpu.ops._pallas_util import sds as _sds
from apex_tpu.ops.attention import NEG_INF, attention_reference, flash_attention
from apex_tpu.ops.layer_norm import layer_norm
from apex_tpu.parallel.mesh import axis_size as _axis_size
from apex_tpu.serve.kv_cache import KVCacheConfig, gather_kv, paged_write

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

Pytree = Any


# ---------------------------------------------------------------------------
# Paged attention — reference


def paged_attention_reference(q, cache_layer, cfg: KVCacheConfig,
                              block_tables, ctx_lens,
                              scale: Optional[float] = None):
    """q (n, H, D) against one layer's paged pools; (n,) ``ctx_lens`` tokens
    of context per slot. Returns (n, H, D) in q.dtype.

    Math is EXACTLY ``attention_reference`` over the gathered K/V with a
    ``kpos >= ctx_len`` mask — the fp32-exact ground truth the Pallas
    kernel and the engine's decode step are tested against. Slots with
    ``ctx_len == 0`` produce a finite junk row (uniform weights over
    NEG_INF-masked scores), never NaN — callers mask by activity.
    """
    k, v = gather_kv(cache_layer, cfg, block_tables)  # (n, H, S, D)
    s_tot = k.shape[2]
    kpos = jnp.arange(s_tot)
    mask = kpos[None, None, None, :] >= ctx_lens[:, None, None, None]
    o = attention_reference(q[:, :, None], k, v, mask=mask, scale=scale)
    return o[:, :, 0]


# ---------------------------------------------------------------------------
# Paged attention — Pallas gather-attend kernel. Grid (slots, blocks); the
# block table rides scalar prefetch so each (slot, j) step DMAs pool block
# ``table[slot, j]`` directly; dead blocks (past the context) clamp their
# fetch to the last live block (Mosaic elides the repeated copy — the
# ops/attention.py causal-clamp trick) and skip compute.


def _nibble_dequant(packed, s, group):
    """In-kernel int4 pool dequant: (.., bs, D/2) packed uint8 codes +
    (.., bs, D/group) bf16 group scales -> (.., bs, D) fp32. Bit-for-bit
    the ``kv_cache._dequant_rows_int4`` math — ``unpack_int4`` is pure
    jnp bit ops, so it traces straight into the Pallas kernel and the
    codes/scales never round-trip through HBM as fp."""
    from apex_tpu.comm.quantize import unpack_int4

    codes = unpack_int4(packed)
    d = codes.shape[-1]
    g = codes.reshape(codes.shape[:-1] + (d // group, group))
    out = g.astype(jnp.float32) * s.astype(jnp.float32)[..., None]
    return out.reshape(codes.shape)


def _paged_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, *refs,
                  scale, block_size, nb, quantized, kv_bits=8, kv_group=0):
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        o_ref, m_scr, l_scr, acc_scr = refs
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    ctx = len_ref[i]

    @pl.when(j * block_size < ctx)
    def _compute():
        q = q_ref[0]                       # (H, D)
        k = k_ref[:, 0]                    # (H, bs, D) | packed (H, bs, D/2)
        v = v_ref[:, 0]
        if quantized and kv_bits == 4:
            k = _nibble_dequant(k, ks_ref[:, 0], kv_group)
            v = _nibble_dequant(v, vs_ref[:, 0], kv_group)
        elif quantized:
            k = k.astype(jnp.float32) * ks_ref[:, 0][..., None]
            v = v.astype(jnp.float32) * vs_ref[:, 0][..., None]
        s = lax.dot_general(
            q, k, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale  # (H, bs)
        kpos = j * block_size + lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(kpos >= ctx, NEG_INF, s)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nb - 1)
    def _finish():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)  # ctx==0 slot: emit zeros
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


def _paged_pallas(q, cache_layer, cfg: KVCacheConfig, block_tables,
                  ctx_lens, scale, interpret):
    n, h, d = q.shape
    nb = block_tables.shape[1]
    bs = cfg.block_size
    bt_flat = block_tables.reshape(-1).astype(jnp.int32)
    lens = ctx_lens.astype(jnp.int32)

    def blk_index(i, j, bt, ln):
        # clamp dead steps at the last live block: repeated index elides
        # the DMA; max(ctx-1, 0) keeps a ctx==0 slot in range
        jl = jnp.maximum(ln[i] - 1, 0) // bs
        return (0, bt[i * nb + jnp.minimum(j, jl)], 0, 0)

    def blk_index_s(i, j, bt, ln):
        jl = jnp.maximum(ln[i] - 1, 0) // bs
        return (0, bt[i * nb + jnp.minimum(j, jl)], 0)

    dk = d // 2 if cfg.quantized and cfg.bits == 4 else d
    in_specs = [
        pl.BlockSpec((1, h, d), lambda i, j, bt, ln: (i, 0, 0)),
        pl.BlockSpec((h, 1, bs, dk), blk_index),
        pl.BlockSpec((h, 1, bs, dk), blk_index),
    ]
    inputs = [q, cache_layer["k"], cache_layer["v"]]
    if cfg.quantized and cfg.bits == 4:
        # group scales carry a trailing head_dim/group dim — same 4-d
        # rank as the packed code pools, same block walk
        gdim = d // cfg.kv_group
        in_specs += [pl.BlockSpec((h, 1, bs, gdim), blk_index),
                     pl.BlockSpec((h, 1, bs, gdim), blk_index)]
        inputs += [cache_layer["k_scale"], cache_layer["v_scale"]]
    elif cfg.quantized:
        in_specs += [pl.BlockSpec((h, 1, bs), blk_index_s),
                     pl.BlockSpec((h, 1, bs), blk_index_s)]
        inputs += [cache_layer["k_scale"], cache_layer["v_scale"]]
    kernel = functools.partial(
        _paged_kernel, scale=scale, block_size=bs, nb=nb,
        quantized=cfg.quantized, kv_bits=cfg.bits if cfg.quantized else 8,
        kv_group=cfg.kv_group if cfg.quantized else 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, h, d), lambda i, j, bt, ln: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, 128), jnp.float32),
            pltpu.VMEM((h, 128), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=_sds((n, h, d), q.dtype, q),
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(bt_flat, lens, *inputs)


def _pallas_ok(head_dim: int, allow_interpret: bool) -> bool:
    if not _HAS_PALLAS or head_dim % 8 != 0:
        return False
    return allow_interpret or _compiled_backend()


# head_dims whose silent kernel->reference fallback was already logged
# (warn ONCE per shape: a 10x slower serve run must be diagnosable from
# the log, not only from the bench line)
_FALLBACK_WARNED: set = set()


def _warn_reference_fallback(head_dim: int) -> None:
    if head_dim in _FALLBACK_WARNED:
        return
    _FALLBACK_WARNED.add(head_dim)
    from apex_tpu._logging import get_logger

    get_logger("apex_tpu.serve").warning(
        "paged_attention: head_dim %d %% 8 != 0 — falling back to the "
        "pure-JAX gather+reference path on a compiled TPU backend "
        "(expect a much slower decode step; pad head_dim to a multiple "
        "of 8 to get the Pallas kernel)", head_dim)


def paged_attention(q, cache_layer, cfg: KVCacheConfig, block_tables,
                    ctx_lens, scale: Optional[float] = None,
                    use_pallas: Optional[bool] = None,
                    interpret: Optional[bool] = None):
    """Dispatching front door: Pallas gather-attend on compiled TPU
    backends (head_dim % 8), the gather+reference path elsewhere — the
    ``flash_attention`` gating pattern. Same signature/result as
    :func:`paged_attention_reference`."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if use_pallas is None:
        use_pallas = _pallas_ok(q.shape[-1], allow_interpret=False)
        if (not use_pallas and _HAS_PALLAS and _compiled_backend()
                and q.shape[-1] % 8 != 0):
            _warn_reference_fallback(q.shape[-1])
    elif use_pallas and not _pallas_ok(q.shape[-1], allow_interpret=True):
        raise ValueError(
            f"pallas paged_attention needs head_dim % 8 == 0 "
            f"(got {q.shape[-1]}) and pallas available")
    if not use_pallas:
        if interpret is not None:
            raise ValueError(
                "interpret= only applies to the Pallas path (pass "
                "use_pallas=True to force the kernel)")
        return paged_attention_reference(q, cache_layer, cfg, block_tables,
                                         ctx_lens, scale=scale)
    if interpret is None:
        interpret = not _compiled_backend()
    return _paged_pallas(q, cache_layer, cfg, block_tables, ctx_lens,
                         scale, interpret)


# ---------------------------------------------------------------------------
# Axis-optional TP plumbing: one code path that runs as plain dots on a
# single device (tp_axis=None — the stock-jax serving case) and as the
# tensor_parallel layers inside a mesh program.


def _tp_size(tp_axis: Optional[str]) -> int:
    if tp_axis is None:
        return 1
    return _axis_size(tp_axis)


def _col(x, kernel, bias, tp_axis: Optional[str]):
    """Column-parallel projection (output-sharded, no gather)."""
    if tp_axis is None:
        y = jnp.dot(x, kernel.astype(x.dtype))
        return y + bias if bias is not None else y
    from apex_tpu.transformer.tensor_parallel.layers import (
        column_parallel_linear,
    )

    return column_parallel_linear(x, kernel, bias, gather_output=False,
                                  axis_name=tp_axis)


def _row(x, kernel, bias, tp_axis: Optional[str], overlap: bool = False):
    """Row-parallel projection (input-sharded, psum exit; ``overlap`` only
    meaningful for 3D (b, s, h) prefill activations)."""
    if tp_axis is None:
        y = jnp.dot(x, kernel.astype(x.dtype))
        return y + bias if bias is not None else y
    from apex_tpu.transformer.tensor_parallel.layers import (
        row_parallel_linear,
    )

    return row_parallel_linear(x, kernel, bias, input_is_parallel=True,
                               axis_name=tp_axis,
                               overlap_comm=overlap and x.ndim == 3)


def _embed(embed, tokens, positions, tp_axis: Optional[str]):
    """Token + position embedding at explicit positions (decode feeds one
    token per slot at its own offset — no implicit arange)."""
    if tp_axis is None:
        x = jnp.take(embed["tok"], tokens, axis=0)
    else:
        from apex_tpu.transformer.tensor_parallel.layers import (
            vocab_parallel_embedding,
        )

        x = vocab_parallel_embedding(tokens, embed["tok"],
                                     axis_name=tp_axis)
    pos = jnp.take(embed["pos"], positions, axis=0)  # OOB clamps (jnp.take)
    return x + pos.astype(x.dtype)


def serve_logits(params, x, cfg, tp_axis: Optional[str] = None):
    """Final LN + LM head -> FULL-vocab fp32 logits (sampling needs the
    global argmax/top-k, so TP-sharded logits are all-gathered here —
    unlike training, where the fused loss never materializes them)."""
    head = params["head"]
    x = layer_norm(x, head["ln_w"], head["ln_b"], use_pallas=cfg.ln_pallas)
    if cfg.tie_embeddings:
        logits = jnp.einsum("...h,vh->...v", x,
                            params["embed"]["tok"].astype(x.dtype))
    else:
        logits = jnp.dot(x, head["lm"].astype(x.dtype))
    if tp_axis is not None:
        logits = lax.all_gather(logits, tp_axis, axis=logits.ndim - 1,
                                tiled=True)
    return logits.astype(jnp.float32)


def _split_qkv(qkv, heads_local: int, head_dim: int):
    """Per-head interleaved unpack — the standalone_gpt packing, so serve
    reads the SAME checkpoints at any TP degree."""
    lead = qkv.shape[:-1]
    qkv = qkv.reshape(*lead, heads_local, 3, head_dim)
    return qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]


def _serve_heads(cfg, tp_axis: Optional[str]) -> int:
    tp = _tp_size(tp_axis)
    if cfg.num_heads % tp:
        raise ValueError(
            f"num_heads ({cfg.num_heads}) not divisible by tp ({tp})")
    return cfg.num_heads // tp


def ensure_dense_ffn(num_experts: int) -> None:
    """The ONE MoE serving refusal (shared by every serve entry point —
    the paged forward programs and the engine constructor): the decode
    path assumes a dense FFN; routed-expert serving is ROADMAP item 5a."""
    if num_experts:
        raise NotImplementedError(
            "serve does not support MoE layers (num_experts > 0) yet — "
            "the paged decode/prefill programs assume a dense FFN, and "
            "no ServeConfig.plan residency strategy (tp/pp/fsdp, "
            "apex_tpu.serve.sharded) shards experts either: a plan moves "
            "dense weights, it does not route tokens. One refusal for "
            "both stacks; routed-expert serving is ROADMAP item 5a.")


def _check_stack_cfg(cfg, kv_cfg: KVCacheConfig, tp_axis) -> None:
    """The layer-stack-local half of the serve config check (no layer
    COUNT assertion — a PP stage's pools hold its own layer slice)."""
    ensure_dense_ffn(cfg.num_experts)
    heads_local = _serve_heads(cfg, tp_axis)
    if kv_cfg.num_heads != heads_local or kv_cfg.head_dim != cfg.head_dim:
        raise ValueError(
            f"KVCacheConfig ({kv_cfg.num_heads} heads x {kv_cfg.head_dim}) "
            f"does not match the model's local layout ({heads_local} x "
            f"{cfg.head_dim})")


def _check_serve_cfg(cfg, kv_cfg: KVCacheConfig, tp_axis) -> None:
    _check_stack_cfg(cfg, kv_cfg, tp_axis)
    if kv_cfg.num_layers != cfg.num_layers:
        raise ValueError(
            f"KVCacheConfig.num_layers ({kv_cfg.num_layers}) != "
            f"cfg.num_layers ({cfg.num_layers})")


# ---------------------------------------------------------------------------
# Full-prompt prefill: flash attention over the in-flight K/V (the cache
# is write-only here). Since the chunked-prefill engine rewrite this is
# the COLD-PATH ORACLE — the reference the chunked / prefix-cached /
# speculative engine streams are pinned against — and the TP-overlap
# showcase (3D activations give the rings flops to hide behind).


def gpt_prefill(params, tokens, prompt_len, cache, block_row,
                cfg, kv_cfg: KVCacheConfig,
                tp_axis: Optional[str] = None) -> Tuple[Pytree, jnp.ndarray]:
    """Process one prompt into the cache; return the next-token logits.

    ``tokens``: (bucket,) int32, the prompt padded to its compile bucket
    (padding ignored: causal attention means positions < prompt_len never
    see it, and padded K/V writes are dropped). ``prompt_len``: traced
    scalar. ``block_row``: (max_blocks,) int32 blocks owning this slot.
    Returns ``(cache', logits (vocab,))`` — logits at ``prompt_len - 1``,
    fp32, full vocab.
    """
    _check_serve_cfg(cfg, kv_cfg, tp_axis)
    heads_local = _serve_heads(cfg, tp_axis)
    t = tokens.shape[0]
    positions = jnp.arange(t)
    valid = positions < prompt_len
    x = _embed(params["embed"], tokens[None], positions, tp_axis)  # (1,t,h)

    def body(x, xs):
        lp, cl = xs
        h1 = layer_norm(x, lp["ln1_w"], lp["ln1_b"],
                        use_pallas=cfg.ln_pallas)
        qkv = _col(h1, lp["qkv_kernel"], lp["qkv_bias"], tp_axis)
        q, k, v = _split_qkv(qkv, heads_local, cfg.head_dim)  # (1,t,H,D)
        q, k, v = (a.transpose(0, 2, 1, 3) for a in (q, k, v))  # (1,H,t,D)
        ctx = flash_attention(q, k, v, causal=True,
                              block_q=cfg.attn_block_q,
                              block_k=cfg.attn_block_k)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(1, t,
                                                heads_local * cfg.head_dim)
        a = _row(ctx, lp["out_kernel"], lp["out_bias"], tp_axis,
                 overlap=cfg.overlap_comm)
        x = x + a
        h2 = layer_norm(x, lp["ln2_w"], lp["ln2_b"],
                        use_pallas=cfg.ln_pallas)
        y = jax.nn.gelu(_col(h2, lp["fc1_kernel"], lp["fc1_bias"], tp_axis),
                        approximate=True)
        m = _row(y, lp["fc2_kernel"], lp["fc2_bias"], tp_axis,
                 overlap=cfg.overlap_comm)
        x = x + m
        cl = paged_write(cl, kv_cfg, k[0], v[0],
                         jnp.broadcast_to(block_row, (t, block_row.shape[0])),
                         positions, valid)
        return x, cl

    x, cache = lax.scan(body, x, (params["layers"], cache))
    last = jnp.take(x[0], jnp.maximum(prompt_len - 1, 0), axis=0)  # (h,)
    return cache, serve_logits(params, last, cfg, tp_axis)


# ---------------------------------------------------------------------------
# The unified paged forward: q tokens per slot through the whole stack —
# ONE compiled program per (n, q) shape. q=1 is the decode step, q=k+1 the
# speculative verify, (n=1, q=chunk) the chunked prefill. Per-ROW math is
# identical across q (each token row embeds at its own position, writes
# its K/V, then attends through the paged gather masked to its own
# context), which is exactly why speculative verification and chunked
# prefill produce BITWISE the streams sequential decode would — the
# oracle tests in tests/test_serve_prefix.py pin it.


def paged_layer_stack(x, layers, start_lens, n_valid, active, cache,
                      block_tables, cfg, kv_cfg: KVCacheConfig, *,
                      tp_axis: Optional[str] = None,
                      use_pallas: Optional[bool] = None,
                      adapters: Optional[Pytree] = None,
                      adapter_ids=None,
                      gather_layer=None
                      ) -> Tuple[jnp.ndarray, Pytree]:
    """Scan embedded activations ``x`` (n, q, h) through a STACK of
    transformer layers against their paged pools — the body of
    :func:`gpt_paged_forward`, exposed so the PP-staged serving tier
    (``serve.sharded``) can run layer SLICES: stage s streams the x'
    this returns to stage s+1 instead of feeding the LM head, and each
    stage's ``cache`` holds pools for ITS layers only (same block ids,
    shared host allocator).

    ``layers``: stacked (L, ...) layer params — or, with
    ``gather_layer``, whatever per-layer pytree that hook turns into the
    full layer dict. ``gather_layer`` is the FSDP weight-residency hook:
    the scan's xs carry resident block-aligned SHARDS and each layer's
    full weights materialize for exactly one body evaluation
    (gather-on-demand; nothing is restacked, so the gathered copy dies
    with the scan step). Returns ``(x', cache')``.
    """
    _check_stack_cfg(cfg, kv_cfg, tp_axis)
    if adapters is not None:
        if tp_axis is not None:
            raise NotImplementedError(
                "paged LoRA adapters are single-device for now — the pool "
                "is not TP-sharded (pass tp_axis=None)")
        if adapter_ids is None:
            raise ValueError("adapters given without adapter_ids")
        from apex_tpu.serve.adapters import lora_delta
    heads_local = _serve_heads(cfg, tp_axis)
    n, q = x.shape[:2]
    offs = jnp.arange(q)
    positions = start_lens[:, None] + offs[None, :]            # (n, q)
    valid = active[:, None] & (offs[None, :] < n_valid[:, None])
    ctx_lens = jnp.where(valid, positions + 1, 0)
    # flat row views for the paged write/gather (each token is its own
    # "slot" sharing its owner's block-table row)
    bt_rows = jnp.repeat(block_tables, q, axis=0)   # (n*q, max_blocks)
    pos_flat = positions.reshape(-1)
    valid_flat = valid.reshape(-1)
    # q_len>1 row exits honor cfg.overlap_comm: the decomposed ring
    # scatters over the q dim, so it needs q divisible by the axis size;
    # q=1 decode stays monolithic (the PR-5 pin — a single-row GEMM has
    # nothing to hide a hop behind)
    overlap = (tp_axis is not None and cfg.overlap_comm
               and q > 1 and q % _tp_size(tp_axis) == 0)

    def body(x, xs):
        if adapters is None:
            lp, cl = xs
            ad = None
        else:
            lp, cl, ad = xs
        if gather_layer is not None:
            lp = gather_layer(lp)
        h1 = layer_norm(x, lp["ln1_w"], lp["ln1_b"],
                        use_pallas=cfg.ln_pallas)
        qkv = _col(h1, lp["qkv_kernel"], lp["qkv_bias"], tp_axis)
        if ad is not None:
            qkv = qkv + lora_delta(h1, ad["qkv_a"], ad["qkv_b"],
                                   adapter_ids)
        qh, k, v = _split_qkv(qkv, heads_local, cfg.head_dim)  # (n,q,H,D)
        k_flat = k.reshape(n * q, heads_local, cfg.head_dim)
        v_flat = v.reshape(n * q, heads_local, cfg.head_dim)
        cl = paged_write(cl, kv_cfg, k_flat.transpose(1, 0, 2),
                         v_flat.transpose(1, 0, 2), bt_rows, pos_flat,
                         valid_flat)
        ctx = paged_attention(qh.reshape(n * q, heads_local, cfg.head_dim),
                              cl, kv_cfg, bt_rows,
                              ctx_lens.reshape(-1), use_pallas=use_pallas)
        ctx = ctx.reshape(n, q, heads_local * cfg.head_dim)
        a = _row(ctx, lp["out_kernel"], lp["out_bias"], tp_axis,
                 overlap=overlap)
        if ad is not None:
            a = a + lora_delta(ctx, ad["out_a"], ad["out_b"], adapter_ids)
        x = x + a
        h2 = layer_norm(x, lp["ln2_w"], lp["ln2_b"],
                        use_pallas=cfg.ln_pallas)
        pre = _col(h2, lp["fc1_kernel"], lp["fc1_bias"], tp_axis)
        if ad is not None:
            pre = pre + lora_delta(h2, ad["fc1_a"], ad["fc1_b"],
                                   adapter_ids)
        y = jax.nn.gelu(pre, approximate=True)
        m = _row(y, lp["fc2_kernel"], lp["fc2_bias"], tp_axis,
                 overlap=overlap)
        if ad is not None:
            m = m + lora_delta(y, ad["fc2_a"], ad["fc2_b"], adapter_ids)
        x = x + m
        return x, cl

    # the adapter pool rides the scan as read-only xs (sliced per layer,
    # never restacked into ys — no per-step pool copy); the caller's jit
    # site donates it and returns it untouched
    xs = ((layers, cache) if adapters is None
          else (layers, cache, adapters))
    return lax.scan(body, x, xs)


def gpt_paged_forward(params, tokens, start_lens, n_valid, active, cache,
                      block_tables, cfg, kv_cfg: KVCacheConfig,
                      tp_axis: Optional[str] = None,
                      use_pallas: Optional[bool] = None,
                      adapters: Optional[Pytree] = None,
                      adapter_ids=None,
                      gather_layer=None
                      ) -> Tuple[Pytree, jnp.ndarray]:
    """Process ``tokens`` (n, q) — per slot, q consecutive tokens starting
    at position ``start_lens[slot]`` — against the paged cache.

    ``n_valid``: (n,) how many of each slot's q tokens are real (the rest
    are padding: K/V writes dropped, logits junk). ``active``: (n,) bool.
    Returns ``(cache', logits (n, q, vocab) fp32)`` — logits[i, j] is the
    next-token distribution after feeding tokens[i, j] at position
    ``start_lens[i] + j``. Inactive slots and invalid positions produce
    finite junk logits the engine ignores.

    ``adapters``: an optional ``serve.adapters`` AdapterPool — per-layer
    LoRA slot stacks riding the layer scan as read-only xs; each row adds
    its adapter's gathered BGMV delta (``lora_delta``) to the four
    adapted projections, with ``adapter_ids`` (n,) int32 selecting the
    pool slot per batch row (id 0 = base = exact zero delta). Per-ROW
    like everything else here, so the same pool serves decode, verify
    and chunked prefill from one compiled program each.

    ``gather_layer``: optional per-layer param materializer — see
    :func:`paged_layer_stack` (``params["layers"]`` then carries FSDP
    shard leaves instead of full stacked weights).
    """
    _check_serve_cfg(cfg, kv_cfg, tp_axis)
    n, q = tokens.shape
    offs = jnp.arange(q)
    positions = start_lens[:, None] + offs[None, :]            # (n, q)
    positions_c = jnp.minimum(positions, cfg.max_seq - 1)
    x = _embed(params["embed"], tokens, positions_c, tp_axis)  # (n, q, h)
    x, cache = paged_layer_stack(
        x, params["layers"], start_lens, n_valid, active, cache,
        block_tables, cfg, kv_cfg, tp_axis=tp_axis, use_pallas=use_pallas,
        adapters=adapters, adapter_ids=adapter_ids,
        gather_layer=gather_layer)
    return cache, serve_logits(params, x, cfg, tp_axis)


def gpt_decode_step(params, last_tokens, seq_lens, active, cache,
                    block_tables, cfg, kv_cfg: KVCacheConfig,
                    tp_axis: Optional[str] = None,
                    use_pallas: Optional[bool] = None,
                    adapters: Optional[Pytree] = None,
                    adapter_ids=None,
                    gather_layer=None
                    ) -> Tuple[Pytree, jnp.ndarray]:
    """Advance every active slot by one token (q=1 paged forward).

    ``last_tokens``: (n,) the token each slot feeds this step (the one
    sampled last step). ``seq_lens``: (n,) tokens already cached — the fed
    token's position. ``active``: (n,) bool. Returns ``(cache', logits
    (n, vocab) fp32)``; inactive slots produce finite junk logits the
    engine ignores. ``adapters``/``adapter_ids``: optional per-slot LoRA
    (see :func:`gpt_paged_forward`).
    """
    n = last_tokens.shape[0]
    cache, logits = gpt_paged_forward(
        params, last_tokens[:, None], seq_lens,
        jnp.ones((n,), jnp.int32), active, cache, block_tables, cfg,
        kv_cfg, tp_axis=tp_axis, use_pallas=use_pallas,
        adapters=adapters, adapter_ids=adapter_ids,
        gather_layer=gather_layer)
    return cache, logits[:, 0]


def gpt_verify_step(params, fed_tokens, seq_lens, n_fed, active, cache,
                    block_tables, cfg, kv_cfg: KVCacheConfig,
                    tp_axis: Optional[str] = None,
                    use_pallas: Optional[bool] = None,
                    adapters: Optional[Pytree] = None,
                    adapter_ids=None,
                    gather_layer=None
                    ) -> Tuple[Pytree, jnp.ndarray]:
    """Speculative verify: feed ``fed_tokens`` (n, k+1) — each slot's last
    sampled token followed by up to k drafted tokens — in ONE paged call
    (the MPK amortization: q_len=k+1 turns k+1 dispatch-bound steps into
    one). Returns ``(cache', logits (n, k+1, vocab))``; logits[i, j]
    scores the token AFTER fed_tokens[i, j], so the engine accepts the
    longest run where the sampled token matches the next draft. Rejected
    drafts' K/V writes need no rollback: the accepted length caps
    ``seq_lens``, the stale positions are masked by every later context
    window and overwritten when real tokens reach them (the same
    ``mode="drop"``/masking contract that drops padded writes).

    :func:`megakernel.gpt_verify_step_fused` is the fused sibling —
    same semantics, one Pallas block per layer — which the engine wires
    in when ``ServeConfig.megakernel`` resolves on; this per-op path is
    the parity oracle the fused one is pinned against."""
    return gpt_paged_forward(params, fed_tokens, seq_lens, n_fed, active,
                             cache, block_tables, cfg, kv_cfg,
                             tp_axis=tp_axis, use_pallas=use_pallas,
                             adapters=adapters, adapter_ids=adapter_ids,
                             gather_layer=gather_layer)


def gpt_prefill_chunk(params, tokens, start, n_valid, cache, block_row,
                      cfg, kv_cfg: KVCacheConfig,
                      tp_axis: Optional[str] = None,
                      use_pallas: Optional[bool] = None,
                      adapters: Optional[Pytree] = None,
                      adapter_id=None,
                      gather_layer=None
                      ) -> Tuple[Pytree, jnp.ndarray]:
    """Process one fixed-size chunk of ONE prompt into the cache.

    ``tokens``: (chunk,) int32, prompt positions ``start .. start+n_valid-1``
    padded to the chunk size (padding writes dropped). ``block_row``:
    (max_blocks,) int32 blocks owning the slot. Returns ``(cache', logits
    (vocab,))`` — the next-token logits after the chunk's LAST valid
    token, meaningful only on the final chunk of a prompt (the engine
    samples the first generated token from it).

    One chunk shape -> ONE compiled prefill program for the engine's
    lifetime, replacing the PR-5 bucket ladder: the chunk interleaves
    into decode steps, so long prompts neither stall running decodes nor
    mint per-bucket compilations.

    ``adapters``/``adapter_id``: optional LoRA — ``adapter_id`` is the
    ONE prefilling slot's pool id (scalar; the prompt's K/V must be
    written with the same adapted projections decode will use).
    """
    aids = (None if adapters is None
            else jnp.reshape(jnp.asarray(adapter_id, jnp.int32), (1,)))
    cache, logits = gpt_paged_forward(
        params, tokens[None, :], jnp.asarray(start)[None],
        jnp.asarray(n_valid)[None], jnp.ones((1,), bool), cache,
        block_row[None, :], cfg, kv_cfg, tp_axis=tp_axis,
        use_pallas=use_pallas, adapters=adapters, adapter_ids=aids,
        gather_layer=gather_layer)
    last = jnp.take(logits[0], jnp.maximum(n_valid - 1, 0), axis=0)
    return cache, last

"""Draft-token proposers for self-speculative decoding.

At q_len=1 the decode program is dispatch/latency-bound, not flops-bound
(the MPK observation, arXiv 2512.22219): verifying k drafted tokens in
ONE paged-attention call (``serve.decode.gpt_verify_step``) costs barely
more wall-clock than one decode step, so any drafter that guesses right
even occasionally buys throughput. The interface is deliberately tiny —
``propose(tokens, k) -> up to k draft ids`` on the host, between steps —
so a small draft MODEL can slot in later without touching the engine;
what ships now is the zero-cost **prompt-lookup / n-gram** drafter
(PLD / arXiv 2304.04487 lineage): find the most recent earlier occurrence
of the sequence's last ``ngram`` tokens and propose whatever followed it.
That is exactly the right drafter for the shared-system-prompt serving
workloads the prefix cache targets — summarization, RAG, code editing,
few-shot prompts — where the continuation frequently copies spans of the
prompt.

Correctness never depends on the drafter: the engine accepts only the
longest run of drafts that match what its own verify pass sampled at each
position, so streams stay BITWISE identical to non-speculative decode
(greedy and same-key sampled alike; pinned by test). A bad drafter costs
wasted verify columns, never wrong tokens.
"""

from __future__ import annotations

from typing import List, Protocol, Sequence, runtime_checkable

__all__ = ["Drafter", "NGramDrafter"]


@runtime_checkable
class Drafter(Protocol):
    """Host-side draft proposer. ``tokens`` is the request's full history
    (prompt + generated so far); return at most ``k`` draft ids — an empty
    list opts the slot out of this step's speculation (it decodes
    normally). Called between engine steps: keep it cheap."""

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        ...


class NGramDrafter:
    """Prompt-lookup drafter: match the last ``ngram`` tokens against the
    most recent earlier occurrence in the history and propose the tokens
    that followed it. O(len(history) * ngram) per call with no state —
    cheap enough to run for every active slot every step.

    ``min_context``: histories shorter than this never propose (too little
    signal to be worth the verify columns)."""

    def __init__(self, ngram: int = 3, min_context: int = 8):
        if ngram < 1:
            raise ValueError("ngram must be >= 1")
        self.ngram = ngram
        self.min_context = max(min_context, ngram + 1)

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        # the engine hands its incrementally-maintained history list:
        # don't copy the whole thing per step
        t = tokens if isinstance(tokens, list) else list(tokens)
        n = len(t)
        if k < 1 or n < self.min_context:
            return []
        tail = t[n - self.ngram:]
        # most recent earlier occurrence wins (locality: recent repeats
        # predict the continuation better than distant ones)
        for i in range(n - self.ngram - 1, -1, -1):
            if t[i:i + self.ngram] == tail:
                return t[i + self.ngram:i + self.ngram + k]
        return []

"""Per-tenant paged LoRA adapters — ROADMAP item 5's weight-side pager.

One base model, thousands of tenant variants, zero recompiles: rank-r
LoRA deltas (A/B pairs for the four adapted projections — fused QKV,
attention out-proj, FC1, FC2) live in an :func:`init_adapter_pool`
**AdapterPool** — ONE donated pytree of fixed-shape per-layer slot
stacks that rides alongside the paged KV pools through every serve
program. Slot 0 is the base model: all-zeros A/B, so ``adapter_id == 0``
is an EXACT zero delta and base-traffic streams are bitwise the
pre-adapter engine's. Application is Punica-style gathered BGMV
(:func:`lora_delta`): each batch row gathers ITS adapter's factors by
id and adds ``(x @ A[aid]) @ B[aid]`` — per-row math, so decode,
speculative verify and chunked prefill all honor adapters from the SAME
compiled program per jit site regardless of which adapters are resident
or active.

Host-side, :class:`AdapterRegistry` is the ``kv_cache.BlockAllocator``
discipline applied to weights: named adapters load/unload into pool
slots at runtime, every decoding slot holds a refcount on its adapter,
idle (refcount-0) residents park in an LRU and are evicted under pool
pressure, and ``assert_consistent`` keeps the bookkeeping loud. The
LoRA scale is folded into B at :func:`write_adapter` time, so the
device pool needs no per-adapter scale array and the compiled programs
never see it.

The offline oracle lives here too: :func:`merge_adapter_params` bakes
``W + A @ B * scale`` into a dense parameter pytree — run it through the
cold flash-prefill oracle (``decode.gpt_prefill``) and the paged
adapter stream must match within fp tolerance (tests pin it).
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

Pytree = Any

# the four adapted projections; pool keys are f"{target}_a"/f"{target}_b"
ADAPTER_TARGETS = ("qkv", "out", "fc1", "fc2")


def _target_dims(cfg) -> Dict[str, Tuple[int, int]]:
    """(d_in, d_out) per adapted projection — the standalone_gpt layer
    kernel shapes (qkv (h, 3h), out (h, h), fc1 (h, f), fc2 (f, h))."""
    h, f = cfg.hidden, cfg.ffn_hidden
    return {"qkv": (h, 3 * h), "out": (h, h), "fc1": (h, f), "fc2": (f, h)}


def init_adapter_pool(cfg, rank: int, max_adapters: int,
                      dtype=None) -> Pytree:
    """The AdapterPool: one zero-initialized pytree of per-layer slot
    stacks — ``f"{t}_a"`` of shape (L, S, d_in, r) and ``f"{t}_b"`` of
    (L, S, r, d_out) for each target t, with S = ``max_adapters + 1``
    slots (slot 0 reserved for the base model's exact zero delta).

    The leading layer dim rides the serve programs' layer scan as a
    read-only xs alongside the stacked layer params; the whole pool is
    donated through every jit site and returned untouched, so no decode
    step ever copies it and no adapter load/swap ever retraces.
    """
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    if max_adapters < 1:
        raise ValueError(f"max_adapters must be >= 1, got {max_adapters}")
    dt = dtype if dtype is not None else cfg.dtype
    L, S = cfg.num_layers, max_adapters + 1
    pool = {}
    for t, (d_in, d_out) in _target_dims(cfg).items():
        pool[f"{t}_a"] = jnp.zeros((L, S, d_in, rank), dt)
        pool[f"{t}_b"] = jnp.zeros((L, S, rank, d_out), dt)
    return pool


def adapter_pool_bytes(cfg, rank: int, max_adapters: int,
                       dtype=None) -> int:
    """HBM bytes :func:`init_adapter_pool` allocates (capacity planning
    next to ``kv_cache_bytes``)."""
    dt = jnp.dtype(dtype if dtype is not None else cfg.dtype)
    S = max_adapters + 1
    elems = sum((d_in + d_out) * rank
                for d_in, d_out in _target_dims(cfg).values())
    return cfg.num_layers * S * elems * dt.itemsize


def make_adapter_weights(cfg, rank: int, key, std: float = 0.02) -> Pytree:
    """Random host-side adapter weights for tests/benches: per target,
    ``f"{t}_a"`` (L, d_in, r) and ``f"{t}_b"`` (L, r, d_out), both
    normal(std) so the delta is nonzero (unlike training-style B=0
    init, a zero delta would make the merged-oracle test vacuous)."""
    out = {}
    keys = jax.random.split(key, 2 * len(ADAPTER_TARGETS))
    dims = _target_dims(cfg)
    for i, t in enumerate(ADAPTER_TARGETS):
        d_in, d_out = dims[t]
        out[f"{t}_a"] = (jax.random.normal(
            keys[2 * i], (cfg.num_layers, d_in, rank)) * std
        ).astype(cfg.dtype)
        out[f"{t}_b"] = (jax.random.normal(
            keys[2 * i + 1], (cfg.num_layers, rank, d_out)) * std
        ).astype(cfg.dtype)
    return out


def _check_weights(pool: Pytree, weights: Pytree) -> None:
    for t in ADAPTER_TARGETS:
        for side in ("a", "b"):
            k = f"{t}_{side}"
            if k not in weights:
                raise ValueError(f"adapter weights missing {k!r}")
            want = pool[k].shape[:1] + pool[k].shape[2:]  # (L, ...) sans S
            got = jnp.shape(weights[k])
            if tuple(got) != want:
                raise ValueError(
                    f"adapter weights[{k!r}] shape {tuple(got)} != pool "
                    f"slot shape {want}")


def write_adapter(pool: Pytree, slot: int, weights: Pytree,
                  scale: float = 1.0) -> Pytree:
    """Write one adapter's A/B factors into pool ``slot`` (host-side
    eager update — never a jit site, so loads can't mint compiles).
    ``scale`` (the LoRA alpha/r) is folded into B here; the programs
    apply a bare ``(x @ A) @ B``. Slot 0 is the base model's zero delta
    and refuses writes."""
    if not 1 <= slot <= pool["qkv_a"].shape[1] - 1:
        raise ValueError(
            f"slot must be in [1, {pool['qkv_a'].shape[1] - 1}] "
            f"(slot 0 is the reserved base zero-delta), got {slot}")
    _check_weights(pool, weights)
    out = dict(pool)
    for t in ADAPTER_TARGETS:
        a = jnp.asarray(weights[f"{t}_a"]).astype(pool[f"{t}_a"].dtype)
        b = (jnp.asarray(weights[f"{t}_b"]) * scale).astype(
            pool[f"{t}_b"].dtype)
        out[f"{t}_a"] = pool[f"{t}_a"].at[:, slot].set(a)
        out[f"{t}_b"] = pool[f"{t}_b"].at[:, slot].set(b)
    return out


def merge_adapter_params(params: Pytree, weights: Pytree,
                         scale: float = 1.0) -> Pytree:
    """The dense merged-weight ORACLE: a new parameter pytree with every
    adapted kernel replaced by ``W + A @ B * scale`` — what a per-tenant
    merged checkpoint would serve. Run it through the cold flash-prefill
    oracle and the paged adapter stream must agree within fp tolerance
    (the MIGRATION.md "per-tenant fine-tunes" recipe inverted)."""
    layers = dict(params["layers"])
    for t, kern in (("qkv", "qkv_kernel"), ("out", "out_kernel"),
                    ("fc1", "fc1_kernel"), ("fc2", "fc2_kernel")):
        a = jnp.asarray(weights[f"{t}_a"])
        b = jnp.asarray(weights[f"{t}_b"])
        w = layers[kern]
        delta = jnp.einsum("lir,lro->lio", a.astype(w.dtype),
                           b.astype(w.dtype)) * scale
        layers[kern] = w + delta.astype(w.dtype)
    return {**params, "layers": layers}


def lora_delta(x, a, b, adapter_ids):
    """Punica-style gathered BGMV: ``x`` (n, q, d_in) against one
    layer's slot stacks ``a`` (S, d_in, r) / ``b`` (S, r, d_out), each
    row applying ITS adapter — returns ``(x @ A[aid]) @ B[aid]``
    (n, q, d_out). ``adapter_ids`` (n,) int32; id 0 gathers the
    all-zeros base slot, an EXACT zero delta (zero matmul, not a
    select), which is what keeps base-traffic streams bitwise intact.
    The scale is pre-folded into ``b`` by :func:`write_adapter`."""
    ag = jnp.take(a, adapter_ids, axis=0).astype(x.dtype)  # (n, d_in, r)
    bg = jnp.take(b, adapter_ids, axis=0).astype(x.dtype)  # (n, r, d_out)
    t = jnp.einsum("nqi,nir->nqr", x, ag)
    return jnp.einsum("nqr,nro->nqo", t, bg)


class AdapterRegistry:
    """Host-side slot bookkeeping for the AdapterPool — the
    ``BlockAllocator`` discipline applied to weights.

    Named adapters map to pool slots ``1..max_adapters`` (slot 0 is the
    base model and never allocated). :meth:`acquire` pins an adapter for
    a decoding slot (refcount up, LRU touch); :meth:`release` unpins;
    :meth:`load` assigns a slot to a new name, LRU-evicting an IDLE
    (refcount-0) resident under pool pressure and refusing — loudly —
    when every resident is pinned. The registry never touches the
    device pool; callers pair ``load`` with :func:`write_adapter`.

    Counters mirror the allocator's: ``hits_total`` / ``misses_total``
    (acquire outcomes), ``loads_total`` / ``unloads_total`` /
    ``evictions_total``. :meth:`assert_consistent` checks the slot
    partition, refcount and LRU invariants (the chaos test drives it
    every step)."""

    def __init__(self, max_adapters: int):
        if max_adapters < 1:
            raise ValueError(
                f"max_adapters must be >= 1, got {max_adapters}")
        self.max_adapters = max_adapters
        # LIFO free list, slot 1 on top (deterministic assignment order)
        self._free: List[int] = list(range(max_adapters, 0, -1))
        self._slots: Dict[str, int] = {}
        self._refs: Dict[str, int] = {}
        # idle (refcount-0) residents in LRU order: front = evict first
        self._idle: "collections.OrderedDict[str, None]" = (
            collections.OrderedDict())
        self.hits_total = 0
        self.misses_total = 0
        self.loads_total = 0
        self.unloads_total = 0
        self.evictions_total = 0

    # -- queries -----------------------------------------------------------
    def lookup(self, name: str) -> Optional[int]:
        """Resident slot of ``name`` (no refcount, no counters)."""
        return self._slots.get(name)

    def resident(self) -> Dict[str, int]:
        """name -> slot for every resident adapter (the membership
        heartbeat advertisement reads this)."""
        return dict(self._slots)

    @property
    def resident_count(self) -> int:
        return len(self._slots)

    @property
    def free_count(self) -> int:
        return len(self._free)

    def refcount(self, name: str) -> int:
        if name not in self._slots:
            raise KeyError(f"adapter {name!r} is not resident")
        return self._refs[name]

    def counters(self) -> Dict[str, int]:
        return {"hits_total": self.hits_total,
                "misses_total": self.misses_total,
                "loads_total": self.loads_total,
                "unloads_total": self.unloads_total,
                "evictions_total": self.evictions_total}

    # -- refcounting (one ref per decoding slot) ---------------------------
    def acquire(self, name: str) -> Optional[int]:
        """Pin ``name`` for a decoding slot: refcount up, slot returned.
        ``None`` when the adapter is not resident (a MISS — the engine
        sheds, the cluster cold-loads); a pinned adapter can never be
        evicted out from under a live stream."""
        slot = self._slots.get(name)
        if slot is None:
            self.misses_total += 1
            return None
        self.hits_total += 1
        self._refs[name] += 1
        self._idle.pop(name, None)
        return slot

    def release(self, name: str) -> None:
        """Drop one ref; at zero the adapter parks in the idle LRU
        (most-recently-released evicts last)."""
        if name not in self._slots:
            raise RuntimeError(f"release of non-resident adapter {name!r}")
        if self._refs[name] <= 0:
            raise RuntimeError(f"release of unreferenced adapter {name!r}")
        self._refs[name] -= 1
        if self._refs[name] == 0:
            self._idle[name] = None

    # -- load / unload / evict ---------------------------------------------
    def load(self, name: str) -> int:
        """Assign a pool slot to ``name`` (idempotent refresh when
        already resident). Under pool pressure the LEAST-recently-idle
        resident is evicted; when every resident is pinned by a decoding
        slot the load refuses instead of corrupting a live stream."""
        slot = self._slots.get(name)
        if slot is not None:
            self.loads_total += 1
            return slot
        if not self._free:
            if not self._idle:
                raise RuntimeError(
                    f"adapter pool exhausted: all {self.max_adapters} "
                    f"resident adapters are pinned by decoding slots — "
                    f"retire or migrate their requests first")
            victim, _ = self._idle.popitem(last=False)
            self._free.append(self._slots.pop(victim))
            del self._refs[victim]
            self.evictions_total += 1
        slot = self._free.pop()
        self._slots[name] = slot
        self._refs[name] = 0
        self._idle[name] = None
        self.loads_total += 1
        return slot

    def unload(self, name: str) -> None:
        """Explicitly remove an IDLE resident (refcount must be 0)."""
        if name not in self._slots:
            raise KeyError(f"adapter {name!r} is not resident")
        if self._refs[name] > 0:
            raise RuntimeError(
                f"cannot unload adapter {name!r}: "
                f"{self._refs[name]} decoding slot(s) still reference it")
        self._free.append(self._slots.pop(name))
        del self._refs[name]
        self._idle.pop(name, None)
        self.unloads_total += 1

    # -- invariants ---------------------------------------------------------
    def assert_consistent(self) -> None:
        """Loud invariant check (the chaos-test hook): resident slots +
        free slots exactly partition 1..max_adapters, refcounts exist
        for precisely the residents and are never negative, and the
        idle LRU is exactly the refcount-0 residents."""
        used = sorted(self._slots.values())
        if len(set(used)) != len(used):
            raise AssertionError(f"duplicate slot assignment: {used}")
        if set(used) & set(self._free):
            raise AssertionError("slot both resident and free")
        if sorted(used + self._free) != list(
                range(1, self.max_adapters + 1)):
            raise AssertionError(
                f"slots {sorted(used + self._free)} do not partition "
                f"1..{self.max_adapters}")
        if set(self._refs) != set(self._slots):
            raise AssertionError("refcount keys != resident keys")
        if any(r < 0 for r in self._refs.values()):
            raise AssertionError(f"negative refcount: {self._refs}")
        idle = {n for n, r in self._refs.items() if r == 0}
        if set(self._idle) != idle:
            raise AssertionError(
                f"idle LRU {set(self._idle)} != refcount-0 set {idle}")

"""Iteration-level continuous-batching inference engine.

The TPU-v3-pod MLPerf lesson (arXiv 1909.09756) applied to serving:
throughput at scale is slot occupancy — a static batch drains to its
longest member while every other chip's slot idles. This engine batches at
**iteration granularity** (Orca/vLLM's scheduling, rebuilt for jitted JAX
programs): a fixed grid of decode slots advances one token per step, and
between steps finished requests retire and new ones are admitted into the
freed slots. Nothing retraces, and three stacked throughput optimizations
ride the same paged cache:

* **chunked prefill, bounded compilation** — prompts are processed as
  fixed-size chunks (``ServeConfig.prefill_chunk``) interleaved into the
  decode loop: ONE compiled chunk program + ONE decode program (+ at most
  one verify program per speculative k) for the engine's whole lifetime —
  the PR-5 prompt bucket ladder and its ``n_buckets`` compile set are
  gone, and with them the TTFT-vs-throughput tradeoff of picking a ladder
  (``compile_counts()`` is the gate ``tests/test_serve.py`` pins). The
  MPK argument (arXiv 2512.22219) in scheduler form: decode is
  latency-bound, so the whole step — embed, every layer, paged attention,
  sampling — is one compiled program, one dispatch. With
  ``ServeConfig.megakernel`` the argument goes one level deeper: each
  layer's interior (LN + QKV + paged attend + MLP, int8 dequant in
  kernel) becomes ONE fused Pallas block (``serve.megakernel``), cutting
  the per-layer op count ~14 -> 2 inside that single program.
* **prefix caching** — the block allocator is content-addressed
  (``kv_cache.BlockAllocator(prefix_cache=True)``): admission looks up
  the longest cached prefix of the prompt at block granularity and only
  prefills the tail, so a shared system prompt costs ZERO prefill flops
  after its first admission; retired requests' cached blocks park in an
  evictable LRU at refcount 0 and are reclaimed only under memory
  pressure. Copy-on-write covers the one divergent-write case (a
  fully-cached prompt recomputing its final position) — a shared block is
  never mutated.
* **self-speculative decoding** — an optional host-side drafter
  (``serve.drafter``, prompt-lookup n-gram by default, pluggable for a
  small model) proposes up to k tokens per slot; ONE q_len=k+1
  paged-attention call (``gpt_verify_step``) verifies all of them,
  amortizing the dispatch-bound decode step k-fold. The engine accepts
  the longest run matching its own position-keyed draws, so streams are
  BITWISE identical to non-speculative decode (greedy and sampled);
  rejected drafts need no rollback — their K/V writes are masked by every
  later context window and overwritten when real tokens arrive.

* **donation-safe state** — the paged KV pools (``serve.kv_cache``) are
  donated through every chunk/decode/verify call; slot bookkeeping
  (block tables, lengths, last tokens, keys) stays host-side numpy with
  CACHED device mirrors — an array is re-uploaded only after an
  admission/retirement/decode actually changed it
  (``engine.transfer_counts`` pins it).
* **request-order invariance** — greedy streams are bitwise equal to
  single-request decode of each prompt, and sampled streams equal under
  the same key, because per-slot computation is row-independent and
  sampling keys are request-intrinsic (``serve.sampling``).

Weights arrive through ``resilience.CheckpointManager.latest_valid()``
(:meth:`InferenceEngine.from_checkpoint`) — a serving replica points at
the training job's checkpoint directory and refuses torn/corrupt saves.
Telemetry rides the ``monitor`` pipeline: an in-graph ``Metrics`` pytree
out of the decode/verify programs plus host-side step records (tokens/s,
TTFT, occupancy, modeled decode flops/MFU, KV bytes, chunked-prefill
backlog, speculative proposed/accepted, cumulative prefix-cache hit
counters) into a ``JsonlSink``; ``python -m apex_tpu.monitor.view``
summarizes all of them.

Monitor **tier 2** (request-level attribution, constant memory): every
request runs a lifecycle timeline — ``submitted → admitted →
prefill_start/end → first_token → decode_chunk* → retired`` on one
monotonic clock through an optional ``monitor.EventLog`` (JSONL + Chrome
trace via ``monitor.chrome_trace``, one Perfetto track per slot and per
request) — and retirement FOLDS the request's latencies (TTFT, mean
per-output-token, queue wait, end-to-end) into streaming
``monitor.Histogram``\\ s plus an optional ``monitor.SloTracker``, then
drops every per-uid entry. Engine state stays O(slots + backlog) across
millions of requests when ``retain_streams=False`` (per-request token
streams go to the ``on_retire`` callback instead of an ever-growing
dict); :meth:`InferenceEngine.stats` returns the histograms, latency
quantiles, prefix-cache/speculation counters and goodput-under-SLO
report as one JSON-serializable dict.
"""

from __future__ import annotations

import collections
import dataclasses
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu.monitor.events import EventLog
from apex_tpu.monitor.hist import DEFAULT_LATENCY_SPEC, HistSpec, Histogram
from apex_tpu.monitor.meter import Meter, modeled_request_flops
from apex_tpu.monitor.metrics import Metrics
from apex_tpu.monitor.slo import SloSpec, SloTracker
from apex_tpu.monitor.trace import span
from apex_tpu.serve.adapters import (
    AdapterRegistry,
    adapter_pool_bytes,
    init_adapter_pool,
    write_adapter,
)
from apex_tpu.serve.decode import (
    ensure_dense_ffn,
    gpt_decode_step,
    gpt_prefill_chunk,
    gpt_verify_step,
)
from apex_tpu.serve.drafter import Drafter, NGramDrafter
from apex_tpu.serve.kv_cache import (
    BlockAllocator,
    KVCacheConfig,
    copy_block,
    init_kv_cache,
    kv_cache_bytes,
    kv_read_bytes,
    kv_write_bytes_per_token,
    prefix_block_hashes,
)
from apex_tpu.serve.sampling import SamplingConfig, request_key, sample

Pytree = Any


def default_bucket_ladder(max_context: int, start: int = 16
                          ) -> Tuple[int, ...]:
    """COMPAT SHIM (pre-chunked-prefill API): powers-of-two prompt buckets
    up to ``max_context``. The engine no longer compiles per-bucket
    prefill programs — prompts stream through one fixed-size chunk program
    — but the ladder remains for callers that sized workloads by it."""
    out = []
    b = start
    while b < max_context:
        out.append(b)
        b *= 2
    out.append(max_context)
    return tuple(out)


@dataclasses.dataclass
class Request:
    """One generation request. ``seed`` feeds the request's sampling key
    (default: crc32 of the uid — stable across runs and admission orders);
    irrelevant under greedy decoding. ``tenant`` names the paying party
    for the cluster router's weighted fair queueing (the single engine
    ignores it). ``adapter`` names the tenant's LoRA adapter (None =
    the base model): admission binds it to a resident pool slot and an
    unknown name is SHED via ``on_reject``, never served on the wrong
    weights."""

    uid: str
    tokens: Sequence[int]
    max_new_tokens: int = 64
    seed: Optional[int] = None
    tenant: str = "default"
    adapter: Optional[str] = None

    def sampling_seed(self) -> int:
        if self.seed is not None:
            return int(self.seed)
        return zlib.crc32(self.uid.encode())


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine shape knobs (all static — they pick the compiled programs)."""

    num_slots: int = 4
    block_size: int = 16
    # total pool blocks; default = num_slots * blocks-per-max-context (no
    # oversubscription). Smaller pools admit fewer concurrent requests —
    # admission simply waits for frees, it never preempts.
    num_blocks: Optional[int] = None
    # COMPAT SHIM: the pre-chunked-prefill bucket ladder. Accepted and
    # surfaced via engine.buckets/bucket_for for old callers, but NO
    # prefill program is compiled per bucket anymore.
    prefill_buckets: Optional[Tuple[int, ...]] = None
    # tokens per prefill chunk: ONE compiled prefill program, interleaved
    # into the decode loop one chunk per step
    prefill_chunk: int = 32
    # content-addressed block reuse across requests (zero prefill flops
    # for cached shared prefixes)
    prefix_cache: bool = True
    # self-speculative decoding: draft up to spec_k tokens per slot per
    # step and verify them in one q_len=spec_k+1 call; 0 disables
    spec_k: int = 0
    spec_ngram: int = 3  # n-gram order of the default prompt-lookup drafter
    # fused per-layer decode megakernel (serve.megakernel): "auto" uses it
    # when supported AND a compiled Mosaic backend is available, "on"
    # forces it (interpret mode off-TPU — the parity tests' mode; raises
    # when the model shape is unsupported), "off" keeps the per-op
    # gpt_decode_step program
    megakernel: str = "auto"
    max_context: Optional[int] = None  # default: model cfg.max_seq
    eos_id: Optional[int] = None
    # "none" | "int8" | "int4" (comm.quantize codec; int4 = nibble-packed
    # codes + bf16 group scales, half the int8 pool bytes — doubles the
    # contexts a fixed KV budget serves)
    kv_quant: str = "none"
    # int4 scale-group length along head_dim (None: one scale per vector)
    kv_group: Optional[int] = None
    # per-tenant paged LoRA (serve.adapters): rank of the A/B factors
    # (0 disables — the programs are built WITHOUT adapter arguments and
    # trace identically to the pre-adapter engine) and the number of
    # concurrently-resident adapters (pool slots beyond the reserved
    # base slot 0)
    lora_rank: int = 0
    max_adapters: int = 0
    # model-parallel serving (apex_tpu.serve.sharded): a ParallelismPlan
    # whose ONE sharding term (tp= / pp= / data='fsdp') picks the
    # residency strategy — ``sharded.build_engine`` reads it; None keeps
    # the single-chip engine. Validated inference-legal at validate()
    # time via plan.serve_overrides() (optimizer-coupled knobs refused).
    plan: Optional[Any] = None
    sampling: SamplingConfig = dataclasses.field(
        default_factory=SamplingConfig)

    def validate(self) -> None:
        if self.num_slots <= 0:
            raise ValueError("num_slots must be positive")
        if self.block_size <= 0:
            raise ValueError("block_size must be positive")
        if self.num_blocks is not None and self.num_blocks <= 0:
            raise ValueError("num_blocks must be positive when given")
        if self.prefill_chunk <= 0:
            raise ValueError("prefill_chunk must be positive")
        if self.spec_k < 0:
            raise ValueError("spec_k must be >= 0")
        if self.spec_ngram < 1:
            raise ValueError("spec_ngram must be >= 1")
        if self.megakernel not in ("auto", "on", "off"):
            raise ValueError(f"megakernel must be 'auto', 'on' or 'off', "
                             f"got {self.megakernel!r}")
        if self.max_context is not None and self.max_context <= 0:
            raise ValueError("max_context must be positive when given")
        if self.kv_quant not in ("none", "int8", "int4"):
            raise ValueError(f"kv_quant must be 'none', 'int8' or 'int4', "
                             f"got {self.kv_quant!r}")
        if self.kv_group is not None and self.kv_quant != "int4":
            raise ValueError("kv_group only applies to kv_quant='int4'")
        if self.lora_rank < 0:
            raise ValueError("lora_rank must be >= 0")
        if self.max_adapters < 0:
            raise ValueError("max_adapters must be >= 0")
        if self.lora_rank > 0 and self.max_adapters < 1:
            raise ValueError("lora_rank > 0 needs max_adapters >= 1")
        if self.max_adapters > 0 and self.lora_rank == 0:
            raise ValueError("max_adapters > 0 needs lora_rank > 0")
        if self.plan is not None:
            if not hasattr(self.plan, "serve_overrides"):
                raise ValueError(
                    f"plan must be a ParallelismPlan "
                    f"(apex_tpu.parallel.plan), got {type(self.plan)!r}")
            # runs the inference-legality validation eagerly: a plan that
            # only makes sense feeding an optimizer dies here, not
            # mid-build inside serve.sharded
            self.plan.serve_overrides()
            if self.lora_rank > 0:
                raise NotImplementedError(
                    "paged LoRA adapters are single-device for now — the "
                    "AdapterPool is not plan-sharded (lora_rank needs "
                    "plan=None)")
        self.sampling.validate()


# the engine's latency dimensions; each gets a streaming Histogram
_HIST_NAMES = ("ttft_ms", "tpot_ms", "queue_ms", "e2e_ms",
               "decode_step_ms", "verify_step_ms")

# host arrays with cached device mirrors (uploaded only when dirty)
_MIRROR_NAMES = ("block_tables", "seq_lens", "last_tokens", "active",
                 "keys", "adapter_ids")


@dataclasses.dataclass
class _SlotState:
    request: Request
    blocks: List[int]          # every block the slot holds a ref on
    generated: List[int]
    # prompt + generated, maintained incrementally so the drafter reads
    # it without an O(prompt_len) re-concatenation every step
    history: List[int]
    prompt_len: int
    prefill_pos: int           # prompt tokens cached so far (chunk cursor)
    cached_tokens: int         # prompt tokens served by the prefix cache
    # (block_id, hash, end_pos): commit to the content map once the chunk
    # cursor passes end_pos (the block is then fully written)
    pending_commits: List[Tuple[int, int, int]]
    # request timeline, ms on the engine's one monotonic clock
    t_submit_ms: float
    t_first_ms: float = 0.0
    queue_ms: float = 0.0
    ttft_ms: float = 0.0
    chunk_start_ms: float = 0.0  # start of the decode chunk being accumulated
    chunk_done: int = 0          # tokens already covered by emitted chunks
    adapter_id: int = 0          # resident pool slot this request decodes on


class InferenceEngine:
    """Continuous-batching engine over one parameter pytree.

    Tensor parallelism: pass ``tp_axis``/``tp_size`` AND a ``transform``
    that shard_maps the chunk/decode/verify python callables over that
    axis (params TP-sharded by ``gpt_param_specs``-style specs, everything
    else replicated) — the programs then route through the
    ``tensor_parallel`` layers with vocab-gathered logits, and the KV
    pools hold the ``num_heads / tp_size`` LOCAL heads. The default
    (``tp_axis=None``, identity transform) drives the single-device
    programs — the stock-jax path the acceptance tests pin.

    ``sink``: an ``apex_tpu.monitor.JsonlSink`` (or None) receiving one
    record per engine step. ``peak_flops_per_s``: chip peak for the
    modeled decode-MFU column (omitted -> mfu not reported).

    ``drafter``: a ``serve.drafter.Drafter`` for the speculative path
    (default when ``spec_k > 0``: ``NGramDrafter(spec_ngram)``). The
    drafter only proposes — acceptance is decided by the engine's own
    verify pass, so a bad drafter can never change a stream.

    Tier-2 telemetry: ``events`` (a ``monitor.EventLog``) records every
    request's lifecycle; ``slo`` (a ``monitor.SloSpec``) turns on
    goodput/violation accounting; ``hist_spec`` overrides the latency
    bucket ladder; ``chunk_tokens`` sets the decode-chunk EVENT span
    granularity (unrelated to ``prefill_chunk``, the compiled chunk
    size). ``retain_streams=False`` keeps per-request state O(slots):
    retirement hands the stream to ``on_retire(uid, tokens)`` (or drops
    it) instead of growing the ``finished`` dict forever.
    """

    def __init__(
        self,
        params: Pytree,
        cfg,  # transformer.testing.GPTConfig
        serve_cfg: Optional[ServeConfig] = None,
        *,
        base_key=None,
        sink=None,
        peak_flops_per_s: Optional[float] = None,
        transform: Optional[Callable[[Callable], Callable]] = None,
        tp_axis: Optional[str] = None,
        tp_size: int = 1,
        use_pallas: Optional[bool] = None,
        events: Optional[EventLog] = None,
        slo: Optional[SloSpec] = None,
        hist_spec: Optional[HistSpec] = None,
        retain_streams: bool = True,
        on_retire: Optional[Callable[[str, List[int]], None]] = None,
        chunk_tokens: int = 16,
        drafter: Optional[Drafter] = None,
        gather_layer: Optional[Callable] = None,
        on_reject: Optional[Callable[[Request, Dict[str, Any]],
                                     None]] = None,
        meter: Optional[Meter] = None,
        meter_worker: str = "engine",
    ):
        scfg = serve_cfg or ServeConfig()
        scfg.validate()
        ensure_dense_ffn(cfg.num_experts)
        if (tp_axis is None) != (tp_size == 1):
            raise ValueError("pass tp_axis together with tp_size > 1 "
                             "(and a shard_map transform)")
        if cfg.num_heads % tp_size:
            raise ValueError(f"num_heads ({cfg.num_heads}) not divisible "
                             f"by tp_size ({tp_size})")
        self.params = params
        self.cfg = cfg
        self.serve_cfg = scfg
        if scfg.max_context is not None and scfg.max_context > cfg.max_seq:
            raise ValueError(
                f"max_context ({scfg.max_context}) exceeds the model's "
                f"max_seq ({cfg.max_seq})")
        self.max_context = scfg.max_context or cfg.max_seq
        bs = scfg.block_size
        self._blocks_per_slot = -(-self.max_context // bs)
        num_blocks = (scfg.num_blocks if scfg.num_blocks is not None
                      else scfg.num_slots * self._blocks_per_slot)
        self._tp_axis = tp_axis
        self.kv_cfg = KVCacheConfig(
            num_layers=cfg.num_layers, num_heads=cfg.num_heads // tp_size,
            head_dim=cfg.head_dim, num_blocks=num_blocks, block_size=bs,
            dtype=cfg.dtype, quantized=scfg.kv_quant != "none",
            bits=4 if scfg.kv_quant == "int4" else 8,
            group_size=scfg.kv_group)
        self.allocator = BlockAllocator(num_blocks,
                                        prefix_cache=scfg.prefix_cache)
        self.cache = init_kv_cache(self.kv_cfg)
        self.drafter: Optional[Drafter] = None
        if scfg.spec_k > 0:
            self.drafter = (drafter if drafter is not None
                            else NGramDrafter(ngram=scfg.spec_ngram))
        elif drafter is not None:
            raise ValueError("drafter given but spec_k == 0 — set "
                             "ServeConfig.spec_k to enable speculation")
        # per-tenant paged LoRA: the donated AdapterPool + the host-side
        # registry (None/None when disabled — the programs are then built
        # WITHOUT adapter arguments, trace-identical to the pre-adapter
        # engine)
        self._lora_pool = None
        self.adapters: Optional[AdapterRegistry] = None
        self._adapter_load_ms_total = 0.0
        if scfg.lora_rank > 0:
            if tp_axis is not None:
                raise NotImplementedError(
                    "paged LoRA adapters are single-device for now — the "
                    "AdapterPool is not TP-sharded (lora_rank needs "
                    "tp_axis=None)")
            self._lora_pool = init_adapter_pool(cfg, scfg.lora_rank,
                                                scfg.max_adapters)
            self.adapters = AdapterRegistry(scfg.max_adapters)
        n = scfg.num_slots
        self._block_tables = np.zeros((n, self._blocks_per_slot), np.int32)
        self._seq_lens = np.zeros((n,), np.int32)
        self._last_tokens = np.zeros((n,), np.int32)
        self._active = np.zeros((n,), bool)
        self._keys = np.zeros((n, 2), np.uint32)
        self._adapter_ids = np.zeros((n,), np.int32)
        # device mirrors of the host arrays above: uploaded lazily, reused
        # until a host mutation marks them dirty (the satellite gate —
        # steady-state decode re-uploads ONLY what changed)
        self._dev_cache: Dict[str, Any] = {}
        self.transfer_counts: Dict[str, int] = {
            nm: 0 for nm in _MIRROR_NAMES}
        self._slots: List[Optional[_SlotState]] = [None] * n
        # admission-ordered slots with prompt tokens still to prefill; the
        # front slot gets one chunk per step (FCFS-to-completion: best
        # TTFT under interleaving)
        self._prefill_queue: collections.deque = collections.deque()
        self._pending: collections.deque = collections.deque()
        self._finished: Dict[str, List[int]] = {}
        self._base_key = (base_key if base_key is not None
                          else jax.random.PRNGKey(0))
        self._sink = sink
        self._peak = peak_flops_per_s
        self._step_idx = 0
        self._tokens_generated = 0
        self._t_start: Optional[float] = None
        # tier-2 telemetry: one monotonic clock (the EventLog's when
        # given, so event timestamps and latency folds agree), streaming
        # histograms, optional SLO accounting — all O(1) per request
        self._events = events
        self._t_anchor = time.perf_counter()
        if chunk_tokens < 1:
            raise ValueError(
                f"chunk_tokens must be >= 1, got {chunk_tokens}")
        self._chunk_tokens = int(chunk_tokens)
        hspec = hist_spec or DEFAULT_LATENCY_SPEC
        self.hists: Dict[str, Histogram] = {
            name: Histogram(hspec) for name in _HIST_NAMES}
        # tier-4 attribution: the engine-LOCAL decomposition from the slot
        # timeline (queue/prefill/decode; transfer and stall only exist at
        # the cluster, whose event-tap AttributionAccumulator owns them)
        self._attrib_hists: Dict[str, Histogram] = {
            c: Histogram(hspec) for c in ("queue", "prefill", "decode")}
        self._attrib_n = 0
        # tier-4 metering: retirement charges the request's tenant into
        # the (possibly cluster-shared) ledger — exactly once, by
        # whichever engine retires it
        self._meter = meter
        self._meter_worker = meter_worker
        # the tracker SHARES the engine's histograms (decode_step_ms is
        # engine-only): one fold per retirement, one source of truth for
        # both the stats() quantiles and the slo_report
        self._slo = (SloTracker(slo, hists={
            d: self.hists[d]
            for d in ("ttft_ms", "tpot_ms", "queue_ms", "e2e_ms")})
            if slo is not None else None)
        self._retain_streams = retain_streams
        self._on_retire = on_retire
        # overload behavior: with an on_reject hook, a request the pool
        # can NEVER fit is handed back as a structured rejection (the
        # cluster router's shed path) instead of run()'s deadlock-loud
        # RuntimeError; default behavior (raise) unchanged
        self._on_reject = on_reject
        self._rejected = 0
        self._completed = 0
        # throughput-optimization counters (stats() + step records)
        self._prefix_blocks_hit = 0
        self._prefix_blocks_needed = 0
        self._prefill_tokens_saved = 0
        self._prefill_flops_saved = 0.0
        self._cow_copies = 0
        self._chunks_run = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._verify_steps = 0
        self._decode_steps = 0
        self._n_params = sum(
            x.size for x in jax.tree_util.tree_leaves(params))
        # model-parallel serving telemetry hook (serve.sharded sets it):
        # a zero-arg callable returning the flat plan fields stats()
        # merges — plan, hbm_model_bytes, weight_gather_ms,
        # pp_bubble_fraction. None on single-chip engines (the fields
        # are then absent, and monitor.regress skips what isn't there).
        self.plan_stats: Optional[Callable[[], Dict[str, Any]]] = None
        wrap = transform if transform is not None else (lambda f: f)
        # FSDP weight residency (serve.sharded): per-layer param
        # materializer threaded into the paged forwards — params then
        # carry resident shards, gathered for one layer body at a time
        self._gather_layer = gather_layer
        self._use_pallas = use_pallas
        self._megakernel = self._resolve_megakernel()
        self._build_programs(wrap)

    def _resolve_megakernel(self) -> bool:
        """ServeConfig.megakernel -> whether the decode AND verify
        programs are the fused per-layer block. ``auto`` requires a
        compiled Mosaic backend (the interpreter saves no dispatch);
        ``on`` forces it and raises on unsupported shapes (TP, LoRA,
        MoE, layers whose live TILE set exceeds the VMEM budget) with
        the measured refusal reason. An ``auto`` fallback on a COMPILED
        backend warns once per reason — a 10x slower serve run must be
        diagnosable from the log, not only from the bench line's
        ``decode_kernel`` field."""
        from apex_tpu.ops._pallas_util import compiled_backend
        from apex_tpu.serve.megakernel import (megakernel_refusal,
                                               warn_megakernel_fallback)

        mode = self.serve_cfg.megakernel
        if mode == "off":
            return False
        # the verify step feeds spec_k+1 rows per slot; gate on the
        # larger live set so speculation never flips the kernel choice
        q = self.serve_cfg.spec_k + 1
        if self._tp_axis is not None:
            reason = "TP-sharded programs ride the per-op layer body"
        elif self._gather_layer is not None:
            reason = ("plan-sharded (FSDP weight-resident) params ride "
                      "the per-op layer body")
        elif self.serve_cfg.lora_rank > 0:
            reason = ("per-slot LoRA adapters (lora_rank > 0) ride the "
                      "per-op layer body")
        else:
            reason = megakernel_refusal(self.cfg, self.kv_cfg,
                                        allow_interpret=(mode == "on"),
                                        q=q)
        if mode == "on":
            if reason is not None:
                raise ValueError(
                    f"megakernel='on' but the fused decode block does "
                    f"not support this configuration: {reason} — use "
                    f"megakernel='off'/'auto'")
            return True
        if reason is not None:
            if compiled_backend():
                warn_megakernel_fallback(reason)
            return False
        return True

    @property
    def megakernel_enabled(self) -> bool:
        """Whether decode steps run the fused per-layer Pallas block."""
        return self._megakernel

    @property
    def decode_kernel(self) -> str:
        """The decode path this engine actually runs: ``fused`` (the
        per-layer megakernel), ``pallas`` (gather-attend kernel inside
        the per-op layer body) or ``reference`` (pure-JAX gather +
        softmax). Emitted in :meth:`stats` and the bench record so the
        stage-12 regression gate can tell a kernel FALLBACK from a real
        regression."""
        if self._megakernel:
            return "fused"
        from apex_tpu.serve.decode import _pallas_ok

        use_pallas = self._use_pallas
        if use_pallas is None:
            use_pallas = _pallas_ok(self.cfg.head_dim,
                                    allow_interpret=False)
        return "pallas" if use_pallas else "reference"

    @property
    def verify_kernel(self) -> Optional[str]:
        """The speculative verify path this engine actually runs:
        ``None`` when ``spec_k == 0`` (no verify program exists), else
        ``fused``/``pallas``/``reference`` — the same resolution as
        :attr:`decode_kernel`, because one ``megakernel`` flag drives
        both jit sites. Emitted in :meth:`stats` so the verify A/B gate
        can tell a kernel fallback from a regression."""
        if self.serve_cfg.spec_k <= 0:
            return None
        return self.decode_kernel

    # -- device mirrors ---------------------------------------------------
    def _dirty(self, *names: str) -> None:
        for nm in names:
            self._dev_cache.pop(nm, None)

    def _dev(self, name: str):
        """Cached device copy of host array ``self._<name>`` — uploads
        only when a mutation marked it dirty (``transfer_counts`` tallies
        actual uploads; the identity test pins reuse)."""
        arr = self._dev_cache.get(name)
        if arr is None:
            arr = jnp.asarray(getattr(self, "_" + name))
            self._dev_cache[name] = arr
            self.transfer_counts[name] += 1
        return arr

    # -- program construction (the ONLY jit sites) -------------------------
    def _build_programs(self, wrap) -> None:
        cfg, kv_cfg, scfg = self.cfg, self.kv_cfg, self.serve_cfg

        tp_axis = self._tp_axis
        if self._lora_pool is not None:
            # the adapter-enabled closures take the donated pool as a
            # second donated argument and return it untouched
            self._build_lora_programs(wrap)
            return

        def chunk_prefill(params, cache, tokens, start, n_valid, block_row,
                          key):
            cache, logits = gpt_prefill_chunk(
                params, tokens, start, n_valid, cache, block_row, cfg,
                kv_cfg, tp_axis=tp_axis, use_pallas=self._use_pallas,
                gather_layer=self._gather_layer)
            # the draw for the token that will sit at position start+n_valid
            # — meaningful only on a prompt's FINAL chunk; junk otherwise
            tok = sample(logits[None], key[None],
                         jnp.reshape(start + n_valid, (1,)), scfg.sampling)
            return cache, tok[0]

        use_mega = self._megakernel

        def decode(params, cache, last_tokens, seq_lens, active,
                   block_tables, keys):
            if use_mega:
                from apex_tpu.serve.megakernel import gpt_decode_step_fused

                cache, logits = gpt_decode_step_fused(
                    params, last_tokens, seq_lens, active, cache,
                    block_tables, cfg, kv_cfg)
            else:
                cache, logits = gpt_decode_step(
                    params, last_tokens, seq_lens, active, cache,
                    block_tables, cfg, kv_cfg, tp_axis=tp_axis,
                    use_pallas=self._use_pallas,
                    gather_layer=self._gather_layer)
            toks = sample(logits, keys, seq_lens + 1, scfg.sampling)
            # in-graph step metrics: donation-safe, fixed treedef — the
            # monitor.Metrics contract (zero extra compilations)
            m = Metrics().record(
                active_slots=jnp.sum(active),
                context_tokens=jnp.sum(
                    jnp.where(active, seq_lens + 1, 0)))
            return cache, toks, m

        def verify(params, cache, fed_tokens, seq_lens, n_fed, active,
                   block_tables, keys):
            if use_mega:
                from apex_tpu.serve.megakernel import gpt_verify_step_fused

                cache, logits = gpt_verify_step_fused(
                    params, fed_tokens, seq_lens, n_fed, active, cache,
                    block_tables, cfg, kv_cfg)
            else:
                cache, logits = gpt_verify_step(
                    params, fed_tokens, seq_lens, n_fed, active, cache,
                    block_tables, cfg, kv_cfg, tp_axis=tp_axis,
                    use_pallas=self._use_pallas,
                    gather_layer=self._gather_layer)
            k1 = fed_tokens.shape[1]
            draw_pos = seq_lens[:, None] + 1 + jnp.arange(k1)[None, :]
            toks = sample(logits, keys, draw_pos, scfg.sampling)
            m = Metrics().record(
                active_slots=jnp.sum(active),
                context_tokens=jnp.sum(
                    jnp.where(active, seq_lens + 1, 0)))
            return cache, toks, m

        def cow(cache, src, dst):
            # local closure (not the module-level copy_block directly):
            # jax keys jit caches on function identity, and compile_counts
            # must report THIS engine's compiles only
            return copy_block(cache, src, dst)

        self._chunk_prefill = jax.jit(wrap(chunk_prefill),
                                      donate_argnums=(1,))
        self._decode = jax.jit(wrap(decode), donate_argnums=(1,))
        self._verify = (jax.jit(wrap(verify), donate_argnums=(1,))
                        if scfg.spec_k > 0 else None)
        # copy-on-write block copy (src/dst traced -> one compile, ever)
        self._cow = jax.jit(wrap(cow), donate_argnums=(0,))

    def _build_lora_programs(self, wrap) -> None:
        """The adapter-enabled program set: same jit sites, same keys,
        ONE compile each — the AdapterPool rides every call as a SECOND
        donated argument (argnum 2, next to the KV cache at 1) and is
        returned untouched (identity output aliasing: no copy, no leak —
        ``analyze.adapters`` pins it). Which adapters are resident or
        active is pure DATA (pool contents + the ``adapter_ids`` mirror),
        so loads/unloads/swaps never retrace."""
        cfg, kv_cfg, scfg = self.cfg, self.kv_cfg, self.serve_cfg

        tp_axis = self._tp_axis

        def chunk_prefill(params, cache, lora, tokens, start, n_valid,
                          block_row, key, aid):
            cache, logits = gpt_prefill_chunk(
                params, tokens, start, n_valid, cache, block_row, cfg,
                kv_cfg, tp_axis=tp_axis, use_pallas=self._use_pallas,
                adapters=lora, adapter_id=aid)
            tok = sample(logits[None], key[None],
                         jnp.reshape(start + n_valid, (1,)), scfg.sampling)
            return cache, lora, tok[0]

        def decode(params, cache, lora, last_tokens, seq_lens, active,
                   block_tables, keys, adapter_ids):
            cache, logits = gpt_decode_step(
                params, last_tokens, seq_lens, active, cache,
                block_tables, cfg, kv_cfg, tp_axis=tp_axis,
                use_pallas=self._use_pallas, adapters=lora,
                adapter_ids=adapter_ids)
            toks = sample(logits, keys, seq_lens + 1, scfg.sampling)
            m = Metrics().record(
                active_slots=jnp.sum(active),
                context_tokens=jnp.sum(
                    jnp.where(active, seq_lens + 1, 0)))
            return cache, lora, toks, m

        def verify(params, cache, lora, fed_tokens, seq_lens, n_fed,
                   active, block_tables, keys, adapter_ids):
            cache, logits = gpt_verify_step(
                params, fed_tokens, seq_lens, n_fed, active, cache,
                block_tables, cfg, kv_cfg, tp_axis=tp_axis,
                use_pallas=self._use_pallas, adapters=lora,
                adapter_ids=adapter_ids)
            k1 = fed_tokens.shape[1]
            draw_pos = seq_lens[:, None] + 1 + jnp.arange(k1)[None, :]
            toks = sample(logits, keys, draw_pos, scfg.sampling)
            m = Metrics().record(
                active_slots=jnp.sum(active),
                context_tokens=jnp.sum(
                    jnp.where(active, seq_lens + 1, 0)))
            return cache, lora, toks, m

        def cow(cache, src, dst):
            return copy_block(cache, src, dst)

        self._chunk_prefill = jax.jit(wrap(chunk_prefill),
                                      donate_argnums=(1, 2))
        self._decode = jax.jit(wrap(decode), donate_argnums=(1, 2))
        self._verify = (jax.jit(wrap(verify), donate_argnums=(1, 2))
                        if scfg.spec_k > 0 else None)
        self._cow = jax.jit(wrap(cow), donate_argnums=(0,))

    def programs(self) -> Dict[str, Optional[Callable]]:
        """The engine's jitted programs, keyed like :meth:`compile_counts`
        — hand this straight to ``analyze.recompile_guard`` to pin a
        workload's compile behavior in place::

            with recompile_guard(engine.programs(), budget=0):
                engine.run(requests)   # steady state: no new compiles
        """
        return {"chunk_prefill": self._chunk_prefill,
                "decode": self._decode,
                "verify": self._verify,
                "cow_copy": self._cow}

    def compile_counts(self) -> Dict[str, Optional[int]]:
        """Jit-cache sizes of the engine programs — the compile-count gate
        reads this (expected: exactly 1 chunked prefill + 1 decode, plus
        <= 1 verify per distinct spec-k shape and <= 1 CoW copy). One
        implementation: ``analyze.recompile.compile_counts``."""
        from apex_tpu.analyze.recompile import compile_counts

        return compile_counts(self.programs())

    # -- submission --------------------------------------------------------
    @property
    def buckets(self) -> Tuple[int, ...]:
        """COMPAT SHIM: the ladder old callers sized workloads by. The
        engine compiles no per-bucket programs anymore."""
        return tuple(sorted(self.serve_cfg.prefill_buckets
                            or default_bucket_ladder(self.max_context)))

    def bucket_for(self, prompt_len: int) -> int:
        """COMPAT SHIM: smallest compat-ladder bucket holding the prompt
        (no compilation consequence since chunked prefill)."""
        for b in self.buckets:
            if b >= prompt_len:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket "
            f"({self.buckets[-1]})")

    def submit(self, request: Request) -> None:
        p = len(request.tokens)
        if p < 1:
            raise ValueError(f"{request.uid}: empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError(f"{request.uid}: max_new_tokens must be >= 1")
        if p >= self.max_context:
            raise ValueError(
                f"{request.uid}: prompt ({p}) must leave room to generate "
                f"(max_context {self.max_context})")
        if request.adapter is not None and self.adapters is None:
            raise ValueError(
                f"{request.uid}: adapter {request.adapter!r} requested "
                f"but adapters are disabled (ServeConfig.lora_rank == 0)")
        t = self._now_ms()
        self._pending.append((request, t))
        if self._events is not None:
            self._events.emit("submitted", request.uid, t_ms=t,
                              prompt_tokens=p,
                              max_new_tokens=request.max_new_tokens)
            self._events.gauge("queue_depth", len(self._pending), t_ms=t)

    def _now_ms(self) -> float:
        """Ms on the engine's one monotonic clock (the EventLog's anchor
        when events are wired, so both artifacts share timestamps)."""
        if self._events is not None:
            return self._events.now_ms()
        return (time.perf_counter() - self._t_anchor) * 1e3

    # -- admission ---------------------------------------------------------
    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _total_tokens(self, request: Request) -> int:
        # cached tokens at retirement: prompt + all generated but the last
        # (never fed back); budget the full generation window, clamped
        return min(len(request.tokens) + request.max_new_tokens,
                   self.max_context)

    def _resolve_adapter(self, request: Request) -> Optional[int]:
        """Bind the head request to its adapter's pool slot (refcount
        acquired — released at retirement/eviction). None means the
        request was SHED (unknown adapter, reject hook wired): the head
        was popped, the admission loop continues. Without a hook the
        unknown adapter raises — the single-engine analogue of run()'s
        deadlock-loud pool_exhausted."""
        if request.adapter is None:
            return 0
        assert self.adapters is not None  # submit() refused otherwise
        aid = self.adapters.acquire(request.adapter)
        if aid is not None:
            return aid
        self._pending.popleft()
        self._rejected += 1
        info = {"reason": "unknown_adapter", "adapter": request.adapter,
                "resident": sorted(self.adapters.resident())}
        if self._on_reject is not None:
            self._on_reject(request, info)
            if self._events is not None:
                self._events.emit("shed", request.uid,
                                  reason="unknown_adapter",
                                  adapter=request.adapter)
            return None
        raise KeyError(
            f"{request.uid}: unknown adapter {request.adapter!r} "
            f"(resident: {info['resident']}) — load_adapter() it first "
            f"or wire on_reject to shed")

    def _try_admit(self) -> int:
        admitted = 0
        while self._pending:
            slot = self._free_slot()
            if slot is None:
                break
            request, t_submit = self._pending[0]
            aid = self._resolve_adapter(request)
            if aid is None:
                continue  # shed: head popped, try the next request
            n_blocks = self.kv_cfg.blocks_for_tokens(
                self._total_tokens(request))
            bs = self.kv_cfg.block_size
            hashes = (prefix_block_hashes(request.tokens, bs)
                      if self.serve_cfg.prefix_cache else [])
            # acquire the longest cached prefix FIRST (a ref pins those
            # blocks against the eviction alloc() may run next)
            hit = self.allocator.lookup(hashes)
            # FULL-prompt hit (p % bs == 0): the final prompt position
            # must be recomputed for its logits, and that write lands
            # inside the last matched block — the one genuinely divergent
            # write. Copy-on-write: one extra private block to copy the
            # shared content into; the sharers' block is never mutated
            # (bitwise-pinned by test).
            cow = bool(hit) and len(hit) * bs >= len(request.tokens)
            fresh = self.allocator.alloc(
                n_blocks - len(hit) + (1 if cow else 0))
            if fresh is None and cow:
                # pool too tight for the CoW copy: degrade to dropping the
                # last matched block and prefilling it into a fresh one
                self.allocator.free([hit[-1]])
                hit = hit[:-1]
                cow = False
                fresh = self.allocator.alloc(n_blocks - len(hit))
            if fresh is None:
                if hit:
                    self.allocator.free(hit)  # release the acquired refs
                if aid and request.adapter is not None:
                    # drop the adapter pin too — re-acquired on retry
                    self.adapters.release(request.adapter)
                break  # pool full: wait for a retirement to free blocks
            self._pending.popleft()
            self._admit(slot, request, hit, fresh, cow, hashes, t_submit,
                        aid)
            admitted += 1
        return admitted

    def _admit(self, slot: int, request: Request, hit: List[int],
               fresh: List[int], cow: bool, hashes: List[int],
               t_submit_ms: float, adapter_id: int = 0) -> None:
        p = len(request.tokens)
        bs = self.kv_cfg.block_size
        n_hit = len(hit)
        if cow:
            # fresh[0] is the private replacement for the last matched
            # block: copy the shared content on device, swap it into the
            # table, drop OUR ref on the shared source (sharers keep it)
            src, dst = hit[-1], fresh[0]
            self.cache = self._cow(self.cache, jnp.int32(src),
                                   jnp.int32(dst))
            self.allocator.free([src])
            blocks = hit[:-1] + [dst] + fresh[1:]
            self._cow_copies += 1
        else:
            blocks = hit + fresh
        hit_len = n_hit * bs
        cached = min(hit_len, p - 1)  # position p-1 always recomputed
        n_full = p // bs
        if self.serve_cfg.prefix_cache:
            self._prefix_blocks_needed += n_full
            self._prefix_blocks_hit += min(n_hit, n_full)
        self._prefill_tokens_saved += cached
        # modeled flops the cache saved: 2N matmul per skipped token plus
        # the causal attention term (the decode_flops_per_token model
        # summed over the skipped positions)
        self._prefill_flops_saved += (
            2.0 * self._n_params * cached
            + 4.0 * self.cfg.num_layers * self.cfg.hidden
            * (cached * (cached + 1)) / 2.0)
        t_adm = self._now_ms()
        queue_ms = t_adm - t_submit_ms
        if self._events is not None:
            self._events.emit("admitted", request.uid, t_ms=t_adm,
                              slot=slot, queue_ms=round(queue_ms, 3),
                              cached_tokens=cached)
            self._events.emit("prefill_start", request.uid, t_ms=t_adm,
                              slot=slot, prompt_tokens=p,
                              chunk=self.serve_cfg.prefill_chunk)
        row = np.zeros((self._blocks_per_slot,), np.int32)
        row[:len(blocks)] = blocks
        key = np.asarray(
            request_key(self._base_key, request.sampling_seed()), np.uint32)
        # blocks the tail prefill will fill: committed to the content map
        # as the chunk cursor passes their end (never before — a block is
        # addressable only once fully written); empty when the prefix
        # cache is off (no hashes computed)
        commits = [(int(row[j]), hashes[j], (j + 1) * bs)
                   for j in range(n_hit, n_full)] if hashes else []
        if cow:
            # the CoW copy is content-complete once position p-1 rewrites;
            # commit is a no-op while the shared source stays mapped but
            # re-registers the content if the source gets evicted first
            commits.append((int(blocks[n_hit - 1]), hashes[n_full - 1], p))
        state = _SlotState(request=request, blocks=blocks, generated=[],
                           history=[int(t) for t in request.tokens],
                           prompt_len=p, prefill_pos=cached,
                           cached_tokens=cached, pending_commits=commits,
                           t_submit_ms=t_submit_ms, queue_ms=queue_ms,
                           adapter_id=adapter_id)
        self._slots[slot] = state
        self._block_tables[slot] = row
        self._keys[slot] = key
        self._adapter_ids[slot] = adapter_id
        self._dirty("block_tables", "keys", "adapter_ids")
        self._prefill_queue.append(slot)

    # -- chunked prefill ---------------------------------------------------
    def _prefill_backlog_tokens(self) -> int:
        """Prompt tokens admitted but not yet chunk-prefilled (the
        chunked-prefill backlog depth gauge)."""
        return sum(s.prompt_len - s.prefill_pos
                   for s in self._slots
                   if s is not None and s.prefill_pos < s.prompt_len)

    def _run_prefill_chunk(self) -> bool:
        """One fixed-size chunk for the front of the prefill queue; on the
        prompt's final chunk, sample the first token and promote the slot
        to the decode grid."""
        if not self._prefill_queue:
            return False
        slot = self._prefill_queue[0]
        state = self._slots[slot]
        assert state is not None
        C = self.serve_cfg.prefill_chunk
        c = state.prefill_pos
        p = state.prompt_len
        n_valid = min(C, p - c)
        tokens = np.zeros((C,), np.int32)
        tokens[:n_valid] = np.asarray(
            state.request.tokens[c:c + n_valid], np.int32)
        with span("prefill"):
            if self._lora_pool is None:
                self.cache, tok = self._chunk_prefill(
                    self.params, self.cache, jnp.asarray(tokens),
                    jnp.int32(c), jnp.int32(n_valid),
                    self._dev("block_tables")[slot], self._dev("keys")[slot])
            else:
                self.cache, self._lora_pool, tok = self._chunk_prefill(
                    self.params, self.cache, self._lora_pool,
                    jnp.asarray(tokens), jnp.int32(c), jnp.int32(n_valid),
                    self._dev("block_tables")[slot], self._dev("keys")[slot],
                    self._dev("adapter_ids")[slot])
            state.prefill_pos = c + n_valid
            self._chunks_run += 1
            done = state.prefill_pos >= p
            if done:
                first = int(tok)  # fence: TTFT includes the round-trip
        # full blocks the cursor passed are now content-addressable
        while (state.pending_commits
               and state.pending_commits[0][2] <= state.prefill_pos):
            b, h, _ = state.pending_commits.pop(0)
            self.allocator.commit(b, h)
        if not done:
            return True
        self._prefill_queue.popleft()
        t_first = self._now_ms()
        ttft_ms = t_first - state.t_submit_ms
        if self._events is not None:
            self._events.emit("prefill_end", state.request.uid,
                              t_ms=t_first, slot=slot)
            self._events.emit("first_token", state.request.uid,
                              t_ms=t_first, slot=slot,
                              ttft_ms=round(ttft_ms, 3))
        if self._t_start is None:
            self._t_start = time.perf_counter()
        self._tokens_generated += 1
        state.generated.append(first)
        state.history.append(first)
        state.t_first_ms = t_first
        state.ttft_ms = ttft_ms
        state.chunk_start_ms = t_first
        state.chunk_done = 1
        self._seq_lens[slot] = p
        self._last_tokens[slot] = first
        self._active[slot] = True
        self._dirty("seq_lens", "last_tokens", "active")
        if self._events is not None:
            self._events.gauge("occupancy", self.occupancy(), t_ms=t_first)
        if self._should_retire(state, first):
            self._retire(slot)
        return True

    # -- retirement --------------------------------------------------------
    def _should_retire(self, state: _SlotState, tok: int) -> bool:
        if (self.serve_cfg.eos_id is not None
                and tok == self.serve_cfg.eos_id):
            return True
        if len(state.generated) >= state.request.max_new_tokens:
            return True
        # feeding the next token would write at position p + generated - 1,
        # which must stay inside the context window: continue while
        # p + generated <= max_context, retire beyond
        return (state.prompt_len + len(state.generated)
                > self.max_context)

    def _retire(self, slot: int) -> None:
        """Retirement FOLDS the request's timeline into the streaming
        histograms (and SLO tracker) and drops every per-uid entry — the
        O(slots) state contract. Streams are retained only when the
        engine was built with ``retain_streams=True`` (the default, for
        ``run()``'s return value) or handed to ``on_retire``. Freed
        blocks that carry a content address PARK in the allocator's
        evictable LRU — the prefix cache outlives its requests."""
        state = self._slots[slot]
        assert state is not None
        uid = state.request.uid
        now = self._now_ms()
        n_gen = len(state.generated)
        e2e_ms = now - state.t_submit_ms
        tpot_ms = ((now - state.t_first_ms) / (n_gen - 1)
                   if n_gen > 1 else None)
        if self._slo is not None:
            # the tracker folds into the SAME shared histograms
            self._slo.observe(ttft_ms=state.ttft_ms, tpot_ms=tpot_ms,
                              queue_ms=state.queue_ms, e2e_ms=e2e_ms)
        else:
            self.hists["ttft_ms"].add([state.ttft_ms])
            self.hists["queue_ms"].add([state.queue_ms])
            self.hists["e2e_ms"].add([e2e_ms])
            if tpot_ms is not None:
                self.hists["tpot_ms"].add([tpot_ms])
        if self._events is not None:
            if n_gen > state.chunk_done:  # final partial decode chunk
                self._events.emit(
                    "decode_chunk", uid, t_ms=now, slot=slot,
                    start_ms=round(state.chunk_start_ms, 3),
                    n_tokens=n_gen - state.chunk_done)
            self._events.emit(
                "retired", uid, t_ms=now, slot=slot, n_tokens=n_gen,
                ttft_ms=round(state.ttft_ms, 3), e2e_ms=round(e2e_ms, 3),
                tpot_ms=(round(tpot_ms, 3) if tpot_ms is not None
                         else None))
        # tier-4: engine-local latency attribution — the three local
        # components partition e2e exactly (queue + prefill + decode,
        # with prefill = ttft - queue and decode = e2e - ttft)
        self._attrib_hists["queue"].add([max(0.0, state.queue_ms)])
        self._attrib_hists["prefill"].add(
            [max(0.0, state.ttft_ms - state.queue_ms)])
        self._attrib_hists["decode"].add([max(0.0, e2e_ms - state.ttft_ms)])
        self._attrib_n += 1
        if self._meter is not None:
            # charge-once-at-retirement: a migrated request's source
            # engine EVICTS (never retires), so the destination's single
            # charge covers the whole request — Σ tenants == fleet totals
            held_s = max(0.0, now - (state.t_submit_ms
                                     + state.queue_ms)) / 1e3
            usage = {
                "flops": modeled_request_flops(
                    self._n_params, self.cfg.num_layers, self.cfg.hidden,
                    state.prompt_len, n_gen, state.cached_tokens),
                "kv_block_s": len(state.blocks) * held_s,
            }
            if state.adapter_id and state.request.adapter is not None:
                usage["adapter_s"] = held_s
            self._meter.charge(state.request.tenant,
                               worker=self._meter_worker, t_ms=now,
                               tokens=n_gen, requests=1, **usage)
        self._completed += 1
        if self._retain_streams:
            self._finished[uid] = state.generated
        if self._on_retire is not None:
            self._on_retire(uid, state.generated)
        self.allocator.free(state.blocks)
        if state.adapter_id and state.request.adapter is not None:
            self.adapters.release(state.request.adapter)
        self._release_slot(slot, now)

    def _release_slot(self, slot: int, now: float) -> None:
        """Clear one slot's grid state (the shared tail of retirement and
        eviction — block ownership is the caller's concern: retirement
        frees, eviction hands the blocks to the evicted record)."""
        self._slots[slot] = None
        self._active[slot] = False
        self._seq_lens[slot] = 0
        self._last_tokens[slot] = 0
        self._block_tables[slot] = 0
        self._adapter_ids[slot] = 0
        self._dirty("block_tables", "seq_lens", "last_tokens", "active",
                    "adapter_ids")
        if self._events is not None:
            self._events.gauge("occupancy", self.occupancy(), t_ms=now)

    # -- live-slot eviction (the migration primitive) ----------------------
    def evict_slot(self, uid: str) -> Dict[str, Any]:
        """Extract a LIVE decoding slot's full state and free the slot —
        the request is neither retired nor forgotten, it is *portable*:
        :meth:`restore_slot` (here or on another engine with the same
        model/kv config, after its pool blocks were shipped) resumes the
        stream bitwise where it stopped, because everything the decode
        program reads is in the record: the written-context length
        (``seq_len``), the next token to feed (``last_token``), the
        request (whose seed reproduces the sampling key), and the block
        ids holding the K/V.

        The record OWNS the listed blocks: they stay allocated (and
        refcounted — shared prefix-cache blocks are safe to read) until
        the caller either restores the slot locally or, after extracting
        their contents for the wire, releases them with
        ``engine.allocator.free(record["blocks"])``.

        Only fully-prefilled slots are evictable — a mid-prefill slot
        has no resumable decode state yet (its prompt is host-side;
        re-enqueue the request instead)."""
        for slot, state in enumerate(self._slots):
            if state is not None and state.request.uid == uid:
                break
        else:
            raise KeyError(f"no occupied slot holds request {uid!r}")
        if state.prefill_pos < state.prompt_len or not self._active[slot]:
            raise RuntimeError(
                f"{uid}: slot is mid-prefill — only decoding slots are "
                f"evictable (re-enqueue the request instead)")
        record: Dict[str, Any] = {
            "request": state.request,
            "blocks": list(state.blocks),
            "generated": list(state.generated),
            "history": list(state.history),
            "prompt_len": state.prompt_len,
            "cached_tokens": state.cached_tokens,
            "seq_len": int(self._seq_lens[slot]),
            "last_token": int(self._last_tokens[slot]),
            "t_submit_ms": state.t_submit_ms,
            "t_first_ms": state.t_first_ms,
            "queue_ms": state.queue_ms,
            "ttft_ms": state.ttft_ms,
            # the adapter BINDING travels with the KV blocks: the name
            # (per-worker slot ids don't survive migration) — the restore
            # target re-resolves it against ITS registry
            "adapter": state.request.adapter,
        }
        if state.adapter_id and state.request.adapter is not None:
            self.adapters.release(state.request.adapter)
        self._release_slot(slot, self._now_ms())
        return record

    def restore_slot(self, record: Dict[str, Any],
                     blocks: Optional[List[int]] = None) -> int:
        """Re-install an :meth:`evict_slot` record into a free slot.
        ``blocks=None`` reuses the record's own block ids (local evict +
        restore is bitwise a no-op — the pool never moved); a migration
        destination passes the freshly allocated ids its ``insert``
        program landed the transferred blocks in. Returns the slot
        index; raises when no slot is free (callers check capacity
        first — this is an installation primitive, not an admission
        queue)."""
        slot = self._free_slot()
        if slot is None:
            raise RuntimeError(
                f"{record['request'].uid}: no free slot to restore into")
        blocks = list(record["blocks"] if blocks is None else blocks)
        now = self._now_ms()
        aname = record.get("adapter")
        aid = 0
        if aname is not None:
            if self.adapters is None:
                raise RuntimeError(
                    f"{record['request'].uid}: record is bound to adapter "
                    f"{aname!r} but this engine has adapters disabled")
            aid = self.adapters.acquire(aname)
            if aid is None:
                raise RuntimeError(
                    f"{record['request'].uid}: adapter {aname!r} is not "
                    f"resident on the restore target — load_adapter() it "
                    f"before restoring (the cluster's adapter_load path)")
        state = _SlotState(
            request=record["request"], blocks=blocks,
            generated=list(record["generated"]),
            history=list(record["history"]),
            prompt_len=record["prompt_len"],
            prefill_pos=record["prompt_len"],
            cached_tokens=record.get("cached_tokens", 0),
            pending_commits=[],
            t_submit_ms=record["t_submit_ms"],
            t_first_ms=record["t_first_ms"],
            queue_ms=record["queue_ms"], ttft_ms=record["ttft_ms"],
            chunk_start_ms=now, chunk_done=len(record["generated"]),
            adapter_id=aid)
        self._slots[slot] = state
        row = np.zeros((self._blocks_per_slot,), np.int32)
        row[:len(blocks)] = blocks
        self._block_tables[slot] = row
        self._keys[slot] = np.asarray(
            request_key(self._base_key, record["request"].sampling_seed()),
            np.uint32)
        self._seq_lens[slot] = record["seq_len"]
        self._last_tokens[slot] = record["last_token"]
        self._active[slot] = True
        self._adapter_ids[slot] = aid
        self._dirty("block_tables", "keys", "seq_lens", "last_tokens",
                    "active", "adapter_ids")
        if self._t_start is None:
            self._t_start = time.perf_counter()
        if self._events is not None:
            self._events.gauge("occupancy", self.occupancy(), t_ms=now)
        return slot

    # -- adapter lifecycle -------------------------------------------------
    def load_adapter(self, name: str, weights: Dict[str, Any], *,
                     scale: float = 1.0) -> int:
        """Install (or refresh) a named LoRA adapter into the paged pool.
        Host-side eager writes into the donated pool leaves — loading an
        adapter never traces, so compile counts stay flat no matter how
        many tenants churn through. Under pool pressure the registry
        evicts the least-recently-used IDLE adapter (refcount 0); loading
        while every slot is pinned by a decoding request raises. Returns
        the pool slot the adapter landed in."""
        if self.adapters is None:
            raise RuntimeError(
                "adapters are disabled (ServeConfig.lora_rank == 0) — "
                "construct the engine with lora_rank > 0 to load adapters")
        t0 = time.perf_counter()
        slot = self.adapters.load(name)
        self._lora_pool = write_adapter(self._lora_pool, slot, weights,
                                        scale=scale)
        ms = (time.perf_counter() - t0) * 1e3
        self._adapter_load_ms_total += ms
        if self._meter is not None:
            # install time precedes any tenant binding — the _fleet
            # pseudo-tenant pays (a per-tenant amortization would guess)
            self._meter.charge("_fleet", worker=self._meter_worker,
                               adapter_load_ms=ms)
        if self._events is not None:
            self._events.emit("adapter_load", name, slot=slot,
                              load_ms=round(ms, 3))
        return slot

    def unload_adapter(self, name: str) -> None:
        """Drop a named adapter from the pool (must be idle — refcount 0).
        The pool slot's weights are left in place and overwritten by the
        next load; correctness never reads a free slot (per-slot
        adapter-id rows only ever point at resident adapters)."""
        if self.adapters is None:
            raise RuntimeError("adapters are disabled")
        self.adapters.unload(name)
        if self._events is not None:
            self._events.emit("adapter_unload", name)

    # -- speculative drafting ---------------------------------------------
    def _collect_drafts(self) -> Optional[Dict[int, List[int]]]:
        """Ask the drafter for up to spec_k tokens per active slot, capped
        so fed positions stay inside the slot's allocated blocks, the
        context window, and the remaining generation budget. None when no
        slot proposes (the step falls back to the plain decode program)."""
        if self.drafter is None:
            return None
        out: Dict[int, List[int]] = {}
        any_drafts = False
        for i, state in enumerate(self._slots):
            if state is None or not self._active[i]:
                continue
            s = int(self._seq_lens[i])
            remaining = state.request.max_new_tokens - len(state.generated)
            cap = min(
                self.serve_cfg.spec_k,
                remaining - 1,  # the last token is never fed back
                len(state.blocks) * self.kv_cfg.block_size - 1 - s,
                self.max_context - 1 - s,
            )
            if cap < 1:
                continue
            drafts = list(self.drafter.propose(state.history, cap))[:cap]
            if drafts:
                out[i] = [int(t) for t in drafts]
                any_drafts = True
        return out if any_drafts else None

    # -- stepping ----------------------------------------------------------
    def step(self) -> bool:
        """Admit what fits, run one prefill chunk if any prompt is mid-
        prefill, then advance every decode-ready slot — one token via the
        decode program, or up to spec_k+1 via the speculative verify
        program when the drafter proposed. Returns False when nothing
        happened (no admission, no prefill, no active slots). An
        admission-time shed (unknown adapter popped via ``on_reject``)
        counts as progress: the queue moved, even though no slot did —
        otherwise ``run()`` would misread the step as a pool stall."""
        shed0 = self._rejected
        admitted = self._try_admit()
        chunked = self._run_prefill_chunk()
        if not self._active.any():
            if self._sink is not None and chunked:
                self._sink.write(step=self._step_idx,
                                 phase="prefill_chunk",
                                 prefill_backlog_tokens=(
                                     self._prefill_backlog_tokens()))
            if chunked:
                self._step_idx += 1
            return admitted > 0 or chunked or self._rejected > shed0
        t0 = time.perf_counter()
        drafts = self._collect_drafts()
        with span("decode"):
            if drafts is None:
                self._decode_steps += 1
                if self._lora_pool is None:
                    self.cache, toks, metrics = self._decode(
                        self.params, self.cache,
                        self._dev("last_tokens"), self._dev("seq_lens"),
                        self._dev("active"), self._dev("block_tables"),
                        self._dev("keys"))
                else:
                    (self.cache, self._lora_pool, toks,
                     metrics) = self._decode(
                        self.params, self.cache, self._lora_pool,
                        self._dev("last_tokens"), self._dev("seq_lens"),
                        self._dev("active"), self._dev("block_tables"),
                        self._dev("keys"), self._dev("adapter_ids"))
            else:
                self._verify_steps += 1
                k1 = self.serve_cfg.spec_k + 1
                n = self.serve_cfg.num_slots
                fed = np.zeros((n, k1), np.int32)
                fed[:, 0] = self._last_tokens
                n_fed = np.where(self._active, 1, 0).astype(np.int32)
                for i, d in drafts.items():
                    fed[i, 1:1 + len(d)] = d
                    n_fed[i] = 1 + len(d)
                if self._lora_pool is None:
                    self.cache, toks, metrics = self._verify(
                        self.params, self.cache, jnp.asarray(fed),
                        self._dev("seq_lens"), jnp.asarray(n_fed),
                        self._dev("active"), self._dev("block_tables"),
                        self._dev("keys"))
                else:
                    (self.cache, self._lora_pool, toks,
                     metrics) = self._verify(
                        self.params, self.cache, self._lora_pool,
                        jnp.asarray(fed), self._dev("seq_lens"),
                        jnp.asarray(n_fed), self._dev("active"),
                        self._dev("block_tables"), self._dev("keys"),
                        self._dev("adapter_ids"))
            toks = np.asarray(toks)  # fence — the iteration-level sync
        dt = time.perf_counter() - t0
        self.hists["decode_step_ms"].add([dt * 1e3])
        if drafts is not None:
            # the verify A/B's own latency dimension — spec steps also
            # land in decode_step_ms (one engine iteration either way)
            self.hists["verify_step_ms"].add([dt * 1e3])
        now_ms = self._now_ms()
        active_lens = [int(s) + 1 for s, a
                       in zip(self._seq_lens, self._active) if a]
        # tokens FED through the program per active slot (the write/flops
        # unit: a verify step feeds 1 + len(drafts) per slot)
        fed_counts = [1 + len(drafts.get(i, [])) if drafts is not None
                      else 1
                      for i in range(len(self._slots)) if self._active[i]]
        n_active = len(active_lens)
        step_proposed = step_accepted = step_emitted = 0
        for i in range(len(self._slots)):
            if not self._active[i]:
                continue
            state = self._slots[i]
            if drafts is None:
                emitted = [int(toks[i])]
            else:
                d = drafts.get(i, [])
                step_proposed += len(d)
                a = 1
                while a <= len(d) and int(toks[i, a - 1]) == d[a - 1]:
                    a += 1
                emitted = [int(toks[i, j]) for j in range(a)]
                step_accepted += a - 1
            retired = False
            n_emit = 0
            for tok in emitted:
                state.generated.append(tok)
                state.history.append(tok)
                self._tokens_generated += 1
                n_emit += 1
                if self._should_retire(state, tok):
                    retired = True
                    break
            step_emitted += n_emit
            self._seq_lens[i] += n_emit
            self._last_tokens[i] = state.generated[-1]
            if (self._events is not None and not retired
                    and len(state.generated) - state.chunk_done
                    >= self._chunk_tokens):
                self._events.emit(
                    "decode_chunk", state.request.uid, t_ms=now_ms,
                    slot=i, start_ms=round(state.chunk_start_ms, 3),
                    n_tokens=len(state.generated) - state.chunk_done)
                state.chunk_start_ms = now_ms
                state.chunk_done = len(state.generated)
            if retired:
                self._retire(i)
        self._dirty("seq_lens", "last_tokens")
        self._spec_proposed += step_proposed
        self._spec_accepted += step_accepted
        self._step_idx += 1
        self._emit_metrics(metrics, dt, n_active, active_lens, fed_counts,
                           step_proposed, step_accepted, step_emitted)
        return True

    def _emit_metrics(self, metrics: Metrics, dt: float, n_active: int,
                      active_lens: List[int], fed_counts: List[int],
                      step_proposed: int, step_accepted: int,
                      step_emitted: int) -> None:
        if self._sink is None:
            return
        # a verify step feeds (writes K/V for, and gathers context per)
        # 1+len(drafts) tokens per slot and emits 1+accepted — the record
        # must not read 1/slot on exactly the steps speculation
        # accelerates
        flops = sum(f * decode_flops_per_token(
            self._n_params, self.cfg.num_layers, self.cfg.hidden, s)
            for s, f in zip(active_lens, fed_counts))
        fed_total = sum(fed_counts)
        read_lens = [s for s, f in zip(active_lens, fed_counts)
                     for _ in range(f)]  # one gather per FED row
        rec = {
            "phase": "decode",
            "step_ms": round(dt * 1e3, 3),
            "occupancy": n_active / self.serve_cfg.num_slots,
            "tokens_per_s": round(step_emitted / dt, 3) if dt else 0.0,
            "kv_read_bytes": kv_read_bytes(self.kv_cfg, read_lens),
            "kv_write_bytes": fed_total * kv_write_bytes_per_token(
                self.kv_cfg),
            "decode_flops_modeled": flops,
            # throughput-optimization telemetry (per-step + cumulative;
            # monitor.view aggregates these)
            "prefill_backlog_tokens": self._prefill_backlog_tokens(),
            "spec_proposed": step_proposed,
            "spec_accepted": step_accepted,
            "prefix_blocks_hit_total": self._prefix_blocks_hit,
            "prefix_blocks_needed_total": self._prefix_blocks_needed,
            "prefill_flops_saved_total": self._prefill_flops_saved,
        }
        if self._peak:
            rec["decode_mfu"] = (flops / dt) / self._peak if dt else 0.0
        self._sink.write(step=self._step_idx, metrics=metrics, **rec)

    # -- driving -----------------------------------------------------------
    def run(self, requests: Sequence[Request],
            max_steps: Optional[int] = None) -> Dict[str, List[int]]:
        """Serve ``requests`` to completion; returns uid -> generated
        tokens (the per-request streams, admission-order-invariant)."""
        for r in requests:
            self.submit(r)
        steps = 0
        while self._pending or self._active.any() or self._prefill_queue:
            if max_steps is not None and steps >= max_steps:
                break
            if not self.step():
                request = self._pending[0][0]
                state_blocks = self.kv_cfg.blocks_for_tokens(
                    self._total_tokens(request))
                if self._on_reject is not None:
                    # structured rejection instead of the deadlock-loud
                    # raise: drop the unservable head and keep serving —
                    # the caller (e.g. the cluster router) decides what a
                    # rejection means
                    self._pending.popleft()
                    self._rejected += 1
                    self._on_reject(request, {
                        "reason": "pool_exhausted",
                        "needed_blocks": state_blocks,
                        "free_blocks": self.allocator.free_count,
                        "pool_blocks": self.kv_cfg.num_blocks,
                    })
                    if self._events is not None:
                        self._events.emit("shed", request.uid,
                                          reason="pool_exhausted")
                    continue
                raise RuntimeError(
                    f"engine stalled: next request needs {state_blocks} "
                    f"blocks, pool has {self.allocator.free_count} free "
                    f"and no active slot will release more — the pool is "
                    f"too small for this request")
            steps += 1
        return dict(self._finished)

    # -- introspection / stats --------------------------------------------
    @property
    def finished(self) -> Dict[str, List[int]]:
        return dict(self._finished)

    @property
    def completed(self) -> int:
        """Requests retired so far (counts even when streams are not
        retained)."""
        return self._completed

    def per_request_state_count(self) -> int:
        """Per-request entries the engine is holding: retained streams +
        queued submissions + occupied slots. With ``retain_streams=False``
        this is O(slots + backlog) forever — the leak gate
        ``tests/test_serve.py`` pins after 10× slot-count requests."""
        return (len(self._finished) + len(self._pending)
                + sum(s is not None for s in self._slots))

    def stats(self) -> Dict[str, Any]:
        """One JSON-serializable telemetry snapshot: counts, latency
        quantiles (p50/p99 from the streaming histograms — bounded
        relative error, O(1) memory), full histogram dumps, the
        prefix-cache / chunked-prefill / speculative-decoding counters,
        and the goodput-under-SLO report when an ``SloSpec`` was given."""
        out: Dict[str, Any] = {
            "completed": self._completed,
            "rejected": self._rejected,
            "steps": self._step_idx,
            "generated_tokens": self._tokens_generated,
            "queue_depth": len(self._pending),
            "occupancy": self.occupancy(),
        }
        tput = self.throughput()
        out["tokens_per_s"] = round(tput, 3) if tput else None
        for name in _HIST_NAMES:
            h = self.hists[name]
            if h.total == 0:
                continue
            out[f"{name}_p50"] = round(h.quantile(0.5), 3)
            out[f"{name}_p99"] = round(h.quantile(0.99), 3)
        # tier-4 forensics: per-component latency attribution (flat keys,
        # lower-better under regress) + the plane's own coverage
        for c, h in self._attrib_hists.items():
            if h.total == 0:
                continue
            out[f"{c}_component_ms_p50"] = round(h.quantile(0.5), 3)
            out[f"{c}_component_ms_p99"] = round(h.quantile(0.99), 3)
        if self._completed:
            out["attrib_coverage"] = round(
                self._attrib_n / self._completed, 4)
        if self._meter is not None:
            m = self._meter.stats(completed=self._completed)
            out["meter"] = m
            out["cost_per_token"] = m["cost_per_token"]
            out["cost_per_request"] = m["cost_per_request"]
            out["meter_coverage"] = m["meter_coverage"]
        out["prefix_cache"] = {
            "enabled": self.serve_cfg.prefix_cache,
            "blocks_hit": self._prefix_blocks_hit,
            "blocks_needed": self._prefix_blocks_needed,
            "hit_rate": round(
                self._prefix_blocks_hit / self._prefix_blocks_needed, 4)
            if self._prefix_blocks_needed else None,
            "tokens_saved": self._prefill_tokens_saved,
            "prefill_flops_saved": self._prefill_flops_saved,
            "cow_copies": self._cow_copies,
            "cached_blocks": self.allocator.cached_count,
            "evictions": self.allocator.blocks_evicted_total,
        }
        out["megakernel"] = self._megakernel
        out["decode_kernel"] = self.decode_kernel
        out["verify_kernel"] = self.verify_kernel
        # the sub-8-bit KV headline fields (watcher-gated: kv_bits and
        # the budget are lower-better, contexts_max higher-better)
        out["kv_bits"] = (self.kv_cfg.bits if self.kv_cfg.quantized
                          else 8 * jnp.dtype(self.kv_cfg.dtype).itemsize)
        out["kv_cache_bytes"] = kv_cache_bytes(self.kv_cfg)
        out["contexts_max"] = (self.kv_cfg.tokens_capacity
                               // self.max_context)
        out["prefill"] = {
            "chunk": self.serve_cfg.prefill_chunk,
            "chunks_run": self._chunks_run,
            "backlog_tokens": self._prefill_backlog_tokens(),
        }
        out["speculative"] = {
            "k": self.serve_cfg.spec_k,
            "proposed": self._spec_proposed,
            "accepted": self._spec_accepted,
            "acceptance_rate": round(
                self._spec_accepted / self._spec_proposed, 4)
            if self._spec_proposed else None,
            "verify_steps": self._verify_steps,
            "decode_steps": self._decode_steps,
        }
        if self.adapters is not None:
            a = self.adapters
            lookups = a.hits_total + a.misses_total
            out["adapters"] = {
                "rank": self.serve_cfg.lora_rank,
                "max_adapters": self.serve_cfg.max_adapters,
                "resident": a.resident_count,
                "pool_bytes": adapter_pool_bytes(
                    self.cfg, self.serve_cfg.lora_rank,
                    self.serve_cfg.max_adapters),
                "hits": a.hits_total,
                "misses": a.misses_total,
                "loads": a.loads_total,
                "unloads": a.unloads_total,
                "evictions": a.evictions_total,
            }
            # flat watcher-gated fields: hit rate higher-better,
            # load latency and eviction churn lower-better
            out["adapter_hit_rate"] = (
                round(a.hits_total / lookups, 4) if lookups else None)
            out["adapter_evictions"] = a.evictions_total
            out["adapter_load_ms"] = round(self._adapter_load_ms_total, 3)
        # flat aliases for regression gating (monitor.regress flattens
        # dotted keys; these are the two headline rates)
        out["prefix_hit_rate"] = out["prefix_cache"]["hit_rate"]
        out["spec_acceptance_rate"] = out["speculative"]["acceptance_rate"]
        # model-parallel serving fields (serve.sharded engines only):
        # plan (the residency story), hbm_model_bytes (unsharded "does
        # it fit one chip" numerator), weight_gather_ms /
        # pp_bubble_fraction (strategy-specific, lower-better under
        # monitor.regress)
        if self.plan_stats is not None:
            out.update(self.plan_stats())
        out["hists"] = {k: v.to_dict() for k, v in self.hists.items()}
        if self._slo is not None:
            out["slo_report"] = self._slo.report()
        return out

    # -- fleet exposition (monitor tier 3) --------------------------------
    def collect_registry(self, reg, worker: str = "engine",
                         t_ms: Optional[float] = None,
                         include_hists: bool = False) -> None:
        """Populate a :class:`~apex_tpu.monitor.registry.MetricsRegistry`
        with this engine's live series, labeled ``worker=``. Counters
        are cumulative-at-scrape (the Prometheus pull model: the fleet
        view sums across WORKERS, never across time); ``include_hists``
        additionally snapshots the latency histograms (skipped on the
        per-tick scrape cadence — quantile merges belong to stats())."""
        if t_ms is None:
            t_ms = self._now_ms()
        L = {"worker": worker}
        reg.gauge("worker_up", 1.0, t_ms=t_ms, **L)
        reg.counter("requests_completed_total", self._completed, **L)
        reg.counter("requests_rejected_total", self._rejected, **L)
        reg.counter("tokens_generated_total", self._tokens_generated, **L)
        reg.counter("decode_steps_total",
                    self._decode_steps + self._verify_steps, **L)
        reg.gauge("occupancy", self.occupancy(), t_ms=t_ms, **L)
        reg.gauge("queue_depth", float(len(self._pending)), t_ms=t_ms, **L)
        reg.gauge("backlog_tokens", float(self._prefill_backlog_tokens()),
                  t_ms=t_ms, **L)
        if self._slo is not None:
            reg.counter("slo_good_total", self._slo.good, **L)
        if self.adapters is not None:
            reg.gauge("adapters_resident", float(
                self.adapters.resident_count), t_ms=t_ms, **L)
            reg.counter("adapter_hits_total", self.adapters.hits_total, **L)
            reg.counter("adapter_misses_total",
                        self.adapters.misses_total, **L)
            reg.counter("adapter_loads_total",
                        self.adapters.loads_total, **L)
            reg.counter("adapter_evictions_total",
                        self.adapters.evictions_total, **L)
        if include_hists:
            for name, h in self.hists.items():
                reg.set_histogram(name, h, **L)

    def scrape(self, worker: str = "engine",
               t_ms: Optional[float] = None,
               include_hists: bool = False) -> Dict[str, Any]:
        """One :class:`~apex_tpu.monitor.registry.MetricsRegistry`
        snapshot of this engine (what a ``FleetScraper`` target
        returns; ``MetricsRegistry.expose_text`` of the same registry
        is the Prometheus text endpoint)."""
        from apex_tpu.monitor.registry import MetricsRegistry

        reg = MetricsRegistry()
        if t_ms is None:
            t_ms = self._now_ms()
        self.collect_registry(reg, worker=worker, t_ms=t_ms,
                              include_hists=include_hists)
        return reg.snapshot(t_ms)

    @property
    def active(self) -> bool:
        """Whether the engine still has work: a slot mid-generation or
        mid-prefill, or a queued submission (the drive-loop condition
        loadgen polls)."""
        return (bool(self._active.any()) or bool(self._pending)
                or bool(self._prefill_queue))

    def occupancy(self) -> float:
        """Occupied slots (decoding or mid-prefill) / total slots."""
        return (sum(s is not None for s in self._slots)
                / self.serve_cfg.num_slots)

    def throughput(self) -> Optional[float]:
        """Generated tokens per second since the first token."""
        if self._t_start is None:
            return None
        dt = time.perf_counter() - self._t_start
        return self._tokens_generated / dt if dt > 0 else None

    def kv_budget_bytes(self) -> int:
        return kv_cache_bytes(self.kv_cfg)

    # -- checkpoint integration -------------------------------------------
    @classmethod
    def from_checkpoint(cls, directory: str, template_params: Pytree, cfg,
                        serve_cfg: Optional[ServeConfig] = None,
                        **kwargs) -> "InferenceEngine":
        """Build an engine from the newest VALID checkpoint under
        ``directory`` (``resilience.CheckpointManager.latest_valid`` —
        torn/corrupt saves are skipped, a wrong-revision manifest refuses
        to bind). ``template_params`` supplies the pytree structure (e.g.
        ``init_gpt_params`` output)."""
        from apex_tpu.resilience.checkpoint import CheckpointManager

        mgr = CheckpointManager(directory)
        params, step = mgr.restore(template_params)
        eng = cls(params, cfg, serve_cfg, **kwargs)
        eng.checkpoint_step = step
        return eng


def decode_flops_per_token(n_params: int, num_layers: int, hidden: int,
                           context: int) -> float:
    """Modeled forward flops to decode ONE token at the given context:
    ``2N`` matmul flops plus paged attention ``4·L·hidden·context`` (QKᵀ
    and PV against the cached context). The serving analogue of
    ``monitor.report.gpt_analytic_flops_per_token`` (which counts fwd+bwd
    at 6N) — bench_serve divides by this so its MFU column is honest about
    being a model."""
    return float(2 * n_params + 4 * num_layers * hidden * context)

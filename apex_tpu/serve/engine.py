"""Iteration-level continuous-batching inference engine.

The TPU-v3-pod MLPerf lesson (arXiv 1909.09756) applied to serving:
throughput at scale is slot occupancy — a static batch drains to its
longest member while every other chip's slot idles. This engine batches at
**iteration granularity** (Orca/vLLM's scheduling, rebuilt for jitted JAX
programs): a fixed grid of decode slots advances one token per step, and
between steps finished requests retire and new ones are admitted into the
freed slots. Nothing retraces:

* **bounded compilation** — prompts are padded to a fixed **bucket
  ladder**, so the engine compiles at most ``len(buckets)`` prefill
  programs plus EXACTLY ONE decode program for its whole lifetime (the
  compile-count gate in ``tests/test_serve.py`` pins it). The MPK argument
  (arXiv 2512.22219) in scheduler form: decode is latency-bound, so the
  whole step — embed, every layer, paged attention, sampling — is one
  compiled program, one dispatch.
* **donation-safe state** — the paged KV pools (``serve.kv_cache``) are
  donated through every prefill/decode call; slot bookkeeping
  (block tables, lengths, last tokens, keys) stays host-side numpy, cheap
  to re-upload and trivially correct across admissions.
* **request-order invariance** — greedy streams are bitwise equal to
  single-request decode of each prompt, and sampled streams equal under
  the same key, because per-slot computation is row-independent and
  sampling keys are request-intrinsic (``serve.sampling``).

Weights arrive through ``resilience.CheckpointManager.latest_valid()``
(:meth:`InferenceEngine.from_checkpoint`) — a serving replica points at
the training job's checkpoint directory and refuses torn/corrupt saves.
Telemetry rides the PR-2 ``monitor`` pipeline: an in-graph ``Metrics``
pytree out of the decode program plus host-side step records (tokens/s,
TTFT, occupancy, modeled decode flops/MFU, KV bytes from
``serve.kv_cache``'s accounting) into a ``JsonlSink``.

Monitor **tier 2** (request-level attribution, constant memory): every
request runs a lifecycle timeline — ``submitted → admitted →
prefill_start/end → first_token → decode_chunk* → retired`` on one
monotonic clock through an optional ``monitor.EventLog`` (JSONL + Chrome
trace via ``monitor.chrome_trace``, one Perfetto track per slot and per
request) — and retirement FOLDS the request's latencies (TTFT, mean
per-output-token, queue wait, end-to-end) into streaming
``monitor.Histogram``\\ s plus an optional ``monitor.SloTracker``, then
drops every per-uid entry. Engine state stays O(slots + backlog) across
millions of requests when ``retain_streams=False`` (per-request token
streams go to the ``on_retire`` callback instead of an ever-growing
dict); :meth:`InferenceEngine.stats` returns the histograms, latency
quantiles and goodput-under-SLO report as one JSON-serializable dict.
"""

from __future__ import annotations

import collections
import dataclasses
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from apex_tpu.monitor.events import EventLog
from apex_tpu.monitor.hist import DEFAULT_LATENCY_SPEC, HistSpec, Histogram
from apex_tpu.monitor.metrics import Metrics
from apex_tpu.monitor.slo import SloSpec, SloTracker
from apex_tpu.monitor.trace import span
from apex_tpu.serve.decode import gpt_decode_step, gpt_prefill
from apex_tpu.serve.kv_cache import (
    BlockAllocator,
    KVCacheConfig,
    init_kv_cache,
    kv_cache_bytes,
    kv_read_bytes,
    kv_write_bytes_per_token,
)
from apex_tpu.serve.sampling import SamplingConfig, request_key, sample

Pytree = Any


def default_bucket_ladder(max_context: int, start: int = 16
                          ) -> Tuple[int, ...]:
    """Powers-of-two prompt buckets up to ``max_context`` — each prompt
    compiles against the smallest bucket that holds it, so total prefill
    compilations are bounded by ``log2`` of the context length."""
    out = []
    b = start
    while b < max_context:
        out.append(b)
        b *= 2
    out.append(max_context)
    return tuple(out)


@dataclasses.dataclass
class Request:
    """One generation request. ``seed`` feeds the request's sampling key
    (default: crc32 of the uid — stable across runs and admission orders);
    irrelevant under greedy decoding."""

    uid: str
    tokens: Sequence[int]
    max_new_tokens: int = 64
    seed: Optional[int] = None

    def sampling_seed(self) -> int:
        if self.seed is not None:
            return int(self.seed)
        return zlib.crc32(self.uid.encode())


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine shape knobs (all static — they pick the compiled programs)."""

    num_slots: int = 4
    block_size: int = 16
    # total pool blocks; default = num_slots * blocks-per-max-context (no
    # oversubscription). Smaller pools admit fewer concurrent requests —
    # admission simply waits for frees, it never preempts.
    num_blocks: Optional[int] = None
    # prompt-length compile buckets; default: powers of two to max_context
    prefill_buckets: Optional[Tuple[int, ...]] = None
    max_context: Optional[int] = None  # default: model cfg.max_seq
    eos_id: Optional[int] = None
    kv_quant: str = "none"  # "none" | "int8" (comm.quantize codec)
    sampling: SamplingConfig = dataclasses.field(
        default_factory=SamplingConfig)

    def validate(self) -> None:
        if self.num_slots <= 0:
            raise ValueError("num_slots must be positive")
        if self.block_size <= 0:
            raise ValueError("block_size must be positive")
        if self.num_blocks is not None and self.num_blocks <= 0:
            raise ValueError("num_blocks must be positive when given")
        if self.max_context is not None and self.max_context <= 0:
            raise ValueError("max_context must be positive when given")
        if self.kv_quant not in ("none", "int8"):
            raise ValueError(f"kv_quant must be 'none' or 'int8', "
                             f"got {self.kv_quant!r}")
        self.sampling.validate()


# the engine's latency dimensions; each gets a streaming Histogram
_HIST_NAMES = ("ttft_ms", "tpot_ms", "queue_ms", "e2e_ms",
               "decode_step_ms")


@dataclasses.dataclass
class _SlotState:
    request: Request
    blocks: List[int]
    generated: List[int]
    # request timeline, ms on the engine's one monotonic clock
    t_submit_ms: float
    t_first_ms: float
    queue_ms: float
    ttft_ms: float
    chunk_start_ms: float   # start of the decode chunk being accumulated
    chunk_done: int         # tokens already covered by emitted chunks


class InferenceEngine:
    """Continuous-batching engine over one parameter pytree.

    Tensor parallelism: pass ``tp_axis``/``tp_size`` AND a ``transform``
    that shard_maps the prefill/decode python callables over that axis
    (params TP-sharded by ``gpt_param_specs``-style specs, everything else
    replicated) — the programs then route through the
    ``tensor_parallel`` layers with vocab-gathered logits, and the KV
    pools hold the ``num_heads / tp_size`` LOCAL heads. The default
    (``tp_axis=None``, identity transform) drives the single-device
    programs — the stock-jax path the acceptance tests pin.

    ``sink``: an ``apex_tpu.monitor.JsonlSink`` (or None) receiving one
    record per engine step. ``peak_flops_per_s``: chip peak for the
    modeled decode-MFU column (omitted -> mfu not reported).

    Tier-2 telemetry: ``events`` (a ``monitor.EventLog``) records every
    request's lifecycle; ``slo`` (a ``monitor.SloSpec``) turns on
    goodput/violation accounting; ``hist_spec`` overrides the latency
    bucket ladder; ``chunk_tokens`` sets the decode-chunk span
    granularity. ``retain_streams=False`` keeps per-request state
    O(slots): retirement hands the stream to ``on_retire(uid, tokens)``
    (or drops it) instead of growing the ``finished`` dict forever.
    """

    def __init__(
        self,
        params: Pytree,
        cfg,  # transformer.testing.GPTConfig
        serve_cfg: Optional[ServeConfig] = None,
        *,
        base_key=None,
        sink=None,
        peak_flops_per_s: Optional[float] = None,
        transform: Optional[Callable[[Callable], Callable]] = None,
        tp_axis: Optional[str] = None,
        tp_size: int = 1,
        use_pallas: Optional[bool] = None,
        events: Optional[EventLog] = None,
        slo: Optional[SloSpec] = None,
        hist_spec: Optional[HistSpec] = None,
        retain_streams: bool = True,
        on_retire: Optional[Callable[[str, List[int]], None]] = None,
        chunk_tokens: int = 16,
    ):
        scfg = serve_cfg or ServeConfig()
        scfg.validate()
        if cfg.num_experts:
            raise NotImplementedError("serve does not support MoE yet")
        if (tp_axis is None) != (tp_size == 1):
            raise ValueError("pass tp_axis together with tp_size > 1 "
                             "(and a shard_map transform)")
        if cfg.num_heads % tp_size:
            raise ValueError(f"num_heads ({cfg.num_heads}) not divisible "
                             f"by tp_size ({tp_size})")
        self.params = params
        self.cfg = cfg
        self.serve_cfg = scfg
        if scfg.max_context is not None and scfg.max_context > cfg.max_seq:
            raise ValueError(
                f"max_context ({scfg.max_context}) exceeds the model's "
                f"max_seq ({cfg.max_seq})")
        self.max_context = scfg.max_context or cfg.max_seq
        bs = scfg.block_size
        self._blocks_per_slot = -(-self.max_context // bs)
        num_blocks = (scfg.num_blocks if scfg.num_blocks is not None
                      else scfg.num_slots * self._blocks_per_slot)
        self._tp_axis = tp_axis
        self.kv_cfg = KVCacheConfig(
            num_layers=cfg.num_layers, num_heads=cfg.num_heads // tp_size,
            head_dim=cfg.head_dim, num_blocks=num_blocks, block_size=bs,
            dtype=cfg.dtype, quantized=scfg.kv_quant == "int8")
        self.buckets = tuple(sorted(
            scfg.prefill_buckets or default_bucket_ladder(self.max_context)))
        if self.buckets[-1] < self.max_context:
            raise ValueError(
                f"largest bucket ({self.buckets[-1]}) below max_context "
                f"({self.max_context}) — long prompts would be unservable")
        self.allocator = BlockAllocator(num_blocks)
        self.cache = init_kv_cache(self.kv_cfg)
        n = scfg.num_slots
        self._block_tables = np.zeros((n, self._blocks_per_slot), np.int32)
        self._seq_lens = np.zeros((n,), np.int32)
        self._last_tokens = np.zeros((n,), np.int32)
        self._active = np.zeros((n,), bool)
        self._keys = np.zeros((n, 2), np.uint32)
        self._slots: List[Optional[_SlotState]] = [None] * n
        self._pending: collections.deque = collections.deque()
        self._finished: Dict[str, List[int]] = {}
        self._base_key = (base_key if base_key is not None
                          else jax.random.PRNGKey(0))
        self._sink = sink
        self._peak = peak_flops_per_s
        self._step_idx = 0
        self._tokens_generated = 0
        self._t_start: Optional[float] = None
        # tier-2 telemetry: one monotonic clock (the EventLog's when
        # given, so event timestamps and latency folds agree), streaming
        # histograms, optional SLO accounting — all O(1) per request
        self._events = events
        self._t_anchor = time.perf_counter()
        if chunk_tokens < 1:
            raise ValueError(
                f"chunk_tokens must be >= 1, got {chunk_tokens}")
        self._chunk_tokens = int(chunk_tokens)
        hspec = hist_spec or DEFAULT_LATENCY_SPEC
        self.hists: Dict[str, Histogram] = {
            name: Histogram(hspec) for name in _HIST_NAMES}
        # the tracker SHARES the engine's histograms (decode_step_ms is
        # engine-only): one fold per retirement, one source of truth for
        # both the stats() quantiles and the slo_report
        self._slo = (SloTracker(slo, hists={
            d: self.hists[d]
            for d in ("ttft_ms", "tpot_ms", "queue_ms", "e2e_ms")})
            if slo is not None else None)
        self._retain_streams = retain_streams
        self._on_retire = on_retire
        self._completed = 0
        self._n_params = sum(
            x.size for x in jax.tree_util.tree_leaves(params))
        wrap = transform if transform is not None else (lambda f: f)
        self._use_pallas = use_pallas
        self._build_programs(wrap)

    # -- program construction (the ONLY jit sites) -------------------------
    def _build_programs(self, wrap) -> None:
        cfg, kv_cfg, scfg = self.cfg, self.kv_cfg, self.serve_cfg

        tp_axis = self._tp_axis

        def prefill(params, cache, tokens, prompt_len, block_row, key):
            cache, logits = gpt_prefill(params, tokens, prompt_len, cache,
                                        block_row, cfg, kv_cfg,
                                        tp_axis=tp_axis)
            tok = sample(logits[None], key[None],
                         jnp.stack([prompt_len]), scfg.sampling)
            return cache, tok[0]

        def decode(params, cache, last_tokens, seq_lens, active,
                   block_tables, keys):
            cache, logits = gpt_decode_step(
                params, last_tokens, seq_lens, active, cache, block_tables,
                cfg, kv_cfg, tp_axis=tp_axis, use_pallas=self._use_pallas)
            toks = sample(logits, keys, seq_lens + 1, scfg.sampling)
            # in-graph step metrics: donation-safe, fixed treedef — the
            # monitor.Metrics contract (zero extra compilations)
            m = Metrics().record(
                active_slots=jnp.sum(active),
                context_tokens=jnp.sum(
                    jnp.where(active, seq_lens + 1, 0)))
            return cache, toks, m

        self._prefill = jax.jit(wrap(prefill), donate_argnums=(1,))
        self._decode = jax.jit(wrap(decode), donate_argnums=(1,))

    def compile_counts(self) -> Dict[str, Optional[int]]:
        """Jit-cache sizes of the two programs — the compile-count gate
        reads this (expected: <= len(buckets) prefills + 1 decode)."""
        def n(f):
            fn = getattr(f, "_cache_size", None)
            return fn() if callable(fn) else None

        return {"prefill": n(self._prefill), "decode": n(self._decode)}

    # -- submission --------------------------------------------------------
    def bucket_for(self, prompt_len: int) -> int:
        for b in self.buckets:
            if b >= prompt_len:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest bucket "
            f"({self.buckets[-1]})")

    def submit(self, request: Request) -> None:
        p = len(request.tokens)
        if p < 1:
            raise ValueError(f"{request.uid}: empty prompt")
        if request.max_new_tokens < 1:
            raise ValueError(f"{request.uid}: max_new_tokens must be >= 1")
        if p >= self.max_context:
            raise ValueError(
                f"{request.uid}: prompt ({p}) must leave room to generate "
                f"(max_context {self.max_context})")
        self.bucket_for(p)  # unservable prompts fail at submit, not admit
        t = self._now_ms()
        self._pending.append((request, t))
        if self._events is not None:
            self._events.emit("submitted", request.uid, t_ms=t,
                              prompt_tokens=p,
                              max_new_tokens=request.max_new_tokens)
            self._events.gauge("queue_depth", len(self._pending), t_ms=t)

    def _now_ms(self) -> float:
        """Ms on the engine's one monotonic clock (the EventLog's anchor
        when events are wired, so both artifacts share timestamps)."""
        if self._events is not None:
            return self._events.now_ms()
        return (time.perf_counter() - self._t_anchor) * 1e3

    # -- admission ---------------------------------------------------------
    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s is None:
                return i
        return None

    def _total_tokens(self, request: Request) -> int:
        # cached tokens at retirement: prompt + all generated but the last
        # (never fed back); budget the full generation window, clamped
        return min(len(request.tokens) + request.max_new_tokens,
                   self.max_context)

    def _try_admit(self) -> int:
        admitted = 0
        while self._pending:
            slot = self._free_slot()
            if slot is None:
                break
            request, t_submit = self._pending[0]
            n_blocks = self.kv_cfg.blocks_for_tokens(
                self._total_tokens(request))
            blocks = self.allocator.alloc(n_blocks)
            if blocks is None:
                break  # pool full: wait for a retirement to free blocks
            self._pending.popleft()
            self._admit(slot, request, blocks, t_submit)
            admitted += 1
        return admitted

    def _admit(self, slot: int, request: Request, blocks: List[int],
               t_submit_ms: float) -> None:
        p = len(request.tokens)
        bucket = self.bucket_for(p)
        t_adm = self._now_ms()
        queue_ms = t_adm - t_submit_ms
        if self._events is not None:
            self._events.emit("admitted", request.uid, t_ms=t_adm,
                              slot=slot, queue_ms=round(queue_ms, 3))
            self._events.emit("prefill_start", request.uid, t_ms=t_adm,
                              slot=slot, bucket=bucket, prompt_tokens=p)
        row = np.zeros((self._blocks_per_slot,), np.int32)
        row[:len(blocks)] = blocks
        tokens = np.zeros((bucket,), np.int32)
        tokens[:p] = np.asarray(request.tokens, np.int32)
        key = np.asarray(
            request_key(self._base_key, request.sampling_seed()), np.uint32)
        with span("prefill"):
            self.cache, first = self._prefill(
                self.params, self.cache, jnp.asarray(tokens),
                jnp.int32(p), jnp.asarray(row), jnp.asarray(key))
            first = int(first)  # fence: TTFT includes the device round-trip
        t_first = self._now_ms()
        ttft_ms = t_first - t_submit_ms
        if self._events is not None:
            self._events.emit("prefill_end", request.uid, t_ms=t_first,
                              slot=slot)
            self._events.emit("first_token", request.uid, t_ms=t_first,
                              slot=slot, ttft_ms=round(ttft_ms, 3))
        if self._t_start is None:
            self._t_start = time.perf_counter()
        self._tokens_generated += 1
        state = _SlotState(request=request, blocks=blocks,
                           generated=[first], t_submit_ms=t_submit_ms,
                           t_first_ms=t_first, queue_ms=queue_ms,
                           ttft_ms=ttft_ms, chunk_start_ms=t_first,
                           chunk_done=1)
        self._slots[slot] = state
        self._block_tables[slot] = row
        self._seq_lens[slot] = p
        self._last_tokens[slot] = first
        self._keys[slot] = key
        self._active[slot] = True
        if self._events is not None:
            self._events.gauge("occupancy", self.occupancy(), t_ms=t_first)
        if self._should_retire(state, first):
            self._retire(slot)

    # -- retirement --------------------------------------------------------
    def _should_retire(self, state: _SlotState, tok: int) -> bool:
        if (self.serve_cfg.eos_id is not None
                and tok == self.serve_cfg.eos_id):
            return True
        if len(state.generated) >= state.request.max_new_tokens:
            return True
        # feeding the next token would write at position p + generated - 1,
        # which must stay inside the context window: continue while
        # p + generated <= max_context, retire beyond
        return (len(state.request.tokens) + len(state.generated)
                > self.max_context)

    def _retire(self, slot: int) -> None:
        """Retirement FOLDS the request's timeline into the streaming
        histograms (and SLO tracker) and drops every per-uid entry — the
        O(slots) state contract. Streams are retained only when the
        engine was built with ``retain_streams=True`` (the default, for
        ``run()``'s return value) or handed to ``on_retire``."""
        state = self._slots[slot]
        assert state is not None
        uid = state.request.uid
        now = self._now_ms()
        n_gen = len(state.generated)
        e2e_ms = now - state.t_submit_ms
        tpot_ms = ((now - state.t_first_ms) / (n_gen - 1)
                   if n_gen > 1 else None)
        if self._slo is not None:
            # the tracker folds into the SAME shared histograms
            self._slo.observe(ttft_ms=state.ttft_ms, tpot_ms=tpot_ms,
                              queue_ms=state.queue_ms, e2e_ms=e2e_ms)
        else:
            self.hists["ttft_ms"].add([state.ttft_ms])
            self.hists["queue_ms"].add([state.queue_ms])
            self.hists["e2e_ms"].add([e2e_ms])
            if tpot_ms is not None:
                self.hists["tpot_ms"].add([tpot_ms])
        if self._events is not None:
            if n_gen > state.chunk_done:  # final partial decode chunk
                self._events.emit(
                    "decode_chunk", uid, t_ms=now, slot=slot,
                    start_ms=round(state.chunk_start_ms, 3),
                    n_tokens=n_gen - state.chunk_done)
            self._events.emit(
                "retired", uid, t_ms=now, slot=slot, n_tokens=n_gen,
                ttft_ms=round(state.ttft_ms, 3), e2e_ms=round(e2e_ms, 3),
                tpot_ms=(round(tpot_ms, 3) if tpot_ms is not None
                         else None))
        self._completed += 1
        if self._retain_streams:
            self._finished[uid] = state.generated
        if self._on_retire is not None:
            self._on_retire(uid, state.generated)
        self.allocator.free(state.blocks)
        self._slots[slot] = None
        self._active[slot] = False
        self._seq_lens[slot] = 0
        self._last_tokens[slot] = 0
        self._block_tables[slot] = 0
        if self._events is not None:
            self._events.gauge("occupancy", self.occupancy(), t_ms=now)

    # -- stepping ----------------------------------------------------------
    def step(self) -> bool:
        """Admit what fits, then advance every active slot one token.
        Returns False when nothing happened (no active slots and nothing
        admissible)."""
        admitted = self._try_admit()
        if not self._active.any():
            return admitted > 0
        t0 = time.perf_counter()
        with span("decode"):
            self.cache, toks, metrics = self._decode(
                self.params, self.cache,
                jnp.asarray(self._last_tokens), jnp.asarray(self._seq_lens),
                jnp.asarray(self._active), jnp.asarray(self._block_tables),
                jnp.asarray(self._keys))
            toks = np.asarray(toks)  # fence — the iteration-level sync
        dt = time.perf_counter() - t0
        self.hists["decode_step_ms"].add([dt * 1e3])
        now_ms = self._now_ms()
        active_lens = [int(s) + 1 for s, a
                       in zip(self._seq_lens, self._active) if a]
        n_active = len(active_lens)
        for i in range(len(self._slots)):
            if not self._active[i]:
                continue
            state = self._slots[i]
            tok = int(toks[i])
            state.generated.append(tok)
            self._seq_lens[i] += 1
            self._last_tokens[i] = tok
            self._tokens_generated += 1
            if (self._events is not None
                    and len(state.generated) - state.chunk_done
                    >= self._chunk_tokens):
                self._events.emit(
                    "decode_chunk", state.request.uid, t_ms=now_ms,
                    slot=i, start_ms=round(state.chunk_start_ms, 3),
                    n_tokens=len(state.generated) - state.chunk_done)
                state.chunk_start_ms = now_ms
                state.chunk_done = len(state.generated)
            if self._should_retire(state, tok):
                self._retire(i)
        self._step_idx += 1
        self._emit_metrics(metrics, dt, n_active, active_lens)
        return True

    def _emit_metrics(self, metrics: Metrics, dt: float, n_active: int,
                      active_lens: List[int]) -> None:
        if self._sink is None:
            return
        flops = sum(decode_flops_per_token(
            self._n_params, self.cfg.num_layers, self.cfg.hidden, s)
            for s in active_lens)
        rec = {
            "phase": "decode",
            "step_ms": round(dt * 1e3, 3),
            "occupancy": n_active / self.serve_cfg.num_slots,
            "tokens_per_s": round(n_active / dt, 3) if dt else 0.0,
            "kv_read_bytes": kv_read_bytes(self.kv_cfg, active_lens),
            "kv_write_bytes": n_active * kv_write_bytes_per_token(
                self.kv_cfg),
            "decode_flops_modeled": flops,
        }
        if self._peak:
            rec["decode_mfu"] = (flops / dt) / self._peak if dt else 0.0
        self._sink.write(step=self._step_idx, metrics=metrics, **rec)

    # -- driving -----------------------------------------------------------
    def run(self, requests: Sequence[Request],
            max_steps: Optional[int] = None) -> Dict[str, List[int]]:
        """Serve ``requests`` to completion; returns uid -> generated
        tokens (the per-request streams, admission-order-invariant)."""
        for r in requests:
            self.submit(r)
        steps = 0
        while self._pending or self._active.any():
            if max_steps is not None and steps >= max_steps:
                break
            if not self.step():
                state_blocks = self.kv_cfg.blocks_for_tokens(
                    self._total_tokens(self._pending[0][0]))
                raise RuntimeError(
                    f"engine stalled: next request needs {state_blocks} "
                    f"blocks, pool has {self.allocator.free_count} free "
                    f"and no active slot will release more — the pool is "
                    f"too small for this request")
            steps += 1
        return dict(self._finished)

    # -- introspection / stats --------------------------------------------
    @property
    def finished(self) -> Dict[str, List[int]]:
        return dict(self._finished)

    @property
    def completed(self) -> int:
        """Requests retired so far (counts even when streams are not
        retained)."""
        return self._completed

    def per_request_state_count(self) -> int:
        """Per-request entries the engine is holding: retained streams +
        queued submissions + occupied slots. With ``retain_streams=False``
        this is O(slots + backlog) forever — the leak gate
        ``tests/test_serve.py`` pins after 10× slot-count requests."""
        return (len(self._finished) + len(self._pending)
                + sum(s is not None for s in self._slots))

    def stats(self) -> Dict[str, Any]:
        """One JSON-serializable telemetry snapshot: counts, latency
        quantiles (p50/p99 from the streaming histograms — bounded
        relative error, O(1) memory), full histogram dumps, and the
        goodput-under-SLO report when an ``SloSpec`` was given."""
        out: Dict[str, Any] = {
            "completed": self._completed,
            "steps": self._step_idx,
            "generated_tokens": self._tokens_generated,
            "queue_depth": len(self._pending),
            "occupancy": self.occupancy(),
        }
        tput = self.throughput()
        out["tokens_per_s"] = round(tput, 3) if tput else None
        for name in _HIST_NAMES:
            h = self.hists[name]
            if h.total == 0:
                continue
            out[f"{name}_p50"] = round(h.quantile(0.5), 3)
            out[f"{name}_p99"] = round(h.quantile(0.99), 3)
        out["hists"] = {k: v.to_dict() for k, v in self.hists.items()}
        if self._slo is not None:
            out["slo_report"] = self._slo.report()
        return out

    @property
    def active(self) -> bool:
        """Whether the engine still has work: a slot mid-generation or a
        queued submission (the drive-loop condition loadgen polls)."""
        return bool(self._active.any()) or bool(self._pending)

    def occupancy(self) -> float:
        return float(self._active.sum()) / self.serve_cfg.num_slots

    def throughput(self) -> Optional[float]:
        """Generated tokens per second since the first prefill."""
        if self._t_start is None:
            return None
        dt = time.perf_counter() - self._t_start
        return self._tokens_generated / dt if dt > 0 else None

    def kv_budget_bytes(self) -> int:
        return kv_cache_bytes(self.kv_cfg)

    # -- checkpoint integration -------------------------------------------
    @classmethod
    def from_checkpoint(cls, directory: str, template_params: Pytree, cfg,
                        serve_cfg: Optional[ServeConfig] = None,
                        **kwargs) -> "InferenceEngine":
        """Build an engine from the newest VALID checkpoint under
        ``directory`` (``resilience.CheckpointManager.latest_valid`` —
        torn/corrupt saves are skipped, a wrong-revision manifest refuses
        to bind). ``template_params`` supplies the pytree structure (e.g.
        ``init_gpt_params`` output)."""
        from apex_tpu.resilience.checkpoint import CheckpointManager

        mgr = CheckpointManager(directory)
        params, step = mgr.restore(template_params)
        eng = cls(params, cfg, serve_cfg, **kwargs)
        eng.checkpoint_step = step
        return eng


def decode_flops_per_token(n_params: int, num_layers: int, hidden: int,
                           context: int) -> float:
    """Modeled forward flops to decode ONE token at the given context:
    ``2N`` matmul flops plus paged attention ``4·L·hidden·context`` (QKᵀ
    and PV against the cached context). The serving analogue of
    ``monitor.report.gpt_analytic_flops_per_token`` (which counts fwd+bwd
    at 6N) — bench_serve divides by this so its MFU column is honest about
    being a model."""
    return float(2 * n_params + 4 * num_layers * hidden * context)

"""Block-paged KV cache — the inference engine's device memory manager.

Reference context: NVIDIA Apex has no serving story at all — its only
inference artifact is ``amp.initialize(..., opt_level)`` eval-mode half
precision over a stateless module. A TPU decode path lives or dies on KV
memory management: a contiguous per-request cache fragments HBM the moment
requests have different lengths, and re-allocating on every admission
retraces the step. The paged design (vLLM's PagedAttention, here rebuilt
for donated JAX pytrees) splits every sequence's K/V into fixed-size
**blocks** drawn from one shared pool:

* the pool is a single statically-shaped pytree — ``(L, H, num_blocks,
  block_size, head_dim)`` per K and V — threaded through the jitted
  prefill/decode programs with ``donate_argnums``, so the engine never
  re-allocates or retraces as requests come and go;
* a host-side :class:`BlockAllocator` free-list hands block ids to new
  requests and reclaims them at retirement — admission is pure bookkeeping,
  zero device work;
* per-slot **block tables** (``(slots, max_blocks)`` int32) map logical
  token positions to pool blocks; the decode attention gathers through
  them (``apex_tpu.serve.decode``).

Optional int8 KV quantization (``quantized=True``) stores the pools as
int8 codes plus one fp32 scale per (token, head) vector — the
``comm.quantize`` blockwise codec applied at codec-block = ``head_dim``,
so KV HBM traffic drops ~3.6× (``1 + 4/head_dim`` bytes per bf16 element's
2) and the same deterministic round-trip error bounds proven for the
gradient wire apply to the cache.

Byte accounting (:func:`kv_write_bytes_per_token`, :func:`kv_read_bytes`)
uses the same modeled-bytes convention as ``comm.accounting`` — the
engine reports both through the ``monitor`` pipeline and
``benchmarks/bench_serve.py`` prints them on the one-JSON-line record.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

Pytree = Any


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static shape/layout of the paged pools.

    ``num_heads`` is the LOCAL head count (``cfg.num_heads // tp`` inside a
    TP mesh program; the global count on a single device). ``num_blocks``
    is the POOL size shared by every slot — the unit of HBM budgeting:
    ``num_blocks * block_size`` total cacheable tokens.
    """

    num_layers: int
    num_heads: int
    head_dim: int
    num_blocks: int
    block_size: int = 16
    dtype: Any = jnp.bfloat16
    # int8 codes + fp32 scale per (token, head) head_dim vector, via the
    # comm.quantize blockwise codec (codec block = head_dim)
    quantized: bool = False

    @property
    def tokens_capacity(self) -> int:
        return self.num_blocks * self.block_size

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` (ceil)."""
        return -(-n_tokens // self.block_size)

    def validate(self) -> None:
        for name in ("num_layers", "num_heads", "head_dim", "num_blocks",
                     "block_size"):
            if getattr(self, name) <= 0:
                raise ValueError(f"{name} must be positive")


def init_kv_cache(cfg: KVCacheConfig) -> Dict[str, jnp.ndarray]:
    """Zeroed pool pytree: ``{"k", "v"}`` (+ ``{"k_scale", "v_scale"}`` when
    quantized). One allocation for the engine's whole lifetime; every
    prefill/decode step donates it back in."""
    cfg.validate()
    shape = (cfg.num_layers, cfg.num_heads, cfg.num_blocks, cfg.block_size,
             cfg.head_dim)
    dt = jnp.int8 if cfg.quantized else cfg.dtype
    cache = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
    if cfg.quantized:
        sshape = shape[:-1]
        # scale 1 keeps dequantize(0-codes) well-defined (codec convention)
        cache["k_scale"] = jnp.ones(sshape, jnp.float32)
        cache["v_scale"] = jnp.ones(sshape, jnp.float32)
    return cache


def _quant_rows(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize (..., head_dim) vectors with the comm.quantize blockwise
    codec at codec-block = head_dim: int8 codes same shape + fp32 scale per
    vector. Deterministic (round-to-nearest) — KV is an activation signal
    read many times, so the unbiased-stochastic mode's extra noise per read
    buys nothing here."""
    from apex_tpu.comm.quantize import quantize_blockwise

    d = x.shape[-1]
    q, s = quantize_blockwise(x.astype(jnp.float32).reshape(-1), d,
                              use_pallas=False)
    return q.reshape(x.shape), s.reshape(x.shape[:-1])


def _dequant_rows(q: jnp.ndarray, s: jnp.ndarray,
                  dtype: Any) -> jnp.ndarray:
    return (q.astype(jnp.float32) * s[..., None]).astype(dtype)


# ---------------------------------------------------------------------------
# In-graph paged writes/reads. These operate on ONE layer's pools — the
# natural view inside the model's lax.scan over layers (the stacked (L, ...)
# pools ride the scan's xs/ys). Positions map to (block, offset) through the
# slot's block-table row; invalid writes (inactive slot, padded prefill
# position) are routed to an out-of-range pool index and dropped by scatter
# mode="drop" — no branch, no extra compilation.


def _pool_write(pool, values, block_ids, offsets, valid):
    """Scatter ``values`` (H, n, ...) into ``pool`` (H, B, bs, ...) at
    ``(block_ids[i], offsets[i])``; entries with ``valid[i] == False`` are
    dropped (routed out of bounds). Works for both the code pools
    ((H, B, bs, D) <- (H, n, D)) and the scale pools ((H, B, bs) <-
    (H, n)) — indexing touches only dims 1-2."""
    num_blocks = pool.shape[1]
    idx = jnp.where(valid, block_ids, num_blocks)  # OOB -> dropped
    return pool.at[:, idx, offsets].set(values.astype(pool.dtype),
                                        mode="drop")


def paged_write(
    cache_layer: Dict[str, jnp.ndarray],
    cfg: KVCacheConfig,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    block_rows: jnp.ndarray,
    positions: jnp.ndarray,
    valid: jnp.ndarray,
) -> Dict[str, jnp.ndarray]:
    """Write per-token K/V into one layer's pools.

    ``cache_layer``: ``{"k": (H, B, bs, D), "v": ...}`` (+ scales when
    quantized). ``k_new``/``v_new``: (H, n, head_dim) — n tokens (one per
    decode slot, or the prompt positions of one prefill). ``block_rows``:
    (n, max_blocks) int32 block-table rows owning each token.
    ``positions``: (n,) int32 logical token positions. ``valid``: (n,) bool
    — False entries (inactive slots, bucket padding past the prompt) are
    dropped.
    """
    bs = cfg.block_size
    mb = block_rows.shape[1]
    block_ids = jnp.take_along_axis(
        block_rows, jnp.minimum(positions[:, None] // bs, mb - 1), axis=1
    )[:, 0]
    offsets = positions % bs
    valid = valid & (positions < mb * bs)
    out = dict(cache_layer)
    if cfg.quantized:
        kq, ks = _quant_rows(k_new)
        vq, vs = _quant_rows(v_new)
        out["k"] = _pool_write(cache_layer["k"], kq, block_ids, offsets,
                               valid)
        out["v"] = _pool_write(cache_layer["v"], vq, block_ids, offsets,
                               valid)
        out["k_scale"] = _pool_write(cache_layer["k_scale"], ks, block_ids,
                                     offsets, valid)
        out["v_scale"] = _pool_write(cache_layer["v_scale"], vs, block_ids,
                                     offsets, valid)
    else:
        out["k"] = _pool_write(cache_layer["k"], k_new, block_ids, offsets,
                               valid)
        out["v"] = _pool_write(cache_layer["v"], v_new, block_ids, offsets,
                               valid)
    return out


def gather_kv(
    cache_layer: Dict[str, jnp.ndarray],
    cfg: KVCacheConfig,
    block_tables: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Assemble contiguous K/V from one layer's pools through the block
    tables.

    ``block_tables``: (n, max_blocks) int32. Returns ``(k, v)`` of shape
    (n, H, max_blocks*block_size, head_dim) in ``cfg.dtype`` — dequantized
    when the cache is int8. The gather is exact: positions never written
    come back as whatever the pool holds and MUST be masked by the caller's
    context lengths.
    """
    def grab(pool):
        g = pool[:, block_tables]  # (H, n, mb, bs, D)
        h, n, mb, bs, d = g.shape
        return g.transpose(1, 0, 2, 3, 4).reshape(n, h, mb * bs, d)

    k, v = grab(cache_layer["k"]), grab(cache_layer["v"])
    if cfg.quantized:
        def grab_s(pool):
            g = pool[:, block_tables]  # (H, n, mb, bs)
            h, n, mb, bs = g.shape
            return g.transpose(1, 0, 2, 3).reshape(n, h, mb * bs)

        k = _dequant_rows(k, grab_s(cache_layer["k_scale"]), cfg.dtype)
        v = _dequant_rows(v, grab_s(cache_layer["v_scale"]), cfg.dtype)
    else:
        k, v = k.astype(cfg.dtype), v.astype(cfg.dtype)
    return k, v


# ---------------------------------------------------------------------------
# Host-side block allocator: a plain LIFO free-list. Admission happens
# between steps on the host, so this needs no device work and no locking
# (the engine is single-threaded by construction).


class BlockAllocator:
    """Free-list over the pool's ``num_blocks`` block ids."""

    def __init__(self, num_blocks: int):
        if num_blocks <= 0:
            raise ValueError("num_blocks must be positive")
        self.num_blocks = num_blocks
        # LIFO: recently freed blocks are re-used first (still warm in any
        # cache hierarchy; also makes tests deterministic). The shadow set
        # keeps the double-free check O(1) — retirement frees thousands of
        # blocks on production pools and must stay off the step's critical
        # path.
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._free_set = set(self._free)

    @property
    def free_count(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` block ids, or None when the pool cannot satisfy the request
        (caller defers admission — never a partial grant)."""
        if n < 0:
            raise ValueError("n must be >= 0")
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(out)
        return out

    def free(self, ids: Sequence[int]) -> None:
        for b in ids:
            if not 0 <= b < self.num_blocks:
                raise ValueError(f"block id {b} out of range")
            if b in self._free_set:
                raise ValueError(f"double free of block {b}")
            self._free.append(b)
            self._free_set.add(b)


# ---------------------------------------------------------------------------
# Byte accounting — modeled HBM traffic of the paged cache, the serving
# analogue of comm.accounting's modeled wire bytes. bench_serve.py joins
# these with collective_report() on the compiled decode program.


def _elem_bytes(cfg: KVCacheConfig) -> float:
    """Bytes per cached K or V element, scale overhead amortized in."""
    if cfg.quantized:
        return 1.0 + 4.0 / cfg.head_dim  # int8 code + fp32 scale per vector
    return float(jnp.dtype(cfg.dtype).itemsize)


def kv_cache_bytes(cfg: KVCacheConfig) -> int:
    """Total HBM held by the pools (the engine's fixed KV budget)."""
    n = (cfg.num_layers * cfg.num_heads * cfg.num_blocks * cfg.block_size
         * cfg.head_dim)
    return int(2 * n * _elem_bytes(cfg))


def kv_write_bytes_per_token(cfg: KVCacheConfig) -> float:
    """Bytes written to the pools per cached token (all layers, K+V)."""
    return 2 * cfg.num_layers * cfg.num_heads * cfg.head_dim * _elem_bytes(cfg)


def kv_read_bytes(cfg: KVCacheConfig, seq_lens: Sequence[int]) -> float:
    """Modeled bytes read by ONE decode step over the given active context
    lengths: each slot streams its live blocks (whole blocks — the paged
    gather fetches block granules, like the wire models price whole
    transfers) through every layer's attention."""
    toks = sum(cfg.blocks_for_tokens(int(s)) * cfg.block_size
               for s in seq_lens if int(s) > 0)
    return (2 * cfg.num_layers * cfg.num_heads * cfg.head_dim
            * _elem_bytes(cfg) * toks)

"""Block-paged KV cache — the inference engine's device memory manager.

Reference context: NVIDIA Apex has no serving story at all — its only
inference artifact is ``amp.initialize(..., opt_level)`` eval-mode half
precision over a stateless module. A TPU decode path lives or dies on KV
memory management: a contiguous per-request cache fragments HBM the moment
requests have different lengths, and re-allocating on every admission
retraces the step. The paged design (vLLM's PagedAttention, here rebuilt
for donated JAX pytrees) splits every sequence's K/V into fixed-size
**blocks** drawn from one shared pool:

* the pool is a single statically-shaped pytree — ``(L, H, num_blocks,
  block_size, head_dim)`` per K and V — threaded through the jitted
  prefill/decode programs with ``donate_argnums``, so the engine never
  re-allocates or retraces as requests come and go;
* a host-side :class:`BlockAllocator` free-list hands block ids to new
  requests and reclaims them at retirement — admission is pure bookkeeping,
  zero device work;
* per-slot **block tables** (``(slots, max_blocks)`` int32) map logical
  token positions to pool blocks; the decode attention gathers through
  them (``apex_tpu.serve.decode``);
* **prefix caching** (``BlockAllocator(prefix_cache=True)``) adds
  content-addressed reuse: full prompt blocks get a chained
  hash-of-token-prefix address (:func:`prefix_block_hashes`), freed
  cached blocks park in an evictable LRU at refcount 0 instead of being
  recycled, and a later request sharing the prefix re-acquires them via
  :meth:`BlockAllocator.lookup` — a shared system prompt costs zero
  prefill flops after its first admission. :func:`copy_block` is the
  copy-on-write escape hatch for the one case where a request must write
  inside a shared block.

Optional int8 KV quantization (``quantized=True``) stores the pools as
int8 codes plus one fp32 scale per (token, head) vector — the
``comm.quantize`` blockwise codec applied at codec-block = ``head_dim``,
so KV HBM traffic drops ~3.6× (``1 + 4/head_dim`` bytes per bf16 element's
2) and the same deterministic round-trip error bounds proven for the
gradient wire apply to the cache.

Byte accounting (:func:`kv_write_bytes_per_token`, :func:`kv_read_bytes`)
uses the same modeled-bytes convention as ``comm.accounting`` — the
engine reports both through the ``monitor`` pipeline and
``benchmarks/bench_serve.py`` prints them on the one-JSON-line record.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

Pytree = Any


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static shape/layout of the paged pools.

    ``num_heads`` is the LOCAL head count (``cfg.num_heads // tp`` inside a
    TP mesh program; the global count on a single device). ``num_blocks``
    is the POOL size shared by every slot — the unit of HBM budgeting:
    ``num_blocks * block_size`` total cacheable tokens.

    Quantized modes: ``quantized=True, bits=8`` is the PR-5 layout (int8
    codes + one fp32 scale per (token, head) head_dim vector);
    ``bits=4`` drops to the sub-8-bit tier — codes nibble-packed two per
    byte (pool leaf last dim = ``head_dim // 2``) and GROUP-quantized
    along head_dim: one **bf16** scale per ``group_size`` consecutive
    channel values (default group = the whole vector, so the pool is
    exactly HALF the int8 pool's bytes at every head_dim — a bf16 scale's
    8-bit mantissa costs ~0.4% relative scale error, an order below the
    4-bit codes' half-step; smaller groups trade scale bytes back for
    code resolution). Scale pools grow a trailing
    ``head_dim // group_size`` dim.
    """

    num_layers: int
    num_heads: int
    head_dim: int
    num_blocks: int
    block_size: int = 16
    dtype: Any = jnp.bfloat16
    # quantized codes + scales via the comm.quantize codec
    quantized: bool = False
    bits: int = 8
    # int4 scale-group length along head_dim; None -> head_dim (one scale
    # per vector, the exact-2x-vs-int8 default)
    group_size: Optional[int] = None

    @property
    def tokens_capacity(self) -> int:
        return self.num_blocks * self.block_size

    @property
    def kv_group(self) -> int:
        """Effective scale-group length along head_dim (the full vector
        unless int4 ``group_size`` narrows it)."""
        if self.bits == 8 or self.group_size is None:
            return self.head_dim
        return self.group_size

    def blocks_for_tokens(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` (ceil)."""
        return -(-n_tokens // self.block_size)

    def validate(self) -> None:
        for name in ("num_layers", "num_heads", "head_dim", "num_blocks",
                     "block_size"):
            if getattr(self, name) <= 0:
                raise ValueError(f"{name} must be positive")
        if self.bits not in (4, 8):
            raise ValueError(f"bits must be 4 or 8, got {self.bits}")
        if self.group_size is not None and self.bits == 8:
            raise ValueError("group_size only applies to the int4 mode "
                             "(int8 scales one full head_dim vector)")
        if self.quantized and self.bits == 4:
            g = self.kv_group
            if self.head_dim % 2:
                raise ValueError(
                    f"int4 KV needs an even head_dim (nibble packing): "
                    f"{self.head_dim}")
            if g % 2 or g <= 0 or self.head_dim % g:
                raise ValueError(
                    f"int4 KV group_size must be even and divide head_dim "
                    f"({self.head_dim}): got {g}")


def init_kv_cache(cfg: KVCacheConfig) -> Dict[str, jnp.ndarray]:
    """Zeroed pool pytree: ``{"k", "v"}`` (+ ``{"k_scale", "v_scale"}`` when
    quantized). One allocation for the engine's whole lifetime; every
    prefill/decode step donates it back in. int4 pools store nibble-packed
    uint8 codes (last dim halved) + per-group scales (trailing
    ``head_dim // group`` dim)."""
    cfg.validate()
    shape = (cfg.num_layers, cfg.num_heads, cfg.num_blocks, cfg.block_size,
             cfg.head_dim)
    if cfg.quantized and cfg.bits == 4:
        code_shape = shape[:-1] + (cfg.head_dim // 2,)
        cache = {"k": jnp.zeros(code_shape, jnp.uint8),
                 "v": jnp.zeros(code_shape, jnp.uint8)}
        sshape = shape[:-1] + (cfg.head_dim // cfg.kv_group,)
        # bf16 scales: half the int8 layout's scale bytes (see the config
        # docstring); scale 1 keeps dequantize(0-codes) well-defined
        cache["k_scale"] = jnp.ones(sshape, jnp.bfloat16)
        cache["v_scale"] = jnp.ones(sshape, jnp.bfloat16)
        return cache
    dt = jnp.int8 if cfg.quantized else cfg.dtype
    cache = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
    if cfg.quantized:
        sshape = shape[:-1]
        # scale 1 keeps dequantize(0-codes) well-defined (codec convention)
        cache["k_scale"] = jnp.ones(sshape, jnp.float32)
        cache["v_scale"] = jnp.ones(sshape, jnp.float32)
    return cache


def _quant_rows(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize (..., head_dim) vectors with the comm.quantize blockwise
    codec at codec-block = head_dim: int8 codes same shape + fp32 scale per
    vector. Deterministic (round-to-nearest) — KV is an activation signal
    read many times, so the unbiased-stochastic mode's extra noise per read
    buys nothing here."""
    from apex_tpu.comm.quantize import quantize_blockwise

    d = x.shape[-1]
    q, s = quantize_blockwise(x.astype(jnp.float32).reshape(-1), d,
                              use_pallas=False)
    return q.reshape(x.shape), s.reshape(x.shape[:-1])


def _dequant_rows(q: jnp.ndarray, s: jnp.ndarray,
                  dtype: Any) -> jnp.ndarray:
    return (q.astype(jnp.float32) * s[..., None]).astype(dtype)


def _quant_rows_int4(x: jnp.ndarray, group: int
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(..., head_dim) vectors -> (packed uint8 codes (..., head_dim/2),
    bf16 scales (..., head_dim/group)) — the comm.quantize int4 math
    (absmax/7 per group, round-to-nearest, ±7 clip, nibble pack) with the
    scale ROUNDED TO bf16 FIRST and the codes computed against that
    stored value, so the half-step bound holds against exactly what the
    pool holds."""
    from apex_tpu.comm.quantize import QMAX4, pack_int4

    d = x.shape[-1]
    g = x.astype(jnp.float32).reshape(x.shape[:-1] + (d // group, group))
    amax = jnp.max(jnp.abs(g), axis=-1)
    scale = jnp.where(amax > 0, amax / QMAX4, 1.0).astype(jnp.bfloat16)
    q = jnp.clip(jnp.round(g / scale.astype(jnp.float32)[..., None]),
                 -QMAX4, QMAX4).astype(jnp.int8)
    return pack_int4(q.reshape(x.shape)), scale


def _dequant_rows_int4(q: jnp.ndarray, s: jnp.ndarray, group: int,
                       dtype: Any) -> jnp.ndarray:
    """Inverse of :func:`_quant_rows_int4`: unpack nibbles, scale per
    group, restore (..., head_dim)."""
    from apex_tpu.comm.quantize import unpack_int4

    codes = unpack_int4(q)                                # (..., D)
    d = codes.shape[-1]
    g = codes.reshape(codes.shape[:-1] + (d // group, group))
    out = g.astype(jnp.float32) * s.astype(jnp.float32)[..., None]
    return out.reshape(codes.shape).astype(dtype)


# ---------------------------------------------------------------------------
# In-graph paged writes/reads. These operate on ONE layer's pools — the
# natural view inside the model's lax.scan over layers (the stacked (L, ...)
# pools ride the scan's xs/ys). Positions map to (block, offset) through the
# slot's block-table row; invalid writes (inactive slot, padded prefill
# position) are routed to an out-of-range pool index and dropped by scatter
# mode="drop" — no branch, no extra compilation.


def _pool_write(pool, values, block_ids, offsets, valid):
    """Scatter ``values`` (H, n, ...) into ``pool`` (H, B, bs, ...) at
    ``(block_ids[i], offsets[i])``; entries with ``valid[i] == False`` are
    dropped (routed out of bounds). Works for both the code pools
    ((H, B, bs, D) <- (H, n, D)) and the scale pools ((H, B, bs) <-
    (H, n)) — indexing touches only dims 1-2."""
    num_blocks = pool.shape[1]
    idx = jnp.where(valid, block_ids, num_blocks)  # OOB -> dropped
    return pool.at[:, idx, offsets].set(values.astype(pool.dtype),
                                        mode="drop")


def paged_write(
    cache_layer: Dict[str, jnp.ndarray],
    cfg: KVCacheConfig,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    block_rows: jnp.ndarray,
    positions: jnp.ndarray,
    valid: jnp.ndarray,
) -> Dict[str, jnp.ndarray]:
    """Write per-token K/V into one layer's pools.

    ``cache_layer``: ``{"k": (H, B, bs, D), "v": ...}`` (+ scales when
    quantized). ``k_new``/``v_new``: (H, n, head_dim) — n tokens (one per
    decode slot, or the prompt positions of one prefill). ``block_rows``:
    (n, max_blocks) int32 block-table rows owning each token.
    ``positions``: (n,) int32 logical token positions. ``valid``: (n,) bool
    — False entries (inactive slots, bucket padding past the prompt) are
    dropped.
    """
    bs = cfg.block_size
    mb = block_rows.shape[1]
    block_ids = jnp.take_along_axis(
        block_rows, jnp.minimum(positions[:, None] // bs, mb - 1), axis=1
    )[:, 0]
    offsets = positions % bs
    valid = valid & (positions < mb * bs)
    out = dict(cache_layer)
    if cfg.quantized:
        if cfg.bits == 4:
            kq, ks = _quant_rows_int4(k_new, cfg.kv_group)
            vq, vs = _quant_rows_int4(v_new, cfg.kv_group)
        else:
            kq, ks = _quant_rows(k_new)
            vq, vs = _quant_rows(v_new)
        out["k"] = _pool_write(cache_layer["k"], kq, block_ids, offsets,
                               valid)
        out["v"] = _pool_write(cache_layer["v"], vq, block_ids, offsets,
                               valid)
        out["k_scale"] = _pool_write(cache_layer["k_scale"], ks, block_ids,
                                     offsets, valid)
        out["v_scale"] = _pool_write(cache_layer["v_scale"], vs, block_ids,
                                     offsets, valid)
    else:
        out["k"] = _pool_write(cache_layer["k"], k_new, block_ids, offsets,
                               valid)
        out["v"] = _pool_write(cache_layer["v"], v_new, block_ids, offsets,
                               valid)
    return out


def gather_kv(
    cache_layer: Dict[str, jnp.ndarray],
    cfg: KVCacheConfig,
    block_tables: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Assemble contiguous K/V from one layer's pools through the block
    tables.

    ``block_tables``: (n, max_blocks) int32. Returns ``(k, v)`` of shape
    (n, H, max_blocks*block_size, head_dim) in ``cfg.dtype`` — dequantized
    when the cache is int8. The gather is exact: positions never written
    come back as whatever the pool holds and MUST be masked by the caller's
    context lengths.
    """
    def grab(pool):
        g = pool[:, block_tables]  # (H, n, mb, bs[, D])
        h, n, mb, bs = g.shape[:4]
        tail = g.shape[4:]
        perm = (1, 0, 2, 3) + tuple(range(4, g.ndim))
        return g.transpose(perm).reshape((n, h, mb * bs) + tail)

    k, v = grab(cache_layer["k"]), grab(cache_layer["v"])
    if cfg.quantized and cfg.bits == 4:
        ks, vs = grab(cache_layer["k_scale"]), grab(cache_layer["v_scale"])
        k = _dequant_rows_int4(k, ks, cfg.kv_group, cfg.dtype)
        v = _dequant_rows_int4(v, vs, cfg.kv_group, cfg.dtype)
    elif cfg.quantized:
        k = _dequant_rows(k, grab(cache_layer["k_scale"]), cfg.dtype)
        v = _dequant_rows(v, grab(cache_layer["v_scale"]), cfg.dtype)
    else:
        k, v = k.astype(cfg.dtype), v.astype(cfg.dtype)
    return k, v


def copy_block(cache: Dict[str, jnp.ndarray], src, dst
               ) -> Dict[str, jnp.ndarray]:
    """Copy pool block ``src`` -> ``dst`` across every layer and pool leaf
    (K, V, and the int8 scales when present) — the device half of
    copy-on-write: when a request must write into a SHARED cached block
    (recomputing the last prompt position of a fully-cached prompt), the
    engine allocates a private block, copies the shared content here, and
    rewrites its block table; the sharing requests' block is never
    mutated. ``src``/``dst`` are traced scalars, so the jitted copy is ONE
    compiled program for the engine's lifetime."""
    return {k: v.at[:, :, dst].set(v[:, :, src]) for k, v in cache.items()}


# ---------------------------------------------------------------------------
# Prefix hashing — the content address of a FULL block of prompt tokens.
# Chained (each block's hash folds its predecessor's), so a hash names the
# whole token prefix ending at that block, not just the block's own span:
# matching block j implies the entire prefix [0, (j+1)*block_size) matches.
# Ints only (python salts str hashing per process; int hashing is stable
# within one process, which is all a per-engine cache needs).


def hash_block_tokens(prev_hash: int, tokens: Sequence[int]) -> int:
    """Chained content hash of one full block: ``h_j = H(h_{j-1}, tokens)``."""
    return hash((prev_hash,) + tuple(int(t) for t in tokens))


def prefix_block_hashes(tokens: Sequence[int],
                        block_size: int) -> List[int]:
    """Chain hashes of every FULL block of ``tokens`` (the partial tail
    block has no content address — it is never shared)."""
    out: List[int] = []
    h = hash(("apex_tpu.serve.prefix", block_size))
    for j in range(len(tokens) // block_size):
        h = hash_block_tokens(h, tokens[j * block_size:(j + 1) * block_size])
        out.append(h)
    return out


# ---------------------------------------------------------------------------
# Host-side block allocator. Admission happens between steps on the host,
# so this needs no device work and no locking (the engine is
# single-threaded by construction). Two modes:
#
# * plain (``prefix_cache=False``) — a LIFO free-list, every block owned by
#   exactly one request (the PR-5 behavior);
# * prefix-caching (``prefix_cache=True``) — content-addressed reuse: a
#   hash-of-token-prefix -> block-id map at block granularity with
#   per-block refcounts. Freed blocks that carry a content address are
#   PARKED in an LRU of evictable cached blocks instead of returning to
#   the free list — a later request whose prompt shares the prefix
#   re-acquires them via :meth:`lookup` and pays ZERO prefill flops for
#   those tokens; ``alloc`` evicts least-recently-used refcount-0 cached
#   blocks only when the free list runs dry.


class BlockAllocator:
    """Refcounted free-list (+ optional content-addressed prefix cache)
    over the pool's ``num_blocks`` block ids.

    Invariants (``assert_consistent`` checks them; the chaos test in
    ``tests/test_serve_prefix.py`` hammers them under random admit/retire/
    evict interleavings):

    * every block is in exactly ONE of: free list, evictable LRU
      (cached, refcount 0), or allocated (refcount >= 1);
    * a block is evictable iff its refcount is 0 and it holds a content
      hash; eviction drops the hash and returns it to the free list;
    * ``free`` of a block whose refcount is already 0 raises (double
      free), as does an out-of-range id.
    """

    def __init__(self, num_blocks: int, prefix_cache: bool = False):
        if num_blocks <= 0:
            raise ValueError("num_blocks must be positive")
        self.num_blocks = num_blocks
        self.prefix_cache = prefix_cache
        # LIFO: recently freed blocks are re-used first (still warm in any
        # cache hierarchy; also makes tests deterministic).
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self._refcount: Dict[int, int] = {}
        self._hash_to_block: Dict[int, int] = {}
        self._block_hash: Dict[int, int] = {}
        # refcount-0 cached blocks, least-recently-used first
        self._lru: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        # lifetime counters (the engine's prefix-cache stats read these)
        self.blocks_reused_total = 0
        self.blocks_evicted_total = 0

    @property
    def free_count(self) -> int:
        """Allocatable blocks: truly free + evictable cached."""
        return len(self._free) + len(self._lru)

    @property
    def cached_count(self) -> int:
        """Blocks holding a content address (shared or parked)."""
        return len(self._block_hash)

    def refcount(self, block: int) -> int:
        return self._refcount.get(block, 0)

    def _evict_one(self) -> None:
        b, _ = self._lru.popitem(last=False)  # least recently used
        h = self._block_hash.pop(b)
        del self._hash_to_block[h]
        self._free.append(b)
        self.blocks_evicted_total += 1

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh block ids at refcount 1, or None when the pool
        cannot satisfy the request even after evicting every refcount-0
        cached block (caller defers admission — never a partial grant)."""
        if n < 0:
            raise ValueError("n must be >= 0")
        if n > self.free_count:
            return None
        while len(self._free) < n:
            self._evict_one()
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refcount[b] = 1
        return out

    def free(self, ids: Sequence[int]) -> None:
        """Drop one reference per id. A cached block reaching refcount 0
        parks in the evictable LRU (its content stays addressable); an
        uncached block returns to the free list."""
        for b in ids:
            if not 0 <= b < self.num_blocks:
                raise ValueError(f"block id {b} out of range")
            rc = self._refcount.get(b, 0)
            if rc <= 0:
                raise ValueError(f"double free of block {b}")
            if rc > 1:
                self._refcount[b] = rc - 1
                continue
            del self._refcount[b]
            if b in self._block_hash:
                self._lru[b] = None          # most-recently-used end
            else:
                self._free.append(b)

    # -- content-addressed reuse ------------------------------------------
    def lookup(self, hashes: Sequence[int]) -> List[int]:
        """Longest cached prefix of the chained ``hashes``: acquires (one
        reference each) and returns the matched block ids in prefix order.
        A parked block leaves the LRU; a shared one just gains a holder.
        Always misses when the allocator was built plain
        (``prefix_cache=False``)."""
        if not self.prefix_cache:
            return []
        out: List[int] = []
        for h in hashes:
            b = self._hash_to_block.get(h)
            if b is None:
                break
            out.append(b)
        for b in out:
            rc = self._refcount.get(b, 0)
            if rc == 0:
                self._lru.pop(b, None)
            self._refcount[b] = rc + 1
            self.blocks_reused_total += 1
        return out

    def commit(self, block: int, h: int) -> bool:
        """Register an allocated, fully-written block under its content
        hash. No-op (False) when the allocator is plain
        (``prefix_cache=False``), when the hash is already mapped (a
        concurrent identical prompt won the race — this copy stays
        private), or when the block already carries an address."""
        if self._refcount.get(block, 0) <= 0:
            raise ValueError(f"commit of unallocated block {block}")
        if not self.prefix_cache:
            return False
        if h in self._hash_to_block or block in self._block_hash:
            return False
        self._hash_to_block[h] = block
        self._block_hash[block] = h
        return True

    def assert_consistent(self) -> None:
        """Every-block-in-exactly-one-place conservation check (cheap; the
        chaos test calls it after every random operation)."""
        free = set(self._free)
        lru = set(self._lru)
        alloc = set(self._refcount)
        assert not (free & lru) and not (free & alloc) and not (lru & alloc)
        assert len(free) + len(lru) + len(alloc) == self.num_blocks
        assert all(rc >= 1 for rc in self._refcount.values())
        for b in lru:
            assert b in self._block_hash, f"evictable block {b} uncached"
        for h, b in self._hash_to_block.items():
            assert self._block_hash.get(b) == h


# ---------------------------------------------------------------------------
# Byte accounting — modeled HBM traffic of the paged cache, the serving
# analogue of comm.accounting's modeled wire bytes. bench_serve.py joins
# these with collective_report() on the compiled decode program.


def _elem_bytes(cfg: KVCacheConfig) -> float:
    """Bytes per cached K or V element, scale overhead amortized in."""
    if cfg.quantized and cfg.bits == 4:
        # nibble-packed code + bf16 scale per group along head_dim:
        # exactly half the int8 layout at group = head_dim
        return 0.5 + 2.0 / cfg.kv_group
    if cfg.quantized:
        return 1.0 + 4.0 / cfg.head_dim  # int8 code + fp32 scale per vector
    return float(jnp.dtype(cfg.dtype).itemsize)


def kv_cache_bytes(cfg: KVCacheConfig) -> int:
    """Total HBM held by the pools (the engine's fixed KV budget)."""
    n = (cfg.num_layers * cfg.num_heads * cfg.num_blocks * cfg.block_size
         * cfg.head_dim)
    return int(2 * n * _elem_bytes(cfg))


def kv_write_bytes_per_token(cfg: KVCacheConfig) -> float:
    """Bytes written to the pools per cached token (all layers, K+V)."""
    return 2 * cfg.num_layers * cfg.num_heads * cfg.head_dim * _elem_bytes(cfg)


def kv_read_bytes(cfg: KVCacheConfig, seq_lens: Sequence[int]) -> float:
    """Modeled bytes read by ONE decode step over the given active context
    lengths: each slot streams its live blocks (whole blocks — the paged
    gather fetches block granules, like the wire models price whole
    transfers) through every layer's attention."""
    toks = sum(cfg.blocks_for_tokens(int(s)) * cfg.block_size
               for s in seq_lens if int(s) > 0)
    return (2 * cfg.num_layers * cfg.num_heads * cfg.head_dim
            * _elem_bytes(cfg) * toks)

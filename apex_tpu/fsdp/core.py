"""FSDP (ZeRO-3) parameter sharding — gather-on-demand over the dp axis.

Reference context: the contrib ZeRO optimizers
(``apex/contrib/optimizers/distributed_fused_adam.py``) stop at stage 1+2 —
optimizer state is dp-sharded but the *parameters* (and the gradients the
backward materializes) still cost full-model HBM on every chip. Xu et al.,
"Automatic Cross-Replica Sharding of Weight Update" (arXiv:2004.13336) and
the MLPerf TPU-pod scaling playbook (arXiv:1909.09756) take the last step:
shard the parameters too and hide the forward/backward all-gathers behind
compute. This module is that step for the TPU mesh:

* each dp rank owns a **flat block-aligned shard** of every leaf (the
  ``_sharding`` shard-multiple layout from contrib ZeRO, so an int8 comm
  codec's fp32 scale blocks never straddle ranks);
* the forward **gathers parameters on demand** through a ``custom_vjp``
  whose backward **reduce-scatters the gradient straight into shard
  layout** — the dp grad sum and the ZeRO-3 shard delivery are ONE
  collective. The gather wire optionally rides the blockwise-int8
  ``comm.quantize`` codec (``weight_gather=``), the grad reduce-scatter
  optionally rides ``comm.collectives.compressed_psum_scatter``
  (``compression=``);
* matmul-adjacent leaves can skip the materialized gather entirely:
  :meth:`FSDP.linear` stores the weight as a **column shard** and rides
  ``comm.overlap.matmul_param_gather``'s decomposed ppermute ring — each
  gather hop travels behind a partial GEMM (the dependent
  collective→matmul chain XLA cannot overlap on its own), the backward
  re-gather ring is the classic FSDP re-materialize, and the dW ring
  reduce-scatters into shard layout. Reshard-after-forward is structural:
  the ring's residual is the shard, the full weight is never saved;
* the optimizer (``fsdp.optim.FSDPAdam``) steps only the local shard
  through the shared ZeRO tail (``_sharding.adam_shard_update``, Pallas
  ``fused_update`` included) — there is NO replicated parameter copy: the
  fp32 master shard is the canonical store, full parameters exist only
  transiently inside the gathered step.

Declarative entry point: ``apex_tpu.parallel.ParallelismPlan`` composes
this with dp/tp/pp meshes, overlap, compression and the monitor/resilience
wiring — see ``parallel/plan.py``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.comm.collectives import (
    CompressionConfig,
    compressed_psum_scatter,
)
from apex_tpu.contrib.optimizers._sharding import (
    gather_leaf,
    scatter_leaf,
    shard_multiple_lcm,
    slice_leaf,
)
from apex_tpu.parallel.mesh import DP_AXIS
from apex_tpu.parallel.mesh import axis_size as _axis_size

Pytree = Any


@dataclasses.dataclass(frozen=True)
class LeafMeta:
    """Static per-leaf record (NOT a pytree container — travels as a leaf
    through ``tree_map`` next to the shard pytree): the full shape/dtype a
    gathered leaf must be restored to."""

    shape: tuple
    dtype: str

    @property
    def size(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n


def _prod(shape) -> int:
    n = 1
    for d in shape:
        n *= d
    return n


# ---------------------------------------------------------------------------
# the gather-on-demand primitive (plain leaves)
#
# custom_vjp so the backward is OUR reduce-scatter (optionally quantized)
# landing in shard layout — jax's built-in all_gather transpose would psum
# the full gradient first.


def _gather_impl(shard, axis_name, shape, dtype, wg):
    n = _prod(shape)
    if wg is not None and wg.compresses(n):
        # round to the model dtype FIRST (the wire carries what the model
        # would see anyway — same contract as ZeRO's e5m2_allgather), then
        # packed codes + fp32 block scales on the wire via the config's
        # policy-dispatched codec (int8 or the nibble-packed int4 tier).
        # The shard is block-aligned by construction (shard_multiple), so
        # no scale block — or packed nibble pair — straddles ranks.
        vals = shard.astype(dtype).astype(jnp.float32)
        q, s = wg.quantize(vals)
        qf = lax.all_gather(q, axis_name, axis=0, tiled=True)
        sf = lax.all_gather(s, axis_name, axis=0, tiled=True)
        full = wg.dequantize(qf, sf)
        return full[:n].reshape(shape).astype(dtype)
    # uncompressed: the ZeRO-1 gather path — model dtype on the wire
    # (transport_dtype=dtype is the saturating master→model-dtype cast),
    # so the two strategies can never diverge in layout or unpad math
    return gather_leaf(shard, shape, dtype, axis_name,
                       transport_dtype=dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def _gather_leaf_op(shard, axis_name, shape, dtype, wg, rs, multiple):
    return _gather_impl(shard, axis_name, shape, jnp.dtype(dtype), wg)


def _gather_leaf_fwd(shard, axis_name, shape, dtype, wg, rs, multiple):
    # NO residuals: the gather is linear and the backward re-derives its
    # shapes from the static args — the full parameter is never saved
    # (reshard-after-forward), and neither is the shard
    return _gather_impl(shard, axis_name, shape, jnp.dtype(dtype), wg), None


def _gather_leaf_bwd(axis_name, shape, dtype, wg, rs, multiple, res, dy):
    del res, shape
    flat = dy.reshape(-1).astype(jnp.float32)
    if rs is not None and rs.enabled:
        # quantized grad reduce-scatter (no EF/stochastic state — the VJP
        # is stateless; FSDP validates those policies away at construction)
        g, _ = compressed_psum_scatter(flat, axis_name, rs,
                                       shard_multiple=multiple)
        return (g,)
    return (scatter_leaf(flat, axis_name, multiple=multiple),)


_gather_leaf_op.defvjp(_gather_leaf_fwd, _gather_leaf_bwd)


@dataclasses.dataclass(frozen=True)
class FSDP:
    """The ZeRO-3 engine: shard layout + gather-on-demand + grad
    reduce-scatter, one dp axis. Use inside the mesh program::

        fsdp = FSDP(compression=CompressionConfig("int8"))
        opt = FSDPAdam(fsdp=fsdp, lr=1e-3)
        meta = fsdp.meta(params)            # static, once
        state = opt.init(params)            # fp32 master/moment SHARDS

        def loss_fn(master):
            p = fsdp.gather(master, meta)   # full params, model dtype
            return model_loss(p, batch)

        loss, g_shards = jax.value_and_grad(loss_fn)(state.master)
        state = opt.step(g_shards, state)   # local-shard update, no gather

    ``compression``: wire policy of the gradient reduce-scatter (policy
    ``int8``; ``int8_ef``/stochastic rounding are refused — the VJP is
    stateless). ``weight_gather``: optional int8 codec for the parameter
    all-gather wire (lossy within codec tolerance; the fp32 master shard
    stays exact). Shards are flat ``(k,)`` with ``k`` aligned to the lcm
    of both codecs' block sizes."""

    axis_name: str = DP_AXIS
    compression: Optional[CompressionConfig] = None
    weight_gather: Optional[CompressionConfig] = None
    bidirectional: bool = False

    def __post_init__(self):
        for name, cfg in (("compression", self.compression),
                          ("weight_gather", self.weight_gather)):
            if cfg is None:
                continue
            if cfg.error_feedback:
                raise ValueError(
                    f"FSDP {name} cannot carry error feedback: the "
                    "gather/reduce-scatter VJP is stateless — use policy "
                    "'int8' (ZeRO-1 DistributedFusedAdam supports "
                    "'int8_ef' on its grad leg)")
            if cfg.stochastic_rounding:
                raise ValueError(
                    f"FSDP {name} does not support stochastic_rounding "
                    "(no per-step seed reaches the stateless VJP)")

    @property
    def shard_multiple(self) -> int:
        return shard_multiple_lcm(self.compression, self.weight_gather)

    # -- layout ------------------------------------------------------------
    def meta(self, params_template: Pytree) -> Pytree:
        """Static :class:`LeafMeta` pytree mirroring ``params_template``
        (shapes/dtypes from avals — no device reads)."""
        return jax.tree_util.tree_map(
            lambda p: LeafMeta(tuple(jnp.shape(p)),
                               str(jnp.result_type(p))),
            params_template)

    def shard_params(self, params: Pytree) -> Pytree:
        """This rank's flat fp32 shard of every (replicated) leaf — call
        inside the mesh program. The fp32 copy is the canonical store
        (master); there is no separate replicated parameter copy."""
        return jax.tree_util.tree_map(
            lambda p: slice_leaf(p.astype(jnp.float32), self.axis_name,
                                 multiple=self.shard_multiple),
            params)

    def policy_dtype(self, meta: Pytree):
        """The model compute dtype this engine's gathered forwards run in
        (the widest low-precision leaf dtype of ``meta``, else the widest
        overall) — the declared policy region
        ``apex_tpu.analyze.dtype_leak`` checks the compiled step against:
        a forward whose dots come out f32 under a bf16 ``meta`` is a
        leak, not a preference."""
        dts = {jnp.dtype(m.dtype) for m in jax.tree_util.tree_leaves(
            meta, is_leaf=lambda x: isinstance(x, LeafMeta))
            if isinstance(m, LeafMeta)}
        # FLOAT dtypes only: an int8 codebook/bool mask leaf is not a
        # compute-dtype declaration (and would silently disarm the
        # dtype-leak gate, whose low-precision set is float-typed)
        dts = {d for d in dts if jnp.issubdtype(d, jnp.floating)}
        if not dts:
            return None
        low = [d for d in dts if d.itemsize < 4]
        # deterministic pick: widest by itemsize, name as the tie-break
        # (np dtype comparison is partial across ml_dtypes — never sort
        # dtypes directly)
        return max(low or dts, key=lambda d: (d.itemsize, d.name))

    # -- forward -----------------------------------------------------------
    def gather_leaf(self, shard, meta: LeafMeta):
        return _gather_leaf_op(shard, self.axis_name, meta.shape,
                               meta.dtype, self.weight_gather,
                               self.compression, self.shard_multiple)

    def gather(self, shards: Pytree, meta: Pytree) -> Pytree:
        """Full parameters (model dtype) from the shard pytree. Each leaf
        is an independent all-gather emitted under the ``comm`` monitor
        span — XLA's latency-hiding scheduler overlaps them with
        neighbouring compute; backward is the per-leaf grad
        reduce-scatter straight into shard layout."""
        from apex_tpu.monitor.trace import span

        with span("comm"):
            return jax.tree_util.tree_map(
                self.gather_leaf, shards, meta,
                is_leaf=lambda x: isinstance(x, LeafMeta))

    # -- the fused matmul path (module mode) -------------------------------
    def shard_linear_weight(self, w):
        """Column shard ``(in, out/W)`` of a 2-D weight for
        :meth:`linear` — fp32 master layout, ``out`` divisible by the
        axis size (fail loudly; the flat layout has no such constraint)."""
        if w.ndim != 2:
            raise ValueError(
                f"shard_linear_weight needs a 2-D kernel, got {w.shape}")
        world = _axis_size(self.axis_name)
        if w.shape[-1] % world:
            raise ValueError(
                f"linear weight out dim {w.shape[-1]} not divisible by "
                f"the {self.axis_name} axis size {world}")
        idx = lax.axis_index(self.axis_name)
        n_loc = w.shape[-1] // world
        return lax.dynamic_slice_in_dim(
            w.astype(jnp.float32), idx * n_loc, n_loc, 1)

    def linear(self, x, w_shard, dtype=None):
        """``x @ all_gather(w_shard)`` on the overlapped
        :func:`~apex_tpu.comm.overlap.matmul_param_gather` ring — the
        gather hops hide behind partial GEMMs, backward re-gathers
        (re-materialize) and reduce-scatters dW into the column shard.
        ``w_shard``: fp32 master column shard (from
        :meth:`shard_linear_weight`); ``dtype``: compute dtype (default
        ``x.dtype``)."""
        from apex_tpu.comm.overlap import matmul_param_gather

        dt = x.dtype if dtype is None else dtype
        return matmul_param_gather(x, w_shard.astype(dt),
                                   axis_name=self.axis_name,
                                   bidirectional=self.bidirectional)

    # -- accounting --------------------------------------------------------
    def gather_wire_bytes(self, meta: Pytree, world: int) -> float:
        """Modeled bytes-on-wire per device of one full parameter gather
        (forward leg), same ring model ``comm.accounting`` prices off
        compiled HLO. Static — free to record on the Metrics pipeline."""
        from apex_tpu.fsdp.accounting import param_gather_wire_bytes

        return param_gather_wire_bytes(meta, world, self.weight_gather,
                                       self.shard_multiple)

    def reduce_wire_bytes(self, meta: Pytree, world: int) -> float:
        """Modeled wire bytes of the backward grad reduce-scatter leg."""
        from apex_tpu.comm.collectives import psum_scatter_wire_bytes

        total = 0.0
        for m in jax.tree_util.tree_leaves(
                meta, is_leaf=lambda x: isinstance(x, LeafMeta)):
            total += psum_scatter_wire_bytes(
                m.size, 4, world, self.compression, self.shard_multiple)
        return total

"""FSDP optimizer — Adam on the local shard only, no gather in the step.

The ZeRO-1 optimizers (``contrib/optimizers/distributed_fused_adam.py``)
own the whole reduce-scatter → update → all-gather pipeline. Under FSDP the
first and last legs moved into the model's autodiff (the gather VJP
delivers dp-summed shard grads; the next forward re-gathers), so the
optimizer shrinks to the middle: the shared Adam tail
(``_sharding.adam_shard_update`` — bit-identical math to ZeRO-1, Pallas
``fused_update`` included) over fp32 master/moment shards. The master
shard IS the parameter store; ``hbm_params_bytes`` accounting lives in
``fsdp/accounting.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.contrib.optimizers._sharding import (
    adam_shard_update,
    global_norm_shards,
    local_sq,
)
from apex_tpu.fsdp.core import FSDP
from apex_tpu.parallel.mesh import DP_AXIS

Pytree = Any


class FSDPAdamState(NamedTuple):
    count: jnp.ndarray
    master: Pytree  # fp32 param shards — the canonical parameter store
    mu: Pytree  # fp32 moment shards
    nu: Pytree


@dataclasses.dataclass(frozen=True)
class FSDPAdam:
    """AdamW over FSDP shards. Usage (inside the mesh program) — see
    :class:`apex_tpu.fsdp.FSDP` for the full loop. ``step`` takes the
    shard grads the gather VJP produced (already dp-SUMMED by the
    reduce-scatter) and averages them here, mirroring
    ``DistributedFusedAdam``'s sum-then-divide."""

    fsdp: FSDP = dataclasses.field(default_factory=FSDP)
    lr: float = 1e-3
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    adam_w_mode: bool = True
    max_grad_norm: Optional[float] = None
    fused_update: str = "auto"

    def __post_init__(self):
        from apex_tpu.ops.fused_update import resolve_fused

        resolve_fused(self.fused_update)

    @property
    def axis_name(self) -> str:
        return self.fsdp.axis_name

    # -- state -------------------------------------------------------------
    def init(self, params: Pytree) -> FSDPAdamState:
        """Shard fp32 masters + zero moments from replicated ``params``
        (call inside the mesh program)."""
        master = self.fsdp.shard_params(params)
        return self.init_shards(master)

    def init_shards(self, master: Pytree) -> FSDPAdamState:
        """State from an already-sharded fp32 master pytree (the module
        mode: column shards from :meth:`FSDP.shard_linear_weight` mixed
        with flat shards — the tail math is elementwise, any shard shape
        works)."""
        zeros = jax.tree_util.tree_map(jnp.zeros_like, master)
        return FSDPAdamState(
            count=jnp.zeros((), jnp.int32), master=master, mu=zeros,
            nu=jax.tree_util.tree_map(jnp.zeros_like, master))

    # -- checkpointing (the resilience manifest path) ----------------------
    def state_dict(self, state: FSDPAdamState,
                   params: Optional[Pytree] = None,
                   dp: Optional[int] = None) -> dict:
        """Flat fingerprinted dict via the shared manifest path — the
        fingerprint pins every shard's shape/dtype, so a checkpoint from a
        different dp degree or shard alignment is refused at restore.

        Pass ``params`` (the LOGICAL unsharded params) + ``dp`` to stamp
        the :meth:`elastic_spec` manifest, making the checkpoint
        topology-elastic (restorable at a different dp degree with
        ``allow_reshard=True``). Flat-sharded leaves only — the module
        mode's column shards have no flat-layout spec."""
        from apex_tpu.resilience.checkpoint import state_dict

        elastic = None
        if params is not None:
            if dp is None:
                raise ValueError("state_dict(params=...) needs dp= (the dp "
                                 "degree the shards were built at)")
            elastic = self.elastic_spec(params, dp)
        return state_dict(state, elastic=elastic)

    def load_state_dict(self, template: FSDPAdamState, d: dict,
                        allow_reshard: bool = False) -> FSDPAdamState:
        from apex_tpu.resilience.checkpoint import load_state_dict

        return load_state_dict(template, d, allow_reshard=allow_reshard)

    def elastic_spec(self, params: Pytree, dp: int) -> FSDPAdamState:
        """Per-leaf :class:`~apex_tpu.resilience.reshard.LeafSpec` tree
        matching :meth:`init`'s state: master/moment shards are
        ``dp_flat`` slices of each logical param (size, dp, the FSDP
        shard multiple), ``count`` replicated — same arithmetic ZeRO-1
        uses, so a dp=N FSDP checkpoint re-slices to dp=M exactly."""
        import math

        from apex_tpu.resilience.reshard import dp_flat_spec, replicated_spec

        mult = self.fsdp.shard_multiple
        flat = jax.tree_util.tree_map(
            lambda p: dp_flat_spec(math.prod(jnp.shape(p)), int(dp), mult),
            params)
        return FSDPAdamState(
            count=replicated_spec(), master=flat, mu=flat, nu=flat)

    # -- step --------------------------------------------------------------
    def step(
        self,
        g_shards: Pytree,
        state: FSDPAdamState,
        scale: Optional[jnp.ndarray] = None,
        metrics: Optional[Any] = None,
        meta: Optional[Pytree] = None,
    ):
        """One update on the local shards; returns ``state`` (or
        ``(state, metrics)`` when ``metrics`` is passed).

        ``g_shards``: dp-summed fp32 shard grads from the gather VJP
        (``jax.grad`` of a loss over ``state.master``). ``scale``: AMP
        loss scale to divide out. ``metrics``: a ``monitor.Metrics`` —
        records ``grad_norm``/``param_norm``/``update_norm`` (one stacked
        psum like ZeRO-1) plus, when ``meta`` (the static
        :meth:`FSDP.meta` pytree) is given, the modeled
        ``param_gather_bytes``/``comm_wire_bytes`` and per-chip
        ``hbm_params_bytes`` of this strategy.
        """
        world = lax.axis_size(self.axis_name)
        g_shards = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) / world, g_shards)
        if scale is not None:
            g_shards = jax.tree_util.tree_map(lambda g: g / scale, g_shards)
        gnorm = (global_norm_shards(g_shards, self.axis_name)
                 if self.max_grad_norm is not None or metrics is not None
                 else None)
        if self.max_grad_norm is not None:
            clip = jnp.minimum(1.0, self.max_grad_norm / (gnorm + 1e-6))
            g_shards = jax.tree_util.tree_map(lambda g: g * clip, g_shards)

        count = state.count + 1
        t = count.astype(jnp.float32)
        b1, b2 = self.betas
        c1 = 1.0 - jnp.power(b1, t)
        c2 = 1.0 - jnp.power(b2, t)
        from apex_tpu.ops.fused_update import resolve_fused

        use_fused = resolve_fused(self.fused_update)

        g_l, treedef = jax.tree_util.tree_flatten(g_shards)
        out = [adam_shard_update(
            g, m, v, p, c1, c2, lr=self.lr, betas=self.betas, eps=self.eps,
            weight_decay=self.weight_decay, adam_w_mode=self.adam_w_mode,
            use_fused=use_fused)
            for g, m, v, p in zip(
                g_l, jax.tree_util.tree_leaves(state.mu),
                jax.tree_util.tree_leaves(state.nu),
                jax.tree_util.tree_leaves(state.master))]
        master = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
        mu = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
        nu = jax.tree_util.tree_unflatten(treedef, [o[2] for o in out])
        new_state = FSDPAdamState(count, master, mu, nu)
        if metrics is None:
            return new_state
        delta = jax.tree_util.tree_map(lambda a, b: a - b, master,
                                       state.master)
        both = jnp.sqrt(lax.psum(
            jnp.stack([local_sq(master), local_sq(delta)]), self.axis_name))
        entries = dict(grad_norm=gnorm, param_norm=both[0],
                       update_norm=both[1])
        if meta is not None:
            from apex_tpu.fsdp.accounting import hbm_params_bytes

            gather = self.fsdp.gather_wire_bytes(meta, world)
            entries["param_gather_bytes"] = gather
            entries["comm_wire_bytes"] = (
                gather + self.fsdp.reduce_wire_bytes(meta, world))
            entries["hbm_params_bytes"] = hbm_params_bytes(
                meta, strategy="fsdp", world=world,
                shard_multiple=self.fsdp.shard_multiple)["total"]
        return new_state, metrics.record(**entries)

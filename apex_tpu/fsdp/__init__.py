"""apex_tpu.fsdp — ZeRO-3 parameter sharding on overlapped gather rings.

The third rung of the ZeRO ladder grown from the contrib optimizers:
``parallel.DistributedDataParallel`` replicates everything (stage 0), the
contrib ``DistributedFusedAdam/LAMB`` shard optimizer state (stage 1+2),
and :class:`FSDP` + :class:`FSDPAdam` shard the parameters too — forward
gathers on demand (optionally int8 on the wire), gradients reduce-scatter
straight into shard layout inside autodiff, matmul-adjacent weights ride
``comm.overlap.matmul_param_gather``'s decomposed ppermute ring, and the
optimizer steps only the local shard through the shared Pallas tail.

Configure through :class:`apex_tpu.parallel.ParallelismPlan` (preset
``"fsdp"``/``"fsdp+tp"``) rather than wiring by hand.
"""

from apex_tpu.fsdp.accounting import (  # noqa: F401
    fsdp_step_wire_bytes,
    hbm_params_bytes,
    hbm_reduction,
    param_gather_wire_bytes,
)
from apex_tpu.fsdp.core import FSDP, LeafMeta  # noqa: F401
from apex_tpu.fsdp.optim import FSDPAdam, FSDPAdamState  # noqa: F401

__all__ = [
    "FSDP",
    "FSDPAdam",
    "FSDPAdamState",
    "LeafMeta",
    "fsdp_step_wire_bytes",
    "hbm_params_bytes",
    "hbm_reduction",
    "param_gather_wire_bytes",
]

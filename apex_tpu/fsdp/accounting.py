"""FSDP byte accounting — HBM and wire models for the ZeRO ladder.

Same discipline as ``comm.accounting``: every number here is computed from
static shapes under the same ring model the HLO pricer reads off compiled
programs, so benchmarks and tests can assert the memory story instead of
narrating it. ``hbm_params_bytes`` is the headline (the acceptance metric
of the FSDP PR): per-chip bytes attributable to parameters + gradients +
optimizer state — the terms parameter sharding moves — for each strategy
on the ladder:

``ddp``
    Replicated everything: model-dtype params + grads, fp32 Adam moments,
    plus an fp32 master copy when the model dtype is narrower than fp32
    (the amp-O2 contract).
``zero1``
    ``DistributedFusedAdam``: params + grads still replicated full-model
    (model dtype; the reduce-scatter consumes fp32 casts transiently),
    fp32 master + moments sharded 1/dp.
``fsdp``
    Everything sharded: fp32 master+moments shards ARE the parameter
    store (no replicated copy), grads arrive as fp32 shards, and the only
    full-model-dtype bytes are the transient gather working set (reported
    separately as ``gather_workspace_bytes`` — bounded by the largest
    leaf, not the model).

Activations are deliberately out of scope (unchanged by the ZeRO stage).

``hbm_model_bytes`` / ``hbm_serve_bytes`` are the INFERENCE-mode siblings
(``apex_tpu.serve.sharded``): params + KV cache, no grads or optimizer
state — the terms a serving chip actually holds — modeled per residency
strategy so a plan can prove which strategies fit a chip budget before
any program compiles.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import numpy as np

Pytree = Any

STRATEGIES = ("ddp", "zero1", "fsdp")
# inference residency strategies (serve.sharded): "single" is the
# unsharded baseline the >1-chip-HBM headline is proven against
SERVE_STRATEGIES = ("single", "tp", "pp", "fsdp")


def _leaf_meta(tree: Pytree):
    """(elements, model itemsize) per leaf — accepts a params pytree or an
    ``FSDP.meta`` pytree (LeafMeta leaves)."""
    from apex_tpu.fsdp.core import LeafMeta

    out = []
    for x in jax.tree_util.tree_leaves(
            tree, is_leaf=lambda v: isinstance(v, LeafMeta)):
        if isinstance(x, LeafMeta):
            out.append((x.size, np.dtype(x.dtype).itemsize))
        else:
            n = 1
            for d in jax.numpy.shape(x):
                n *= d
            out.append((n, np.dtype(jax.numpy.result_type(x)).itemsize))
    return out


def _shard_elems(n: int, world: int, multiple: int) -> int:
    from apex_tpu.contrib.optimizers._sharding import shard_size

    return shard_size(n, world, multiple)


def hbm_params_bytes(params_or_meta: Pytree, *, strategy: str, world: int,
                     shard_multiple: int = 1) -> Dict[str, float]:
    """Modeled per-chip param+grad+optimizer-state HBM for one strategy.

    Returns ``{"params_bytes", "grads_bytes", "opt_state_bytes",
    "gather_workspace_bytes", "total"}`` (floats; ``total`` excludes the
    transient gather workspace, which is reported so callers can see it
    stays leaf-sized, not model-sized).
    """
    if strategy not in STRATEGIES:
        raise ValueError(
            f"strategy must be one of {STRATEGIES}, got {strategy!r}")
    leaves = _leaf_meta(params_or_meta)
    params = grads = opt = workspace = 0.0
    for n, isz in leaves:
        k = _shard_elems(n, world, shard_multiple)
        if strategy == "ddp":
            params += n * isz
            grads += n * isz
            opt += n * 8  # fp32 mu+nu (FusedAdam)
            if isz < 4:
                opt += n * 4  # amp fp32 master
        elif strategy == "zero1":
            params += n * isz
            grads += n * isz
            opt += k * 12  # fp32 master+mu+nu shards
        else:  # fsdp
            grads += k * 4  # fp32 shard grads off the reduce-scatter
            opt += k * 12  # fp32 master+mu+nu shards (the param store)
            workspace = max(workspace, 2.0 * n * isz)
    return {
        "params_bytes": params,
        "grads_bytes": grads,
        "opt_state_bytes": opt,
        "gather_workspace_bytes": workspace,
        "total": params + grads + opt,
    }


def hbm_model_bytes(params_or_meta: Pytree) -> float:
    """Unsharded model-dtype parameter bytes — the "does it fit one
    chip" numerator of the serve-plan headline (``engine.stats()``
    surfaces it as ``hbm_model_bytes``; a model is plan-worthy exactly
    when this exceeds the chip's budget minus its KV pool)."""
    return float(sum(n * isz for n, isz in _leaf_meta(params_or_meta)))


def hbm_serve_bytes(params_or_meta: Pytree, *, strategy: str, world: int,
                    kv_bytes: float = 0.0, num_layers: Optional[int] = None,
                    shard_multiple: int = 1) -> Dict[str, float]:
    """Modeled per-chip HBM for one SERVE residency strategy — params +
    KV, NO grads or optimizer state (inference holds neither).

    ``params_or_meta``: the full params pytree (or an ``FSDP.meta``
    mirror). When it is a dict exposing the ``standalone_gpt`` structure
    (a ``"layers"`` key), the stacked layer weights are modeled apart
    from the embed/head leaves — ``pp`` and ``fsdp`` shard only the
    layer stack (embed/head stay replicated: every stage embeds or
    samples eventually, and the fsdp gather ring would pay the vocab
    table's full gather every step). ``kv_bytes``: this chip's KV pool
    bytes — pass the LOCAL pool (``kv_cache_bytes`` of the per-chip
    config); the model adds it verbatim. ``num_layers``: layer count of
    the stacked leaves — sizes the per-LAYER fsdp gather workspace
    (omitted: the whole stacked leaf is assumed gathered at once).

    Returns ``{"params_bytes", "kv_bytes", "gather_workspace_bytes",
    "total"}``; ``total`` excludes the transient gather workspace (same
    reporting convention as :func:`hbm_params_bytes`).
    """
    if strategy not in SERVE_STRATEGIES:
        raise ValueError(
            f"strategy must be one of {SERVE_STRATEGIES}, got {strategy!r}")
    if isinstance(params_or_meta, dict) and "layers" in params_or_meta:
        layer_leaves = _leaf_meta(params_or_meta["layers"])
        other_leaves = _leaf_meta({k: v for k, v in params_or_meta.items()
                                   if k != "layers"})
    else:
        layer_leaves = _leaf_meta(params_or_meta)
        other_leaves = []
    layers_total = sum(n * isz for n, isz in layer_leaves)
    other_total = sum(n * isz for n, isz in other_leaves)
    workspace = 0.0
    if strategy == "single":
        params = layers_total + other_total
    elif strategy == "tp":
        # every megatron dim sharded (embed/head vocab-sharded too);
        # replicated LN/bias leaves are noise against the kernels
        params = (layers_total + other_total) / world
    elif strategy == "pp":
        params = layers_total / world + other_total
    else:  # fsdp
        params = other_total
        for n, isz in layer_leaves:
            params += _shard_elems(n, world, shard_multiple) * isz
            per_layer = n * isz / (num_layers or 1)
            workspace = max(workspace, 2.0 * per_layer)
    return {
        "params_bytes": params,
        "kv_bytes": float(kv_bytes),
        "gather_workspace_bytes": workspace,
        "total": params + float(kv_bytes),
    }


def hbm_reduction(params_or_meta: Pytree, *, world: int,
                  baseline: str = "ddp",
                  shard_multiple: int = 1) -> float:
    """``baseline_total / fsdp_total`` — the headline drop factor."""
    base = hbm_params_bytes(params_or_meta, strategy=baseline, world=world,
                            shard_multiple=shard_multiple)["total"]
    ours = hbm_params_bytes(params_or_meta, strategy="fsdp", world=world,
                            shard_multiple=shard_multiple)["total"]
    return base / ours if ours else float("inf")


def param_gather_wire_bytes(meta: Pytree, world: int,
                            weight_gather=None,
                            shard_multiple: int = 1) -> float:
    """Modeled per-device wire bytes of ONE full parameter gather (the
    FSDP forward leg): per leaf, a tiled all-gather of the model-dtype
    shard — ``k·isz·(W-1)`` — or, with a quantized codec, packed codes
    (1 B/element int8, 0.5 B/element nibble-packed int4) + fp32 block
    scales. Matches what ``comm.accounting.collective_report`` prices on
    the compiled program (``all_gather_wire_bytes`` convention: result
    bytes × (W-1)/W)."""
    from apex_tpu.comm.collectives import all_gather_wire_bytes

    total = 0.0
    for n, isz in _leaf_meta(meta):
        if world <= 1:
            continue
        k = _shard_elems(n, world, shard_multiple)
        if weight_gather is not None and weight_gather.compresses(n):
            # packed codes + fp32 scales, both gathered tiled; the codec's
            # payload_bytes is the per-pass unit, here gathered ring-style
            total += (weight_gather.payload_bytes(k * world)
                      * (world - 1) / world)
        else:
            total += all_gather_wire_bytes(k * world, isz, world)
    return total


def fsdp_step_wire_bytes(meta: Pytree, world: int,
                         compression: Optional[Any] = None,
                         weight_gather: Optional[Any] = None,
                         shard_multiple: int = 1,
                         remat_gathers: int = 1) -> float:
    """Whole-step wire model: ``remat_gathers`` forward gathers (2 under
    full remat — the backward replays the gather: the FSDP re-materialize)
    plus the fp32 grad reduce-scatter leg."""
    from apex_tpu.comm.collectives import psum_scatter_wire_bytes

    total = param_gather_wire_bytes(
        meta, world, weight_gather, shard_multiple) * max(1, remat_gathers)
    for n, _ in _leaf_meta(meta):
        total += psum_scatter_wire_bytes(n, 4, world, compression,
                                         shard_multiple)
    return total

"""Deterministic fault injection — the test harness the recovery paths
are proven against.

Every guard/checkpoint/preemption claim in this subsystem is only as good
as the failure it survived in CI. This module provides the failures, all
deterministic (seedless, step-keyed, byte-exact) so a recovery test is
reproducible:

* :func:`inject_nonfinite` — in-graph NaN/Inf poisoning of a pytree at an
  exact step (a ``jnp.where`` on the step counter: jit-stable, no
  recompile, no host sync — the injection itself must not perturb the run
  it corrupts).
* :func:`corrupt_file` / :func:`corrupt_checkpoint` — host-side torn-write
  and bit-rot simulation: truncate, flip bytes, or delete members of a
  published checkpoint so ``latest_valid()`` has something real to reject.
* :class:`PreemptionAtStep` — fires a
  :class:`~apex_tpu.resilience.preemption.PreemptionHandler` at step k
  through the exact code path the SIGTERM handler uses.

Used by ``tests/test_resilience.py``; importable by users who want to
chaos-test their own train loops.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.resilience.preemption import PreemptionHandler

Pytree = Any


def inject_nonfinite(
    tree: Pytree,
    step: jnp.ndarray,
    at_step: int,
    mode: str = "nan",
    leaf_index: Optional[int] = 0,
) -> Pytree:
    """Return ``tree`` with non-finite values injected iff ``step ==
    at_step`` (both may be traced). ``mode``: ``"nan"`` or ``"inf"``.
    ``leaf_index`` poisons one leaf (default: the first inexact one);
    ``None`` poisons every inexact leaf. Exact-dtype leaves (ints, bools)
    pass through — they cannot hold a NaN."""
    if mode not in ("nan", "inf"):
        raise ValueError(f"mode must be 'nan' or 'inf', got {mode!r}")
    poison = jnp.float32(jnp.nan if mode == "nan" else jnp.inf)
    hit = jnp.asarray(step) == at_step
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    inexact = [i for i, x in enumerate(leaves)
               if jnp.issubdtype(jnp.result_type(x), jnp.inexact)]
    if not inexact:
        return tree
    targets = set(inexact) if leaf_index is None \
        else {inexact[leaf_index % len(inexact)]}
    out = [
        jnp.where(hit, poison.astype(x.dtype), x) if i in targets else x
        for i, x in enumerate(leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def corrupt_file(path: str, mode: str = "truncate", nbytes: int = 64) -> None:
    """Simulate a torn write / bit rot on one file. ``mode``:

    * ``"truncate"`` — drop the final ``nbytes`` (torn tail);
    * ``"flip"`` — XOR ``nbytes`` bytes in the middle (silent bit rot);
    * ``"delete"`` — remove the file (lost member).
    """
    if mode == "delete":
        os.remove(path)
        return
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(0, size - nbytes))
    elif mode == "flip":
        with open(path, "r+b") as f:
            off = max(0, size // 2 - nbytes // 2)
            f.seek(off)
            chunk = f.read(min(nbytes, size - off))
            f.seek(off)
            f.write(bytes(b ^ 0xFF for b in chunk))
    else:
        raise ValueError(
            f"mode must be 'truncate', 'flip' or 'delete', got {mode!r}")


def _payload_files(ckpt_dir: str) -> list:
    """Data files of a published checkpoint, largest first (manifest and
    zero-byte markers excluded) — the realistic bit-rot targets."""
    from apex_tpu.resilience.checkpoint import MANIFEST_NAME

    out = []
    for root, _, files in os.walk(ckpt_dir):
        for name in files:
            if name == MANIFEST_NAME:
                continue
            p = os.path.join(root, name)
            if os.path.getsize(p) > 0:
                out.append(p)
    return sorted(out, key=os.path.getsize, reverse=True)


def corrupt_checkpoint(ckpt_dir: str, part: str = "payload",
                       mode: str = "truncate",
                       shard: Optional[int] = None) -> str:
    """Corrupt one member of a published checkpoint directory so that
    verification must fail. ``part``: ``"payload"`` (largest data file) or
    ``"manifest"``. ``shard``: for a PR-9 SHARDED checkpoint, target
    process ``shard``'s ``shard-p{K}/`` subdirectory instead of the top
    level — its payload (or its per-shard manifest) is corrupted, and
    ``verify()``'s cross-shard crc sweep must reject the whole step so
    ``latest_valid()`` skips it. Loud ``FileNotFoundError`` when the
    checkpoint has no such shard dir (a plain checkpoint, or a dp degree
    that never had that process) — an undetectable fault configuration is
    a test bug, not a no-op. Returns the path corrupted."""
    from apex_tpu.resilience.checkpoint import MANIFEST_NAME

    if shard is not None:
        sub = os.path.join(ckpt_dir, f"shard-p{int(shard)}")
        if not os.path.isdir(sub):
            raise FileNotFoundError(
                f"{ckpt_dir} has no shard-p{int(shard)}/ — not a sharded "
                "checkpoint, or no such process index; this fault would "
                "be undetectable")
        ckpt_dir = sub
    if part == "manifest":
        p = os.path.join(ckpt_dir, MANIFEST_NAME)
        if mode == "flip":
            # JSON-breaking flip (a bitwise flip could stay parseable)
            with open(p) as f:
                text = f.read()
            with open(p, "w") as f:
                f.write(text[: max(1, len(text) // 2)])
        else:
            corrupt_file(p, mode)
        return p
    if part != "payload":
        raise ValueError(f"part must be 'payload' or 'manifest', got "
                         f"{part!r}")
    files = _payload_files(ckpt_dir)
    if not files:
        raise FileNotFoundError(f"no payload files under {ckpt_dir}")
    corrupt_file(files[0], mode)
    return files[0]


def make_manifest_lie(ckpt_dir: str, leaf: int = 0) -> None:
    """Silent-corruption variant: leave the payload intact but falsify one
    leaf's crc32 in the manifest — models a writer that recorded the wrong
    bytes. ``verify()`` must catch the mismatch."""
    from apex_tpu.resilience.checkpoint import MANIFEST_NAME

    p = os.path.join(ckpt_dir, MANIFEST_NAME)
    with open(p) as f:
        m = json.load(f)
    m["leaves"][leaf]["crc32"] ^= 0x5A5A5A5A
    with open(p, "w") as f:
        json.dump(m, f)


class PreemptionAtStep:
    """Deterministically preempt at step k::

        pre = PreemptionHandler(install=False)
        chaos = PreemptionAtStep(pre, at_step=7)
        for step in range(n):
            ...
            chaos.maybe_fire(step)
            if pre.sync_save_step(step) is not None:
                save_and_exit()
    """

    def __init__(self, handler: PreemptionHandler, at_step: int):
        self.handler = handler
        self.at_step = int(at_step)
        self.fired = False

    def maybe_fire(self, step: int) -> bool:
        if not self.fired and int(step) >= self.at_step:
            self.fired = True
            self.handler.trigger()
        return self.fired


# -- the step-keyed training fault plan ------------------------------------
#
# ``serve/cluster/chaos.py`` gave the SERVING cluster its ordered,
# deterministic fault plan; this is the same discipline for the training
# supervisor. Every fault is keyed on the step counter — no randomness, no
# wall time — and an undetectable configuration fails loudly at fire time
# instead of silently doing nothing.


@dataclasses.dataclass(frozen=True)
class KillRankAtStep:
    """Fail-stop ``rank`` at step ``at_step``: the supervisor exits
    IMMEDIATELY without saving (no grace window — harsher than
    preemption), leaving a restart manifest that points at the last
    already-durable checkpoint. The recovery claim under test is the
    elastic resume: re-launch (possibly at a different dp degree) +
    :meth:`~apex_tpu.resilience.supervisor.TrainSupervisor.resume`."""

    at_step: int
    rank: int = 0


@dataclasses.dataclass(frozen=True)
class CorruptShardFile:
    """Bit-rot process ``shard``'s ``shard-p{K}/`` member of the latest
    valid checkpoint at step ``at_step`` (via :func:`corrupt_checkpoint`
    with ``shard=``): ``verify()``'s cross-shard crc sweep must reject
    the step and ``latest_valid()`` must fall back to the previous one."""

    at_step: int
    shard: int = 0
    part: str = "payload"
    mode: str = "flip"


@dataclasses.dataclass(frozen=True)
class SlowRank:
    """Inflate ``rank``'s step time by ``factor`` for ``for_steps`` steps
    starting at ``at_step`` — the straggler the robust-z sentinel must
    flag (and a clean fleet must not)."""

    at_step: int
    rank: int
    factor: float = 4.0
    for_steps: int = 1


_TRAIN_FAULT_TYPES = (KillRankAtStep, CorruptShardFile, SlowRank)


class TrainChaosPlan:
    """An ordered, deterministic training fault plan (the ``ClusterChaos``
    architecture). The supervisor calls :meth:`apply` at the top of every
    step; each fault fires exactly once, at the first step >= its
    ``at_step``. ``fired`` keeps the (step, fault) ledger for the chaos
    record."""

    def __init__(self, faults: Sequence[Any]):
        for f in faults:
            if not isinstance(f, _TRAIN_FAULT_TYPES):
                raise TypeError(f"not a training fault: {f!r}")
            if f.at_step < 0:
                raise ValueError(f"at_step must be >= 0: {f!r}")
        self._pending: List[Any] = sorted(faults, key=lambda f: f.at_step)
        self.fired: List[Tuple[int, Any]] = []

    @property
    def pending(self) -> int:
        return len(self._pending)

    def apply(self, supervisor, step_idx: int) -> List[Any]:
        """Fire every not-yet-fired fault whose ``at_step`` has arrived;
        returns the faults fired this step."""
        fired_now: List[Any] = []
        while self._pending and self._pending[0].at_step <= step_idx:
            f = self._pending.pop(0)
            self._fire(supervisor, f, step_idx)
            self.fired.append((step_idx, f))
            fired_now.append(f)
        return fired_now

    def _fire(self, supervisor, f: Any, step_idx: int) -> None:
        if isinstance(f, KillRankAtStep):
            supervisor.kill()
        elif isinstance(f, CorruptShardFile):
            mgr = getattr(supervisor, "manager", None)
            latest = mgr.latest_valid() if mgr is not None else None
            if latest is None:
                # corrupting nothing proves nothing — fail the plan loudly
                raise ValueError(
                    "CorruptShardFile fired but no valid checkpoint has "
                    "been published yet — schedule it after a save_freq "
                    "boundary")
            corrupt_checkpoint(latest, part=f.part, mode=f.mode,
                               shard=f.shard)
        elif isinstance(f, SlowRank):
            supervisor.inject_slow(f.rank, f.factor, f.for_steps)

    def summary(self) -> List[Dict[str, Any]]:
        """JSON-ready ledger of fired faults (for the bench record)."""
        return [{"step": step, "fault": type(f).__name__,
                 **dataclasses.asdict(f)} for step, f in self.fired]

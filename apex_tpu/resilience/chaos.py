"""Deterministic fault injection — the test harness the recovery paths
are proven against.

Every guard/checkpoint/preemption claim in this subsystem is only as good
as the failure it survived in CI. This module provides the failures, all
deterministic (seedless, step-keyed, byte-exact) so a recovery test is
reproducible:

* :func:`inject_nonfinite` — in-graph NaN/Inf poisoning of a pytree at an
  exact step (a ``jnp.where`` on the step counter: jit-stable, no
  recompile, no host sync — the injection itself must not perturb the run
  it corrupts).
* :func:`corrupt_file` / :func:`corrupt_checkpoint` — host-side torn-write
  and bit-rot simulation: truncate, flip bytes, or delete members of a
  published checkpoint so ``latest_valid()`` has something real to reject.
* :class:`PreemptionAtStep` — fires a
  :class:`~apex_tpu.resilience.preemption.PreemptionHandler` at step k
  through the exact code path the SIGTERM handler uses.

Used by ``tests/test_resilience.py``; importable by users who want to
chaos-test their own train loops.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp

from apex_tpu.resilience.preemption import PreemptionHandler

Pytree = Any


def inject_nonfinite(
    tree: Pytree,
    step: jnp.ndarray,
    at_step: int,
    mode: str = "nan",
    leaf_index: Optional[int] = 0,
) -> Pytree:
    """Return ``tree`` with non-finite values injected iff ``step ==
    at_step`` (both may be traced). ``mode``: ``"nan"`` or ``"inf"``.
    ``leaf_index`` poisons one leaf (default: the first inexact one);
    ``None`` poisons every inexact leaf. Exact-dtype leaves (ints, bools)
    pass through — they cannot hold a NaN."""
    if mode not in ("nan", "inf"):
        raise ValueError(f"mode must be 'nan' or 'inf', got {mode!r}")
    poison = jnp.float32(jnp.nan if mode == "nan" else jnp.inf)
    hit = jnp.asarray(step) == at_step
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    inexact = [i for i, x in enumerate(leaves)
               if jnp.issubdtype(jnp.result_type(x), jnp.inexact)]
    if not inexact:
        return tree
    targets = set(inexact) if leaf_index is None \
        else {inexact[leaf_index % len(inexact)]}
    out = [
        jnp.where(hit, poison.astype(x.dtype), x) if i in targets else x
        for i, x in enumerate(leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, out)


def corrupt_file(path: str, mode: str = "truncate", nbytes: int = 64) -> None:
    """Simulate a torn write / bit rot on one file. ``mode``:

    * ``"truncate"`` — drop the final ``nbytes`` (torn tail);
    * ``"flip"`` — XOR ``nbytes`` bytes in the middle (silent bit rot);
    * ``"delete"`` — remove the file (lost member).
    """
    if mode == "delete":
        os.remove(path)
        return
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(0, size - nbytes))
    elif mode == "flip":
        with open(path, "r+b") as f:
            off = max(0, size // 2 - nbytes // 2)
            f.seek(off)
            chunk = f.read(min(nbytes, size - off))
            f.seek(off)
            f.write(bytes(b ^ 0xFF for b in chunk))
    else:
        raise ValueError(
            f"mode must be 'truncate', 'flip' or 'delete', got {mode!r}")


def _payload_files(ckpt_dir: str) -> list:
    """Data files of a published checkpoint, largest first (manifest and
    zero-byte markers excluded) — the realistic bit-rot targets."""
    from apex_tpu.resilience.checkpoint import MANIFEST_NAME

    out = []
    for root, _, files in os.walk(ckpt_dir):
        for name in files:
            if name == MANIFEST_NAME:
                continue
            p = os.path.join(root, name)
            if os.path.getsize(p) > 0:
                out.append(p)
    return sorted(out, key=os.path.getsize, reverse=True)


def corrupt_checkpoint(ckpt_dir: str, part: str = "payload",
                       mode: str = "truncate") -> str:
    """Corrupt one member of a published checkpoint directory so that
    verification must fail. ``part``: ``"payload"`` (largest data file) or
    ``"manifest"``. Returns the path corrupted."""
    from apex_tpu.resilience.checkpoint import MANIFEST_NAME

    if part == "manifest":
        p = os.path.join(ckpt_dir, MANIFEST_NAME)
        if mode == "flip":
            # JSON-breaking flip (a bitwise flip could stay parseable)
            with open(p) as f:
                text = f.read()
            with open(p, "w") as f:
                f.write(text[: max(1, len(text) // 2)])
        else:
            corrupt_file(p, mode)
        return p
    if part != "payload":
        raise ValueError(f"part must be 'payload' or 'manifest', got "
                         f"{part!r}")
    files = _payload_files(ckpt_dir)
    if not files:
        raise FileNotFoundError(f"no payload files under {ckpt_dir}")
    corrupt_file(files[0], mode)
    return files[0]


def make_manifest_lie(ckpt_dir: str, leaf: int = 0) -> None:
    """Silent-corruption variant: leave the payload intact but falsify one
    leaf's crc32 in the manifest — models a writer that recorded the wrong
    bytes. ``verify()`` must catch the mismatch."""
    from apex_tpu.resilience.checkpoint import MANIFEST_NAME

    p = os.path.join(ckpt_dir, MANIFEST_NAME)
    with open(p) as f:
        m = json.load(f)
    m["leaves"][leaf]["crc32"] ^= 0x5A5A5A5A
    with open(p, "w") as f:
        json.dump(m, f)


class PreemptionAtStep:
    """Deterministically preempt at step k::

        pre = PreemptionHandler(install=False)
        chaos = PreemptionAtStep(pre, at_step=7)
        for step in range(n):
            ...
            chaos.maybe_fire(step)
            if pre.sync_save_step(step) is not None:
                save_and_exit()
    """

    def __init__(self, handler: PreemptionHandler, at_step: int):
        self.handler = handler
        self.at_step = int(at_step)
        self.fired = False

    def maybe_fire(self, step: int) -> bool:
        if not self.fired and int(step) >= self.at_step:
            self.fired = True
            self.handler.trigger()
        return self.fired

"""In-graph anomaly guard — skip / rollback / halt on non-finite steps.

Reference context: the only fault tolerance in the reference stack is the
loss scaler's overflow skip (``apex/amp/scaler.py:197-217`` — on ``found_inf``
the patched ``optimizer.step`` is a no-op and the scale halves). That guards
exactly one failure mode (fp16 overflow) at exactly one point (post-backward).
At pod scale transient numeric blowups also arrive through data corruption,
flaky interconnect reductions, and diverging optimizer state — and a single
NaN that reaches the params is permanent: every later step is NaN.

This module generalizes the scaler's skip into a policy-driven ladder that
runs *inside* the jitted train step (no host sync, ``jnp.where`` guards so
the step shape is static and donation still works):

* **skip** — the scaler's move: keep the pre-step state, drop the update.
* **rollback** — restore a last-good snapshot of the train state carried
  through the step as part of :class:`GuardState` (one extra copy of the
  state). Skip handles a bad *update*; rollback handles bad *state* — e.g.
  a NaN that already reached the params through an unguarded path. The
  snapshot deliberately lags the live state by one accepted step: a clean
  step refreshes it to the state its own finite loss/grads were computed
  from, so poison that slips past one step's detectors cannot enter the
  snapshot before the next step's checks expose it.
* **halt** — raise host-side via :meth:`AnomalyGuard.raise_if_halted` (and
  optionally log through a ``jax.debug.callback``): the run is not making
  progress and a human (or the preemption layer) should take over.

Escalation: ``skip_budget`` consecutive bad steps are skipped, then each
further bad step rolls back; ``rollback_budget`` consecutive rollbacks
without an intervening clean step escalate to halt. ``on_anomaly`` picks
the entry rung (``"skip"`` walks the whole ladder; ``"rollback"`` starts at
rollback; ``"halt"`` halts on the first anomaly).

Telemetry rides the PR-2 monitor pipeline: :meth:`AnomalyGuard.check` and
:meth:`AnomalyGuard.apply` accumulate ``nonfinite_grads_total`` /
``nonfinite_loss_total`` / ``guard_skips_total`` / ``rollbacks_total``
counters into a :class:`apex_tpu.monitor.Metrics` threaded through the step.

Typical wiring (composes with the AMP scaler — the guard consumes the same
``found_inf`` the scaler derives, so an overflow spends guard budget too)::

    guard = AnomalyGuard(GuardPolicy(on_anomaly="skip", skip_budget=3))
    gstate = guard.init(train_state)

    @jax.jit
    def step(train_state, gstate, metrics, batch):
        proposed, grads, loss = update(train_state, batch)
        bad, metrics = guard.check(loss=loss, grads=grads, metrics=metrics)
        train_state, gstate, metrics = guard.apply(
            gstate, bad, proposed, train_state, metrics=metrics)
        return train_state, gstate, metrics

    for batch in data:
        train_state, gstate, metrics = step(train_state, gstate, metrics, b)
        guard.raise_if_halted(gstate)     # cheap: one scalar device read
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

Pytree = Any

_ACTIONS = ("skip", "rollback", "halt")


class AnomalyHalted(RuntimeError):
    """Raised host-side when the guard escalated to halt."""


@dataclasses.dataclass(frozen=True)
class GuardPolicy:
    """Static anomaly policy (python-level config, never traced).

    ``on_anomaly``: entry rung of the skip→rollback→halt ladder.
    ``skip_budget``: consecutive bad steps absorbed by skipping before the
    ladder escalates to rollback (ignored when ``on_anomaly != "skip"``).
    ``rollback_budget``: consecutive rollbacks (no clean step between)
    before the ladder escalates to halt.
    ``halt_callback``: also fire a ``jax.debug.callback`` that logs the
    halt from inside the graph (host-visible even if the driver loop never
    calls :meth:`AnomalyGuard.raise_if_halted`).
    """

    on_anomaly: str = "skip"
    skip_budget: int = 3
    rollback_budget: int = 2
    halt_callback: bool = False

    def __post_init__(self):
        if self.on_anomaly not in _ACTIONS:
            raise ValueError(
                f"on_anomaly must be one of {_ACTIONS}, got "
                f"{self.on_anomaly!r}")
        if self.skip_budget < 0 or self.rollback_budget < 0:
            raise ValueError("budgets must be >= 0")


class GuardState(NamedTuple):
    """Guard carry — a pytree threaded through the jitted step.

    ``snapshot`` is the last-good copy of the guarded train state (present
    only when rollback is reachable under the policy, else an empty tuple —
    no memory cost for pure-skip guards).
    """

    consecutive_bad: jnp.ndarray  # i32 — bad steps since last clean one
    consecutive_rollbacks: jnp.ndarray  # i32 — rollbacks since last clean
    halted: jnp.ndarray  # f32 0/1 — latched once set
    bad_total: jnp.ndarray  # f32 — lifetime anomaly count
    snapshot: Pytree


def nonfinite_count(tree: Pytree) -> jnp.ndarray:
    """Number of non-finite scalars across every leaf of ``tree`` (f32 so
    it can ride a psum / a Metrics). The per-leaf ``isfinite`` reductions
    fuse into whatever sweep already reads the leaves — same fusion the
    scaler's overflow check rides."""
    leaves = [x for x in jax.tree_util.tree_leaves(tree)
              if jnp.issubdtype(jnp.result_type(x), jnp.inexact)]
    if not leaves:
        return jnp.float32(0.0)
    # isfinite on the NATIVE dtype — downcasting an f64 leaf to f32 first
    # would turn large finite values into inf and flag a healthy step
    return sum(jnp.sum(~jnp.isfinite(x)).astype(jnp.float32)
               for x in leaves)


class AnomalyGuard:
    """Pure methods over :class:`GuardState` for one :class:`GuardPolicy`
    (the loss-scaler architecture: static config object, explicit state)."""

    def __init__(self, policy: Optional[GuardPolicy] = None):
        self.policy = policy or GuardPolicy()

    # -- state -------------------------------------------------------------
    def init(self, train_state: Optional[Pytree] = None) -> GuardState:
        """Build the initial carry. Pass the train state iff the policy can
        reach rollback — the snapshot starts as a copy of it."""
        if self._rollback_reachable() and train_state is None:
            raise ValueError(
                f"policy {self.policy.on_anomaly!r} can reach rollback: "
                "init(train_state) needs the state to snapshot")
        snap = () if not self._rollback_reachable() else \
            jax.tree_util.tree_map(jnp.asarray, train_state)
        return GuardState(
            consecutive_bad=jnp.asarray(0, jnp.int32),
            consecutive_rollbacks=jnp.asarray(0, jnp.int32),
            halted=jnp.asarray(0.0, jnp.float32),
            bad_total=jnp.asarray(0.0, jnp.float32),
            snapshot=snap)

    def _rollback_reachable(self) -> bool:
        return self.policy.on_anomaly in ("skip", "rollback")

    # -- detection ---------------------------------------------------------
    def check(
        self,
        *,
        loss: Optional[jnp.ndarray] = None,
        grads: Optional[Pytree] = None,
        updates: Optional[Pytree] = None,
        params: Optional[Pytree] = None,
        found_inf: Optional[jnp.ndarray] = None,
        metrics: Optional[Any] = None,
        axis_names: Optional[Union[str, Sequence[str]]] = None,
    ) -> Union[jnp.ndarray, Tuple[jnp.ndarray, Any]]:
        """Non-finite detection over whatever is passed; returns a f32 0/1
        ``bad`` flag (and the updated Metrics when one is given).

        ``found_inf`` is the AMP scaler's overflow flag
        (:meth:`apex_tpu.amp.LossScaler.unscale` output) — passing it makes
        an fp16 overflow spend the same guard budget as any other anomaly,
        so the scaler's skip and the guard's ladder agree on what a bad
        step is. Metrics counters accumulated: ``nonfinite_loss_total``,
        ``nonfinite_grads_total``, ``nonfinite_updates_total``,
        ``nonfinite_params_total``, ``anomalies_total``.

        ``axis_names``: mesh axis name(s) to max-reduce every flag over
        BEFORE the counters are accumulated. Under SPMD this is required
        when a metrics is passed: a rank-local anomaly (corrupt shard of
        the batch) must update the replicated-declared counters on every
        rank, not just the one that saw it — and the returned ``bad`` is
        then already rank-uniform, so a separate :meth:`all_reduce_bad`
        is unnecessary.
        """
        flags = {}
        if loss is not None:
            flags["nonfinite_loss_total"] = nonfinite_count(loss)
        if grads is not None:
            flags["nonfinite_grads_total"] = nonfinite_count(grads)
        if updates is not None:
            flags["nonfinite_updates_total"] = nonfinite_count(updates)
        if params is not None:
            flags["nonfinite_params_total"] = nonfinite_count(params)
        if axis_names is not None:
            flags = {k: jax.lax.pmax(v, axis_names)
                     for k, v in flags.items()}
        bad = jnp.float32(0.0)
        for v in flags.values():
            bad = jnp.maximum(bad, (v > 0).astype(jnp.float32))
        if found_inf is not None:
            fi = (jnp.asarray(found_inf) > 0).astype(jnp.float32)
            if axis_names is not None:
                fi = jax.lax.pmax(fi, axis_names)
            bad = jnp.maximum(bad, fi)
        if metrics is not None:
            counters = {k: (v > 0).astype(jnp.float32)
                        for k, v in flags.items()}
            counters["anomalies_total"] = (bad > 0).astype(jnp.float32)
            return bad, metrics.accumulate(**counters)
        return bad

    @staticmethod
    def all_reduce_bad(bad: jnp.ndarray,
                       axis_names: Union[str, Sequence[str]]) -> jnp.ndarray:
        """Max-reduce the anomaly flag across mesh axes so every rank takes
        the same branch (the ``LossScaler.all_reduce_found_inf`` move — a
        rank-local skip under SPMD would desynchronize the replicas)."""
        return jax.lax.pmax(bad, axis_names)

    # -- application -------------------------------------------------------
    def apply(
        self,
        gstate: GuardState,
        bad: jnp.ndarray,
        proposed: Pytree,
        previous: Pytree,
        metrics: Optional[Any] = None,
    ) -> Tuple[Pytree, GuardState, Any]:
        """Resolve one step: pick between ``proposed`` (the post-update
        train state), ``previous`` (pre-update — the skip target) and the
        carried snapshot (the rollback target), and advance the ladder.

        Everything is ``jnp.where``-guarded: both branches are computed,
        the select fuses, the step stays a single static program (the
        ``_guard_tree`` pattern ``amp.apply_grads`` uses). Returns
        ``(train_state, new_gstate, metrics)`` (metrics is ``None`` in/out
        when not passed).
        """
        pol = self.policy
        is_bad = jnp.asarray(bad) > 0
        n_bad = jnp.where(is_bad, gstate.consecutive_bad + 1, 0)

        if pol.on_anomaly == "halt":
            do_skip = is_bad  # keep previous state while halting
            do_rollback = jnp.asarray(False)
            halt_now = is_bad
        elif pol.on_anomaly == "rollback":
            do_rollback = is_bad
            do_skip = jnp.asarray(False)
            halt_now = is_bad & (
                gstate.consecutive_rollbacks + 1 > pol.rollback_budget)
        else:  # skip → rollback → halt
            over_skip = n_bad > pol.skip_budget
            do_skip = is_bad & ~over_skip
            do_rollback = is_bad & over_skip
            halt_now = do_rollback & (
                gstate.consecutive_rollbacks + 1 > pol.rollback_budget)

        n_roll = jnp.where(
            do_rollback, gstate.consecutive_rollbacks + 1,
            jnp.where(is_bad, gstate.consecutive_rollbacks, 0))
        halted = jnp.maximum(gstate.halted,
                             halt_now.astype(jnp.float32))

        def select(flag, a, b):
            """tree-where: a where flag else b (non-array leaves follow the
            eager branch only — inside jit every leaf is an array)."""
            return jax.tree_util.tree_map(
                lambda x, y: jnp.where(flag, x, y)
                if hasattr(x, "dtype") or hasattr(y, "dtype")
                else (x if flag else y),
                a, b)

        # skip keeps the pre-step state; rollback restores the snapshot
        out = select(do_skip, previous, proposed)
        if self._rollback_reachable():
            out = select(do_rollback, gstate.snapshot, out)
            # clean step → refresh the snapshot to PREVIOUS, not to the
            # just-proposed state: this step's finite loss/grads were
            # computed FROM previous, so previous is the newest state with
            # evidence of health. The proposed state is unchecked until the
            # next step — refreshing with it would let state-poisoning that
            # slips past this step's detectors (e.g. a NaN that reached the
            # params while the grads stayed finite) into the snapshot, and
            # rollback would then restore the poison.
            new_snap = select(is_bad, gstate.snapshot, previous)
        else:
            new_snap = gstate.snapshot

        if pol.halt_callback:
            jax.debug.callback(self._halt_log, halt_now)

        new_gstate = GuardState(
            consecutive_bad=n_bad.astype(jnp.int32),
            consecutive_rollbacks=n_roll.astype(jnp.int32),
            halted=halted,
            bad_total=gstate.bad_total + is_bad.astype(jnp.float32),
            snapshot=new_snap)
        if metrics is not None:
            metrics = metrics.accumulate(
                guard_skips_total=do_skip.astype(jnp.float32),
                rollbacks_total=do_rollback.astype(jnp.float32),
            ).record(guard_halted=halted)
        return out, new_gstate, metrics

    # -- host side ---------------------------------------------------------
    @staticmethod
    def _halt_log(halt_now) -> None:
        import numpy as np

        if bool(np.any(np.asarray(halt_now))):
            from apex_tpu._logging import get_logger

            get_logger("apex_tpu.resilience").error(
                "anomaly guard escalated to HALT — training state is not "
                "recovering; stop the loop and inspect")

    def raise_if_halted(self, gstate: GuardState) -> None:
        """Host-side halt check (one scalar device read). Call once per
        step — or every N steps — from the driver loop."""
        if float(jax.device_get(gstate.halted)) > 0:
            raise AnomalyHalted(
                "anomaly guard halted after "
                f"{int(jax.device_get(gstate.consecutive_bad))} consecutive "
                "bad steps "
                f"({int(jax.device_get(gstate.consecutive_rollbacks))} "
                "rollbacks); last-known-good state is in "
                "GuardState.snapshot")

"""Production checkpointing — atomic, manifested, async, self-verifying.

Reference context: the reference delegates checkpointing to ``torch.save``
(``examples/imagenet/main_amp.py`` writes one file in-place). At pod scale
that contract is not survivable: a preemption mid-``torch.save`` leaves a
torn file that unpickles halfway or not at all, and with ZeRO-sharded
optimizer state (``contrib/optimizers``) a half-written blob silently
mis-binds shards. This module layers the missing durability on
:mod:`apex_tpu.utils.checkpoint` (which supplies the serialization backend
— orbax when present, atomic pickle otherwise):

* **atomic write** — everything lands in a ``.tmp-*`` staging dir, then one
  ``os.replace`` publishes it; a crash at any point leaves either the old
  checkpoint set or the new one, never a torn member (a same-step re-save
  parks the old copy under ``.trash-*`` between the two renames, so even
  that crash window loses no bytes).
* **versioned manifest** — ``manifest.json`` carries a schema version, the
  step, a treedef+shape/dtype fingerprint of the saved state (the
  ``--resume`` fingerprint contract from the imagenet trainer, now shared),
  and a per-leaf crc32 so corruption is *detected*, not just hoped against.
* **async save** — ``device_get`` happens on the caller (the only part that
  must see the live arrays); serialization + fsync + publish run on a
  single worker thread off the step critical path.
* **retention GC** — keep-last-N plus keep-every-K milestones.
* **latest_valid() discovery** — scan, verify manifests + checksums, and
  skip torn/corrupt checkpoints, so auto-resume always lands on a good one.
* **per-shard manifests** — FSDP/ZeRO pytrees whose leaves are sharded
  ACROSS processes are not refused: each process saves its local shards
  under ``shard-p{K}/`` with its own fingerprinted manifest (leaf index +
  shard placement + crc32), the main manifest records the dp degree, and
  restore validates dp-degree + shard-shape/placement against the live
  sharding before rebinding — skew is refused exactly like a revision
  mismatch. The loud ``CheckpointError`` remains only for leaves with no
  addressable replica-0 shard (genuinely non-addressable).

Telemetry: each save records ``ckpt_save_ms`` / ``ckpt_bytes`` (readable on
:attr:`CheckpointManager.last_save_ms`; pass ``sink=`` to append a
``monitor`` JSONL record per save), and the blocking host section traces
under the ``ckpt`` monitor span.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import zlib
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

MANIFEST_NAME = "manifest.json"
MANIFEST_SCHEMA = 1
# sharded checkpoints (FSDP/ZeRO leaves in per-process shard-p{K} payloads,
# absent from the main payload) are a different on-disk format: they carry
# schema 2 so a pre-sharding reader refuses with a loud schema mismatch
# instead of a misleading "payload is missing leaf K" corruption error.
# Plain checkpoints keep schema 1 (bidirectionally compatible).
MANIFEST_SCHEMA_SHARDED = 2
_PREFIX = "ckpt_"
_TMP_PREFIX = ".tmp-"
_TRASH_PREFIX = ".trash-"


class CheckpointError(RuntimeError):
    """A checkpoint could not be written, verified, or restored."""


def fingerprint(state: Pytree) -> str:
    """Structure fingerprint: treedef + per-leaf shape/dtype. Leaves are
    checkpointed by flat positional index and re-hung on the LIVE treedef,
    so a same-leaf-count checkpoint from another code revision would
    otherwise silently mis-bind optimizer/amp/guard state. Shape/dtype come
    from the avals — no device-to-host copies."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    per_leaf = ";".join(
        f"{tuple(jnp.shape(x))}:{jnp.result_type(x)}" for x in leaves)
    return f"{treedef}|{per_leaf}"


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def _is_cross_process(x) -> bool:
    """A leaf this process cannot materialize whole — an FSDP/ZeRO shard
    pytree under multi-process SPMD. Module-level so tests can exercise
    the per-shard path on a single-process mesh."""
    return (hasattr(x, "is_fully_addressable")
            and not x.is_fully_addressable
            and not getattr(x, "is_fully_replicated", False))


def _index_key(index, shape) -> str:
    """Serializable key for a shard's position: 'start:stop' per dim.
    Pins the shard SHAPE and placement, so a checkpoint written at a
    different dp degree (different slicing) is refused at restore."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append(f"{start}:{stop}")
    return ",".join(out)


def _local_shards(x):
    """This process's unique (replica-0) shards of a cross-process-sharded
    leaf: ``[(index_key, np.ndarray)]``. A leaf with NO addressable
    replica-0 shard is genuinely non-addressable here — the loud refusal
    stays for that case only."""
    shards = [s for s in x.addressable_shards
              if getattr(s, "replica_id", 0) == 0]
    if not shards:
        raise CheckpointError(
            "state contains an array with no addressable replica-0 shard "
            f"on this process (shape {getattr(x, 'shape', '?')}) — "
            "genuinely non-addressable; all-gather it first or use an "
            "orbax multihost checkpointer")
    return [(_index_key(s.index, x.shape), np.asarray(s.data))
            for s in shards]


def _process_info():
    try:
        return jax.process_index(), jax.process_count()
    except Exception:  # jax not initialized — single-process tooling
        return 0, 1


def state_dict(state: Pytree, elastic: Optional[Any] = None
               ) -> Dict[str, Any]:
    """Pytree → flat fingerprinted dict (the manifest path's in-memory
    form): leaves keyed by flat index plus the structure fingerprint, so a
    restore against different code fails loudly instead of mis-binding.

    FSDP/ZeRO shard pytrees ride the same path: a leaf SHARDED across
    processes is stored as this process's local shards (``{"__sharded__":
    ..., "shards": {index_key: array}}``) stamped with the process
    index/count — :func:`load_state_dict` validates the dp degree and
    every shard's placement before rebinding. Only a leaf with no
    addressable replica-0 shard is refused.

    ``elastic``: an optional per-leaf ``reshard.LeafSpec`` tree (or
    pre-flattened mapping) stamped into the dict, so a later
    ``load_state_dict(..., allow_reshard=True)`` at a different dp degree
    can redo the shard arithmetic instead of refusing."""
    leaves = jax.tree_util.tree_leaves(state)
    pidx, pcount = _process_info()
    out: Dict[str, Any] = {"fingerprint": fingerprint(state), "leaves": {}}
    if elastic is not None:
        from apex_tpu.resilience.reshard import elastic_manifest

        out["elastic"] = elastic_manifest(state, elastic)
    host_idx = [i for i, x in enumerate(leaves) if not _is_cross_process(x)]
    fetched = jax.device_get([leaves[i] for i in host_idx])
    for i, h in zip(host_idx, fetched):
        out["leaves"][str(i)] = np.asarray(h)
    for i, x in enumerate(leaves):
        if _is_cross_process(x):
            out["leaves"][str(i)] = {
                "__sharded__": True,
                "global_shape": list(jnp.shape(x)),
                "dtype": str(jnp.result_type(x)),
                "process_index": pidx,
                "process_count": pcount,
                "shards": dict(_local_shards(x)),
            }
    return out


def _manifest_ident(path: str):
    """Filesystem identity (inode+mtime+size) of a published dir's
    manifest — lets a peer distinguish a stale same-step dir (left by a
    crashed previous run, possibly with a colliding ``save_seq``) from
    process 0's fresh publish, whose manifest is always a new file."""
    try:
        st = os.stat(os.path.join(path, MANIFEST_NAME))
    except OSError:
        return None
    return (st.st_ino, st.st_mtime_ns, st.st_size)


def _restore_sharded_leaf(template_leaf, entry: Dict[str, Any], i: int):
    """Rebind one per-shard entry onto the LIVE template leaf's sharding,
    refusing dp-degree or shard-shape/placement skew — the failure mode
    parameter sharding adds over replicated state."""
    pidx, pcount = _process_info()
    if entry["process_count"] != pcount:
        raise CheckpointError(
            f"leaf {i}: checkpoint shards were written at dp degree "
            f"{entry['process_count']} processes, live mesh has {pcount} "
            "— refusing to mis-bind shards (restore on the original "
            "topology or all-gather + reshard explicitly)")
    if list(jnp.shape(template_leaf)) != list(entry["global_shape"]):
        raise CheckpointError(
            f"leaf {i}: checkpoint global shape {entry['global_shape']} "
            f"!= live {list(jnp.shape(template_leaf))}")
    saved = entry["shards"]
    live_shards = [s for s in template_leaf.addressable_shards
                   if getattr(s, "replica_id", 0) == 0]
    live_keys = {_index_key(s.index, template_leaf.shape)
                 for s in live_shards}
    if set(saved) != live_keys:
        raise CheckpointError(
            f"leaf {i}: shard layout skew — checkpoint holds shards "
            f"{sorted(saved)}, live sharding expects {sorted(live_keys)} "
            "(different dp degree or shard alignment)")
    arrays = []
    for s in template_leaf.addressable_shards:
        key = _index_key(s.index, template_leaf.shape)
        if key not in saved:
            # an addressable replica>0 copy whose replica-0 home lives on
            # another process: its bytes are in that process's shard
            # payload, not ours — refuse loudly rather than KeyError
            raise CheckpointError(
                f"leaf {i}: live sharding places a replica copy of shard "
                f"{key} on this process but its replica-0 home is on "
                "another process — per-process shard payloads cannot "
                "rebuild it; restore on the original topology")
        arr = np.asarray(saved[key]).astype(
            jnp.result_type(template_leaf), copy=False)
        arrays.append(jax.device_put(arr, s.device))
    return jax.make_array_from_single_device_arrays(
        template_leaf.shape, template_leaf.sharding, arrays)


def _treedef_compatible(saved_fp: Optional[str], template: Pytree) -> bool:
    """True iff ``saved_fp`` names the same tree STRUCTURE as ``template``
    (the treedef prefix of the fingerprint — per-leaf shapes may differ,
    which is exactly what an elastic reshard changes)."""
    if saved_fp is None:
        return True
    _, treedef = jax.tree_util.tree_flatten(template)
    return str(saved_fp).startswith(f"{treedef}|")


def _rebind_global(leaf, i: int, full: np.ndarray):
    """Bind one assembled-and-retargeted GLOBAL array onto the live leaf:
    slice per live placement + device_put for a cross-process-sharded
    target, a plain asarray otherwise."""
    if not _is_cross_process(leaf):
        return jnp.asarray(full, jnp.result_type(leaf))
    if tuple(full.shape) != tuple(leaf.shape):
        raise CheckpointError(
            f"leaf {i}: resharded global shape {tuple(full.shape)} != "
            f"live {tuple(leaf.shape)}")
    arrays = []
    for s in leaf.addressable_shards:
        piece = np.ascontiguousarray(full[s.index]).astype(
            jnp.result_type(leaf), copy=False)
        arrays.append(jax.device_put(piece, s.device))
    return jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding, arrays)


def _sharded_layout_skew(leaf, entry: Dict[str, Any]) -> bool:
    """True iff a ``__sharded__`` entry cannot rebind exactly onto the
    live leaf: different process count, global shape, or shard placement
    set. (The fingerprint misses the mesh-slicing case — a (64,) leaf
    sharded 8-ways and 2-ways fingerprints identically.)"""
    pidx, pcount = _process_info()
    if entry.get("process_count") != pcount:
        return True
    if list(jnp.shape(leaf)) != list(entry["global_shape"]):
        return True
    live_keys = {_index_key(s.index, leaf.shape)
                 for s in leaf.addressable_shards
                 if getattr(s, "replica_id", 0) == 0}
    return set(entry["shards"]) != live_keys


def _reshard_entry_leaf(leaf, entry: Dict[str, Any], i: int,
                        espec: Optional[Dict[str, Any]]):
    """Elastic restore of one ``__sharded__`` entry onto a live leaf whose
    layout differs: reassemble the logical leaf from its placements,
    retarget via the elastic spec when the global shape changed, re-slice
    to the live placements. Needs the FULL placement set — a
    multi-process state_dict holds only the local shards, in which case
    :func:`assemble_leaf`'s coverage check refuses loudly."""
    from apex_tpu.resilience import reshard as _rs

    full = _rs.assemble_leaf(entry["global_shape"], entry["dtype"],
                             entry["shards"])
    if tuple(full.shape) != tuple(jnp.shape(leaf)):
        if espec is None:
            raise CheckpointError(
                f"leaf {i}: saved global shape {entry['global_shape']} != "
                f"live {list(jnp.shape(leaf))} and the checkpoint carries "
                "no elastic spec for it — re-save with elastic= (the "
                "optimizers' elastic_spec()) or restore on the original "
                "topology")
        full = _rs.retarget_leaf(full, espec, jnp.shape(leaf))
    return _rebind_global(leaf, i, full)


def load_state_dict(template: Pytree, d: Dict[str, Any],
                    allow_reshard: bool = False) -> Pytree:
    """Restore a :func:`state_dict` blob onto ``template``'s structure,
    refusing a fingerprint mismatch (and, for per-shard entries, any
    dp-degree or shard-shape skew against the live sharding).

    ``allow_reshard=True`` relaxes the refusal for TOPOLOGY skew only:
    the treedef and leaf count must still match, but leaves whose
    shard layout (or dp-flat size) changed are reassembled and re-sliced
    via the dict's ``elastic`` specs (see
    :mod:`apex_tpu.resilience.reshard`). Without the flag, behavior is
    byte-for-byte the old refusal."""
    live = fingerprint(template)
    saved = d.get("fingerprint")
    reshard_mode = False
    if saved is not None and saved != live:
        if not allow_reshard:
            raise CheckpointError(
                "state_dict was written by a different state revision — "
                f"refusing to mis-bind.\n   saved: {str(saved)[:200]}\n"
                f"   live:  {live[:200]}")
        if not _treedef_compatible(saved, template):
            raise CheckpointError(
                "allow_reshard only relaxes per-leaf shard layouts; this "
                "state_dict has a different tree STRUCTURE — revision "
                "skew, not topology skew")
        reshard_mode = True
    leaves, treedef = jax.tree_util.tree_flatten(template)
    if len(d["leaves"]) != len(leaves):
        raise CheckpointError(
            f"state_dict has {len(d['leaves'])} leaves, live structure "
            f"has {len(leaves)}")
    elastic = d.get("elastic") or {}
    out = []
    for i, leaf in enumerate(leaves):
        entry = d["leaves"][str(i)]
        if isinstance(entry, dict) and entry.get("__sharded__"):
            if allow_reshard and (
                    not _is_cross_process(leaf)
                    or _sharded_layout_skew(leaf, entry)):
                out.append(_reshard_entry_leaf(leaf, entry, i,
                                               elastic.get(str(i))))
                continue
            if not _is_cross_process(leaf):
                raise CheckpointError(
                    f"leaf {i} was checkpointed as per-process shards but "
                    "the live template is fully addressable — dp-degree "
                    "skew; restore on the original topology")
            out.append(_restore_sharded_leaf(leaf, entry, i))
        elif reshard_mode and (
                tuple(np.shape(entry)) != tuple(jnp.shape(leaf))):
            from apex_tpu.resilience import reshard as _rs

            espec = elastic.get(str(i))
            if espec is None:
                raise CheckpointError(
                    f"leaf {i}: shape changed "
                    f"{tuple(np.shape(entry))} -> "
                    f"{tuple(jnp.shape(leaf))} and the state_dict carries "
                    "no elastic spec for it — save with elastic= or "
                    "restore on the original topology")
            full = _rs.retarget_leaf(np.asarray(entry), espec,
                                     jnp.shape(leaf))
            out.append(_rebind_global(leaf, i, full))
        else:
            out.append(jnp.asarray(entry, jnp.result_type(leaf)))
    return jax.tree_util.tree_unflatten(treedef, out)


def _step_of(name: str) -> Optional[int]:
    if not name.startswith(_PREFIX):
        return None
    try:
        return int(name[len(_PREFIX):])
    except ValueError:
        return None


def _is_process_zero() -> bool:
    try:
        return jax.process_index() == 0
    except Exception:  # jax not initialized — single-process tooling
        return True


class CheckpointManager:
    """Atomic, manifested checkpoint directory. Typical loop::

        mgr = CheckpointManager(ckpt_dir, keep_last_n=3, async_save=True)
        found = mgr.latest_valid()
        if found:
            state, start = mgr.restore(target=state)
        for step in range(start, n):
            state = train_step(state, ...)
            if step % save_freq == 0:
                mgr.save(state, step)
        mgr.close()                        # drains the async worker

    ``state`` is any pytree (amp state, optimizer state incl. ZeRO shards,
    batch stats, DDP error-feedback residuals, guard state, ...).
    """

    def __init__(
        self,
        directory: str,
        keep_last_n: int = 3,
        keep_every_k: int = 0,
        async_save: bool = False,
        fsync: bool = True,
        sink: Optional[Any] = None,
        process0_only: bool = True,
        shard_publish_timeout_s: float = 60.0,
        allow_reshard: bool = False,
    ):
        self.directory = os.path.abspath(directory)
        # default for restore(): opt into topology-elastic restores (a
        # per-call allow_reshard= overrides)
        self.allow_reshard = bool(allow_reshard)
        self.keep_last_n = max(1, int(keep_last_n))
        self.keep_every_k = max(0, int(keep_every_k))
        self.async_save = async_save
        self.fsync = fsync
        self.sink = sink
        # multi-process SPMD (the preemption barrier's world): every
        # process calls save() at the agreed step, but only process 0
        # touches the shared directory — the JsonlSink gating pattern.
        # Reads (latest_valid/restore) stay ungated: they are idempotent.
        self.write_enabled = _is_process_zero() if process0_only else True
        self._process0_only = bool(process0_only)
        # how long a non-zero process waits for process 0's publish before
        # declaring the sharded save failed (slow shared filesystems need
        # more than the default)
        self.shard_publish_timeout_s = float(shard_publish_timeout_s)
        # save-call counter, advanced in lockstep on EVERY process (save()
        # is SPMD): stamps the manifest so peers publishing shards can tell
        # THIS save's dir from an older same-step dir (re-save)
        self._save_seq = 0
        self.last_save_ms: Optional[float] = None
        self.last_save_bytes: Optional[int] = None
        # host ms spent in the reshard arithmetic of the last elastic
        # restore (0.0 when the last restore bound exactly) — the
        # bench_elastic reshard_ms source
        self.last_reshard_ms: float = 0.0
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pending: List[Future] = []
        self._lock = threading.Lock()

    # -- paths -------------------------------------------------------------
    def step_path(self, step: int) -> str:
        return os.path.join(self.directory, f"{_PREFIX}{int(step):08d}")

    def all_steps(self) -> List[int]:
        """Published checkpoint steps, ascending (no validity check)."""
        if not os.path.isdir(self.directory):
            return []
        steps = [_step_of(n) for n in os.listdir(self.directory)]
        return sorted(s for s in steps if s is not None)

    # -- save --------------------------------------------------------------
    def save(self, state: Pytree, step: int, block: Optional[bool] = None,
             elastic: Optional[Any] = None) -> str:
        """Write ``state`` at ``step``; returns the (future) final path.

        ``block=None`` follows the manager's ``async_save`` setting. Only
        the device→host transfer (plus, for async, one private host copy —
        donation safety) runs on the caller; checksums, serialization and
        the atomic publish run on the worker thread. Errors from an async
        save surface on the next :meth:`save` / :meth:`wait` /
        :meth:`close`.

        ``elastic``: optional per-leaf ``reshard.LeafSpec`` tree (or
        pre-flattened mapping) stamped into the manifest so
        :meth:`restore` with ``allow_reshard=True`` can rebuild the state
        at a DIFFERENT dp degree (see
        :mod:`apex_tpu.resilience.reshard`; the ZeRO-1/FSDP optimizers
        build it via ``elastic_spec()``).
        """
        from apex_tpu.monitor.trace import span

        final = self.step_path(step)
        # advanced on every process, even ones that end up writing nothing
        # — the counters must stay in lockstep for the publish handshake
        save_seq = self._save_seq
        self._save_seq += 1
        # captured NOW, before process 0 can have started this save's
        # write: whatever dir currently sits at `final` is stale (an older
        # save of this step) and must never receive this save's shards
        stale_ident = None if self.write_enabled else _manifest_ident(final)
        leaves, _ = jax.tree_util.tree_flatten(state)
        pidx, pcount = _process_info()
        # FSDP/ZeRO shard pytrees: leaves sharded ACROSS processes ride the
        # per-process shard-payload path (each process saves its local
        # shards; _local_shards raises the loud refusal for the genuinely
        # non-addressable case). Everything else is process-0's payload.
        shard_entries: List[Tuple[int, str, np.ndarray]] = []
        host_idx = []
        for i, x in enumerate(leaves):
            if _is_cross_process(x):
                for key, arr in _local_shards(x):
                    shard_entries.append((i, key, arr))
            else:
                host_idx.append(i)
        if not self.write_enabled and not shard_entries:
            return final  # non-zero process, nothing sharded: no write
        self._raise_pending()
        t0 = time.perf_counter()
        sync = not self.async_save if block is None else block
        if shard_entries and pcount > 1:
            if not self._process0_only:
                # with every process a full writer there is no single
                # manifest owner: each would publish its own step dir
                # holding only its own shard-p{K} and the last os.replace
                # wins — every save would verify as torn
                raise CheckpointError(
                    "multi-process sharded saves need process0_only=True: "
                    "the per-shard publish protocol has process 0 own the "
                    "manifest and peers rename their shard dirs in")
            # multi-process sharded saves publish in two phases (shard
            # subdirs land after process 0's manifest) — keep the whole
            # sequence on the caller so the preemption barrier that agreed
            # on the step also brackets the write
            sync = True
        if not sync:
            # backpressure: at most ONE in-flight async save — a second
            # submit would pin a second full host snapshot of the state
            # (unbounded RAM when serialization is slower than the save
            # cadence); blocking here degrades to sync-save pacing instead
            self.wait()
        with span("ckpt"):
            if self.write_enabled:
                fetched = jax.device_get([leaves[i] for i in host_idx])
                host = list(zip(host_idx,
                                [np.asarray(h) for h in fetched]))
            else:
                # non-writer process: _write ignores the replicated
                # payload — don't pay a full device→host transfer on the
                # forced-sync critical path for bytes never written
                host = []
            if not sync:
                # donation safety: on the CPU backend device_get can alias
                # the live buffer, which a donating train step may overwrite
                # while the worker is still serializing — snapshot it. (The
                # checksum/serialize work itself runs on the worker.)
                host = [(i, np.array(h, copy=True)) for i, h in host]
                shard_entries = [(i, k, np.array(a, copy=True))
                                 for i, k, a in shard_entries]
        meta = {
            "schema": (MANIFEST_SCHEMA_SHARDED if shard_entries
                       else MANIFEST_SCHEMA),
            "step": int(step),
            "save_seq": save_seq,
            "fingerprint": fingerprint(state),
            "num_leaves": len(leaves),
        }
        if elastic is not None:
            from apex_tpu.resilience.reshard import elastic_manifest

            meta["elastic"] = elastic_manifest(state, elastic)
        if shard_entries:
            sharded = {}
            for i, _, _ in shard_entries:
                sharded[str(i)] = {
                    "global_shape": list(jnp.shape(leaves[i])),
                    "dtype": str(jnp.result_type(leaves[i])),
                    "dp_degree": pcount,
                }
            meta["sharded"] = sharded
        if sync:
            self.wait()  # a sync save must not interleave with the worker
            self._write(host, shard_entries, meta, final, t0, stale_ident)
        else:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="apex-tpu-ckpt")
            with self._lock:
                self._pending.append(self._pool.submit(
                    self._write, host, shard_entries, meta, final, t0,
                    stale_ident))
        return final

    def _write_shard_subdir(self, parent: str,
                            shard_entries: List[Tuple[int, str, np.ndarray]],
                            meta: Dict[str, Any]) -> int:
        """This process's shard payload + fingerprinted shard manifest
        under ``parent/shard-p{K}``; returns the shard bytes."""
        from apex_tpu.utils.checkpoint import save_checkpoint

        pidx, pcount = _process_info()
        sub = os.path.join(parent, f"shard-p{pidx}")
        os.makedirs(sub, exist_ok=True)
        payload = save_checkpoint(
            os.path.join(sub, "payload"),
            {f"{i}|{key}": arr for i, key, arr in shard_entries})
        manifest = {
            "schema": MANIFEST_SCHEMA,
            "step": meta["step"],
            "process_index": pidx,
            "process_count": pcount,
            "payload": os.path.basename(payload),
            "shards": [{"leaf": i, "index": key, "shape": list(a.shape),
                        "dtype": str(a.dtype), "crc32": _crc(a)}
                       for i, key, a in shard_entries],
        }
        with open(os.path.join(sub, MANIFEST_NAME), "w") as f:
            json.dump(manifest, f)
            if self.fsync:
                f.flush()
                os.fsync(f.fileno())
        return int(sum(a.nbytes for _, _, a in shard_entries))

    def _publish_shard_subdir(self, shard_entries, meta, final,
                              stale_ident=None) -> None:
        """Non-zero process under multi-process SPMD: stage this process's
        shards, wait for process 0 to publish THIS save's checkpoint dir,
        then rename the staging in. A crash before the rename leaves a
        manifest whose expected shard dir is missing — verify() reports
        the checkpoint torn, exactly like a torn payload.

        The wait must not match an OLDER dir for the same step (re-save:
        process 0 parks the old copy and publishes a fresh dir — renaming
        into the old one would land the shard in the copy about to be
        trashed). The fresh dir is recognized by its manifest carrying
        this save's ``save_seq``, not being the dir captured as stale at
        save() entry (``stale_ident`` closes the restart case where a
        crashed previous run left a torn dir whose save_seq collides),
        and not yet holding this process's shard subdir (a completed
        older save always holds one)."""
        pidx, _ = _process_info()
        staging = os.path.join(
            self.directory,
            f"{_TMP_PREFIX}shard-{os.path.basename(final)}-p{pidx}")
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        os.makedirs(staging)

        def _fresh_dir_published() -> bool:
            if os.path.exists(os.path.join(final, f"shard-p{pidx}")):
                return False  # an older, completed copy of this step
            ident = _manifest_ident(final)
            if ident is None or ident == stale_ident:
                return False  # absent, or the stale copy seen at entry
            try:
                with open(os.path.join(final, MANIFEST_NAME)) as f:
                    m = json.load(f)
            except (OSError, ValueError):
                return False
            return m.get("save_seq") == meta["save_seq"]

        try:
            self._write_shard_subdir(staging, shard_entries, meta)
            deadline = time.monotonic() + self.shard_publish_timeout_s
            while not _fresh_dir_published():
                if time.monotonic() > deadline:
                    raise CheckpointError(
                        f"process {pidx}: {final} (save_seq "
                        f"{meta['save_seq']}) was never published by "
                        "process 0 — this save is lost on this process "
                        "(its staged shards are discarded)")
                time.sleep(0.05)
            # _write_shard_subdir staged under staging/shard-p{K}
            os.replace(os.path.join(staging, f"shard-p{pidx}"),
                       os.path.join(final, f"shard-p{pidx}"))
        finally:
            shutil.rmtree(staging, ignore_errors=True)

    def _write(self, host: List[Tuple[int, np.ndarray]],
               shard_entries: List[Tuple[int, str, np.ndarray]],
               meta: Dict[str, Any], final: str, t0: float,
               stale_ident=None) -> None:
        from apex_tpu.utils.checkpoint import save_checkpoint

        if not self.write_enabled:
            # non-zero process: only its shard subdir (sharded saves only
            # reach here with shard entries)
            self._publish_shard_subdir(shard_entries, meta, final,
                                       stale_ident)
            ms = (time.perf_counter() - t0) * 1000.0
            self.last_save_ms = ms
            self.last_save_bytes = int(
                sum(a.nbytes for _, _, a in shard_entries))
            return
        # checksum + manifest assembly on the worker: the host list is a
        # private snapshot, so only the device transfer had to stay on the
        # caller (the async save's critical-path cost)
        manifest = dict(
            meta,
            leaves=[{"leaf_index": i, "shape": list(h.shape),
                     "dtype": str(h.dtype), "crc32": _crc(h)}
                    for i, h in host],
            bytes=int(sum(h.nbytes for _, h in host)))
        os.makedirs(self.directory, exist_ok=True)
        tmp = os.path.join(
            self.directory,
            f"{_TMP_PREFIX}{os.path.basename(final)}-{os.getpid()}")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            payload = save_checkpoint(
                os.path.join(tmp, "payload"),
                {str(i): h for i, h in host})
            manifest = dict(manifest, payload=os.path.basename(payload))
            if shard_entries:
                # process 0's own shards land INSIDE the staging dir, so
                # the atomic publish below covers them too
                manifest["bytes"] += self._write_shard_subdir(
                    tmp, shard_entries, meta)
            mpath = os.path.join(tmp, MANIFEST_NAME)
            with open(mpath, "w") as f:
                json.dump(manifest, f)
                if self.fsync:
                    f.flush()
                    os.fsync(f.fileno())
            trash = None
            if os.path.isdir(final):
                # re-save of the same step: POSIX cannot atomically swap a
                # non-empty dir, so park the old copy under a hidden name
                # first — a crash between the two renames leaves this step
                # missing but the old bytes intact (and recoverable),
                # never a torn mixture
                trash = os.path.join(
                    self.directory,
                    f"{_TRASH_PREFIX}{os.path.basename(final)}-"
                    f"{os.getpid()}")
                if os.path.isdir(trash):
                    shutil.rmtree(trash)
                os.replace(final, trash)
            os.replace(tmp, final)  # the publish — atomic on POSIX
            if trash is not None:
                shutil.rmtree(trash, ignore_errors=True)
            if self.fsync:
                dirfd = os.open(self.directory, os.O_RDONLY)
                try:
                    os.fsync(dirfd)
                finally:
                    os.close(dirfd)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._gc()
        ms = (time.perf_counter() - t0) * 1000.0
        self.last_save_ms = ms
        self.last_save_bytes = manifest["bytes"]
        if self.sink is not None:
            self.sink.write(step=manifest["step"], ckpt_save_ms=round(ms, 3),
                            ckpt_bytes=manifest["bytes"], ckpt_path=final)

    # -- async bookkeeping -------------------------------------------------
    def _raise_pending(self) -> None:
        with self._lock:
            done = [f for f in self._pending if f.done()]
            self._pending = [f for f in self._pending if not f.done()]
        for f in done:
            f.result()  # re-raise the worker's exception, if any

    def wait(self) -> None:
        """Drain in-flight async saves; re-raise their errors."""
        while True:
            with self._lock:
                if not self._pending:
                    return
                f = self._pending.pop(0)
            f.result()

    def close(self) -> None:
        self.wait()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- verify / discover -------------------------------------------------
    def read_manifest(self, path: str) -> Dict[str, Any]:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            m = json.load(f)
        if m.get("schema") not in (MANIFEST_SCHEMA, MANIFEST_SCHEMA_SHARDED):
            raise CheckpointError(
                f"{path}: manifest schema {m.get('schema')!r} not in "
                f"{(MANIFEST_SCHEMA, MANIFEST_SCHEMA_SHARDED)}")
        return m

    def _load_leaves(self, path: str, manifest: Dict[str, Any]
                     ) -> List[np.ndarray]:
        from apex_tpu.utils.checkpoint import load_checkpoint

        blob = load_checkpoint(os.path.join(path, manifest["payload"]))
        entries = manifest["leaves"]
        try:
            # keys are original flat leaf indices (sharded leaves are
            # absent — they live in the per-process shard payloads); old
            # manifests without leaf_index are positional
            return [np.asarray(blob[str(e.get("leaf_index", j))])
                    for j, e in enumerate(entries)]
        except KeyError as e:
            raise CheckpointError(
                f"{path}: payload is missing leaf {e} of "
                f"{len(entries)}") from e

    def _load_shard_dir(self, path: str, manifest: Dict[str, Any],
                        pidx: Optional[int] = None):
        """One process's shard payload of a sharded checkpoint (default:
        this process's): ``{leaf_index: {index_key: np.ndarray}}`` after
        verifying the shard manifest + per-shard crc32s; raises
        CheckpointError on a missing/torn shard dir (a crash between
        process 0's publish and this process's shard rename)."""
        from apex_tpu.utils.checkpoint import load_checkpoint

        if pidx is None:
            pidx, _ = _process_info()
        sub = os.path.join(path, f"shard-p{pidx}")
        try:
            with open(os.path.join(sub, MANIFEST_NAME)) as f:
                sm = json.load(f)
        except OSError as e:
            raise CheckpointError(
                f"{path}: missing shard dir for process {pidx} "
                "(torn sharded save)") from e
        if sm.get("schema") != MANIFEST_SCHEMA:
            raise CheckpointError(
                f"{sub}: shard manifest schema {sm.get('schema')!r} != "
                f"{MANIFEST_SCHEMA}")
        blob = load_checkpoint(os.path.join(sub, sm["payload"]))
        out: Dict[int, Dict[str, np.ndarray]] = {}
        for spec in sm["shards"]:
            key = f"{spec['leaf']}|{spec['index']}"
            try:
                arr = np.asarray(blob[key])
            except KeyError as e:
                raise CheckpointError(
                    f"{sub}: shard payload is missing {key}") from e
            if (list(arr.shape) != spec["shape"]
                    or str(arr.dtype) != spec["dtype"]
                    or _crc(arr) != spec["crc32"]):
                raise CheckpointError(
                    f"{sub}: shard {key} fails its manifest "
                    "shape/dtype/crc32 — corrupt shard payload")
            out.setdefault(int(spec["leaf"]), {})[spec["index"]] = arr
        expected = set(manifest.get("sharded", {}))
        if {str(i) for i in out} != expected:
            raise CheckpointError(
                f"{sub}: shard payload covers leaves {sorted(out)}, "
                f"manifest expects {sorted(expected)}")
        return out, sm

    def verify(self, path: str) -> bool:
        """True iff ``path`` holds a complete, uncorrupted checkpoint:
        manifest parses, payload loads, every leaf matches its manifest
        shape/dtype/crc32."""
        try:
            self._verify_or_raise(path)
            return True
        except Exception:
            return False

    def _verify_or_raise(self, path: str):
        manifest = self.read_manifest(path)
        host = self._load_leaves(path, manifest)
        for i, (h, spec) in enumerate(zip(host, manifest["leaves"])):
            if list(h.shape) != spec["shape"] or str(h.dtype) != spec["dtype"]:
                raise CheckpointError(
                    f"{path}: leaf {i} is {h.shape}:{h.dtype}, manifest "
                    f"says {spec['shape']}:{spec['dtype']}")
            if _crc(h) != spec["crc32"]:
                raise CheckpointError(
                    f"{path}: leaf {i} fails its crc32 — corrupt payload")
        by_proc = None
        if manifest.get("sharded"):
            by_proc = self._check_all_shard_dirs(path, manifest)
        return manifest, host, by_proc

    def _check_all_shard_dirs(self, path: str, manifest: Dict[str, Any]
                              ) -> Dict[int, Dict[int, Dict[str, Any]]]:
        """EVERY process's shard dir must be present, step-consistent AND
        pass its own manifest's per-shard crc32s. Checked by every process
        (not just for its own shard) so all ranks reach the same
        verify()/latest_valid() verdict — a torn or bit-rotted shard dir
        (even another rank's) makes the whole job fall back to the
        previous checkpoint instead of rank K alone restoring older state
        and diverging from its peers. Returns the verified payloads keyed
        by process index — restore's exact path uses its own, the elastic
        reshard path assembles from all of them."""
        degree = max(int(s["dp_degree"])
                     for s in manifest["sharded"].values())
        by_proc: Dict[int, Dict[int, Dict[str, Any]]] = {}
        for p in range(degree):
            sub = os.path.join(path, f"shard-p{p}")
            try:
                with open(os.path.join(sub, MANIFEST_NAME)) as f:
                    sm = json.load(f)
            except OSError as e:
                raise CheckpointError(
                    f"{path}: records dp degree {degree} but the shard dir "
                    f"for process {p} is missing — torn sharded save or "
                    "dp-degree skew") from e
            if sm.get("step") != manifest["step"]:
                raise CheckpointError(
                    f"{sub}: shard dir step {sm.get('step')} != manifest "
                    f"step {manifest['step']} — stale shard dir")
            by_proc[p], _ = self._load_shard_dir(path, manifest, pidx=p)
        return by_proc

    def latest_valid(self) -> Optional[str]:
        """Path of the newest checkpoint that verifies; torn or corrupt
        ones (crashed save, truncated payload, flipped bits) are skipped
        with a warning. ``None`` when no valid checkpoint exists."""
        from apex_tpu._logging import get_logger

        for step in reversed(self.all_steps()):
            p = self.step_path(step)
            if self.verify(p):
                return p
            get_logger("apex_tpu.resilience").warning(
                "skipping invalid checkpoint %s (torn or corrupt)", p)
        return None

    # -- restore -----------------------------------------------------------
    @staticmethod
    def _merged_shards(by_proc, leaf_idx: int) -> Dict[str, Any]:
        """Every process's placements of one leaf, merged (the elastic
        assembly input — replica-0 placements are disjoint by
        construction; overlap is caught downstream by assemble_leaf)."""
        merged: Dict[str, Any] = {}
        for shards in (by_proc or {}).values():
            merged.update(shards.get(leaf_idx, {}))
        return merged

    def restore(self, target: Pytree, path: Optional[str] = None,
                allow_reshard: Optional[bool] = None) -> Tuple[Pytree, int]:
        """Load a checkpoint onto ``target``'s structure; returns
        ``(state, step)``. ``path=None`` discovers :meth:`latest_valid`.
        The manifest fingerprint must match ``target``'s — a checkpoint
        from a different train-state revision is refused, not mis-bound.

        ``allow_reshard`` (default: the manager's constructor setting)
        relaxes the refusal for TOPOLOGY skew only: the treedef and leaf
        count must still match, but leaves whose dp shard layout changed
        are reassembled from EVERY process's crc-verified shard dir and
        re-sliced onto the live layout via the manifest's ``elastic``
        specs (:mod:`apex_tpu.resilience.reshard`) — save at dp=N,
        resume at dp=M. The host ms spent resharding lands on
        :attr:`last_reshard_ms`. Without the flag the old loud refusal is
        unchanged."""
        allow = (self.allow_reshard if allow_reshard is None
                 else bool(allow_reshard))
        if path is None:
            path = self.latest_valid()
            if path is None:
                raise CheckpointError(
                    f"no valid checkpoint under {self.directory}")
        try:
            manifest, host, by_proc = self._verify_or_raise(path)
        except CheckpointError:
            raise
        except Exception as e:
            # missing dir, a path to a pre-manager-format file, damaged
            # JSON, ... — one error type for callers to catch
            raise CheckpointError(
                f"'{path}' is not a readable checkpoint "
                f"({type(e).__name__}: {e})") from e
        self.last_reshard_ms = 0.0
        live = fingerprint(target)
        reshard_mode = manifest["fingerprint"] != live
        if reshard_mode:
            if not allow:
                raise CheckpointError(
                    f"checkpoint '{path}' was written by a different "
                    "train-state revision — refusing to mis-bind state.\n"
                    f"   saved: {manifest['fingerprint'][:200]}...\n"
                    f"   live:  {live[:200]}...")
            if not _treedef_compatible(manifest["fingerprint"], target):
                raise CheckpointError(
                    f"checkpoint '{path}': allow_reshard only relaxes "
                    "per-leaf shard layouts; this checkpoint has a "
                    "different tree STRUCTURE — revision skew, not "
                    "topology skew")
        leaves, treedef = jax.tree_util.tree_flatten(target)
        if reshard_mode and manifest.get("num_leaves") != len(leaves):
            raise CheckpointError(
                f"checkpoint '{path}' has {manifest.get('num_leaves')} "
                f"leaves, live structure has {len(leaves)}")
        sharded = manifest.get("sharded", {})
        if not sharded and not reshard_mode:
            state = jax.tree_util.tree_unflatten(
                treedef, [jnp.asarray(h) for h in host])
            return state, int(manifest["step"])
        pidx, _ = _process_info()
        shards = (by_proc or {}).get(pidx) or {}
        elastic = manifest.get("elastic") or {}
        by_idx = {e.get("leaf_index", j): h
                  for j, (e, h) in enumerate(zip(manifest["leaves"], host))}
        from apex_tpu.resilience import reshard as _rs

        out = []
        for i, leaf in enumerate(leaves):
            if str(i) in sharded:
                spec = sharded[str(i)]
                entry = {
                    "__sharded__": True,
                    "global_shape": spec["global_shape"],
                    "dtype": spec["dtype"],
                    "process_count": spec["dp_degree"],
                    "shards": shards.get(i, {}),
                }
                if allow and (not _is_cross_process(leaf)
                              or _sharded_layout_skew(leaf, entry)):
                    t0 = time.perf_counter()
                    out.append(_reshard_entry_leaf(
                        leaf,
                        dict(entry, shards=self._merged_shards(by_proc, i)),
                        i, elastic.get(str(i))))
                    self.last_reshard_ms += (
                        time.perf_counter() - t0) * 1000.0
                    continue
                if not _is_cross_process(leaf):
                    raise CheckpointError(
                        f"{path}: leaf {i} was saved as per-process shards "
                        "(dp degree "
                        f"{spec['dp_degree']}) but the live target is "
                        "fully addressable — dp-degree skew; restore on "
                        "the original topology")
                out.append(_restore_sharded_leaf(leaf, entry, i))
            else:
                h = by_idx[i]
                shape_skew = tuple(h.shape) != tuple(jnp.shape(leaf))
                if reshard_mode and shape_skew:
                    espec = elastic.get(str(i))
                    if espec is None:
                        raise CheckpointError(
                            f"{path}: leaf {i} shape changed "
                            f"{tuple(h.shape)} -> "
                            f"{tuple(jnp.shape(leaf))} and the checkpoint "
                            "carries no elastic spec for it — re-save "
                            "with elastic= (the optimizers' "
                            "elastic_spec()) or restore on the original "
                            "topology")
                    t0 = time.perf_counter()
                    full = _rs.retarget_leaf(h, espec, jnp.shape(leaf))
                    self.last_reshard_ms += (
                        time.perf_counter() - t0) * 1000.0
                    out.append(_rebind_global(leaf, i, full))
                elif reshard_mode and _is_cross_process(leaf):
                    # plain-saved leaf binding onto a sharded live layout
                    # (e.g. a replicated leaf the new topology shards):
                    # pure placement retarget, no arithmetic needed
                    out.append(_rebind_global(leaf, i, np.asarray(h)))
                else:
                    out.append(jnp.asarray(h))
        return (jax.tree_util.tree_unflatten(treedef, out),
                int(manifest["step"]))

    # -- retention ---------------------------------------------------------
    def _gc(self) -> None:
        """keep-last-N + keep-every-K milestone retention, plus a sweep of
        staging/trash dirs orphaned by a crashed writer — a relaunch-heavy
        spot job must not leak one checkpoint-sized dir per kill."""
        pid_suffix = f"-{os.getpid()}"
        for name in os.listdir(self.directory):
            if name.endswith(pid_suffix):
                continue  # this writer's own live staging
            if name.startswith(f"{_TMP_PREFIX}shard-"):
                # another process's shard staging. A LIVE peer mid-publish
                # (its step's dir exists but its shard is not yet renamed
                # in) must not be torn — but a dead peer's staging would
                # otherwise leak one shard-sized dir per crash. Dead means
                # the publish can no longer complete: the step dir is gone
                # (GC'd / never published before the job died) or already
                # holds this process's shard (rename done, cleanup lost).
                rest = name[len(f"{_TMP_PREFIX}shard-"):]
                target, _, pname = rest.rpartition("-")
                tdir = os.path.join(self.directory, target)
                if (not os.path.isdir(tdir)
                        or os.path.exists(os.path.join(
                            tdir, f"shard-{pname}"))):
                    shutil.rmtree(os.path.join(self.directory, name),
                                  ignore_errors=True)
                continue
            p = os.path.join(self.directory, name)
            if name.startswith(_TMP_PREFIX):
                # a dead writer's staging dir: never completed, delete
                shutil.rmtree(p, ignore_errors=True)
            elif name.startswith(_TRASH_PREFIX):
                # a dead writer's parked old copy (same-step re-save). If
                # the crash hit between the two renames, this trash is the
                # ONLY copy of that step — restore it, don't delete it.
                orig = name[len(_TRASH_PREFIX):].rsplit("-", 1)[0]
                dest = os.path.join(self.directory, orig)
                if _step_of(orig) is not None and not os.path.isdir(dest):
                    try:
                        os.replace(p, dest)
                        continue
                    except OSError:
                        pass
                shutil.rmtree(p, ignore_errors=True)
        steps = self.all_steps()
        if len(steps) <= self.keep_last_n:
            return
        keep = set(steps[-self.keep_last_n:])
        if self.keep_every_k:
            keep.update(s for s in steps if s % self.keep_every_k == 0)
        for s in steps:
            if s not in keep:
                shutil.rmtree(self.step_path(s), ignore_errors=True)

"""Production checkpointing — atomic, manifested, async, self-verifying.

Reference context: the reference delegates checkpointing to ``torch.save``
(``examples/imagenet/main_amp.py`` writes one file in-place). At pod scale
that contract is not survivable: a preemption mid-``torch.save`` leaves a
torn file that unpickles halfway or not at all, and with ZeRO-sharded
optimizer state (``contrib/optimizers``) a half-written blob silently
mis-binds shards. This module layers the missing durability on
:mod:`apex_tpu.utils.checkpoint` (which supplies the serialization backend
— orbax when present, atomic pickle otherwise):

* **atomic write** — everything lands in a ``.tmp-*`` staging dir, then one
  ``os.replace`` publishes it; a crash at any point leaves either the old
  checkpoint set or the new one, never a torn member (a same-step re-save
  parks the old copy under ``.trash-*`` between the two renames, so even
  that crash window loses no bytes).
* **versioned manifest** — ``manifest.json`` carries a schema version, the
  step, a treedef+shape/dtype fingerprint of the saved state (the
  ``--resume`` fingerprint contract from the imagenet trainer, now shared),
  and a per-leaf crc32 so corruption is *detected*, not just hoped against.
* **async save** — ``device_get`` happens on the caller (the only part that
  must see the live arrays); serialization + fsync + publish run on a
  single worker thread off the step critical path.
* **retention GC** — keep-last-N plus keep-every-K milestones.
* **latest_valid() discovery** — scan, verify manifests + checksums, and
  skip torn/corrupt checkpoints, so auto-resume always lands on a good one.

Telemetry: each save records ``ckpt_save_ms`` / ``ckpt_bytes`` (readable on
:attr:`CheckpointManager.last_save_ms`; pass ``sink=`` to append a
``monitor`` JSONL record per save), and the blocking host section traces
under the ``ckpt`` monitor span.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
import zlib
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

MANIFEST_NAME = "manifest.json"
MANIFEST_SCHEMA = 1
_PREFIX = "ckpt_"
_TMP_PREFIX = ".tmp-"
_TRASH_PREFIX = ".trash-"


class CheckpointError(RuntimeError):
    """A checkpoint could not be written, verified, or restored."""


def fingerprint(state: Pytree) -> str:
    """Structure fingerprint: treedef + per-leaf shape/dtype. Leaves are
    checkpointed by flat positional index and re-hung on the LIVE treedef,
    so a same-leaf-count checkpoint from another code revision would
    otherwise silently mis-bind optimizer/amp/guard state. Shape/dtype come
    from the avals — no device-to-host copies."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    per_leaf = ";".join(
        f"{tuple(jnp.shape(x))}:{jnp.result_type(x)}" for x in leaves)
    return f"{treedef}|{per_leaf}"


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def _require_host_fetchable(leaves) -> None:
    """Boundary of this module's checkpoint paths: every process must be
    able to materialize the whole array (single-process meshes, or
    replicated multihost state — ``device_get`` can fetch those). Arrays
    SHARDED across processes need a per-process-shard writer (orbax's
    multihost manager) — fail loudly with one clear error, not with a
    device_get crash inside the preemption grace window."""
    for x in leaves:
        if (hasattr(x, "is_fully_addressable")
                and not x.is_fully_addressable
                and not getattr(x, "is_fully_replicated", False)):
            raise CheckpointError(
                "state contains an array sharded across processes "
                f"(shape {getattr(x, 'shape', '?')}); checkpoint writes "
                "happen on process 0 only and cannot fetch non-addressable "
                "shards — all-gather the state first or use an orbax "
                "multihost checkpointer")


def state_dict(state: Pytree) -> Dict[str, Any]:
    """Pytree → flat fingerprinted dict (the manifest path's in-memory
    form): leaves keyed by flat index plus the structure fingerprint, so a
    restore against different code fails loudly instead of mis-binding.
    The ZeRO optimizers and the DDP comm-state expose their sharded state
    through this (gather or replicate cross-process shards first — see
    :func:`_require_host_fetchable`)."""
    leaves = jax.tree_util.tree_leaves(state)
    _require_host_fetchable(leaves)
    return {
        "fingerprint": fingerprint(state),
        "leaves": {str(i): np.asarray(x)
                   for i, x in enumerate(jax.device_get(leaves))},
    }


def load_state_dict(template: Pytree, d: Dict[str, Any]) -> Pytree:
    """Restore a :func:`state_dict` blob onto ``template``'s structure,
    refusing a fingerprint mismatch."""
    live = fingerprint(template)
    saved = d.get("fingerprint")
    if saved is not None and saved != live:
        raise CheckpointError(
            "state_dict was written by a different state revision — "
            f"refusing to mis-bind.\n   saved: {str(saved)[:200]}\n"
            f"   live:  {live[:200]}")
    leaves, treedef = jax.tree_util.tree_flatten(template)
    if len(d["leaves"]) != len(leaves):
        raise CheckpointError(
            f"state_dict has {len(d['leaves'])} leaves, live structure "
            f"has {len(leaves)}")
    return jax.tree_util.tree_unflatten(
        treedef,
        [jnp.asarray(d["leaves"][str(i)], jnp.result_type(leaves[i]))
         for i in range(len(leaves))])


def _step_of(name: str) -> Optional[int]:
    if not name.startswith(_PREFIX):
        return None
    try:
        return int(name[len(_PREFIX):])
    except ValueError:
        return None


def _is_process_zero() -> bool:
    try:
        return jax.process_index() == 0
    except Exception:  # jax not initialized — single-process tooling
        return True


class CheckpointManager:
    """Atomic, manifested checkpoint directory. Typical loop::

        mgr = CheckpointManager(ckpt_dir, keep_last_n=3, async_save=True)
        found = mgr.latest_valid()
        if found:
            state, start = mgr.restore(target=state)
        for step in range(start, n):
            state = train_step(state, ...)
            if step % save_freq == 0:
                mgr.save(state, step)
        mgr.close()                        # drains the async worker

    ``state`` is any pytree (amp state, optimizer state incl. ZeRO shards,
    batch stats, DDP error-feedback residuals, guard state, ...).
    """

    def __init__(
        self,
        directory: str,
        keep_last_n: int = 3,
        keep_every_k: int = 0,
        async_save: bool = False,
        fsync: bool = True,
        sink: Optional[Any] = None,
        process0_only: bool = True,
    ):
        self.directory = os.path.abspath(directory)
        self.keep_last_n = max(1, int(keep_last_n))
        self.keep_every_k = max(0, int(keep_every_k))
        self.async_save = async_save
        self.fsync = fsync
        self.sink = sink
        # multi-process SPMD (the preemption barrier's world): every
        # process calls save() at the agreed step, but only process 0
        # touches the shared directory — the JsonlSink gating pattern.
        # Reads (latest_valid/restore) stay ungated: they are idempotent.
        self.write_enabled = _is_process_zero() if process0_only else True
        self.last_save_ms: Optional[float] = None
        self.last_save_bytes: Optional[int] = None
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pending: List[Future] = []
        self._lock = threading.Lock()

    # -- paths -------------------------------------------------------------
    def step_path(self, step: int) -> str:
        return os.path.join(self.directory, f"{_PREFIX}{int(step):08d}")

    def all_steps(self) -> List[int]:
        """Published checkpoint steps, ascending (no validity check)."""
        if not os.path.isdir(self.directory):
            return []
        steps = [_step_of(n) for n in os.listdir(self.directory)]
        return sorted(s for s in steps if s is not None)

    # -- save --------------------------------------------------------------
    def save(self, state: Pytree, step: int, block: Optional[bool] = None
             ) -> str:
        """Write ``state`` at ``step``; returns the (future) final path.

        ``block=None`` follows the manager's ``async_save`` setting. Only
        the device→host transfer (plus, for async, one private host copy —
        donation safety) runs on the caller; checksums, serialization and
        the atomic publish run on the worker thread. Errors from an async
        save surface on the next :meth:`save` / :meth:`wait` /
        :meth:`close`.
        """
        from apex_tpu.monitor.trace import span

        final = self.step_path(step)
        leaves, _ = jax.tree_util.tree_flatten(state)
        _require_host_fetchable(leaves)
        if not self.write_enabled:
            return final  # non-zero process under SPMD: no shared-dir write
        self._raise_pending()
        t0 = time.perf_counter()
        sync = not self.async_save if block is None else block
        if not sync:
            # backpressure: at most ONE in-flight async save — a second
            # submit would pin a second full host snapshot of the state
            # (unbounded RAM when serialization is slower than the save
            # cadence); blocking here degrades to sync-save pacing instead
            self.wait()
        with span("ckpt"):
            host = [np.asarray(x) for x in jax.device_get(leaves)]
            if not sync:
                # donation safety: on the CPU backend device_get can alias
                # the live buffer, which a donating train step may overwrite
                # while the worker is still serializing — snapshot it. (The
                # checksum/serialize work itself runs on the worker.)
                host = [np.array(h, copy=True) for h in host]
        meta = {
            "schema": MANIFEST_SCHEMA,
            "step": int(step),
            "fingerprint": fingerprint(state),
        }
        if sync:
            self.wait()  # a sync save must not interleave with the worker
            self._write(host, meta, final, t0)
        else:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="apex-tpu-ckpt")
            with self._lock:
                self._pending.append(self._pool.submit(
                    self._write, host, meta, final, t0))
        return final

    def _write(self, host: List[np.ndarray], meta: Dict[str, Any],
               final: str, t0: float) -> None:
        from apex_tpu.utils.checkpoint import save_checkpoint

        # checksum + manifest assembly on the worker: the host list is a
        # private snapshot, so only the device transfer had to stay on the
        # caller (the async save's critical-path cost)
        manifest = dict(
            meta,
            leaves=[{"shape": list(h.shape), "dtype": str(h.dtype),
                     "crc32": _crc(h)} for h in host],
            bytes=int(sum(h.nbytes for h in host)))
        os.makedirs(self.directory, exist_ok=True)
        tmp = os.path.join(
            self.directory,
            f"{_TMP_PREFIX}{os.path.basename(final)}-{os.getpid()}")
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        try:
            payload = save_checkpoint(
                os.path.join(tmp, "payload"),
                {str(i): h for i, h in enumerate(host)})
            manifest = dict(manifest, payload=os.path.basename(payload))
            mpath = os.path.join(tmp, MANIFEST_NAME)
            with open(mpath, "w") as f:
                json.dump(manifest, f)
                if self.fsync:
                    f.flush()
                    os.fsync(f.fileno())
            trash = None
            if os.path.isdir(final):
                # re-save of the same step: POSIX cannot atomically swap a
                # non-empty dir, so park the old copy under a hidden name
                # first — a crash between the two renames leaves this step
                # missing but the old bytes intact (and recoverable),
                # never a torn mixture
                trash = os.path.join(
                    self.directory,
                    f"{_TRASH_PREFIX}{os.path.basename(final)}-"
                    f"{os.getpid()}")
                if os.path.isdir(trash):
                    shutil.rmtree(trash)
                os.replace(final, trash)
            os.replace(tmp, final)  # the publish — atomic on POSIX
            if trash is not None:
                shutil.rmtree(trash, ignore_errors=True)
            if self.fsync:
                dirfd = os.open(self.directory, os.O_RDONLY)
                try:
                    os.fsync(dirfd)
                finally:
                    os.close(dirfd)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._gc()
        ms = (time.perf_counter() - t0) * 1000.0
        self.last_save_ms = ms
        self.last_save_bytes = manifest["bytes"]
        if self.sink is not None:
            self.sink.write(step=manifest["step"], ckpt_save_ms=round(ms, 3),
                            ckpt_bytes=manifest["bytes"], ckpt_path=final)

    # -- async bookkeeping -------------------------------------------------
    def _raise_pending(self) -> None:
        with self._lock:
            done = [f for f in self._pending if f.done()]
            self._pending = [f for f in self._pending if not f.done()]
        for f in done:
            f.result()  # re-raise the worker's exception, if any

    def wait(self) -> None:
        """Drain in-flight async saves; re-raise their errors."""
        while True:
            with self._lock:
                if not self._pending:
                    return
                f = self._pending.pop(0)
            f.result()

    def close(self) -> None:
        self.wait()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- verify / discover -------------------------------------------------
    def read_manifest(self, path: str) -> Dict[str, Any]:
        with open(os.path.join(path, MANIFEST_NAME)) as f:
            m = json.load(f)
        if m.get("schema") != MANIFEST_SCHEMA:
            raise CheckpointError(
                f"{path}: manifest schema {m.get('schema')!r} != "
                f"{MANIFEST_SCHEMA}")
        return m

    def _load_leaves(self, path: str, manifest: Dict[str, Any]
                     ) -> List[np.ndarray]:
        from apex_tpu.utils.checkpoint import load_checkpoint

        blob = load_checkpoint(os.path.join(path, manifest["payload"]))
        n = len(manifest["leaves"])
        try:
            return [np.asarray(blob[str(i)]) for i in range(n)]
        except KeyError as e:
            raise CheckpointError(
                f"{path}: payload is missing leaf {e} of {n}") from e

    def verify(self, path: str) -> bool:
        """True iff ``path`` holds a complete, uncorrupted checkpoint:
        manifest parses, payload loads, every leaf matches its manifest
        shape/dtype/crc32."""
        try:
            self._verify_or_raise(path)
            return True
        except Exception:
            return False

    def _verify_or_raise(self, path: str) -> Tuple[Dict[str, Any],
                                                   List[np.ndarray]]:
        manifest = self.read_manifest(path)
        host = self._load_leaves(path, manifest)
        for i, (h, spec) in enumerate(zip(host, manifest["leaves"])):
            if list(h.shape) != spec["shape"] or str(h.dtype) != spec["dtype"]:
                raise CheckpointError(
                    f"{path}: leaf {i} is {h.shape}:{h.dtype}, manifest "
                    f"says {spec['shape']}:{spec['dtype']}")
            if _crc(h) != spec["crc32"]:
                raise CheckpointError(
                    f"{path}: leaf {i} fails its crc32 — corrupt payload")
        return manifest, host

    def latest_valid(self) -> Optional[str]:
        """Path of the newest checkpoint that verifies; torn or corrupt
        ones (crashed save, truncated payload, flipped bits) are skipped
        with a warning. ``None`` when no valid checkpoint exists."""
        from apex_tpu._logging import get_logger

        for step in reversed(self.all_steps()):
            p = self.step_path(step)
            if self.verify(p):
                return p
            get_logger("apex_tpu.resilience").warning(
                "skipping invalid checkpoint %s (torn or corrupt)", p)
        return None

    # -- restore -----------------------------------------------------------
    def restore(self, target: Pytree, path: Optional[str] = None
                ) -> Tuple[Pytree, int]:
        """Load a checkpoint onto ``target``'s structure; returns
        ``(state, step)``. ``path=None`` discovers :meth:`latest_valid`.
        The manifest fingerprint must match ``target``'s — a checkpoint
        from a different train-state revision is refused, not mis-bound."""
        if path is None:
            path = self.latest_valid()
            if path is None:
                raise CheckpointError(
                    f"no valid checkpoint under {self.directory}")
        try:
            manifest, host = self._verify_or_raise(path)
        except CheckpointError:
            raise
        except Exception as e:
            # missing dir, a path to a pre-manager-format file, damaged
            # JSON, ... — one error type for callers to catch
            raise CheckpointError(
                f"'{path}' is not a readable checkpoint "
                f"({type(e).__name__}: {e})") from e
        live = fingerprint(target)
        if manifest["fingerprint"] != live:
            raise CheckpointError(
                f"checkpoint '{path}' was written by a different "
                "train-state revision — refusing to mis-bind state.\n"
                f"   saved: {manifest['fingerprint'][:200]}...\n"
                f"   live:  {live[:200]}...")
        treedef = jax.tree_util.tree_structure(target)
        state = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(h) for h in host])
        return state, int(manifest["step"])

    # -- retention ---------------------------------------------------------
    def _gc(self) -> None:
        """keep-last-N + keep-every-K milestone retention, plus a sweep of
        staging/trash dirs orphaned by a crashed writer — a relaunch-heavy
        spot job must not leak one checkpoint-sized dir per kill."""
        pid_suffix = f"-{os.getpid()}"
        for name in os.listdir(self.directory):
            if name.endswith(pid_suffix):
                continue  # this writer's own live staging
            p = os.path.join(self.directory, name)
            if name.startswith(_TMP_PREFIX):
                # a dead writer's staging dir: never completed, delete
                shutil.rmtree(p, ignore_errors=True)
            elif name.startswith(_TRASH_PREFIX):
                # a dead writer's parked old copy (same-step re-save). If
                # the crash hit between the two renames, this trash is the
                # ONLY copy of that step — restore it, don't delete it.
                orig = name[len(_TRASH_PREFIX):].rsplit("-", 1)[0]
                dest = os.path.join(self.directory, orig)
                if _step_of(orig) is not None and not os.path.isdir(dest):
                    try:
                        os.replace(p, dest)
                        continue
                    except OSError:
                        pass
                shutil.rmtree(p, ignore_errors=True)
        steps = self.all_steps()
        if len(steps) <= self.keep_last_n:
            return
        keep = set(steps[-self.keep_last_n:])
        if self.keep_every_k:
            keep.update(s for s in steps if s % self.keep_every_k == 0)
        for s in steps:
            if s not in keep:
                shutil.rmtree(self.step_path(s), ignore_errors=True)

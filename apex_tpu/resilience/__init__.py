"""Fault-tolerant training (L-resilience).

Not in the reference: NVIDIA Apex survives exactly one failure mode — fp16
overflow — through the loss scaler's skip; checkpointing is ``torch.save``
and preemption is the user's problem. At pod scale (the MLPerf-on-TPU-pods
regime) preemptions and transient numeric blowups are routine, and with
ZeRO-sharded optimizer state a torn checkpoint silently corrupts a run.
This subsystem is the durability layer:

* :mod:`~apex_tpu.resilience.guard` — :class:`AnomalyGuard`, the in-graph
  generalization of the scaler's overflow skip: non-finite detection over
  loss/grads/updates/params (+ the scaler's ``found_inf``), a
  skip → rollback → halt escalation ladder driven by
  :class:`GuardPolicy` budgets, a last-good snapshot carried through the
  jitted step, and ``nonfinite_*_total`` / ``rollbacks_total`` counters on
  the monitor pipeline.
* :mod:`~apex_tpu.resilience.checkpoint` — :class:`CheckpointManager`:
  atomic publish (staging dir + ``os.replace``), versioned manifest with a
  treedef fingerprint and per-leaf crc32, async save off the critical
  path, keep-last-N / keep-every-K retention, and :meth:`latest_valid`
  discovery that skips torn/corrupt checkpoints for auto-resume.
* :mod:`~apex_tpu.resilience.reshard` — topology-elastic restore: the flat
  block-aligned dp shard layout is a deterministic function of
  ``(leaf, dp, shard_multiple)``, so a dp=N checkpoint re-slices to a live
  dp=M topology by pure (bitwise-verifiable) arithmetic — exposed as
  ``CheckpointManager.restore(..., allow_reshard=True)`` with
  :class:`LeafSpec` elastic manifests stamped at save time.
* :mod:`~apex_tpu.resilience.supervisor` — :class:`TrainSupervisor`, the
  host-side step-loop driver: retry-with-backoff on transient failures,
  host-side GuardPolicy skip→rollback→halt escalation, preemption →
  synchronized save → clean exit, and an elastic ``restart.json`` naming
  the checkpoint + the dp degrees it can legally resume at.
* :mod:`~apex_tpu.resilience.preemption` — :class:`PreemptionHandler`
  (SIGTERM → multihost-agreed save step → atomic save inside the grace
  window) and :class:`StallWatchdog` (wall-clock step-stall detector that
  dumps thread stacks + a JSONL diagnostic record).
* :mod:`~apex_tpu.resilience.sentinel` — :class:`StragglerSentinel`
  (per-rank step-time robust-z through the alert plane) and
  :class:`SDCSentinel` (cross-replica grad-checksum agreement, rank-
  uniform by construction, riding the guard ladder).
* :mod:`~apex_tpu.resilience.chaos` — the deterministic fault-injection
  harness (NaN at step k, torn/corrupt checkpoints — sharded dirs
  included, simulated preemption) plus :class:`TrainChaosPlan`, the
  step-keyed training fault plan (kill/corrupt-shard/slow-rank) the
  elastic recovery tests drive.
"""

from apex_tpu.resilience.chaos import (  # noqa: F401
    CorruptShardFile,
    KillRankAtStep,
    PreemptionAtStep,
    SlowRank,
    TrainChaosPlan,
    corrupt_checkpoint,
    corrupt_file,
    inject_nonfinite,
    make_manifest_lie,
)
from apex_tpu.resilience.checkpoint import (  # noqa: F401
    MANIFEST_SCHEMA,
    CheckpointError,
    CheckpointManager,
    fingerprint,
    load_state_dict,
    state_dict,
)
from apex_tpu.resilience.guard import (  # noqa: F401
    AnomalyGuard,
    AnomalyHalted,
    GuardPolicy,
    GuardState,
    nonfinite_count,
)
from apex_tpu.resilience.preemption import (  # noqa: F401
    PreemptionHandler,
    StallWatchdog,
)
from apex_tpu.resilience.reshard import (  # noqa: F401
    LeafSpec,
    ReshardError,
    dp_flat_spec,
    dp_stacked_spec,
    elastic_manifest,
    legal_resume_degrees,
    replicated_spec,
    spec_like,
)
from apex_tpu.resilience.sentinel import (  # noqa: F401
    SDCSentinel,
    StragglerSentinel,
    grad_checksum,
)
from apex_tpu.resilience.supervisor import (  # noqa: F401
    TrainSupervisor,
)

__all__ = [
    "AnomalyGuard",
    "AnomalyHalted",
    "CheckpointError",
    "CheckpointManager",
    "CorruptShardFile",
    "GuardPolicy",
    "GuardState",
    "KillRankAtStep",
    "LeafSpec",
    "MANIFEST_SCHEMA",
    "PreemptionAtStep",
    "PreemptionHandler",
    "ReshardError",
    "SDCSentinel",
    "SlowRank",
    "StallWatchdog",
    "StragglerSentinel",
    "TrainChaosPlan",
    "TrainSupervisor",
    "corrupt_checkpoint",
    "corrupt_file",
    "dp_flat_spec",
    "dp_stacked_spec",
    "elastic_manifest",
    "fingerprint",
    "grad_checksum",
    "inject_nonfinite",
    "legal_resume_degrees",
    "load_state_dict",
    "make_manifest_lie",
    "nonfinite_count",
    "replicated_spec",
    "spec_like",
    "state_dict",
]

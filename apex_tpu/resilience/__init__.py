"""Fault-tolerant training (L-resilience).

Not in the reference: NVIDIA Apex survives exactly one failure mode — fp16
overflow — through the loss scaler's skip; checkpointing is ``torch.save``
and preemption is the user's problem. At pod scale (the MLPerf-on-TPU-pods
regime) preemptions and transient numeric blowups are routine, and with
ZeRO-sharded optimizer state a torn checkpoint silently corrupts a run.
This subsystem is the durability layer:

* :mod:`~apex_tpu.resilience.guard` — :class:`AnomalyGuard`, the in-graph
  generalization of the scaler's overflow skip: non-finite detection over
  loss/grads/updates/params (+ the scaler's ``found_inf``), a
  skip → rollback → halt escalation ladder driven by
  :class:`GuardPolicy` budgets, a last-good snapshot carried through the
  jitted step, and ``nonfinite_*_total`` / ``rollbacks_total`` counters on
  the monitor pipeline.
* :mod:`~apex_tpu.resilience.checkpoint` — :class:`CheckpointManager`:
  atomic publish (staging dir + ``os.replace``), versioned manifest with a
  treedef fingerprint and per-leaf crc32, async save off the critical
  path, keep-last-N / keep-every-K retention, and :meth:`latest_valid`
  discovery that skips torn/corrupt checkpoints for auto-resume.
* :mod:`~apex_tpu.resilience.preemption` — :class:`PreemptionHandler`
  (SIGTERM → multihost-agreed save step → atomic save inside the grace
  window) and :class:`StallWatchdog` (wall-clock step-stall detector that
  dumps thread stacks + a JSONL diagnostic record).
* :mod:`~apex_tpu.resilience.chaos` — the deterministic fault-injection
  harness (NaN at step k, torn/corrupt checkpoints, simulated preemption)
  the recovery tests drive.
"""

from apex_tpu.resilience.chaos import (  # noqa: F401
    PreemptionAtStep,
    corrupt_checkpoint,
    corrupt_file,
    inject_nonfinite,
    make_manifest_lie,
)
from apex_tpu.resilience.checkpoint import (  # noqa: F401
    MANIFEST_SCHEMA,
    CheckpointError,
    CheckpointManager,
    fingerprint,
    load_state_dict,
    state_dict,
)
from apex_tpu.resilience.guard import (  # noqa: F401
    AnomalyGuard,
    AnomalyHalted,
    GuardPolicy,
    GuardState,
    nonfinite_count,
)
from apex_tpu.resilience.preemption import (  # noqa: F401
    PreemptionHandler,
    StallWatchdog,
)

__all__ = [
    "AnomalyGuard",
    "AnomalyHalted",
    "CheckpointError",
    "CheckpointManager",
    "GuardPolicy",
    "GuardState",
    "MANIFEST_SCHEMA",
    "PreemptionAtStep",
    "PreemptionHandler",
    "StallWatchdog",
    "corrupt_checkpoint",
    "corrupt_file",
    "fingerprint",
    "inject_nonfinite",
    "load_state_dict",
    "make_manifest_lie",
    "nonfinite_count",
    "state_dict",
]

"""Topology-elastic checkpoint resharding — dp=N shards onto a dp=M run.

PR 9's sharded checkpoints refuse dp-degree skew outright: a preempted
dp=8 job cannot resume on the dp=4 slice the scheduler hands back, even
though nothing about the state is topology-bound. The refusal was the
right default — silently mis-binding shards is how ZeRO runs corrupt —
but the ZeRO-1/FSDP shard layout (``contrib/optimizers/_sharding.py``)
is a *deterministic* flat block-aligned function of
``(leaf, dp, shard_multiple)``:

* every leaf flattens, pads to ``shard_size(n, dp, multiple) * dp``, and
  rank ``r`` owns elements ``[r*k, (r+1)*k)``;
* the CONCATENATED layout is therefore dp-independent except for the
  trailing zero padding — resharding is truncate-or-zero-pad on the
  assembled flat, bitwise exact.

This module is that arithmetic, plus the per-leaf metadata
(:class:`LeafSpec`) a checkpoint needs to carry so a later restore at a
different dp degree can redo it safely. Three leaf kinds:

* ``dp_flat`` — the sharded-flat layout above (fp32 masters, Adam/LAMB
  moments, FSDP shards). Reshard = assemble → check the padding tail is
  all-zero → re-pad to the new degree's size. Bitwise round-trips at any
  degree.
* ``replicated`` — identical on every rank (step count, scaler state);
  passes through unchanged, any shape change is refused.
* ``dp_stacked`` — genuinely per-rank state with a leading dp axis (the
  error-feedback residuals, stacked across ranks). Growing dp keeps the
  existing rows and zero-pads new ranks; shrinking folds row ``j + i*M``
  into row ``j`` (strided sum), which conserves the rank-SUM — exactly
  the quantity the psum'd EF correction injects — and makes
  grow-then-shrink a bitwise round trip.

Refusals are loud :class:`ReshardError`\\ s (a ``CheckpointError``
subclass, so existing ``except CheckpointError`` recovery paths still
catch them): a live layout whose flat size the saved ``shard_multiple``
cannot divide, a non-zero padding tail (the layout assumption broken —
corrupt bytes or a non-standard writer), placements that do not tile the
global shape, or a leaf with no elastic spec at all.

Entry points: ``CheckpointManager.save(..., elastic=spec_tree)`` stamps
the manifest; ``restore(..., allow_reshard=True)`` consumes it. The
ZeRO-1/FSDP optimizers build their spec trees via ``elastic_spec()``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import numpy as np

from apex_tpu.contrib.optimizers._sharding import shard_size
from apex_tpu.resilience.checkpoint import CheckpointError

Pytree = Any

DP_FLAT = "dp_flat"
DP_STACKED = "dp_stacked"
REPLICATED = "replicated"
_KINDS = (DP_FLAT, DP_STACKED, REPLICATED)

__all__ = [
    "DP_FLAT", "DP_STACKED", "REPLICATED", "LeafSpec", "ReshardError",
    "assemble_leaf", "dp_flat_spec", "dp_stacked_spec", "elastic_manifest",
    "legal_resume_degrees", "replicated_spec", "reshard_flat",
    "reshard_stacked", "retarget_leaf", "spec_like",
]


class ReshardError(CheckpointError):
    """A checkpoint could not be resharded onto the live topology."""


@dataclasses.dataclass(frozen=True)
class LeafSpec:
    """Elastic metadata for ONE checkpoint leaf — everything a future
    restore at a different dp degree needs to redo the shard arithmetic.

    ``kind``: ``dp_flat`` | ``replicated`` | ``dp_stacked``.
    ``n``: logical (unpadded) element count — the flattened size of the
    parameter the ``dp_flat`` leaf shards; the padding boundary.
    ``multiple``: the shard alignment (``compression.block_size`` when a
    quantized wire is configured, else 1) — the new layout's per-rank
    size must stay a multiple of it or scale blocks would straddle ranks.
    ``dp``: the dp degree the leaf was saved at (``dp_stacked``'s leading
    axis; for ``dp_flat`` it pins the save-time arithmetic so a mangled
    manifest is caught instead of trusted).
    """

    kind: str
    n: int = 0
    multiple: int = 1
    dp: int = 1

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(
                f"kind must be one of {_KINDS}, got {self.kind!r}")
        if self.n < 0 or self.multiple < 1 or self.dp < 1:
            raise ValueError(
                f"bad LeafSpec arithmetic: n={self.n} "
                f"multiple={self.multiple} dp={self.dp}")


def replicated_spec() -> LeafSpec:
    """Spec for a rank-identical leaf (step count, scaler, guard state)."""
    return LeafSpec(kind=REPLICATED)


def dp_flat_spec(n: int, dp: int, multiple: int = 1) -> LeafSpec:
    """Spec for one dp-flat sharded leaf; ``n`` is the LOGICAL element
    count of the parameter it shards (not the padded stored size)."""
    return LeafSpec(kind=DP_FLAT, n=int(n), multiple=int(multiple),
                    dp=int(dp))


def dp_stacked_spec(dp: int) -> LeafSpec:
    """Spec for per-rank state stacked on a leading dp axis (EF
    residuals)."""
    return LeafSpec(kind=DP_STACKED, dp=int(dp))


def spec_like(state: Pytree, fn) -> Pytree:
    """Map ``fn(leaf) -> LeafSpec`` over ``state``'s structure — the spec
    tree :func:`elastic_manifest` zips against it leaf-for-leaf."""
    return jax.tree_util.tree_map(fn, state)


def elastic_manifest(state: Pytree, spec: Any) -> Dict[str, Dict[str, Any]]:
    """Flatten a spec tree (or pass through an already-flat mapping) into
    the manifest form ``{flat_leaf_index: {kind, n, multiple, dp}}``,
    validated against ``state``'s flat leaf count."""
    n_leaves = len(jax.tree_util.tree_leaves(state))
    if isinstance(spec, Mapping) and all(
            isinstance(v, (Mapping, LeafSpec)) for v in spec.values()) \
            and all(str(k).isdigit() for k in spec):
        flat = {str(k): (dataclasses.asdict(v) if isinstance(v, LeafSpec)
                         else dict(v)) for k, v in spec.items()}
    else:
        specs = jax.tree_util.tree_leaves(
            spec, is_leaf=lambda x: isinstance(x, LeafSpec))
        if len(specs) != n_leaves:
            raise ReshardError(
                f"elastic spec tree has {len(specs)} leaves, state has "
                f"{n_leaves} — build it with spec_like(state, ...) so the "
                "structures match")
        flat = {str(i): dataclasses.asdict(s) for i, s in enumerate(specs)}
    for k, d in flat.items():
        LeafSpec(**d)  # validate eagerly — a bad spec dies at save time
        if int(k) >= n_leaves:
            raise ReshardError(
                f"elastic spec names leaf {k}, state has {n_leaves} leaves")
    return flat


# -- the arithmetic ---------------------------------------------------------
def reshard_flat(flat: np.ndarray, n: int, dp_new: int,
                 multiple: int = 1) -> np.ndarray:
    """Re-pad a dp-flat GLOBAL layout (the concatenation of every rank's
    shard, ``shard_size(n, dp_old, m) * dp_old`` elements) to the dp_new
    layout. Bitwise exact: elements ``[0, n)`` are the data, everything
    past ``n`` must be the layout's zero padding — a non-zero tail means
    the layout assumption is broken and is refused, not truncated."""
    flat = np.asarray(flat).reshape(-1)
    if flat.size < n:
        raise ReshardError(
            f"dp_flat leaf holds {flat.size} elements, elastic spec says "
            f"the logical size is {n} — manifest/payload mismatch")
    tail = flat[n:]
    if tail.size and np.any(tail != 0):
        raise ReshardError(
            "dp_flat leaf has non-zero bytes in its padding tail "
            f"(logical size {n}, stored {flat.size}) — the block-aligned "
            "layout assumption is broken; refusing to reshard")
    k = shard_size(n, dp_new, multiple)
    out = np.zeros(k * dp_new, dtype=flat.dtype)
    out[:n] = flat[:n]
    return out


def reshard_stacked(stacked: np.ndarray, dp_new: int) -> np.ndarray:
    """Retarget per-rank state with a leading dp axis. Growing keeps the
    existing rows and zero-pads the new ranks; shrinking folds row
    ``j + i*dp_new`` into row ``j`` (strided sum) — the rank-sum (the
    psum'd pending EF correction) is conserved, and grow-then-shrink
    round-trips bitwise."""
    stacked = np.asarray(stacked)
    dp_old = stacked.shape[0]
    if dp_new == dp_old:
        return stacked
    if dp_new > dp_old:
        pad = np.zeros((dp_new - dp_old,) + stacked.shape[1:],
                       dtype=stacked.dtype)
        return np.concatenate([stacked, pad], axis=0)
    out = np.zeros((dp_new,) + stacked.shape[1:], dtype=stacked.dtype)
    for j in range(dp_new):
        out[j] = stacked[j::dp_new].sum(axis=0, dtype=stacked.dtype)
    return out


def retarget_leaf(arr: np.ndarray, spec: Any,
                  live_shape: Sequence[int]) -> np.ndarray:
    """Reshard one assembled GLOBAL leaf onto the live layout named by
    ``live_shape``. ``spec`` is a :class:`LeafSpec` or its manifest dict.
    Loud refusals: a replicated leaf changing shape, a live flat size the
    saved ``shard_multiple`` cannot divide, mismatched trailing dims on a
    dp_stacked leaf."""
    if isinstance(spec, Mapping):
        spec = LeafSpec(**spec)
    arr = np.asarray(arr)
    live_shape = tuple(int(d) for d in live_shape)
    if tuple(arr.shape) == live_shape and spec.kind != DP_STACKED:
        return arr
    if spec.kind == REPLICATED:
        raise ReshardError(
            f"replicated leaf changed shape {tuple(arr.shape)} -> "
            f"{live_shape} across the reshard — replicated state is "
            "topology-independent; this is a revision skew, not a dp skew")
    if spec.kind == DP_FLAT:
        if len(live_shape) != 1:
            raise ReshardError(
                f"dp_flat leaf must restore onto a 1-D flat layout, live "
                f"shape is {live_shape}")
        size = live_shape[0]
        stored = shard_size(spec.n, spec.dp, spec.multiple) * spec.dp
        if arr.size != stored:
            raise ReshardError(
                f"dp_flat leaf stores {arr.size} elements but its elastic "
                f"spec (n={spec.n}, dp={spec.dp}, "
                f"multiple={spec.multiple}) implies {stored} — manifest "
                "arithmetic mismatch")
        if size % spec.multiple != 0:
            raise ReshardError(
                f"live flat size {size} is not a multiple of the saved "
                f"shard alignment {spec.multiple} "
                "(compression.block_size) — shard_multiple arithmetic "
                "cannot divide the new topology; rebuild the live state "
                "with the same block alignment")
        if size < spec.n:
            raise ReshardError(
                f"live flat size {size} cannot hold the leaf's {spec.n} "
                "logical elements — the live layout was built for a "
                "smaller parameter; revision skew, not dp skew")
        full = reshard_flat(arr, spec.n, 1, 1)[:spec.n]
        out = np.zeros(size, dtype=arr.dtype)
        out[:spec.n] = full
        return out
    # DP_STACKED
    if arr.ndim < 1 or len(live_shape) != arr.ndim:
        raise ReshardError(
            f"dp_stacked leaf rank mismatch: stored {arr.shape}, live "
            f"{live_shape}")
    if tuple(arr.shape[1:]) != live_shape[1:]:
        raise ReshardError(
            f"dp_stacked leaf trailing dims changed {arr.shape[1:]} -> "
            f"{live_shape[1:]} — per-rank state shape is "
            "topology-independent; revision skew")
    return reshard_stacked(arr, live_shape[0])


# -- placement assembly -----------------------------------------------------
def _parse_index_key(key: str) -> List[Tuple[int, int]]:
    out = []
    for part in key.split(","):
        start, stop = part.split(":")
        out.append((int(start), int(stop)))
    return out


def assemble_leaf(global_shape: Sequence[int], dtype: Any,
                  placements: Mapping[str, np.ndarray]) -> np.ndarray:
    """Reassemble one logical leaf from its ``start:stop`` placements (the
    per-shard manifest's index keys). Every element must be covered
    exactly once — gaps and overlaps are both refused, they mean shard
    dirs from different saves were mixed."""
    shape = tuple(int(d) for d in global_shape)
    out = np.zeros(shape, dtype=np.dtype(dtype))
    covered = np.zeros(shape, dtype=bool)
    for key, arr in placements.items():
        arr = np.asarray(arr)
        bounds = _parse_index_key(key)
        if len(bounds) != len(shape):
            raise ReshardError(
                f"placement {key!r} has {len(bounds)} dims, leaf has "
                f"{len(shape)}")
        idx = tuple(slice(s, t) for s, t in bounds)
        want = tuple(t - s for s, t in bounds)
        if tuple(arr.shape) != want:
            raise ReshardError(
                f"placement {key!r} holds shape {tuple(arr.shape)}, its "
                f"index implies {want}")
        if covered[idx].any():
            raise ReshardError(
                f"placement {key!r} overlaps another shard — shard dirs "
                "from different saves mixed?")
        out[idx] = arr
        covered[idx] = True
    if not covered.all():
        missing = int(covered.size - covered.sum())
        raise ReshardError(
            f"placements cover only {int(covered.sum())} of {covered.size} "
            f"elements ({missing} missing) — incomplete shard set; a "
            "process's shard dir is absent")
    return out


def legal_resume_degrees(
    specs: Mapping[str, Any],
    candidates: Sequence[int] = (1, 2, 4, 8, 16, 32, 64),
) -> List[int]:
    """The dp degrees a checkpoint with this elastic manifest can resume
    at without an all-padding rank: every ``dp_flat`` leaf must give the
    LAST rank at least one logical element (``n > (M-1) *
    shard_size(n, M, multiple)``). The restart manifest names these so an
    elastic scheduler can pick a slice without trial-and-error."""
    out = []
    for m in candidates:
        ok = True
        for d in specs.values():
            spec = d if isinstance(d, LeafSpec) else LeafSpec(**dict(d))
            if spec.kind != DP_FLAT:
                continue
            k = shard_size(spec.n, m, spec.multiple)
            if spec.n <= (m - 1) * k:
                ok = False
                break
        if ok:
            out.append(int(m))
    return out

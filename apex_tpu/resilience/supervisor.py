"""TrainSupervisor — the host-side driver that makes a step loop elastic.

Reference context: the reference stack's "supervisor" is torchrun's
``--max-restarts`` — a process-level hammer that re-execs the whole job and
relies on the user's hand-rolled resume code. This module is the train-side
counterpart of ``ServeCluster.step``: ONE object owns the step loop and
wires the resilience tiers together so every failure path is a tested
state-machine transition, not an exception stack unwinding through user
code:

* **retry with backoff** — a transient step failure (flaky host collective,
  an input-pipeline hiccup) is retried up to ``max_retries`` times with
  exponential backoff before the ladder is consulted.
  :class:`~apex_tpu.resilience.guard.AnomalyHalted` (the in-graph guard
  already escalated), ``KeyboardInterrupt`` and ``SystemExit`` are never
  treated as transient.
* **escalation ladder** — retries exhausted → the supervisor walks the same
  :class:`~apex_tpu.resilience.guard.GuardPolicy` skip→rollback→halt ladder
  the in-graph guard uses, but host-side: *skip* drops the step (state
  unchanged), *rollback* restores ``latest_valid()`` through the manager,
  *halt* writes a restart manifest and raises ``AnomalyHalted``.
  Consecutive-failure counters reset on every clean step, mirroring
  ``GuardState``.
* **preemption** — SIGTERM lands in the
  :class:`~apex_tpu.resilience.preemption.PreemptionHandler`; the loop
  polls ``sync_save_step`` once per step, performs the synchronized save
  (``block=True``), writes the restart manifest and exits cleanly inside
  the grace window.
* **elastic restart manifest** — every non-running exit (preempted, killed,
  halted, completed-with-checkpoints) leaves ``restart.json`` next to the
  checkpoints naming the checkpoint to resume from, the dp degree it was
  written at, and — when an elastic spec is attached — the dp degrees it
  can LEGALLY resume at (:func:`~apex_tpu.resilience.reshard
  .legal_resume_degrees`), so an elastic scheduler re-launches on whatever
  slice it got back and calls :meth:`TrainSupervisor.resume` with
  ``allow_reshard=True``.
* **chaos hooks** — ``clock``/``sleep`` are injectable (manual clock, no
  real sleeps in tests) and a :class:`~apex_tpu.resilience.chaos
  .TrainChaosPlan` fires step-keyed faults through :meth:`kill` /
  :meth:`inject_slow` / the manager, exactly like ``ServeCluster``'s
  ``ClusterChaos``.

Sentinels ride along: a :class:`~apex_tpu.resilience.sentinel
.StragglerSentinel` gets the per-rank step-time gauge every step (chaos
``SlowRank`` inflates the injected rank's time), and the in-graph SDC check
lives inside ``step_fn`` where the grads are.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

from apex_tpu._logging import get_logger
from apex_tpu.resilience.guard import AnomalyHalted, GuardPolicy
from apex_tpu.resilience.reshard import legal_resume_degrees

Pytree = Any

RESTART_NAME = "restart.json"

_NON_TRANSIENT = (AnomalyHalted, KeyboardInterrupt, SystemExit)


class TrainSupervisor:
    """Drives ``step_fn(state, step) -> state`` with retries, escalation,
    preemption and elastic restart manifests.

    ``step_fn``: one training step; raises on failure. ``manager``: a
    :class:`~apex_tpu.resilience.checkpoint.CheckpointManager` (required
    for rollback, periodic saves and restart manifests). ``policy``: the
    GuardPolicy reused as HOST-side escalation config (entry rung +
    budgets). ``elastic``: a spec tree / flat mapping for
    :func:`~apex_tpu.resilience.reshard.elastic_manifest` — stamped into
    every save and the restart manifest so the checkpoint is resharding-
    capable. ``dp_degree``: the live dp degree (recorded in the manifest;
    also the fan-out of the per-rank step-time gauge). ``save_freq``:
    checkpoint every N clean steps (0 = only on preemption/halt).
    ``max_retries``/``backoff_s``: transient-failure retry knobs —
    ``sleep`` is only called when ``backoff_s > 0``, and both ``clock``
    and ``sleep`` are injectable so chaos tests run on a manual clock
    with no real sleeps.
    """

    def __init__(
        self,
        step_fn: Callable[[Pytree, int], Pytree],
        manager: Optional[Any] = None,
        *,
        policy: Optional[GuardPolicy] = None,
        preemption: Optional[Any] = None,
        elastic: Optional[Any] = None,
        dp_degree: int = 1,
        save_freq: int = 0,
        max_retries: int = 2,
        backoff_s: float = 0.0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        chaos: Optional[Any] = None,
        straggler: Optional[Any] = None,
        sink: Optional[Any] = None,
    ):
        if dp_degree < 1:
            raise ValueError(f"dp_degree must be >= 1, got {dp_degree}")
        if max_retries < 0 or backoff_s < 0 or save_freq < 0:
            raise ValueError("max_retries, backoff_s and save_freq must "
                             "be >= 0")
        self.step_fn = step_fn
        self.manager = manager
        self.policy = policy or GuardPolicy()
        self.preemption = preemption
        self.elastic = elastic
        self.dp_degree = int(dp_degree)
        self.save_freq = int(save_freq)
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.clock = clock
        self.sleep = sleep
        self.chaos = chaos
        self.straggler = straggler
        self.sink = sink
        self.log = get_logger("apex_tpu.resilience")

        self.counters: Dict[str, int] = {
            "steps_total": 0, "retries_total": 0, "skips_total": 0,
            "rollbacks_total": 0, "saves_total": 0,
            "elastic_resumes_total": 0,
        }
        self.exited: Optional[str] = None  # "completed"|"preempted"|"killed"
        self._killed = False
        self._slow: Dict[int, Tuple[float, int]] = {}  # rank → (factor, left)
        self._consecutive_failed = 0
        self._consecutive_rollbacks = 0

    # -- chaos entry points ------------------------------------------------
    def kill(self) -> None:
        """Hard-kill this rank at the next step boundary: the loop exits
        WITHOUT saving (harsher than preemption — no grace window), and
        the restart manifest points at ``latest_valid()``. What chaos
        ``KillRankAtStep`` fires."""
        self._killed = True

    def inject_slow(self, rank: int, factor: float, for_steps: int) -> None:
        """Inflate ``rank``'s reported step time by ``factor`` for the
        next ``for_steps`` steps (chaos ``SlowRank`` — consumed by the
        straggler sentinel through the per-rank gauge)."""
        if not (0 <= rank < self.dp_degree):
            raise ValueError(
                f"SlowRank rank {rank} out of range for dp={self.dp_degree}")
        self._slow[int(rank)] = (float(factor), int(for_steps))

    # -- the loop ----------------------------------------------------------
    def run(self, state: Pytree, start_step: int = 0,
            num_steps: int = 1) -> Tuple[Pytree, int]:
        """Run up to ``num_steps`` steps from ``start_step``; returns
        ``(state, next_step)`` — ``next_step`` is where a resume should
        continue. Check :attr:`exited` for why the loop ended."""
        self.exited = None
        step = int(start_step)
        end = step + int(num_steps)
        while step < end:
            if self.chaos is not None:
                self.chaos.apply(self, step)
            if self._killed:
                # killed ranks get no save: the manifest points at the
                # last checkpoint that was already durable
                self.exited = "killed"
                self._write_restart(self._latest(), step, reason="killed")
                self.log.warning(
                    "rank killed at step %d — exiting without save; "
                    "resume from %s", step, self._latest())
                return state, step
            t0 = self.clock()
            try:
                state = self._attempt(state, step)
            except AnomalyHalted:
                self._write_restart(self._latest(), step, reason="halted")
                raise
            except _EscalationNeeded as esc:
                state, moved = self._escalate(state, step, esc.cause)
                if not moved:
                    continue  # rolled back — retry the same step range
            else:
                self._consecutive_failed = 0
                self._consecutive_rollbacks = 0
            self.counters["steps_total"] += 1
            self._observe_times(step, self.clock() - t0)
            step += 1
            if (self.manager is not None and self.save_freq
                    and step % self.save_freq == 0):
                self._save(state, step)
            if self.preemption is not None:
                save_at = self.preemption.sync_save_step(step)
                if save_at is not None:
                    if self.manager is not None:
                        self._save(state, save_at + 1, block=True)
                    self.exited = "preempted"
                    self._write_restart(
                        self._latest(), save_at + 1, reason="preempted")
                    self.log.warning(
                        "preempted at step %d — synchronized save done, "
                        "exiting inside the grace window", save_at)
                    return state, save_at + 1
        self.exited = "completed"
        if self.manager is not None and self._latest() is not None:
            self._write_restart(self._latest(), step, reason="completed")
        return state, step

    def _attempt(self, state: Pytree, step: int) -> Pytree:
        """One step with the transient-retry loop; raises
        :class:`_EscalationNeeded` when retries are exhausted."""
        attempt = 0
        while True:
            try:
                return self.step_fn(state, step)
            except _NON_TRANSIENT:
                raise
            # anything else is treated as transient (flaky I/O, preempted
            # collectives) and retried up to max_retries before escalating
            except Exception as exc:
                attempt += 1
                self.counters["retries_total"] += 1
                if attempt > self.max_retries:
                    raise _EscalationNeeded(exc) from exc
                if self.backoff_s > 0:
                    self.sleep(self.backoff_s * (2 ** (attempt - 1)))
                self.log.warning(
                    "step %d failed (%s) — retry %d/%d", step, exc,
                    attempt, self.max_retries)

    def _escalate(self, state: Pytree, step: int,
                  cause: BaseException) -> Tuple[Pytree, bool]:
        """Retries exhausted: walk the GuardPolicy ladder host-side.
        Returns ``(state, moved)`` — ``moved`` False means the state was
        rolled back and the SAME step index should be retried."""
        pol = self.policy
        self._consecutive_failed += 1
        if (pol.on_anomaly == "skip"
                and self._consecutive_failed <= pol.skip_budget):
            self.counters["skips_total"] += 1
            self.log.warning(
                "step %d failed after retries — SKIPPED (%d/%d budget): %s",
                step, self._consecutive_failed, pol.skip_budget, cause)
            return state, True  # advance past the poisoned step
        if pol.on_anomaly in ("skip", "rollback"):
            self._consecutive_rollbacks += 1
            if self._consecutive_rollbacks <= pol.rollback_budget:
                latest = self._latest()
                if self.manager is None or latest is None:
                    self._halt(step, cause,
                               "rollback rung reached but no valid "
                               "checkpoint to roll back to")
                self.counters["rollbacks_total"] += 1
                self.log.warning(
                    "step %d failed — ROLLBACK to %s (%d/%d budget): %s",
                    step, latest, self._consecutive_rollbacks,
                    pol.rollback_budget, cause)
                state, _ = self.manager.restore(target=state, path=latest)
                return state, False
        self._halt(step, cause, "escalation budgets exhausted")

    def _halt(self, step: int, cause: BaseException, why: str) -> None:
        self._write_restart(self._latest(), step, reason="halted")
        raise AnomalyHalted(
            f"supervisor halted at step {step} ({why}); last failure: "
            f"{cause!r}; restart manifest written") from cause

    # -- sentinel feed -----------------------------------------------------
    def _observe_times(self, step: int, dt: float) -> None:
        times = [dt] * self.dp_degree
        for rank in list(self._slow):
            factor, left = self._slow[rank]
            times[rank] = dt * factor
            self._slow[rank] = (factor, left - 1)
            if left - 1 <= 0:
                del self._slow[rank]
        if self.straggler is not None:
            self.straggler.observe(step, times)
        if self.sink is not None:
            self.sink.write(step=step, step_time_s=dt,
                            rank_step_time_s=times)

    # -- checkpoints + the restart manifest --------------------------------
    def _latest(self) -> Optional[str]:
        return None if self.manager is None else self.manager.latest_valid()

    def _save(self, state: Pytree, step: int, block: Optional[bool] = None):
        self.manager.save(state, step, block=block, elastic=self.elastic)
        self.counters["saves_total"] += 1

    def _write_restart(self, checkpoint: Optional[str], step: int,
                       reason: str) -> None:
        if self.manager is None:
            return
        legal = [self.dp_degree]
        if self.elastic is not None and checkpoint is not None:
            # the saved manifest's stamped spec is authoritative (matches
            # what is actually on disk); a flat digit-keyed spec mapping
            # passed at construction works as a fallback
            specs = self._specs_from_checkpoint(checkpoint)
            if not specs and isinstance(self.elastic, dict) \
                    and all(str(k).isdigit() for k in self.elastic):
                specs = self.elastic
            if specs:
                legal = legal_resume_degrees(specs)
        info = {
            "checkpoint": checkpoint, "step": int(step),
            "dp_degree": self.dp_degree, "legal_resume_dp": legal,
            "reason": reason, "allow_reshard": self.elastic is not None,
        }
        path = os.path.join(self.manager.directory, RESTART_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(info, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)

    def _specs_from_checkpoint(self, checkpoint: str) -> Dict[str, Any]:
        try:
            from apex_tpu.resilience.checkpoint import MANIFEST_NAME
            with open(os.path.join(checkpoint, MANIFEST_NAME)) as f:
                manifest = json.load(f)
            return manifest.get("elastic") or {}
        # best-effort read: a missing/corrupt manifest just means no
        # elastic specs ride the restart hint — restore will still refuse
        except Exception:
            return {}

    # -- resume ------------------------------------------------------------
    @staticmethod
    def read_restart(directory: str) -> Optional[Dict[str, Any]]:
        """Parse ``restart.json`` from a checkpoint directory (what the
        re-launched job — possibly at a different dp degree — reads
        first). ``None`` when no manifest exists (fresh start)."""
        path = os.path.join(directory, RESTART_NAME)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    def resume(self, template: Pytree,
               allow_reshard: Optional[bool] = None) -> Tuple[Pytree, int]:
        """Restore from the restart manifest (falling back to
        ``latest_valid()``): returns ``(state, step)`` ready for
        :meth:`run`. ``allow_reshard`` defaults to what the manifest
        granted — a manifest written WITH an elastic spec opts in, so a
        resume at a different dp degree just works; pass ``False`` to
        insist on the exact topology."""
        if self.manager is None:
            raise ValueError("resume() needs a CheckpointManager")
        info = self.read_restart(self.manager.directory)
        path = info.get("checkpoint") if info else None
        if allow_reshard is None:
            allow_reshard = bool(info.get("allow_reshard")) if info else False
        if (info and info.get("legal_resume_dp")
                and self.dp_degree not in info["legal_resume_dp"]):
            raise ValueError(
                f"dp={self.dp_degree} is not a legal resume degree for "
                f"{path} (legal: {info['legal_resume_dp']}) — the "
                "shard_multiple arithmetic cannot divide this topology")
        state, step = self.manager.restore(
            target=template, path=path, allow_reshard=allow_reshard)
        if info and info.get("dp_degree") != self.dp_degree:
            self.counters["elastic_resumes_total"] += 1
            self.log.warning(
                "elastic resume: checkpoint written at dp=%s, resuming at "
                "dp=%d (reshard %s)", info.get("dp_degree"), self.dp_degree,
                "on" if allow_reshard else "OFF")
        return state, step

    # -- reporting ---------------------------------------------------------
    def summary(self) -> Dict[str, Any]:
        out = dict(self.counters)
        out["exited"] = self.exited
        if self.straggler is not None:
            out["straggler_flags_total"] = self.straggler.flags_total
        if self.chaos is not None:
            out["chaos"] = self.chaos.summary()
        return out


class _EscalationNeeded(Exception):
    """Internal: transient retries exhausted, consult the ladder."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause

"""Straggler and silent-data-corruption sentinels for the train loop.

Two failure modes the guard ladder cannot see on its own:

* **Stragglers** — a rank that still makes progress but 4× slower than
  its peers (thermal throttling, a sick host, a noisy neighbour) drags
  the whole synchronous step down without ever producing a NaN. The
  :class:`StragglerSentinel` is a host-side robust-z detector over the
  per-rank step-time gauge: median + MAD across ranks, flag a rank whose
  modified z-score clears the threshold AND whose time clears a relative
  slack (so microsecond jitter on a fast step never flags). Flags count
  into ``straggler_flags_total`` and fire through the PR-14 alert plane
  (``AlertEngine.fire`` — the external-detector one-shot entry), so a
  straggler pages exactly like an SLO burn.

* **Silent data corruption** — a chip that flips bits without faulting
  poisons the run through the grads while every value stays finite
  (fleet-scale SDC is routine at TPU-pod scale). The :class:`SDCSentinel`
  is a periodic cross-replica agreement check: after the grad psum the
  gradients are identical on every rank BY CONSTRUCTION, so a rank-local
  f32 checksum all-gathered to a ``(dp,)`` vector must be constant — any
  spread means a rank computed different bytes. The disagreement flag is
  computed from the SAME gathered vector on every rank, so it is
  rank-uniform by construction (no desynchronized branches), counts into
  ``sdc_disagreements_total``, and feeds the guard ladder through
  ``AnomalyGuard.check(found_inf=flag)`` — a corrupting chip trips
  skip → rollback → halt instead of silently walking the loss away.

Both are zero-false-positive on a clean run: identical step times give
MAD 0 and no flags; identical post-psum grads give spread 0.
:meth:`SDCSentinel.disagreement` is the stock-jax-safe core (pure math on
a ``(dp,)`` array); :meth:`SDCSentinel.check` adds the in-graph
``all_gather`` for real mesh programs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.parallel.mesh import DP_AXIS

Pytree = Any

__all__ = ["SDCSentinel", "StragglerSentinel", "grad_checksum"]


def grad_checksum(grads: Pytree) -> jnp.ndarray:
    """Deterministic f32 checksum of a grad pytree: Σ leaf-sums. Cheap
    (fuses into the sweep that already reads the leaves), and identical
    across ranks whenever the grads are — the SDC agreement quantity."""
    leaves = [x for x in jax.tree_util.tree_leaves(grads)
              if jnp.issubdtype(jnp.result_type(x), jnp.inexact)]
    if not leaves:
        return jnp.float32(0.0)
    return sum(jnp.sum(x.astype(jnp.float32)) for x in leaves)


@dataclasses.dataclass(frozen=True)
class SDCSentinel:
    """Cross-replica grad-checksum agreement (static config; pure
    methods — the guard/scaler architecture).

    ``axis_name``: the dp mesh axis the check gathers over.
    ``every``: check period in steps (the checksum itself is nearly
    free; the knob exists so the gather can be amortized on latency-bound
    multi-host meshes).
    ``tol``: absolute spread tolerated before flagging — 0.0 for the
    post-psum case (bitwise-identical by construction); set a small
    epsilon only if the checksum is computed pre-reduction.
    """

    axis_name: str = DP_AXIS
    every: int = 1
    tol: float = 0.0

    def __post_init__(self):
        if self.every < 1:
            raise ValueError(f"every must be >= 1, got {self.every}")
        if self.tol < 0:
            raise ValueError(f"tol must be >= 0, got {self.tol}")

    @staticmethod
    def disagreement(checksums: jnp.ndarray,
                     tol: float = 0.0) -> jnp.ndarray:
        """f32 0/1 flag from the gathered ``(dp,)`` checksum vector —
        rank-uniform because every rank evaluates the same reduction of
        the same gathered values. NaN-safe: a non-finite checksum on any
        rank also flags (it cannot agree with anything)."""
        checksums = jnp.asarray(checksums, jnp.float32)
        spread = jnp.max(checksums) - jnp.min(checksums)
        bad = (spread > tol) | ~jnp.isfinite(spread)
        return bad.astype(jnp.float32)

    def check(
        self,
        grads: Pytree,
        step: Optional[jnp.ndarray] = None,
        metrics: Optional[Any] = None,
    ) -> Union[jnp.ndarray, Tuple[jnp.ndarray, Any]]:
        """In-graph check (call inside the mesh program, AFTER the grad
        psum/reduce-scatter consumed the same tensors): returns the
        rank-uniform f32 0/1 disagreement flag, gated to fire only on
        ``step % every == 0`` steps when ``step`` is passed. With
        ``metrics``, accumulates ``sdc_disagreements_total`` and returns
        ``(flag, metrics)``. Feed the flag to
        ``AnomalyGuard.check(found_inf=...)`` to ride the ladder."""
        local = grad_checksum(grads)
        sums = lax.all_gather(local, self.axis_name)
        flag = self.disagreement(sums, self.tol)
        if step is not None and self.every > 1:
            due = (jnp.asarray(step) % self.every) == 0
            flag = jnp.where(due, flag, 0.0)
        if metrics is not None:
            return flag, metrics.accumulate(sdc_disagreements_total=flag)
        return flag


class StragglerSentinel:
    """Host-side per-rank step-time straggler detector (robust z over the
    cross-rank distribution at each step).

    ``threshold``: modified z-score (0.6745·dev/MAD) above which a rank
    flags. ``slack``: the rank's time must ALSO exceed ``slack ×
    median`` — the absolute guard that keeps MAD-relative jitter on a
    fast step from flagging. ``min_ranks``: below this many ranks the
    median is meaningless and the sentinel stays quiet.

    ``alerts``: an optional :class:`apex_tpu.monitor.alerts.AlertEngine`
    — each flag fires a one-shot ``straggler`` alert with the rank and
    times in context (the PR-14 external-detector entry). ``sink``: an
    optional monitor JSONL sink for a per-flag record.
    """

    def __init__(self, threshold: float = 4.0, slack: float = 1.5,
                 min_ranks: int = 3, alerts: Optional[Any] = None,
                 sink: Optional[Any] = None):
        if threshold <= 0 or slack < 1.0:
            raise ValueError(
                f"threshold must be > 0 and slack >= 1.0, got "
                f"{threshold}/{slack}")
        self.threshold = float(threshold)
        self.slack = float(slack)
        self.min_ranks = int(min_ranks)
        self.alerts = alerts
        self.sink = sink
        self.flags_total = 0
        self.flagged: List[Tuple[int, int, float, float]] = []

    def observe(self, step: int, rank_times: Sequence[float]) -> List[int]:
        """One step's per-rank wall times (seconds); returns the flagged
        rank indices (usually empty). Zero false positives on a uniform
        fleet: identical times give deviation 0 everywhere."""
        times = np.asarray(list(rank_times), dtype=np.float64)
        if times.size < self.min_ranks or not np.all(np.isfinite(times)):
            return []
        med = float(np.median(times))
        if med <= 0.0:
            return []
        mad = float(np.median(np.abs(times - med)))
        # MAD collapses to 0 when >half the ranks tie (the common clean
        # case AND the one-outlier case) — fall back to a small fraction
        # of the median so a genuine outlier still scores, while exact
        # ties score z=0
        scale = mad if mad > 0.0 else 0.01 * med
        out = []
        for r, t in enumerate(times):
            z = 0.6745 * (t - med) / scale
            if z > self.threshold and t > self.slack * med:
                out.append(r)
        for r in out:
            self.flags_total += 1
            self.flagged.append((int(step), r, float(times[r]), med))
            if self.alerts is not None:
                self.alerts.fire(
                    "straggler", float(step), severity="warn", rank=r,
                    step_time_s=float(times[r]), median_s=med)
            if self.sink is not None:
                self.sink.write(step=int(step), straggler_rank=r,
                                step_time_s=float(times[r]),
                                median_step_time_s=med,
                                straggler_flags_total=self.flags_total)
        return out

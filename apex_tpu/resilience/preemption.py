"""Preemption handling — save-and-exit on SIGTERM, plus a stall watchdog.

Reference context: the reference leaves preemption to the user (a CUDA job
that catches SIGTERM mid-``torch.save`` corrupts its own checkpoint). On
TPU pods preemption is *routine* — maintenance events and spot reclaims
deliver SIGTERM with a grace window — and under multi-process SPMD every
process must agree on the step it saves at, or the sharded/replicated state
written by different processes describes different steps.

:class:`PreemptionHandler` turns the signal into a cooperative, barriered
save: the handler only sets a flag; the train loop polls
:meth:`PreemptionHandler.sync_save_step` once per step, which (under
``jax.distributed``) max-reduces ``(flag, step)`` across processes so all
of them pick the SAME save step — the process that got the signal late
still saves at the agreed step. The save itself goes through the atomic
:class:`~apex_tpu.resilience.checkpoint.CheckpointManager`, so even a
too-short grace window leaves the previous valid checkpoint behind.

:class:`StallWatchdog` covers the opposite failure: the job is *not*
preempted but stopped making progress (deadlocked collective, wedged host).
A daemon thread watches wall-clock time since the last :meth:`tick`; on
expiry it dumps per-thread stacks and a diagnostic record through the
monitor JSONL sink, then (optionally) invokes a callback.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
import time
import traceback
from typing import Any, Callable, Iterable, Optional

import jax
import numpy as np


class PreemptionHandler:
    """Cooperative SIGTERM/preemption handler. Typical loop::

        mgr = CheckpointManager(ckpt_dir)
        pre = PreemptionHandler()                 # installs SIGTERM handler
        for step in range(start, n):
            state = train_step(state, ...)
            save_at = pre.sync_save_step(step)    # multihost agreement
            if save_at is not None:
                mgr.save(state, save_at + 1, block=True)
                break                             # exit inside the grace window

    :meth:`trigger` simulates a preemption (what
    :func:`apex_tpu.resilience.chaos.PreemptionAtStep` calls) — same code
    path as the real signal, minus the kernel.
    """

    def __init__(
        self,
        signals: Iterable[int] = (signal.SIGTERM,),
        sync_every: int = 1,
        install: bool = True,
    ):
        self._flag = threading.Event()
        self._signals = tuple(signals)
        self._previous = {}
        self.sync_every = max(1, int(sync_every))
        self.signaled_at: Optional[float] = None
        if install:
            self.install()

    # -- signal plumbing ---------------------------------------------------
    def install(self) -> None:
        """Install handlers (main thread only — signal module contract).
        The previous handlers are remembered and still called, so an outer
        supervisor's SIGTERM hook keeps working."""
        for sig in self._signals:
            self._previous[sig] = signal.signal(sig, self._on_signal)

    def uninstall(self) -> None:
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()

    def _on_signal(self, signum, frame) -> None:
        self.trigger()
        prev = self._previous.get(signum)
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)

    def trigger(self) -> None:
        """Mark this process preempted (signal handler body; also the
        chaos-test entry point)."""
        if not self._flag.is_set():
            self.signaled_at = time.monotonic()
        self._flag.set()

    def preempted(self) -> bool:
        """This process's local flag (pre-barrier)."""
        return self._flag.is_set()

    # -- the barrier -------------------------------------------------------
    def sync_save_step(self, step: int) -> Optional[int]:
        """Poll once per step. Returns the agreed save step when ANY
        process has been preempted, else ``None``.

        Under multi-process ``jax.distributed`` the decision is a max-
        reduce of ``(preempted, step)`` over processes: everyone returns
        the same step (the max proposed — processes can be a step apart
        when the signal lands mid-step), so the checkpoint the survivors
        write describes one consistent step. Single-process: the local
        flag. ``sync_every > 1`` amortizes the collective by only
        participating every Nth step (every process must use the same
        value — it is part of the SPMD program's control flow)."""
        if step % self.sync_every != 0:
            return None
        if jax.process_count() <= 1:
            return step if self._flag.is_set() else None
        from jax.experimental import multihost_utils

        local = np.asarray(
            [1 if self._flag.is_set() else 0, int(step)], dtype=np.int64)
        agreed = np.max(
            np.asarray(multihost_utils.process_allgather(local)), axis=0)
        if int(agreed[0]) == 0:
            return None
        self._flag.set()  # adopt the cluster-wide decision locally
        return int(agreed[1])


class StallWatchdog:
    """Wall-clock step-stall watchdog. ``tick()`` every step; if no tick
    arrives within ``timeout_s`` the watchdog dumps diagnostics — one
    JSONL record (via ``sink`` or the module logger) plus every thread's
    stack — and fires ``on_stall``. One shot per stall: it re-arms on the
    next tick. ``start()``/``stop()`` manage the daemon thread; usable as
    a context manager.

    ``clock`` defaults to ``time.monotonic``; pass any zero-arg float
    callable (seconds) to run the watchdog on a different clock — the
    serve cluster drives per-worker watchdogs from its shared EventLog
    clock, and tests drive a manual clock with :meth:`check` directly
    (no daemon thread, no sleeps)."""

    def __init__(
        self,
        timeout_s: float,
        sink: Optional[Any] = None,
        on_stall: Optional[Callable[[float], Any]] = None,
        poll_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if timeout_s <= 0:
            raise ValueError("timeout_s must be > 0")
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s) if poll_s else min(1.0, timeout_s / 4)
        self.sink = sink
        self.on_stall = on_stall
        self.stalls = 0
        self._clock = clock
        self._last = clock()
        self._last_step: Optional[int] = None
        self._fired = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def tick(self, step: Optional[int] = None) -> None:
        self._last = self._clock()
        self._last_step = step
        self._fired = False

    def check(self, now: Optional[float] = None) -> bool:
        """Run the expiry logic once (what the daemon thread does every
        ``poll_s``): if no tick arrived within ``timeout_s`` of ``now``
        (default: the watchdog's clock), dump diagnostics and fire
        ``on_stall``. Returns True iff this call fired — one shot per
        stall, re-armed by the next tick. Callable without ``start()``
        for manual-clock drivers."""
        idle = (self._clock() if now is None else float(now)) - self._last
        if idle >= self.timeout_s and not self._fired:
            self._fired = True  # one report per stall
            self.stalls += 1
            self._report(idle)
            return True
        return False

    def start(self) -> "StallWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self.tick(self._last_step)
            self._thread = threading.Thread(
                target=self._run, name="apex-tpu-stall-watchdog", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5 * self.poll_s + 1)
            self._thread = None

    __enter__ = start

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- internals ---------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self.check()

    def _report(self, idle: float) -> None:
        from apex_tpu._logging import get_logger

        names = {t.ident: t.name for t in threading.enumerate()}
        parts = []
        for ident, frame in sys._current_frames().items():
            parts.append(f"Thread {names.get(ident, ident)}:")
            parts.extend(
                line.rstrip() for line in traceback.format_stack(frame))
        stacks = "\n".join(parts)
        log = get_logger("apex_tpu.resilience")
        log.error(
            "step stall: no progress for %.1fs (last step %s, pid %d) — "
            "dumping thread stacks", idle, self._last_step, os.getpid())
        for line in stacks.splitlines():
            log.error("  %s", line)
        if self.sink is not None:
            try:
                self.sink.write(step=self._last_step, stall_s=round(idle, 3),
                                stalls_total=self.stalls, stacks=stacks)
                self.sink.flush()
            except Exception:
                log.exception("stall watchdog could not write to sink")
        if self.on_stall is not None:
            try:
                self.on_stall(idle)
            except Exception:
                log.exception("on_stall callback raised")

"""Segment-aware (packed varlen) flash attention — Pallas TPU kernels.

Reference: ``apex/contrib/csrc/fmha/`` (7.3k LoC CUDA) — fused attention
over token-packed variable-length batches, driven by
``apex/contrib/fmha/fmha.py:33-76`` with ``cu_seqlens`` prefix sums. The
kernel family exists precisely so packed batches never materialize the
(total, total) score matrix; it is hard-limited to seqlen <= 512.

TPU re-design: the flash scheme of ``ops/attention.py`` extended with
per-token integer segment ids (-1 = padding):

* an in-tile mask ``allowed = (seg_q == seg_k) & (seg_q >= 0)`` — pads
  match nothing, including other pads, and fully-masked query rows emit
  zero output (the reference kernels also zero pad outputs);
* **block-level early exit**: per-block segment [min, max] ranges are
  precomputed on the host side of the launch and passed through scalar
  prefetch; a K/V block whose segment range cannot intersect the Q block's
  is skipped before any MXU work. Packed sequences are contiguous, so for
  a batch of length-L sequences this recovers the O(total x L) work of the
  reference's per-sequence launch without its seqlen limit.

Backward masks ``p`` explicitly (a pad row has lse == NEG_INF and
``exp(s - lse)`` would resurrect as 1), then follows the standard flash
dQ / dK+dV accumulation kernels.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax import lax

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

from apex_tpu.ops._pallas_util import sds as _sds
from apex_tpu.ops._pallas_util import compiled_backend as _compiled_backend
from apex_tpu.ops.attention import NEG_INF, _pick_block


# ---------------------------------------------------------------------------
# Dense reference (ground truth + fallback)

def attention_varlen_reference(q, k, v, seg_q, seg_k=None,
                               causal: bool = False,
                               scale: Optional[float] = None):
    """Dense segment-masked attention; pad (seg < 0) query rows output 0.

    ``q``/``k``/``v``: (b, h, s, d); ``seg_q``/``seg_k``: (b, s) int32.
    """
    if seg_k is None:
        seg_k = seg_q
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    allowed = ((seg_q[:, None, :, None] == seg_k[:, None, None, :])
               & (seg_q[:, None, :, None] >= 0))
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        allowed = allowed & (jnp.arange(sk)[None, None, None, :]
                             <= jnp.arange(sq)[None, None, :, None])
    s = jnp.where(allowed, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    p = jnp.where(allowed, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    o = o / jnp.where(l == 0.0, 1.0, l)
    return o.astype(q.dtype)


# ---------------------------------------------------------------------------
# Kernels. Grid (b, h, nq, nk) — batch and head split so the scalar-prefetch
# block ranges (b, nq)/(b, nk) index directly by the first grid dim.

# Mosaic requires a block's last two dims to be (8k, 128k)-divisible or
# equal to the full array dims; a (1, block) slice of a (b, s) id array is
# neither. Widen host-side instead (the jax.experimental flash kernel's
# scheme): q ids broadcast along a 128-lane axis -> (b, sq, 128) so a
# (1, block_q, 128) block is tile-legal and column 0 is the id column;
# kv ids broadcast along an 8-sublane axis -> (b, 8, sk) so a
# (1, 8, block_k) block is legal and row 0 is the id row.
_SEG_LANES = 128
_SEG_SUBLANES = 8


def _pick_kv_block(sk: int, want: int):
    """KV block size whose seg-id block is Mosaic-legal: the (1, 8, block_k)
    seg_k tile has block_k on the LANE dim, so it must be a multiple of 128
    — or one full-seq block (block == array dim is always legal; sublane
    rules still need sk % 8 == 0). A sub-128 ``want`` is coerced UP to the
    smallest legal size (128) rather than down: 128 divides every seq a
    sub-128 power-of-two block would have divided more often than not, and
    honoring the hint exactly is impossible. Returns None when nothing is
    legal (callers fall back to the dense reference)."""
    for cand in (1024, 512, 256, 128):
        if cand <= max(want, 128) and cand <= sk and sk % cand == 0:
            return cand
    if sk % 8 == 0 and sk <= 2048:  # one block; cap keeps K/V tiles in VMEM
        return sk
    return None


def _seg_wide(seg_q, seg_k):
    """(b, sq)/(b, sk) int32 ids -> tile-legal (b, sq, 128) / (b, 8, sk)."""
    b, sq = seg_q.shape
    sk = seg_k.shape[1]
    segq3 = jax.lax.broadcast_in_dim(seg_q, (b, sq, _SEG_LANES), (0, 1))
    segk3 = jax.lax.broadcast_in_dim(seg_k, (b, _SEG_SUBLANES, sk), (0, 2))
    return segq3, segk3


def _seg_tile(seg_q_ref, seg_k_ref):
    """(1, bq, 128) x (1, 8, bk) segment blocks -> (bq, bk) allowed mask."""
    sq_col = seg_q_ref[0, :, :1]  # (bq, 1)
    sk_row = seg_k_ref[0, :1, :]  # (1, bk)
    return (sq_col == sk_row) & (sq_col >= 0)


def _skip(qmin_ref, qmax_ref, kmin_ref, kmax_ref, b_i, q_i, kv_i,
          causal, block_q, block_k):
    interact = ~((qmin_ref[b_i, q_i] > kmax_ref[b_i, kv_i])
                 | (qmax_ref[b_i, q_i] < kmin_ref[b_i, kv_i]))
    run = interact & (qmax_ref[b_i, q_i] >= 0) & (kmax_ref[b_i, kv_i] >= 0)
    if causal:
        run = run & (kv_i * block_k <= q_i * block_q + block_q - 1)
    return run


def _vl_fwd_kernel(qmin_ref, qmax_ref, kmin_ref, kmax_ref, jlo_ref, jhi_ref,
                   seg_q_ref, seg_k_ref, q_ref, k_ref, v_ref,
                   o_ref, lse_ref, m_scr, l_scr, acc_scr,
                   *, scale, causal, block_q, block_k, nk):
    b_i = pl.program_id(0)
    q_i = pl.program_id(2)
    kv_i = pl.program_id(3)

    @pl.when(kv_i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = _skip(qmin_ref, qmax_ref, kmin_ref, kmax_ref, b_i, q_i, kv_i,
                causal, block_q, block_k)

    @pl.when(run)
    def _compute():
        # model dtype straight into the MXU (fp32 upcast would leave the
        # fast bf16 matmul path); accumulation stays fp32
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        allowed = _seg_tile(seg_q_ref, seg_k_ref)
        if causal:
            qpos = q_i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kv_i * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            allowed = allowed & (kpos <= qpos)
        s = jnp.where(allowed, s, NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(allowed, p, 0.0)  # all-masked rows: m_new = NEG_INF
        corr = jnp.exp(m_prev - m_new)
        corr = jnp.where(m_prev <= NEG_INF / 2, 0.0, corr)
        l_scr[:, :1] = corr * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(kv_i == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.where(
            l == 0.0, NEG_INF, m_scr[:, :1] + jnp.log(safe_l))


def _vl_bwd_dq_kernel(qmin_ref, qmax_ref, kmin_ref, kmax_ref, jlo_ref,
                      jhi_ref, seg_q_ref, seg_k_ref, q_ref, k_ref, v_ref,
                      do_ref, lse_ref, delta_ref, dq_ref, dq_scr,
                      *, scale, causal, block_q, block_k, nk):
    b_i = pl.program_id(0)
    q_i = pl.program_id(2)
    kv_i = pl.program_id(3)

    @pl.when(kv_i == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = _skip(qmin_ref, qmax_ref, kmin_ref, kmax_ref, b_i, q_i, kv_i,
                causal, block_q, block_k)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        allowed = _seg_tile(seg_q_ref, seg_k_ref)
        if causal:
            qpos = q_i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kv_i * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            allowed = allowed & (kpos <= qpos)
        # mask p by value: pad rows have lse == NEG_INF and exp(s - lse)
        # would otherwise resurrect to 1
        p = jnp.where(allowed, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot(ds.astype(k.dtype), k,
                                 preferred_element_type=jnp.float32)

    @pl.when(kv_i == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _vl_bwd_dkv_kernel(qmin_ref, qmax_ref, kmin_ref, kmax_ref, ilo_ref,
                       ihi_ref, seg_q_ref, seg_k_ref, q_ref, k_ref, v_ref,
                       do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_scr,
                       dv_scr, *, scale, causal, block_q, block_k, nq):
    b_i = pl.program_id(0)
    kv_i = pl.program_id(2)
    q_i = pl.program_id(3)

    @pl.when(q_i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = _skip(qmin_ref, qmax_ref, kmin_ref, kmax_ref, b_i, q_i, kv_i,
                causal, block_q, block_k)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        allowed = _seg_tile(seg_q_ref, seg_k_ref)
        if causal:
            qpos = q_i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kv_i * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            allowed = allowed & (kpos <= qpos)
        p = jnp.where(allowed, jnp.exp(s - lse), 0.0)
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(q_i == nq - 1)
    def _finish():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# Launch plumbing

def _block_ranges(seg, block):
    """(b, s) -> per-block (b, s//block) min and max segment ids."""
    b, s = seg.shape
    r = seg.reshape(b, s // block, block)
    return r.min(axis=2), r.max(axis=2)


def _interact_matrix(qmin, qmax, kmin, kmax, causal, block_q, block_k):
    """(b, nq, nk) bool: can q block i and kv block j interact at all?
    Mirrors the kernel-side ``_skip`` predicate exactly."""
    inter = ((qmin[:, :, None] <= kmax[:, None, :])
             & (qmax[:, :, None] >= kmin[:, None, :])
             & (qmax[:, :, None] >= 0) & (kmax[:, None, :] >= 0))
    if causal:
        nq, nk = qmin.shape[1], kmin.shape[1]
        i = jnp.arange(nq)[None, :, None]
        j = jnp.arange(nk)[None, None, :]
        inter = inter & (j * block_k <= i * block_q + block_q - 1)
    return inter


def _live_range(inter, axis):
    """First/last True index along ``axis`` of the interact matrix (0 when
    the row is empty — the clamp target is arbitrary for rows the kernel's
    ``run`` predicate skips entirely)."""
    n = inter.shape[axis]
    any_ = inter.any(axis=axis)
    lo = jnp.where(any_, jnp.argmax(inter, axis=axis), 0)
    hi = jnp.where(any_,
                   n - 1 - jnp.argmax(jnp.flip(inter, axis=axis), axis=axis),
                   0)
    return lo.astype(jnp.int32), hi.astype(jnp.int32)


def _vl_call(q, k, v, seg_q, seg_k, scale, causal, block_q, block_k,
             interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nq, nk = sq // block_q, sk // block_k
    qmin, qmax = _block_ranges(seg_q, block_q)
    kmin, kmax = _block_ranges(seg_k, block_k)
    # per-q-block live kv range: index maps clamp the kv fetch into it so
    # skipped iterations re-request an edge block (Mosaic elides the
    # repeated copy) instead of streaming dead K/V
    inter = _interact_matrix(qmin, qmax, kmin, kmax, causal,
                             block_q, block_k)
    jlo, jhi = _live_range(inter, axis=2)

    def kv_index(b, h, i, j, qmn, qmx, kmn, kmx, jlo, jhi):
        jc = jnp.clip(j, jlo[b, i], jhi[b, i])
        return (b, h, jc, 0)

    def segk_index(b, h, i, j, qmn, qmx, kmn, kmx, jlo, jhi):
        return (b, 0, jnp.clip(j, jlo[b, i], jhi[b, i]))

    seg_q3, seg_k3 = _seg_wide(seg_q, seg_k)
    kernel = functools.partial(
        _vl_fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, _SEG_LANES),
                         lambda b, h, i, j, *_: (b, i, 0)),
            pl.BlockSpec((1, _SEG_SUBLANES, block_k), segk_index),
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j, *_: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
            pl.BlockSpec((1, 1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j, *_: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j, *_: (b, h, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            _sds((b, h, sq, d), q.dtype, q, k, v),
            _sds((b, h, sq, 1), jnp.float32, q, k, v),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qmin, qmax, kmin, kmax, jlo, jhi, seg_q3, seg_k3, q, k, v)
    return o, lse


def _vl_bwd_call(q, k, v, seg_q, seg_k, o, lse, do, scale, causal,
                 block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    nq, nk = sq // block_q, sk // block_k
    qmin, qmax = _block_ranges(seg_q, block_q)
    kmin, kmax = _block_ranges(seg_k, block_k)
    inter = _interact_matrix(qmin, qmax, kmin, kmax, causal,
                             block_q, block_k)
    jlo, jhi = _live_range(inter, axis=2)  # per q block: live kv range
    ilo, ihi = _live_range(inter, axis=1)  # per kv block: live q range
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)

    def kv_index(b, h, i, j, qmn, qmx, kmn, kmx, jlo, jhi):
        return (b, h, jnp.clip(j, jlo[b, i], jhi[b, i]), 0)

    def segk_index(b, h, i, j, qmn, qmx, kmn, kmx, jlo, jhi):
        return (b, 0, jnp.clip(j, jlo[b, i], jhi[b, i]))

    seg_q3, seg_k3 = _seg_wide(seg_q, seg_k)

    dq = pl.pallas_call(
        functools.partial(_vl_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=6,
            grid=(b, h, nq, nk),
            in_specs=[
                pl.BlockSpec((1, block_q, _SEG_LANES),
                             lambda b, h, i, j, *_: (b, i, 0)),
                pl.BlockSpec((1, _SEG_SUBLANES, block_k), segk_index),
                pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j, *_: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_k, d), kv_index),
                pl.BlockSpec((1, 1, block_k, d), kv_index),
                pl.BlockSpec((1, 1, block_q, d), lambda b, h, i, j, *_: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j, *_: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j, *_: (b, h, i, 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, block_q, d),
                                   lambda b, h, i, j, *_: (b, h, i, 0)),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        ),
        out_shape=_sds((b, h, sq, d), q.dtype, q, k, v, do),
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qmin, qmax, kmin, kmax, jlo, jhi, seg_q3, seg_k3, q, k, v, do, lse, delta)

    def q_index(b, h, j, i, qmn, qmx, kmn, kmx, ilo, ihi):
        return (b, h, jnp.clip(i, ilo[b, j], ihi[b, j]), 0)

    def q1_index(b, h, j, i, qmn, qmx, kmn, kmx, ilo, ihi):
        return (b, h, jnp.clip(i, ilo[b, j], ihi[b, j]), 0)

    def segq_index(b, h, j, i, qmn, qmx, kmn, kmx, ilo, ihi):
        return (b, jnp.clip(i, ilo[b, j], ihi[b, j]), 0)

    dk, dv = pl.pallas_call(
        functools.partial(_vl_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq=nq),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=6,
            grid=(b, h, nk, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, _SEG_LANES), segq_index),
                pl.BlockSpec((1, _SEG_SUBLANES, block_k),
                             lambda b, h, j, i, *_: (b, 0, j)),
                pl.BlockSpec((1, 1, block_q, d), q_index),
                pl.BlockSpec((1, 1, block_k, d), lambda b, h, j, i, *_: (b, h, j, 0)),
                pl.BlockSpec((1, 1, block_k, d), lambda b, h, j, i, *_: (b, h, j, 0)),
                pl.BlockSpec((1, 1, block_q, d), q_index),
                pl.BlockSpec((1, 1, block_q, 1), q1_index),
                pl.BlockSpec((1, 1, block_q, 1), q1_index),
            ],
            out_specs=[
                pl.BlockSpec((1, 1, block_k, d),
                             lambda b, h, j, i, *_: (b, h, j, 0)),
                pl.BlockSpec((1, 1, block_k, d),
                             lambda b, h, j, i, *_: (b, h, j, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
        ),
        out_shape=[
            _sds((b, h, sk, d), k.dtype, q, k, v, do),
            _sds((b, h, sk, d), v.dtype, q, k, v, do),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(qmin, qmax, kmin, kmax, ilo, ihi, seg_q3, seg_k3, q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp + public API

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _varlen(q, k, v, seg_q, seg_k, scale, causal, block_q, block_k,
            interpret):
    o, _ = _varlen_fwd(q, k, v, seg_q, seg_k, scale, causal, block_q,
                       block_k, interpret)
    return o


def _varlen_fwd(q, k, v, seg_q, seg_k, scale, causal, block_q, block_k,
                interpret):
    o, lse = _vl_call(q, k, v, seg_q, seg_k, scale, causal, block_q,
                      block_k, interpret)
    # same names as the dense flash residuals: the dots_attn remat policy
    # saves them so backward skips the forward-kernel replay
    o = checkpoint_name(o, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return o, (q, k, v, seg_q, seg_k, o, lse)


def _varlen_bwd(scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, seg_q, seg_k, o, lse = res
    dq, dk, dv = _vl_bwd_call(q, k, v, seg_q, seg_k, o, lse, do, scale,
                              causal, block_q, block_k, interpret)
    return dq, dk, dv, None, None


_varlen.defvjp(_varlen_fwd, _varlen_bwd)


def flash_attention_varlen(
    q, k, v, seg_q, seg_k=None,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    use_pallas: Optional[bool] = None,
    interpret: Optional[bool] = None,
):
    """Packed-varlen attention over (b, h, s, d) with (b, s) segment ids.

    Pads (seg < 0) attend to nothing and output zero. Pallas kernels with
    block-level segment skipping on TPU; dense masked reference elsewhere.
    ``block_k`` is a hint, not a contract: the widened seg-id lane layout
    makes sub-128 kv blocks Mosaic-illegal, so a request that resolves to
    one is coerced to the nearest legal size (a 128-multiple dividing the
    seq, else one full-seq block — which also disables block skipping).
    ``interpret`` selects interpret vs compiled Mosaic execution of the
    Pallas path and therefore only applies when that path is taken; pass
    ``use_pallas=True`` alongside it (``interpret=False`` + the
    ``force_compiled()`` context is how the AOT TPU-lowering guard runs
    Mosaic verification on a CPU box), else ValueError.
    """
    if seg_k is None:
        seg_k = seg_q
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    bq = _pick_block(sq, block_q)
    bk = _pick_kv_block(sk, block_k)
    if (_HAS_PALLAS and d % 8 == 0 and (bq is None or bk is None)
            and (use_pallas or (use_pallas is None and _compiled_backend()))):
        # seq lengths with no legal block (e.g. sk = 2056: 8-aligned but
        # not 128-divisible and past the one-block VMEM cap) would
        # otherwise drop to the dense O(s^2) reference exactly at the long
        # seqs where the kernel matters most. Pad to the next 128-multiple
        # with seg = -1 instead: padded keys match nothing, padded query
        # rows output zero and are sliced back off.
        pq = (-sq) % 128 if bq is None else 0
        pk = (-sk) % 128 if bk is None else 0
        if pq or pk:
            out = flash_attention_varlen(
                jnp.pad(q, ((0, 0), (0, 0), (0, pq), (0, 0))),
                jnp.pad(k, ((0, 0), (0, 0), (0, pk), (0, 0))),
                jnp.pad(v, ((0, 0), (0, 0), (0, pk), (0, 0))),
                jnp.pad(seg_q, ((0, 0), (0, pq)), constant_values=-1),
                jnp.pad(seg_k, ((0, 0), (0, pk)), constant_values=-1),
                causal=causal, scale=scale, block_q=block_q,
                block_k=block_k, use_pallas=use_pallas,
                interpret=interpret)
            return out[:, :, :sq]
        # pq == pk == 0: the seq is already aligned and the block pick
        # still failed (a block hint < 8 on an aligned seq) — padding
        # cannot fix that; fall through to the error/fallback below
    fits = (_HAS_PALLAS and bq is not None and bk is not None
            and d % 8 == 0)
    if use_pallas is None:
        use_pallas = fits and _compiled_backend()
    elif use_pallas and not fits:
        raise ValueError(
            f"pallas flash_attention_varlen unavailable for q {q.shape}, "
            f"k {k.shape}, block_q={block_q}, block_k={block_k}: needs "
            f"Pallas importable, head_dim % 8 == 0, and a usable block "
            f"hint (>= 8; misaligned seq lengths are padded "
            f"automatically, a too-small hint on an aligned seq is not)")
    if not use_pallas:
        if interpret is not None:
            raise ValueError(
                "interpret= only applies to the Pallas path; this call "
                "resolved to the dense reference (pass use_pallas=True "
                "to force the kernel, or drop interpret=)")
        return attention_varlen_reference(q, k, v, seg_q, seg_k,
                                          causal=causal, scale=scale)
    if interpret is None:
        interpret = not _compiled_backend()
    return _varlen(q, k, v, seg_q.astype(jnp.int32), seg_k.astype(jnp.int32),
                   scale, causal, bq, bk, interpret)

"""Fused LM-head + softmax cross-entropy — never materializes the logits.

Reference capability: ``apex/contrib/csrc/xentropy`` (fused CE that saves
lse instead of softmax) and the Megatron loss path
``apex/transformer/tensor_parallel/cross_entropy.py`` (vocab-parallel CE over
sharded logits). Both still *receive* a materialized (tokens, vocab) logits
tensor from the LM head matmul. At GPT-2 scale that tensor is the single
largest HBM consumer in the step: (32·1024, 50304) bf16 ≈ 3.3 GB written by
the head matmul, re-read by the CE forward, and re-written as dlogits in
backward — ~10 GB of HBM traffic for ~10% of the model's FLOPs.

TPU re-design: fuse the head matmul INTO the loss, flash-attention style.
A Pallas kernel streams (block_v, hidden) tiles of the projection matrix
through the MXU against (block_n, hidden) tiles of the hidden states,
keeping a running row-max / row-sum (online logsumexp) and the target-column
logit in VMEM scratch. The logits tile lives only in VMEM; HBM sees the
hidden states and the weights, each read O(nN) times. Backward recomputes
the logits tile-wise from the saved (x, w, lse) — two accumulation kernels:

* dX: grid (rows, vocab-blocks), ``dx += ((p - onehot)·g) @ W_blk``
* dW: grid (vocab-blocks, rows), ``dw += ((p - onehot)·g)ᵀ @ X_blk``

where ``p = exp(x·wᵀ − lse)`` is already normalized (the flash backward
identity). Under tensor parallelism the vocab dim is sharded: the kernel
works on the local shard and the wrapper merges per-rank (lse, target-logit)
with a pmax/psum logsumexp merge — the same three collectives as the
reference's vocab-parallel CE, on O(tokens) vectors instead of O(logits).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

from apex_tpu.ops._pallas_util import sds as _sds
from apex_tpu.ops._pallas_util import compiled_backend as _compiled_backend

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Pure-JAX reference (ground truth for tests; fallback for odd shapes).

def lm_head_loss_reference(x2, w, targets, axis_name: Optional[str] = None):
    """Per-position CE of ``logits = x2 @ wᵀ`` vs global target ids, fp32.

    ``x2``: (N, h) hidden states; ``w``: (V_local, h) vocab-sharded
    projection; ``targets``: (N,) global ids. Materializes the logits —
    use only for small shapes / verification.
    """
    logits = jnp.einsum("nh,vh->nv", x2.astype(jnp.float32),
                        w.astype(jnp.float32))
    v_local = w.shape[0]
    if axis_name is None:
        t_local = targets
        lse = jax.nn.logsumexp(logits, axis=-1)
        pred = jnp.take_along_axis(logits, t_local[:, None], axis=1)[:, 0]
        return lse - pred
    rank = lax.axis_index(axis_name)
    t_local = targets - rank * v_local
    in_range = (t_local >= 0) & (t_local < v_local)
    picked = jnp.take_along_axis(
        logits, jnp.where(in_range, t_local, 0)[:, None], axis=1)[:, 0]
    pred = lax.psum(jnp.where(in_range, picked, 0.0), axis_name)
    lse_l = jax.nn.logsumexp(logits, axis=-1)
    m = lax.pmax(lse_l, axis_name)
    lse = m + jnp.log(lax.psum(jnp.exp(lse_l - m), axis_name))
    return lse - pred


# ---------------------------------------------------------------------------
# Pallas kernels. Layouts: x (N, h), w (V, h), t/g/lse as (N, 1) columns
# (last-dim-1 blocks avoid lane<->sublane transposes, like the attention
# kernel's lse). The vocab grid dim is innermost/arbitrary; a ragged final
# vocab block is masked with a column iota (V need not divide block_v).


def _col_ids(v_i, block_n, block_v):
    return v_i * block_v + lax.broadcasted_iota(
        jnp.int32, (block_n, block_v), 1)


def _fwd_kernel(t_ref, x_ref, w_ref, lse_ref, pred_ref, m_scr, l_scr, p_scr,
                *, block_n, block_v, nv, v_total):
    v_i = pl.program_id(1)

    @pl.when(v_i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        p_scr[:] = jnp.zeros_like(p_scr)

    # model-dtype inputs straight into the MXU (bf16 x bf16 -> fp32 accum);
    # an fp32 upcast first would land on the much slower fp32 matmul path
    x = x_ref[...]
    w = w_ref[...]
    s = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    col = _col_ids(v_i, block_n, block_v)
    if v_total % block_v:
        s = jnp.where(col >= v_total, NEG_INF, s)
    t = t_ref[...]  # (block_n, 1) int32, local ids (may be out of range)
    hit = col == t
    p_scr[:, :1] += jnp.sum(jnp.where(hit, s, 0.0), axis=1, keepdims=True)
    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    l_scr[:, :1] = (l_scr[:, :1] * jnp.exp(m_prev - m_new)
                    + jnp.sum(jnp.exp(s - m_new), axis=1, keepdims=True))
    m_scr[:, :1] = m_new

    @pl.when(v_i == nv - 1)
    def _finish():
        lse_ref[...] = m_scr[:, :1] + jnp.log(l_scr[:, :1])
        pred_ref[...] = p_scr[:, :1]


def _dx_kernel(t_ref, g_ref, lse_ref, x_ref, w_ref, dx_ref, dx_scr,
               *, block_n, block_v, nv, v_total):
    v_i = pl.program_id(1)

    @pl.when(v_i == 0)
    def _init():
        dx_scr[:] = jnp.zeros_like(dx_scr)

    x = x_ref[...]
    w = w_ref[...]
    col = _col_ids(v_i, block_n, block_v)
    if v_total % block_v:
        # zero padded w rows: dl is 0 there, but 0 x (OOB-pad garbage) = NaN
        row = v_i * block_v + lax.broadcasted_iota(jnp.int32, w.shape, 0)
        w = jnp.where(row < v_total, w, jnp.zeros_like(w))
    s = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if v_total % block_v:
        s = jnp.where(col >= v_total, NEG_INF, s)
    p = jnp.exp(s - lse_ref[...])  # masked cols -> exp(NEG_INF - lse) = 0
    hit = (col == t_ref[...]).astype(jnp.float32)
    dl = (p - hit) * g_ref[...]
    dx_scr[:] += jax.lax.dot(dl.astype(w.dtype), w,
                             preferred_element_type=jnp.float32)

    @pl.when(v_i == nv - 1)
    def _finish():
        dx_ref[...] = dx_scr[:].astype(dx_ref.dtype)


def _dw_kernel(t_ref, g_ref, lse_ref, x_ref, w_ref, dw_ref, dw_scr,
               *, block_n, block_v, nn, v_total):
    v_i = pl.program_id(0)
    n_i = pl.program_id(1)

    @pl.when(n_i == 0)
    def _init():
        dw_scr[:] = jnp.zeros_like(dw_scr)

    x = x_ref[...]
    w = w_ref[...]
    s = jax.lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    col = _col_ids(v_i, block_n, block_v)
    if v_total % block_v:
        s = jnp.where(col >= v_total, NEG_INF, s)
    p = jnp.exp(s - lse_ref[...])
    hit = (col == t_ref[...]).astype(jnp.float32)
    dl = (p - hit) * g_ref[...]
    dw_scr[:] += jax.lax.dot_general(dl.astype(x.dtype), x,
                                     (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)

    @pl.when(n_i == nn - 1)
    def _finish():
        dw_ref[...] = dw_scr[:].astype(dw_ref.dtype)


def _grids(n, v, block_n, block_v):
    return n // block_n, -(-v // block_v)  # nN exact, nV ceil (ragged ok)


def _run_fwd(x2, w, t_local, block_n, block_v, interpret):
    n, h = x2.shape
    v = w.shape[0]
    nn, nv = _grids(n, v, block_n, block_v)
    kernel = functools.partial(_fwd_kernel, block_n=block_n, block_v=block_v,
                               nv=nv, v_total=v)
    lse, pred = pl.pallas_call(
        kernel,
        grid=(nn, nv),
        in_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, h), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, h), lambda i, j: (j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_shape=[
            _sds((n, 1), jnp.float32, x2, w, t_local),
            _sds((n, 1), jnp.float32, x2, w, t_local),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, 128), jnp.float32),
            pltpu.VMEM((block_n, 128), jnp.float32),
            pltpu.VMEM((block_n, 128), jnp.float32),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(t_local[:, None], x2, w)
    return lse[:, 0], pred[:, 0]


def _run_bwd(x2, w, t_local, lse, g, block_n, block_v, interpret):
    n, h = x2.shape
    v = w.shape[0]
    nn, nv = _grids(n, v, block_n, block_v)
    t2, g2, lse2 = t_local[:, None], g[:, None], lse[:, None]

    # dw streams X once per vocab block — the opposite trade from dx, which
    # streams W once per row block. Tall vocab blocks and short row blocks
    # minimize dw's X re-reads while the (block_v, h) fp32 accumulator and
    # the (block_n, block_v) score tile stay inside VMEM.
    bn_dw = 512 if block_n > 512 and n % 512 == 0 else block_n
    # only widen the vocab block while the (bv_dw, h) fp32 accumulator stays
    # within a conservative VMEM budget (cf. layer_norm's _VMEM_BUDGET_BYTES)
    bv_dw = block_v
    if block_v < 1024 <= v and 1024 * h * 4 <= 8 * 1024 * 1024:
        bv_dw = 1024  # never wider than the vocab shard (caller clamps ≤ v)
    nn_dw, nv_dw = _grids(n, v, bn_dw, bv_dw)

    dx = pl.pallas_call(
        functools.partial(_dx_kernel, block_n=block_n, block_v=block_v,
                          nv=nv, v_total=v),
        grid=(nn, nv),
        in_specs=[
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, h), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, h), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, h), lambda i, j: (i, 0)),
        out_shape=_sds((n, h), x2.dtype, x2, w, t_local, g),
        scratch_shapes=[pltpu.VMEM((block_n, h), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(t2, g2, lse2, x2, w)

    dw = pl.pallas_call(
        functools.partial(_dw_kernel, block_n=bn_dw, block_v=bv_dw,
                          nn=nn_dw, v_total=v),
        grid=(nv_dw, nn_dw),
        in_specs=[
            pl.BlockSpec((bn_dw, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((bn_dw, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((bn_dw, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((bn_dw, h), lambda j, i: (i, 0)),
            pl.BlockSpec((bv_dw, h), lambda j, i: (j, 0)),
        ],
        out_specs=pl.BlockSpec((bv_dw, h), lambda j, i: (j, 0)),
        out_shape=_sds((v, h), w.dtype, x2, w, t_local, g),
        scratch_shapes=[pltpu.VMEM((bv_dw, h), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(t2, g2, lse2, x2, w)
    return dx, dw


# ---------------------------------------------------------------------------
# Dense local impl — same (lse, pred)/(dx, dw) contract as the kernels.
# Exists so the custom_vjp + TP collectives can be exercised under the
# virtual CPU mesh, where pallas interpret mode cannot run inside shard_map
# (its re-evaluated kernel jaxpr mixes mesh-invariant iotas/scratch with
# rank-varying operands, which the VMA checker rejects).

def _dense_fwd(x2, w, t_local):
    logits = jnp.einsum("nh,vh->nv", x2.astype(jnp.float32),
                        w.astype(jnp.float32))
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
    v = w.shape[0]
    in_range = (t_local >= 0) & (t_local < v)
    picked = jnp.take_along_axis(
        logits, jnp.where(in_range, t_local, 0)[:, None], axis=1)[:, 0]
    pred = jnp.where(in_range, picked, 0.0)
    return lse, pred


def _dense_bwd(x2, w, t_local, lse, g):
    logits = jnp.einsum("nh,vh->nv", x2.astype(jnp.float32),
                        w.astype(jnp.float32))
    p = jnp.exp(logits - lse[:, None])
    v = w.shape[0]
    iota = lax.broadcasted_iota(jnp.int32, p.shape, 1)
    hit = (iota == t_local[:, None]).astype(jnp.float32)
    dl = (p - hit) * g[:, None]
    dx = (dl @ w.astype(jnp.float32)).astype(x2.dtype)
    dw = jnp.einsum("nv,nh->vh", dl, x2.astype(jnp.float32)).astype(w.dtype)
    return dx, dw


# ---------------------------------------------------------------------------
# custom_vjp over the local shard + TP merge collectives

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _lm_head_loss(x2, w, targets, axis_name, block_n, block_v, impl):
    loss, _ = _lm_fwd(x2, w, targets, axis_name, block_n, block_v, impl)
    return loss


def _localize(targets, v_local, axis_name):
    if axis_name is None:
        return targets.astype(jnp.int32)
    return (targets - lax.axis_index(axis_name) * v_local).astype(jnp.int32)


def _lm_fwd(x2, w, targets, axis_name, block_n, block_v, impl):
    t_local = _localize(targets, w.shape[0], axis_name)
    if impl == "dense":
        lse, pred = _dense_fwd(x2, w, t_local)
    else:
        lse, pred = _run_fwd(x2, w, t_local, block_n, block_v,
                             impl == "pallas_interpret")
    if axis_name is not None:
        # logsumexp merge across vocab shards + sum of the (unique) target
        # logit — the reference's MAX/SUM/SUM collective triple on O(N) data.
        m = lax.pmax(lse, axis_name)
        lse = m + jnp.log(lax.psum(jnp.exp(lse - m), axis_name))
        pred = lax.psum(pred, axis_name)
    loss = lse - pred
    return loss, (x2, w, t_local, lse)


def _lm_bwd(axis_name, block_n, block_v, impl, res, g):
    x2, w, t_local, lse = res
    g = g.astype(jnp.float32)
    if impl == "dense":
        dx, dw = _dense_bwd(x2, w, t_local, lse, g)
    else:
        dx, dw = _run_bwd(x2, w, t_local, lse, g, block_n, block_v,
                          impl == "pallas_interpret")
    # dx is this rank's partial (local vocab shard); the caller's
    # copy_to_tensor_model_parallel_region transpose psums it — same
    # contract as differentiating through a vocab-sharded matmul.
    return dx, dw, None


_lm_head_loss.defvjp(_lm_fwd, _lm_bwd)


# ---------------------------------------------------------------------------
# Public API

DEFAULT_BLOCK_N = 1024
DEFAULT_BLOCK_V = 512
_MIN_BLOCK_N = 128


def _resolve_block_n(n: int, block_n: int) -> Optional[int]:
    """Largest block ≤ ``block_n`` that divides ``n`` (halving steps down to
    the 128-row floor, sublane-aligned); None when no grid covers ``n``.
    ``pallas_fits`` and ``lm_head_loss`` both use this, so the gate and the
    op cannot disagree."""
    if n <= 0 or n % 8:
        return None
    b = min(block_n, n)
    while b >= _MIN_BLOCK_N:
        if n % b == 0 and b % 8 == 0:
            return b
        b //= 2
    return n if n < _MIN_BLOCK_N else None


def pallas_fits(n: int, h: int, block_n: int = DEFAULT_BLOCK_N) -> bool:
    """True when the kernel grid covers (n, h) exactly — callers with an
    unfused alternative (e.g. logits+CE) should check this before choosing
    the fused path, because the shape fallback below is a dense fp32
    reference, not a tuned kernel."""
    if not _HAS_PALLAS:
        return False
    return _resolve_block_n(n, block_n) is not None and h % 128 == 0


def lm_head_loss(
    x,
    w,
    targets,
    axis_name: Optional[str] = None,
    block_n: int = DEFAULT_BLOCK_N,
    block_v: int = DEFAULT_BLOCK_V,
    use_pallas: Optional[bool] = None,
):
    """Per-position CE of the projection ``x @ wᵀ`` without materializing it.

    ``x``: (..., h) hidden states; ``w``: (V_local, h); ``targets``: (...)
    int global ids. Returns fp32 loss shaped like ``targets``. Differentiable
    in ``x`` and ``w``; under TP (``axis_name``) ``dx`` is the local partial
    (reduced by the enclosing copy-to-region transpose, Megatron-style).
    """
    h = x.shape[-1]
    lead = x.shape[:-1]
    x2 = x.reshape(-1, h)
    t1 = targets.reshape(-1)
    n = x2.shape[0]
    bn = _resolve_block_n(n, block_n)
    fits = _HAS_PALLAS and bn is not None and h % 128 == 0
    if use_pallas is None:
        use_pallas = fits and _compiled_backend()
    elif use_pallas and not fits:
        raise ValueError(
            f"pallas lm_head_loss needs pallas available, a row block "
            f"dividing rows ({n}), and hidden ({h}) divisible by 128")
    if bn is None:
        bn = n  # dense impl ignores the block size
    if use_pallas:
        impl = ("pallas" if _compiled_backend()
                else "pallas_interpret")
    else:
        impl = "dense"
    loss = _lm_head_loss(x2, w, t1, axis_name, bn, min(block_v, w.shape[0]),
                         impl)
    return loss.reshape(lead)

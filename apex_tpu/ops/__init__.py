"""Kernel layer (L0) — Pallas TPU kernels + XLA-fused JAX, replacing csrc/.

Mapping to the reference extensions (SURVEY.md §2.2):

=============================================  =================================
Reference CUDA ext                             apex_tpu equivalent
=============================================  =================================
``fused_layer_norm_cuda``, ``fast_layer_norm`` ``ops.layer_norm`` (Pallas)
``scaled_masked_softmax_cuda`` (+causal)       ``ops.softmax``
``xentropy_cuda``                              ``ops.xentropy``
``mlp_cuda``, ``fused_dense_cuda``             ``apex_tpu.mlp`` / ``fused_dense``
``fmhalib``, ``fast_multihead_attn``           ``ops.flash_attention``
``amp_C`` multi-tensor kernels                 jit over pytrees (+``ops.multi_tensor``)
``multi_tensor_adam/lamb`` update kernels      ``ops.fused_update`` (Pallas)
``syncbn`` Welford kernels                     ``parallel.sync_batchnorm``
=============================================  =================================
"""

from apex_tpu.ops.attention import (  # noqa: F401
    attention_reference,
    flash_attention,
    flash_attention_with_lse,
)
from apex_tpu.ops.fused_update import (  # noqa: F401
    adam_tail_reference,
    fused_adam_tail,
    fused_lamb_tail,
    lamb_tail_reference,
)
from apex_tpu.ops.layer_norm import (  # noqa: F401
    layer_norm,
    layer_norm_reference,
    rms_norm,
    rms_norm_reference,
)
from apex_tpu.ops.softmax import (  # noqa: F401
    scaled_masked_softmax,
    scaled_softmax,
    scaled_upper_triang_masked_softmax,
)
from apex_tpu.ops.xentropy import softmax_cross_entropy_loss  # noqa: F401

__all__ = [
    "adam_tail_reference",
    "attention_reference",
    "flash_attention",
    "flash_attention_with_lse",
    "fused_adam_tail",
    "fused_lamb_tail",
    "lamb_tail_reference",
    "layer_norm",
    "layer_norm_reference",
    "rms_norm",
    "rms_norm_reference",
    "scaled_masked_softmax",
    "scaled_softmax",
    "scaled_upper_triang_masked_softmax",
    "softmax_cross_entropy_loss",
]

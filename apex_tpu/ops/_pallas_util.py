"""Shared Pallas plumbing for the kernel layer."""

from __future__ import annotations

import jax


def sds(shape, dtype, *like):
    """ShapeDtypeStruct whose varying-mesh-axes set is the union of the
    inputs' — pallas_call outputs inside shard_map (check_vma=True) must
    declare how they vary across mesh axes."""
    vma = set()
    tracked = False
    for x in like:
        try:
            vma |= set(jax.typeof(x).vma)
            tracked = True
        except (AttributeError, TypeError):
            pass
    if tracked:
        # under shard_map the vma set must be explicit even when empty
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)

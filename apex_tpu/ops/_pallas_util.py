"""Shared Pallas plumbing for the kernel layer."""

from __future__ import annotations

import contextlib
import contextvars

import jax

_FORCE_COMPILED = contextvars.ContextVar("apex_tpu_force_compiled",
                                         default=False)


@contextlib.contextmanager
def force_compiled():
    """Treat the current backend as TPU for kernel dispatch: every Pallas
    entry point selects its compiled (non-interpret) Mosaic path.

    Exists for the AOT TPU-lowering regression guard
    (``tests/test_tpu_lowering.py``): ``jit(f).trace(args).lower(
    lowering_platforms=("tpu",))`` runs Mosaic's block-shape/layout
    verification on a CPU-only box — interpret mode skips exactly those
    checks, which is how a kernel that lowers nowhere can pass the whole
    CPU suite (the varlen seg-block bug, round 4).

    AOT-lowering-only: wrap ``.trace(...).lower(...)`` calls, never code
    that EXECUTES on CPU — jit would cache the trace with
    ``interpret=False`` baked in and later executions of that cached
    callable off-chip would fail. The flag is a ``contextvars.ContextVar``
    so concurrent threads/tasks see independent values."""
    token = _FORCE_COMPILED.set(True)
    try:
        yield
    finally:
        _FORCE_COMPILED.reset(token)


def compiled_backend() -> bool:
    """True when kernel dispatch should pick the compiled Mosaic path."""
    return _FORCE_COMPILED.get() or jax.default_backend() == "tpu"


def sds(shape, dtype, *like):
    """ShapeDtypeStruct whose varying-mesh-axes set is the union of the
    inputs' — pallas_call outputs inside shard_map (check_vma=True) must
    declare how they vary across mesh axes."""
    vma = set()
    tracked = False
    for x in like:
        try:
            vma |= set(jax.typeof(x).vma)
            tracked = True
        except (AttributeError, TypeError):
            pass
    if tracked:
        # under shard_map the vma set must be explicit even when empty
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)

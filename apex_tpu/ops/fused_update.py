"""Fused optimizer update tail — one Pallas kernel per parameter leaf.

The ZeRO half of the megakernel PR (ROADMAP item 4): after the gradient
reduce-scatter, the optimizer "tail" — moment updates, bias correction,
weight decay, the update direction — is a chain of ~10 tiny elementwise
XLA ops **per leaf**. Like the q_len=1 decode step, the math is
bandwidth-trivial and the per-op dispatch dominates on a sharded state
(ZeRO shards are 1/dp of each leaf). This module fuses the whole chain
into ONE kernel per leaf:

* :func:`fused_adam_tail` — ``m' = β₁m + (1-β₁)g``, ``v' = β₂v +
  (1-β₂)g²``, ``u = (m'/c₁)/(√(v'/c₂)+ε)`` with either decay mode
  (ADAM_MODE_0 decoupled / ADAM_MODE_1 L2 — the ``multi_tensor_adam.cu``
  split), emitted as ``(u, m', v')``. The caller applies ``p - lr·u``
  (or feeds ``-lr·u`` to optax) — the one op deliberately left outside,
  since LAMB must scale ``u`` by the trust ratio first and FusedAdam's
  optax contract returns updates, not params.
* :func:`fused_lamb_tail` — the same kernel with two extra ``(1, 1)``
  outputs accumulated across the sequential grid: the LOCAL sq-sums
  ``Σp²`` and ``Σu²`` that LAMB's trust ratio needs (the Pallas analogue
  of the reference's two-stage ``multi_tensor_l2norm``); the caller
  psums them over the dp axis and applies ``p - lr·trust·u``.

Leaves are flattened, zero-padded to the fp32 tile (rows of 128 lanes,
row count a multiple of 8) and processed in row blocks; padding lanes
compute ``u = 0`` and contribute nothing to the norm accumulators, so
results are exact after the final slice. Deliberately per-leaf — fusing
across leaves would need a concat/split round-trip of the whole optimizer
state through HBM every step, trading real bandwidth for saved dispatch.

Wired behind ``fused_update=`` on the ZeRO
``DistributedFusedAdam``/``DistributedFusedLAMB`` and ``fused_tail=`` on
the single-device ``FusedAdam`` ("auto" picks the kernel only on a
compiled Mosaic backend). ``*_reference`` twins carry the identical math
for parity tests and the off-TPU fallback.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.ops._pallas_util import compiled_backend as _compiled_backend
from apex_tpu.ops._pallas_util import sds as _sds

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

_LANES = 128
_TILE = 8 * _LANES        # fp32 min tile: small leaves pad to a multiple
_BLOCK_ROWS = 512         # row block per grid step for large leaves
_TILE_BIG = _BLOCK_ROWS * _LANES  # large leaves pad to whole row blocks


# ---------------------------------------------------------------------------
# references — the exact math the ZeRO/FusedAdam ``upd`` closures ran
# before fusion (and still run when the kernel is off)


def adam_tail_reference(g, m, v, p, c1, c2, *, betas, eps,
                        weight_decay=0.0, adam_w_mode=True):
    """Elementwise Adam tail on fp32 leaves -> ``(u, m', v')``."""
    b1, b2 = betas
    if not adam_w_mode and weight_decay:
        g = g + weight_decay * p
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    u = (m_new / c1) / (jnp.sqrt(v_new / c2) + eps)
    if adam_w_mode and weight_decay:
        u = u + weight_decay * p
    return u, m_new, v_new


def lamb_tail_reference(g, m, v, p, c1, c2, *, betas, eps,
                        weight_decay=0.0):
    """LAMB tail -> ``(u, m', v', Σp², Σu²)`` (sums LOCAL — LAMB psums
    them over the dp axis before the trust ratio). LAMB's decay is always
    the decoupled ``u + wd·p`` form."""
    u, m_new, v_new = adam_tail_reference(
        g, m, v, p, c1, c2, betas=betas, eps=eps,
        weight_decay=weight_decay, adam_w_mode=True)
    return u, m_new, v_new, jnp.sum(p * p), jnp.sum(u * u)


# ---------------------------------------------------------------------------
# kernel


def _tail_kernel(c_ref, g_ref, m_ref, v_ref, p_ref, *refs,
                 b1, b2, eps, wd, adam_w, with_norms):
    if with_norms:
        u_ref, m_out, v_out, wsq_ref, usq_ref = refs
    else:
        u_ref, m_out, v_out = refs
    c1 = c_ref[0, 0]
    c2 = c_ref[0, 1]
    g = g_ref[:]
    p = p_ref[:]
    if not adam_w and wd:
        g = g + wd * p
    m_new = b1 * m_ref[:] + (1.0 - b1) * g
    v_new = b2 * v_ref[:] + (1.0 - b2) * g * g
    u = (m_new / c1) / (jnp.sqrt(v_new / c2) + eps)
    if adam_w and wd:
        u = u + wd * p
    u_ref[:] = u
    m_out[:] = m_new
    v_out[:] = v_new
    if with_norms:
        # sequential-grid accumulation into one (1, 1) block (the
        # layer_norm backward's partial-grad idiom); zero padding adds 0
        @pl.when(pl.program_id(0) == 0)
        def _init():
            wsq_ref[0, 0] = 0.0
            usq_ref[0, 0] = 0.0

        wsq_ref[0, 0] += jnp.sum(p * p)
        usq_ref[0, 0] += jnp.sum(u * u)


def _pallas_ok(allow_interpret: bool) -> bool:
    if not _HAS_PALLAS:
        return False
    return allow_interpret or _compiled_backend()


def _tail_pallas(g, m, v, p, c1, c2, *, betas, eps, weight_decay,
                 adam_w_mode, with_norms, interpret):
    shape = g.shape
    n = g.size
    # one grid step for small leaves; fixed 512-row blocks for large ones
    # (padding a leaf out to whole blocks costs < 256 KiB fp32 and keeps
    # the grid short — grid steps are pure overhead for elementwise work)
    pad = (-n) % (_TILE if n <= _TILE_BIG else _TILE_BIG)
    flat = [jnp.pad(a.reshape(-1).astype(jnp.float32), (0, pad))
            for a in (g, m, v, p)]
    rows = (n + pad) // _LANES
    block = min(rows, _BLOCK_ROWS)
    mats = [a.reshape(rows, _LANES) for a in flat]
    c = jnp.stack([jnp.asarray(c1, jnp.float32),
                   jnp.asarray(c2, jnp.float32)]).reshape(1, 2)
    b1, b2 = betas
    kernel = functools.partial(
        _tail_kernel, b1=b1, b2=b2, eps=eps, wd=weight_decay,
        adam_w=adam_w_mode, with_norms=with_norms)
    row_spec = pl.BlockSpec((block, _LANES), lambda i: (i, 0))
    out_specs = [row_spec, row_spec, row_spec]
    out_shape = [_sds((rows, _LANES), jnp.float32, g, m, v, p)] * 3
    if with_norms:
        out_specs += [pl.BlockSpec((1, 1), lambda i: (0, 0))] * 2
        out_shape += [_sds((1, 1), jnp.float32, g, m, v, p)] * 2
    out = pl.pallas_call(
        kernel,
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # (1, 2) c1/c2
            row_spec, row_spec, row_spec, row_spec,
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(c, *mats)

    def unpad(a):
        return a.reshape(-1)[:n].reshape(shape)

    u, m_new, v_new = (unpad(a) for a in out[:3])
    if with_norms:
        return u, m_new, v_new, out[3][0, 0], out[4][0, 0]
    return u, m_new, v_new


def fused_adam_tail(g, m, v, p, c1, c2, *, betas, eps,
                    weight_decay=0.0, adam_w_mode=True,
                    use_pallas: Optional[bool] = None,
                    interpret: Optional[bool] = None) -> Tuple:
    """Dispatching front door: ONE fused kernel for the whole Adam tail of
    one (shard) leaf, reference math elsewhere. ``c1``/``c2`` are the
    (traced) bias corrections ``1 - βᵗ``. Inputs any shape/dtype; results
    fp32 in the input shape. Returns ``(u, m', v')`` — apply with
    ``p - lr·u``."""
    if use_pallas is None:
        use_pallas = _pallas_ok(allow_interpret=False)
    elif use_pallas and not _pallas_ok(allow_interpret=True):
        raise ValueError("pallas fused_adam_tail needs pallas importable")
    if not use_pallas:
        if interpret is not None:
            raise ValueError("interpret= only applies to the Pallas path")
        return adam_tail_reference(
            g.astype(jnp.float32), m, v, p, c1, c2, betas=betas, eps=eps,
            weight_decay=weight_decay, adam_w_mode=adam_w_mode)
    if interpret is None:
        interpret = not _compiled_backend()
    return _tail_pallas(g, m, v, p, c1, c2, betas=betas, eps=eps,
                        weight_decay=weight_decay, adam_w_mode=adam_w_mode,
                        with_norms=False, interpret=interpret)


def fused_lamb_tail(g, m, v, p, c1, c2, *, betas, eps,
                    weight_decay=0.0,
                    use_pallas: Optional[bool] = None,
                    interpret: Optional[bool] = None) -> Tuple:
    """LAMB variant: ``(u, m', v', Σp², Σu²)`` with the trust-ratio
    sq-sums accumulated in-kernel (LOCAL — psum them over dp, then
    ``p - lr·trust·u``)."""
    if use_pallas is None:
        use_pallas = _pallas_ok(allow_interpret=False)
    elif use_pallas and not _pallas_ok(allow_interpret=True):
        raise ValueError("pallas fused_lamb_tail needs pallas importable")
    if not use_pallas:
        if interpret is not None:
            raise ValueError("interpret= only applies to the Pallas path")
        return lamb_tail_reference(
            g.astype(jnp.float32), m, v, p, c1, c2, betas=betas, eps=eps,
            weight_decay=weight_decay)
    if interpret is None:
        interpret = not _compiled_backend()
    return _tail_pallas(g, m, v, p, c1, c2, betas=betas, eps=eps,
                        weight_decay=weight_decay, adam_w_mode=True,
                        with_norms=True, interpret=interpret)


def resolve_fused(mode: str, what: str = "fused_update") -> bool:
    """``"auto" | "on" | "off"`` -> whether to run the fused kernels.
    ``auto`` picks them only where they are a win — a compiled Mosaic
    backend; off-TPU the interpreter just re-expands the kernel body into
    the same XLA ops, saving no dispatch (``"on"`` forces exactly that,
    which is how the parity tests run)."""
    if mode == "off":
        return False
    if mode == "on":
        if not _HAS_PALLAS:
            raise ValueError(f"{what}='on' but pallas is not importable")
        return True
    if mode == "auto":
        return _HAS_PALLAS and _compiled_backend()
    raise ValueError(
        f"{what} must be 'auto', 'on' or 'off', got {mode!r}")

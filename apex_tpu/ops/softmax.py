"""Fused scale + mask + softmax — the Megatron softmax kernels, TPU-style.

Reference: ``csrc/megatron/scaled_masked_softmax*`` (padding-mask variant) and
``scaled_upper_triang_masked_softmax*`` (causal variant), driven by
``apex/transformer/functional/fused_softmax.py:21-199``. The CUDA kernels
exist to fuse scale→mask→softmax→(bwd from saved output) into one pass and are
shape-limited (fp16/bf16, sk ≤ 2048).

TPU re-design: the fusion itself is XLA's bread and butter — a single jitted
``scale→where→softmax`` chain compiles to one fused loop — so the kernels
here are expressed as pure JAX with a ``custom_vjp`` that reproduces the
reference's *backward-from-saved-softmax-output* memory trade (the reference
saves the softmax output instead of the input, ``fused_softmax.py:30-42``),
in fp32 accumulation, with **no sequence-length limit**. The masked-out value
is -10000.0, matching the reference kernels' fill.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

MASK_FILL = -10000.0


def _softmax_last(x32):
    m = jnp.max(x32, axis=-1, keepdims=True)
    e = jnp.exp(x32 - m)
    return e / jnp.sum(e, axis=-1, keepdims=True)


def _softmax_bwd_from_output(y, dy):
    """dx = (dy - sum(dy*y)) * y — the saved-output backward used by both
    reference kernels (scaled_masked_softmax.h backward)."""
    y32 = y.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    s = jnp.sum(dy32 * y32, axis=-1, keepdims=True)
    return (dy32 - s) * y32


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def scaled_masked_softmax(x, mask, scale: float = 1.0):
    """softmax(scale * x masked by `mask`) over the last axis.

    ``x``: (b, np, sq, sk) or any shape ending in the key axis.
    ``mask``: broadcastable boolean, True = MASKED OUT (the reference's
    convention: mask==1 positions are filled with -10000 before softmax,
    ``scaled_masked_softmax.h``). Returns x.dtype.
    """
    return _sms_fwd(x, mask, scale)[0]


def _sms_fwd(x, mask, scale):
    x32 = x.astype(jnp.float32) * scale
    if mask is not None:
        x32 = jnp.where(mask, MASK_FILL, x32)
    y = _softmax_last(x32).astype(x.dtype)
    return y, y


def _sms_fwd_vjp(x, mask, scale):
    y, _ = _sms_fwd(x, mask, scale)
    return y, y


def _sms_bwd_vjp(scale, y, dy):
    dx = _softmax_bwd_from_output(y, dy) * scale
    return dx.astype(y.dtype), None


scaled_masked_softmax.defvjp(_sms_fwd_vjp, _sms_bwd_vjp)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def scaled_upper_triang_masked_softmax(x, scale: float = 1.0):
    """Causal softmax(scale * x) over the last axis (ref
    ``scaled_upper_triang_masked_softmax_cuda``): position (q, k) with k > q
    is masked. ``x``: (..., sq, sk) with sq == sk."""
    return _suts_fwd(x, scale)[0]


def _causal_mask(sq, sk):
    q = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
    k = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
    return k > q


def _suts_fwd(x, scale):
    sq, sk = x.shape[-2], x.shape[-1]
    x32 = x.astype(jnp.float32) * scale
    x32 = jnp.where(_causal_mask(sq, sk), MASK_FILL, x32)
    y = _softmax_last(x32).astype(x.dtype)
    return y, y


def _suts_fwd_vjp(x, scale):
    y, _ = _suts_fwd(x, scale)
    return y, y


def _suts_bwd_vjp(scale, y, dy):
    dx = _softmax_bwd_from_output(y, dy) * scale
    # zero the masked triangle in the grad as the reference kernel does
    sq, sk = y.shape[-2], y.shape[-1]
    dx = jnp.where(_causal_mask(sq, sk), 0.0, dx)
    return (dx.astype(y.dtype),)


scaled_upper_triang_masked_softmax.defvjp(_suts_fwd_vjp, _suts_bwd_vjp)


def scaled_softmax(x, scale: float = 1.0):
    """No-mask variant (ref ``scaled_softmax_cuda`` entry in fused_softmax.py)."""
    return scaled_masked_softmax(x, None, scale)

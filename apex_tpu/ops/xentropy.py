"""Fused softmax cross-entropy with label smoothing.

Reference: ``apex/contrib/csrc/xentropy/`` (``xentropy_cuda``) driven by
``apex/contrib/xentropy/softmax_xentropy.py:4-37``. The CUDA kernel's trick is
to save only (max, logsumexp) per row for the backward instead of the full
softmax — halving activation memory vs the naive composition.

TPU re-design: same memory trade via ``custom_vjp``: forward saves the scalar
``logsumexp`` per row; backward recomputes ``softmax = exp(logits - lse)``
in-register (one fused XLA loop) rather than storing it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def softmax_cross_entropy_loss(
    logits, labels, smoothing: float = 0.0, half_to_float: bool = False
):
    """Per-example loss (ref ``SoftmaxCrossEntropyLoss.forward``).

    ``logits``: (N, V); ``labels``: (N,) int. With label smoothing s, the
    target distribution is (1-s) on the label + s/V uniform; loss =
    lse - (1-s)*logit[label] - (s/V)*sum(logits).
    """
    loss, _ = _xent_fwd(logits, labels, smoothing, half_to_float)
    return loss


def _xent_fwd(logits, labels, smoothing, half_to_float):
    x = logits.astype(jnp.float32)
    m = jnp.max(x, axis=-1, keepdims=True)
    lse = (jnp.log(jnp.sum(jnp.exp(x - m), axis=-1, keepdims=True)) + m)[..., 0]
    n = x.shape[-1]
    picked = jnp.take_along_axis(x, labels[..., None], axis=-1)[..., 0]
    if smoothing > 0.0:
        mean_all = jnp.mean(x, axis=-1)
        nll = lse - (1.0 - smoothing) * picked - smoothing * mean_all
    else:
        nll = lse - picked
    out_dtype = jnp.float32 if half_to_float else logits.dtype
    return nll.astype(out_dtype), (logits, labels, lse)


def _xent_fwd_vjp(logits, labels, smoothing, half_to_float):
    loss, res = _xent_fwd(logits, labels, smoothing, half_to_float)
    return loss, res


def _xent_bwd_vjp(smoothing, half_to_float, res, dloss):
    logits, labels, lse = res
    x = logits.astype(jnp.float32)
    n = x.shape[-1]
    # softmax recomputed from saved lse (the xentropy_cuda backward)
    p = jnp.exp(x - lse[..., None])
    onehot = jax.nn.one_hot(labels, n, dtype=jnp.float32)
    if smoothing > 0.0:
        target = (1.0 - smoothing) * onehot + smoothing / n
    else:
        target = onehot
    dx = (p - target) * dloss.astype(jnp.float32)[..., None]
    return dx.astype(logits.dtype), None


softmax_cross_entropy_loss.defvjp(_xent_fwd_vjp, _xent_bwd_vjp)

"""Flash attention — Pallas TPU kernels with online softmax, plus reference.

Reference: ``apex/contrib/csrc/fmha/`` (``fmhalib`` — fused MHA for packed
varlen sequences ≤512, driver ``apex/contrib/fmha/fmha.py:33-76``) and
``apex/contrib/csrc/multihead_attn/`` (``fast_multihead_attn`` — fused
QKV+softmax+dropout+out-proj, drivers ``apex/contrib/multihead_attn/``).
Those CUDA kernels exist because eager attention materializes the (sq, sk)
score matrix in HBM; they are hard-limited to seqlen ≤ 512.

TPU re-design: the flash-attention scheme — tile Q into VMEM blocks, stream
K/V blocks through the MXU, keep a running row-max and denominator (online
softmax), never materialize the score matrix. This removes the reference's
sequence-length limit entirely and is the building block for ring attention
(``apex_tpu/transformer/sequence_parallel.py``). Backward recomputes scores
blockwise from the saved output and row log-sum-exp (the standard flash
backward), as two accumulation kernels (dQ, and dK/dV).

Layout: (batch, heads, seq, head_dim) — matches the Megatron attention core
the transformer layer uses.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

try:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False

# Finite stand-in for -inf: keeps exp() exact zero without nan from (-inf) - (-inf).
NEG_INF = -1e30


from apex_tpu.ops._pallas_util import sds as _sds  # noqa: E402
from apex_tpu.ops._pallas_util import compiled_backend as _compiled_backend


# ---------------------------------------------------------------------------
# Pure-JAX reference (ground truth for kernel tests; also the fallback path
# for arbitrary masks / unaligned shapes — XLA fuses it into a few loops).

def attention_reference(q, k, v, mask=None, scale: Optional[float] = None,
                        causal: bool = False, dropout_rate: float = 0.0,
                        dropout_key=None, bias=None, dropout_keep=None):
    """Plain softmax(QKᵀ·scale + bias)V in fp32 accumulation.

    ``mask``: broadcastable boolean over (..., sq, sk), True = masked OUT
    (the reference convention, ``apex/contrib/fmha/fmha.py`` cu_seqlens
    padding → masked). ``bias``: additive logit bias broadcastable over
    (..., sq, sk) — e.g. T5 relative position bias (heads, sq, sk).
    Optional probability dropout on the softmax (the reference kernels'
    fused dropout, here materialized); ``dropout_keep`` supplies an
    explicit keep mask instead of the ``dropout_key`` draw (how
    ``flash_attention``'s fallback stays on the kernels' counter-hash
    stream). Returns q.dtype.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    q32 = q.astype(jnp.float32)
    k32 = k.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    s = jnp.einsum("...qd,...kd->...qk", q32, k32) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        qpos = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        kpos = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(kpos > qpos + (sk - sq), NEG_INF, s)
    if mask is not None:
        s = jnp.where(mask, NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0:
        if dropout_keep is not None and dropout_key is not None:
            raise ValueError(
                "pass either dropout_key (draw a mask) or dropout_keep "
                "(explicit mask), not both — the key would be silently "
                "ignored")
        if dropout_keep is None:
            if dropout_key is None:
                raise ValueError("dropout_rate > 0 needs dropout_key")
            dropout_keep = jax.random.bernoulli(dropout_key,
                                                1.0 - dropout_rate, p.shape)
        p = jnp.where(dropout_keep, p / (1.0 - dropout_rate), 0.0)
    o = jnp.einsum("...qk,...kd->...qd", p, v32)
    return o.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas forward

def _dropout_keep(seed_ref, rate, block_q, block_k, q_i, kv_i, bh_i):
    """Deterministic keep mask from a counter-based hash of (seed, batch*head,
    GLOBAL q position, GLOBAL k position) — the philox-counter scheme of
    the reference's fmhalib dropout. Position-keyed (not block-keyed), so the
    identical mask regenerates in forward and both backward kernels even at
    different block sizes, and plain integer ops keep it portable to pallas
    interpret mode (pltpu's hardware PRNG is TPU-only). ``bh_i`` must be read
    at kernel top level (program_id inside a pl.when body does not lower in
    interpret mode).

    ``seed_ref`` is the SMEM operand ``[seed, q_off, k_off]``: the offsets
    translate kernel-local positions to global sequence positions, so a
    seq-sharded call (ring attention's per-chunk kernels) regenerates
    EXACTLY the corresponding slice of the dense global mask — sharding is
    invisible to the dropout stream."""
    # all-uint32 arithmetic: mixing a signed scalar into the uint32 iota
    # would promote/wrap and skew the keep probability
    qpos = (seed_ref[1].astype(jnp.uint32)
            + (q_i * block_q).astype(jnp.uint32)
            + jax.lax.broadcasted_iota(jnp.uint32, (block_q, block_k), 0))
    kpos = (seed_ref[2].astype(jnp.uint32)
            + (kv_i * block_k).astype(jnp.uint32)
            + jax.lax.broadcasted_iota(jnp.uint32, (block_q, block_k), 1))
    return _hash_keep(qpos, kpos, seed_ref[0].astype(jnp.uint32),
                      bh_i.astype(jnp.uint32), rate)


def _hash_keep(qpos, kpos, seed_u32, bh_u32, rate: float):
    """The ONE mask derivation both the Pallas kernels and the dense/ring
    einsum paths share — any drift between copies would silently break the
    ring-equals-dense dropout invariant. All operands uint32."""
    x = (qpos * jnp.uint32(0x9E3779B1)
         + kpos * jnp.uint32(0x85EBCA77)
         + seed_u32 * jnp.uint32(0xC2B2AE3D)
         + bh_u32 * jnp.uint32(0x27D4EB2F))
    # murmur3 fmix32 finalizer: full-avalanche 32-bit mixing
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> jnp.uint32(16))
    thresh = jnp.uint32(min(int(rate * 4294967296.0), 4294967295))
    return x >= thresh


def _seed3(seed):
    """Normalize the dropout SMEM operand to ``[seed, q_off, k_off]``;
    scalar/(1,) legacy callers get zero offsets."""
    if seed is None:
        return jnp.zeros((3,), jnp.int32)
    seed = jnp.asarray(seed, jnp.int32).reshape(-1)
    if seed.shape[0] == 1:
        return jnp.concatenate([seed, jnp.zeros((2,), jnp.int32)])
    if seed.shape[0] != 3:
        raise ValueError(f"dropout seed operand must be scalar, (1,) or "
                         f"(3,) [seed, q_off, k_off]; got {seed.shape}")
    return seed


def attention_dropout_mask(seed, rate: float, bh: int, sq: int, sk: int,
                           q_off=0, k_off=0):
    """(bh, sq, sk) keep mask — bit-identical to what the Pallas kernels
    regenerate from ``(seed, batch*head, global positions)``. Used by the
    ring-SP einsum chunk path and parity tests: with the right offsets a
    seq shard's mask IS the corresponding slice of the dense mask."""
    qpos = (jnp.asarray(q_off).astype(jnp.uint32)
            + jnp.arange(sq, dtype=jnp.uint32))[None, :, None]
    kpos = (jnp.asarray(k_off).astype(jnp.uint32)
            + jnp.arange(sk, dtype=jnp.uint32))[None, None, :]
    bh_i = jnp.arange(bh, dtype=jnp.uint32)[:, None, None]
    return _hash_keep(qpos, kpos, jnp.asarray(seed).astype(jnp.uint32),
                      bh_i, rate)


def _fa_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, *refs,
                   scale, causal, block_q, block_k, nk, dropout_rate,
                   has_bias=False):
    if has_bias:
        bias_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    else:
        bias_ref = None
        o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    bh_i = pl.program_id(0)
    q_i = pl.program_id(1)
    kv_i = pl.program_id(2)

    @pl.when(kv_i == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: skip K/V blocks entirely above the diagonal.
    run = (kv_i * block_k <= q_i * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        # inputs stay in model dtype: MXU runs bf16 x bf16 -> fp32 natively;
        # upcasting first would push the matmul onto the (8x slower) fp32 path
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if has_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            qpos = q_i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kv_i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos > qpos, NEG_INF, s)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        # l accumulates the UNdropped p: normalization precedes dropout,
        # so the final divide yields dropout(softmax(s)) @ v exactly
        l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref, dropout_rate, block_q, block_k,
                                 q_i, kv_i, bh_i)
            p = jnp.where(keep, p * (1.0 / (1.0 - dropout_rate)), 0.0)
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kv_i == nk - 1)
    def _finish():
        l = l_scr[:, :1]
        # Fully-masked rows (possible under ring-attention partial blocks)
        # produce l == 0; emit 0 output and lse = NEG_INF for the merge.
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # lse is laid out (bh, sq, 1): a (block_q, 1) block writes/reads with
        # no lane↔sublane transpose (TPU block rules need the last dim to be
        # 128-divisible or equal to the full array dim — here it's 1 == 1).
        lse_ref[0] = jnp.where(l == 0.0, NEG_INF, m_scr[:, :1] + jnp.log(safe_l))


def _kv_lim(i, block_q, block_k):
    """Last K/V block index the causal mask leaves live for q block ``i``."""
    return (i * block_q + block_q - 1) // block_k


def _bias_spec(num_heads, block_q, block_k, causal=False):
    """BlockSpec for a batch-shared (heads, sq, sk) bias: grid dim 0 is the
    flattened b*h (b-major), so the head index is bh mod heads. Under
    ``causal`` the kv coordinate is clamped at the diagonal (see
    ``_fa_fwd``)."""

    def index(b, i, j):
        if causal:
            j = jnp.minimum(j, _kv_lim(i, block_q, block_k))
        return (jax.lax.rem(b, num_heads), i, j)

    return pl.BlockSpec((1, block_q, block_k), index)


def _fa_fwd(q3, k3, v3, scale, causal, block_q, block_k, interpret,
            dropout_rate=0.0, seed=None, bias=None):
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    nq = sq // block_q
    nk = sk // block_k
    seed = _seed3(seed)
    has_bias = bias is not None
    kernel = functools.partial(
        _fa_fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk, dropout_rate=dropout_rate,
        has_bias=has_bias)

    # Causal: clamp the K/V fetch at the diagonal. The ``run`` predicate
    # already skips the compute for blocks above it; clamping the index map
    # makes those iterations re-request the diagonal block, and Mosaic
    # elides a copy whose block index matches the previous iteration —
    # halving K/V HBM traffic instead of fetching masked-out blocks.
    def kv_index(b, i, j):
        if causal:
            j = jnp.minimum(j, _kv_lim(i, block_q, block_k))
        return (b, j, 0)

    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), kv_index),
        pl.BlockSpec((1, block_k, d), kv_index),
    ]
    inputs = [seed, q3, k3, v3]
    if has_bias:
        in_specs.append(_bias_spec(bias.shape[0], block_q, block_k, causal))
        inputs.append(bias)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _sds((bh, sq, d), q3.dtype, q3, k3, v3),
            _sds((bh, sq, 1), jnp.float32, q3, k3, v3),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*inputs)
    return o, lse


# ---------------------------------------------------------------------------
# Pallas backward: dQ kernel (grid over K/V blocks innermost) and dK/dV kernel
# (grid over Q blocks innermost). Scores are recomputed from q, k and the
# saved lse — p = exp(s - lse) is already normalized, so no second pass over
# the row is needed (the flash-attention backward identity).

def _fa_bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, *refs,
                      scale, causal, block_q, block_k, nk, dropout_rate,
                      has_bias=False):
    if has_bias:
        bias_ref, dq_ref, dq_scr = refs
    else:
        bias_ref = None
        dq_ref, dq_scr = refs
    bh_i = pl.program_id(0)
    q_i = pl.program_id(1)
    kv_i = pl.program_id(2)

    @pl.when(kv_i == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = (kv_i * block_k <= q_i * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if has_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            qpos = q_i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kv_i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos > qpos, NEG_INF, s)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref, dropout_rate, block_q, block_k,
                                 q_i, kv_i, bh_i)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot(ds.astype(k.dtype), k,
                                 preferred_element_type=jnp.float32)

    @pl.when(kv_i == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                       delta_ref, *refs,
                       scale, causal, block_q, block_k, nq, dropout_rate,
                       has_bias=False):
    if has_bias:
        bias_ref, dk_ref, dv_ref, dk_scr, dv_scr = refs
    else:
        bias_ref = None
        dk_ref, dv_ref, dk_scr, dv_scr = refs
    bh_i = pl.program_id(0)
    kv_i = pl.program_id(1)
    q_i = pl.program_id(2)

    @pl.when(q_i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = (q_i * block_q + block_q - 1 >= kv_i * block_k) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if has_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            qpos = q_i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kv_i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos > qpos, NEG_INF, s)
        p = jnp.exp(s - lse)
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref, dropout_rate, block_q, block_k,
                                 q_i, kv_i, bh_i)
            inv = 1.0 / (1.0 - dropout_rate)
            p_v = jnp.where(keep, p * inv, 0.0)
        else:
            p_v = p
        dv_scr[:] += jax.lax.dot_general(
            p_v.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            dp = jnp.where(keep, dp * inv, 0.0)
        ds = p * (dp - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(q_i == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _fa_bwd_dbias_kernel(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                         delta_ref, bias_ref, db_ref, db_scr,
                         *, scale, causal, block_q, block_k, nb, num_heads,
                         dropout_rate):
    """dL/dbias for a batch-shared (heads, sq, sk) bias: recompute ds
    blockwise (the flash backward identity) and accumulate over the batch
    (innermost grid dim). dL/ds excludes the q·kᵀ ``scale`` — bias enters
    the logits after scaling."""
    h_i = pl.program_id(0)
    q_i = pl.program_id(1)
    kv_i = pl.program_id(2)
    b_i = pl.program_id(3)
    bh_i = b_i * num_heads + h_i

    @pl.when(b_i == 0)
    def _init():
        db_scr[:] = jnp.zeros_like(db_scr)

    run = (kv_i * block_k <= q_i * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            qpos = q_i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = kv_i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos > qpos, NEG_INF, s)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref, dropout_rate, block_q, block_k,
                                 q_i, kv_i, bh_i)
            dp = jnp.where(keep, dp * (1.0 / (1.0 - dropout_rate)), 0.0)
        db_scr[:] += p * (dp - delta)

    @pl.when(b_i == nb - 1)
    def _finish():
        db_ref[0] = db_scr[:].astype(db_ref.dtype)


def _fa_bwd(q3, k3, v3, o3, lse, do3, scale, causal, block_q, block_k,
            interpret, dropout_rate=0.0, seed=None, bias=None):
    bh, sq, d = q3.shape
    sk = k3.shape[1]
    nq = sq // block_q
    nk = sk // block_k
    seed = _seed3(seed)
    has_bias = bias is not None
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1, keepdims=True)

    dq_kernel = functools.partial(
        _fa_bwd_dq_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk, dropout_rate=dropout_rate,
        has_bias=has_bias)
    # same causal diagonal clamp as the forward (elide masked-block DMA)
    def kv_index(b, i, j):
        if causal:
            j = jnp.minimum(j, _kv_lim(i, block_q, block_k))
        return (b, j, 0)

    dq_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), kv_index),
        pl.BlockSpec((1, block_k, d), kv_index),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
    ]
    dq_inputs = [seed, q3, k3, v3, do3, lse, delta]
    if has_bias:
        dq_specs.append(_bias_spec(bias.shape[0], block_q, block_k, causal))
        dq_inputs.append(bias)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=_sds((bh, sq, d), q3.dtype, q3, k3, v3, do3),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*dq_inputs)

    dkv_kernel = functools.partial(
        _fa_bwd_dkv_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nq=nq, dropout_rate=dropout_rate,
        has_bias=has_bias)
    # dK/dV mirror clamp: for kv block j the first live q block is
    # (j*block_k)//block_q; earlier (masked-out) iterations re-request it,
    # eliding their q/do/lse/delta DMA
    def q_clamp(i, j):
        return jnp.maximum(i, (j * block_k) // block_q) if causal else i

    dkv_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, q_clamp(i, j), 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, q_clamp(i, j), 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, q_clamp(i, j), 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, q_clamp(i, j), 0)),
    ]
    dkv_inputs = [seed, q3, k3, v3, do3, lse, delta]
    if has_bias:
        num_heads = bias.shape[0]
        dkv_specs.append(pl.BlockSpec(
            (1, block_q, block_k),
            lambda b, j, i: (jax.lax.rem(b, num_heads), q_clamp(i, j), j)))
        dkv_inputs.append(bias)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, nk, nq),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _sds((bh, sk, d), k3.dtype, q3, k3, v3, do3),
            _sds((bh, sk, d), v3.dtype, q3, k3, v3, do3),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*dkv_inputs)

    if not has_bias:
        return dq, dk, dv, None

    num_heads = bias.shape[0]
    nb = bh // num_heads
    dbias_kernel = functools.partial(
        _fa_bwd_dbias_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nb=nb, num_heads=num_heads,
        dropout_rate=dropout_rate)
    def b_live(i, j, b):
        # tiles above the causal diagonal never compute: pin their batch
        # fetch to item 0 so the repeated index elides the per-b DMA
        if not causal:
            return b
        return jnp.where(j * block_k <= i * block_q + block_q - 1, b, 0)

    db = pl.pallas_call(
        dbias_kernel,
        # batch innermost ("arbitrary"): the (h, q, k) tile accumulates
        # its batch sum in scratch and writes once at the last batch item
        grid=(num_heads, nq, nk, nb),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d),
                         lambda h, i, j, b: (b_live(i, j, b) * num_heads + h,
                                             i, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda h, i, j, b: (b_live(i, j, b) * num_heads + h,
                                             j, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda h, i, j, b: (b_live(i, j, b) * num_heads + h,
                                             j, 0)),
            pl.BlockSpec((1, block_q, d),
                         lambda h, i, j, b: (b_live(i, j, b) * num_heads + h,
                                             i, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda h, i, j, b: (b_live(i, j, b) * num_heads + h,
                                             i, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda h, i, j, b: (b_live(i, j, b) * num_heads + h,
                                             i, 0)),
            pl.BlockSpec((1, block_q, block_k), lambda h, i, j, b: (h, i, j)),
        ],
        out_specs=pl.BlockSpec((1, block_q, block_k),
                               lambda h, i, j, b: (h, i, j)),
        out_shape=_sds((num_heads, sq, sk), jnp.float32, q3, k3, v3, do3),
        scratch_shapes=[pltpu.VMEM((block_q, block_k), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(seed, q3, k3, v3, do3, lse, delta, bias)
    return dq, dk, dv, db


# ---------------------------------------------------------------------------
# custom_vjp plumbing over (bh, seq, d) arrays

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash3(q3, k3, v3, seed, scale, causal, block_q, block_k, interpret,
            dropout_rate):
    o, _ = _fa_fwd(q3, k3, v3, scale, causal, block_q, block_k, interpret,
                   dropout_rate, seed)
    return o


def _flash3_fwd(q3, k3, v3, seed, scale, causal, block_q, block_k, interpret,
                dropout_rate):
    o, lse = _fa_fwd(q3, k3, v3, scale, causal, block_q, block_k, interpret,
                     dropout_rate, seed)
    # named so a remat policy can save EXACTLY the backward's residuals
    # (q/k/v/seed are region inputs; o + lse are the only computed ones) —
    # naming just the public output would still replay the forward kernel
    # to rebuild lse (reviewer-verified). See GPTConfig.remat_policy
    # 'dots_attn'.
    o = checkpoint_name(o, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return o, (q3, k3, v3, seed, o, lse)


def _flash3_bwd(scale, causal, block_q, block_k, interpret, dropout_rate,
                res, do3):
    q3, k3, v3, seed, o3, lse = res
    dq, dk, dv, _ = _fa_bwd(q3, k3, v3, o3, lse, do3, scale, causal, block_q,
                            block_k, interpret, dropout_rate, seed)
    return dq, dk, dv, None


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


# Bias-carrying variant: same kernels with the additive (heads, sq, sk)
# logit bias (T5 relative position bias) threaded through forward and all
# three backward kernels; the extra dbias kernel batch-reduces dL/ds.

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash3_bias(q3, k3, v3, bias, seed, scale, causal, block_q, block_k,
                 interpret, dropout_rate):
    o, _ = _fa_fwd(q3, k3, v3, scale, causal, block_q, block_k, interpret,
                   dropout_rate, seed, bias=bias)
    return o


def _flash3_bias_fwd(q3, k3, v3, bias, seed, scale, causal, block_q, block_k,
                     interpret, dropout_rate):
    o, lse = _fa_fwd(q3, k3, v3, scale, causal, block_q, block_k, interpret,
                     dropout_rate, seed, bias=bias)
    o = checkpoint_name(o, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return o, (q3, k3, v3, bias, seed, o, lse)


def _flash3_bias_bwd(scale, causal, block_q, block_k, interpret, dropout_rate,
                     res, do3):
    q3, k3, v3, bias, seed, o3, lse = res
    dq, dk, dv, db = _fa_bwd(q3, k3, v3, o3, lse, do3, scale, causal,
                             block_q, block_k, interpret, dropout_rate, seed,
                             bias=bias)
    return dq, dk, dv, db.astype(bias.dtype), None


_flash3_bias.defvjp(_flash3_bias_fwd, _flash3_bias_bwd)


def flash_attention_with_lse(q3, k3, v3, scale, causal, block_q, block_k,
                             interpret):
    """Forward-only variant returning (o, lse) with lse (bh, sq) — the
    ring-attention building block (merging partial results needs the
    log-sum-exp). Not differentiable; ring attention differentiates through
    its own recompute."""
    o, lse = _fa_fwd(q3, k3, v3, scale, causal, block_q, block_k, interpret)
    return o, lse[..., 0]


# ---------------------------------------------------------------------------
# Public API

def _pick_block(seq: int, want: int) -> Optional[int]:
    for cand in (want, 512, 256, 128, 64, 32, 16, 8):
        if cand <= want and seq % cand == 0:
            return cand
    return None


def _pallas_ok(sq, sk, d, causal, allow_interpret):
    if not _HAS_PALLAS:
        return False
    if _pick_block(sq, 128) is None or _pick_block(sk, 128) is None:
        return False
    if d % 8 != 0:
        return False
    if causal and sq != sk:
        return False
    return allow_interpret or _compiled_backend()


def flash_attention(
    q, k, v,
    mask=None,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
    use_pallas: Optional[bool] = None,
    dropout_rate: float = 0.0,
    dropout_seed=None,
    bias=None,
    interpret: Optional[bool] = None,
):
    """Memory-efficient attention over (batch, heads, seq, head_dim).

    Pallas flash kernel for the causal / no-mask cases on aligned shapes
    (ref capability: ``fmhalib`` + ``fast_multihead_attn``, without their
    seqlen ≤ 512 limit); XLA reference path for arbitrary ``mask`` or odd
    shapes. ``mask`` True = masked out.

    ``bias``: optional batch-shared additive logit bias of shape
    (heads, sq, sk) — the T5 relative-position-bias contract. It rides the
    Pallas path (added to the score tile inside all kernels; its gradient
    comes from a dedicated batch-reducing kernel) and is differentiable.
    Note the compiled TPU path tiles the bias (block_q, block_k), so sk
    must be a multiple of 128 or fit one block; the reference fallback has
    no such limit.

    ``dropout_rate`` > 0 applies probability dropout to the (normalized)
    attention weights *inside* the kernel — the counter-based keep mask is
    regenerated identically in forward and backward from ``dropout_seed``
    (an int32 scalar/array; required when the rate is nonzero), so training
    configs with attention dropout stay on the Pallas path. The non-pallas
    fallback materializes the SAME counter-hash mask, so the result does
    not depend on which dispatch path ran.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("dropout_rate > 0 needs dropout_seed")
    if bias is not None and bias.shape != (h, sq, sk):
        raise ValueError(
            f"bias must be batch-shared (heads, sq, sk) = {(h, sq, sk)}, "
            f"got {bias.shape}")
    pallas_possible = mask is None and _pallas_ok(
        sq, sk, d, causal, allow_interpret=True)
    if use_pallas is None:
        use_pallas = mask is None and _pallas_ok(
            sq, sk, d, causal, allow_interpret=False)
    elif use_pallas and not pallas_possible:
        raise ValueError(
            f"pallas flash_attention needs mask=None, seq divisible by a "
            f"block size, head_dim % 8 == 0, and sq == sk when causal "
            f"(got q {q.shape}, k {k.shape}, causal={causal}, "
            f"mask={'set' if mask is not None else None})")
    if not use_pallas:
        if interpret is not None:
            raise ValueError(
                "interpret= only applies to the Pallas path; this call "
                "resolved to the reference (pass use_pallas=True to force "
                "the kernel, or drop interpret=)")
        keep = None
        if dropout_rate > 0.0:
            # the kernels' counter-hash stream, NOT a jax.random draw: the
            # fallback must drop the same entries as the compiled kernel
            # (and the ring's chunks) for the same seed, or results change
            # with the dispatch path
            keep = attention_dropout_mask(
                jnp.asarray(dropout_seed).reshape(()), float(dropout_rate),
                b * h, sq, sk).reshape(b, h, sq, sk)
        return attention_reference(q, k, v, mask=mask, scale=scale,
                                   causal=causal, dropout_rate=dropout_rate,
                                   dropout_keep=keep, bias=bias)
    bq = _pick_block(sq, block_q)
    bk = _pick_block(sk, block_k)
    if interpret is None:
        interpret = not _compiled_backend()
    seed = (jnp.zeros((1,), jnp.int32) if dropout_seed is None
            else jnp.asarray(dropout_seed, jnp.int32).reshape((1,)))
    if bias is not None:
        o3 = _flash3_bias(
            q.reshape(b * h, sq, d), k.reshape(b * h, sk, d),
            v.reshape(b * h, sk, d), bias, seed, scale, causal, bq, bk,
            interpret, float(dropout_rate))
        return o3.reshape(b, h, sq, d)
    o3 = _flash3(
        q.reshape(b * h, sq, d), k.reshape(b * h, sk, d),
        v.reshape(b * h, sk, d), seed, scale, causal, bq, bk, interpret,
        float(dropout_rate))
    return o3.reshape(b, h, sq, d)

"""Fused LayerNorm / RMSNorm — Pallas TPU kernels with an XLA fallback.

Reference: ``csrc/layer_norm_cuda_kernel.cu`` — Welford forward
(``cuApplyLayerNorm:411``), two-stage γ/β gradient (``cuComputePartGradGammaBeta:541``)
and dgrad (``:678``); plus the ``fast_layer_norm`` contrib ext
(``apex/contrib/csrc/layer_norm/``) for large hidden sizes. The Python driver
is ``apex/normalization/fused_layer_norm.py``.

TPU re-design: one Pallas kernel per direction. Rows are blocked over the
grid; each block computes row statistics in fp32 on the VPU, normalizes, and
applies the affine. The backward accumulates the γ/β partials across
sequential grid steps into a single output block — the Pallas equivalent of
the reference's two-stage part-grad reduction (TPU grids iterate sequentially,
so accumulation into a shared output block replaces the CUDA inter-block
reduction). Variance uses the E[x²]−E[x]² form so zero-padded lanes (hidden
not a multiple of the 128-lane tile) cannot corrupt the sums; the Pallas path
is gated to tile-aligned shapes anyway, with the XLA path (same math, fused
well by XLA) covering the rest.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.ops._pallas_util import sds as _sds
from apex_tpu.ops._pallas_util import compiled_backend as _compiled_backend

try:  # Pallas is part of jax, but keep import-failure graceful (CPU-only envs)
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PALLAS = True
except Exception:  # pragma: no cover
    _HAS_PALLAS = False


# ---------------------------------------------------------------------------
# Pure-JAX reference implementations (the math XLA fuses on its own; also the
# ground truth the kernels are tested against).

def layer_norm_reference(x, weight=None, bias=None, eps: float = 1e-5):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    # clamp: E[x²]−E[x]² cancellation can dip negative → nan through rsqrt
    var = jnp.maximum(
        jnp.mean(jnp.square(x32), axis=-1, keepdims=True) - jnp.square(mean),
        0.0)
    y = (x32 - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def rms_norm_reference(x, weight=None, eps: float = 1e-5):
    """Ref ``apex/normalization/fused_layer_norm.py:16-31`` (manual_rms_norm)."""
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(ms + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# Pallas kernels

def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps, hidden):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.sum(x, axis=1, keepdims=True) / hidden
    msq = jnp.sum(x * x, axis=1, keepdims=True) / hidden
    var = jnp.maximum(msq - mean * mean, 0.0)  # cancellation guard
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (x - mean) * rstd
    y = xhat * w_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _ln_bwd_kernel(
    dy_ref, x_ref, mean_ref, rstd_ref, w_ref, dx_ref, dw_ref, db_ref, *, hidden
):
    dy = dy_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)
    mean = mean_ref[:]
    rstd = rstd_ref[:]
    w = w_ref[:].astype(jnp.float32)
    xhat = (x - mean) * rstd

    # dgrad (ref cuComputeGradInput:678): dx = rstd*(g - mean(g) - xhat*mean(g*xhat))
    g = dy * w
    c1 = jnp.sum(g, axis=1, keepdims=True) / hidden
    c2 = jnp.sum(g * xhat, axis=1, keepdims=True) / hidden
    dx = (g - c1 - xhat * c2) * rstd
    dx_ref[:] = dx.astype(dx_ref.dtype)

    # two-stage γ/β grads: partial sums per row-block accumulated across the
    # sequential grid into one (1, hidden) block (ref cuComputePartGradGammaBeta).
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[:] = jnp.zeros_like(dw_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    dw_ref[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[:] += jnp.sum(dy, axis=0, keepdims=True)


def _rms_fwd_kernel(x_ref, w_ref, y_ref, rstd_ref, *, eps, hidden):
    x = x_ref[:].astype(jnp.float32)
    msq = jnp.sum(x * x, axis=1, keepdims=True) / hidden
    rstd = jax.lax.rsqrt(msq + eps)
    y = x * rstd * w_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    rstd_ref[:] = rstd


def _rms_bwd_kernel(dy_ref, x_ref, rstd_ref, w_ref, dx_ref, dw_ref, *, hidden):
    dy = dy_ref[:].astype(jnp.float32)
    x = x_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    w = w_ref[:].astype(jnp.float32)
    xhat = x * rstd
    g = dy * w
    c2 = jnp.sum(g * xhat, axis=1, keepdims=True) / hidden
    dx = (g - xhat * c2) * rstd
    dx_ref[:] = dx.astype(dx_ref.dtype)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dw_ref[:] = jnp.zeros_like(dw_ref)

    dw_ref[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)


# The backward kernel keeps ~7 block-sized fp32 buffers resident (dy, x,
# xhat, g, dx + weight row + partial-grad row); budget half of a core's
# ~16 MB VMEM. The reference needs a separate ``fast_layer_norm`` extension
# for large hidden (up to 65k); here large hidden shrinks the row block and
# past the budget falls back to the XLA path rather than faulting on VMEM.
_VMEM_BUDGET_BYTES = 8 * 1024 * 1024
_BWD_LIVE_BUFFERS = 7


def _pick_block_rows(rows: int, hidden: int) -> Optional[int]:
    for cand in (256, 128, 64, 32, 16, 8):
        if (rows % cand == 0
                and cand * hidden * 4 * _BWD_LIVE_BUFFERS
                <= _VMEM_BUDGET_BYTES):
            return cand
    return None


def _pallas_ok(rows: int, hidden: int, allow_interpret: bool) -> bool:
    """Shape/platform gate. By default the Pallas path is only *selected* on
    real TPU; off-TPU it runs through the (slow) Pallas interpreter and is
    therefore opt-in via use_pallas=True (tests do this)."""
    if not _HAS_PALLAS:
        return False
    if _pick_block_rows(rows, hidden) is None:
        return False
    if hidden % 128 != 0:
        return False
    return allow_interpret or _compiled_backend()


def _interpret_default() -> bool:
    return not _compiled_backend()


# ---------------------------------------------------------------------------
# custom_vjp entry points

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layer_norm_affine(x2d, w, b, eps):
    y, _, _ = _ln_fwd(x2d, w, b, eps)
    return y


def _ln_fwd(x2d, w, b, eps):
    rows, hidden = x2d.shape
    block = _pick_block_rows(rows, hidden)
    interpret = _interpret_default()
    kernel = functools.partial(_ln_fwd_kernel, eps=eps, hidden=hidden)
    y, mean, rstd = pl.pallas_call(
        kernel,
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec((block, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block, hidden), lambda i: (i, 0)),
            pl.BlockSpec((block, 1), lambda i: (i, 0)),
            pl.BlockSpec((block, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            _sds((rows, hidden), x2d.dtype, x2d, w, b),
            _sds((rows, 1), jnp.float32, x2d, w, b),
            _sds((rows, 1), jnp.float32, x2d, w, b),
        ],
        interpret=interpret,
    )(x2d, w.reshape(1, -1), b.reshape(1, -1))
    return y, mean, rstd


def _layer_norm_affine_fwd(x2d, w, b, eps):
    y, mean, rstd = _ln_fwd(x2d, w, b, eps)
    return y, (x2d, w, mean, rstd)


def _layer_norm_affine_bwd(eps, res, dy):
    x2d, w, mean, rstd = res
    rows, hidden = x2d.shape
    block = _pick_block_rows(rows, hidden)
    kernel = functools.partial(_ln_bwd_kernel, hidden=hidden)
    dx, dw, db = pl.pallas_call(
        kernel,
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec((block, hidden), lambda i: (i, 0)),
            pl.BlockSpec((block, hidden), lambda i: (i, 0)),
            pl.BlockSpec((block, 1), lambda i: (i, 0)),
            pl.BlockSpec((block, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        out_shape=[
            _sds((rows, hidden), x2d.dtype, x2d, w, dy),
            _sds((1, hidden), jnp.float32, x2d, w, dy),
            _sds((1, hidden), jnp.float32, x2d, w, dy),
        ],
        interpret=_interpret_default(),
    )(dy, x2d, mean, rstd, w.reshape(1, -1))
    return dx, dw.reshape(-1).astype(w.dtype), db.reshape(-1).astype(w.dtype)


_layer_norm_affine.defvjp(_layer_norm_affine_fwd, _layer_norm_affine_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rms_norm_affine(x2d, w, eps):
    y, _ = _rms_fwd(x2d, w, eps)
    return y


def _rms_fwd(x2d, w, eps):
    rows, hidden = x2d.shape
    block = _pick_block_rows(rows, hidden)
    kernel = functools.partial(_rms_fwd_kernel, eps=eps, hidden=hidden)
    y, rstd = pl.pallas_call(
        kernel,
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec((block, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block, hidden), lambda i: (i, 0)),
            pl.BlockSpec((block, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            _sds((rows, hidden), x2d.dtype, x2d, w),
            _sds((rows, 1), jnp.float32, x2d, w),
        ],
        interpret=_interpret_default(),
    )(x2d, w.reshape(1, -1))
    return y, rstd


def _rms_norm_affine_fwd(x2d, w, eps):
    y, rstd = _rms_fwd(x2d, w, eps)
    return y, (x2d, w, rstd)


def _rms_norm_affine_bwd(eps, res, dy):
    x2d, w, rstd = res
    rows, hidden = x2d.shape
    block = _pick_block_rows(rows, hidden)
    kernel = functools.partial(_rms_bwd_kernel, hidden=hidden)
    dx, dw = pl.pallas_call(
        kernel,
        grid=(rows // block,),
        in_specs=[
            pl.BlockSpec((block, hidden), lambda i: (i, 0)),
            pl.BlockSpec((block, hidden), lambda i: (i, 0)),
            pl.BlockSpec((block, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        out_shape=[
            _sds((rows, hidden), x2d.dtype, x2d, w, dy),
            _sds((1, hidden), jnp.float32, x2d, w, dy),
        ],
        interpret=_interpret_default(),
    )(dy, x2d, rstd, w.reshape(1, -1))
    return dx, dw.reshape(-1).astype(w.dtype)


_rms_norm_affine.defvjp(_rms_norm_affine_fwd, _rms_norm_affine_bwd)


# ---------------------------------------------------------------------------
# Public functional API

def layer_norm(
    x,
    weight=None,
    bias=None,
    eps: float = 1e-5,
    use_pallas: Optional[bool] = None,
):
    """Fused layer norm over the last axis (ref ``fused_layer_norm_cuda``
    forward/backward entry points, ``csrc/layer_norm_cuda.cpp:428-440``).

    Pallas kernel when shapes are tile-aligned on TPU (or interpret mode on
    CPU); identical-math XLA fallback otherwise. ``weight``/``bias`` may be
    None (non-affine variant, ref ``fused_layer_norm.py:32-58``).
    """
    hidden = x.shape[-1]
    rows = math.prod(x.shape[:-1])
    if use_pallas is None:
        use_pallas = _pallas_ok(rows, hidden, allow_interpret=False)
    elif use_pallas and not _pallas_ok(rows, hidden, allow_interpret=True):
        raise ValueError(
            f"pallas layer_norm requires row count divisible by 8, hidden "
            f"% 128 == 0, and a row block fitting VMEM at this hidden size; "
            f"got shape {x.shape}"
        )
    if not use_pallas or weight is None or bias is None:
        return layer_norm_reference(x, weight, bias, eps)
    x2d = x.reshape(rows, hidden)
    return _layer_norm_affine(x2d, weight, bias, eps).reshape(x.shape)


def rms_norm(
    x,
    weight=None,
    eps: float = 1e-5,
    use_pallas: Optional[bool] = None,
):
    """Fused RMS norm (ref RMSNorm variants in ``csrc/layer_norm_cuda.cpp``)."""
    hidden = x.shape[-1]
    rows = math.prod(x.shape[:-1])
    if use_pallas is None:
        use_pallas = _pallas_ok(rows, hidden, allow_interpret=False)
    elif use_pallas and not _pallas_ok(rows, hidden, allow_interpret=True):
        raise ValueError(
            f"pallas rms_norm requires row count divisible by 8, hidden "
            f"% 128 == 0, and a row block fitting VMEM at this hidden size; "
            f"got shape {x.shape}"
        )
    if not use_pallas or weight is None:
        return rms_norm_reference(x, weight, eps)
    x2d = x.reshape(rows, hidden)
    return _rms_norm_affine(x2d, weight, eps).reshape(x.shape)



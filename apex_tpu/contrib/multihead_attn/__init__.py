"""Fused multi-head attention modules (ref ``apex/contrib/multihead_attn``)."""

from apex_tpu.contrib.multihead_attn.modules import (  # noqa: F401
    EncdecMultiheadAttn,
    SelfMultiheadAttn,
)

__all__ = ["SelfMultiheadAttn", "EncdecMultiheadAttn"]

"""Self / encoder-decoder multi-head attention with optional norm-add fusion.

Reference: ``apex/contrib/multihead_attn/self_multihead_attn.py:27`` and
``encdec_multihead_attn.py:27`` + 8k LoC of CUDA (``fast_multihead_attn``):
fused QKV GEMM → softmax(+mask) → dropout → context GEMM → out-proj, with
``include_norm_add`` variants that fuse a pre-LayerNorm and residual add,
and ``mask_additive`` variants that add the mask instead of filling -inf.

TPU re-design: one flax module per reference class; the attention core is
the Pallas flash kernel (``apex_tpu.ops.flash_attention``) — no seqlen≤512
limit — with the QKV projection as a single fused GEMM (column concat), and
norm-add as ``ops.layer_norm`` + residual, all fused by XLA around the
kernel. Dropout on attention probabilities runs INSIDE the flash kernel
(counter-based keep mask regenerated in backward — the reference kernels'
philox dropout); only arbitrary boolean/additive masks route through the
XLA reference attention, which the kernel does not model.

Layout note: the reference uses (seq, batch, embed) like fairseq; TPU-native
is (batch, seq, embed), which is what these modules take.
"""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import attention_reference, flash_attention
from apex_tpu.ops.layer_norm import layer_norm


def _split_heads(x, num_heads):
    b, s, e = x.shape
    return x.reshape(b, s, num_heads, e // num_heads).transpose(0, 2, 1, 3)


def _merge_heads(x):
    b, h, s, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, s, h * d)


def _attend(q, k, v, *, key_padding_mask, attn_mask, mask_additive,
            dropout_rate, deterministic, dropout_rng, scale):
    """Shared core: pick flash vs reference path. Masks follow the reference
    conventions: ``key_padding_mask`` (b, sk) True = pad; ``attn_mask``
    (sq, sk) True = masked (or additive float when ``mask_additive``)."""
    if mask_additive and attn_mask is not None:
        # additive float mask (ref mask_additive=True): fold into scores via
        # the reference path
        b, h, sq, d = q.shape
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        s = s + attn_mask.astype(jnp.float32)
        if key_padding_mask is not None:
            s = jnp.where(key_padding_mask[:, None, None, :], -1e30, s)
        p = jax.nn.softmax(s, axis=-1)
        if dropout_rate > 0.0 and not deterministic:
            keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                        p.shape)
            p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)

    mask = None
    if key_padding_mask is not None:
        mask = key_padding_mask[:, None, None, :]
    if attn_mask is not None:
        am = attn_mask[None, None, :, :]
        mask = am if mask is None else (mask | am)
    if dropout_rate > 0.0 and not deterministic:
        if mask is None:
            # in-kernel counter-based dropout (ref fast_multihead_attn's
            # fused philox dropout); stays on the Pallas path
            seed = jax.random.bits(dropout_rng, dtype=jnp.uint32).astype(
                jnp.int32)
            return flash_attention(q, k, v, scale=scale,
                                   dropout_rate=dropout_rate,
                                   dropout_seed=seed)
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        s = jnp.where(mask, -1e30, s)
        p = jax.nn.softmax(s, axis=-1)
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", p,
                          v.astype(jnp.float32)).astype(q.dtype)
    return flash_attention(q, k, v, mask=mask, scale=scale)


class SelfMultiheadAttn(nn.Module):
    """Ref ``self_multihead_attn.py:27`` — fused QKV self-attention.

    ``include_norm_add``: pre-LayerNorm + residual add around the block
    (the reference's norm-add CUDA variant). ``mask_additive``: ``attn_mask``
    is an additive float mask instead of boolean fill.
    """

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    mask_additive: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, query, *, key_padding_mask=None, attn_mask=None,
                 is_training: bool = True, dropout_rng=None):
        if self.embed_dim % self.num_heads != 0:
            raise ValueError("embed_dim must be divisible by num_heads")
        e = self.embed_dim
        residual = query
        x = query
        if self.include_norm_add:
            ln_w = self.param("ln_weight", nn.initializers.ones, (e,),
                              self.param_dtype)
            ln_b = self.param("ln_bias", nn.initializers.zeros, (e,),
                              self.param_dtype)
            x = layer_norm(x, ln_w, ln_b)
        # single fused QKV GEMM (ref in_proj weight of shape (3e, e))
        qkv_w = self.param(
            "in_proj_weight",
            nn.initializers.variance_scaling(1.0, "fan_in", "normal"),
            (e, 3 * e), self.param_dtype)
        qkv = x @ qkv_w
        if self.bias:
            qkv = qkv + self.param("in_proj_bias", nn.initializers.zeros,
                                   (3 * e,), self.param_dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (_split_heads(t, self.num_heads) for t in (q, k, v))
        if dropout_rng is None and self.dropout > 0.0 and is_training:
            dropout_rng = self.make_rng("dropout")
        ctx = _attend(
            q, k, v, key_padding_mask=key_padding_mask, attn_mask=attn_mask,
            mask_additive=self.mask_additive, dropout_rate=self.dropout,
            deterministic=not is_training, dropout_rng=dropout_rng,
            scale=1.0 / math.sqrt(e // self.num_heads))
        out_w = self.param(
            "out_proj_weight",
            nn.initializers.variance_scaling(1.0, "fan_in", "normal"),
            (e, e), self.param_dtype)
        out = _merge_heads(ctx) @ out_w
        if self.bias:
            out = out + self.param("out_proj_bias", nn.initializers.zeros,
                                   (e,), self.param_dtype)
        if self.include_norm_add:
            out = out + residual
        return out


class EncdecMultiheadAttn(nn.Module):
    """Ref ``encdec_multihead_attn.py:27`` — Q from the decoder stream, K/V
    from the encoder stream (one fused KV GEMM)."""

    embed_dim: int
    num_heads: int
    dropout: float = 0.0
    bias: bool = False
    include_norm_add: bool = False
    mask_additive: bool = False
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, query, key, *, key_padding_mask=None, attn_mask=None,
                 is_training: bool = True, dropout_rng=None):
        if self.embed_dim % self.num_heads != 0:
            raise ValueError("embed_dim must be divisible by num_heads")
        e = self.embed_dim
        residual = query
        x = query
        if self.include_norm_add:
            ln_w = self.param("ln_weight", nn.initializers.ones, (e,),
                              self.param_dtype)
            ln_b = self.param("ln_bias", nn.initializers.zeros, (e,),
                              self.param_dtype)
            x = layer_norm(x, ln_w, ln_b)
        q_w = self.param(
            "q_weight", nn.initializers.variance_scaling(1.0, "fan_in",
                                                         "normal"),
            (e, e), self.param_dtype)
        kv_w = self.param(
            "kv_weight", nn.initializers.variance_scaling(1.0, "fan_in",
                                                          "normal"),
            (e, 2 * e), self.param_dtype)
        q = x @ q_w
        kv = key @ kv_w
        if self.bias:
            q = q + self.param("q_bias", nn.initializers.zeros, (e,),
                               self.param_dtype)
            kv = kv + self.param("kv_bias", nn.initializers.zeros, (2 * e,),
                                 self.param_dtype)
        k, v = jnp.split(kv, 2, axis=-1)
        q, k, v = (_split_heads(t, self.num_heads) for t in (q, k, v))
        if dropout_rng is None and self.dropout > 0.0 and is_training:
            dropout_rng = self.make_rng("dropout")
        ctx = _attend(
            q, k, v, key_padding_mask=key_padding_mask, attn_mask=attn_mask,
            mask_additive=self.mask_additive, dropout_rate=self.dropout,
            deterministic=not is_training, dropout_rng=dropout_rng,
            scale=1.0 / math.sqrt(e // self.num_heads))
        out_w = self.param(
            "out_proj_weight",
            nn.initializers.variance_scaling(1.0, "fan_in", "normal"),
            (e, e), self.param_dtype)
        out = _merge_heads(ctx) @ out_w
        if self.bias:
            out = out + self.param("out_proj_bias", nn.initializers.zeros,
                                   (e,), self.param_dtype)
        if self.include_norm_add:
            out = out + residual
        return out

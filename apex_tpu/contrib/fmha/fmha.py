"""Packed-varlen attention (see package doc).

Reference driver: ``apex/contrib/fmha/fmha.py:33-76`` — packed ``qkv``
(total, 3, heads, d) + ``cu_seqlens`` prefix sums, dispatched to the
``fmhalib`` CUDA kernels (seqlen <= 512 only). Here the packed batch maps
to the segment-id convention of ``ops/attention_varlen.py``: the Pallas
kernels mask cross-segment pairs in-tile and skip non-intersecting blocks
outright, with no sequence-length limit and no dense (total, total) mask.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from apex_tpu.ops.attention_varlen import flash_attention_varlen


def cu_seqlens_to_segment_ids(cu_seqlens, total: int):
    """[0, l1, l1+l2, ...] -> per-token sequence index (ref fmha.py cu_seqlens
    convention). Tokens at/after the last boundary get segment -1 (padding):
    they attend to nothing — including other padding — and output zero."""
    positions = jnp.arange(total)
    # segment of token t = number of boundaries <= t, minus 1
    seg = jnp.sum(positions[:, None] >= cu_seqlens[None, :-1], axis=1) - 1
    pad = positions >= cu_seqlens[-1]
    return jnp.where(pad, -1, seg)


def fmha_packed(qkv, cu_seqlens, *, causal: bool = False,
                scale: Optional[float] = None,
                use_pallas: Optional[bool] = None):
    """Attention over a packed batch.

    ``qkv``: (total_tokens, 3, heads, head_dim) — the reference's interleaved
    layout (``fmha.py:33``). ``cu_seqlens``: (batch+1,) int32 prefix sums.
    Returns (total_tokens, heads, head_dim); padding rows are zero.
    """
    total, three, h, d = qkv.shape
    if three != 3:
        raise ValueError(f"qkv must be (total, 3, heads, d), got {qkv.shape}")
    seg = cu_seqlens_to_segment_ids(cu_seqlens, total)[None]  # (1, total)
    q, k, v = (qkv[:, i].transpose(1, 0, 2)[None] for i in range(3))
    o = flash_attention_varlen(q, k, v, seg, causal=causal, scale=scale,
                               use_pallas=use_pallas)
    return o[0].transpose(1, 0, 2)


class FMHA(nn.Module):
    """Ref ``fmha.py:59-76`` — module wrapper around the packed op."""

    num_heads: int

    @nn.compact
    def __call__(self, qkv, cu_seqlens, *, causal: bool = False):
        return fmha_packed(qkv, cu_seqlens, causal=causal)

"""Segment-masked attention over token-packed batches (see package doc)."""

from __future__ import annotations

import math
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from apex_tpu.ops.attention import attention_reference


def cu_seqlens_to_segment_ids(cu_seqlens, total: int):
    """[0, l1, l1+l2, ...] -> per-token sequence index (ref fmha.py cu_seqlens
    convention). Tokens at/after the last boundary get segment -1 (padding),
    which matches nothing — including other padding — in the mask."""
    positions = jnp.arange(total)
    # segment of token t = number of boundaries <= t, minus 1
    seg = jnp.sum(positions[:, None] >= cu_seqlens[None, :-1], axis=1) - 1
    pad = positions >= cu_seqlens[-1]
    return jnp.where(pad, -1, seg)


def fmha_packed(qkv, cu_seqlens, *, causal: bool = False,
                scale: Optional[float] = None):
    """Attention over a packed batch.

    ``qkv``: (total_tokens, 3, heads, head_dim) — the reference's interleaved
    layout (``fmha.py:33``). ``cu_seqlens``: (batch+1,) int32 prefix sums.
    Returns (total_tokens, heads, head_dim).
    """
    total, three, h, d = qkv.shape
    if three != 3:
        raise ValueError(f"qkv must be (total, 3, heads, d), got {qkv.shape}")
    seg = cu_seqlens_to_segment_ids(cu_seqlens, total)
    # cross-segment (and any-padding) pairs masked out
    mask = (seg[:, None] != seg[None, :]) | (seg[:, None] < 0)
    if causal:
        mask = mask | (jnp.arange(total)[None, :] > jnp.arange(total)[:, None])
    q, k, v = (qkv[:, i].transpose(1, 0, 2)[None] for i in range(3))
    o = attention_reference(q, k, v, mask=mask[None, None], scale=scale)
    return o[0].transpose(1, 0, 2)


class FMHA(nn.Module):
    """Ref ``fmha.py:59-76`` — module wrapper around the packed op."""

    num_heads: int

    @nn.compact
    def __call__(self, qkv, cu_seqlens, *, causal: bool = False):
        return fmha_packed(qkv, cu_seqlens, causal=causal)

"""Packed variable-length attention (ref ``apex/contrib/fmha``).

Reference: ``apex/contrib/fmha/fmha.py:33-76`` + ``fmhalib`` (7.3k LoC CUDA):
fused MHA over token-packed batches — sequences of different lengths
concatenated into one (total_tokens, ...) tensor with ``cu_seqlens``
boundaries, seqlen ≤ 512, BERT-style.

TPU re-design: XLA wants static shapes, so the packed layout is kept but the
variable lengths become a **segment-id mask**: position i may attend to j iff
they belong to the same sequence. That is one broadcasted compare — no
kernel needed beyond the attention itself — and there is no 512 limit.
"""

from apex_tpu.contrib.fmha.fmha import (  # noqa: F401
    FMHA,
    cu_seqlens_to_segment_ids,
    fmha_packed,
)

__all__ = ["FMHA", "fmha_packed", "cu_seqlens_to_segment_ids"]

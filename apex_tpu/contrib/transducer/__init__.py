"""RNN-T transducer joint + loss (ref ``apex/contrib/transducer``)."""

from apex_tpu.contrib.transducer.transducer import (  # noqa: F401
    TransducerJoint,
    TransducerLoss,
    transducer_joint,
    transducer_loss,
    unpack_transducer_input,
)

__all__ = ["TransducerJoint", "TransducerLoss", "transducer_joint",
           "transducer_loss", "unpack_transducer_input"]

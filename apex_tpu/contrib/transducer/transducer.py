"""RNN-T joint and alpha-beta loss.

Reference: ``apex/contrib/transducer/transducer.py:5-196`` +
``transducer_joint_cuda`` / ``transducer_loss_cuda`` (~2k LoC): a tiled
broadcast-add joint with fused ReLU/dropout and output packing (skipping
padded (t, u) cells), and a forward-backward transducer loss whose backward
uses the saved alpha/beta lattices.

TPU re-design: the joint is a broadcast add XLA fuses with its epilogue
(packing is a CUDA memory trick that XLA's static-shape world replaces with
masking). The loss is the standard log-space alpha recursion as a
``lax.scan`` over time with an inner scan over the label axis; autodiff
through the scans reproduces the reference backward without storing both
lattices. Batch entries are masked by ``f_len``/``y_len``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG = -1e30


def _packed_cell_coords(batch_offset, per_batch_len, packed_batch: int):
    """Map packed row r -> (b, local) under the reference layout: batch b's
    cells occupy rows [offset[b-1], offset[b]) with ``per_batch_len[b]``
    cells each (ref ``TransducerJoint.forward:43-66`` batch_offset
    contract). Returns (b, local, valid) for every static row index."""
    r = jnp.arange(packed_batch)
    b = jnp.searchsorted(batch_offset, r, side="right")
    total = batch_offset[-1]
    b_safe = jnp.clip(b, 0, batch_offset.shape[0] - 1)
    start = batch_offset[b_safe] - per_batch_len[b_safe]
    local = r - start
    return b_safe, local, r < total


def transducer_joint(f, g, f_len=None, g_len=None, *, relu: bool = False,
                     dropout_rate: float = 0.0, dropout_rng=None,
                     pack_output: bool = False, batch_offset=None,
                     packed_batch: int = 0):
    """Broadcast joint: ``f`` (B, T, H) + ``g`` (B, U, H) -> (B, T, U, H)
    (ref ``TransducerJoint.forward:5-66``).

    With ``pack_output`` the don't-care lattice cells are removed and the
    result is (packed_batch, H): batch b's valid (t, u) cells sit at rows
    ``batch_offset[b-1] + t * g_len[b] + u`` (``batch_offset =
    cumsum(f_len * g_len)``, the reference's contract). The CUDA original
    packs by copying the dense output; on TPU the packed rows are computed
    DIRECTLY — a searchsorted row->cell gather feeds one static-shape
    broadcast add, so the dense (B, T, U, H) lattice never materializes.
    ``packed_batch`` must be a static int (>= batch_offset[-1]); surplus
    rows are zeroed."""
    if pack_output:
        if batch_offset is None or packed_batch == 0 or f_len is None \
                or g_len is None:
            raise ValueError(
                "pack_output needs f_len, g_len, batch_offset "
                "(= cumsum(f_len * g_len)) and a static packed_batch")
        b, local, valid = _packed_cell_coords(
            batch_offset, f_len * g_len, packed_batch)
        # surplus rows (r >= batch_offset[-1]) clamp b to the LAST batch;
        # if that batch has g_len == 0 the // and % would divide by zero
        # (backend-defined result, and only masked after the fact) — use a
        # safe divisor; the valid multiply zeroes those rows regardless
        g_safe = jnp.maximum(g_len[b], 1)
        t, u = local // g_safe, local % g_safe
        out = f[b, t] + g[b, u]  # (packed_batch, H)
        if relu:
            out = jax.nn.relu(out)
        if dropout_rate > 0.0 and dropout_rng is not None:
            keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                        out.shape)
            out = jnp.where(keep, out / (1.0 - dropout_rate), 0.0)
        return out * valid[:, None]
    out = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        out = jax.nn.relu(out)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, out.shape)
        out = jnp.where(keep, out / (1.0 - dropout_rate), 0.0)
    if f_len is not None:
        t_mask = jnp.arange(f.shape[1])[None, :] < f_len[:, None]
        out = out * t_mask[:, :, None, None]
    if g_len is not None:
        u_mask = jnp.arange(g.shape[1])[None, :] < g_len[:, None]
        out = out * u_mask[:, None, :, None]
    return out


def unpack_transducer_input(x_packed, f_len, y_len, batch_offset,
                            max_f_len: int, max_u1: int):
    """Packed loss input (packed_batch, V) -> dense (B, max_f_len, max_u1,
    V). Layout: batch b's cell (t, u) at row ``batch_offset[b-1] +
    t * (y_len[b] + 1) + u`` (ref ``TransducerLoss.forward:96-110``
    batch_offset contract). Invalid cells gather-fill with 0 — the alpha
    recursion never reads them into a valid terminal cell."""
    t = jnp.arange(max_f_len)[None, :, None]
    u = jnp.arange(max_u1)[None, None, :]
    u1 = (y_len + 1)[:, None, None]
    start = (batch_offset - f_len * (y_len + 1))[:, None, None]
    rows = start + t * u1 + u
    valid = (t < f_len[:, None, None]) & (u < u1)
    rows = jnp.clip(rows, 0, x_packed.shape[0] - 1)
    return jnp.where(valid[..., None], x_packed[rows], 0.0)


def transducer_loss(x, label, f_len, y_len, blank_idx: int = 0):
    """Per-sequence RNN-T negative log-likelihood.

    ``x``: (B, T, U+1, V) joint **log-probs** (log-softmax over V).
    ``label``: (B, U) int targets. ``f_len``: (B,) valid frames.
    ``y_len``: (B,) valid labels. (ref ``TransducerLoss:68-130``.)

    alpha recursion (log space):
      alpha[0,0] = 0
      alpha[t,u] = logaddexp(alpha[t-1,u] + blank[t-1,u],
                             alpha[t,u-1] + emit[t,u-1])
      nll = -(alpha[f_len-1, y_len] + blank[f_len-1, y_len])
    """
    B, T, U1, V = x.shape
    U = U1 - 1
    blank = x[..., blank_idx]  # (B, T, U+1)
    emit = jnp.take_along_axis(
        x[:, :, :U, :], label[:, None, :, None], axis=-1)[..., 0]  # (B,T,U)

    def time_step(alpha_prev, t):
        # horizontal move: consume frame t-1 with a blank
        from_blank = alpha_prev + blank[:, t - 1, :]  # (B, U+1)

        # vertical moves at time t: emit labels sequentially in u
        def u_step(carry, u):
            # carry: alpha_new[u-1]; produce alpha_new[u]
            val = jnp.logaddexp(from_blank[:, u],
                                carry + emit[:, t, u - 1])
            return val, val

        a0 = from_blank[:, 0]
        _, rest = lax.scan(u_step, a0, jnp.arange(1, U1))
        alpha_t = jnp.concatenate([a0[:, None], rest.T], axis=1)
        return alpha_t, None

    # alpha at t=0: only vertical emissions
    def u_step0(carry, u):
        val = carry + emit[:, 0, u - 1]
        return val, val

    a00 = jnp.zeros((B,))
    _, rest0 = lax.scan(u_step0, a00, jnp.arange(1, U1))
    alpha0 = jnp.concatenate([a00[:, None], rest0.T], axis=1)

    # keep every time row: the terminal cell is at (f_len-1, y_len), which
    # differs per batch entry
    def time_step_keep(alpha_prev, t):
        alpha_t, _ = time_step(alpha_prev, t)
        return alpha_t, alpha_t

    if T > 1:
        _, rows = lax.scan(time_step_keep, alpha0, jnp.arange(1, T))
        all_alpha = jnp.concatenate([alpha0[None], rows], axis=0)  # (T,B,U+1)
    else:
        all_alpha = alpha0[None]
    all_alpha = all_alpha.transpose(1, 0, 2)  # (B, T, U+1)

    t_idx = jnp.clip(f_len - 1, 0, T - 1)
    final_alpha = jnp.take_along_axis(
        all_alpha, t_idx[:, None, None].repeat(U1, 2), axis=1)[:, 0, :]
    final_alpha = jnp.take_along_axis(
        final_alpha, y_len[:, None], axis=1)[:, 0]
    final_blank = jnp.take_along_axis(
        jnp.take_along_axis(blank, t_idx[:, None, None].repeat(U1, 2),
                            axis=1)[:, 0, :],
        y_len[:, None], axis=1)[:, 0]
    return -(final_alpha + final_blank)


class TransducerJoint:
    """Module-shaped wrapper (ref ``TransducerJoint:5``)."""

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: float = 0.0):
        self.pack_output = pack_output
        self.relu = relu
        self.dropout = dropout

    def __call__(self, f, g, f_len=None, g_len=None, dropout_rng=None,
                 batch_offset=None, packed_batch: int = 0):
        return transducer_joint(
            f, g, f_len, g_len, relu=self.relu,
            dropout_rate=self.dropout if dropout_rng is not None else 0.0,
            dropout_rng=dropout_rng, pack_output=self.pack_output,
            batch_offset=batch_offset, packed_batch=packed_batch)


class TransducerLoss:
    """Module-shaped wrapper (ref ``TransducerLoss:68``)."""

    def __init__(self, fuse_softmax_backward: bool = True,
                 packed_input: bool = False):
        self.fuse_softmax = fuse_softmax_backward
        self.packed_input = packed_input

    def __call__(self, x, label, f_len, y_len, blank_idx: int = 0,
                 batch_offset=None, max_f_len: Optional[int] = None):
        """``x``: raw joint activations; log-softmax applied here (the
        reference fuses softmax backward into the loss backward — autodiff
        through ``log_softmax`` does the same). With ``packed_input``,
        ``x`` is the (packed_batch, V) lattice from a ``pack_output``
        joint (``batch_offset = cumsum(f_len * (y_len + 1))``, static
        ``max_f_len`` required); log-softmax runs on the packed rows and a
        gather restores the dense lattice for the alpha recursion —
        autodiff scatters the cotangent back to packed form."""
        logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
        if self.packed_input:
            if batch_offset is None or max_f_len is None:
                raise ValueError(
                    "packed_input needs batch_offset "
                    "(= cumsum(f_len * (y_len + 1))) and a static max_f_len")
            logp = unpack_transducer_input(
                logp, f_len, y_len, batch_offset, max_f_len,
                label.shape[1] + 1)
        return transducer_loss(logp, label, f_len, y_len, blank_idx)

"""RNN-T joint and alpha-beta loss.

Reference: ``apex/contrib/transducer/transducer.py:5-196`` +
``transducer_joint_cuda`` / ``transducer_loss_cuda`` (~2k LoC): a tiled
broadcast-add joint with fused ReLU/dropout and output packing (skipping
padded (t, u) cells), and a forward-backward transducer loss whose backward
uses the saved alpha/beta lattices.

TPU re-design: the joint is a broadcast add XLA fuses with its epilogue
(packing is a CUDA memory trick that XLA's static-shape world replaces with
masking). The loss is the standard log-space alpha recursion as a
``lax.scan`` over time with an inner scan over the label axis; autodiff
through the scans reproduces the reference backward without storing both
lattices. Batch entries are masked by ``f_len``/``y_len``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG = -1e30


def transducer_joint(f, g, f_len=None, g_len=None, *, relu: bool = False,
                     dropout_rate: float = 0.0, dropout_rng=None):
    """Broadcast joint: ``f`` (B, T, H) + ``g`` (B, U, H) -> (B, T, U, H)
    (ref ``TransducerJoint.forward:5-66``; packing omitted — masked lattice
    cells simply carry zeros)."""
    out = f[:, :, None, :] + g[:, None, :, :]
    if relu:
        out = jax.nn.relu(out)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, out.shape)
        out = jnp.where(keep, out / (1.0 - dropout_rate), 0.0)
    if f_len is not None:
        t_mask = jnp.arange(f.shape[1])[None, :] < f_len[:, None]
        out = out * t_mask[:, :, None, None]
    if g_len is not None:
        u_mask = jnp.arange(g.shape[1])[None, :] < g_len[:, None]
        out = out * u_mask[:, None, :, None]
    return out


def transducer_loss(x, label, f_len, y_len, blank_idx: int = 0):
    """Per-sequence RNN-T negative log-likelihood.

    ``x``: (B, T, U+1, V) joint **log-probs** (log-softmax over V).
    ``label``: (B, U) int targets. ``f_len``: (B,) valid frames.
    ``y_len``: (B,) valid labels. (ref ``TransducerLoss:68-130``.)

    alpha recursion (log space):
      alpha[0,0] = 0
      alpha[t,u] = logaddexp(alpha[t-1,u] + blank[t-1,u],
                             alpha[t,u-1] + emit[t,u-1])
      nll = -(alpha[f_len-1, y_len] + blank[f_len-1, y_len])
    """
    B, T, U1, V = x.shape
    U = U1 - 1
    blank = x[..., blank_idx]  # (B, T, U+1)
    emit = jnp.take_along_axis(
        x[:, :, :U, :], label[:, None, :, None], axis=-1)[..., 0]  # (B,T,U)

    def time_step(alpha_prev, t):
        # horizontal move: consume frame t-1 with a blank
        from_blank = alpha_prev + blank[:, t - 1, :]  # (B, U+1)

        # vertical moves at time t: emit labels sequentially in u
        def u_step(carry, u):
            # carry: alpha_new[u-1]; produce alpha_new[u]
            val = jnp.logaddexp(from_blank[:, u],
                                carry + emit[:, t, u - 1])
            return val, val

        a0 = from_blank[:, 0]
        _, rest = lax.scan(u_step, a0, jnp.arange(1, U1))
        alpha_t = jnp.concatenate([a0[:, None], rest.T], axis=1)
        return alpha_t, None

    # alpha at t=0: only vertical emissions
    def u_step0(carry, u):
        val = carry + emit[:, 0, u - 1]
        return val, val

    a00 = jnp.zeros((B,))
    _, rest0 = lax.scan(u_step0, a00, jnp.arange(1, U1))
    alpha0 = jnp.concatenate([a00[:, None], rest0.T], axis=1)

    # keep every time row: the terminal cell is at (f_len-1, y_len), which
    # differs per batch entry
    def time_step_keep(alpha_prev, t):
        alpha_t, _ = time_step(alpha_prev, t)
        return alpha_t, alpha_t

    if T > 1:
        _, rows = lax.scan(time_step_keep, alpha0, jnp.arange(1, T))
        all_alpha = jnp.concatenate([alpha0[None], rows], axis=0)  # (T,B,U+1)
    else:
        all_alpha = alpha0[None]
    all_alpha = all_alpha.transpose(1, 0, 2)  # (B, T, U+1)

    t_idx = jnp.clip(f_len - 1, 0, T - 1)
    final_alpha = jnp.take_along_axis(
        all_alpha, t_idx[:, None, None].repeat(U1, 2), axis=1)[:, 0, :]
    final_alpha = jnp.take_along_axis(
        final_alpha, y_len[:, None], axis=1)[:, 0]
    final_blank = jnp.take_along_axis(
        jnp.take_along_axis(blank, t_idx[:, None, None].repeat(U1, 2),
                            axis=1)[:, 0, :],
        y_len[:, None], axis=1)[:, 0]
    return -(final_alpha + final_blank)


class TransducerJoint:
    """Module-shaped wrapper (ref ``TransducerJoint:5``)."""

    def __init__(self, pack_output: bool = False, relu: bool = False,
                 dropout: float = 0.0):
        if pack_output:
            raise NotImplementedError(
                "pack_output is a CUDA memory-layout optimization; the TPU "
                "path keeps the dense masked lattice")
        self.relu = relu
        self.dropout = dropout

    def __call__(self, f, g, f_len=None, g_len=None, dropout_rng=None):
        return transducer_joint(
            f, g, f_len, g_len, relu=self.relu,
            dropout_rate=self.dropout if dropout_rng is not None else 0.0,
            dropout_rng=dropout_rng)


class TransducerLoss:
    """Module-shaped wrapper (ref ``TransducerLoss:68``)."""

    def __init__(self, fuse_softmax_backward: bool = True,
                 packed_input: bool = False):
        if packed_input:
            raise NotImplementedError("packed input not supported on TPU")
        self.fuse_softmax = fuse_softmax_backward

    def __call__(self, x, label, f_len, y_len, blank_idx: int = 0):
        """``x``: raw joint activations; log-softmax applied here (the
        reference fuses softmax backward into the loss backward — autodiff
        through ``log_softmax`` does the same)."""
        logp = jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)
        return transducer_loss(logp, label, f_len, y_len, blank_idx)

"""Contrib xentropy API (ref ``apex/contrib/xentropy/softmax_xentropy.py:4``):
the fused label-smoothing cross-entropy lives in ``apex_tpu.ops.xentropy``;
this package re-exports it under the reference's contrib name."""

from apex_tpu.ops.xentropy import softmax_cross_entropy_loss  # noqa: F401

SoftmaxCrossEntropyLoss = softmax_cross_entropy_loss

__all__ = ["SoftmaxCrossEntropyLoss", "softmax_cross_entropy_loss"]

"""Fused ResNet bottleneck (ref ``apex/contrib/bottleneck``).

Reference: ``Bottleneck`` (``bottleneck/bottleneck.py:112``) — a
cudnn-frontend-fused conv-bn-relu block — and ``SpatialBottleneck`` (:386),
which shards the spatial H dim across GPUs with NVLink halo exchanges.

TPU re-design: the plain block is ``apex_tpu.models.resnet.BottleneckBlock``
(XLA fuses BN+ReLU into the convs; NHWC native). The spatial variant is
:func:`spatial_conv3x3`: H-sharded conv with a 1-row halo exchanged over
``ppermute`` — the ICI-native equivalent of the reference's ``nccl_p2p``
halo kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.models.resnet import BottleneckBlock as Bottleneck  # noqa: F401
from apex_tpu.parallel.mesh import SP_AXIS


def _halo_exchange(x, axis_name: str):
    """Send my top row to the previous rank and bottom row to the next
    (ref ``bottleneck.py`` halo_exchange with nccl_p2p): returns
    (row_from_prev, row_from_next), zeros at the boundary ranks."""
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    top = x[:, :1]
    bot = x[:, -1:]
    # bottom row of rank i-1 arrives at rank i (shift +1)
    from_prev = lax.ppermute(bot, axis_name, [(i, (i + 1) % n) for i in range(n)])
    from_next = lax.ppermute(top, axis_name, [(i, (i - 1) % n) for i in range(n)])
    zero = jnp.zeros_like(top)
    from_prev = jnp.where(idx == 0, zero, from_prev)
    from_next = jnp.where(idx == n - 1, zero, from_next)
    return from_prev, from_next


def spatial_conv3x3(x, kernel, axis_name: str = SP_AXIS):
    """3x3 'SAME' conv over an H-sharded NHWC tensor (ref SpatialBottleneck
    middle conv): exchange 1-row halos, convolve VALID over the padded
    shard, producing exactly the rows this rank owns.

    ``x``: (B, H_local, W, Cin); ``kernel``: (3, 3, Cin, Cout).
    """
    from_prev, from_next = _halo_exchange(x, axis_name)
    padded = jnp.concatenate([from_prev, x, from_next], axis=1)
    out = lax.conv_general_dilated(
        padded, kernel, window_strides=(1, 1),
        padding=((0, 0), (1, 1)),  # H handled by halos, W by zero-pad
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return out


__all__ = ["Bottleneck", "spatial_conv3x3"]

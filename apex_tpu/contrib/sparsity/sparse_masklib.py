"""N:M structured sparsity mask computation.

Reference: ``apex/contrib/sparsity/sparse_masklib.py`` — ``create_mask``
builds per-tensor boolean masks for patterns like ``m4n2_1d`` (of every 4
consecutive elements along the input dim, keep the 2 largest-magnitude).

TPU note: the mask *computation* is plain top-k over reshaped groups (no
kernel needed); the *payoff* differs from Ampere sparse tensor cores — on
TPU, 2:4 masking preserves model-accuracy workflows and memory/bandwidth
wins for masked storage, not an MXU rate doubling. The API is kept for
capability parity.
"""

from __future__ import annotations

import re

import jax.numpy as jnp


def _parse_pattern(pattern: str):
    m = re.fullmatch(r"m(\d+)n(\d+)_(1|2)d", pattern)
    if not m:
        raise ValueError(
            f"unknown sparsity pattern {pattern!r} (expected e.g. 'm4n2_1d')")
    return int(m.group(1)), int(m.group(2)), m.group(3)


def create_mask(tensor, pattern: str = "m4n2_1d"):
    """Boolean keep-mask with the same shape as ``tensor`` (ref
    ``create_mask``): in every group of ``m`` consecutive elements along the
    last dim, keep the ``n`` largest magnitudes. ``_2d`` applies the same
    rule to the flattened trailing 2-D blocks (approximation of the
    reference's permuted-2d search, which is an optional accuracy tweak)."""
    m, n, _dims = _parse_pattern(pattern)
    shape = tensor.shape
    if shape[-1] % m != 0:
        raise ValueError(f"last dim {shape[-1]} not divisible by group {m}")
    g = jnp.abs(tensor).reshape(shape[:-1] + (shape[-1] // m, m))
    # rank within each group; keep the n largest magnitudes
    order = jnp.argsort(g, axis=-1)  # ascending
    ranks = jnp.argsort(order, axis=-1)
    keep = ranks >= (m - n)
    return keep.reshape(shape)

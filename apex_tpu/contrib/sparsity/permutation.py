"""Channel-permutation search for 2:4 structured sparsity.

Reference capability: ``apex/contrib/sparsity/permutation_lib.py`` +
``permutation_search_kernels/`` (exhaustive stripe-group search, greedy
channel-swap CUDA kernels, bounded escapes). Permuting the input channels of
a weight matrix before m4n2 pruning changes WHICH elements fall into each
group of four, so a good permutation raises the magnitude the 2:4 mask
preserves — the accuracy-recovery step MLPerf submissions rely on.

Redesign notes: the reference enumerates stripe-group permutations with a
pickled cache and loops column pairs one swap at a time (CUDA kernels when
available). Here the search is a *vectorized* greedy descent: one numpy
einsum scores every candidate swap of a column against all other columns at
once, applied column-by-column until a sweep finds no improvement, with
bounded random-restart escapes (the reference's ``escape_attempts``). numpy
is the right tool — this is an offline preprocessing pass over host weights,
not a device op.

Scope note: this module finds and applies permutations on individual
matrices. Propagating a permutation through a whole network (permuting the
producing layer's output channels to compensate, the reference's
``permutation_lib.Permutation`` graph pass) is a model-surgery step the
caller drives, because a functional param pytree has no generic graph of
which leaf feeds which.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

GROUP = 4  # m4n2: groups of 4 input channels, keep 2


def magnitude_after_2_4(matrix: np.ndarray) -> float:
    """Total |magnitude| preserved by 2:4 pruning along the last dim.

    ``matrix``: (rows, cols) with cols % 4 == 0. For every row and every
    aligned group of 4 columns, the 2 largest |values| survive.
    """
    a = np.abs(np.asarray(matrix, dtype=np.float32))
    r, c = a.shape
    g = a.reshape(r, c // GROUP, GROUP)
    # sum of top-2 per group = sum - (two smallest) = partition
    top2 = np.partition(g, GROUP - 2, axis=2)[:, :, GROUP - 2:]
    return float(top2.sum())


def _group_scores(a: np.ndarray) -> np.ndarray:
    """(rows, n_groups) preserved magnitude per aligned 4-column group."""
    r, c = a.shape
    g = a.reshape(r, c // GROUP, GROUP)
    return np.partition(g, GROUP - 2, axis=2)[:, :, GROUP - 2:].sum(axis=(0, 2))


_CHUNK_ELEMS = 16_000_000  # bound candidate temporaries to ~256 MB fp32


def _swap_gains(a: np.ndarray, col: int) -> np.ndarray:
    """Score improvement of swapping ``col`` with every other column.

    Returns (cols,) gains; entries inside ``col``'s own group are 0 (a swap
    within a group never changes the 2:4 score). Vectorized: builds the
    candidate group of ``col``'s group with each foreign column substituted
    in, and each foreign group with ``col`` substituted — chunked over
    candidate columns so temporaries stay bounded on large layers.
    """
    r, c = a.shape
    ngroups = c // GROUP
    gi = col // GROUP
    slot = col % GROUP
    groups = a.reshape(r, ngroups, GROUP)

    base = _group_scores(a)  # (ngroups,)
    gains = np.empty(c, np.float32)
    chunk = max(GROUP, min(c, _CHUNK_ELEMS // max(r * GROUP, 1)))
    slots = np.tile(np.arange(GROUP), ngroups)  # slot of each column j

    for j0 in range(0, c, chunk):
        j1 = min(j0 + chunk, c)
        n = j1 - j0
        # candidate A: col's group with column j substituted into col's slot
        cand_a = np.broadcast_to(groups[:, gi, None, :], (r, n, GROUP)).copy()
        cand_a[:, :, slot] = a[:, j0:j1]
        top2_a = np.partition(np.abs(cand_a), GROUP - 2, axis=2)[:, :, GROUP - 2:]
        score_a = top2_a.sum(axis=(0, 2))  # (n,)

        # candidate B: j's group with col substituted into j's slot
        cand_b = groups[:, j0 // GROUP:(j1 - 1) // GROUP + 1, :]
        cand_b = np.repeat(cand_b, GROUP, axis=1)[:, j0 % GROUP:, :][:, :n, :].copy()
        cand_b[:, np.arange(n), slots[j0:j1]] = a[:, [col]]
        top2_b = np.partition(np.abs(cand_b), GROUP - 2, axis=2)[:, :, GROUP - 2:]
        score_b = top2_b.sum(axis=(0, 2))  # (n,)

        gains[j0:j1] = (score_a + score_b) - (
            base[gi] + base[np.arange(j0, j1) // GROUP])
    gains[gi * GROUP:(gi + 1) * GROUP] = 0.0  # same-group swaps are no-ops
    return gains


def search_permutation(
    matrix: np.ndarray,
    escape_attempts: int = 10,
    max_sweeps: int = 100,
    seed: int = 0,
    max_rows: int = 4096,
) -> Tuple[np.ndarray, float, float]:
    """Greedy channel-permutation search maximizing post-2:4 magnitude.

    Returns ``(permutation, base_magnitude, best_magnitude)`` where
    ``matrix[:, permutation]`` is the permuted matrix achieving
    ``best_magnitude``. Greedy sweeps apply the best available swap per
    column until no swap improves; ``escape_attempts`` random swaps restart
    the descent from perturbed points (ref ``escape_attempts``), keeping the
    best permutation seen.

    Matrices with more than ``max_rows`` rows are row-subsampled for the
    *search* (the column grouping statistics concentrate well); the returned
    base/best magnitudes are always evaluated on the full matrix.
    """
    full = np.abs(np.asarray(matrix, dtype=np.float32))
    r, c = full.shape
    if c % GROUP != 0:
        raise ValueError(f"columns ({c}) must be divisible by {GROUP}")
    rng = np.random.default_rng(seed)
    a = full
    if r > max_rows:
        a = full[rng.choice(r, size=max_rows, replace=False)]
    perm = np.arange(c)
    base = magnitude_after_2_4(full)

    best_perm = perm.copy()
    best_score = base
    cur = a.copy()
    escapes_left = escape_attempts

    while True:
        improved = True
        sweeps = 0
        while improved and sweeps < max_sweeps:
            improved = False
            sweeps += 1
            for col in range(c):
                gains = _swap_gains(cur, col)
                j = int(np.argmax(gains))
                if gains[j] > 1e-6:
                    cur[:, [col, j]] = cur[:, [j, col]]
                    perm[[col, j]] = perm[[j, col]]
                    improved = True
        score = magnitude_after_2_4(full[:, perm])
        if score > best_score:
            best_score = score
            best_perm = perm.copy()
        if escapes_left <= 0:
            break
        # bounded escape: random swap pair, resume the descent
        escapes_left -= 1
        i, j = rng.choice(c, size=2, replace=False)
        cur[:, [i, j]] = cur[:, [j, i]]
        perm[[i, j]] = perm[[j, i]]

    return best_perm, base, best_score


def invert_permutation(perm: np.ndarray) -> np.ndarray:
    """inv such that ``x[:, perm][:, inv] == x``."""
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm))
    return inv


def permute_and_mask(matrix, escape_attempts: int = 10, seed: int = 0):
    """Search a permutation, prune in the permuted domain, and return the
    mask mapped back to the ORIGINAL column order.

    This is the pure-masking use of the search (no model surgery): the mask
    computed on the permuted matrix is un-permuted, so callers keep their
    layout while the mask's group structure follows the permutation. Note
    the un-permuted mask is no longer aligned-4-group structured — hardware
    that requires aligned 2:4 groups needs the full weight-permutation
    surgery instead (see module docstring).

    Returns ``(mask, perm, base_magnitude, best_magnitude)``.
    """
    import jax.numpy as jnp

    from apex_tpu.contrib.sparsity.sparse_masklib import create_mask

    m = np.asarray(matrix)
    orig_shape = m.shape
    m2 = m.reshape(-1, orig_shape[-1])
    perm, base, best = search_permutation(m2, escape_attempts, seed=seed)
    permuted = m2[:, perm]
    mask_p = np.asarray(create_mask(jnp.asarray(permuted), "m4n2_1d"))
    mask = mask_p[:, invert_permutation(perm)].reshape(orig_shape)
    return mask, perm, base, best

"""ASP — mask bookkeeping and optimizer patching, functionally.

Reference: ``apex/contrib/sparsity/asp.py:28`` — ``ASP`` walks the model for
whitelisted layers, computes m4n2 masks, and patches ``optimizer.step`` to
re-apply masks after every update so pruned weights stay zero through
fine-tuning. The channel-permutation search (``permutation_lib.py``) that
recovers accuracy before pruning is an offline preprocessing step and is not
re-implemented here (its output is just a better mask).

TPU re-design: masks are a pytree parallel to the params; "patching step"
becomes wrapping the optax transform so updates are masked — one tree_map.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from apex_tpu.contrib.sparsity.sparse_masklib import create_mask

Pytree = Any


def _default_whitelist(path: str, x) -> bool:
    """Ref whitelist (asp.py:40-80): weight matrices of linear/conv layers —
    here: float tensors with ndim >= 2 and a 4-divisible last dim."""
    return (hasattr(x, "ndim") and x.ndim >= 2
            and jnp.issubdtype(jnp.result_type(x), jnp.floating)
            and x.shape[-1] % 4 == 0)


class ASP:
    """Functional ASP (ref classmethod surface ``init_model_for_pruning`` /
    ``compute_sparse_masks`` / ``init_optimizer_for_pruning`` /
    ``restore_pruned_weights``)."""

    def __init__(self, mask_calculator: str = "m4n2_1d",
                 whitelist: Callable[[str, Any], bool] = _default_whitelist,
                 allow_permutation: bool = False,
                 permutation_escape_attempts: int = 10):
        self.pattern = mask_calculator
        self.whitelist = whitelist
        self.allow_permutation = allow_permutation
        self.permutation_escape_attempts = permutation_escape_attempts
        if allow_permutation and mask_calculator != "m4n2_1d":
            raise ValueError(
                f"channel-permutation search assumes 2:4 groups (m4n2_1d); "
                f"got mask_calculator={mask_calculator!r}")

    def compute_sparse_masks(self, params: Pytree) -> Pytree:
        """Mask pytree: keep-masks for whitelisted leaves, ``None`` (keep all)
        elsewhere (ref ``compute_sparse_masks:204``).

        With ``allow_permutation`` (ref ``init_model_for_pruning``'s
        ``allow_permutation``), each whitelisted leaf's input channels are
        permuted by the greedy search of
        :mod:`apex_tpu.contrib.sparsity.permutation` before pruning and the
        mask is mapped back — preserving more magnitude than aligned-group
        pruning on the raw layout."""
        from apex_tpu.amp.frontend import _path_str

        def leaf(path, x):
            if not self.whitelist(_path_str(path), x):
                return None
            if self.allow_permutation:
                from apex_tpu.contrib.sparsity.permutation import (
                    permute_and_mask,
                )

                mask, _, _, _ = permute_and_mask(
                    x, self.permutation_escape_attempts)
                return jnp.asarray(mask)
            return create_mask(x, self.pattern)

        return jax.tree_util.tree_map_with_path(leaf, params)

    @staticmethod
    def apply_masks(params: Pytree, masks: Pytree) -> Pytree:
        """Zero out pruned weights (ref mask-apply in patched step)."""
        return jax.tree_util.tree_map(
            lambda p, m: p if m is None else jnp.where(m, p, 0).astype(p.dtype),
            params, masks, is_leaf=lambda x: x is None)

    def init_optimizer_for_pruning(self, optimizer, masks: Pytree):
        """Wrap an optax transform so post-step params stay masked (ref
        ``init_optimizer_for_pruning:176`` — patches ``optimizer.step``).
        Masking the UPDATE keeps ``p + u`` masked as long as ``p`` starts
        masked (both are zero at pruned slots)."""
        import optax

        def update(grads, state, params=None):
            updates, new_state = optimizer.update(grads, state, params)
            masked = jax.tree_util.tree_map(
                lambda u, m: u if m is None
                else jnp.where(m, u, 0).astype(u.dtype),
                updates, masks, is_leaf=lambda x: x is None)
            return masked, new_state

        return optax.GradientTransformation(optimizer.init, update)

    @staticmethod
    def restore_pruned_weights(params: Pytree, dense_params: Pytree) -> Pytree:
        """Ref ``restore_pruned_weights:257``: recover the dense copy."""
        return jax.tree_util.tree_map(lambda _, d: d, params, dense_params)

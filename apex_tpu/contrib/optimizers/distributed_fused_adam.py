"""ZeRO-style Adam with dp-sharded optimizer state.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py:9`` —
``DistributedFusedAdam``: shards Adam moments and fp32 master weights across
the data-parallel group and pipelines bucketed reduce-scatter (grads) /
all-gather (params) overlapped with backward, with optional global-norm
clipping and AMP grad scaling. ~1000 LoC of stream bookkeeping + CUDA
multi-tensor kernels.

TPU re-design: the same dataflow expressed per-leaf with three collectives
(see ``_sharding.py``), run inside the mesh program. State (fp32 master
shard + moment shards) is 1/dp per device — ZeRO stage 1+2 memory. The
whole step is one pure function; XLA overlaps the collectives with compute.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.comm.collectives import (
    CompressionConfig,
    compressed_psum_scatter,
    fold_seed,
)
from apex_tpu.comm.error_feedback import init_error_feedback
from apex_tpu.contrib.optimizers._sharding import (
    adam_shard_update,
    gather_leaf,
    global_norm_shards,
    local_sq,
    scatter_leaf,
    shard_multiple,
    slice_leaf,
)
from apex_tpu.parallel.mesh import DP_AXIS

Pytree = Any

# the shard alignment / norm helpers moved to ``_sharding.py`` (shared with
# apex_tpu.fsdp); the private names stay importable for existing callers
_shard_multiple = shard_multiple
_local_sq = local_sq
_global_norm_shards = global_norm_shards


def _reduce_grad_leaf(g, axis_name, compression, residual, seed):
    """One leaf's grad reduce-scatter — quantized wire when configured.
    Returns (fp32 summed shard, new residual or None). Traced under the
    ``comm`` monitor span (phase attribution in trace/pyprof reports)."""
    from apex_tpu.monitor.trace import span

    with span("comm"):
        if compression is not None and compression.enabled:
            return compressed_psum_scatter(
                g.reshape(-1).astype(jnp.float32), axis_name, compression,
                residual=residual, seed=seed,
                shard_multiple=compression.block_size)
        return scatter_leaf(g.astype(jnp.float32), axis_name), residual


def _reduce_grads(grads, comm_state, axis_name, compression, seed,
                  scale=None):
    """All leaves' grad reduce — flattened, so tuple-shaped CONTAINER nodes
    in the grads pytree are never mistaken for (shard, residual) pairs.
    Returns (shard pytree, new comm_state pytree or None).

    ``scale``: AMP loss scale. The residual is carried in UNSCALED units —
    re-scaled on the way into the collective and unscaled on the way out —
    so a dynamic-scaler scale change between steps cannot mis-scale the
    injected correction."""
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    res = (jax.tree_util.tree_flatten(comm_state)[0]
           if comm_state is not None else [None] * len(leaves))
    if len(res) != len(leaves):
        raise ValueError(
            f"comm_state has {len(res)} leaves, grads have {len(leaves)}")
    shards, new_res = [], []
    for i, (g, r) in enumerate(zip(leaves, res)):
        leaf_seed = None if seed is None else fold_seed(seed, i)
        r_in = r if (r is None or scale is None) else r * scale
        s, r2 = _reduce_grad_leaf(g, axis_name, compression, r_in, leaf_seed)
        if r2 is not None and scale is not None:
            r2 = r2 / scale
        shards.append(s)
        new_res.append(r2)
    g_shards = jax.tree_util.tree_unflatten(treedef, shards)
    if comm_state is None:
        return g_shards, None
    return g_shards, jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(comm_state), new_res)


def _record_zero_metrics(metrics, gnorm, master, old_master, grads,
                         world: int, compression, e5m2_allgather: bool,
                         axis_name: str):
    """Shared Adam/LAMB metrics tail: shard norms + modeled comm bytes.
    The param and update norms ride ONE stacked psum — scalar allreduces
    are latency-bound on multi-host meshes, so the telemetry adds a single
    extra collective, not two."""
    delta = jax.tree_util.tree_map(lambda a, b: a - b, master, old_master)
    both = jnp.sqrt(lax.psum(
        jnp.stack([_local_sq(master), _local_sq(delta)]), axis_name))
    return metrics.record(
        grad_norm=gnorm,
        param_norm=both[0],
        update_norm=both[1],
        comm_wire_bytes=_zero_wire_bytes(
            grads, world, compression, e5m2_allgather=e5m2_allgather))


def _zero_wire_bytes(grads, world: int,
                     compression: Optional[CompressionConfig],
                     e5m2_allgather: bool = False) -> float:
    """Modeled bytes-on-wire of one ZeRO step (grad reduce-scatter + param
    all-gather legs, ring model — same pricing ``comm.accounting`` reads
    off compiled HLO). Static shapes only; free to record."""
    from apex_tpu.comm.collectives import (
        all_gather_wire_bytes,
        psum_scatter_wire_bytes,
    )
    from apex_tpu.contrib.optimizers._sharding import shard_size

    mult = _shard_multiple(compression)
    gather_item = 1 if e5m2_allgather else 4
    total = 0.0
    for g in jax.tree_util.tree_leaves(grads):
        total += psum_scatter_wire_bytes(g.size, 4, world, compression, mult)
        k = shard_size(g.size, world, mult)
        total += all_gather_wire_bytes(k * world, gather_item, world)
    return total


class DistAdamState(NamedTuple):
    count: jnp.ndarray
    master: Pytree  # fp32 param shards, (k,) per leaf
    mu: Pytree  # fp32 moment shards
    nu: Pytree


@dataclasses.dataclass(frozen=True)
class DistributedFusedAdam:
    """Ref constructor surface (distributed_fused_adam.py:16-46), minus the
    CUDA plumbing knobs (stream counts, bucket sizes — XLA's job now).

    Usage (inside ``shard_map`` over the full mesh)::

        opt = DistributedFusedAdam(lr=1e-3, max_grad_norm=1.0)
        state = opt.init(params)              # sharded fp32 master+moments
        params, state = opt.step(grads, state, params)
    """

    lr: float = 1e-3
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    adam_w_mode: bool = True
    max_grad_norm: Optional[float] = None  # ref clip_grad_norm
    axis_name: str = DP_AXIS
    # ref ``e5m2_allgather`` dwu option: ship the updated param shards as
    # float8_e5m2 (half the all-gather bytes); masters stay fp32-exact,
    # only the replicated model copy carries the e5m2 rounding
    e5m2_allgather: bool = False
    # int8-quantized gradient reduce-scatter (comm/collectives.py): the
    # grad leg of the ZeRO dataflow rides int8 codes + fp32 block scales;
    # policy 'int8_ef' carries an error-feedback residual — thread
    # ``comm_state`` through :meth:`step` (see :meth:`init_comm_state`)
    compression: Optional[CompressionConfig] = None
    # the update tail (moments + bias correction + decay + direction) as
    # ONE Pallas kernel per shard leaf (ops/fused_update.py) instead of
    # ~10 elementwise XLA ops: "auto" on compiled Mosaic backends, "on"
    # forces (interpret off-TPU — the parity tests' mode), "off" keeps
    # the per-op chain
    fused_update: str = "auto"

    def __post_init__(self):
        # validate eagerly (like FusedAdam's fused_tail): a bad mode must
        # fail at construction, not mid-trace inside the first step()
        from apex_tpu.ops.fused_update import resolve_fused

        resolve_fused(self.fused_update)

    def init(self, params: Pytree) -> DistAdamState:
        """Shard fp32 masters + zero moments (call inside the mesh program;
        ``params`` replicated across ``axis_name``)."""
        mult = _shard_multiple(self.compression)
        master = jax.tree.map(
            lambda p: slice_leaf(p.astype(jnp.float32), self.axis_name,
                                 multiple=mult),
            params)
        zeros = jax.tree.map(lambda m: jnp.zeros_like(m), master)
        return DistAdamState(
            count=jnp.zeros((), jnp.int32), master=master, mu=zeros,
            nu=jax.tree.map(jnp.zeros_like, master))

    def init_comm_state(self, params: Pytree) -> Optional[Pytree]:
        """Error-feedback residuals (policy ``int8_ef``), else ``None``.
        Unsharded fp32 — EF compensates the rank-local quantization error,
        which lives on the full gradient."""
        if self.compression is not None and self.compression.error_feedback:
            return init_error_feedback(params)
        return None

    # -- checkpointing (the resilience manifest path) ----------------------
    def state_dict(self, state: DistAdamState,
                   params: Optional[Pytree] = None,
                   dp: Optional[int] = None) -> dict:
        """Sharded state (count + master/moment shards) → flat
        fingerprinted dict. The fingerprint pins the treedef AND every
        shard's shape/dtype, so a checkpoint written at a different dp
        degree or shard alignment (``compression.block_size``) is refused
        at restore instead of silently mis-binding shards — the failure
        mode ZeRO adds over replicated optimizers.

        Pass ``params`` + ``dp`` to stamp the :meth:`elastic_spec`
        manifest into the dict, making it topology-elastic: a restore at
        a different dp degree becomes legal with ``allow_reshard=True``."""
        from apex_tpu.resilience.checkpoint import state_dict

        elastic = None
        if params is not None:
            if dp is None:
                raise ValueError("state_dict(params=...) needs dp= (the dp "
                                 "degree the shards were built at)")
            elastic = self.elastic_spec(params, dp)
        return state_dict(state, elastic=elastic)

    def load_state_dict(self, template: DistAdamState, d: dict,
                        allow_reshard: bool = False) -> DistAdamState:
        """Restore onto a live ``init(params)`` structure; refuses a
        fingerprint mismatch unless ``allow_reshard=True`` AND the dict
        carries an elastic manifest (written by ``state_dict(params=...,
        dp=...)``) — then each shard leaf is re-sliced onto the live dp
        degree's block-aligned layout (pure arithmetic, bitwise exact;
        see :mod:`apex_tpu.resilience.reshard`)."""
        from apex_tpu.resilience.checkpoint import load_state_dict

        return load_state_dict(template, d, allow_reshard=allow_reshard)

    def elastic_spec(self, params: Pytree, dp: int) -> DistAdamState:
        """Per-leaf :class:`~apex_tpu.resilience.reshard.LeafSpec` tree
        matching :meth:`init`'s state structure: masters/moments are
        ``dp_flat`` slices of each logical param (size, dp, the
        compression block multiple), ``count`` is replicated. Pass as
        ``elastic=`` to ``CheckpointManager.save`` / :meth:`state_dict`."""
        import math

        from apex_tpu.resilience.reshard import dp_flat_spec, replicated_spec

        mult = _shard_multiple(self.compression)
        flat = jax.tree.map(
            lambda p: dp_flat_spec(math.prod(jnp.shape(p)), int(dp), mult),
            params)
        return DistAdamState(
            count=replicated_spec(), master=flat, mu=flat, nu=flat)

    def elastic_comm_spec(self, params: Pytree, dp: int) -> Optional[Pytree]:
        """Elastic spec for :meth:`init_comm_state`'s EF residuals,
        checkpointed in the STACKED convention (leaf shape ``(dp, *grad
        .shape)`` — each rank's residual compensates its OWN quantization
        error, so the per-rank copies genuinely differ and are saved
        side-by-side). Across a topology change the leaves are
        ``dp_stacked``: grown ranks start at zero residual, shrunk ranks
        fold their predecessors' rows so the rank-SUM — the psum'd EF
        correction the next step applies — is conserved exactly.
        ``None`` when EF is off."""
        if self.compression is None or not self.compression.error_feedback:
            return None
        from apex_tpu.resilience.reshard import dp_stacked_spec

        return jax.tree.map(lambda p: dp_stacked_spec(int(dp)), params)

    def _global_norm(self, shards) -> jnp.ndarray:
        return _global_norm_shards(shards, self.axis_name)

    def step(
        self,
        grads: Pytree,
        state: DistAdamState,
        params: Pytree,
        scale: Optional[jnp.ndarray] = None,
        comm_state: Optional[Pytree] = None,
        seed=None,
        metrics: Optional[Any] = None,
    ) -> Tuple[Pytree, ...]:
        """reduce-scatter → (unscale, clip) → Adam on shards → all-gather.

        ``grads``: per-device gradients (NOT yet dp-reduced — the
        reduce-scatter does the sum, ref "overlap_reductions" dataflow).
        ``scale``: optional AMP loss scale to divide out
        (ref step_supports_amp_scaling).
        ``comm_state``/``seed``: error-feedback residuals and the
        stochastic-rounding seed for the compressed reduce-scatter; when
        ``comm_state`` is passed the return is ``(params, state,
        comm_state)``.
        ``metrics``: an :class:`apex_tpu.monitor.Metrics` to record
        shard-computed telemetry into — ``grad_norm`` (global, pre-clip),
        ``param_norm``, ``update_norm`` (each a local shard sq-sum + one
        psum: the reference's two-stage ``multi_tensor_l2norm``), plus the
        modeled ``comm_wire_bytes`` of the scatter+gather legs. When
        passed, the updated Metrics is appended to the return tuple.
        """
        if (self.compression is not None and self.compression.error_feedback
                and comm_state is None):
            raise ValueError(
                "compression policy 'int8_ef' carries state: pass "
                "comm_state=opt.init_comm_state(params) and thread the "
                "returned state")
        b1, b2 = self.betas
        g_shards, new_comm = _reduce_grads(grads, comm_state, self.axis_name,
                                           self.compression, seed,
                                           scale=scale)
        world = lax.axis_size(self.axis_name)
        # reduce-scatter sums over dp; grads are averaged like DDP does
        g_shards = jax.tree.map(lambda g: g / world, g_shards)
        if scale is not None:
            g_shards = jax.tree.map(lambda g: g / scale, g_shards)
        gnorm = (self._global_norm(g_shards)
                 if self.max_grad_norm is not None or metrics is not None
                 else None)
        if self.max_grad_norm is not None:
            clip = jnp.minimum(1.0, self.max_grad_norm / (gnorm + 1e-6))
            g_shards = jax.tree.map(lambda g: g * clip, g_shards)

        count = state.count + 1
        t = count.astype(jnp.float32)
        c1 = 1.0 - jnp.power(b1, t)
        c2 = 1.0 - jnp.power(b2, t)
        from apex_tpu.ops.fused_update import resolve_fused

        use_fused = resolve_fused(self.fused_update)

        def upd(g, m, v, p32):
            # the shared ZeRO-1/FSDP Adam tail (_sharding.adam_shard_update)
            return adam_shard_update(
                g, m, v, p32, c1, c2, lr=self.lr, betas=self.betas,
                eps=self.eps, weight_decay=self.weight_decay,
                adam_w_mode=self.adam_w_mode, use_fused=use_fused)

        # flattened, not is_leaf=tuple: a tuple CONTAINER node in the grads
        # pytree must not be mistaken for upd's (p, m, v) result triple
        g_l, treedef = jax.tree_util.tree_flatten(g_shards)
        out = [upd(g, m, v, p) for g, m, v, p in zip(
            g_l, jax.tree_util.tree_leaves(state.mu),
            jax.tree_util.tree_leaves(state.nu),
            jax.tree_util.tree_leaves(state.master))]
        master = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
        mu = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
        nu = jax.tree_util.tree_unflatten(treedef, [o[2] for o in out])

        from apex_tpu.monitor.trace import span

        transport = jnp.float8_e5m2 if self.e5m2_allgather else None
        with span("comm"):
            new_params = jax.tree.map(
                lambda m, p: gather_leaf(m, p.shape, p.dtype, self.axis_name,
                                         transport_dtype=transport),
                master, params)
        new_state = DistAdamState(count, master, mu, nu)
        out: Tuple[Pytree, ...] = (new_params, new_state)
        if comm_state is not None:
            out += (new_comm,)
        if metrics is not None:
            out += (_record_zero_metrics(
                metrics, gnorm, master, state.master, grads, world,
                self.compression, self.e5m2_allgather, self.axis_name),)
        return out

"""ZeRO-style Adam with dp-sharded optimizer state.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py:9`` —
``DistributedFusedAdam``: shards Adam moments and fp32 master weights across
the data-parallel group and pipelines bucketed reduce-scatter (grads) /
all-gather (params) overlapped with backward, with optional global-norm
clipping and AMP grad scaling. ~1000 LoC of stream bookkeeping + CUDA
multi-tensor kernels.

TPU re-design: the same dataflow expressed per-leaf with three collectives
(see ``_sharding.py``), run inside the mesh program. State (fp32 master
shard + moment shards) is 1/dp per device — ZeRO stage 1+2 memory. The
whole step is one pure function; XLA overlaps the collectives with compute.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.contrib.optimizers._sharding import (
    gather_leaf,
    scatter_leaf,
    slice_leaf,
)
from apex_tpu.parallel.mesh import DP_AXIS

Pytree = Any


class DistAdamState(NamedTuple):
    count: jnp.ndarray
    master: Pytree  # fp32 param shards, (k,) per leaf
    mu: Pytree  # fp32 moment shards
    nu: Pytree


@dataclasses.dataclass(frozen=True)
class DistributedFusedAdam:
    """Ref constructor surface (distributed_fused_adam.py:16-46), minus the
    CUDA plumbing knobs (stream counts, bucket sizes — XLA's job now).

    Usage (inside ``shard_map`` over the full mesh)::

        opt = DistributedFusedAdam(lr=1e-3, max_grad_norm=1.0)
        state = opt.init(params)              # sharded fp32 master+moments
        params, state = opt.step(grads, state, params)
    """

    lr: float = 1e-3
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    weight_decay: float = 0.0
    adam_w_mode: bool = True
    max_grad_norm: Optional[float] = None  # ref clip_grad_norm
    axis_name: str = DP_AXIS
    # ref ``e5m2_allgather`` dwu option: ship the updated param shards as
    # float8_e5m2 (half the all-gather bytes); masters stay fp32-exact,
    # only the replicated model copy carries the e5m2 rounding
    e5m2_allgather: bool = False

    def init(self, params: Pytree) -> DistAdamState:
        """Shard fp32 masters + zero moments (call inside the mesh program;
        ``params`` replicated across ``axis_name``)."""
        master = jax.tree.map(
            lambda p: slice_leaf(p.astype(jnp.float32), self.axis_name),
            params)
        zeros = jax.tree.map(lambda m: jnp.zeros_like(m), master)
        return DistAdamState(
            count=jnp.zeros((), jnp.int32), master=master, mu=zeros,
            nu=jax.tree.map(jnp.zeros_like, master))

    def _global_norm(self, shards) -> jnp.ndarray:
        sq = sum(jnp.sum(jnp.square(g)) for g in jax.tree.leaves(shards))
        return jnp.sqrt(lax.psum(sq, self.axis_name))

    def step(
        self,
        grads: Pytree,
        state: DistAdamState,
        params: Pytree,
        scale: Optional[jnp.ndarray] = None,
    ) -> Tuple[Pytree, DistAdamState]:
        """reduce-scatter → (unscale, clip) → Adam on shards → all-gather.

        ``grads``: per-device gradients (NOT yet dp-reduced — the
        reduce-scatter does the sum, ref "overlap_reductions" dataflow).
        ``scale``: optional AMP loss scale to divide out
        (ref step_supports_amp_scaling).
        """
        b1, b2 = self.betas
        g_shards = jax.tree.map(
            lambda g: scatter_leaf(g.astype(jnp.float32), self.axis_name),
            grads)
        world = lax.axis_size(self.axis_name)
        # reduce-scatter sums over dp; grads are averaged like DDP does
        g_shards = jax.tree.map(lambda g: g / world, g_shards)
        if scale is not None:
            g_shards = jax.tree.map(lambda g: g / scale, g_shards)
        if self.max_grad_norm is not None:
            gnorm = self._global_norm(g_shards)
            clip = jnp.minimum(1.0, self.max_grad_norm / (gnorm + 1e-6))
            g_shards = jax.tree.map(lambda g: g * clip, g_shards)

        count = state.count + 1
        t = count.astype(jnp.float32)
        c1 = 1.0 - jnp.power(b1, t)
        c2 = 1.0 - jnp.power(b2, t)

        def upd(g, m, v, p32):
            if not self.adam_w_mode and self.weight_decay:
                g = g + self.weight_decay * p32
            m_new = b1 * m + (1.0 - b1) * g
            v_new = b2 * v + (1.0 - b2) * g * g
            u = (m_new / c1) / (jnp.sqrt(v_new / c2) + self.eps)
            if self.adam_w_mode and self.weight_decay:
                u = u + self.weight_decay * p32
            return p32 - self.lr * u, m_new, v_new

        out = jax.tree.map(upd, g_shards, state.mu, state.nu, state.master)
        is3 = lambda x: isinstance(x, tuple)
        master = jax.tree.map(lambda o: o[0], out, is_leaf=is3)
        mu = jax.tree.map(lambda o: o[1], out, is_leaf=is3)
        nu = jax.tree.map(lambda o: o[2], out, is_leaf=is3)

        transport = jnp.float8_e5m2 if self.e5m2_allgather else None
        new_params = jax.tree.map(
            lambda m, p: gather_leaf(m, p.shape, p.dtype, self.axis_name,
                                     transport_dtype=transport),
            master, params)
        return new_params, DistAdamState(count, master, mu, nu)

"""ZeRO-style LAMB with dp-sharded state and global-norm clipping.

Reference: ``apex/contrib/optimizers/distributed_fused_lamb.py:10`` —
``DistributedFusedLAMB`` (the MLPerf BERT optimizer): same reduce-scatter /
all-gather dataflow as DistributedFusedAdam plus the LAMB trust ratio, which
needs **per-parameter** weight and update norms; the reference computes them
with ``fused_norm`` kernels over the shards and a global reduction.

TPU re-design: per-leaf shard math as in DistributedFusedAdam; the
per-parameter norms are a local squared-sum over the shard followed by a
``psum`` over dp — exactly the reference's sharded-norm + all-reduce, in two
lines. Update math mirrors ``apex_tpu.optimizers.FusedLAMB`` (which matches
``multi_tensor_lamb.cu``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.comm.collectives import CompressionConfig
from apex_tpu.comm.error_feedback import init_error_feedback
from apex_tpu.contrib.optimizers._sharding import (
    gather_leaf,
    global_norm_shards as _global_norm_shards,
    shard_multiple as _shard_multiple,
    slice_leaf,
)
from apex_tpu.contrib.optimizers.distributed_fused_adam import (
    _reduce_grads,
)
from apex_tpu.parallel.mesh import DP_AXIS

Pytree = Any


class DistLambState(NamedTuple):
    count: jnp.ndarray
    master: Pytree
    mu: Pytree
    nu: Pytree


@dataclasses.dataclass(frozen=True)
class DistributedFusedLAMB:
    """Ref constructor surface (distributed_fused_lamb.py:37-80 essentials)."""

    lr: float = 1e-3
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-6
    weight_decay: float = 0.01
    bias_correction: bool = True
    grad_averaging: bool = True
    max_grad_norm: Optional[float] = 1.0
    use_nvlamb: bool = False  # apply trust ratio even with wd == 0
    axis_name: str = DP_AXIS
    # ref e5m2 compressed all-gather (see DistributedFusedAdam)
    e5m2_allgather: bool = False
    # int8-quantized gradient reduce-scatter (see DistributedFusedAdam)
    compression: Optional[CompressionConfig] = None
    # fused update tail (see DistributedFusedAdam.fused_update): the LAMB
    # kernel additionally accumulates the trust ratio's local Σp²/Σu²
    # in-kernel — only the psum + trust scale + lr axpy stay outside
    fused_update: str = "auto"

    def __post_init__(self):
        # validate eagerly (see DistributedFusedAdam)
        from apex_tpu.ops.fused_update import resolve_fused

        resolve_fused(self.fused_update)

    def init(self, params: Pytree) -> DistLambState:
        mult = _shard_multiple(self.compression)
        master = jax.tree.map(
            lambda p: slice_leaf(p.astype(jnp.float32), self.axis_name,
                                 multiple=mult),
            params)
        return DistLambState(
            count=jnp.zeros((), jnp.int32), master=master,
            mu=jax.tree.map(jnp.zeros_like, master),
            nu=jax.tree.map(jnp.zeros_like, master))

    def init_comm_state(self, params: Pytree) -> Optional[Pytree]:
        """Error-feedback residuals (policy ``int8_ef``), else ``None``."""
        if self.compression is not None and self.compression.error_feedback:
            return init_error_feedback(params)
        return None

    # -- checkpointing (the resilience manifest path) ----------------------
    def state_dict(self, state: DistLambState) -> dict:
        """See :meth:`DistributedFusedAdam.state_dict` — same fingerprinted
        flat format, same shard-mis-binding protection."""
        from apex_tpu.resilience.checkpoint import state_dict

        return state_dict(state)

    def load_state_dict(self, template: DistLambState,
                        d: dict) -> DistLambState:
        from apex_tpu.resilience.checkpoint import load_state_dict

        return load_state_dict(template, d)

    def step(
        self,
        grads: Pytree,
        state: DistLambState,
        params: Pytree,
        scale: Optional[jnp.ndarray] = None,
        comm_state: Optional[Pytree] = None,
        seed=None,
        metrics: Optional[Any] = None,
    ) -> Tuple[Pytree, ...]:
        """See :meth:`DistributedFusedAdam.step` — same calling convention,
        including the optional ``metrics`` (shard norms + modeled comm
        bytes appended to the return tuple)."""
        if (self.compression is not None and self.compression.error_feedback
                and comm_state is None):
            raise ValueError(
                "compression policy 'int8_ef' carries state: pass "
                "comm_state=opt.init_comm_state(params) and thread the "
                "returned state")
        b1, b2 = self.betas
        g_shards, new_comm = _reduce_grads(grads, comm_state, self.axis_name,
                                           self.compression, seed,
                                           scale=scale)
        world = lax.axis_size(self.axis_name)
        if self.grad_averaging:
            g_shards = jax.tree.map(lambda g: g / world, g_shards)
        if scale is not None:
            g_shards = jax.tree.map(lambda g: g / scale, g_shards)
        gnorm = None
        if self.max_grad_norm is not None or metrics is not None:
            # global grad norm over ALL shards (ref fused clip path)
            gnorm = _global_norm_shards(g_shards, self.axis_name)
        if self.max_grad_norm is not None:
            clip = jnp.minimum(1.0, self.max_grad_norm / (gnorm + 1e-6))
            g_shards = jax.tree.map(lambda g: g * clip, g_shards)

        count = state.count + 1
        t = count.astype(jnp.float32)
        c1 = 1.0 - jnp.power(b1, t) if self.bias_correction else 1.0
        c2 = 1.0 - jnp.power(b2, t) if self.bias_correction else 1.0

        from apex_tpu.ops.fused_update import fused_lamb_tail, resolve_fused

        use_fused = resolve_fused(self.fused_update)

        def upd(g, m, v, p32):
            if use_fused:
                # moments + direction + the trust ratio's LOCAL sq-sums in
                # ONE kernel; the cross-shard psum stays a collective
                u, m_new, v_new, wsq, usq = fused_lamb_tail(
                    g, m, v, p32, c1, c2, betas=self.betas, eps=self.eps,
                    weight_decay=self.weight_decay, use_pallas=True)
                w_norm = jnp.sqrt(lax.psum(wsq, self.axis_name))
                u_norm = jnp.sqrt(lax.psum(usq, self.axis_name))
            else:
                m_new = b1 * m + (1.0 - b1) * g
                v_new = b2 * v + (1.0 - b2) * g * g
                u = (m_new / c1) / (jnp.sqrt(v_new / c2) + self.eps)
                if self.weight_decay:
                    u = u + self.weight_decay * p32
                # per-PARAMETER norms: local shard sq-sum + psum (ref
                # two-stage multi_tensor_l2norm + allreduce)
                w_norm = jnp.sqrt(
                    lax.psum(jnp.sum(p32 * p32), self.axis_name))
                u_norm = jnp.sqrt(lax.psum(jnp.sum(u * u), self.axis_name))
            apply_trust = (w_norm > 0) & (u_norm > 0)
            if not self.use_nvlamb and not self.weight_decay:
                trust = 1.0
            else:
                trust = jnp.where(apply_trust, w_norm / u_norm, 1.0)
            return p32 - self.lr * trust * u, m_new, v_new

        # flattened, not is_leaf=tuple (see DistributedFusedAdam.step)
        g_l, treedef = jax.tree_util.tree_flatten(g_shards)
        out = [upd(g, m, v, p) for g, m, v, p in zip(
            g_l, jax.tree_util.tree_leaves(state.mu),
            jax.tree_util.tree_leaves(state.nu),
            jax.tree_util.tree_leaves(state.master))]
        master = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
        mu = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
        nu = jax.tree_util.tree_unflatten(treedef, [o[2] for o in out])
        from apex_tpu.monitor.trace import span

        with span("comm"):
            new_params = jax.tree.map(
                lambda m, p: gather_leaf(
                    m, p.shape, p.dtype, self.axis_name,
                    transport_dtype=(jnp.float8_e5m2 if self.e5m2_allgather
                                     else None)),
                master, params)
        new_state = DistLambState(count, master, mu, nu)
        out: Tuple[Pytree, ...] = (new_params, new_state)
        if comm_state is not None:
            out += (new_comm,)
        if metrics is not None:
            from apex_tpu.contrib.optimizers.distributed_fused_adam import (
                _record_zero_metrics,
            )

            out += (_record_zero_metrics(
                metrics, gnorm, master, state.master, grads, world,
                self.compression, self.e5m2_allgather, self.axis_name),)
        return out

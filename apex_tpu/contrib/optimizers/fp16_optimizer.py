"""Contrib FP16_Optimizer (ref ``apex/contrib/optimizers/fp16_optimizer.py:4``).

The contrib variant differs from ``apex.fp16_utils.FP16_Optimizer`` only in
taking explicit grads/output-params for the legacy contrib fused kernels;
under the functional API both collapse to the same wrapper, re-exported here
for import parity."""

from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer  # noqa: F401

__all__ = ["FP16_Optimizer"]

"""Per-leaf shard/unshard plumbing for dp-sharded optimizer state.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py:9`` flattens
all grads into pre-sized blocks/chunks and drives a bucketed
reduce-scatter → local update → all-gather pipeline by hand (~1000 LoC +
CUDA). On TPU the same dataflow is three collectives inside ``shard_map``:

* ``psum_scatter`` the flattened grad leaf over ``dp`` — each rank owns
  1/dp of every parameter (and sums over data-parallel replicas in the same
  collective, like the reference's reduce-scatter);
* run the (fused, fp32) optimizer math on the local shard only — optimizer
  state lives sharded, cutting its memory by dp;
* ``all_gather`` the updated shard back to the full parameter.

XLA's latency-hiding scheduler overlaps these with neighbouring compute —
the part the reference implements with manual stream juggling.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.mesh import axis_size as _axis_size

Pytree = Any


def shard_multiple(compression) -> int:
    """Shard-size alignment for a (possibly ``None``) ``CompressionConfig``:
    with a quantized wire the shards are block-aligned so the codec's fp32
    scale blocks never straddle ranks. Shared by the ZeRO-1 optimizers and
    ``apex_tpu.fsdp`` (which aligns to the lcm of its grad and weight-gather
    codecs via :func:`shard_multiple_lcm`)."""
    if compression is not None and compression.enabled:
        return compression.block_size
    return 1


def shard_multiple_lcm(*compressions) -> int:
    """lcm of the block alignments of several codecs (FSDP's grad
    reduce-scatter and weight-gather wires may use different block sizes;
    one shard layout must satisfy both)."""
    import math

    m = 1
    for c in compressions:
        m = math.lcm(m, shard_multiple(c))
    return m


def local_sq(tree: Pytree) -> jnp.ndarray:
    """Σ x² over every leaf (fp32 scalar) — the local half of a sharded
    global norm."""
    return sum((jnp.sum(jnp.square(x))
                for x in jax.tree_util.tree_leaves(tree)),
               jnp.float32(0.0))


def global_norm_shards(tree: Pytree, axis_name: str) -> jnp.ndarray:
    """Global L2 norm of dp-sharded leaves: local shard sq-sum + one psum
    (the reference's two-stage ``multi_tensor_l2norm`` + allreduce). Shared
    by the ZeRO-1 optimizers' and FSDP's clipping and metrics paths."""
    return jnp.sqrt(lax.psum(local_sq(tree), axis_name))


def adam_shard_update(g, m, v, p32, c1, c2, *, lr, betas, eps,
                      weight_decay=0.0, adam_w_mode=True,
                      use_fused=False):
    """The per-(shard-)leaf Adam tail shared by ``DistributedFusedAdam``
    (ZeRO-1) and ``apex_tpu.fsdp.FSDPAdam`` (ZeRO-3) — identical math, so
    the two stages produce bit-matched updates given the same shard grads.
    ``use_fused`` routes through the ONE-kernel Pallas tail
    (``ops/fused_update.py``); only the lr axpy stays outside it.
    Returns ``(p32', m', v')``."""
    b1, b2 = betas
    if use_fused:
        from apex_tpu.ops.fused_update import fused_adam_tail

        u, m_new, v_new = fused_adam_tail(
            g, m, v, p32, c1, c2, betas=betas, eps=eps,
            weight_decay=weight_decay, adam_w_mode=adam_w_mode,
            use_pallas=True)
        return p32 - lr * u, m_new, v_new
    if not adam_w_mode and weight_decay:
        g = g + weight_decay * p32
    m_new = b1 * m + (1.0 - b1) * g
    v_new = b2 * v + (1.0 - b2) * g * g
    u = (m_new / c1) / (jnp.sqrt(v_new / c2) + eps)
    if adam_w_mode and weight_decay:
        u = u + weight_decay * p32
    return p32 - lr * u, m_new, v_new


def shard_size(n: int, world: int, multiple: int = 1) -> int:
    """ceil(n/world), rounded up to ``multiple``. The compressed-collective
    path (``comm/collectives.py``) passes the quantization block size so no
    scale block ever straddles a shard boundary; state built by
    :func:`slice_leaf` and grads from either scatter path then agree on the
    shard shape."""
    k = (n + world - 1) // world
    return -(-k // multiple) * multiple


def scatter_leaf(x, axis_name: str, multiple: int = 1):
    """flatten + pad + reduce-scatter: (shape) -> (shard_size(n, world),),
    summed over the axis (the grad reduce-scatter)."""
    world = _axis_size(axis_name)
    flat = x.reshape(-1)
    k = shard_size(flat.size, world, multiple)
    pad = k * world - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return lax.psum_scatter(flat, axis_name, scatter_dimension=0, tiled=True)


def slice_leaf(x, axis_name: str, multiple: int = 1):
    """This rank's shard of a replicated leaf (no reduction): used to build
    the initial sharded master/moment state."""
    world = _axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    flat = x.reshape(-1)
    k = shard_size(flat.size, world, multiple)
    pad = k * world - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return lax.dynamic_slice_in_dim(flat, rank * k, k, 0)


def gather_leaf(shard, shape, dtype, axis_name: str, transport_dtype=None):
    """all-gather + unpad + reshape: (k,) -> shape (the param all-gather).

    ``transport_dtype``: optional narrow dtype for the wire — e.g.
    ``jnp.float8_e5m2`` halves the all-gather bytes (the reference's
    ``e5m2_allgather`` option). The shard is first rounded to the model
    ``dtype`` so the only extra loss is the e5m2 truncation the reference
    also pays; the sharded fp32 master stays exact.
    """
    if transport_dtype is not None:
        # saturate instead of overflow: float8_e5m2 maxes at 57344 and a
        # plain cast of anything larger becomes inf on every rank
        lim = float(jnp.finfo(transport_dtype).max)
        shard = jnp.clip(shard.astype(jnp.float32), -lim, lim)
        shard = shard.astype(dtype).astype(transport_dtype)
    full = lax.all_gather(shard, axis_name, axis=0, tiled=True)
    n = 1
    for d in shape:
        n *= d
    return full[:n].reshape(shape).astype(dtype)

"""Per-leaf shard/unshard plumbing for dp-sharded optimizer state.

Reference: ``apex/contrib/optimizers/distributed_fused_adam.py:9`` flattens
all grads into pre-sized blocks/chunks and drives a bucketed
reduce-scatter → local update → all-gather pipeline by hand (~1000 LoC +
CUDA). On TPU the same dataflow is three collectives inside ``shard_map``:

* ``psum_scatter`` the flattened grad leaf over ``dp`` — each rank owns
  1/dp of every parameter (and sums over data-parallel replicas in the same
  collective, like the reference's reduce-scatter);
* run the (fused, fp32) optimizer math on the local shard only — optimizer
  state lives sharded, cutting its memory by dp;
* ``all_gather`` the updated shard back to the full parameter.

XLA's latency-hiding scheduler overlaps these with neighbouring compute —
the part the reference implements with manual stream juggling.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

Pytree = Any


def shard_size(n: int, world: int, multiple: int = 1) -> int:
    """ceil(n/world), rounded up to ``multiple``. The compressed-collective
    path (``comm/collectives.py``) passes the quantization block size so no
    scale block ever straddles a shard boundary; state built by
    :func:`slice_leaf` and grads from either scatter path then agree on the
    shard shape."""
    k = (n + world - 1) // world
    return -(-k // multiple) * multiple


def scatter_leaf(x, axis_name: str, multiple: int = 1):
    """flatten + pad + reduce-scatter: (shape) -> (shard_size(n, world),),
    summed over the axis (the grad reduce-scatter)."""
    world = lax.axis_size(axis_name)
    flat = x.reshape(-1)
    k = shard_size(flat.size, world, multiple)
    pad = k * world - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return lax.psum_scatter(flat, axis_name, scatter_dimension=0, tiled=True)


def slice_leaf(x, axis_name: str, multiple: int = 1):
    """This rank's shard of a replicated leaf (no reduction): used to build
    the initial sharded master/moment state."""
    world = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    flat = x.reshape(-1)
    k = shard_size(flat.size, world, multiple)
    pad = k * world - flat.size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return lax.dynamic_slice_in_dim(flat, rank * k, k, 0)


def gather_leaf(shard, shape, dtype, axis_name: str, transport_dtype=None):
    """all-gather + unpad + reshape: (k,) -> shape (the param all-gather).

    ``transport_dtype``: optional narrow dtype for the wire — e.g.
    ``jnp.float8_e5m2`` halves the all-gather bytes (the reference's
    ``e5m2_allgather`` option). The shard is first rounded to the model
    ``dtype`` so the only extra loss is the e5m2 truncation the reference
    also pays; the sharded fp32 master stays exact.
    """
    if transport_dtype is not None:
        # saturate instead of overflow: float8_e5m2 maxes at 57344 and a
        # plain cast of anything larger becomes inf on every rank
        lim = float(jnp.finfo(transport_dtype).max)
        shard = jnp.clip(shard.astype(jnp.float32), -lim, lim)
        shard = shard.astype(dtype).astype(transport_dtype)
    full = lax.all_gather(shard, axis_name, axis=0, tiled=True)
    n = 1
    for d in shape:
        n *= d
    return full[:n].reshape(shape).astype(dtype)

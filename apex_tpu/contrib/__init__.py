"""Contrib layer — TPU equivalents of ``apex/contrib`` (SURVEY.md §2.1).

Subpackages mirror the reference's opt-in perf extensions. Where the
reference needs a dedicated CUDA ext, the TPU build usually reuses the core
Pallas/XLA kernels (``apex_tpu.ops``) under the contrib API names:

=====================  ======================================================
``contrib.multihead_attn``  fused self/enc-dec MHA over the flash kernel
``contrib.fmha``            packed-varlen attention via segment masking
``contrib.xentropy``        fused softmax cross-entropy (``ops.xentropy``)
``contrib.layer_norm``      FastLayerNorm (``ops.layer_norm``)
``contrib.optimizers``      ZeRO-style distributed Adam/LAMB
``contrib.sparsity``        ASP 2:4 structured sparsity
``contrib.transducer``      RNN-T joint + loss
``contrib.groupbn``         group BatchNorm (``parallel.sync_batchnorm``)
=====================  ======================================================
"""

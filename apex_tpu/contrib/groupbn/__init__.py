"""Group BatchNorm (ref ``apex/contrib/groupbn``).

Reference: ``BatchNorm2d_NHWC`` (``groupbn/batch_norm.py:101``) + the ``bnp``
ext (5.1k LoC): NHWC fused BN(+add)+ReLU whose statistics are exchanged
across a ``bn_group`` of GPUs through CUDA-IPC peer memory.

TPU re-design: NHWC is already the native layout, BN+ReLU(+add) fusion is
XLA's job, and "BN group" is an ``axis_index_groups`` partition of the dp
axis — the same SyncBatchNorm kernel handles it (SURVEY §2.3
"grouped/partial-replica collectives").
"""

from __future__ import annotations

import functools
from typing import Optional

from apex_tpu.parallel.mesh import DP_AXIS
from apex_tpu.parallel.sync_batchnorm import (
    SyncBatchNorm,
    create_syncbn_process_group,
)


def BatchNorm2d_NHWC(num_features: int, fuse_relu: bool = False,
                     bn_group: int = 1, world_size: Optional[int] = None,
                     axis_name: str = DP_AXIS, **kw):
    """Ref constructor (``batch_norm.py:101-130``): ``bn_group`` devices share
    statistics. Returns a :class:`SyncBatchNorm` configured with the group
    partition (``bn_group=1`` -> local BN, no collectives)."""
    if bn_group <= 1:
        return SyncBatchNorm(features=num_features, axis_name=None,
                             fuse_relu=fuse_relu, **kw)
    if world_size is None:
        import jax

        world_size = len(jax.devices())
    groups = create_syncbn_process_group(bn_group, world_size)
    return SyncBatchNorm(features=num_features, axis_name=axis_name,
                         axis_index_groups=groups, fuse_relu=fuse_relu, **kw)


__all__ = ["BatchNorm2d_NHWC", "create_syncbn_process_group"]

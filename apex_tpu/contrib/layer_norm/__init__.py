"""Contrib FastLayerNorm API (ref ``apex/contrib/layer_norm/layer_norm.py:40``
over the ``fast_layer_norm`` ext for hidden sizes up to 65k): the Pallas
layer-norm kernel in ``apex_tpu.ops.layer_norm`` covers all hidden sizes, so
this package just re-exports it under the contrib name."""

from apex_tpu.normalization import FusedLayerNorm as FastLayerNorm  # noqa: F401
from apex_tpu.ops.layer_norm import layer_norm as fast_layer_norm  # noqa: F401

__all__ = ["FastLayerNorm", "fast_layer_norm"]

"""Repo graph-lint — AST pass over ``apex_tpu/`` for repeat-offender bugs.

Tier B of :mod:`apex_tpu.analyze`: where the program analyzers inspect
jaxprs and compiled HLO, this pass inspects the SOURCE for anti-patterns
the codebase has repeatedly fixed by hand, so the next instance fails
tier-1 instead of shipping:

``tracer-branch``
    Python ``if``/``while`` on a ``jnp``/``lax``-valued expression inside
    a jit-decorated function — a data-dependent branch that either
    crashes at trace time or silently bakes one side into the program.
``jnp-array-on-tracer``
    ``jnp.array(x)`` on a bare name inside a jit-decorated function —
    forces a copy (and a fresh const) where ``jnp.asarray``/nothing was
    meant.
``bare-except``
    ``except Exception:`` / bare ``except:`` with no justification
    comment on the handler line or the line above — the pattern that has
    eaten real errors here before; an explanatory comment (or ``# pragma``)
    marks the deliberate ones.
``mutable-default-arg``
    ``def f(x, acc=[])`` — the classic shared-state default.
``missing-donate``
    A step-shaped jit (function name containing ``step``/``update``,
    decorated or wrapped with ``jax.jit``) without ``donate_argnums``/
    ``donate_argnames`` — the donation the Metrics/scaler/KV threading
    depends on, silently absent.

Violations are identified by ``(rule, file, normalized source line)`` —
NOT line numbers — so the checked-in baseline
(``tests/lint_baseline.json``) survives unrelated edits: existing
accepted sites pass, while a NEW violation (or a new copy of an old one)
fails. CLI::

    python -m apex_tpu.analyze.lint apex_tpu/ [--baseline FILE]
    python -m apex_tpu.analyze.lint apex_tpu/ --write-baseline  # re-bless

Exit 0 when every current violation is covered by the baseline, 1
otherwise (the tier-1 wiring in ``tests/test_analyze.py``).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import sys
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Violation", "RULES", "lint_file", "lint_paths",
           "load_baseline", "write_baseline", "new_violations", "main"]

RULES = ("tracer-branch", "jnp-array-on-tracer", "bare-except",
         "mutable-default-arg", "missing-donate")

_STEP_SHAPED = ("step", "update")
_JNP_NAMES = ("jnp", "lax")


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    file: str       # repo-relative, '/'-separated
    line: int       # 1-indexed (diagnostic only; NOT part of identity)
    code: str       # stripped source line (identity)
    message: str

    @property
    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: stable across unrelated line drift."""
        return (self.rule, self.file, self.code)

    def __str__(self):
        return (f"{self.file}:{self.line}: [{self.rule}] {self.message}"
                f"\n    {self.code}")


def _is_jax_jit(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` / ``pjit`` as a name or attribute."""
    if isinstance(node, ast.Attribute):
        return node.attr in ("jit", "pjit")
    if isinstance(node, ast.Name):
        return node.id in ("jit", "pjit")
    return False


def _jit_decorator(dec: ast.AST) -> Optional[ast.Call]:
    """The jit Call of a decorator, if this decorator jits the function:
    ``@jax.jit``, ``@jit``, ``@functools.partial(jax.jit, ...)``.
    Returns the Call carrying the jit kwargs (or None for a bare name)."""
    if _is_jax_jit(dec):
        return None if not isinstance(dec, ast.Call) else dec
    if isinstance(dec, ast.Call):
        if _is_jax_jit(dec.func):
            return dec
        fname = dec.func
        is_partial = (isinstance(fname, ast.Attribute)
                      and fname.attr == "partial") or \
                     (isinstance(fname, ast.Name) and fname.id == "partial")
        if is_partial and dec.args and _is_jax_jit(dec.args[0]):
            return dec
    return None


def _decorated_jit(fn: ast.AST) -> Optional[Tuple[bool, bool]]:
    """(is_jitted, has_donate) for a function def's decorator list."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    for dec in fn.decorator_list:
        if _is_jax_jit(dec) and not isinstance(dec, ast.Call):
            return True, False
        call = _jit_decorator(dec)
        if call is not None:
            donate = any(kw.arg in ("donate_argnums", "donate_argnames")
                         for kw in call.keywords)
            return True, donate
    return None


def _mentions_jnp(expr: ast.AST) -> bool:
    """Does the expression subtree call into jnp/lax (tracer-valued)?"""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in _JNP_NAMES:
            return True
    return False


def _step_shaped(name: str) -> bool:
    low = name.lower()
    return any(t in low for t in _STEP_SHAPED)


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, lines: Sequence[str]):
        self.path = path
        self.lines = lines
        self.out: List[Violation] = []
        self._jit_depth = 0  # inside a jit-decorated function (nested incl.)

    # -- helpers ----------------------------------------------------------
    def _code(self, node: ast.AST) -> str:
        i = getattr(node, "lineno", 1) - 1
        return self.lines[i].strip() if i < len(self.lines) else ""

    def _flag(self, rule: str, node: ast.AST, message: str) -> None:
        self.out.append(Violation(rule=rule, file=self.path,
                                  line=getattr(node, "lineno", 0),
                                  code=self._code(node), message=message))

    def _has_comment(self, lineno: int) -> bool:
        """A '#' comment on the line itself or the line above counts as
        justification (crude but deliberate: the ask is a WHY, not a
        format)."""
        for i in (lineno - 1, lineno - 2):
            if 0 <= i < len(self.lines) and "#" in self.lines[i]:
                return True
        return False

    # -- function defs: jit context, donate rule, mutable defaults --------
    def _visit_fn(self, node) -> None:
        jit = _decorated_jit(node)
        if jit is not None:
            is_jit, has_donate = jit
            if is_jit and not has_donate and _step_shaped(node.name):
                self._flag(
                    "missing-donate", node,
                    f"step-shaped jit '{node.name}' without "
                    f"donate_argnums — carried state will be copied, "
                    f"not aliased")
        for default in list(node.args.defaults) \
                + [d for d in node.args.kw_defaults if d is not None]:
            mutable = isinstance(default, (ast.List, ast.Dict, ast.Set)) \
                or (isinstance(default, ast.Call)
                    and isinstance(default.func, ast.Name)
                    and default.func.id in ("list", "dict", "set"))
            if mutable:
                self._flag("mutable-default-arg", node,
                           f"mutable default argument on '{node.name}'")
        if jit is not None:
            self._jit_depth += 1
            self.generic_visit(node)
            self._jit_depth -= 1
        else:
            self.generic_visit(node)

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- jit-context rules -------------------------------------------------
    def visit_If(self, node: ast.If) -> None:
        if self._jit_depth and _mentions_jnp(node.test):
            self._flag("tracer-branch", node,
                       "Python `if` on a jnp/lax-valued expression in a "
                       "jitted path — use jnp.where/lax.cond")
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        if self._jit_depth and _mentions_jnp(node.test):
            self._flag("tracer-branch", node,
                       "Python `while` on a jnp/lax-valued expression in "
                       "a jitted path — use lax.while_loop")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # jnp.array(<bare name>) inside a jitted function
        if (self._jit_depth
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "array"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "jnp"
                and node.args
                and isinstance(node.args[0], (ast.Name, ast.Attribute))):
            self._flag("jnp-array-on-tracer", node,
                       "jnp.array() on a traced value forces a copy — "
                       "jnp.asarray (or nothing) was meant")
        # jax.jit(step_fn, ...) call form of the donate rule
        if _is_jax_jit(node.func) and node.args:
            target = node.args[0]
            tname = None
            if isinstance(target, ast.Name):
                tname = target.id
            elif isinstance(target, ast.Attribute):
                tname = target.attr
            elif isinstance(target, ast.Call) \
                    and isinstance(target.func, ast.Name) \
                    and target.args \
                    and isinstance(target.args[0], ast.Name):
                tname = target.args[0].id  # jax.jit(wrap(step))
            if tname and _step_shaped(tname) and not any(
                    kw.arg in ("donate_argnums", "donate_argnames")
                    for kw in node.keywords):
                self._flag(
                    "missing-donate", node,
                    f"step-shaped jit of '{tname}' without donate_argnums")
        self.generic_visit(node)

    # -- bare except --------------------------------------------------------
    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        bare = node.type is None or (
            isinstance(node.type, ast.Name)
            and node.type.id in ("Exception", "BaseException"))
        if bare and not self._has_comment(node.lineno):
            self._flag("bare-except", node,
                       "bare `except Exception` without a justification "
                       "comment — name the exception or say why")
        self.generic_visit(node)


def lint_file(path: str, root: str = ".") -> List[Violation]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Violation(rule="syntax-error", file=rel,
                          line=e.lineno or 0, code=e.text or "",
                          message=str(e))]
    linter = _Linter(rel, source.splitlines())
    linter.visit(tree)
    return linter.out


def lint_paths(paths: Sequence[str], root: str = ".") -> List[Violation]:
    """Lint files and directory trees (``.py`` files, recursively)."""
    out: List[Violation] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.extend(lint_file(os.path.join(dirpath, fn),
                                             root))
        else:
            out.extend(lint_file(p, root))
    return out


# ---------------------------------------------------------------------------
# baseline allowlist


def load_baseline(path: str) -> Counter:
    """Baseline multiset of accepted violation keys. A missing file is an
    empty baseline (everything flags)."""
    if not os.path.exists(path):
        return Counter()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return Counter((e["rule"], e["file"], e["code"])
                   for e in data.get("violations", []))


def write_baseline(violations: Sequence[Violation], path: str) -> None:
    """Bless the current violation set. Entries keep the line number for
    human navigation; matching ignores it."""
    entries = [{"rule": v.rule, "file": v.file, "line": v.line,
                "code": v.code}
               for v in sorted(violations, key=lambda v: v.key)]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"schema": 1, "violations": entries}, f, indent=1)
        f.write("\n")


def new_violations(violations: Sequence[Violation],
                   baseline: Counter) -> List[Violation]:
    """Multiset subtraction: each baseline entry absolves ONE occurrence
    of its key — a second copy of an accepted anti-pattern still flags."""
    budget = Counter(baseline)
    fresh = []
    for v in violations:
        if budget[v.key] > 0:
            budget[v.key] -= 1
        else:
            fresh.append(v)
    return fresh


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="apex_tpu repo graph-lint (baseline-gated)")
    ap.add_argument("paths", nargs="+", help="files or directories to lint")
    ap.add_argument("--baseline", default="tests/lint_baseline.json",
                    help="accepted-violations allowlist (default: "
                         "tests/lint_baseline.json)")
    ap.add_argument("--root", default=".",
                    help="path prefix violations are keyed relative to")
    ap.add_argument("--write-baseline", action="store_true",
                    help="re-bless: write the current violation set as "
                         "the baseline and exit 0")
    args = ap.parse_args(argv)

    violations = lint_paths(args.paths, root=args.root)
    if args.write_baseline:
        write_baseline(violations, args.baseline)
        print(f"baseline written: {len(violations)} accepted violations "
              f"-> {args.baseline}", file=sys.stderr)
        return 0
    fresh = new_violations(violations, load_baseline(args.baseline))
    print(f"linted: {len(violations)} violations, "
          f"{len(violations) - len(fresh)} baselined, {len(fresh)} new",
          file=sys.stderr)
    for v in fresh:
        print(str(v), file=sys.stderr)
    return 1 if fresh else 0


if __name__ == "__main__":
    sys.exit(main())

"""Donation checker — is a donated buffer ACTUALLY aliased when compiled?

``donate_argnums`` is a request, not a guarantee: XLA aliases a donated
input to an output only when some output has the same shape/dtype/layout,
and silently falls back to a copy otherwise (jax warns once at lowering,
easily lost in a log). Everything this repo threads through jitted steps —
the ``monitor.Metrics`` pytree, the amp scaler state, the serve KV pools —
depends on that aliasing being real: a silently-copied KV pool doubles
serve HBM and nobody notices until OOM. This checker promotes the
property into an assertion on the COMPILED executable:

* :func:`donation_report` — parse the ``input_output_alias`` attribute
  off a compiled module (via :func:`apex_tpu.analyze.hlo.parse`) and the
  "donated buffers were not usable" lowering warnings into one record;
* :func:`check_donation` — compile ``fn`` with ``donate_argnums`` and
  return the report (also accepts an already-jitted/lowered/compiled
  program);
* :func:`assert_donated` — raise :class:`DonationError` naming every
  donated leaf that was NOT aliased.

Stock-jax-safe: pure text analysis of ``compiled.as_text()``.
"""

from __future__ import annotations

import dataclasses
import re
import warnings
from typing import Any, List, Optional, Sequence, Tuple

import jax

from apex_tpu.analyze.hlo import as_text, input_output_aliases

__all__ = ["DonationError", "DonationReport", "assert_donated",
           "check_donation", "donation_report"]

_UNUSABLE_RE = re.compile(r"ShapedArray\([^)]*\)")


class DonationError(AssertionError):
    """A buffer declared donated was silently copied by XLA."""


@dataclasses.dataclass
class DonationReport:
    """Aliasing evidence for one compiled program.

    ``aliased_params``: entry-parameter numbers the compiled module
    aliases to an output (the donation actually happened).
    ``expected_leaves``: how many donated array leaves the caller
    declared (``None`` when only a compiled artifact was given — then
    ``ok`` requires at least one alias).
    ``unusable``: the ShapedArray strings jax's lowering warned were
    donated-but-not-usable — the copied buffers, by name.
    """

    aliased_params: Tuple[int, ...]
    expected_leaves: Optional[int] = None
    unusable: Tuple[str, ...] = ()

    @property
    def n_aliased(self) -> int:
        return len(self.aliased_params)

    @property
    def ok(self) -> bool:
        if self.unusable:
            return False
        if self.expected_leaves is None:
            return self.n_aliased > 0
        return self.n_aliased >= self.expected_leaves

    def as_record(self) -> dict:
        """Flat json_record fields (joins the bench-record convention)."""
        return {"donated_aliased": self.n_aliased,
                "donated_expected": self.expected_leaves,
                "donated_copied": len(self.unusable),
                "donation_ok": self.ok}

    def __repr__(self):
        exp = ("?" if self.expected_leaves is None
               else str(self.expected_leaves))
        return (f"DonationReport({self.n_aliased}/{exp} aliased, "
                f"{len(self.unusable)} copied)")


def donation_report(compiled, expected_leaves: Optional[int] = None,
                    unusable: Sequence[str] = ()) -> DonationReport:
    """Read the aliasing truth off a compiled program (text or anything
    with ``.as_text()``)."""
    aliases = input_output_aliases(as_text(compiled))
    return DonationReport(
        aliased_params=tuple(sorted({p for _, p, _, _ in aliases})),
        expected_leaves=expected_leaves,
        unusable=tuple(unusable))


def _donated_leaf_count(args: Sequence[Any],
                        donate_argnums: Sequence[int]) -> int:
    n = 0
    for i in donate_argnums:
        n += len(jax.tree_util.tree_leaves(args[i]))
    return n


def check_donation(fn, *args, donate_argnums: Sequence[int] = (),
                   **kwargs) -> DonationReport:
    """Compile ``fn(*args, **kwargs)`` and report donation aliasing.

    ``fn`` may be a plain callable (jitted here with ``donate_argnums``),
    an already-jitted function (its own donation declaration is used and
    ``donate_argnums`` names the donated positions for leaf counting), or
    an already-compiled/lowered artifact (``donate_argnums`` ignored,
    ``ok`` = at least one alias). The "donated buffers were not usable"
    lowering warnings are captured so the report NAMES the copied
    buffers."""
    if not callable(fn):  # a Compiled/Lowered/text artifact
        return donation_report(fn)
    donate_argnums = tuple(donate_argnums)
    expected = _donated_leaf_count(args, donate_argnums) \
        if donate_argnums else None
    jitted = fn if hasattr(fn, "lower") else \
        jax.jit(fn, donate_argnums=donate_argnums)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        compiled = jitted.lower(*args, **kwargs).compile()
    unusable: List[str] = []
    for w in caught:
        msg = str(w.message)
        if "donated buffers were not usable" in msg.lower():
            unusable.extend(_UNUSABLE_RE.findall(msg) or [msg])
    return donation_report(compiled, expected_leaves=expected,
                           unusable=unusable)


def assert_donated(fn, *args, donate_argnums: Sequence[int] = (),
                   **kwargs) -> DonationReport:
    """:func:`check_donation`, raising :class:`DonationError` when any
    declared-donated leaf was copied instead of aliased."""
    rep = check_donation(fn, *args, donate_argnums=donate_argnums, **kwargs)
    if not rep.ok:
        copied = "; ".join(rep.unusable) or "no input_output_alias entries"
        raise DonationError(
            f"donation not honored by the compiled executable: "
            f"{rep.n_aliased} aliased of {rep.expected_leaves} donated "
            f"leaves — copied: {copied}")
    return rep

"""Recompile sentinel — jit cache sizes pinned to a declared budget.

The repo's compile-count gates grew up scattered: ``tests/test_serve.py``
pins ``engine.compile_counts()``, ``tests/test_monitor.py`` carried its
own ``_cache_size`` helper, ``tests/test_megakernel.py`` re-asserted the
serve gate. One implementation now lives here:

* :func:`jit_cache_size` — compilation count of one jitted callable
  (``None`` when this jax cannot report it);
* :func:`compile_counts` — the ``engine.compile_counts()`` shape for any
  named set of programs;
* :func:`recompile_guard` — the generalization the issue asked for: a
  context manager that snapshots cache sizes at entry and asserts growth
  stays within a declared budget at exit, so ANY test or bench can write
  ``with recompile_guard(step): run N steps`` and fail loudly on a
  retrace (shape-keyed recompiles, accidental weak-type flips, treedef
  churn — the failure modes the serve/monitor gates exist for).

Budget semantics: ``budget`` bounds cache-size GROWTH inside the block.
The default ``budget=None`` means "warmup allowed": each guarded program
may add at most one entry if its cache was empty at entry, and none
otherwise — the steady-state contract every step loop wants.
"""

from __future__ import annotations

import contextlib
from typing import Callable, Dict, Iterator, Mapping, Optional, Union

__all__ = ["RecompileError", "RecompileGuard", "compile_counts",
           "jit_cache_size", "recompile_guard"]


class RecompileError(AssertionError):
    """A guarded program compiled more than its declared budget."""


def jit_cache_size(jitted) -> Optional[int]:
    """Compilation count of a jitted callable (``None`` if this jax
    cannot say, or the callable is not jit-wrapped)."""
    if jitted is None:
        return 0
    fn = getattr(jitted, "_cache_size", None)
    return fn() if callable(fn) else None


def compile_counts(programs: Mapping[str, Callable]
                   ) -> Dict[str, Optional[int]]:
    """Named jit-cache sizes — the ``engine.compile_counts()`` record
    shape for any program set."""
    return {name: jit_cache_size(fn) for name, fn in programs.items()}


class RecompileGuard:
    """State of one :func:`recompile_guard` block (inspectable inside)."""

    def __init__(self, programs: Mapping[str, Callable],
                 budget: Optional[int]):
        self.programs = dict(programs)
        self.budget = budget
        self.entry = compile_counts(self.programs)
        self.supported = any(v is not None for v in self.entry.values())

    def counts(self) -> Dict[str, Optional[int]]:
        return compile_counts(self.programs)

    def growth(self) -> Dict[str, int]:
        """Cache-size growth since entry, per program (unknowns as 0)."""
        now = self.counts()
        return {k: (now[k] or 0) - (self.entry[k] or 0)
                for k in self.programs}

    def check(self) -> None:
        """Raise :class:`RecompileError` if any program exceeded its
        budget (called automatically at block exit)."""
        if not self.supported:
            return  # this jax cannot report cache sizes: nothing to pin
        over = {}
        for name, grew in self.growth().items():
            allowed = self.budget
            if allowed is None:  # warmup contract: 1 if cold, else 0
                allowed = 1 if not self.entry[name] else 0
            if grew > allowed:
                over[name] = (grew, allowed)
        if over:
            detail = ", ".join(
                f"{name}: +{grew} compiles (budget {allowed})"
                for name, (grew, allowed) in sorted(over.items()))
            raise RecompileError(
                f"jit cache grew past the declared budget — {detail}. "
                f"Something retraced: shape-keyed inputs, weak-type "
                f"flips, or a changing carry treedef.")


def _name_of(fn: Callable, i: int) -> str:
    inner = getattr(fn, "__wrapped__", fn)
    return getattr(inner, "__name__", None) or f"program{i}"


@contextlib.contextmanager
def recompile_guard(
    programs: Union[Callable, Mapping[str, Callable]],
    *more: Callable,
    budget: Optional[int] = None,
) -> Iterator[RecompileGuard]:
    """Assert the jit caches of ``programs`` stay within ``budget`` new
    compilations across the block::

        with recompile_guard(step) as g:       # warmup contract
            for batch in data:
                params = step(params, batch)
        # exits cleanly: exactly one compile; raises RecompileError on
        # ANY retrace. g.growth() is inspectable mid-block.

        with recompile_guard({"prefill": eng._chunk_prefill,
                              "decode": eng._decode}, budget=0):
            eng.run(requests)                  # steady state: no compiles

    ``programs``: one callable, several, or a ``{name: callable}`` dict.
    ``budget=None`` (default) is the warmup contract — one compile
    allowed per cold program, zero per warm one; an integer bounds growth
    for every program uniformly. On a jax that cannot report cache sizes
    the guard degrades to a no-op (the property is unpinnable there, not
    violated)."""
    if callable(programs):
        named: Dict[str, Callable] = {}
        for i, f in enumerate((programs,) + more):
            name = _name_of(f, i)
            if name in named:   # every step is named "step": keep both
                name = f"{name}#{i}"
            named[name] = f
        programs = named
    elif more:
        raise TypeError("pass either one mapping or bare callables")
    guard = RecompileGuard(programs, budget)
    yield guard
    guard.check()

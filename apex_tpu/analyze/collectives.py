"""Exposed-collective checker — assert comm latency is hidden, from HLO.

``comm.accounting.overlap_report`` PROVES overlap for the decomposed
ppermute rings (async start/done windows with dots inside); what it does
not do is gate: every ring/FSDP/cluster bench re-derived its own "is the
exposed share small enough" arithmetic. This module extends the report
into an assertion pass over ALL collective kinds (not just permutes — a
monolithic ``all-gather`` sitting on the critical path with no
data-independent compute is exactly the exposed traffic the decomposition
exists to remove):

* :func:`exposed_report` — per-kind hidden/exposed wire-byte split using
  the same evidence rules as ``overlap_report`` (async pairs: a ``dot``
  scheduled inside the start→done window; sync ops: a def-use-independent
  ``dot`` in the same computation) priced by the ``accounting`` ring
  model;
* :func:`assert_no_exposed` — raise :class:`ExposedCollectiveError` when
  exposed bytes exceed a declared budget (``assert_no_exposed(hlo,
  budget_bytes)`` — the gate every bench imports instead of re-deriving).

Built on :func:`apex_tpu.analyze.hlo.parse` and the pricing helpers of
:mod:`apex_tpu.comm.accounting` so the bytes here and the bytes in
``collective_report`` are the SAME model.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

from apex_tpu.analyze.hlo import OPERAND_RE, dependency_graph, parse, reach
from apex_tpu.comm.accounting import (
    COLLECTIVE_KINDS,
    OverlapReport,
    _async_result_bytes,
    _dot_bearing,
    _group_size,
    _is_dot_like,
    _paren_span,
    _result_bytes,
    _wire_cost,
    overlap_report,
)

__all__ = ["ExposedCollectiveError", "ExposedReport", "assert_no_exposed",
           "exposed_report", "overlap_assertion"]


class ExposedCollectiveError(AssertionError):
    """Collective traffic sits exposed on the critical path beyond the
    declared budget."""


@dataclasses.dataclass
class ExposedReport:
    """Hidden/exposed wire-byte split over every collective kind."""

    hidden_wire_bytes: float = 0.0
    exposed_wire_bytes: float = 0.0
    hidden_by_kind: Dict[str, float] = dataclasses.field(default_factory=dict)
    exposed_by_kind: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    collectives: int = 0
    hidden: int = 0

    @property
    def exposed(self) -> int:
        return self.collectives - self.hidden

    @property
    def hidden_fraction(self) -> float:
        total = self.hidden_wire_bytes + self.exposed_wire_bytes
        return self.hidden_wire_bytes / total if total else 1.0

    def as_record(self) -> dict:
        """Flat json_record fields (``exposed_bytes`` is the
        ``monitor.regress`` lower-is-better gate field)."""
        return {"exposed_bytes": round(self.exposed_wire_bytes),
                "hidden_bytes": round(self.hidden_wire_bytes),
                "hidden_fraction": round(self.hidden_fraction, 4),
                "collectives": self.collectives,
                "collectives_hidden": self.hidden}

    def __repr__(self):
        return (f"ExposedReport({self.hidden}/{self.collectives} hidden, "
                f"hidden_bytes={self.hidden_wire_bytes:.0f}, "
                f"exposed_bytes={self.exposed_wire_bytes:.0f})")


def exposed_report(hlo, kinds: Optional[Sequence[str]] = None,
                   default_group_size: Optional[int] = None
                   ) -> ExposedReport:
    """Split every collective's modeled wire bytes into hidden vs exposed.

    ``kinds`` restricts the op set (default: all of
    ``accounting.COLLECTIVE_KINDS``); pass ``("collective-permute",)``
    for exactly the ``overlap_report`` surface. Evidence rules match
    ``overlap_report``: async ``-start``/``-done`` pairs are hidden when
    a ``dot`` is scheduled inside the window; sync ops are hidden when
    some ``dot`` in the same computation neither feeds nor consumes them.
    """
    kinds = tuple(kinds) if kinds is not None else COLLECTIVE_KINDS
    mod = parse(hlo)
    dot_comps = _dot_bearing(mod.computations)
    rep = ExposedReport()

    def _tally(kind: str, b: float, hidden: bool) -> None:
        rep.collectives += 1
        bucket = rep.hidden_by_kind if hidden else rep.exposed_by_kind
        bucket[kind] = bucket.get(kind, 0.0) + b
        if hidden:
            rep.hidden += 1
            rep.hidden_wire_bytes += b
        else:
            rep.exposed_wire_bytes += b

    for comp, instrs in mod.computations.items():
        # the SAME def-use walk overlap_report runs (analyze.hlo owns it:
        # the evidence rules must never diverge between the two reports)
        _index, deps, users = dependency_graph(instrs)
        dot_idx = [i for i, (name, op, line) in enumerate(instrs)
                   if _is_dot_like(op, line, dot_comps)]

        for i, (name, op, line) in enumerate(instrs):
            if op.endswith("-start") and op[:-len("-start")] in kinds:
                kind = op[: -len("-start")]
                open_idx = line.index(op + "(") + len(op)
                # async start: price from the OPERANDS and reconstruct
                # the sync result bytes (accounting's shared rule — a
                # start's result tuple aliases the input next to the
                # output)
                b_op = _result_bytes(_paren_span(line, open_idx))
                w = _group_size(line, default_group_size or 1)
                wire = _wire_cost(kind,
                                  float(_async_result_bytes(kind, b_op, w)),
                                  w)
                done = next(
                    (j for j, (n2, op2, l2) in enumerate(instrs)
                     if op2 == kind + "-done"
                     and name in OPERAND_RE.findall(
                         l2.split(" = ", 1)[1])), None)
                hidden = done is not None and \
                    any(i < d < done for d in dot_idx)
                _tally(kind, wire, hidden)
            elif op in kinds:
                pre = line.split(" = ", 1)[1]
                open_idx = pre.index(op + "(")
                b = float(_result_bytes(pre[:open_idx]))
                w = _group_size(line, default_group_size or 1)
                wire = _wire_cost(op, b, w)
                blocked = reach(name, users) | reach(name, deps) | {name}
                hidden = any(instrs[d][0] not in blocked for d in dot_idx)
                _tally(op, wire, hidden)
    return rep


def assert_no_exposed(hlo, budget_bytes: float = 0.0,
                      kinds: Optional[Sequence[str]] = None,
                      default_group_size: Optional[int] = None
                      ) -> ExposedReport:
    """Assert a compiled program's exposed collective traffic stays within
    ``budget_bytes`` (modeled wire bytes, the ``accounting`` ring model).
    Returns the :class:`ExposedReport` on success; raises
    :class:`ExposedCollectiveError` with the per-kind breakdown
    otherwise. The assertion pass every ring/FSDP/cluster bench imports
    (``overlap_report`` remains the permute-window prover — see
    :func:`apex_tpu.comm.accounting.overlap_report`)."""
    rep = exposed_report(hlo, kinds=kinds,
                         default_group_size=default_group_size)
    if rep.exposed_wire_bytes > budget_bytes:
        split = ", ".join(f"{k}={v:.0f}B"
                          for k, v in sorted(rep.exposed_by_kind.items()))
        raise ExposedCollectiveError(
            f"{rep.exposed_wire_bytes:.0f} modeled wire bytes exposed "
            f"(budget {budget_bytes:.0f}): {split}; hidden_fraction="
            f"{rep.hidden_fraction:.3f} over {rep.collectives} collectives")
    return rep


def overlap_assertion(hlo, min_hidden_fraction: float = 0.5
                      ) -> OverlapReport:
    """The permute-ring form of the gate: ``overlap_report`` +
    a hidden-byte-fraction floor (what the flagship tp/FSDP gates in
    ``tests/test_collective_counts.py`` assert by hand)."""
    rep = overlap_report(hlo)
    if rep.permutes and rep.hidden_fraction < min_hidden_fraction:
        raise ExposedCollectiveError(
            f"permute traffic under-hidden: hidden_fraction="
            f"{rep.hidden_fraction:.3f} < {min_hidden_fraction} ({rep})")
    return rep

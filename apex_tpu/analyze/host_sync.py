"""Host-sync detector — device↔host round trips reachable from a step.

A jitted step is only as fast as its slowest DISPATCH: one stray
``float(x)`` / ``bool(x)`` on a device value, a ``jax.device_get``, or an
eager ``block_until_ready`` inside the step loop serializes the host
against the device and halves a dispatch-bound decode loop. These bugs
hide well — the program still computes the right answer, just slowly, and
on CPU tests the sync costs nothing. This detector makes them loud:

:func:`host_sync_report` traces ``fn`` with abstract values
(``jax.make_jaxpr``) under a spy that counts the EXPLICIT sync APIs
(``jax.device_get`` / ``jax.block_until_ready`` pass tracers through
silently — the spy counts each call) and catches the IMPLICIT ones as the
concretization errors they raise on tracers (``float``/``int``/``bool``
on a traced value, ``np.asarray``, data-dependent Python ``if``), with
the offending kind and message recorded. A clean step function reports
``host_syncs == 0``.

Caveat (by design of the passthrough spy): functions that captured
``device_get`` via ``from jax import device_get`` at import time bypass
the patch — call through the ``jax.`` namespace in step code, which is
this repo's idiom anyway. Tracing stops at the FIRST implicit sync (the
trace cannot continue past a concretization error), so fix-and-rerun
until clean.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple
from unittest import mock

import jax

__all__ = ["HostSyncError", "HostSyncReport", "assert_no_host_sync",
           "host_sync_report"]

_IMPLICIT_ERRORS = (
    jax.errors.ConcretizationTypeError,       # float()/int(), shape uses
    jax.errors.TracerArrayConversionError,    # np.asarray(tracer)
    jax.errors.TracerBoolConversionError,     # bool(tracer), if tracer:
    jax.errors.TracerIntegerConversionError,  # int(tracer) as index
)

# method-form sync attributes tracers lack: an AttributeError naming one
# of these during the trace is the sync, not a detector bug
_SYNC_ATTRS = ("block_until_ready", "device_buffer", "copy_to_host_async",
               "on_device_size_in_bytes")


class HostSyncError(AssertionError):
    """A host↔device synchronization point is reachable from the step."""


@dataclasses.dataclass
class HostSyncReport:
    """Sync points found on one trace of the step function."""

    device_gets: int = 0
    block_until_readys: int = 0
    implicit_syncs: int = 0
    implicit_kind: Optional[str] = None
    implicit_detail: str = ""

    @property
    def host_syncs(self) -> int:
        return self.device_gets + self.block_until_readys \
            + self.implicit_syncs

    @property
    def ok(self) -> bool:
        return self.host_syncs == 0

    def as_record(self) -> dict:
        return {"host_syncs": self.host_syncs,
                "device_gets": self.device_gets,
                "block_until_readys": self.block_until_readys,
                "implicit_syncs": self.implicit_syncs}

    def __repr__(self):
        tail = f", implicit={self.implicit_kind}" if self.implicit_kind \
            else ""
        return (f"HostSyncReport(device_get={self.device_gets}, "
                f"block_until_ready={self.block_until_readys}{tail})")


def _kind_of(exc: Exception) -> str:
    name = type(exc).__name__
    return {"TracerBoolConversionError": "bool(tracer)",
            "TracerIntegerConversionError": "int(tracer)",
            "TracerArrayConversionError": "np.asarray(tracer)",
            }.get(name, "concretization (float()/shape use of a tracer)")


def host_sync_report(fn, *args, **kwargs) -> HostSyncReport:
    """Trace ``fn(*args, **kwargs)`` and count reachable host syncs (see
    module docstring for the detection rules)."""
    rep = HostSyncReport()
    real_get, real_block = jax.device_get, jax.block_until_ready

    def spy_get(x):
        rep.device_gets += 1
        try:
            return real_get(x)
        except _IMPLICIT_ERRORS:
            return x  # tracer: counted, pass through so the trace goes on

    def spy_block(x):
        rep.block_until_readys += 1
        return x

    with mock.patch.object(jax, "device_get", spy_get), \
            mock.patch.object(jax, "block_until_ready", spy_block):
        try:
            jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
        except _IMPLICIT_ERRORS as e:
            rep.implicit_syncs = 1
            rep.implicit_kind = _kind_of(e)
            rep.implicit_detail = str(e).splitlines()[0][:200]
        except AttributeError as e:
            # the METHOD forms sync through attributes tracers don't
            # have (x.block_until_ready(), x.device_buffer, ...) — an
            # AttributeError naming one of them IS the sync evidence;
            # anything else is a genuine bug and re-raises
            msg = str(e)
            if any(a in msg for a in _SYNC_ATTRS):
                rep.implicit_syncs = 1
                rep.implicit_kind = "sync method on tracer"
                rep.implicit_detail = msg.splitlines()[0][:200]
            else:
                raise
    return rep


def assert_no_host_sync(fn, *args, **kwargs) -> HostSyncReport:
    """:func:`host_sync_report`, raising :class:`HostSyncError` when any
    sync point is reachable from the step."""
    rep = host_sync_report(fn, *args, **kwargs)
    if not rep.ok:
        parts = []
        if rep.device_gets:
            parts.append(f"{rep.device_gets}× jax.device_get")
        if rep.block_until_readys:
            parts.append(f"{rep.block_until_readys}× "
                         f"jax.block_until_ready")
        if rep.implicit_syncs:
            parts.append(f"implicit sync via {rep.implicit_kind}: "
                         f"{rep.implicit_detail}")
        raise HostSyncError(
            "host↔device sync reachable from the step function: "
            + "; ".join(parts))
    return rep

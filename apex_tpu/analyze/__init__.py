"""apex_tpu.analyze — compiled-program contract checker + repo graph-lint.

The repo's correctness story for compiled programs — donation actually
aliased, jit caches bounded, dtype policies respected, collectives hidden
behind compute, no host syncs in the step — grew up as one-off assertions
inside individual test files. This subsystem promotes them into one
reusable static-analysis surface, checked on the program XLA actually
compiled (the EQuARX lesson: claims validated on the artifact, not the
source), in two tiers:

**Tier A — program analyzers** (jaxprs + lowered/compiled HLO):

========================  ==================================================
:mod:`~.donation`          ``assert_donated`` / ``check_donation`` — are
                           declared-donated buffers ALIASED in the
                           compiled executable, or silently copied?
:mod:`~.adapters`          ``assert_adapter_donated`` — the serve LoRA
                           AdapterPool rides EVERY serve jit site as a
                           donated, aliased input (no per-adapter-swap
                           recompiles, no pool-copy donation leak).
:mod:`~.recompile`         ``recompile_guard`` / ``jit_cache_size`` — jit
                           cache sizes pinned to a declared budget across
                           N invocations (the serve compile gate,
                           generalized to any step).
:mod:`~.dtype_leak`        ``assert_no_dtype_leaks`` — fp32 dots/convs
                           under a declared bf16/fp8 policy and
                           f32↔bf16 convert churn, from the jaxpr.
:mod:`~.collectives`       ``assert_no_exposed(hlo, budget_bytes)`` —
                           hidden/exposed wire-byte split over every
                           collective kind (the ``overlap_report``
                           evidence rules as an assertion pass).
:mod:`~.host_sync`         ``assert_no_host_sync`` — ``device_get`` /
                           ``block_until_ready`` / ``float(tracer)``
                           sync points reachable from a step function.
:mod:`~.hlo`               the shared ``as_text``/``parse`` entry point
                           (one HLO normalization for ``comm.accounting``,
                           ``monitor.report`` and every analyzer here).
========================  ==================================================

**Tier B — repo graph-lint** (:mod:`~.lint`): ``python -m
apex_tpu.analyze.lint apex_tpu/`` — an AST pass flagging the
anti-patterns this codebase has repeatedly fixed by hand (tracer
branches, ``jnp.array`` on tracers, unjustified bare excepts, mutable
default args, step-shaped jits missing ``donate_argnums``), gated by a
checked-in baseline (``tests/lint_baseline.json``) so accepted sites pass
while NEW violations fail tier-1.

Analyzer records (``*.as_record()``) join the bench ``json_record``
convention, and ``monitor.regress`` knows their polarity
(``exposed_bytes`` / ``convert_churn_ops`` / ``host_syncs`` /
``lint_violations``: lower is better) so the watcher's stage-16 contract
record is regression-gated like every other banked artifact.
"""

# LAZY exports (PEP 562), deliberately: ``comm.accounting`` imports
# ``analyze.hlo`` (the shared normalization) while ``analyze.collectives``
# imports ``comm.accounting`` (the wire model) — an eager __init__ would
# make that a cycle the moment either side loads first. ``hlo`` itself is
# dependency-free and safe to import here.
import importlib

from apex_tpu.analyze import hlo  # noqa: F401  (submodule re-export)

_EXPORTS = {
    "DonationError": "donation", "DonationReport": "donation",
    "assert_donated": "donation", "check_donation": "donation",
    "donation_report": "donation",
    "adapter_contract_record": "adapters",
    "adapter_donation_report": "adapters",
    "adapter_jit_sites": "adapters",
    "assert_adapter_donated": "adapters",
    "RecompileError": "recompile", "RecompileGuard": "recompile",
    "compile_counts": "recompile", "jit_cache_size": "recompile",
    "recompile_guard": "recompile",
    "DtypeLeakError": "dtype_leak", "DtypeLeakReport": "dtype_leak",
    "assert_no_dtype_leaks": "dtype_leak",
    "dtype_leak_report": "dtype_leak",
    "resolve_policy_dtype": "dtype_leak",
    "ExposedCollectiveError": "collectives", "ExposedReport": "collectives",
    "assert_no_exposed": "collectives", "exposed_report": "collectives",
    "overlap_assertion": "collectives",
    "HostSyncError": "host_sync", "HostSyncReport": "host_sync",
    "assert_no_host_sync": "host_sync", "host_sync_report": "host_sync",
    "Violation": "lint", "lint_paths": "lint", "load_baseline": "lint",
    "new_violations": "lint", "write_baseline": "lint",
}

__all__ = sorted(_EXPORTS) + ["hlo"]


def __getattr__(name: str):
    try:
        modname = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    mod = importlib.import_module(f"{__name__}.{modname}")
    value = getattr(mod, name)
    globals()[name] = value  # cache for the next access
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))

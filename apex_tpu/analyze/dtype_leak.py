"""Dtype-leak detector — fp32 matmuls and convert churn under an amp policy.

An amp policy (bf16 model dtype, fp8 casts, O1 per-op autocast) is a
claim about the PROGRAM: the hot GEMMs run in the low-precision dtype and
values do not ping-pong through f32 on the way. Nothing enforced that
claim — one missing ``.astype`` upstream of a ``dot`` silently runs the
matmul in fp32 at half the TPU's throughput, and a cast placed inside the
wrong scope round-trips every activation f32→bf16→f32. This detector
walks the jaxpr (all sub-jaxprs: ``scan`` bodies, ``pjit`` calls,
``custom_vjp`` wrappers, remat) and reports:

* ``fp32_dots`` — ``dot_general``/``conv_general_dilated`` equations
  whose OPERANDS are f32/f64 while the declared policy dtype is
  low-precision (the "fp32 dot under a bf16 policy" leak — the matmul
  rides the fp32 MXU path), with source sites. Low-precision operands
  accumulating into f32 (``preferred_element_type`` — the TPU-native
  pattern) are NOT leaks; they count separately as ``fp32_accum_dots``;
* ``convert_churn_ops`` — ``convert_element_type`` equations whose input
  was itself produced by a convert in the OPPOSITE direction (an
  f32↔policy-dtype round trip on one edge: pure overhead).

The policy can be declared as a dtype, an
:class:`~apex_tpu.config.PrecisionConfig` (the amp opt-level presets), or
anything with a ``.dtype`` field (``GPTConfig``, FSDP leaf meta) —
:func:`resolve_policy_dtype` is the one resolution rule, shared with the
amp/fsdp wiring.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["DtypeLeakError", "DtypeLeakReport", "assert_no_dtype_leaks",
           "dtype_leak_report", "resolve_policy_dtype"]

_LOW_PRECISION = ("bfloat16", "float16", "float8_e4m3", "float8_e4m3fn",
                  "float8_e5m2", "float8_e4m3fnuz", "float8_e5m2fnuz",
                  "float8_e4m3b11fnuz")
# the policy lattice: a dot is ON-policy when its operands sit at or below
# the declared dtype's rung. fp8 forward (e4m3) and gradient (e5m2) casts
# share the bottom rung — an fp8 policy accepts both (the e4m3/e5m2 split
# is the recipe, not a leak).
_HALF = ("bfloat16", "float16")
_FP8 = tuple(d for d in _LOW_PRECISION if d.startswith("float8"))
_WIDE = ("float32", "float64")
_HOT_PRIMS = ("dot_general", "conv_general_dilated")


class DtypeLeakError(AssertionError):
    """The compiled-program dtype story contradicts the declared policy."""


def resolve_policy_dtype(policy) -> Optional[Any]:
    """One rule for "what dtype did the caller declare": a dtype-like
    passes through; a ``PrecisionConfig`` resolves to its model-cast or
    per-op compute dtype (``None`` for O0 — full precision, nothing to
    leak); an object with ``.dtype`` (``GPTConfig``, FSDP leaf meta)
    contributes that."""
    if policy is None:
        return None
    if hasattr(policy, "cast_model_type") or hasattr(policy, "compute_dtype"):
        # an amp PrecisionConfig: the declaration rule is amp's, not ours
        from apex_tpu.amp.frontend import policy_compute_dtype
        return policy_compute_dtype(policy)
    if hasattr(policy, "dtype") and not isinstance(policy, jnp.dtype):
        return jnp.dtype(policy.dtype)
    return jnp.dtype(policy)


def _subjaxprs(eqn) -> Iterator[Any]:
    for val in eqn.params.values():
        vals = val if isinstance(val, (list, tuple)) else (val,)
        for v in vals:
            if isinstance(v, jax.core.ClosedJaxpr):
                yield v.jaxpr
            elif isinstance(v, jax.core.Jaxpr):
                yield v


def _walk(jaxpr) -> Iterator[Tuple[Any, Any]]:
    """Yield ``(jaxpr, eqn)`` over the whole nest (scan/while bodies,
    pjit/remat calls, custom-vjp wrappers)."""
    for eqn in jaxpr.eqns:
        yield jaxpr, eqn
        for sub in _subjaxprs(eqn):
            yield from _walk(sub)


def _site(eqn) -> str:
    try:
        from jax._src import source_info_util
        return source_info_util.summarize(eqn.source_info)
    except Exception:  # source info is best-effort decoration only
        return ""


def _out_dtype(eqn) -> Optional[Any]:
    for v in eqn.outvars:
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "dtype"):
            return aval.dtype
    return None


def _in_dtype(eqn) -> Optional[Any]:
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        if aval is not None and hasattr(aval, "dtype"):
            return aval.dtype
    return None


def _has_wide_operand(eqn) -> bool:
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        dt = getattr(aval, "dtype", None)
        if dt is not None and dt.name in _WIDE:
            return True
    return False


@dataclasses.dataclass
class DtypeLeakReport:
    """Jaxpr-level precision evidence for one traced program."""

    policy_dtype: Optional[str]
    fp32_dots: int = 0
    fp32_dot_sites: Tuple[str, ...] = ()
    fp32_accum_dots: int = 0  # low-precision operands, f32 accumulate: ok
    # dots one lattice rung ABOVE an fp8 policy (bf16/f16 operands):
    # informational, never raise — fp8 recipes legitimately keep some
    # sites half (norm-adjacent math) but the count should not creep
    off_policy_half_dots: int = 0
    convert_ops: int = 0
    convert_churn_ops: int = 0
    churn_sites: Tuple[str, ...] = ()
    total_dots: int = 0

    @property
    def ok(self) -> bool:
        return self.fp32_dots == 0 and self.convert_churn_ops == 0

    def as_record(self) -> dict:
        return {"fp32_dots": self.fp32_dots,
                "fp32_accum_dots": self.fp32_accum_dots,
                "off_policy_half_dots": self.off_policy_half_dots,
                "convert_churn_ops": self.convert_churn_ops,
                "convert_ops": self.convert_ops,
                "total_dots": self.total_dots,
                "dtype_ok": self.ok}

    def __repr__(self):
        return (f"DtypeLeakReport(policy={self.policy_dtype}, "
                f"fp32_dots={self.fp32_dots}/{self.total_dots}, "
                f"convert_churn={self.convert_churn_ops}"
                f"/{self.convert_ops} converts)")


def dtype_leak_report(fn, *args, policy, **kwargs) -> DtypeLeakReport:
    """Trace ``fn(*args, **kwargs)`` (or accept a ``ClosedJaxpr``) and
    report dtype leaks against the declared ``policy`` (see
    :func:`resolve_policy_dtype`)."""
    policy_dt = resolve_policy_dtype(policy)
    if isinstance(fn, jax.core.ClosedJaxpr):
        closed = fn
    else:
        closed = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    rep = DtypeLeakReport(
        policy_dtype=str(policy_dt) if policy_dt is not None else None)
    low_policy = policy_dt is not None and policy_dt.name in _LOW_PRECISION
    fp32_sites: List[str] = []
    churn_sites: List[str] = []

    # producer maps are per-jaxpr (vars are scoped); group the walk
    by_jaxpr: dict = {}
    for jpr, eqn in _walk(closed.jaxpr):
        by_jaxpr.setdefault(id(jpr), []).append(eqn)

    for eqns in by_jaxpr.values():
        producer = {}
        for eqn in eqns:
            for v in eqn.outvars:
                producer[v] = eqn
        for eqn in eqns:
            name = eqn.primitive.name
            if name in _HOT_PRIMS:
                rep.total_dots += 1
                out_dt = _out_dtype(eqn)
                if low_policy and _has_wide_operand(eqn):
                    # f32 OPERANDS: the matmul computes on the fp32 MXU
                    # path — the leak
                    rep.fp32_dots += 1
                    fp32_sites.append(_site(eqn))
                else:
                    if low_policy and out_dt is not None \
                            and out_dt.name in _WIDE:
                        # low-precision operands accumulating into f32
                        # (preferred_element_type): TPU-native, not a leak
                        rep.fp32_accum_dots += 1
                    if low_policy and policy_dt.name in _FP8 and any(
                            getattr(getattr(v, "aval", None), "dtype",
                                    None) is not None
                            and v.aval.dtype.name in _HALF
                            for v in eqn.invars):
                        # one lattice rung above an fp8 policy: counted,
                        # never raised (see _HALF note above)
                        rep.off_policy_half_dots += 1
            elif name == "convert_element_type":
                src, dst = _in_dtype(eqn), _out_dtype(eqn)
                if src is None or dst is None:
                    continue
                pair = {src.name, dst.name}
                if not (pair & set(_WIDE) and pair & set(_LOW_PRECISION)):
                    continue  # only f32↔low-precision edges are policed
                rep.convert_ops += 1
                prev = producer.get(eqn.invars[0])
                if prev is not None and \
                        prev.primitive.name == "convert_element_type":
                    psrc, pdst = _in_dtype(prev), _out_dtype(prev)
                    if psrc is not None and pdst is not None \
                            and psrc.name == dst.name \
                            and pdst.name == src.name:
                        rep.convert_churn_ops += 1  # A→B→A round trip
                        churn_sites.append(_site(eqn))
    rep.fp32_dot_sites = tuple(fp32_sites)
    rep.churn_sites = tuple(churn_sites)
    return rep


def assert_no_dtype_leaks(fn, *args, policy, allow_fp32_dots: int = 0,
                          allow_churn: int = 0, **kwargs) -> DtypeLeakReport:
    """:func:`dtype_leak_report`, raising :class:`DtypeLeakError` on
    fp32-operand dots/convs beyond ``allow_fp32_dots`` (for the rare
    deliberately-fp32 site, e.g. attention-stability math) or convert
    churn beyond ``allow_churn`` round-trips. f32-ACCUMULATED
    low-precision dots never raise (``fp32_accum_dots`` is
    informational)."""
    rep = dtype_leak_report(fn, *args, policy=policy, **kwargs)
    problems = []
    if rep.fp32_dots > allow_fp32_dots:
        sites = "; ".join(s for s in rep.fp32_dot_sites if s) or "(no src)"
        problems.append(
            f"{rep.fp32_dots} fp32 dot/conv under the "
            f"{rep.policy_dtype} policy (allowed {allow_fp32_dots}) "
            f"at {sites}")
    if rep.convert_churn_ops > allow_churn:
        sites = "; ".join(s for s in rep.churn_sites if s) or "(no src)"
        problems.append(
            f"{rep.convert_churn_ops} f32↔{rep.policy_dtype} convert "
            f"round-trips (allowed {allow_churn}) at {sites}")
    if problems:
        raise DtypeLeakError("dtype policy violated: " +
                             "; ".join(problems))
    return rep

"""Adapter-pool donation contract — does the LoRA pool RIDE every jit site?

The serve adapter design (``apex_tpu.serve.adapters``) only holds its two
headline promises — zero per-adapter-swap recompiles and zero extra pool
copies — if the pool is threaded through every serve program as a DONATED
input that XLA actually aliases to an output:

* if the pool were closed over instead of passed, every
  ``load_adapter``/``write_adapter`` would change the constant and retrace
  (the recompile leak);
* if it were passed but not donated-and-aliased, every step would copy
  ``adapter_pool_bytes`` of HBM (the donation leak — the same silent
  failure mode :mod:`apex_tpu.analyze.donation` exists to catch for the
  KV pools).

This module promotes that into a contract check on the engine's COMPILED
programs: for each lora-enabled jit site (``chunk_prefill`` / ``decode``
/ ``verify`` when spec-k is on), lower the already-jitted program with
representative arguments — AOT ``lower().compile()``, so the engine's jit
caches and ``compile_counts`` are untouched — and require every leaf of
the KV cache AND the adapter pool (donate argnums 1 and 2) to appear in
the executable's ``input_output_alias`` map.

Wired into the stage-16/graph-lint CI surface via
``benchmarks/analyze_contracts.py`` (the ``adapter_donation_ok`` record
field) and pinned by tier-1 tests.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax.numpy as jnp

from apex_tpu.analyze.donation import (DonationError, DonationReport,
                                       check_donation)

__all__ = ["adapter_contract_record", "adapter_donation_report",
           "adapter_jit_sites", "assert_adapter_donated"]


def adapter_jit_sites(engine) -> Dict[str, Tuple[Any, tuple]]:
    """``{site: (jitted_fn, representative_args)}`` for every serve jit
    site the adapter pool rides (argument order mirrors the engine's own
    call sites; shapes come from the engine's mirrors so lowering hits
    the SAME cache entry the live engine compiled)."""
    if getattr(engine, "adapters", None) is None:
        raise ValueError(
            "engine has no adapter pool (ServeConfig.lora_rank == 0) — "
            "nothing for the adapter donation contract to check")
    scfg = engine.serve_cfg
    progs = engine.programs()
    n = scfg.num_slots
    prefill_tokens = jnp.zeros((scfg.prefill_chunk,), jnp.int32)
    sites: Dict[str, Tuple[Any, tuple]] = {
        "chunk_prefill": (progs["chunk_prefill"], (
            engine.params, engine.cache, engine._lora_pool,
            prefill_tokens, jnp.int32(0), jnp.int32(1),
            engine._dev("block_tables")[0], engine._dev("keys")[0],
            engine._dev("adapter_ids")[0])),
        "decode": (progs["decode"], (
            engine.params, engine.cache, engine._lora_pool,
            engine._dev("last_tokens"), engine._dev("seq_lens"),
            engine._dev("active"), engine._dev("block_tables"),
            engine._dev("keys"), engine._dev("adapter_ids"))),
    }
    if progs.get("verify") is not None:
        fed = jnp.zeros((n, scfg.spec_k + 1), jnp.int32)
        n_fed = jnp.zeros((n,), jnp.int32)
        sites["verify"] = (progs["verify"], (
            engine.params, engine.cache, engine._lora_pool,
            fed, engine._dev("seq_lens"), n_fed,
            engine._dev("active"), engine._dev("block_tables"),
            engine._dev("keys"), engine._dev("adapter_ids")))
    return sites


def adapter_donation_report(engine) -> Dict[str, DonationReport]:
    """Per-site :class:`~apex_tpu.analyze.donation.DonationReport` with
    ``expected_leaves`` = leaves(cache) + leaves(pool) — ``ok`` means the
    compiled executable aliases BOTH donated pytrees in full."""
    out: Dict[str, DonationReport] = {}
    for site, (fn, args) in adapter_jit_sites(engine).items():
        out[site] = check_donation(fn, *args, donate_argnums=(1, 2))
    return out


def assert_adapter_donated(engine) -> Dict[str, DonationReport]:
    """:func:`adapter_donation_report`, raising
    :class:`~apex_tpu.analyze.donation.DonationError` naming every site
    where a cache or adapter-pool leaf was silently copied."""
    reports = adapter_donation_report(engine)
    bad: List[str] = []
    for site, rep in reports.items():
        if not rep.ok:
            bad.append(f"{site}: {rep.n_aliased}/{rep.expected_leaves} "
                       f"aliased, {len(rep.unusable)} copied")
    if bad:
        raise DonationError(
            "adapter pool donation not honored — " + "; ".join(bad))
    return reports


def adapter_contract_record(engine) -> Dict[str, Any]:
    """Flat ``json_record`` fields for the analyze-contracts bench record
    (``adapter_donated_copied`` joins the ``donated_copied`` lower-is-
    better polarity family in ``monitor.regress``)."""
    reports = adapter_donation_report(engine)
    copied = sum(len(r.unusable) for r in reports.values())
    aliased = sum(r.n_aliased for r in reports.values())
    expected = sum(r.expected_leaves or 0 for r in reports.values())
    return {"adapter_sites_checked": len(reports),
            "adapter_donated_aliased": aliased,
            "adapter_donated_expected": expected,
            "adapter_donated_copied": copied,
            "adapter_donation_ok": all(r.ok for r in reports.values())}

"""One entry point for reading compiled-HLO text — normalization + parse.

Every compiled-program check in this repo starts the same way: take "an
HLO" (a string, a ``jax.stages.Compiled``, anything with ``.as_text()``),
normalize it to text, and walk its computations in print order (which is
schedule order for post-schedule TPU modules) while chasing
``calls=``/``to_apply=``/``body=`` edges so fusion wrappers and while
bodies are not blind spots. ``comm/accounting.py`` grew one copy of that
walker for :func:`~apex_tpu.comm.accounting.overlap_report`,
``monitor/report.py`` re-did the normalization for ``hlo_stats``, and
every new analyzer would have needed a third. This module is the single
implementation both import (and :mod:`apex_tpu.analyze` builds on):

* :func:`as_text` — the ``isinstance(hlo, str) ... as_text()``
  normalization, in one place;
* :func:`parse_computations` — ``{computation: [(name, opcode, line)]}``
  in print order (the ``overlap_report`` walker, verbatim semantics);
* :func:`parse` — both of the above plus the module header, as one
  :class:`HloModule` with alias/called-computation accessors.

Deliberately dependency-free (stdlib + ``re`` only): ``comm`` and
``monitor`` import it, so it must import neither.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Tuple

__all__ = ["HloModule", "as_text", "parse", "parse_computations",
           "CALLED_RE", "dependency_graph", "input_output_aliases",
           "reach"]

# instruction name on the left of " = "
INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.-]+)\s*=\s*")
# first opcode-like token followed by "(" on the right of " = "
OPCODE_RE = re.compile(r"\b([a-z][\w-]*)\(")
# %operand references inside an instruction's right-hand side
OPERAND_RE = re.compile(r"%([\w.-]+)")
# computation edges: fusions, maps, reductions, while bodies/conditions,
# conditional branches — the walker must see through all of them
CALLED_RE = re.compile(r"(?:calls|to_apply|body|condition|branch_"
                       r"computations)=\{?%?([\w.-]+)")
COMP_HEAD_RE = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.-]+)")

# "{output_path}: (param_number, {param_path}, kind)" entries inside the
# module header's input_output_alias={...} attribute
_ALIAS_ENTRY_RE = re.compile(
    r"\{([0-9, ]*)\}:\s*\((\d+),\s*\{([0-9, ]*)\}(?:,\s*([\w-]+))?\)")

Instruction = Tuple[str, str, str]  # (name, opcode, full line)


def as_text(hlo) -> str:
    """Normalize to HLO text: a ``str`` passes through, anything else must
    provide ``.as_text()`` (``jax.stages.Compiled``/``Lowered``, XLA
    ``HloModule`` wrappers)."""
    if isinstance(hlo, str):
        return hlo
    fn = getattr(hlo, "as_text", None)
    if callable(fn):
        return fn()
    raise TypeError(
        f"expected HLO text or an object with .as_text(), got {type(hlo)}")


def parse_computations(text: str) -> Dict[str, List[Instruction]]:
    """-> ``{comp_name: [(name, opcode, line), ...]}`` in print (schedule)
    order. Instructions outside any recognized computation header land in
    an ``""`` bucket so bare snippets (synthetic tests) still parse."""
    comps: Dict[str, List[Instruction]] = {}
    current = ""
    for line in text.splitlines():
        if line.rstrip().endswith("{") and " = " not in line:
            m = COMP_HEAD_RE.match(line)
            if m and m.group(1) != "HloModule":
                current = m.group(1)
            continue
        if line.strip() == "}":
            current = ""
            continue
        m = INSTR_RE.match(line)
        if not m or " = " not in line:
            continue
        after = line.split(" = ", 1)[1]
        op = OPCODE_RE.search(after)
        comps.setdefault(current, []).append(
            (m.group(1), op.group(1) if op else "", line))
    return comps


def input_output_aliases(text: str) -> List[Tuple[str, int, str, str]]:
    """Donation evidence from the module header: the
    ``input_output_alias={ {out}: (param, {idx}, kind), ... }`` entries of
    a compiled module, as ``(output_path, param_number, param_path,
    kind)`` tuples. An empty list on a program whose inputs were donated
    means XLA aliased NOTHING — every donated buffer was silently
    copied."""
    # the attribute value is brace-nested ({ {0}: (0, {}, kind) ... }):
    # a balanced scan, not a regex, finds its true extent
    idx = text.find("input_output_alias={")
    if idx < 0:
        return []
    depth, start = 0, idx + len("input_output_alias=")
    m_text = ""
    for i in range(start, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                m_text = text[start + 1: i]
                break
    return [(out.strip(), int(param), pidx.strip(), kind or "")
            for out, param, pidx, kind in _ALIAS_ENTRY_RE.findall(m_text)]


def dependency_graph(instrs: List[Instruction]):
    """Def-use maps for ONE computation's instructions (same-computation
    operands only): ``(index, deps, users)`` where ``deps[name]`` are the
    operands an instruction reads and ``users[name]`` the instructions
    that read it. The shared walk under ``overlap_report`` and
    ``analyze.collectives.exposed_report`` — the hidden/exposed evidence
    rules must never diverge between the two."""
    index = {name: i for i, (name, _, _) in enumerate(instrs)}
    users: Dict[str, List[str]] = {}
    deps: Dict[str, List[str]] = {}
    for name, _, line in instrs:
        rhs = line.split(" = ", 1)[1]
        ops_of = [o for o in OPERAND_RE.findall(rhs)
                  if o in index and o != name]
        deps[name] = ops_of
        for o in ops_of:
            users.setdefault(o, []).append(name)
    return index, deps, users


def reach(start: str, edges: Dict[str, List[str]]) -> set:
    """Transitive closure of ``start`` over ``edges`` (deps or users)."""
    seen, stack = set(), [start]
    while stack:
        n = stack.pop()
        for nxt in edges.get(n, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return seen


@dataclasses.dataclass
class HloModule:
    """A parsed module: raw text + computations in print order."""

    text: str
    computations: Dict[str, List[Instruction]]

    @property
    def header(self) -> str:
        return self.text.splitlines()[0] if self.text else ""

    def input_output_aliases(self) -> List[Tuple[str, int, str, str]]:
        return input_output_aliases(self.text)

    def instructions(self) -> List[Instruction]:
        return [i for instrs in self.computations.values() for i in instrs]


def parse(hlo) -> HloModule:
    """THE shared entry point: normalize (:func:`as_text`) + walk
    (:func:`parse_computations`) in one call."""
    text = as_text(hlo)
    return HloModule(text=text, computations=parse_computations(text))

"""Fused dense layers — GEMM+bias and GEMM+bias+GeLU+GEMM+bias.

Reference: ``apex/fused_dense/fused_dense.py`` (``FusedDenseFunc:6``,
``FusedDenseGeluDenseFunc:34``, modules ``:53,71``) over ``fused_dense_cuda``
(cuBLASLt epilogue fusions, ``csrc/fused_dense_cuda.cu``). On TPU these
epilogues are XLA fusions; the value of this module is API parity plus the
exact-gelu choice matching the reference (erf-based, not tanh approximation).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


def _gelu_exact(x):
    # cuBLASLt CUBLASLT_EPILOGUE_GELU uses the erf formulation
    return jax.nn.gelu(x, approximate=False)


def fused_dense(x, kernel, bias=None):
    y = x @ kernel
    if bias is not None:
        y = y + bias
    return y


def fused_dense_gelu_dense(x, kernel1, bias1, kernel2, bias2):
    h = _gelu_exact(fused_dense(x, kernel1, bias1))
    return fused_dense(h, kernel2, bias2)


class FusedDense(nn.Module):
    """Ref ``fused_dense.py:53-69``."""

    features: int
    use_bias: bool = True
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        k = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (x.shape[-1], self.features),
            self.param_dtype,
        )
        b = (
            self.param("bias", nn.initializers.zeros, (self.features,), self.param_dtype)
            if self.use_bias
            else None
        )
        return fused_dense(x, k, b)


class FusedDenseGeluDense(nn.Module):
    """Ref ``fused_dense.py:71-86``."""

    intermediate_features: int
    out_features: int
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        k1 = self.param(
            "kernel1", nn.initializers.lecun_normal(),
            (x.shape[-1], self.intermediate_features), self.param_dtype,
        )
        b1 = self.param(
            "bias1", nn.initializers.zeros, (self.intermediate_features,),
            self.param_dtype,
        )
        k2 = self.param(
            "kernel2", nn.initializers.lecun_normal(),
            (self.intermediate_features, self.out_features), self.param_dtype,
        )
        b2 = self.param(
            "bias2", nn.initializers.zeros, (self.out_features,), self.param_dtype
        )
        return fused_dense_gelu_dense(x, k1, b1, k2, b2)

from apex_tpu.fused_dense.fused_dense import (  # noqa: F401
    FusedDense,
    FusedDenseGeluDense,
    fused_dense,
    fused_dense_gelu_dense,
)

__all__ = [
    "FusedDense",
    "FusedDenseGeluDense",
    "fused_dense",
    "fused_dense_gelu_dense",
]

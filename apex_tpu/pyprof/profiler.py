"""See package docstring."""

from __future__ import annotations

import contextlib
import functools
from typing import Any, Callable, Dict, Optional

import jax


def annotate(name: str):
    """Range annotation visible in profiler traces (ref
    ``pyprof.nvtx`` ranges; ad-hoc NVTX in hot paths like
    ``apex/parallel/distributed.py:360``)."""
    return jax.named_scope(name)


def annotate_function(fn: Callable = None, *, name: Optional[str] = None):
    """Decorator form (ref ``nvtx/nvmarker.py`` function wrapping)."""
    if fn is None:
        return functools.partial(annotate_function, name=name)

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.named_scope(name or fn.__qualname__):
            return fn(*args, **kwargs)

    return wrapped


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False):
    """Capture a device trace to ``log_dir`` (TensorBoard 'profile' plugin /
    Perfetto readable — the nvprof-SQLite analogue)."""
    jax.profiler.start_trace(log_dir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def cost_analysis(fn: Callable, *args, **kwargs) -> Dict[str, float]:
    """Exact compiled-program costs: {'flops', 'bytes accessed', ...} from
    XLA's cost model (ref ``pyprof.prof`` per-op FLOP formulas — here the
    compiler reports the real numbers after fusion)."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # older jax returns [dict]
        ca = ca[0] if ca else {}
    return dict(ca) if ca else {}


def summary(fn: Callable, *args, peak_flops: Optional[float] = None,
            **kwargs) -> Dict[str, Any]:
    """One-call roofline summary of a jittable function: FLOPs, bytes,
    arithmetic intensity, and (given ``peak_flops``) the compute-bound
    ceiling — the pyprof 'prof' report for one step."""
    ca = cost_analysis(fn, *args, **kwargs)
    flops = float(ca.get("flops", 0.0))
    byts = float(ca.get("bytes accessed", 0.0))
    out = {
        "flops": flops,
        "bytes_accessed": byts,
        "arithmetic_intensity": flops / byts if byts else float("inf"),
    }
    if peak_flops:
        out["min_time_s_compute_bound"] = flops / peak_flops
    return out

"""Profiling toolkit (ref ``apex/pyprof``, ~5k LoC).

The reference has three parts: (1) ``nvtx.init()`` monkey-patches the torch
surface to emit NVTX ranges with call-site/shape/dtype payloads
(``nvtx/nvmarker.py``); (2) ``parse`` reads nvprof SQLite databases;
(3) ``prof`` maps kernels to layers and computes per-op FLOPs/bytes
(``prof/blas.py`` etc.).

TPU re-design: XLA already carries op provenance end-to-end, so the three
parts collapse to thin, robust wrappers:

* :func:`annotate` / :func:`annotate_function` — ``jax.named_scope`` ranges
  that show up in the XLA trace viewer (the nvtx.init capability, no
  monkey-patching needed: scopes attach to traced ops).
* :func:`trace` — ``jax.profiler.trace`` context writing a TensorBoard-
  loadable profile (the nvprof capture).
* :func:`cost_analysis` — compiled-HLO FLOPs/bytes per executable (the
  ``prof`` FLOP counting, exact instead of per-op formulas).
* :func:`report` / :func:`op_table` — per-op/per-layer attribution from the
  compiled HLO: every fused instruction with its ``named_scope`` layer path,
  FLOPs, bytes, and roofline time estimate (the ``parse``+``prof`` report).
* :func:`measured_report` / :func:`measured_op_table` — the MEASURED
  analogue: runs the step under ``jax.profiler``, parses the trace, and
  joins per-instruction measured time with the HLO flops/bytes (the
  reference's parse→prof kernel-time join, ``parse/kernel.py`` +
  ``prof/output.py``).
"""

from apex_tpu.pyprof.profiler import (  # noqa: F401
    annotate,
    annotate_function,
    cost_analysis,
    summary,
    trace,
)
from apex_tpu.pyprof.prof import (  # noqa: F401
    format_table,
    op_table,
    report,
)
from apex_tpu.pyprof.parse import (  # noqa: F401
    format_measured_table,
    load_trace_events,
    measured_op_table,
    measured_report,
)

__all__ = ["annotate", "annotate_function", "trace", "cost_analysis",
           "summary", "op_table", "format_table", "report",
           "measured_op_table", "format_measured_table", "measured_report",
           "load_trace_events"]

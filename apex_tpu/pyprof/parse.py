"""Measured per-op time attribution: trace parse + HLO cost join.

Reference capability: ``apex/pyprof/parse`` reads the nvprof/nsys SQLite
database into per-kernel records (``parse/kernel.py``: name, duration,
grid) and ``apex/pyprof/prof/output.py`` renders the joined
{op, time, flops, bytes} table. That answers the question static analysis
cannot: *which op eats the step time?*

TPU re-design: ``jax.profiler`` already writes a Chrome-trace JSON
(``*.trace.json.gz``) whose duration events on the device rows are named by
HLO instruction — the same names the compiled HLO text carries. So the
pipeline is: run the step under ``jax.profiler.trace`` → sum measured
durations per instruction name → join with the flops/bytes rows
:mod:`apex_tpu.pyprof.prof` computes from the compiled HLO → per-op
{name, scope, op, time, flops, bytes, MFU%, GB/s}. No SQLite, no kernel
string munging: the instruction name IS the join key on both sides.

Coverage is reported honestly: measured events that match no entry-
computation instruction (infeed, runtime bookkeeping) are kept as
unattributed rows, and ``coverage_pct`` says how much measured time the
join explained.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import tempfile
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from apex_tpu.pyprof.prof import (
    _SKIP_OPS,
    _comp_flops,
    _conv_flops,
    _dot_flops,
    _nbytes,
    _parse_hlo,
)


def load_trace_events(
    log_dir: str,
) -> Tuple[Dict[str, Tuple[float, int]], float]:
    """Parse the newest trace run under ``log_dir``.

    Returns ``({name: (dur_us, exec_count)}, total_us)`` summed over
    complete ('X') events — the count matters for ops inside compiled
    loops (scan-over-layers bodies execute once per layer per step).
    Device-row events are preferred when any process is a device (host
    rows duplicate dispatch-side spans of the same names); on the CPU
    backend everything rides the host row and all events count.
    """
    runs = sorted(glob.glob(os.path.join(log_dir, "plugins", "profile", "*")))
    if not runs:
        raise FileNotFoundError(f"no profile runs under {log_dir}")
    paths = glob.glob(os.path.join(runs[-1], "*.trace.json.gz"))
    if not paths:
        raise FileNotFoundError(f"no *.trace.json.gz in {runs[-1]}")

    events: List[dict] = []
    pid_names: Dict[int, str] = {}
    for p in paths:
        tr = json.loads(gzip.open(p, "rb").read())
        for e in tr.get("traceEvents", []):
            if e.get("ph") == "M" and e.get("name") == "process_name":
                pid_names[e["pid"]] = e.get("args", {}).get("name", "")
            elif e.get("ph") == "X" and "dur" in e:
                events.append(e)

    device_pids = {p for p, n in pid_names.items() if "/device:" in n}
    if device_pids:
        events = [e for e in events if e.get("pid") in device_pids]
        keep = lambda name: True  # noqa: E731 — device rows are op spans
    else:
        # host-only trace (CPU backend): thunk execution spans carry bare
        # HLO instruction names; dispatch/wait machinery carries pythonic
        # ("$file:line fn") or prose ("Wait for ...", "Foo::Bar") names
        # whose durations OVERLAP the op spans and would corrupt totals.
        keep = lambda name: (  # noqa: E731
            name and " " not in name and "::" not in name
            and not name.startswith("$") and not name.startswith("PjitFunction")
        )

    dur: Dict[str, Tuple[float, int]] = {}
    total = 0.0
    for e in events:
        name = e.get("name", "")
        if not keep(name):
            continue
        d = float(e["dur"])
        t, c = dur.get(name, (0.0, 0))
        dur[name] = (t + d, c + 1)
        total += d
    if len(device_pids) > 1:
        # every device row carries its own copy of an SPMD op's span;
        # report the per-device MEAN of both time AND exec count so
        # ms/step and the flops/bytes scaling downstream (MFU%, GB/s)
        # both describe one chip, not the sum over all chips (advisor r3)
        n = float(len(device_pids))
        dur = {k: (t / n, max(1, round(c / n))) for k, (t, c) in dur.items()}
        total /= n
    return dur, total


def measured_op_table(
    fn: Callable,
    *args: Any,
    steps: int = 3,
    log_dir: Optional[str] = None,
    depth: int = 2,
    peak_flops: float = 197e12,
    **kwargs: Any,
) -> Dict[str, Any]:
    """Run ``steps`` executions of ``jit(fn)(*args)`` under the profiler and
    join measured per-op time with HLO flops/bytes.

    Returns ``{rows, coverage_pct, total_ms_per_step, unattributed}``:

    * ``rows`` — one dict per entry-computation instruction that measured
      nonzero time: ``{name, scope, op, time_ms (per step), flops, bytes,
      mfu_pct, gbps, pct}``, sorted by time.
    * ``unattributed`` — measured device events matching no instruction
      (runtime spans), as ``{name, time_ms}``.
    * ``coverage_pct`` — % of measured device time the rows explain.
    """
    jitted = jax.jit(fn)
    compiled = jitted.lower(*args, **kwargs).compile()
    # warmup outside the trace so compilation never pollutes timing
    out = jitted(*args, **kwargs)
    jax.block_until_ready(out)

    owns_dir = log_dir is None
    if owns_dir:
        log_dir = tempfile.mkdtemp(prefix="apex_tpu_prof_")
    import time as _time

    jax.profiler.start_trace(log_dir)
    try:
        # wall clock spans dispatch -> fence only (NOT the profiler
        # start/stop, which writes trace files); per-op capture overhead
        # stays included, so the number errs slightly pessimistic
        t0 = _time.perf_counter()
        for _ in range(steps):
            out = jitted(*args, **kwargs)
        jax.block_until_ready(out)
        # host-read a leaf: on platforms where block_until_ready returns
        # early (observed on the tunnel transport) a value transfer is the
        # only trustworthy fence
        leaves = jax.tree.leaves(out)
        if leaves:
            jax.device_get(leaves[0])
        wall_ms = (_time.perf_counter() - t0) / steps * 1e3
    finally:
        jax.profiler.stop_trace()

    dur_us, total_us = load_trace_events(log_dir)

    comps, _ = _parse_hlo(compiled.as_text())
    shapes = {i.name: i.type_str for instrs in comps.values() for i in instrs}

    # HLO instruction names are module-unique, so the join spans ALL
    # computations, not just entry — ops inside scan/while bodies (the
    # layer stack of any scan-over-layers model) emit their own trace
    # events per iteration. Container ops (while/call/conditional) are
    # excluded from rows: their spans COVER their bodies' spans and would
    # double-count the attributed total.
    container_ops = {"while", "call", "conditional"}
    all_instrs = {i.name: i for instrs in comps.values() for i in instrs}
    instr_by_name = {
        n: i for n, i in all_instrs.items()
        if i.op not in _SKIP_OPS and i.op not in container_ops
    }
    # container spans COVER their bodies' spans: drop them from the
    # denominator and the unattributed list, or coverage could never
    # approach 100% on loop-dominated (scan-over-layers) programs
    for n, i in all_instrs.items():
        if i.op in container_ops and n in dur_us:
            total_us -= dur_us.pop(n)[0]

    rows: List[Dict[str, Any]] = []
    matched_us = 0.0
    matched_names = set()
    for name, (t_us, count) in dur_us.items():
        ins = instr_by_name.get(name)
        if ins is None:
            continue
        matched_names.add(name)
        matched_us += t_us
        if ins.op == "dot":
            flops = _dot_flops(ins, shapes)
        elif ins.op == "convolution":
            flops = _conv_flops(ins, shapes)
        elif ins.callee:
            flops = _comp_flops(ins.callee, comps, shapes)
        else:
            flops = 0.0
        byts = _nbytes(ins.type_str) + sum(
            _nbytes(shapes.get(o, "")) for o in ins.operands if o in shapes)
        # per-step totals: measured time and executions are summed over
        # all `steps` runs (and all loop iterations within each)
        execs_per_step = count / steps
        flops, byts = flops * execs_per_step, float(byts) * execs_per_step
        parts = [p for p in ins.op_name.split("/") if p] or ["<no-scope>"]
        if parts[0].startswith("jit("):
            parts = parts[1:] or ["<top>"]
        t_s = t_us / 1e6 / steps
        rows.append({
            "name": ins.name,
            "scope": "/".join(parts[:depth]) if parts else "<top>",
            "op": ins.op,
            "count_per_step": execs_per_step,
            "time_ms": t_s * 1e3,
            "flops": flops,
            "bytes": byts,
            "mfu_pct": 100.0 * flops / (t_s * peak_flops) if t_s else 0.0,
            "gbps": byts / t_s / 1e9 if t_s else 0.0,
        })

    rows.sort(key=lambda r: -r["time_ms"])
    total_row_ms = sum(r["time_ms"] for r in rows) or 1.0
    for r in rows:
        r["pct"] = 100.0 * r["time_ms"] / total_row_ms

    unattributed = sorted(
        ({"name": n, "time_ms": d / 1e3 / steps}
         for n, (d, _) in dur_us.items() if n not in matched_names),
        key=lambda r: -r["time_ms"])
    return {
        "rows": rows,
        "unattributed": unattributed,
        "coverage_pct": 100.0 * matched_us / total_us if total_us else 0.0,
        "total_ms_per_step": total_row_ms,
        # host wall clock around the profiled loop (includes trace + async
        # dispatch overhead): the honest step-time denominator when the
        # trace join is partial — attributed time understates the step by
        # 1/coverage, and an empty join leaves the 1.0ms sentinel above
        "wall_ms_per_step": wall_ms,
        "log_dir": log_dir,
        # the exact executable that was measured — downstream joins
        # (monitor.report: wire-byte pricing, cost analysis) read it instead
        # of paying a second lower+compile of the same program
        "compiled": compiled,
    }


def format_measured_table(result: Dict[str, Any], top: int = 25,
                          show_unattributed: int = 5) -> str:
    """Render the measured join like the reference's ``prof/output.py``."""
    rows = result["rows"]
    lines = [
        f"{'name':28s} {'scope':30s} {'op':14s} {'ms/step':>9s} "
        f"{'GFLOP':>9s} {'MB':>9s} {'MFU%':>6s} {'GB/s':>7s} {'%':>5s}",
        "-" * 124,
    ]
    for r in rows[:top]:
        lines.append(
            f"{r['name'][:28]:28s} {r['scope'][:30]:30s} {r['op'][:14]:14s} "
            f"{r['time_ms']:9.3f} {r['flops']/1e9:9.2f} {r['bytes']/1e6:9.1f} "
            f"{r['mfu_pct']:6.1f} {r['gbps']:7.1f} {r['pct']:5.1f}")
    rest = rows[top:]
    if rest:
        lines.append(f"(+{len(rest)} more rows, "
                     f"{sum(r['pct'] for r in rest):.1f}% of attributed time)")
    lines.append(
        f"ATTRIBUTED {result['total_ms_per_step']:.3f} ms/step | trace "
        f"coverage {result['coverage_pct']:.1f}%")
    un = result["unattributed"][:show_unattributed]
    if un:
        lines.append("unattributed device spans: " + ", ".join(
            f"{u['name'][:40]}={u['time_ms']:.3f}ms" for u in un))
    return "\n".join(lines)


def measured_report(fn: Callable, *args: Any, steps: int = 3, top: int = 25,
                    depth: int = 2, peak_flops: float = 197e12,
                    **kwargs: Any) -> str:
    """One command: measured per-op table for a jittable step (printed +
    returned). The measured analogue of :func:`apex_tpu.pyprof.report`."""
    table = format_measured_table(
        measured_op_table(fn, *args, steps=steps, depth=depth,
                          peak_flops=peak_flops, **kwargs), top=top)
    print(table)
    return table

"""Per-op / per-layer attribution report from compiled HLO.

Reference capability: ``apex/pyprof/parse`` + ``apex/pyprof/prof`` — walk a
captured profile, map each kernel back to its layer, attach FLOP/byte
estimates, and render a table (``prof/output.py``).

TPU re-design: the compiled HLO is the ground truth of what actually runs
after XLA fusion — no SQLite scraping needed. Each HLO instruction carries
``metadata={op_name="jit(f)/scope1/scope2/op"}`` where the scopes are
``jax.named_scope`` annotations (:func:`apex_tpu.pyprof.annotate`), so layer
attribution falls out of the same annotation API the reference wraps NVTX
for. FLOPs are computed from dot/convolution shapes (recursing into fusion
subcomputations), bytes from operand+result sizes, and each op gets a
roofline time estimate ``max(flops/peak, bytes/bandwidth)`` — the analogue
of the reference's per-op FLOP formula tables, with the compiler's fused
graph instead of tracing heuristics.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16, "token": 0, "opaque": 0,
}

_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*"
    r"(?P<type>\([^)]*\)|\S+)\s+"  # tuple types contain spaces
    r"(?P<op>[\w\-]+)\(")
_SHAPE_RE = re.compile(r"(?P<dt>\w+)\[(?P<dims>[\d,]*)\]")
_META_RE = re.compile(r'metadata=\{[^}]*op_name="(?P<op_name>[^"]*)"')
_CALLS_RE = re.compile(r"(?:calls|to_apply)=%?(?P<callee>[\w.\-]+)")
_CDIMS_RE = re.compile(r"lhs_contracting_dims=\{(?P<dims>[\d,]*)\}")


def _parse_shape(type_str: str) -> List[Tuple[str, List[int]]]:
    """'(bf16[2,3]{1,0}, f32[4])' or 'bf16[2,3]{1,0}' -> [(dtype, dims)...]"""
    out = []
    for m in _SHAPE_RE.finditer(type_str):
        dims = [int(x) for x in m.group("dims").split(",") if x]
        out.append((m.group("dt"), dims))
    return out


def _nbytes(type_str: str) -> int:
    return sum(
        _DTYPE_BYTES.get(dt, 4) * int(np.prod(dims)) if dims
        else _DTYPE_BYTES.get(dt, 4)
        for dt, dims in _parse_shape(type_str))


@dataclass
class _Instr:
    name: str
    op: str
    type_str: str
    line: str
    op_name: str = ""
    callee: Optional[str] = None
    operands: List[str] = field(default_factory=list)


def _parse_hlo(hlo_text: str) -> Tuple[Dict[str, List[_Instr]], str]:
    """-> ({computation_name: [instrs]}, entry_computation_name)."""
    comps: Dict[str, List[_Instr]] = {}
    entry = ""
    cur: Optional[str] = None
    for line in hlo_text.splitlines():
        header = re.match(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\([^)]*\)\s*->", line)
        if header and not line.lstrip().startswith("ROOT"):
            cur = header.group(2)
            comps[cur] = []
            if header.group(1):
                entry = cur
            continue
        if cur is None:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        ins = _Instr(m.group("name"), m.group("op"), m.group("type"), line)
        meta = _META_RE.search(line)
        if meta:
            ins.op_name = meta.group("op_name")
        calls = _CALLS_RE.search(line)
        if calls:
            ins.callee = calls.group("callee")
        # operand names: %foo references after the opcode's '('
        rest = line[m.end():]
        ins.operands = re.findall(r"%([\w.\-]+)", rest)
        comps[cur].append(ins)
    return comps, entry


def _dot_flops(ins: _Instr, shapes: Dict[str, str]) -> float:
    out = _parse_shape(ins.type_str)
    out_elems = float(np.prod(out[0][1])) if out and out[0][1] else 1.0
    cdims = _CDIMS_RE.search(ins.line)
    csize = 1.0
    if cdims and ins.operands:
        lhs_type = shapes.get(ins.operands[0], "")
        lhs = _parse_shape(lhs_type)
        if lhs:
            dims = lhs[0][1]
            for d in (int(x) for x in cdims.group("dims").split(",") if x):
                if d < len(dims):
                    csize *= dims[d]
    return 2.0 * out_elems * csize


def _conv_flops(ins: _Instr, shapes: Dict[str, str]) -> float:
    # flops = 2 * out_elems * (kernel spatial * in_channels); estimate the
    # multiplier from the rhs (kernel) operand: prod(all dims) / out_channels
    out = _parse_shape(ins.type_str)
    out_elems = float(np.prod(out[0][1])) if out and out[0][1] else 1.0
    mult = 1.0
    if len(ins.operands) >= 2:
        k = _parse_shape(shapes.get(ins.operands[1], ""))
        if k and k[0][1]:
            kd = k[0][1]
            mult = float(np.prod(kd)) / max(kd[-1], 1)  # o is last by default
    return 2.0 * out_elems * mult


def _comp_flops(comp: str, comps: Dict[str, List[_Instr]],
                shapes: Dict[str, str], seen=None) -> float:
    if seen is None:
        seen = set()
    if comp in seen or comp not in comps:
        return 0.0
    seen.add(comp)
    total = 0.0
    for ins in comps[comp]:
        if ins.op == "dot":
            total += _dot_flops(ins, shapes)
        elif ins.op == "convolution":
            total += _conv_flops(ins, shapes)
        elif ins.callee:
            total += _comp_flops(ins.callee, comps, shapes, seen)
    return total


_SKIP_OPS = {"parameter", "constant", "tuple", "get-tuple-element",
             "bitcast", "after-all"}


def op_table(
    fn: Callable,
    *args: Any,
    depth: int = 2,
    peak_flops: float = 197e12,
    hbm_bandwidth: float = 819e9,
    **kwargs: Any,
) -> List[Dict[str, Any]]:
    """Per-op roofline attribution of a jittable function.

    Returns one row per executed HLO instruction of the entry computation
    (fusions counted whole, their inner dots attributed to them):
    ``{scope, op, flops, bytes, est_time_s, bound}``, aggregated up to
    ``depth`` segments of the ``named_scope`` path and sorted by estimated
    time. ``peak_flops`` / ``hbm_bandwidth`` default to TPU v5e spec; pass
    measured numbers for a calibrated roofline.
    """
    lowered = jax.jit(fn).lower(*args, **kwargs)
    hlo = lowered.compile().as_text()
    comps, entry = _parse_hlo(hlo)
    if not entry:
        entry = max(comps, key=lambda c: len(comps[c])) if comps else ""
    shapes = {i.name: i.type_str for instrs in comps.values() for i in instrs}

    rows: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for ins in comps.get(entry, []):
        if ins.op in _SKIP_OPS:
            continue
        if ins.op == "dot":
            flops = _dot_flops(ins, shapes)
        elif ins.op == "convolution":
            flops = _conv_flops(ins, shapes)
        elif ins.callee:
            flops = _comp_flops(ins.callee, comps, shapes)
        else:
            flops = 0.0
        byts = _nbytes(ins.type_str) + sum(
            _nbytes(shapes.get(o, "")) for o in ins.operands
            if o in shapes)
        # scope: drop the jit(...) prefix and the op leaf, keep `depth` segs
        parts = [p for p in ins.op_name.split("/") if p] or ["<no-scope>"]
        if parts[0].startswith("jit("):
            parts = parts[1:] or ["<top>"]
        scope = "/".join(parts[:depth]) if parts else "<top>"
        key = (scope, ins.op)
        row = rows.setdefault(key, {
            "scope": scope, "op": ins.op, "count": 0,
            "flops": 0.0, "bytes": 0.0})
        row["count"] += 1
        row["flops"] += flops
        row["bytes"] += float(byts)

    out = list(rows.values())
    for r in out:
        t_c = r["flops"] / peak_flops if peak_flops else 0.0
        t_m = r["bytes"] / hbm_bandwidth if hbm_bandwidth else 0.0
        r["est_time_s"] = max(t_c, t_m)
        r["bound"] = "compute" if t_c >= t_m else "memory"
    out.sort(key=lambda r: -r["est_time_s"])
    return out


def format_table(rows: List[Dict[str, Any]], top: int = 25) -> str:
    """Render like the reference's ``prof/output.py`` column table."""
    total_t = sum(r["est_time_s"] for r in rows) or 1.0
    lines = [
        f"{'scope':40s} {'op':18s} {'n':>4s} {'GFLOP':>10s} {'MB':>10s} "
        f"{'est_ms':>8s} {'%':>5s} {'bound':>7s}",
        "-" * 108,
    ]
    for r in rows[:top]:
        lines.append(
            f"{r['scope'][:40]:40s} {r['op'][:18]:18s} {r['count']:4d} "
            f"{r['flops']/1e9:10.2f} {r['bytes']/1e6:10.1f} "
            f"{r['est_time_s']*1e3:8.3f} "
            f"{100*r['est_time_s']/total_t:5.1f} {r['bound']:>7s}")
    rest = rows[top:]
    if rest:
        lines.append(
            f"(+{len(rest)} more rows, "
            f"{100*sum(r['est_time_s'] for r in rest)/total_t:.1f}% of est time)")
    lines.append(
        f"TOTAL est {total_t*1e3:.2f} ms | "
        f"{sum(r['flops'] for r in rows)/1e9:.1f} GFLOP | "
        f"{sum(r['bytes'] for r in rows)/1e6:.1f} MB")
    return "\n".join(lines)


def report(fn: Callable, *args: Any, depth: int = 2, top: int = 25,
           peak_flops: float = 197e12, hbm_bandwidth: float = 819e9,
           **kwargs: Any) -> str:
    """One-command per-op report for a jittable step (printed + returned)."""
    table = format_table(
        op_table(fn, *args, depth=depth, peak_flops=peak_flops,
                 hbm_bandwidth=hbm_bandwidth, **kwargs), top=top)
    print(table)
    return table

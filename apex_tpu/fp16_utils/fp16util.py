"""Pytree analogues of ``apex/fp16_utils/fp16util.py``.

The reference walks ``nn.Module`` trees (``convert_module:44``,
``BN_convert_float:22``) and keeps parallel ``model_params`` /
``master_params`` lists (``prep_param_lists:90``). Here "model" = a param
pytree; norm params are recognized by the same path heuristic the amp layer
uses, and master/model are two pytrees related by a pure cast.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.frontend import _path_str, default_norm_predicate

Pytree = Any


def convert_network(
    params: Pytree,
    dtype,
    is_norm_param: Callable[[str], bool] = default_norm_predicate,
) -> Pytree:
    """Cast float params to ``dtype``, keeping norm params fp32
    (ref ``convert_network:60-72`` — BN stays fp32)."""

    def leaf(path, x):
        if not jnp.issubdtype(jnp.result_type(x), jnp.floating):
            return x
        if is_norm_param(_path_str(path)):
            return x.astype(jnp.float32)
        return x.astype(dtype)

    return jax.tree_util.tree_map_with_path(leaf, params)


def network_to_half(params: Pytree, half_dtype=jnp.bfloat16) -> Pytree:
    """Ref ``network_to_half:35`` (tofp16 + BN_convert_float). bf16 is the
    TPU half type; pass ``jnp.float16`` for literal parity."""
    return convert_network(params, half_dtype)


def prep_param_lists(params: Pytree, flat_master: bool = False):
    """-> (model_params, master_params): fp32 master copies of the (half)
    model params (ref ``prep_param_lists:90-135``). ``flat_master`` flattens
    the masters into one fp32 vector (ref flatten path); the structured form
    is the TPU-native default."""
    masters = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.float32)
        if jnp.issubdtype(jnp.result_type(x), jnp.floating) else x,
        params,
    )
    if flat_master:
        leaves = [x.reshape(-1) for x in jax.tree_util.tree_leaves(masters)]
        masters = jnp.concatenate(leaves) if leaves else jnp.zeros((0,))
    return params, masters


def model_grads_to_master_grads(model_grads: Pytree,
                                flat_master: bool = False) -> Pytree:
    """fp16 grads -> fp32 master grads (ref :136-156)."""
    g32 = jax.tree_util.tree_map(lambda g: g.astype(jnp.float32), model_grads)
    if flat_master:
        leaves = [x.reshape(-1) for x in jax.tree_util.tree_leaves(g32)]
        return jnp.concatenate(leaves) if leaves else jnp.zeros((0,))
    return g32


def master_params_to_model_params(master_params: Pytree, model_like: Pytree,
                                  ) -> Pytree:
    """fp32 masters -> model-dtype params (ref :158-175); ``model_like``
    supplies the target dtypes."""
    return jax.tree_util.tree_map(
        lambda m, p: m.astype(p.dtype), master_params, model_like)


def clip_grad_norm(grads: Pytree, max_norm: float,
                   norm_type: float = 2.0) -> Tuple[Pytree, jnp.ndarray]:
    """Global-norm clip; returns ``(clipped_grads, total_norm)``
    (ref ``clip_grad_norm:181-214`` — torch semantics: scale by
    max_norm/(norm+1e-6) when over)."""
    leaves = jax.tree_util.tree_leaves(grads)
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in leaves]))
    elif norm_type == 2.0:
        total = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                             for g in leaves))
    else:
        total = sum(jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type)
                    for g in leaves) ** (1.0 / norm_type)
    coef = jnp.minimum(1.0, max_norm / (total + 1e-6))
    return jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * coef).astype(g.dtype), grads
    ), total


def to_python_float(t) -> float:
    """Ref :176-180."""
    return float(t)

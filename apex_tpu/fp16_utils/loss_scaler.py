"""Legacy loss scalers (ref ``apex/fp16_utils/loss_scaler.py:7,82``).

``LossScaler`` = static scale; ``DynamicLossScaler`` = the pre-amp dynamic
policy (×2 every ``scale_window`` clean steps, ÷2 on overflow after a
cooldown). Thin adapters over the functional ``apex_tpu.amp.scaler`` so the
legacy constructor surface works; state is still an explicit pytree.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScaler as _ModernScaler
from apex_tpu.amp.scaler import LossScalerState


class LossScaler(_ModernScaler):
    """Static scaler (ref :7-80): ``loss_scale`` fixed, ``update_scale`` no-op."""

    def __init__(self, scale: float = 1.0):
        super().__init__(loss_scale=float(scale))

    # legacy attribute name
    @property
    def cur_scale(self) -> float:
        return self._init_scale


class DynamicLossScaler(_ModernScaler):
    """Dynamic scaler (ref :82-180): ``init_scale``/``scale_factor``/
    ``scale_window`` legacy knobs."""

    def __init__(self, init_scale: float = 2.0 ** 32,
                 scale_factor: float = 2.0, scale_window: int = 1000):
        super().__init__("dynamic", init_scale=init_scale,
                         scale_factor=scale_factor, scale_window=scale_window)

    @staticmethod
    def has_overflow(grads) -> jnp.ndarray:
        """Ref ``has_overflow``/``_has_inf_or_nan`` (:97-118): traced bool."""
        import jax

        leaves = jax.tree_util.tree_leaves(grads)
        if not leaves:
            return jnp.asarray(False)
        return ~jnp.stack([jnp.all(jnp.isfinite(g)) for g in leaves]).all()

"""Legacy manual mixed-precision helpers (ref ``apex/fp16_utils``).

The reference predates ``apex.amp``: module-tree casting helpers
(``fp16util.py:35-175``), master-param bookkeeping, and the ``FP16_Optimizer``
wrapper (``fp16_optimizer.py:13``) with static/dynamic loss scaling
(``loss_scaler.py:7,82``). The modern path is ``apex_tpu.amp``; this package
keeps the legacy API shape for capability parity, implemented over the same
pure-pytree machinery.
"""

from apex_tpu.fp16_utils.fp16util import (  # noqa: F401
    clip_grad_norm,
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    network_to_half,
    prep_param_lists,
    to_python_float,
)
from apex_tpu.fp16_utils.fp16_optimizer import FP16_Optimizer  # noqa: F401
from apex_tpu.fp16_utils.loss_scaler import (  # noqa: F401
    DynamicLossScaler,
    LossScaler,
)

__all__ = [
    "network_to_half",
    "convert_network",
    "prep_param_lists",
    "model_grads_to_master_grads",
    "master_params_to_model_params",
    "clip_grad_norm",
    "to_python_float",
    "FP16_Optimizer",
    "LossScaler",
    "DynamicLossScaler",
]

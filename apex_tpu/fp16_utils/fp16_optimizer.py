"""Legacy ``FP16_Optimizer`` wrapper (ref ``apex/fp16_utils/fp16_optimizer.py:13``).

Wraps any optax-style transform with fp32 master weights + a (static or
dynamic) loss scaler: scale loss, backward in half, unscale into fp32 master
grads, skip the step on overflow, copy masters back to model dtype — the
flow ``apex.amp`` O2 later absorbed. Functional: all state in
:class:`FP16OptimizerState`.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from apex_tpu.amp.scaler import LossScalerState
from apex_tpu.fp16_utils.fp16util import (
    clip_grad_norm,
    master_params_to_model_params,
)
from apex_tpu.fp16_utils.loss_scaler import DynamicLossScaler, LossScaler

Pytree = Any


class FP16OptimizerState(NamedTuple):
    master_params: Pytree  # fp32
    inner_state: Any
    scaler: LossScalerState


class FP16_Optimizer:
    """Ref constructor ``FP16_Optimizer(init_optimizer, static_loss_scale=1.0,
    dynamic_loss_scale=False, ...)``. ``optimizer`` is an optax-style
    transform (init/update)."""

    def __init__(self, optimizer, static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: Optional[dict] = None):
        self.optimizer = optimizer
        if dynamic_loss_scale:
            self.loss_scaler = DynamicLossScaler(**(dynamic_loss_args or {}))
        else:
            self.loss_scaler = LossScaler(static_loss_scale)

    def init(self, model_params: Pytree) -> FP16OptimizerState:
        masters = jax.tree_util.tree_map(
            lambda x: x.astype(jnp.float32)
            if jnp.issubdtype(jnp.result_type(x), jnp.floating) else x,
            model_params)
        return FP16OptimizerState(
            master_params=masters,
            inner_state=self.optimizer.init(masters),
            scaler=self.loss_scaler.init_state())

    def scale_loss(self, loss, state: FP16OptimizerState):
        """Ref ``backward`` entry: caller differentiates the scaled loss."""
        return self.loss_scaler.scale_loss(loss, state.scaler)

    def step(
        self,
        model_grads: Pytree,
        state: FP16OptimizerState,
        max_grad_norm: Optional[float] = None,
    ) -> Tuple[Pytree, FP16OptimizerState, jnp.ndarray]:
        """unscale → (clip) → inner step on fp32 masters.

        Returns ``(master_params, new_state, skipped)`` — the fp32 masters,
        NOT model-dtype params; call :meth:`model_params` to refresh the
        half-precision model copy (the ref's explicit
        ``_master_params_to_model_params`` pass). ``skipped`` is the traced
        overflow flag (ref "skip step on overflow", fp16_optimizer.py:160-200).
        """
        grads32, found_inf = self.loss_scaler.unscale(
            model_grads, state.scaler)
        if max_grad_norm is not None:
            grads32, _ = clip_grad_norm(grads32, max_grad_norm)
        new_scaler, skipped = self.loss_scaler.update_scale(
            state.scaler, found_inf)
        updates, new_inner = self.optimizer.update(
            grads32, state.inner_state, state.master_params)
        new_masters = jax.tree_util.tree_map(
            lambda p, u: p + u, state.master_params, updates)
        # skip-step: keep old masters/inner state on overflow
        new_masters, new_inner = jax.tree_util.tree_map(
            lambda new, old: jnp.where(skipped, old, new),
            (new_masters, new_inner), (state.master_params, state.inner_state))
        new_state = FP16OptimizerState(new_masters, new_inner, new_scaler)
        return new_masters, new_state, skipped

    def model_params(self, state: FP16OptimizerState,
                     model_like: Pytree) -> Pytree:
        """fp32 masters viewed in model dtype (ref
        ``_master_params_to_model_params``)."""
        return master_params_to_model_params(state.master_params, model_like)

    # -- checkpointing (ref state_dict :209-270) ---------------------------
    def state_dict(self, state: FP16OptimizerState) -> dict:
        return {
            "loss_scaler": self.loss_scaler.state_dict(state.scaler),
            "master_params": state.master_params,
            "inner_state": state.inner_state,
        }

    def load_state_dict(self, d: dict) -> FP16OptimizerState:
        return FP16OptimizerState(
            master_params=d["master_params"],
            inner_state=d["inner_state"],
            scaler=self.loss_scaler.load_state_dict(d["loss_scaler"]))

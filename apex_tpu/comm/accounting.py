"""Collective accounting — bytes-on-wire from compiled HLO.

``tests/test_collective_counts.py`` regression-guards collective *counts*;
counts cannot prove a compression claim (one int8 all-to-all counts the
same as one fp32 all-reduce). This module parses the compiled HLO text and
prices every collective in bytes, so "int8 gradient allreduce moves ≥3.5×
fewer bytes than fp32" is asserted from the program XLA actually emitted,
not claimed from the Python source.

Pricing uses the standard ring-algorithm wire model, per device, for a
collective whose *result* occupies ``b`` bytes in a group of ``W``:

===================  =======================================================
``all-reduce``       ``2·b·(W-1)/W``  (reduce-scatter + all-gather phases)
``all-gather``       ``b·(W-1)/W``    (receives every other rank's shard)
``reduce-scatter``   ``b·(W-1)``      (result is the 1/W shard; the full
                                      operand is ``b·W``)
``all-to-all``       ``b·(W-1)/W``    (keeps its own chunk)
``collective-permute``  ``b``         (one hop per element)
===================  =======================================================

The absolute numbers are a model (real ICI topologies do better or worse
by constant factors); *ratios between two programs on the same mesh* — the
quantity the tests assert — are exact, because the model is linear in
bytes. Group sizes come from each op's ``replica_groups``; async pairs
(``all-reduce-start``/``-done``) are counted once at the ``-start``.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Optional

COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e5m2": 1, "f8e4m3fn": 1, "f8e4m3": 1,
    "f8e4m3b11fnuz": 1, "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# "<dtype>[<dims>]" shape tokens inside a result type (tuple or array)
_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]+[a-z0-9]*|pred)\[([0-9,]*)\]")
# "<kind>(" right after the result type — definitions only: '-done'
# completions don't match ('-done' is not consumed before the '('), and
# get-tuple-element lines reference "%all-to-all.4)" without a following '('
_OP_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(?:-start)?\(")
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([0-9,]+)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")


@dataclasses.dataclass
class CollectiveReport:
    """Per-kind tallies plus the headline ``wire_bytes`` total."""

    counts: Dict[str, int]
    result_bytes: Dict[str, int]
    wire_bytes_by_kind: Dict[str, float]

    @property
    def wire_bytes(self) -> float:
        return sum(self.wire_bytes_by_kind.values())

    def __repr__(self):  # compact, for assertion messages
        rows = ", ".join(
            f"{k}: n={self.counts[k]} wire={self.wire_bytes_by_kind[k]:.0f}"
            for k in COLLECTIVE_KINDS if self.counts[k])
        return f"CollectiveReport({rows or 'no collectives'})"


def _result_bytes(type_str: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            raise ValueError(f"unknown HLO dtype {dtype!r} in {type_str!r}")
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _group_size(line: str, default: int) -> int:
    m = _GROUPS_LIST_RE.search(line)
    if m:
        return len(m.group(1).split(","))
    m = _GROUPS_IOTA_RE.search(line)
    if m:  # iota form [num_groups, group_size]<=[...]
        return int(m.group(2))
    return default


def _wire_cost(kind: str, b: float, w: int) -> float:
    if kind == "collective-permute":
        # one hop per element; prints source_target_pairs, not groups
        return float(b)
    if w <= 1:
        return 0.0
    if kind == "all-reduce":
        return 2.0 * b * (w - 1) / w
    if kind == "all-gather":
        return b * (w - 1) / w
    if kind == "reduce-scatter":
        return float(b) * (w - 1)
    if kind == "all-to-all":
        return b * (w - 1) / w
    return float(b)  # collective-permute: one hop


def collective_report(hlo, default_group_size: Optional[int] = None
                      ) -> CollectiveReport:
    """Price the collectives of a compiled program.

    ``hlo``: HLO text, or anything with ``.as_text()`` (a
    ``jax.stages.Compiled``). ``default_group_size``: group size used when
    an op prints no ``replica_groups`` (rare; flat single-group programs).
    """
    text = hlo if isinstance(hlo, str) else hlo.as_text()
    counts = {k: 0 for k in COLLECTIVE_KINDS}
    rbytes = {k: 0 for k in COLLECTIVE_KINDS}
    wire = {k: 0.0 for k in COLLECTIVE_KINDS}
    for line in text.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        pre = line[: m.start()]
        if " = " not in pre:
            continue  # not a definition line
        kind = m.group(1)
        # result type = everything between the assignment and the op name
        # (tuple-form all-to-all prints "/*index=N*/" comments in there —
        # the shape tokenizer skips them)
        b = _result_bytes(pre.rsplit(" = ", 1)[1])
        w = _group_size(line, default_group_size or 1)
        counts[kind] += 1
        rbytes[kind] += b
        wire[kind] += _wire_cost(kind, b, w)
    return CollectiveReport(counts=counts, result_bytes=rbytes,
                            wire_bytes_by_kind=wire)


def wire_bytes(hlo, default_group_size: Optional[int] = None) -> float:
    """Total modeled bytes-on-wire per device for one execution."""
    return collective_report(hlo, default_group_size).wire_bytes

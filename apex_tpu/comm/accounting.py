"""Collective accounting — bytes-on-wire from compiled HLO.

``tests/test_collective_counts.py`` regression-guards collective *counts*;
counts cannot prove a compression claim (one int8 all-to-all counts the
same as one fp32 all-reduce). This module parses the compiled HLO text and
prices every collective in bytes, so "int8 gradient allreduce moves ≥3.5×
fewer bytes than fp32" is asserted from the program XLA actually emitted,
not claimed from the Python source.

Pricing uses the standard ring-algorithm wire model, per device, for a
collective whose *result* occupies ``b`` bytes in a group of ``W``:

===================  =======================================================
``all-reduce``       ``2·b·(W-1)/W``  (reduce-scatter + all-gather phases)
``all-gather``       ``b·(W-1)/W``    (receives every other rank's shard)
``reduce-scatter``   ``b·(W-1)``      (result is the 1/W shard; the full
                                      operand is ``b·W``)
``all-to-all``       ``b·(W-1)/W``    (keeps its own chunk)
``collective-permute``  ``b``         (one hop per element)
===================  =======================================================

The absolute numbers are a model (real ICI topologies do better or worse
by constant factors); *ratios between two programs on the same mesh* — the
quantity the tests assert — are exact, because the model is linear in
bytes. Group sizes come from each op's ``replica_groups``.

Async pairs (``collective-permute-start``/``-done`` etc. — what the TPU
latency-hiding scheduler emits, and what the :mod:`overlap` decomposition
makes common) are counted once at the ``-start`` and priced from the
``-start``'s OPERANDS: an async start's *result* type is a tuple aliasing
the input buffer next to the output (plus ``u32[]`` context scalars), so
pricing it like a sync result would double-charge every async collective.

:func:`overlap_report` is the comm/compute-overlap prover built on the
same parsed HLO: it pairs each ``collective-permute-start`` with its
``-done`` and counts ``dot``\\ s *scheduled inside the window* (compiled
TPU modules print in schedule order), and for pre-schedule/CPU modules —
which emit synchronous ``collective-permute`` — it falls back to a
def-use reachability check: a hop counts as hideable when some ``dot`` in
the same computation neither feeds it nor consumes it, i.e. a
latency-hiding scheduler is free to run the two concurrently. This is the
repo's established prove-it-from-the-HLO methodology applied to overlap
(``tests/test_collective_counts.py::assert_overlapped``).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Optional

from apex_tpu.analyze.hlo import (
    as_text,
    dependency_graph,
    parse_computations,
    reach,
)

COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e5m2": 1, "f8e4m3fn": 1, "f8e4m3": 1,
    "f8e4m3b11fnuz": 1, "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# "<dtype>[<dims>]" shape tokens inside a result type (tuple or array)
_SHAPE_RE = re.compile(r"\b([a-z]+[0-9]+[a-z0-9]*|pred)\[([0-9,]*)\]")
# "<kind>(" right after the result type — definitions only: '-done'
# completions don't match ('-done' is not consumed before the '('), and
# get-tuple-element lines reference "%all-to-all.4)" without a following '('
_OP_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(-start)?\(")
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{\{([0-9,]+)\}")
_GROUPS_IOTA_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]")


@dataclasses.dataclass
class CollectiveReport:
    """Per-kind tallies plus the headline ``wire_bytes`` total."""

    counts: Dict[str, int]
    result_bytes: Dict[str, int]
    wire_bytes_by_kind: Dict[str, float]

    @property
    def wire_bytes(self) -> float:
        return sum(self.wire_bytes_by_kind.values())

    def __repr__(self):  # compact, for assertion messages
        rows = ", ".join(
            f"{k}: n={self.counts[k]} wire={self.wire_bytes_by_kind[k]:.0f}"
            for k in COLLECTIVE_KINDS if self.counts[k])
        return f"CollectiveReport({rows or 'no collectives'})"


def _result_bytes(type_str: str) -> int:
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            raise ValueError(f"unknown HLO dtype {dtype!r} in {type_str!r}")
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _paren_span(line: str, open_idx: int) -> str:
    """The text inside the balanced parens opening at ``open_idx``."""
    depth = 0
    for i in range(open_idx, len(line)):
        if line[i] == "(":
            depth += 1
        elif line[i] == ")":
            depth -= 1
            if depth == 0:
                return line[open_idx + 1 : i]
    return line[open_idx + 1 :]  # unterminated (truncated dump): best effort


def _group_size(line: str, default: int) -> int:
    m = _GROUPS_LIST_RE.search(line)
    if m:
        return len(m.group(1).split(","))
    m = _GROUPS_IOTA_RE.search(line)
    if m:  # iota form [num_groups, group_size]<=[...]
        return int(m.group(2))
    return default


def _async_result_bytes(kind: str, b_op: int, w: int) -> int:
    """Reconstruct a sync op's result bytes from an async ``-start``'s
    OPERAND bytes (a start's result tuple aliases the operand next to the
    output + u32 contexts — pricing it directly would double-charge).
    One rule, shared with ``analyze.collectives``."""
    if kind == "all-gather":
        return b_op * w  # sync result = the gathered buffer
    if kind == "reduce-scatter":
        return -(-b_op // w) if w else b_op  # sync result = one shard
    return b_op  # all-reduce / all-to-all / collective-permute


def _wire_cost(kind: str, b: float, w: int) -> float:
    if kind == "collective-permute":
        # one hop per element; prints source_target_pairs, not groups
        return float(b)
    if w <= 1:
        return 0.0
    if kind == "all-reduce":
        return 2.0 * b * (w - 1) / w
    if kind == "all-gather":
        return b * (w - 1) / w
    if kind == "reduce-scatter":
        return float(b) * (w - 1)
    if kind == "all-to-all":
        return b * (w - 1) / w
    return float(b)  # collective-permute: one hop


def collective_report(hlo, default_group_size: Optional[int] = None
                      ) -> CollectiveReport:
    """Price the collectives of a compiled program.

    ``hlo``: HLO text, or anything with ``.as_text()`` (a
    ``jax.stages.Compiled``). ``default_group_size``: group size used when
    an op prints no ``replica_groups`` (rare; flat single-group programs).
    """
    text = as_text(hlo)
    counts = {k: 0 for k in COLLECTIVE_KINDS}
    rbytes = {k: 0 for k in COLLECTIVE_KINDS}
    wire = {k: 0.0 for k in COLLECTIVE_KINDS}
    for line in text.splitlines():
        m = _OP_RE.search(line)
        if not m:
            continue
        pre = line[: m.start()]
        if " = " not in pre:
            continue  # not a definition line
        kind = m.group(1)
        w = _group_size(line, default_group_size or 1)
        if m.group(2):
            # async "-start": its RESULT is a tuple aliasing the operand
            # buffer next to the output (+ u32[] context scalars) — pricing
            # it would double-charge. Price from the operand types instead
            # and reconstruct the sync op's result bytes.
            b_op = _result_bytes(_paren_span(line, m.end() - 1))
            b = _async_result_bytes(kind, b_op, w)
        else:
            # result type = everything between the assignment and the op
            # name (tuple-form all-to-all prints "/*index=N*/" comments in
            # there — the shape tokenizer skips them)
            b = _result_bytes(pre.rsplit(" = ", 1)[1])
        counts[kind] += 1
        rbytes[kind] += b
        wire[kind] += _wire_cost(kind, b, w)
    return CollectiveReport(counts=counts, result_bytes=rbytes,
                            wire_bytes_by_kind=wire)


def wire_bytes(hlo, default_group_size: Optional[int] = None) -> float:
    """Total modeled bytes-on-wire per device for one execution."""
    return collective_report(hlo, default_group_size).wire_bytes


# ---------------------------------------------------------------------------
# overlap proving — is the collective latency hidden behind matmuls?

# instruction/operand/computation-walk machinery lives in analyze.hlo
# (the one shared HLO normalization + parser); kept as module aliases for
# the existing consumers of these names
from apex_tpu.analyze.hlo import (  # noqa: E402
    CALLED_RE as _CALLED_RE,
    OPERAND_RE as _OPERAND_RE,
)

_parse_computations = parse_computations


@dataclasses.dataclass
class OverlapReport:
    """Comm/compute overlap evidence read off one HLO module.

    ``async_pairs`` / ``async_hidden``: ``collective-permute-start``/
    ``-done`` pairs, and how many have ≥1 ``dot`` *scheduled inside the
    start→done window* (post-schedule TPU modules print in schedule order
    — a dot in the window executes while the permute is in flight: proof).

    ``sync_permutes`` / ``sync_hidden``: synchronous ``collective-permute``
    ops (pre-schedule or CPU modules), and how many have ≥1 ``dot`` in the
    same computation that neither feeds them nor consumes them — the
    data-independence a latency-hiding scheduler needs to overlap the two
    (eligibility, not proof; the async numbers are the proof).

    ``hidden_wire_bytes`` / ``exposed_wire_bytes``: the permute traffic
    split by that evidence — the decomposition's goal is driving the
    exposed share to ~0 while ``collective_report`` shows total bytes
    unchanged.
    """

    async_pairs: int = 0
    async_hidden: int = 0
    sync_permutes: int = 0
    sync_hidden: int = 0
    dots: int = 0
    hidden_wire_bytes: float = 0.0
    exposed_wire_bytes: float = 0.0

    @property
    def permutes(self) -> int:
        return self.async_pairs + self.sync_permutes

    @property
    def hidden(self) -> int:
        return self.async_hidden + self.sync_hidden

    @property
    def hidden_fraction(self) -> float:
        total = self.hidden_wire_bytes + self.exposed_wire_bytes
        return self.hidden_wire_bytes / total if total else 0.0

    def __repr__(self):
        return (f"OverlapReport(async {self.async_hidden}/{self.async_pairs}"
                f" hidden, sync {self.sync_hidden}/{self.sync_permutes}"
                f" overlappable, dots={self.dots}, hidden_bytes="
                f"{self.hidden_wire_bytes:.0f}, exposed_bytes="
                f"{self.exposed_wire_bytes:.0f})")


def _dot_bearing(comps) -> set:
    """Names of computations that (transitively) execute a ``dot``."""
    direct = {c for c, instrs in comps.items()
              if any(op == "dot" for _, op, _ in instrs)}
    changed = True
    while changed:
        changed = False
        for c, instrs in comps.items():
            if c in direct:
                continue
            for _, _, line in instrs:
                if any(callee in direct
                       for callee in _CALLED_RE.findall(line)):
                    direct.add(c)
                    changed = True
                    break
    return direct


def _is_dot_like(op: str, line: str, dot_comps: set) -> bool:
    if op == "dot":
        return True
    return any(callee in dot_comps for callee in _CALLED_RE.findall(line))


def overlap_report(hlo) -> OverlapReport:
    """Measure how much ``collective-permute`` traffic travels behind a
    ``dot`` (see :class:`OverlapReport`). ``hlo``: text or anything with
    ``.as_text()``. Async pairs are judged by schedule position, sync
    permutes by def-use independence within their computation."""
    text = as_text(hlo)
    comps = parse_computations(text)
    dot_comps = _dot_bearing(comps)
    rep = OverlapReport()
    for comp, instrs in comps.items():
        # def-use adjacency (operand -> user), same computation only —
        # the shared analyze.hlo walk (exposed_report uses the same one)
        _index, deps, users = dependency_graph(instrs)
        dot_idx = [i for i, (name, op, line) in enumerate(instrs)
                   if _is_dot_like(op, line, dot_comps)]
        rep.dots += len(dot_idx)
        _reach = reach

        for i, (name, op, line) in enumerate(instrs):
            if op == "collective-permute-start":
                open_idx = line.index("collective-permute-start(") \
                    + len("collective-permute-start")
                b = float(_result_bytes(_paren_span(line, open_idx)))
                done = next((j for j, (n2, op2, l2) in enumerate(instrs)
                             if op2 == "collective-permute-done"
                             and name in _OPERAND_RE.findall(
                                 l2.split(" = ", 1)[1])), None)
                rep.async_pairs += 1
                if done is not None and any(i < d < done for d in dot_idx):
                    rep.async_hidden += 1
                    rep.hidden_wire_bytes += b
                else:
                    rep.exposed_wire_bytes += b
            elif op == "collective-permute":
                pre = line.split(" = ", 1)[1]
                open_idx = pre.index("collective-permute(")
                b = float(_result_bytes(pre[:open_idx]))
                rep.sync_permutes += 1
                blocked = _reach(name, users) | _reach(name, deps) | {name}
                if any(instrs[d][0] not in blocked for d in dot_idx):
                    rep.sync_hidden += 1
                    rep.hidden_wire_bytes += b
                else:
                    rep.exposed_wire_bytes += b
    return rep

"""Error-feedback residual state for compressed gradient collectives.

The int8 wire discards up to half a quantization step per element per
iteration; over thousands of steps that bias is what separates "compressed
allreduce converges" from "compressed allreduce plateaus". Error feedback
(Seide et al.'s 1-bit SGD trick, standard in the EQuARX/PowerSGD
literature) stores the compression error ``e = c - dq(q(c))`` and adds it
to the next step's gradient before compressing — the error telescopes
instead of accumulating, restoring convergence to within the tolerance of
the uncompressed run (``tests/test_comm.py`` pins this on the GPT
fixture).

The residual is a pytree shaped like the gradients (one fp32 leaf per
grad leaf), carried through the train step exactly like the loss-scaler
state: a pure value in, a pure value out, ``state_dict``/
``load_state_dict`` for checkpoints (mirroring ``fp16_utils.loss_scaler``
— resuming WITHOUT the residual silently re-biases the first steps, so it
belongs in the checkpoint).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any


def init_error_feedback(grads_template: Pytree) -> Pytree:
    """Zero residuals, one fp32 leaf per gradient leaf. ``grads_template``
    may be the gradients themselves or any like-structured pytree (e.g.
    the params)."""
    return jax.tree_util.tree_map(
        lambda g: jnp.zeros(jnp.shape(g), jnp.float32), grads_template)


def state_dict(residual: Pytree) -> Dict[str, Any]:
    """Flat, revision-stable serialization (the loss-scaler state_dict
    pattern): leaves keyed by flat index + the treedef string so a resume
    against different code fails loudly instead of mis-binding."""
    leaves, treedef = jax.tree_util.tree_flatten(residual)
    return {
        "treedef": str(treedef),
        "leaves": {str(i): np.asarray(x) for i, x in enumerate(leaves)},
    }


def load_state_dict(residual_template: Pytree, d: Dict[str, Any]) -> Pytree:
    """Restore onto the live structure; validates the stored treedef."""
    leaves, treedef = jax.tree_util.tree_flatten(residual_template)
    if d.get("treedef") is not None and d["treedef"] != str(treedef):
        raise ValueError(
            "error-feedback state does not match the live gradient "
            f"structure:\n  saved: {d['treedef']}\n  live:  {treedef}")
    if len(d["leaves"]) != len(leaves):
        raise ValueError(
            f"error-feedback state has {len(d['leaves'])} leaves, live "
            f"structure has {len(leaves)}")
    new = [jnp.asarray(d["leaves"][str(i)], leaves[i].dtype)
           for i in range(len(leaves))]
    for got, want in zip(new, leaves):
        if got.shape != want.shape:
            raise ValueError(
                f"error-feedback leaf shape mismatch: saved {got.shape}, "
                f"live {want.shape}")
    return jax.tree_util.tree_unflatten(treedef, new)

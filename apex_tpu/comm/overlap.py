"""Decomposed collective matmuls — comm/compute overlap by construction.

Reference: the reference Apex hides its tensor-parallel collective latency
by hand: ``LinearWithGradAccumulationAndAsyncAllreduce`` launches the
input-grad all-reduce on a side stream and overlaps it with the dW GEMM
(``apex/transformer/tensor_parallel/layers.py:217-269``). Our rebuild's
layers note (``tensor_parallel/layers.py``) punted that job to XLA's
latency-hiding scheduler — which works for *independent* ops but cannot
overlap a **dependent** collective→matmul chain: ``all_gather(x) @ w`` is
one all-gather every FLOP waits on. The fix (Wang et al., "Overlap
Communication with Dependent Computation via Decomposition",
arXiv:2305.06942 — productionized as XLA:TPU's collective-matmul pass —
and the MLPerf TPU-pod playbook, arXiv:1909.09756) is to decompose the
collective into a ``ppermute`` ring and interleave one partial GEMM with
each hop, so every hop travels behind a matmul that does not depend on it.

Three ops, each a ``custom_vjp`` whose backward rides decomposed rings too:

``all_gather_matmul(x, w)``
    ``all_gather(x, gather_axis) @ w`` — the Megatron-SP entry ``g``
    fused with the column-parallel GEMM. Ring all-gather: at step ``t``
    the shard from rank ``idx+t`` arrives and its partial GEMM lands in
    the output slice while the next hop is already in flight.
    Unidirectional (W-1 sequential hops) or bidirectional (two
    counter-rotating streams, ⌈(W-1)/2⌉ sequential hops — both ICI
    directions busy). Exact: the gathered dim is non-contracting, so the
    decomposition reorders no floating-point reduction.

``matmul_reduce_scatter(x, w)``
    ``reduce_scatter(x @ w, scatter_axis)`` — the Megatron-SP exit ``ḡ``
    fused with the row-parallel GEMM. The accumulator for output shard
    ``d`` starts at rank ``d+1`` and rides the ring once; each rank adds
    its partial GEMM for the resident shard, so the hop carrying the
    previous accumulator overlaps the next partial GEMM. Matches the
    monolithic path to fp addition-reorder tolerance (the per-shard sum
    is associated in ring order instead of XLA's).

``matmul_all_reduce(x, w)``
    ``psum(x @ w)`` — the plain (non-SP) row-parallel exit: the
    reduce-scatter ring above followed by a ppermute ring broadcast.
    Backward is purely local (the psum transpose), exactly like the
    monolithic path.

Backward overlap: ``all_gather_matmul``'s dX is a ``matmul_reduce_scatter``
ring and its dW re-gathers ``x`` through a second ring with one partial dW
GEMM per hop (the reference's async-allreduce trick, generalized);
``matmul_reduce_scatter``'s backward runs ONE ring over the output
cotangent computing both dX slices and dW partials per hop.

Because the chip tunnel is unreliable, overlap here is *provable from the
compiled HLO* rather than claimed from a profile:
:func:`apex_tpu.comm.accounting.overlap_report` checks async
``collective-permute-start``/``-done`` pairs with ``dot``\\ s scheduled
inside the window (TPU) or ring hops with data-independent ``dot``\\ s a
latency-hiding scheduler may overlap (pre-schedule/CPU HLO), and the
``*_wire_bytes`` models below agree op-for-op with what
``accounting.collective_report`` prices on the same program. Each ring is
wire-byte-neutral — ``(W-1)`` hops of one shard equal the monolithic
collective's ring cost exactly. One deliberate exception program-wide:
``all_gather_matmul``'s backward re-gathers its input for dW (the
Megatron-SP backward recipe — shard-sized residuals instead of storing
the gathered activation), so under full-remat training, which ALSO
replays the forward ring, the program pays one extra input gather per
column layer (~10% on the flagship; ``benchmarks/bench_overlap.py``
reports both totals) — bytes traded for activation memory, and hops that
all travel behind GEMMs regardless.

``matmul_param_gather(x, w_shard)``
    ``x @ all_gather(w_shard, axis=-1)`` — the same decomposition in **FSDP
    position** (arXiv:2004.13336's weight-update sharding taken to ZeRO-3):
    the *weight* is what is sharded (each dp rank owns a column shard), the
    activation is resident, and the gather ring hops weight shards while
    each hop's partial GEMM lands in an output column slice. Backward is
    the classic FSDP pair: dX **re-gathers** the weight through a second
    ring (re-materialize — the shard is the residual, the full weight is
    never saved: reshard-after-forward by construction) while dW rides a
    travelling-accumulator ring that reduce-scatters the dp-summed weight
    gradient straight into shard layout. The two backward rings rotate in
    opposite directions, so both ICI directions carry payload.

Wired in via ``ColumnParallelLinear``/``RowParallelLinear``/
``column_parallel_linear``/``row_parallel_linear`` ``overlap_comm=`` and
``GPTConfig.overlap_comm`` (``transformer/testing/standalone_gpt.py``);
``matmul_param_gather`` via ``apex_tpu.fsdp.FSDP.linear`` and the
``ParallelismPlan`` fsdp presets.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.mesh import DP_AXIS, TP_AXIS
from apex_tpu.parallel.mesh import axis_size as _axis_size

__all__ = [
    "all_gather_matmul",
    "matmul_param_gather",
    "matmul_reduce_scatter",
    "matmul_all_reduce",
    "all_gather_matmul_wire_bytes",
    "matmul_param_gather_wire_bytes",
    "matmul_reduce_scatter_wire_bytes",
    "matmul_all_reduce_wire_bytes",
]


# ---------------------------------------------------------------------------
# wire-byte models (the accounting.collective_report agreement contract)


def all_gather_matmul_wire_bytes(shard_elems: int, itemsize: int,
                                 world: int) -> float:
    """Modeled bytes-on-wire per device of one ring all-gather-matmul whose
    INPUT shard has ``shard_elems`` elements: ``(W-1)`` collective-permute
    hops of the shard — identical to the monolithic all-gather's
    ``b_full·(W-1)/W``. Bidirectional moves the same bytes in fewer
    sequential steps."""
    if world <= 1:
        return 0.0
    return float(shard_elems) * itemsize * (world - 1)


def matmul_param_gather_wire_bytes(shard_elems: int, itemsize: int,
                                   world: int, backward: bool = False
                                   ) -> float:
    """Modeled wire bytes of one FSDP-position gather-matmul ring whose
    WEIGHT shard has ``shard_elems`` elements: ``(W-1)`` hops of the shard
    forward — identical to the monolithic tiled all-gather of the full
    weight. ``backward=True`` prices the backward pair instead: the dX
    re-gather ring (shard bytes again) plus the dW travelling accumulator
    (fp32, shard-shaped) — identical to the monolithic all-gather +
    fp32 reduce-scatter the unfused FSDP backward pays."""
    if world <= 1:
        return 0.0
    fwd = float(shard_elems) * itemsize * (world - 1)
    if not backward:
        return fwd
    return fwd + float(shard_elems) * 4 * (world - 1)


def matmul_reduce_scatter_wire_bytes(shard_elems: int, itemsize: int,
                                     world: int) -> float:
    """Modeled wire bytes of one matmul-reduce-scatter ring whose OUTPUT
    shard has ``shard_elems`` elements: ``(W-1)`` hops of the travelling
    accumulator — identical to the monolithic reduce-scatter's
    ``b_shard·(W-1)``."""
    if world <= 1:
        return 0.0
    return float(shard_elems) * itemsize * (world - 1)


def matmul_all_reduce_wire_bytes(shard_elems: int, itemsize: int,
                                 world: int) -> float:
    """Reduce-scatter ring + broadcast ring over the result's 1/W shard:
    ``2·b_shard·(W-1)`` — identical to the monolithic all-reduce's
    ``2·b_full·(W-1)/W``."""
    if world <= 1:
        return 0.0
    return 2.0 * float(shard_elems) * itemsize * (world - 1)


# ---------------------------------------------------------------------------
# ring plumbing


def _span_comm():
    """The canonical ``comm`` monitor span — ring hops carry the same HLO
    op-metadata phase tag as the DDP/ZeRO collectives, so
    ``monitor.report.phase_breakdown`` attributes hop time to ``comm``
    while the interleaved partial GEMMs stay in their fwd/bwd phase."""
    from apex_tpu.monitor.trace import span

    return span("comm")


def _pvary_like(x, ref):
    """Promote ``x`` to the value-movement type of ``ref`` (identity
    value-wise; no-op when vma tracking is off). Fresh buffers
    (``jnp.zeros``) are axis-invariant; mixing them with ring chunks needs
    the explicit cast under ``check_vma=True``."""
    from apex_tpu.transformer.tensor_parallel.mappings import pvary_like

    return pvary_like(x, ref)


def _gather_ring(x, axis_name: str, bidirectional: bool):
    """Yield ``(chunk, src_rank)`` for every rank's shard of ``x``, hopping
    between yields. The next hop's ``ppermute`` is issued BEFORE the chunk
    is yielded, so the caller's per-chunk GEMM is data-independent of the
    in-flight hop — the decomposition's whole point. Unidirectional: one
    stream, ``W-1`` hops deep; bidirectional: two counter-rotating
    streams, ``⌈(W-1)/2⌉`` hops deep, same total bytes."""
    world = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    if world == 1:
        yield x, idx
        return
    fwd = [(j, (j - 1) % world) for j in range(world)]  # recv from right
    if not bidirectional:
        chunk = x
        for t in range(world):
            if t < world - 1:
                with _span_comm():
                    nxt = lax.ppermute(chunk, axis_name, fwd)
            else:
                nxt = None
            yield chunk, (idx + t) % world
            chunk = nxt
        return
    bwd = [(j, (j + 1) % world) for j in range(world)]  # recv from left
    k_plus = (world - 1 + 1) // 2  # hops on the + stream (ceil)
    k_minus = (world - 1) // 2  # hops on the − stream (floor)
    yield x, idx
    plus = minus = x
    for t in range(1, max(k_plus, k_minus) + 1):
        with _span_comm():
            if t <= k_plus:
                plus = lax.ppermute(plus, axis_name, fwd)
            if t <= k_minus:
                minus = lax.ppermute(minus, axis_name, bwd)
        if t <= k_plus:
            yield plus, (idx + t) % world
        if t <= k_minus:
            yield minus, (idx - t + world) % world


def _chunk_slice(x, src, size: int, axis: int):
    return lax.dynamic_slice_in_dim(x, src * size, size, axis=axis)


def _place(out, part, src, size: int, axis: int):
    return lax.dynamic_update_slice_in_dim(out, part, src * size, axis=axis)


def _contract_leading(a, b):
    """dW partial: contract every leading (batch/seq) dim of ``a`` against
    ``b`` → ``(a.shape[-1], b.shape[-1])``, accumulated fp32. The
    monolithic dW is ONE dot with an fp32 MXU accumulator; summing W
    model-dtype partials would add W-1 roundings it never takes, so the
    ring keeps its running dW in fp32 and rounds once at the end."""
    n = a.ndim - 1
    return lax.dot_general(
        a, b, (((tuple(range(n)), tuple(range(n)))), ((), ())),
        preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# forward rings (shared by the primals and the VJP rules)


def _ag_matmul_impl(x, kernel, axis_name, gather_axis, bidirectional):
    """all_gather(x, gather_axis) @ kernel, as a ppermute ring of partial
    GEMMs landing in the output slices."""
    world = _axis_size(axis_name)
    s_loc = x.shape[gather_axis]
    if world == 1:
        return jnp.dot(x, kernel)
    out_shape = list(x.shape[:-1]) + [kernel.shape[-1]]
    out_shape[gather_axis] = s_loc * world
    out = _pvary_like(
        jnp.zeros(tuple(out_shape), jnp.result_type(x.dtype, kernel.dtype)),
        x)
    for chunk, src in _gather_ring(x, axis_name, bidirectional):
        out = _place(out, jnp.dot(chunk, kernel), src, s_loc, gather_axis)
    return out


def _matmul_rs_impl(x, kernel, axis_name, scatter_axis):
    """reduce_scatter(x @ kernel, scatter_axis) as a shifting-accumulator
    ring: the accumulator for shard ``d`` starts at rank ``d+1``, visits
    every rank once collecting its partial GEMM, and arrives home after
    ``W-1`` hops — each hop independent of the partial GEMM the receiving
    rank computes next."""
    world = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    s = x.shape[scatter_axis]
    if s % world:
        raise ValueError(
            f"matmul_reduce_scatter needs dim {scatter_axis} ({s}) "
            f"divisible by the axis size ({world})")
    s_shard = s // world
    if world == 1:
        return jnp.dot(x, kernel)
    perm = [(j, (j + 1) % world) for j in range(world)]  # acc moves right
    acc = None
    for t in range(world):
        d = lax.rem(idx - 1 - t + 2 * world, world)
        part = jnp.dot(_chunk_slice(x, d, s_shard, scatter_axis), kernel)
        acc = part if acc is None else acc + part
        if t < world - 1:
            with _span_comm():
                acc = lax.ppermute(acc, axis_name, perm)
    return acc


def _ring_broadcast(shard, axis_name, gather_axis):
    """all_gather as a ppermute ring (the broadcast leg of
    matmul_all_reduce): every hop's payload is placed as it arrives, so
    trailing consumers of early slices can start before the ring drains."""
    world = _axis_size(axis_name)
    if world == 1:
        return shard
    s_loc = shard.shape[gather_axis]
    out_shape = list(shard.shape)
    out_shape[gather_axis] = s_loc * world
    out = _pvary_like(jnp.zeros(tuple(out_shape), shard.dtype), shard)
    for chunk, src in _gather_ring(shard, axis_name, False):
        out = _place(out, chunk, src, s_loc, gather_axis)
    return out


# ---------------------------------------------------------------------------
# public ops (custom VJPs)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _all_gather_matmul(x, kernel, axis_name, gather_axis, bidirectional):
    return _ag_matmul_impl(x, kernel, axis_name, gather_axis, bidirectional)


def _ag_mm_fwd(x, kernel, axis_name, gather_axis, bidirectional):
    return (_ag_matmul_impl(x, kernel, axis_name, gather_axis,
                            bidirectional), (x, kernel))


def _ag_mm_bwd(axis_name, gather_axis, bidirectional, res, dy):
    x, kernel = res
    # dX: reduce_scatter(dy @ Wᵀ) — itself a decomposed overlap ring
    dx = _matmul_rs_impl(dy, kernel.T, axis_name, gather_axis)
    # dW: re-gather x through a second ring, one partial dW GEMM per hop
    # (the reference's input-grad-comm/dW-GEMM overlap, ring-shaped)
    s_loc = x.shape[gather_axis]
    dw = None
    for chunk, src in _gather_ring(x, axis_name, bidirectional):
        part = _contract_leading(
            chunk, _chunk_slice(dy, src, s_loc, gather_axis))
        dw = part if dw is None else dw + part
    return dx.astype(x.dtype), dw.astype(kernel.dtype)


_all_gather_matmul.defvjp(_ag_mm_fwd, _ag_mm_bwd)


def all_gather_matmul(x, kernel, *, axis_name: str = TP_AXIS,
                      gather_axis: int = 1, bidirectional: bool = False):
    """``all_gather(x, gather_axis) @ kernel`` with the gather decomposed
    into a ppermute ring interleaved with partial GEMMs.

    ``x``: the local shard, gathered along ``gather_axis`` (a
    non-contracting dim — seq for the Megatron-SP entry). ``kernel``:
    ``(in, out)``, contracted against ``x``'s last dim. Exact parity with
    the monolithic path (no reduction is reordered). ``bidirectional``
    splits the ring into two counter-rotating streams — same bytes, half
    the sequential hop depth (use on meshes whose both ICI directions are
    otherwise idle). Backward: dX rides a matmul_reduce_scatter ring, dW a
    second gather ring. Must run inside a mesh program; under
    ``check_vma=True`` pass a ``kernel`` already varying on every axis the
    activations vary on (``mappings.pvary_like``) so the dW reduction over
    the data axes lands on the pvary transpose."""
    return _all_gather_matmul(x, kernel, axis_name, gather_axis,
                              bool(bidirectional))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _matmul_reduce_scatter(x, kernel, axis_name, scatter_axis):
    return _matmul_rs_impl(x, kernel, axis_name, scatter_axis)


def _mm_rs_fwd(x, kernel, axis_name, scatter_axis):
    return _matmul_rs_impl(x, kernel, axis_name, scatter_axis), (x, kernel)


def _mm_rs_bwd(axis_name, scatter_axis, res, dy):
    x, kernel = res
    # ONE ring over the cotangent shard computes both grads per hop:
    # dX slice = dy_src @ Wᵀ placed at src, dW += x[src]ᵀ dy_src — two
    # independent GEMMs behind every in-flight hop
    world = _axis_size(axis_name)
    s_loc = dy.shape[scatter_axis]
    shape = list(dy.shape[:-1]) + [kernel.shape[0]]
    shape[scatter_axis] = s_loc * world
    dx = _pvary_like(
        jnp.zeros(tuple(shape), jnp.result_type(dy.dtype, kernel.dtype)),
        dy)
    dw = None
    for chunk, src in _gather_ring(dy, axis_name, False):
        dx = _place(dx, jnp.dot(chunk, kernel.T), src, s_loc, scatter_axis)
        part = _contract_leading(
            _chunk_slice(x, src, s_loc, scatter_axis), chunk)
        dw = part if dw is None else dw + part
    return dx.astype(x.dtype), dw.astype(kernel.dtype)


_matmul_reduce_scatter.defvjp(_mm_rs_fwd, _mm_rs_bwd)


def matmul_reduce_scatter(x, kernel, *, axis_name: str = TP_AXIS,
                          scatter_axis: int = 1):
    """``reduce_scatter(x @ kernel, scatter_axis)`` with the scatter
    decomposed into a shifting-accumulator ppermute ring (Megatron-SP exit
    ``ḡ`` fused with the row-parallel GEMM).

    ``x``: ``(..., s, ..., in_local)`` full-length along ``scatter_axis``
    (divisible by the axis size); returns the local ``s/W`` shard of the
    summed product. Parity with ``psum_scatter(x @ kernel)`` up to fp
    addition reorder (ring association). Backward: one gather ring over
    the cotangent computing dX slices and dW partials per hop. Same
    ``pvary_like`` contract as :func:`all_gather_matmul`."""
    return _matmul_reduce_scatter(x, kernel, axis_name, scatter_axis)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _matmul_all_reduce(x, kernel, axis_name, scatter_axis):
    return _ring_broadcast(
        _matmul_rs_impl(x, kernel, axis_name, scatter_axis),
        axis_name, scatter_axis)


def _mm_ar_fwd(x, kernel, axis_name, scatter_axis):
    y = _ring_broadcast(
        _matmul_rs_impl(x, kernel, axis_name, scatter_axis),
        axis_name, scatter_axis)
    return y, (x, kernel)


def _mm_ar_bwd(axis_name, scatter_axis, res, dy):
    # The ring output is rank-VARYING (equal values, per-rank type), so
    # downstream cotangents arrive as partials of the true dL/dy; sum them
    # once — the monolithic path pays the identical psum at its
    # invariant-output pvary transpose, so backward bytes match. After the
    # sum both grads are local GEMMs (ref row-parallel backward).
    x, kernel = res
    dy = lax.psum(dy, axis_name)
    dx = jnp.dot(dy, kernel.T).astype(x.dtype)
    dw = _contract_leading(x, dy).astype(kernel.dtype)
    return dx, dw


_matmul_all_reduce.defvjp(_mm_ar_fwd, _mm_ar_bwd)


def matmul_all_reduce(x, kernel, *, axis_name: str = TP_AXIS,
                      scatter_axis: int = 1):
    """``psum(x @ kernel)`` decomposed: the matmul_reduce_scatter ring
    followed by a ppermute broadcast ring — the plain row-parallel exit
    with the reduce half hidden behind the partial GEMMs.

    Needs ``x``'s ``scatter_axis`` dim divisible by the axis size (the
    internal shard). The result is value-identical on every rank but
    TYPE-varying under ``check_vma`` (it comes off a ring, not a psum) —
    downstream mappings (``copy_to_...`` etc.) treat varying input as a
    no-op, and the GPT ``_layer_stack`` casts its scan carry to match.
    Backward is purely local (the psum transpose). Same ``pvary_like``
    contract as :func:`all_gather_matmul`."""
    return _matmul_all_reduce(x, kernel, axis_name, scatter_axis)


# ---------------------------------------------------------------------------
# FSDP position: the WEIGHT is the sharded operand


def _mm_pg_impl(x, w_shard, axis_name, bidirectional):
    """x @ all_gather(w_shard, axis=-1): ring-gather the weight shards,
    one partial GEMM per hop landing in the output COLUMN slice. Exact —
    the gathered dim is non-contracting, no reduction is reordered."""
    world = _axis_size(axis_name)
    if world == 1:
        return jnp.dot(x, w_shard)
    n_loc = w_shard.shape[-1]
    out_shape = list(x.shape[:-1]) + [n_loc * world]
    out = _pvary_like(
        jnp.zeros(tuple(out_shape), jnp.result_type(x.dtype, w_shard.dtype)),
        x)
    axis = len(out_shape) - 1
    for chunk, src in _gather_ring(w_shard, axis_name, bidirectional):
        out = _place(out, jnp.dot(x, chunk), src, n_loc, axis)
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _matmul_param_gather(x, w_shard, axis_name, bidirectional):
    return _mm_pg_impl(x, w_shard, axis_name, bidirectional)


def _mm_pg_fwd(x, w_shard, axis_name, bidirectional):
    # residuals are (x, SHARD): the gathered full weight is never saved —
    # reshard-after-forward is structural, not a hook
    return _mm_pg_impl(x, w_shard, axis_name, bidirectional), (x, w_shard)


def _mm_pg_bwd(axis_name, bidirectional, res, dy):
    x, w_shard = res
    world = _axis_size(axis_name)
    if world == 1:
        dx = jnp.dot(dy, w_shard.T).astype(x.dtype)
        dw = _contract_leading(x, dy).astype(w_shard.dtype)
        return dx, dw
    idx = lax.axis_index(axis_name)
    n_loc = w_shard.shape[-1]
    col = dy.ndim - 1
    # ONE loop, two counter-rotating rings: the weight re-gather ring
    # (recv-from-right — the classic FSDP backward re-materialize; the
    # full weight was never a residual) feeds the dX partial sums, while
    # the dW travelling accumulator (moving right) reduce-scatters the
    # dp-summed weight grad straight into shard layout. Each hop of both
    # rings travels behind the two partial GEMMs of the next iteration.
    perm_w = [(j, (j - 1) % world) for j in range(world)]
    perm_acc = [(j, (j + 1) % world) for j in range(world)]
    chunk = w_shard
    dx = None
    acc = None
    for t in range(world):
        src = lax.rem(idx + t, jnp.int32(world))  # which w shard we hold
        # dX partial: dy's src column block against the resident shard.
        # fp32 accumulator — the monolithic dX is ONE dot with an fp32 MXU
        # accumulator; summing W model-dtype partials would add roundings
        p_dx = lax.dot_general(
            _chunk_slice(dy, src, n_loc, col), chunk,
            (((col,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        dx = p_dx if dx is None else dx + p_dx
        # dW partial for the accumulator currently resident (starts at the
        # left neighbour's shard and arrives home after W-1 hops — the
        # _matmul_rs_impl shifting-accumulator recipe)
        d = lax.rem(idx - 1 - t + 2 * world, world)
        p_dw = _contract_leading(x, _chunk_slice(dy, d, n_loc, col))
        acc = p_dw if acc is None else acc + p_dw
        if t < world - 1:
            with _span_comm():
                chunk = lax.ppermute(chunk, axis_name, perm_w)
                acc = lax.ppermute(acc, axis_name, perm_acc)
    return dx.astype(x.dtype), acc.astype(w_shard.dtype)


_matmul_param_gather.defvjp(_mm_pg_fwd, _mm_pg_bwd)


def matmul_param_gather(x, w_shard, *, axis_name: str = DP_AXIS,
                        bidirectional: bool = False):
    """``x @ all_gather(w_shard, axis=-1)`` with the WEIGHT gather
    decomposed into a ppermute ring interleaved with partial GEMMs — the
    collective-matmul decomposition in FSDP (ZeRO-3) position.

    ``x``: the rank-resident activation ``(..., in)`` (each dp rank holds
    its own batch shard). ``w_shard``: this rank's column shard ``(in,
    out/W)`` of the full ``(in, out)`` weight. Forward is EXACT vs the
    monolithic ``x @ all_gather(w)`` (the gathered dim is
    non-contracting). Backward: dX re-gathers the weight through a second
    ring (fp-reorder tolerance — W partials vs one fused dot) and dW
    arrives as this rank's ``(in, out/W)`` shard of the dp-SUMMED weight
    gradient (the FSDP grad reduce-scatter, fused into the same loop);
    divide by the axis size for the data-parallel mean. Wire-byte-neutral
    vs the monolithic gather + reduce-scatter pair
    (:func:`matmul_param_gather_wire_bytes`). Same ``pvary_like``/mesh
    contract as :func:`all_gather_matmul`."""
    return _matmul_param_gather(x, w_shard, axis_name, bool(bidirectional))

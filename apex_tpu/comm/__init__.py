"""Compressed-collective communication (L-comm) — the wire layer between
gradient producers (DDP, ZeRO optimizers) and the mesh.

Not in the reference: NVIDIA Apex moves fp16/fp32 gradient buckets
verbatim. This subsystem adds blockwise-int8 quantized allreduce with
optional error feedback (EQuARX, arxiv 2506.17615; weight-update sharding
composition per Xu et al., arxiv 2004.13336), cutting DP/ZeRO gradient
bytes-on-wire ~4× at matched convergence. One ``CompressionConfig`` object
selects the policy everywhere:

* ``apex_tpu.parallel.DistributedDataParallel(compression=cfg)``
* ``apex_tpu.contrib.optimizers.DistributedFusedAdam(compression=cfg)``
  (and LAMB)

Modules: ``quantize`` (the int8 codec, pure-JAX + Pallas), ``collectives``
(the two-pass quantized allreduce / reduce-scatter), ``error_feedback``
(the residual pytree + checkpoint round-trip), ``accounting`` (bytes-on-
wire pricing of compiled HLO — how the compression claim is *asserted*,
see ``tests/test_collective_counts.py``), ``overlap`` (ppermute-decomposed
collective matmuls — ``all_gather_matmul`` / ``matmul_reduce_scatter`` /
``matmul_all_reduce`` — that hide the remaining collective latency behind
partial GEMMs; the TP layers take them via ``overlap_comm=`` and the
overlap is proved from compiled HLO by ``accounting.overlap_report``).
"""

from apex_tpu.comm.accounting import (  # noqa: F401
    CollectiveReport,
    OverlapReport,
    collective_report,
    overlap_report,
    wire_bytes,
)
from apex_tpu.comm.collectives import (  # noqa: F401
    CompressionConfig,
    all_gather_wire_bytes,
    allreduce_wire_bytes,
    compressed_allreduce,
    compressed_psum_scatter,
    psum_scatter_wire_bytes,
)
from apex_tpu.comm.error_feedback import (  # noqa: F401
    init_error_feedback,
    load_state_dict,
    state_dict,
)
from apex_tpu.comm.overlap import (  # noqa: F401
    all_gather_matmul,
    all_gather_matmul_wire_bytes,
    matmul_all_reduce,
    matmul_all_reduce_wire_bytes,
    matmul_param_gather,
    matmul_param_gather_wire_bytes,
    matmul_reduce_scatter,
    matmul_reduce_scatter_wire_bytes,
)
from apex_tpu.comm.quantize import (  # noqa: F401
    dequantize_blockwise,
    dequantize_blockwise_int4,
    pack_int4,
    quantization_error,
    quantization_error_int4,
    quantize_blockwise,
    quantize_blockwise_int4,
    unpack_int4,
)

__all__ = [
    "CollectiveReport",
    "CompressionConfig",
    "OverlapReport",
    "all_gather_matmul",
    "all_gather_matmul_wire_bytes",
    "all_gather_wire_bytes",
    "allreduce_wire_bytes",
    "collective_report",
    "compressed_allreduce",
    "compressed_psum_scatter",
    "dequantize_blockwise",
    "dequantize_blockwise_int4",
    "init_error_feedback",
    "load_state_dict",
    "matmul_all_reduce",
    "matmul_all_reduce_wire_bytes",
    "matmul_param_gather",
    "matmul_param_gather_wire_bytes",
    "matmul_reduce_scatter",
    "matmul_reduce_scatter_wire_bytes",
    "overlap_report",
    "pack_int4",
    "psum_scatter_wire_bytes",
    "quantization_error",
    "quantization_error_int4",
    "quantize_blockwise",
    "quantize_blockwise_int4",
    "state_dict",
    "unpack_int4",
    "wire_bytes",
]

"""Compressed collectives — quantized allreduce / reduce-scatter on the mesh.

Reference context: the reference DDP's only wire policies are
``allreduce_always_fp32`` and fp16 buckets (``apex/parallel/distributed.py``);
compression hooks live outside apex (torch DDP comm hooks). EQuARX
(arxiv 2506.17615) shows the profitable TPU design is blockwise int8 with a
requantization at the reduction midpoint; Xu et al. (arxiv 2004.13336) show
the reduce-scatter/all-gather decomposition the ZeRO optimizers already use
is exactly where that compression composes.

The quantized allreduce here is the two-pass decomposition, expressed with
explicit mesh collectives so every byte on the wire is an int8 code or an
fp32 block scale:

1. **quantize** the local flat bucket (``quantize.py``: int8 codes +
   per-block fp32 scales);
2. **exchange pass** — ``all_to_all`` of codes and scales over the axis:
   rank *i* receives every rank's *i*-th chunk. This is the reduce-scatter
   leg of a ring allreduce with the wire carrying int-quantized values
   (a psum over int8 would overflow at world ≥ 2 and XLA would widen it to
   int32 on the wire — 4× the bytes — so the sum happens locally, in fp32,
   after dequantizing the W received chunks);
3. **requantize at the midpoint** — the summed shard is quantized again
   (fresh scales: the sum's dynamic range grew by up to ``world``);
4. **broadcast pass** — ``all_gather`` of the shard's codes + scales,
   dequantize, unpad.

Wire bytes per device (ring model, world W, n elements, block B):
``(n + 4n/B)·(W-1)/W`` for each pass ≈ ``2n`` total vs ``8n·(W-1)/W ≈ 8n``
for an fp32 allreduce — the ≥3.5× reduction ``tests/test_collective_counts
.py`` asserts from the compiled HLO (``accounting.py``).

ZeRO integration: :func:`compressed_psum_scatter` is pass 1+2 alone — the
sharded optimizers need exactly the summed shard, so compression there is
half the pipeline (their param all-gather already has the ``e5m2_allgather``
transport).

Error feedback (policy ``int8_ef``): both lossy steps happen where a rank
can measure them locally — pass 1's error on the quantizing rank, pass 3's
on the shard owner — so the residual they feed (``error_feedback.py``)
captures the full compression error of the step.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.mesh import axis_size as _axis_size
from apex_tpu.comm.quantize import (
    dequantize_blockwise,
    dequantize_blockwise_int4,
    padded_size,
    quantize_blockwise,
    quantize_blockwise_int4,
)

POLICIES = ("none", "int8", "int8_ef", "int4", "int4_ef")


@dataclasses.dataclass(frozen=True)
class CompressionConfig:
    """One switch for the gradient-communication wire format.

    ``policy``:
      * ``"none"`` — uncompressed ``psum`` / ``psum_scatter`` (the default
        paths, byte-for-byte unchanged);
      * ``"int8"`` — blockwise int8 wire, quantization error discarded;
      * ``"int8_ef"`` — int8 wire + error feedback: the residual pytree
        (carried like the loss-scaler state) re-injects this step's
        quantization error into the next step's gradients;
      * ``"int4"`` / ``"int4_ef"`` — group-quantized 4-bit wire: codes
        nibble-packed two per byte at 0.5 B/element plus one fp32 scale
        per ``block_size``-element group (EQuARX's sub-8-bit extension).
        EF is strongly recommended at 4 bits — the per-step quantization
        error is ~16× the int8 one, so the telescoping residual is what
        keeps the loss curve on the fp32 track.

    ``block_size``: elements per fp32 scale (wire overhead 4/B per element;
    256 ≈ 1.6%); the int4 policies read it as the GROUP size (must be
    even for nibble packing — keep it a multiple of the ZeRO shard
    multiple, which the sharded optimizers already derive from it).
    ``stochastic_rounding``: unbiased rounding — needs a per-step ``seed``
    at the call sites. ``min_elements``: buckets smaller than this ride
    the uncompressed path (tiny buffers are latency-, not bandwidth-bound;
    compressing them costs accuracy for no wire win). ``use_pallas``:
    forwarded to the codec (None = auto: Pallas on compiled TPU backends).
    """

    policy: str = "int8"
    block_size: int = 256
    stochastic_rounding: bool = False
    min_elements: int = 2048
    use_pallas: Optional[bool] = None

    def __post_init__(self):
        if self.policy not in POLICIES:
            raise ValueError(
                f"policy must be one of {POLICIES}, got {self.policy!r}")
        if self.block_size <= 0:
            raise ValueError(f"block_size must be > 0: {self.block_size}")
        if self.bits == 4 and self.block_size % 2:
            raise ValueError(
                f"int4 policies need an even block_size (nibble packing): "
                f"{self.block_size}")

    @property
    def enabled(self) -> bool:
        return self.policy != "none"

    @property
    def error_feedback(self) -> bool:
        return self.policy in ("int8_ef", "int4_ef")

    @property
    def bits(self) -> int:
        """Code width of the quantized wire (8 or 4)."""
        return 4 if self.policy.startswith("int4") else 8

    def payload_bytes(self, n: int) -> float:
        """Wire bytes of ONE quantized copy of an ``n``-element (padded)
        buffer: packed codes at ``bits/8`` B/element + the fp32 per-block
        scale sidecar. The unit the wire models below and the compiled-HLO
        pricer (``accounting``) must agree on."""
        return n * (self.bits / 8.0) + 4.0 * n / self.block_size

    def compresses(self, n: int) -> bool:
        """Whether a flat buffer of ``n`` elements takes the quantized path."""
        return self.enabled and n >= self.min_elements

    # -- the policy-dispatched codec (THE supported encode/decode surface
    # for every consumer: the collectives below, the FSDP weight gather) --
    def quantize(self, flat, seed=None):
        """Encode a flat fp buffer per this policy: ``(codes, scales)``.
        int4 codes come back nibble-packed (half the element count);
        chunk boundaries never split a packed pair because the (even)
        block size divides every chunk."""
        if self.bits == 4:
            return quantize_blockwise_int4(
                flat, self.block_size, stochastic=self.stochastic_rounding,
                seed=seed, use_pallas=self.use_pallas)
        return quantize_blockwise(
            flat, self.block_size, stochastic=self.stochastic_rounding,
            seed=seed, use_pallas=self.use_pallas)

    def dequantize(self, q, s):
        """Decode ``(codes, scales)`` back to the fp32 flat buffer."""
        if self.bits == 4:
            return dequantize_blockwise_int4(q, s, self.block_size,
                                             use_pallas=self.use_pallas)
        return dequantize_blockwise(q, s, self.block_size,
                                    use_pallas=self.use_pallas)


def allreduce_wire_bytes(n: int, itemsize: int, world: int,
                         config: Optional[CompressionConfig] = None) -> float:
    """Modeled bytes-on-wire per device of ONE flat-buffer allreduce, under
    the same ring model ``accounting.collective_report`` prices compiled
    HLO with — so a producer (DDP) can report per-bucket bytes that agree
    exactly with what the pricer reads off the program XLA emitted
    (asserted by ``tests/test_monitor.py``).

    Mirrors :func:`compressed_allreduce` op-for-op: uncompressed → one
    ``all-reduce`` (``2·b·(W-1)/W``); compressed → two ``all-to-all`` +
    two ``all-gather`` of the padded codes and fp32 block scales
    (``2·payload(n')·(W-1)/W`` with ``n'`` the block·world-padded size and
    ``payload`` the policy's packed-code + scale-sidecar bytes — int8 codes
    at 1 B/element, int4 nibble pairs at 0.5 B/element). Sub-
    ``min_elements`` buffers ride the uncompressed fp32 path, exactly as
    the collective does.
    """
    if world <= 1:
        return 0.0
    ring = (world - 1) / world
    if config is None or not config.compresses(n):
        if config is not None and config.enabled:
            itemsize = 4  # small-buffer fallback psums in fp32
        return 2.0 * n * itemsize * ring
    size = padded_size(n, config.block_size * world)
    return 2.0 * config.payload_bytes(size) * ring


def psum_scatter_wire_bytes(n: int, itemsize: int, world: int,
                            config: Optional[CompressionConfig] = None,
                            shard_multiple: int = 1) -> float:
    """Modeled wire bytes of one :func:`compressed_psum_scatter` (the ZeRO
    gradient leg): the exchange pass alone. Uncompressed → one
    ``reduce-scatter`` priced at shard-result bytes × ``(W-1)``; compressed
    → one ``all-to-all`` pass of codes + scales."""
    if world <= 1:
        return 0.0
    k = -(-n // world)
    k = -(-k // shard_multiple) * shard_multiple
    if config is None or not config.compresses(n):
        if config is not None and config.enabled:
            itemsize = 4
        return float(k) * itemsize * (world - 1)
    size = max(k * world, padded_size(n, config.block_size * world))
    return config.payload_bytes(size) * (world - 1) / world


def all_gather_wire_bytes(n: int, itemsize: int, world: int) -> float:
    """Modeled wire bytes of one tiled all-gather whose RESULT has ``n``
    elements (the ZeRO param broadcast leg): ``b·(W-1)/W``."""
    if world <= 1:
        return 0.0
    return float(n) * itemsize * (world - 1) / world


def _pad_to(flat, size: int):
    if flat.size == size:
        return flat
    return jnp.concatenate(
        [flat, jnp.zeros((size - flat.size,), flat.dtype)])


def _finite_or_zero(err):
    """Never carry inf/NaN in the EF residual: an overflow step (AMP inf
    grads) makes the quantization error non-finite; the loss scaler
    discards that step's gradients, but a poisoned residual would re-inject
    NaN into every LATER step. Dropping the un-measurable entries costs one
    step of compensation at worst."""
    return jnp.where(jnp.isfinite(err), err, 0.0)


def _fmix32(x):
    """murmur3 fmix32 finalizer (full-avalanche 32-bit mix), uint32."""
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> jnp.uint32(13))
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> jnp.uint32(16))
    return x


def fold_seed(seed, salt):
    """Hash-combine a stochastic-rounding seed with a salt (bucket index,
    rank, pass number). NON-linear on purpose: a linear ``seed + C*salt``
    aliases — (seed, salt) and (seed - C, salt + 1) replay one stream, so
    e.g. a step counter used as the seed would correlate adjacent buckets
    across adjacent steps. With the avalanche mix a collision needs an
    exact 32-bit hash collision (same scheme as the ulysses dropout fold,
    ``transformer/sequence_parallel.py``)."""
    s = jnp.asarray(seed, jnp.int32).reshape(()).astype(jnp.uint32)
    t = jnp.asarray(salt).astype(jnp.uint32)
    return _fmix32(s ^ _fmix32(t + jnp.uint32(0x9E3779B9))).astype(jnp.int32)


def _pass_seed(seed, axis: str, pass_idx: int):
    """Per-(rank, pass) stream: decorrelated across ranks (correlated
    rounding error would not average out over the sum) AND across the two
    quantization passes."""
    if seed is None:
        return None
    return fold_seed(fold_seed(seed, lax.axis_index(axis)), pass_idx)


def _exchange_and_sum(flat_padded, axis: str, cfg: CompressionConfig, seed):
    """Pass 1+2: quantize + all_to_all + local fp32 sum -> (summed shard,
    local quantization error over the full padded buffer)."""
    world = _axis_size(axis)
    n = flat_padded.size
    q, s = cfg.quantize(flat_padded, _pass_seed(seed, axis, 1))
    err = flat_padded - cfg.dequantize(q, s)
    # rank i keeps chunk i of everyone's buffer: the reduce-scatter leg,
    # packed codes + fp32 scales on the wire
    qt = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=True)
    st = lax.all_to_all(s, axis, split_axis=0, concat_axis=0, tiled=True)
    k = n // world
    rows = cfg.dequantize(qt, st).reshape(world, k)
    return jnp.sum(rows, axis=0), err


def compressed_allreduce(
    flat: jnp.ndarray,
    axis: str,
    config: CompressionConfig,
    residual: Optional[jnp.ndarray] = None,
    seed=None,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Drop-in for ``lax.psum(flat, axis)`` on a flat fp buffer.

    Returns ``(sum over the axis (fp32), new_residual)``. ``residual`` is
    the error-feedback state for THIS buffer (same shape, fp32) — required
    exactly when ``config.error_feedback``; the returned residual must be
    carried to the next step (see ``error_feedback.py``). With EF the
    compensated buffer ``flat + residual`` is what gets compressed, so over
    steps the summed error telescopes instead of accumulating.

    Must run inside a mesh program with ``axis`` bound. The result is
    value-identical on every rank (it comes off a final all-gather) but is
    built from per-rank collectives — under ``check_vma`` wrap the caller
    accordingly (the DDP integration handles this).
    """
    if config.error_feedback and residual is None:
        raise ValueError(
            f"policy {config.policy!r} needs the residual carried in: "
            "init with error_feedback.init_error_feedback / "
            "DistributedDataParallel.init_comm_state")
    n = flat.size
    if not config.compresses(n):
        out = lax.psum(
            flat.astype(jnp.float32) if config.enabled else flat, axis)
        return out, residual
    if config.stochastic_rounding and seed is None:
        raise ValueError("stochastic_rounding needs a per-step seed")

    world = _axis_size(axis)
    comp = flat.astype(jnp.float32)
    if residual is not None:
        comp = comp + residual.astype(jnp.float32).reshape(-1)
    size = padded_size(n, config.block_size * world)
    padded = _pad_to(comp, size)

    shard_sum, err1 = _exchange_and_sum(padded, axis, config, seed)

    # midpoint requantization: fresh scales for the grown dynamic range
    q2, s2 = config.quantize(shard_sum, _pass_seed(seed, axis, 2))
    qf = lax.all_gather(q2, axis, axis=0, tiled=True)
    sf = lax.all_gather(s2, axis, axis=0, tiled=True)
    out = config.dequantize(qf, sf)

    new_residual = residual
    if config.error_feedback:
        # pass-3 error is measurable only on the shard owner; inject it
        # there — summed over ranks, the residuals then cover the whole
        # lost mass: sum_k r_k = sum_k e1_k + e2
        k = size // world
        err2 = shard_sum - config.dequantize(q2, s2)
        rank = lax.axis_index(axis)
        err = lax.dynamic_update_slice(
            err1, lax.dynamic_slice(err1, (rank * k,), (k,)) + err2,
            (rank * k,))
        new_residual = _finite_or_zero(err[:n]).reshape(
            residual.shape).astype(residual.dtype)
    return out[:n], new_residual


def compressed_psum_scatter(
    flat: jnp.ndarray,
    axis: str,
    config: CompressionConfig,
    residual: Optional[jnp.ndarray] = None,
    seed=None,
    shard_multiple: int = 1,
) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Compressed ``lax.psum_scatter``: pass 1+2 only — each rank gets its
    summed fp32 shard of the flat buffer (the ZeRO gradient reduce).

    Shards are ``ceil(n / world)`` rounded up to ``shard_multiple`` (the
    sharded optimizers pass ``config.block_size`` so quantization blocks
    never straddle shard boundaries). Returns ``(shard, new_residual)``;
    the residual covers the full ``flat`` buffer (EF state is unsharded —
    it compensates the *local* quantization error, which lives rank-side).
    """
    if config.error_feedback and residual is None:
        raise ValueError(
            f"policy {config.policy!r} needs the residual carried in: "
            "init with error_feedback.init_error_feedback")
    world = _axis_size(axis)
    n = flat.size
    k = -(-n // world)
    k = -(-k // shard_multiple) * shard_multiple
    if not config.compresses(n):
        comm = _pad_to(
            flat.astype(jnp.float32) if config.enabled else flat, k * world)
        return (lax.psum_scatter(comm, axis, scatter_dimension=0,
                                 tiled=True), residual)
    if config.stochastic_rounding and seed is None:
        raise ValueError("stochastic_rounding needs a per-step seed")

    comp = flat.astype(jnp.float32)
    if residual is not None:
        comp = comp + residual.astype(jnp.float32).reshape(-1)
    # pad so every world-chunk is block-aligned AND matches the shard size
    # the caller's state was built with
    size = max(k * world,
               padded_size(n, config.block_size * world))
    k = size // world
    padded = _pad_to(comp, size)
    shard_sum, err1 = _exchange_and_sum(padded, axis, config, seed)
    new_residual = residual
    if config.error_feedback:
        new_residual = _finite_or_zero(err1[:n]).reshape(
            residual.shape).astype(residual.dtype)
    return shard_sum, new_residual
